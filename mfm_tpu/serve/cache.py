"""Content-addressed response cache + construct warm-start index.

At millions of users most traffic is repeated traffic, and the serving
stack already computes every key a response cache needs: trace ids are
content hashes of the request line, checkpoints carry a monotonic
``generation`` fence, and scenarios carry a canonical ``spec_hash``.
This module turns those into exact response reuse in front of the
coalescer (``serve/coalesce.py``):

- **Key**: the canonical request body — the parsed JSON object with the
  two per-caller identity keys (``id``, ``trace_id``) removed,
  re-serialized with sorted keys — plus the checkpoint generation and
  the spec hash of the request's scenario tag.  Two users asking the
  same question hit the same entry; a hot reload (``--watch``, replica
  fence audit) bumps the generation and every old entry becomes
  unreachable WITHOUT a sweep (LRU eviction collects the corpses).
- **Hit**: the cached response bytes, re-stamped with the caller's own
  ``id``/``trace_id``.  Everything else is byte-identical to a cold
  computation — asserted by tests/bench, not approximated — because the
  stored body IS a cold response with only the identity keys stripped,
  and canonical-JSON round-trips are exact (Python float repr is
  shortest-round-trip).
- **Miss**: rides today's path verbatim.  The miss's origin token is
  wrapped in a :class:`CacheFill` so the response can be matched back to
  its key at delivery with no id/trace-id ambiguity (client-supplied
  trace ids need not be unique; the wrapped origin is).
- **Population**: only terminal healthy responses enter the cache —
  ``outcome == "ok"`` and not degraded/stale-stamped.  Dead-letter,
  shed, deadline, breaker-reject and error responses never do.

The warm-start tier extends reuse to construction solves: an exact body
match is a plain cache hit (bitwise), while a NEAR miss — same solver
and hmax, exposure vector within a tolerance of a cached solve's key —
seeds the solver's strictly-positive warm-start blend with the cached
solution instead of the request book, at a reduced step budget.  A
warm-started solve is NOT bitwise-equal to a cold one; it records the
parity contract on the response (``warm_start: {used, steps,
steps_saved, parity: "seeded"}``) and tests hold it to a convergence
tolerance instead.  Cold solves carry no ``warm_start`` field, which is
what keeps every existing bitwise contract (batch-of-B == B singles,
coalesced == sequential, chaos replay) intact when the index is idle.

Host-only module (mfmlint R7): JSON, dicts, locks — nothing here may be
reached from traced code.
"""

from __future__ import annotations

import collections
import json
import threading
import time

import numpy as np

from mfm_tpu.obs import instrument as _obs
from mfm_tpu.serve.server import _line_trace_id

#: response keys carrying per-caller identity — stripped from stored
#: bodies, re-stamped on every hit
IDENTITY_KEYS = ("id", "trace_id")


class CacheFill:
    """Origin wrapper riding a cache miss through the serving path.

    Admission stamps the request's origin token onto the queued request;
    wrapping it here lets :meth:`ResponseCache.absorb` match the
    response back to the exact cache key its line hashed to — no
    pending-map keyed on (possibly client-duplicated) trace ids, no
    ambiguity.  ``absorb`` unwraps before responses reach a frontend, so
    nothing downstream ever sees the wrapper."""

    __slots__ = ("origin", "token")

    def __init__(self, origin, token):
        self.origin = origin
        self.token = token


def cacheable_response(resp) -> bool:
    """Only terminal healthy responses may enter the cache: ``ok``
    outcome, not degraded (staleness > 0 or health != ok stamps
    ``degraded`` — serving those from a cache would freeze a transient
    condition into a permanent answer)."""
    return (isinstance(resp, dict) and resp.get("ok") is True
            and resp.get("outcome") == "ok"
            and not resp.get("degraded"))


class ResponseCache:
    """Bounded, thread-safe, content-addressed response cache.

    Args:
      max_entries: LRU bound on entry count.
      max_bytes: LRU bound on resident stored-body bytes.
      generation: initial checkpoint generation fence (see
        :meth:`set_fence`).
      scenario_hashes: ``{scenario name: spec_hash}`` for the served
        scenario table.  A tagged request's key includes its scenario's
        spec hash, so swapping one scenario's spec invalidates exactly
        that scenario's entries.  Names absent from the map fence on the
        name itself (coarser: only a generation bump invalidates them).
      clock: monotonic clock for the hit-latency histogram.
    """

    def __init__(self, max_entries: int = 4096,
                 max_bytes: int = 64 << 20, *, generation: int = 0,
                 scenario_hashes=None,
                 clock=time.perf_counter):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self._resident = 0
        self._generation = int(generation)
        self._scenario_hashes = dict(scenario_hashes or {})
        self._clock = clock
        # per-instance tallies (the obs counters are process-global;
        # tests and manifests want THIS cache's numbers)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- fence ----------------------------------------------------------------
    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def set_fence(self, generation=None, scenario_hashes=None) -> None:
        """Move the fence: entries keyed under the old (generation,
        scenario hash) become unreachable immediately — no sweep, the
        LRU bound evicts them as fresh entries arrive."""
        with self._lock:
            if generation is not None:
                self._generation = int(generation)
            if scenario_hashes is not None:
                self._scenario_hashes = dict(scenario_hashes)

    # -- key derivation -------------------------------------------------------
    def key_for(self, line: str):
        """``(key, rid, tid)`` for one request line, or None when the
        line is not a JSON object (those dead-letter — uncacheable by
        construction).  ``tid`` is the caller's own trace id when the
        request carries one, else the deterministic line hash — exactly
        the id the cold path would stamp."""
        try:
            obj = json.loads(line)
        except (ValueError, TypeError):
            return None
        if not isinstance(obj, dict):
            return None
        rid = obj.pop("id", None)
        raw_tid = obj.pop("trace_id", None)
        tid = str(raw_tid) if raw_tid is not None else _line_trace_id(line)
        if obj.get("sweep"):
            # sweep responses summarize a whole streaming batch run —
            # cache-exempt by contract (ISSUE 17): every sweep streams
            # against the live fenced checkpoint, never a stored answer
            return None
        scen = obj.get("scenario")
        with self._lock:
            gen = self._generation
            scen_hash = ("" if scen is None
                         else self._scenario_hashes.get(str(scen),
                                                        f"name:{scen}"))
        try:
            # compact separators: the canonical form never leaves the
            # cache, and the tight spelling is ~30% less encoder work on
            # the per-request hot path
            body = json.dumps(obj, sort_keys=True, separators=(",", ":"))
        except (TypeError, ValueError):
            return None
        return (body, gen, scen_hash), rid, tid

    # -- lookup / populate ----------------------------------------------------
    def lookup(self, line: str):
        """``(response_or_None, token_or_None)``.  A hit returns the
        cached body re-stamped with THIS caller's id/trace id; a miss
        returns a token for :class:`CacheFill` so delivery can populate
        the entry.  Uncacheable lines return ``(None, None)``."""
        t0 = self._clock()
        keyed = self.key_for(line)
        if keyed is None:
            return None, None
        key, rid, tid = keyed
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        if entry is None:
            _obs.record_cache_miss()
            return None, key
        # shallow-copy the parsed template instead of re-decoding the
        # stored bytes: the floats in it are the exact objects the stored
        # body serialized from, so the re-stamped response still encodes
        # byte-identically — and the hot path skips a json.loads.  The
        # template is immutable by contract: nothing in the serving stack
        # mutates a response body after it is stamped.
        resp = dict(entry[1])
        resp["id"] = rid
        resp["trace_id"] = tid
        _obs.record_cache_hit(self._clock() - t0)
        return resp, key

    def put(self, key, resp: dict) -> bool:
        """Store one response under ``key`` (identity keys stripped).
        Returns False — and stores nothing — for uncacheable outcomes."""
        if not cacheable_response(resp):
            return False
        template = {k: v for k, v in resp.items()
                    if k not in IDENTITY_KEYS}
        # the stored bytes (size accounting + the byte-identity contract)
        # and the parsed template the hot path re-stamps; json.dumps
        # defaults to ensure_ascii, so len(str) IS the byte length
        body = json.dumps(template, sort_keys=True)
        size = len(body)
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._resident -= len(old[0])
            self._entries[key] = (body, template)
            self._resident += size
            while self._entries and (len(self._entries) > self.max_entries
                                     or self._resident > self.max_bytes):
                _, dropped = self._entries.popitem(last=False)
                self._resident -= len(dropped[0])
                evicted += 1
            self.evictions += evicted
            entries_now, resident_now = len(self._entries), self._resident
        _obs.record_cache_store(size, evicted, entries_now, resident_now)
        return True

    def absorb(self, pairs: list) -> list:
        """Delivery-side hook: unwrap every :class:`CacheFill` origin,
        populating the cache from cacheable responses, and count every
        delivered response (hits short-circuit through here too) so the
        doctor audit can check delivered == computed + hits."""
        out = []
        for origin, resp in pairs:
            if isinstance(origin, CacheFill):
                self.put(origin.token, resp)
                origin = origin.origin
            out.append((origin, resp))
        _obs.record_responses_delivered(len(out))
        return out

    # -- introspection --------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "entries": len(self._entries),
                    "resident_bytes": self._resident,
                    "generation": self._generation}


class WarmStartIndex:
    """Near-miss reuse for construction solves.

    Keeps the most recent COLD solutions per ``(solver, hmax)`` (warm
    results are never indexed — chaining warm-from-warm would compound
    convergence error).  :meth:`nearest` returns a cached solution whose
    request book was within ``tol`` (relative L2) of the query's, to
    seed the solver's strictly-positive warm-start blend at a reduced
    step budget.  Hedge solves are excluded: their books are fixed
    inputs, not warm starts.
    """

    #: full-budget steps divide by this for a warm-started solve
    STEPS_DIVISOR = 4

    def __init__(self, tol: float = 0.05, per_solver: int = 64):
        if not (tol > 0):
            raise ValueError(f"tol must be > 0, got {tol}")
        self.tol = float(tol)
        self.per_solver = int(per_solver)
        self._lock = threading.Lock()
        self._rings: dict = {}
        self.uses = 0
        self.steps_saved = 0

    def add(self, solver: str, hmax: float, key_vec, solved) -> None:
        entry = (np.asarray(key_vec, np.float64).copy(),
                 np.asarray(solved, np.float64).copy())
        with self._lock:
            ring = self._rings.setdefault(
                (str(solver), float(hmax)),
                collections.deque(maxlen=self.per_solver))
            ring.append(entry)

    def nearest(self, solver: str, hmax: float, weights):
        w = np.asarray(weights, np.float64)
        with self._lock:
            ring = self._rings.get((str(solver), float(hmax)))
            if not ring:
                return None
            candidates = list(ring)
        best = None
        best_d = np.inf
        for key_vec, solved in reversed(candidates):
            if key_vec.shape != w.shape:
                continue
            d = float(np.linalg.norm(w - key_vec))
            if d <= self.tol * max(1.0, float(np.linalg.norm(key_vec))) \
                    and d < best_d:
                best, best_d = solved, d
        return None if best is None else best.copy()

    def record_use(self, steps: int, steps_saved: int) -> None:
        with self._lock:
            self.uses += 1
            self.steps_saved += int(steps_saved)
        _obs.record_warm_start(int(steps_saved))

    def stats(self) -> dict:
        with self._lock:
            return {"uses": self.uses, "steps_saved": self.steps_saved,
                    "tol": self.tol}
