"""Request coalescing: merge in-flight requests across connections into
the geometric bucket ladder.

The single-stream loop (``QueryServer.run``) already amortizes one jit
dispatch over a whole drained batch — but only when one client pipes many
lines.  Real small-request traffic arrives one line per connection, and a
per-line drain pays the full dispatch latency every time.  The
:class:`Coalescer` closes that gap: frontend threads :meth:`submit` lines
concurrently, admitted requests pool in the wrapped server's queue, and
ONE flush drains them through the unchanged ``drain_routed`` path — which
already groups by (scenario tag, request type, solver) and pads each
sub-batch to its ladder bucket.  Because batch-of-B is bitwise-equal to B
singles (the PR 6 invariant the steady-state tests pin), coalesced
responses are bitwise-identical per request id to the sequential
single-connection run.

Flush policy — the linger budget:

- **full**: a submit that fills the queue to ``policy.batch_max`` flushes
  immediately (high load: batches fill, no waiting).
- **linger**: the background flusher (or an explicit :meth:`poll`) flushes
  once the OLDEST queued request has waited ``linger_s`` (low load: p99 is
  bounded by the linger plus one batch wall, never an unbounded wait for a
  bucket to fill).
- **eof**: :meth:`stop` / :meth:`flush` drain whatever remains.

Thread model: ONE lock serializes every touch of the wrapped server
(admission, drain, reload polling).  Coalescing does not try to overlap
device batches — on this host device work is serial anyway; the win is
amortizing dispatch, not pipelining it.  Responses route back to their
submitting connection via the ``(origin, resp)`` pairs the routed server
API returns.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from mfm_tpu.obs import instrument as _obs
from mfm_tpu.obs import trace as _trace
from mfm_tpu.serve.cache import CacheFill
from mfm_tpu.serve.query import bucket_for


class Coalescer:
    """Thread-safe coalescing front of a :class:`~mfm_tpu.serve.server.
    QueryServer`.

    Args:
      server: the wrapped :class:`QueryServer`.  The coalescer owns it —
        nothing else may call its submit/drain once coalescing starts.
      linger_s: max time the oldest admitted request may wait before a
        flush (the p99 budget at low load).
      clock: monotonic clock, injectable for deterministic tests.
      deliver: optional callback ``deliver(pairs)`` receiving every list
        of ``(origin, resp)`` pairs as it is produced.  When set, submit/
        flush deliver through it and return ``[]``; when None, they return
        the pairs to the caller (the single-threaded test mode).
      cache: optional :class:`~mfm_tpu.serve.cache.ResponseCache` sitting
        between admission and the queue.  A hit answers from the cached
        body (re-stamped with the caller's id/trace id) without touching
        admission; a miss rides the unchanged path with its origin
        wrapped in a ``CacheFill`` so delivery populates the entry.  The
        cache is bypassed whenever the breaker is not closed — reject-
        with-retry-after is the documented degraded behavior, and a
        cache must never argue with the breaker.
    """

    def __init__(self, server, *, linger_s: float = 0.01,
                 clock: Callable[[], float] = time.monotonic,
                 deliver=None, cache=None):
        if linger_s < 0:
            raise ValueError(f"linger_s must be >= 0, got {linger_s}")
        self.server = server
        self.linger_s = float(linger_s)
        self._clock = clock
        self._deliver = deliver
        self.cache = cache
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._oldest_t: float | None = None   # enqueue time of queue head
        self._last_poll = -float("inf")       # hit-path reload-poll stamp
        self._flusher: threading.Thread | None = None
        self._stopping = False

    # -- internals (callers hold self._lock) ---------------------------------
    def _poll_reload_locked(self) -> None:
        """The reload point the hit-path throttle uses.  FleetServer
        overrides this so a rollout fleet never moves the admission
        engine (or the cache fence) ahead of its workers."""
        self.server.poll_reload()

    def _emit(self, pairs):
        if not pairs:
            return []
        if self.cache is not None:
            # unwrap CacheFill origins (populating the cache from
            # cacheable responses) and count every delivered response
            pairs = self.cache.absorb(pairs)
        if self._deliver is not None:
            self._deliver(pairs)
            return []
        return pairs

    def _flush_locked(self, trigger: str) -> list:
        """Drain the whole queue (possibly several batch_max rounds) and
        tally the fill/linger metrics per drained round."""
        out = []
        now = self._clock()
        lingered = (now - self._oldest_t) if self._oldest_t is not None else 0.0
        while self.server._queue:
            n = min(len(self.server._queue), self.server.policy.batch_max)
            self.server.poll_reload()
            pairs = self.server.drain_routed()
            _obs.record_coalesce_flush(n, bucket_for(n), trigger, lingered)
            lingered = 0.0   # later rounds of one flush did not linger
            out.extend(pairs)
        self._oldest_t = None
        return out

    # -- the public API ------------------------------------------------------
    def submit(self, line: str, origin=None) -> list:
        """Admit one request line from any thread.  Immediate responses
        (rejections, dead-letter acks, shed notices) come back right away;
        admitted requests answer at the next flush.  Returns/delivers
        ``(origin, resp)`` pairs."""
        if self.cache is not None:
            # drains poll the checkpoint watch, but an all-hits streak
            # never drains — without this throttled poll a pure repeat
            # stream would keep answering from a retired generation
            # forever.  The linger budget bounds hit-path fence
            # staleness exactly as it bounds response latency.
            now = self._clock()
            if now - self._last_poll >= self.linger_s:
                self._last_poll = now
                with self._lock:
                    self._poll_reload_locked()
            if self.server.breaker.state == "closed":
                resp, token = self.cache.lookup(line)
                if resp is not None:
                    if _trace.tracing_enabled():
                        # a hit never opens a serve.request span — this
                        # child marks the short-circuit on the timeline
                        _trace.end_span(_trace.start_span(
                            "cache.hit", trace_id=resp.get("trace_id"),
                            request_id=resp.get("id")))
                    with self._lock:
                        return self._emit([(origin, resp)])
                if token is not None:
                    origin = CacheFill(origin, token)
        with self._lock:
            was_empty = not self.server._queue
            pairs = list(self.server.submit_line_routed(line, origin))
            if self.server._queue and was_empty:
                self._oldest_t = self._clock()
                self._wake.notify()   # flusher re-arms its linger deadline
            if len(self.server._queue) >= self.server.policy.batch_max:
                pairs.extend(self._flush_locked("full"))
            return self._emit(pairs)

    def poll(self) -> list:
        """Flush if the oldest queued request's linger budget expired
        (call this from a dispatcher loop when not using :meth:`start`)."""
        with self._lock:
            if (self._oldest_t is not None
                    and self._clock() - self._oldest_t >= self.linger_s):
                return self._emit(self._flush_locked("linger"))
            return []

    def flush(self, trigger: str = "eof") -> list:
        """Drain everything queued, regardless of linger state."""
        with self._lock:
            return self._emit(self._flush_locked(trigger))

    def queued(self) -> int:
        with self._lock:
            return len(self.server._queue)

    def next_deadline(self) -> float | None:
        """Clock time the current oldest request must flush by (None when
        the queue is empty)."""
        with self._lock:
            if self._oldest_t is None:
                return None
            return self._oldest_t + self.linger_s

    # -- background flusher --------------------------------------------------
    def start(self) -> None:
        """Run the linger flusher in a daemon thread (requires ``deliver``
        — there is no caller to hand pairs back to)."""
        if self._deliver is None:
            raise ValueError("Coalescer.start() needs a deliver callback")
        if self._flusher is not None:
            return
        # S1 (mfmsync): _stopping is read by the flusher under _lock; a
        # bare write here could race a concurrent stop() and strand the
        # new thread in an immediate-exit or never-exit state.
        with self._lock:
            self._stopping = False
        self._flusher = threading.Thread(target=self._flush_loop,
                                         name="mfm-coalesce-flusher",
                                         daemon=True)
        self._flusher.start()

    def _flush_loop(self) -> None:
        with self._lock:
            while not self._stopping:
                if self._oldest_t is None:
                    self._wake.wait(timeout=0.5)
                    continue
                budget = self._oldest_t + self.linger_s - self._clock()
                if budget > 0:
                    self._wake.wait(timeout=budget)
                    continue
                self._emit(self._flush_locked("linger"))

    def stop(self) -> list:
        """Stop the flusher (if running) and drain the tail.  Returns the
        final pairs in no-deliver mode."""
        with self._lock:
            self._stopping = True
            self._wake.notify_all()
        if self._flusher is not None:
            self._flusher.join(timeout=5.0)
            self._flusher = None
        with self._lock:
            return self._emit(self._flush_locked("eof"))
