"""Batched portfolio-query engine over the served covariance.

The consumer-facing math of a USE4-style risk model (PAPER.md): given the
served factor covariance F — possibly stale, possibly the quarantine
layer's last-healthy matrix — answer, for B portfolios at once,

- predicted volatility  sigma_p = sqrt(x'Fx + sum_i w_i^2 s_i^2),
- marginal factor risk  dsigma^2/dx = Fx  and the Euler contributions
  x_i (Fx)_i (summing exactly to x'Fx),
- active risk vs a named benchmark  sqrt((x-xb)'F(x-xb) + ...),
- portfolio beta vs that benchmark  cov(p, b) / var(b),

in ONE vmapped, donated jit.  B portfolios x K factors is tiny per row —
"millions of users" is a pure batching problem (ROADMAP), so the engine's
whole job is to keep the batch on-device, padded, and compiled once.

**Batch-size buckets.**  A jit specializes on shapes: serving raw request
counts would recompile on every distinct B.  Batches are padded with zero
rows up to a geometric bucket (:func:`bucket_for`), so the steady-state
loop compiles once per bucket and never again —
``utils.contracts.assert_max_compiles(1)`` per bucket is the enforced
contract (tools/faultinject.py drives it).

**Spaces.**  Requests either carry factor exposures directly (K values —
the wire format of ``mfm-tpu serve``, where the checkpoint holds only the
covariance) or stock weights (N values — available when the engine is
built from a full pipeline result via
:meth:`mfm_tpu.pipeline.RiskPipelineResult.query_engine`, which supplies
the date's exposure matrix X and specific variances).

**Donation.**  The per-call batch (weights + benchmark indices) is donated
— it is freshly built for every call, so the jit may retire its buffer
into the outputs.  The engine-lifetime constants (F, X, specific var,
benchmark tables) are NOT donated: they are reused by every batch.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

#: bucket ladder: base * growth**k (k = 0, 1, ...).  Geometric, so padding
#: waste is bounded by ``growth``x and a 1e6-portfolio batch still only
#: ever meets ~10 distinct shapes.
BUCKET_BASE = 8
BUCKET_GROWTH = 4


def bucket_for(n: int, base: int = BUCKET_BASE,
               growth: int = BUCKET_GROWTH) -> int:
    """Smallest ladder bucket >= n (the padded batch shape)."""
    if n < 1:
        raise ValueError(f"batch size must be >= 1, got {n}")
    b = int(base)
    while b < n:
        b *= int(growth)
    return b


class QueryOutputs(NamedTuple):
    """Per-portfolio answers of one batched query (rows past the true B
    are padding).  ``beta``/``active_risk`` vs benchmark row 0 (the zero
    portfolio) are reported as NaN / total risk respectively — the serving
    layer only surfaces them when a benchmark was actually named."""

    total_vol: jax.Array      # (B,)
    factor_var: jax.Array     # (B,)
    specific_var: jax.Array   # (B,)
    contribution: jax.Array   # (B, K) Euler x_i (Fx)_i
    marginal: jax.Array       # (B, K) Fx
    active_risk: jax.Array    # (B,)
    beta: jax.Array           # (B,)


def _one_factor(x, bidx, cov, bx):
    """Single-portfolio factor-space query (vmapped over the batch)."""
    Fx = cov @ x
    fvar = x @ Fx
    xb = bx[bidx]
    Fxb = cov @ xb
    a = x - xb
    avar = a @ (cov @ a)
    var_b = xb @ Fxb
    beta = jnp.where(var_b > 0, (x @ Fxb) / var_b, jnp.nan)
    zero = jnp.zeros((), x.dtype)
    return QueryOutputs(
        total_vol=jnp.sqrt(fvar),
        factor_var=fvar,
        specific_var=zero,
        contribution=x * Fx,
        marginal=Fx,
        active_risk=jnp.sqrt(avar),
        beta=beta,
    )


def _one_stock(w, bidx, cov, X, svar, bx, bw):
    """Single-portfolio stock-space query (vmapped over the batch)."""
    x = w @ X
    Fx = cov @ x
    fvar = x @ Fx
    sv_p = jnp.sum(w * w * svar)
    xb = bx[bidx]
    wb = bw[bidx]
    Fxb = cov @ xb
    a = x - xb
    avar = a @ (cov @ a) + jnp.sum((w - wb) ** 2 * svar)
    var_b = xb @ Fxb + jnp.sum(wb * wb * svar)
    cov_pb = x @ Fxb + jnp.sum(w * wb * svar)
    beta = jnp.where(var_b > 0, cov_pb / var_b, jnp.nan)
    return QueryOutputs(
        total_vol=jnp.sqrt(fvar + sv_p),
        factor_var=fvar,
        specific_var=sv_p,
        contribution=x * Fx,
        marginal=Fx,
        active_risk=jnp.sqrt(avar),
        beta=beta,
    )


# the two batched kernels: ONE vmapped, donated jit each.  Only the batch
# (weights, bench indices) is donated; the trailing operands are
# engine-lifetime constants reused across calls.
@partial(jax.jit, donate_argnums=(0, 1))
def _batch_factor(x, bidx, cov, bx):
    return jax.vmap(_one_factor, in_axes=(0, 0, None, None))(
        x, bidx, cov, bx)


@partial(jax.jit, donate_argnums=(0, 1))
def _batch_stock(w, bidx, cov, X, svar, bx, bw):
    return jax.vmap(_one_stock, in_axes=(0, 0, None, None, None, None,
                                         None))(w, bidx, cov, X, svar, bx, bw)


class QueryEngine:
    """Batched portfolio queries against one served covariance.

    Args:
      cov: (K, K) served factor covariance (e.g. ``state.last_good_cov``).
      factor_names: K names defining the exposure order (defaults to
        ``f0..f{K-1}``).
      exposures: optional (N, K) per-stock factor exposure matrix for the
        served date — supplying it makes this a STOCK-space engine
        (requests carry N stock weights); omitted, requests carry K factor
        exposures directly.
      specific_var: optional (N,) per-stock specific VARIANCE at the served
        date (stock space only; non-finite entries count as 0 — the guard
        layer, not the math, polices weight on vol-less names).
      stocks: optional N stock ids (stock space; used by the request
        guards to map dict-keyed weights).
      benchmarks: ``{name: vector}`` of benchmark portfolios in the
        engine's own space (stock weights / factor exposures).
      staleness: dates since ``cov`` was fit (stamped on every response).
      dtype: compute dtype (defaults to ``cov``'s).
    """

    def __init__(self, cov, *, factor_names=None, exposures=None,
                 specific_var=None, stocks=None, benchmarks=None,
                 staleness: int = 0, dtype=None):
        cov = np.asarray(cov)
        if cov.ndim != 2 or cov.shape[0] != cov.shape[1]:
            raise ValueError(f"cov must be (K, K), got {cov.shape}")
        if not np.isfinite(cov).all():
            raise ValueError("served covariance contains non-finite entries "
                             "— refuse to build a query engine on it")
        self.dtype = np.dtype(dtype) if dtype is not None else cov.dtype
        self.K = int(cov.shape[0])
        self.factor_names = ([f"f{i}" for i in range(self.K)]
                             if factor_names is None
                             else list(map(str, factor_names)))
        if len(self.factor_names) != self.K:
            raise ValueError(f"{len(self.factor_names)} factor names for "
                             f"K={self.K}")
        self.factor_index = {n: i for i, n in enumerate(self.factor_names)}
        self.staleness = int(staleness)
        #: name of the scenario this engine's covariance was shocked under
        #: (None = the plain served matrix; set by :meth:`with_cov`, stamped
        #: on every response by the serve loop)
        self.scenario_id: str | None = None
        # jnp.array (owning copy): these are jit operands; never donated,
        # but the engine must not alias caller-mutable numpy memory
        self._cov = jnp.array(cov.astype(self.dtype))
        if exposures is not None:
            X = np.asarray(exposures, self.dtype)
            if X.ndim != 2 or X.shape[1] != self.K:
                raise ValueError(f"exposures must be (N, {self.K}), got "
                                 f"{X.shape}")
            self.N = int(X.shape[0])
            sv = (np.zeros(self.N, self.dtype) if specific_var is None
                  else np.asarray(specific_var, self.dtype))
            if sv.shape != (self.N,):
                raise ValueError(f"specific_var must be ({self.N},), got "
                                 f"{sv.shape}")
            self._X = jnp.array(np.where(np.isfinite(X), X, 0.0))
            self._svar = jnp.array(np.where(np.isfinite(sv), sv, 0.0))
            self.space = "stock"
        else:
            if specific_var is not None:
                raise ValueError("specific_var needs exposures (stock space)")
            self.N = self.K
            self._X = self._svar = None
            self.space = "factor"
        self.stocks = None if stocks is None else list(map(str, stocks))
        if self.stocks is not None and len(self.stocks) != self.N:
            raise ValueError(f"{len(self.stocks)} stock ids for N={self.N}")
        # benchmark tables: row 0 is the zero portfolio = "no benchmark"
        names = list(benchmarks or {})
        self.benchmark_index = {n: i + 1 for i, n in enumerate(names)}
        bvecs = np.zeros((len(names) + 1, self.N), self.dtype)
        for n, row in self.benchmark_index.items():
            v = np.asarray(benchmarks[n], self.dtype)
            if v.shape != (self.N,) or not np.isfinite(v).all():
                raise ValueError(f"benchmark {n!r}: need {self.N} finite "
                                 "values")
            bvecs[row] = v
        if self.space == "stock":
            self._bw = jnp.array(bvecs)
            self._bx = self._bw @ self._X
        else:
            self._bw = None
            self._bx = jnp.array(bvecs)

    # -- batch entry ---------------------------------------------------------
    def pad_batch(self, weights, bench=None, bucket: int | None = None):
        """Host-side batch assembly: (B, D) weights + per-portfolio
        benchmark names/indices -> zero-padded device operands at the
        bucket shape.  Returns ``(w, bidx, B, bucket)``; ``w``/``bidx`` are
        freshly-owned device arrays, safe to donate."""
        w = np.asarray(weights, self.dtype)
        if w.ndim == 1:
            w = w[None, :]
        B, D = w.shape
        if D != self.N:
            raise ValueError(
                f"{self.space}-space engine expects {self.N} values per "
                f"portfolio, got {D}")
        bucket = bucket_for(B) if bucket is None else int(bucket)
        if bucket < B:
            raise ValueError(f"bucket {bucket} < batch size {B}")
        wp = np.zeros((bucket, self.N), self.dtype)
        wp[:B] = w
        idx = np.zeros(bucket, np.int32)
        if bench is not None:
            bench = list(bench) if not np.isscalar(bench) else [bench] * B
            if len(bench) != B:
                raise ValueError(f"{len(bench)} benchmark entries for B={B}")
            for i, b in enumerate(bench):
                if b is None:
                    continue
                idx[i] = (int(b) if not isinstance(b, str)
                          else self.benchmark_index[b])
                if not 0 <= idx[i] < len(self.benchmark_index) + 1:
                    raise KeyError(f"benchmark index {idx[i]} out of range")
        return jnp.array(wp), jnp.array(idx), B, bucket

    def query(self, weights, bench=None, bucket: int | None = None,
              trim: bool = True) -> QueryOutputs:
        """Answer B portfolio queries in one vmapped, donated jit call.

        ``weights``: (B, N|K) batch (or one (N|K,) row).  ``bench``:
        optional per-portfolio benchmark names (None entries = none).
        ``bucket`` pins the padded shape (tests / steady-state loops);
        default is :func:`bucket_for` of B.  With ``trim`` the outputs are
        sliced back to B rows (numpy); ``trim=False`` returns the raw
        padded device arrays (bench harnesses time the device step alone).
        """
        w, bidx, B, _ = self.pad_batch(weights, bench, bucket)
        # one donating call site: (w, bidx) are dead past this line in
        # either space (the padded batch is rebuilt fresh every query)
        kernel, consts = (
            (_batch_stock, (self._cov, self._X, self._svar, self._bx,
                            self._bw))
            if self.space == "stock"
            else (_batch_factor, (self._cov, self._bx)))
        out = kernel(w, bidx, *consts)
        if not trim:
            return out
        return QueryOutputs(*(np.asarray(o)[:B] for o in out))

    # -- scenario overlays ---------------------------------------------------
    def with_cov(self, cov, *, staleness: int | None = None,
                 scenario_id: str | None = None) -> "QueryEngine":
        """A sibling engine answering under a DIFFERENT covariance.

        The scenario path (mfm_tpu/scenario/): exposures, specific
        variances, benchmark tables, stock ids and dtype are SHARED with
        this engine (immutable device constants — no copies), only the
        covariance changes.  A query through the sibling runs the same
        batched kernels, so plain and scenario queries share the per-bucket
        compile cache.  ``scenario_id`` tags the sibling; the serve loop
        stamps it on every response answered through it.
        """
        import copy

        cov = np.asarray(cov)
        if cov.shape != (self.K, self.K):
            raise ValueError(f"cov must be ({self.K}, {self.K}), got "
                             f"{cov.shape}")
        if not np.isfinite(cov).all():
            raise ValueError("scenario covariance contains non-finite "
                             "entries — refuse to serve it")
        eng = copy.copy(self)
        eng._cov = jnp.array(cov.astype(self.dtype))
        eng.staleness = self.staleness if staleness is None else \
            int(staleness)
        eng.scenario_id = scenario_id
        return eng

    # -- construction from served artifacts ---------------------------------
    @classmethod
    def from_risk_state(cls, state, meta=None, benchmarks=None, dtype=None):
        """Engine over a :class:`~mfm_tpu.models.risk_model.RiskModelState`
        checkpoint's served covariance (factor space).

        Requires a GUARDED state: ``last_good_cov`` + ``staleness`` are the
        degraded-serving contract (serve/guard.py) — an unguarded state
        holds no covariance to serve.  ``meta`` (the checkpoint's
        ``__meta__``) supplies the factor-name order when it carries the
        ``save_pipeline_state`` alignment fields.
        """
        if not getattr(state, "guarded", False):
            raise ValueError(
                "state has no served covariance — the query service serves "
                "the guarded (quarantine-enabled) checkpoint's "
                "last_good_cov; re-run the pipeline with quarantine enabled")
        names = None
        if meta and "style_names" in meta and "industry_codes" in meta:
            # mirror BarraArrays.factor_names(): country + industries + styles
            names = (["country"] + [str(c) for c in meta["industry_codes"]]
                     + [str(s) for s in meta["style_names"]])
        cov = np.asarray(state.last_good_cov)
        if names is not None and len(names) != cov.shape[0]:
            names = None   # foreign checkpoint meta; fall back to f0..fK
        return cls(cov, factor_names=names, benchmarks=benchmarks,
                   staleness=int(np.asarray(state.staleness)), dtype=dtype)
