"""Async socket (and optional HTTP/1.1) front end for the query service.

Replaces the single-reader stdin pipe with a listener that accepts
CONCURRENT connections, each feeding the same thread-safe coalescing
backend (:class:`~mfm_tpu.serve.coalesce.Coalescer` locally, or
:class:`~mfm_tpu.serve.replica.FleetServer` with ``--replicas N``).  Every
existing per-request semantic survives unchanged because admission still
runs through ``QueryServer.submit_line_routed``: guards and dead-letter
quarantine, per-request deadlines, shed-oldest backpressure (a shed
notice routes to the DISPLACED request's connection, which may not be the
one that triggered it) and the circuit breaker.

Raw socket protocol (the default): JSONL both ways.  A client writes one
request per line and reads one response line per request — every
submitted line produces exactly one response eventually (immediate
reject/dead-letter/shed, or a drained answer within the linger budget),
so a client that sent N lines reads exactly N lines.  Half-closing the
write side says "no more requests"; the front end finishes delivering the
tail, then closes.

HTTP/1.1 mode (``--http``): ``POST /`` with a JSONL body (one or many
request lines) answers ``200`` with a JSONL body of the matching
responses, in submission order.  ``GET /healthz`` returns the live serve
summary; ``GET /metrics`` returns the registry snapshot JSON.

Threads: one acceptor + one reader thread per connection + one WRITER
thread per connection + the backend's linger flusher.  Delivery (which
the coalescer invokes under its lock) never touches a socket: it only
enqueues onto the connection's outbox, and the writer thread does the
blocking sends — a client that stops reading stalls (and eventually
drops) only its own connection, never admission or dispatch for the
fleet.  Backend access serializes under the coalescer lock.  This is
deliberately NOT an event loop — connection counts here are bounded by
the replica fan-in, and blocking reads keep the deadline/backpressure
story identical to the pipe loop.
"""

from __future__ import annotations

import json
import queue
import socket
import threading

from mfm_tpu.obs import instrument as _obs


class _Conn:
    """One client connection: the routing origin for its requests.

    All socket writes go through :attr:`outbox`, drained by a dedicated
    writer thread, so the backend's delivery callback (which runs under
    the coalescer lock) never blocks on a slow client.  A client whose
    outbox fills (it stopped reading) is dropped — its responses were
    already tallied; stalling the whole fleet for it is never an option."""

    #: queued-writes bound per connection; overflow drops the connection
    OUTBOX_MAX = 4096
    _CLOSE = object()   # outbox sentinel: drain queued writes, then close

    __slots__ = ("sock", "outbox", "writer", "outstanding", "eof",
                 "closed", "cid")

    def __init__(self, sock, cid: int):
        self.sock = sock
        self.outbox: queue.Queue = queue.Queue(maxsize=self.OUTBOX_MAX)
        self.outstanding = 0   # guarded by the frontend's _lock
        self.eof = False
        self.closed = False
        self.cid = cid
        self.writer = threading.Thread(target=self._write_loop,
                                       daemon=True,
                                       name=f"mfm-frontend-write{cid}")
        self.writer.start()

    def send_line(self, text: str) -> bool:
        return self.send_bytes((text + "\n").encode("utf-8"))

    def send_bytes(self, data: bytes) -> bool:
        """Enqueue one write — never blocks.  A full outbox means the
        client stopped reading: drop it."""
        if self.closed:
            return False
        try:
            self.outbox.put_nowait(data)
            return True
        except queue.Full:
            self._abort()
            return False

    def close(self) -> None:
        """Close AFTER the writer drains everything already queued (a
        direct socket close would lose delivered-but-unsent responses)."""
        try:
            self.outbox.put_nowait(self._CLOSE)
        except queue.Full:
            self._abort()

    def _abort(self) -> None:
        self.closed = True
        try:
            self.sock.close()
        except OSError:
            pass

    def _write_loop(self) -> None:
        while True:
            item = self.outbox.get()
            if item is self._CLOSE:
                break
            if self.closed:
                continue   # discard until the close sentinel arrives
            try:
                self.sock.sendall(item)
            except OSError:
                self.closed = True
        self._abort()


class _HttpPending:
    """Origin for one HTTP POST: collects its responses, in order."""

    __slots__ = ("expected", "got", "done")

    def __init__(self, expected: int):
        self.expected = expected
        self.got: list = []
        self.done = threading.Event()

    def deliver(self, resp: dict) -> None:
        self.got.append(resp)
        if len(self.got) >= self.expected:
            self.done.set()


class SocketFrontend:
    """The listener.  Wire a backend whose ``deliver`` is
    :meth:`deliver`, then :meth:`serve` (blocking) or :meth:`start`.

    Args:
      host/port: bind address (port 0 = ephemeral; :attr:`address` has
        the bound port once listening).
      http: speak HTTP/1.1 instead of raw JSONL.
      deadline_wait_s: HTTP-mode cap on waiting for a batch to flush.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 http: bool = False, deadline_wait_s: float = 30.0):
        self.host, self.port = host, int(port)
        self.http = bool(http)
        self.deadline_wait_s = float(deadline_wait_s)
        self.backend = None
        self._lsock: socket.socket | None = None
        self._lock = threading.Lock()
        self._conns: set[_Conn] = set()
        self._threads: list[threading.Thread] = []
        self._next_cid = 0
        self._stopping = False
        self.address: tuple[str, int] | None = None

    # -- delivery (the backend's `deliver` callback) -------------------------
    def deliver(self, pairs) -> None:
        """Route ``(origin, resp)`` pairs back to their connections.
        Responses for dead/unknown origins are dropped — the client hung
        up; the outcome counters already tallied the work."""
        for origin, resp in pairs:
            if isinstance(origin, _HttpPending):
                origin.deliver(resp)
                continue
            if not isinstance(origin, _Conn):
                continue
            origin.send_line(json.dumps(resp, sort_keys=True))
            with self._lock:
                origin.outstanding -= 1
                finished = origin.eof and origin.outstanding <= 0
            if finished:
                origin.close()

    # -- lifecycle -----------------------------------------------------------
    def listen(self) -> tuple[str, int]:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.host, self.port))
        ls.listen(64)
        self._lsock = ls
        self.address = ls.getsockname()[:2]
        return self.address

    def serve(self, backend) -> None:
        """Accept loop (blocking until :meth:`stop`).  ``backend`` must
        have been constructed with ``deliver=self.deliver``."""
        self.backend = backend
        if self._lsock is None:
            self.listen()
        backend.start()
        try:
            while not self._stopping:
                try:
                    csock, _addr = self._lsock.accept()
                except OSError:
                    break   # listener closed by stop()
                _obs.record_frontend_connection()
                with self._lock:
                    conn = _Conn(csock, self._next_cid)
                    self._next_cid += 1
                    self._conns.add(conn)
                t = threading.Thread(
                    target=(self._http_reader if self.http
                            else self._jsonl_reader),
                    args=(conn,), daemon=True,
                    name=f"mfm-frontend-conn{conn.cid}")
                t.start()
                self._threads.append(t)
        finally:
            self._drain_and_close()

    def start(self) -> threading.Thread:
        """:meth:`serve` on a daemon thread (tests / embedded use)."""
        if self._lsock is None:
            self.listen()
        backend = self.backend
        t = threading.Thread(target=self.serve, args=(backend,),
                             daemon=True, name="mfm-frontend-accept")
        t.start()
        return t

    def stop(self) -> None:
        self._stopping = True
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass

    def _drain_and_close(self) -> None:
        for t in self._threads:
            t.join(timeout=5.0)
        if self.backend is not None:
            self.backend.stop()
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.close()

    def _fleet_shards(self):
        """Live per-worker shards off a fleet backend (None for a plain
        coalescer).  The scrape serializes under the coalescer lock —
        never mid-batch — and each probe carries its own deadline, so a
        wedged worker costs one bounded timeout, not a hung endpoint."""
        scrape = getattr(self.backend, "scrape_fleet", None)
        if not callable(scrape):
            return None
        return scrape()

    # -- raw JSONL connections ----------------------------------------------
    def _jsonl_reader(self, conn: _Conn) -> None:
        try:
            rfile = conn.sock.makefile("r", encoding="utf-8")
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                with self._lock:
                    conn.outstanding += 1
                self.backend.submit(line, origin=conn)
        except OSError:
            pass
        finally:
            with self._lock:
                conn.eof = True
                finished = conn.outstanding <= 0
            if finished:
                conn.close()
            with self._lock:
                self._conns.discard(conn)

    # -- HTTP/1.1 connections -------------------------------------------------
    def _http_reader(self, conn: _Conn) -> None:
        try:
            rfile = conn.sock.makefile("rb")
            while True:
                req = _read_http_request(rfile)
                if req is None:
                    break
                method, path, headers, body = req
                if method == "GET" and path == "/healthz":
                    summary = _obs.serve_summary_from_registry()
                    shards = self._fleet_shards()
                    if shards is not None:
                        # live mid-run merge: one entry per worker,
                        # marked by replica ordinal — no waiting for
                        # shutdown manifests
                        summary["workers"] = [
                            {"replica": s["replica"],
                             "host": s.get("host"),
                             "alive": s["alive"],
                             "wedged": s.get("wedged", False),
                             "summary": s.get("summary")}
                            for s in shards]
                    payload = json.dumps(summary, sort_keys=True)
                    self._http_reply(conn, 200, payload,
                                     "application/json")
                elif method == "GET" and path == "/metrics":
                    from mfm_tpu.obs import slo as _slo
                    from mfm_tpu.obs.metrics import snapshot_json

                    # evaluate BEFORE the snapshot so the burn gauges in
                    # it are current; the structured block rides beside
                    slo_block = _slo.installed_summary()
                    body = snapshot_json()
                    shards = self._fleet_shards()
                    if shards is not None or slo_block is not None:
                        snap = json.loads(body)
                        if slo_block is not None:
                            snap["slo"] = slo_block
                        if shards is not None:
                            snap["workers"] = [
                                {"replica": s["replica"],
                                 "host": s.get("host"),
                                 "alive": s["alive"],
                                 "metrics": s.get("metrics"),
                                 "transport": s.get("transport")}
                                for s in shards]
                        body = json.dumps(snap, sort_keys=True)
                    self._http_reply(conn, 200, body,
                                     "application/json")
                elif method == "POST":
                    lines = [ln for ln in
                             body.decode("utf-8").splitlines()
                             if ln.strip()]
                    if not lines:
                        self._http_reply(conn, 400, "empty body\n")
                        continue
                    pend = _HttpPending(len(lines))
                    for ln in lines:
                        self.backend.submit(ln, origin=pend)
                    pend.done.wait(timeout=self.deadline_wait_s)
                    out = "".join(json.dumps(r, sort_keys=True) + "\n"
                                  for r in pend.got)
                    self._http_reply(conn, 200, out,
                                     "application/jsonl")
                else:
                    self._http_reply(conn, 404, "not found\n")
                if headers.get("connection", "").lower() == "close":
                    break
        except OSError:
            pass
        finally:
            conn.close()
            with self._lock:
                self._conns.discard(conn)

    def _http_reply(self, conn: _Conn, status: int, body: str,
                    ctype: str = "text/plain") -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found"}.get(
            status, "Error")
        data = body.encode("utf-8")
        head = (f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(data)}\r\n\r\n").encode("ascii")
        conn.send_bytes(head + data)


def _read_http_request(rfile):
    """Minimal HTTP/1.1 request parser: (method, path, headers, body) or
    None at EOF.  Enough for the JSONL POST + healthz/metrics surface —
    no chunked encoding, no continuations."""
    start = rfile.readline()
    if not start:
        return None
    try:
        method, path, _version = start.decode("ascii").split(None, 2)
    except ValueError:
        return None
    headers = {}
    while True:
        h = rfile.readline()
        if not h or h in (b"\r\n", b"\n"):
            break
        name, _, val = h.decode("latin-1").partition(":")
        headers[name.strip().lower()] = val.strip()
    length = int(headers.get("content-length", 0) or 0)
    body = rfile.read(length) if length else b""
    return method.upper(), path, headers, body
