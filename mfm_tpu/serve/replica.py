"""Worker replicas: N serving processes behind one coalescing front end,
sharing the generation-fenced checkpoint store — on one host or many.

Process model
-------------
The front end (the :class:`FleetServer` below, usually wrapped by
``serve/frontend.py``) runs ADMISSION only: request guards, dead-letter
quarantine, shed-oldest backpressure and deadline stamping through its own
:class:`~mfm_tpu.serve.server.QueryServer` — which it never drains.
Admitted raw lines pool under the coalescer's linger budget, then each
flush routes one batch to the healthy worker with the lowest EWMA batch
wall (with a starvation guard so no healthy worker goes unfed; see
:meth:`FleetServer._next_replica`).

Workers are ``mfm-tpu serve --worker`` subprocesses (spawned over pipes
with ``--replicas N``) or ``serve --worker --listen HOST:PORT`` processes
on ANY host (attached with ``--workers host:port,...``).  Both speak the
same wire protocol over a deadline-bearing transport
(:mod:`mfm_tpu.serve.transport`), load the SAME fenced checkpoint (so
re-parsing an admitted line is deterministic), and answer with the
unchanged batched drain path — which is why fleet responses stay
bitwise-identical per request id to the single-process loop.

Wire protocol (JSONL both ways, ``__fleet__`` is the control key —
reserved at ADMISSION: ``parse_request`` dead-letters any request
carrying it, and a worker accepts a control frame only when the parsed
object is exactly ``{"__fleet__": ...}``, so a client can never spoof a
flush or shift response ordinals):

- frontend -> worker: admitted request lines verbatim, then
  ``{"__fleet__": "flush"}`` to drain the batch.  A tracing frontend
  precedes the lines with ONE structured prologue frame
  ``{"__fleet__": {"op": "batch", "trace": {...}}}`` carrying the
  dispatch span and each line's parent span id; the prologue consumes no
  seq ordinal and an unknown ``op`` is ignored, so the frame is invisible
  to response routing and to older workers alike.
- worker -> frontend: one envelope ``{"seq": i, "resp": {...}}`` per line
  (``seq`` = the line's ordinal within the current batch — request ids
  need not be unique, ordinals are), then
  ``{"__fleet__": "flushed", "n": k}``.
- between batches the frontend may send single-frame probes, each
  answered with exactly one line: ``"ping"`` -> ``"pong"`` (the
  heartbeat), ``"metrics"`` -> a live summary + registry snapshot (the
  scrape-time observability shard ``/metrics`` and ``/healthz`` merge),
  and ``"reload"`` -> re-fence now and report
  ``{"ok": ..., "generation": ...}`` (the rolling-rollout step).
- piggyback: ``flushed``, ``pong`` and ``reloaded`` replies also carry
  ``clock_us`` (the worker's perf_counter stamp, for RTT-midpoint
  clock-offset estimation) and, when tracing is on, ``spans`` — the
  worker's finished spans in wire form, drained once and merged into the
  frontend ring shifted onto its clock
  (:func:`mfm_tpu.obs.trace.ingest_foreign_spans`), so ONE Chrome trace
  shows the whole request timeline across processes.  Response bodies
  are untouched: the extra keys ride only on control replies, so fleet
  responses stay bitwise-identical per request id.

Failure semantics
-----------------
- A worker that DIES mid-batch (crash, SIGKILL — detected as EOF or a
  broken pipe/reset) loses nothing but its in-flight batch: the batch is
  re-dispatched to the next healthy replica, the death and re-dispatch
  are counted, and the checkpoint bytes are untouched (workers only ever
  read the store).
- A worker that WEDGES (SIGSTOP, a hung device call — detected as a
  per-I/O deadline expiry or a missed heartbeat pong) is quarantined and
  its batch re-dispatched exactly like a death: a frozen worker holding
  a batch hostage is indistinguishable from a dead one to the client.
  The difference is bookkeeping (``wedged`` in the manifest, the
  ``mfm_fleet_transport_*`` counters) and shutdown (a wedged subprocess
  is killed, not drained).
- A worker that fails its FENCE AUDIT on reload force-opens its own
  breaker, so the whole batch comes back ``rejected`` with
  ``breaker == "fence_audit"``.  The front end does NOT deliver those: the
  replica is quarantined — drained out, never killed mid-batch — and the
  batch re-dispatches to a replica that still passes its audit.
- With NO healthy replica left, queued work answers ``error`` locally
  (clients see a well-formed response, the merged manifest shows the
  outage).

Rolling rollout (``--rollout``): workers run with ``--hold-fence`` (no
self-polling), and when the checkpoint pointer's generation moves the
front end re-fences ONE worker at a time with the ``reload`` frame —
never mid-batch, because the roll happens between dispatches under the
coalescer lock.  The admission engine and the response-cache fence
(PR 14) move LAST, only once every surviving worker reports the new
generation, so no response ever crosses a generation boundary mid-batch
and the cache can never answer ahead of the fleet.

At shutdown each worker writes its own serve manifest shard
(``serve_manifest.r{i}.json`` beside the checkpoint); the front end merges
them with its own summary into ``fleet_manifest.json``, whose audit
invariant — per-replica delivered outcome counts plus the front end's
locally-answered ledger sum to the accepted count — is what
``mfm-tpu doctor --serve`` checks, alongside the per-replica transport
counters (reconnects, heartbeat misses, redispatches).
"""

from __future__ import annotations

import json
import os
import subprocess
import time

from mfm_tpu.obs import flightrec as _frec
from mfm_tpu.obs import instrument as _obs
from mfm_tpu.obs import trace as _trace
from mfm_tpu.serve.coalesce import Coalescer
from mfm_tpu.serve.query import bucket_for
from mfm_tpu.serve.server import FLEET_CONTROL_KEY as CONTROL_KEY
from mfm_tpu.serve.transport import (
    DEFAULT_IO_TIMEOUT_S,
    PipeTransport,
    TcpTransport,
    TransportError,
    TransportTimeout,
)

#: per-replica manifest shard name beside the checkpoint
WORKER_MANIFEST_FMT = "serve_manifest.r{idx}.json"
FLEET_MANIFEST_NAME = "fleet_manifest.json"

#: EWMA smoothing for the per-replica batch-wall estimate the router keys on
EWMA_ALPHA = 0.3


class ReplicaDeadError(RuntimeError):
    """The worker is gone mid-batch (crash/SIGKILL/broken pipe)."""


class ReplicaWedgedError(ReplicaDeadError):
    """The worker is alive but frozen (deadline expiry / missed pong).

    Subclasses :class:`ReplicaDeadError` on purpose: every dispatch-side
    recovery path (quarantine + re-dispatch) treats the two identically;
    only bookkeeping and shutdown differ."""


def _control_frame(line: str) -> dict | None:
    """Parse ``line`` as a control frame, or None if it is a request.

    Only an object that is EXACTLY ``{"__fleet__": ...}`` counts:
    admission already dead-letters any request carrying the reserved key
    (``parse_request``), and the strict shape here is the second wall —
    a line that somehow reaches a worker with ``__fleet__`` among other
    keys falls through to normal admission (consuming its seq ordinal)
    instead of flushing mid-batch or silently shifting ordinals, either
    of which would desync the pipe and route responses to the wrong
    clients."""
    if CONTROL_KEY not in line[:16]:
        return None
    try:
        obj = json.loads(line)
    except ValueError:
        return None
    if isinstance(obj, dict) and set(obj) == {CONTROL_KEY}:
        return obj
    return None


# -- worker side --------------------------------------------------------------

def run_worker(server, in_fp, out_fp, *, poll_on_flush: bool = True) -> dict:
    """The worker-side loop: admitted lines in, seq envelopes out.

    ``server`` is a fully-wired :class:`QueryServer` (engine off the
    fenced checkpoint, ``reload_fn`` polling the pointer).  With
    ``poll_on_flush=False`` (the ``--hold-fence`` worker of a rolling
    rollout) the pointer is polled ONLY on the frontend's ``reload``
    frame, so generations move one worker at a time on the frontend's
    schedule.  Returns the worker's serve summary for its manifest
    shard."""

    def emit(pairs):
        for origin, resp in pairs:
            out_fp.write(json.dumps({"seq": origin, "resp": resp},
                                    sort_keys=True) + "\n")

    def flush_out():
        out_fp.flush()
        if server.policy.fsync_emits:
            try:
                os.fsync(out_fp.fileno())
            except (OSError, ValueError):
                pass

    def reply(obj):
        out_fp.write(json.dumps(obj, sort_keys=True) + "\n")
        flush_out()

    def piggyback(frame):
        # completed spans ride back on control replies so the frontend
        # can merge them into one timeline; clock_us lets it estimate
        # this process's perf_counter offset from the probe RTT
        frame["clock_us"] = time.perf_counter() * 1e6
        if _trace.tracing_enabled():
            shipped = _trace.drain_spans()
            if shipped:
                frame["spans"] = shipped
        return frame

    # Immediate responses (worker-side rejections, shed notices) BUFFER
    # until the flush control: the front end writes its whole batch before
    # it starts reading, so a worker that wrote envelopes mid-batch could
    # fill the stdout pipe while the front end fills stdin — a deadlock.
    # Holding writes until flush makes the pipe strictly half-duplex.
    # Probe frames (ping/metrics/reload) only ever arrive between batches
    # and are answered with exactly one line, which keeps the half-duplex
    # discipline: one frame in, one frame out, frontend reads immediately.
    seq = 0
    held: list = []
    trace_ctx: dict | None = None
    for line in in_fp:
        line = line.strip()
        if not line:
            continue
        ctl = _control_frame(line)
        if ctl is not None:
            kind = ctl[CONTROL_KEY]
            if isinstance(kind, dict):
                # structured control frame: op dispatch.  Today's only op
                # is the trace-context prologue a tracing frontend sends
                # before its batch lines; unknown ops are ignored, not
                # fatal, so an older worker survives a newer frontend.
                if kind.get("op") == "batch":
                    tr = kind.get("trace")
                    trace_ctx = tr if isinstance(tr, dict) else {}
                continue
            if kind == "flush":
                n_batch = seq
                bsp = None
                if _trace.tracing_enabled():
                    ref = (trace_ctx or {}).get("dispatch") or []
                    bsp = _trace.start_span(
                        "worker.batch",
                        trace_id=(ref[0] if len(ref) > 0 else None),
                        parent_id=(ref[1] if len(ref) > 1 else None),
                        n=n_batch)
                emit(held)
                held = []
                if poll_on_flush:
                    server.poll_reload()
                while server._queue:
                    emit(server.drain_routed())
                if bsp is not None:
                    _trace.end_span(bsp)
                trace_ctx = None
                reply(piggyback({CONTROL_KEY: "flushed", "n": n_batch}))
                seq = 0   # seq is an ordinal WITHIN a batch
            elif kind == "ping":
                reply(piggyback({CONTROL_KEY: "pong"}))
            elif kind == "metrics":
                from mfm_tpu.obs.metrics import REGISTRY
                reply({CONTROL_KEY: "metrics",
                       "summary": _obs.serve_summary_from_registry(),
                       "metrics": REGISTRY.snapshot()})
            elif kind == "reload":
                rsp = (_trace.start_span("worker.reload_fence")
                       if _trace.tracing_enabled() else None)
                server.poll_reload()
                # a reload that failed its fence audit force-opened the
                # breaker; report it so the frontend quarantines us
                # instead of shipping batches that would all reject
                ok = not (server.breaker.state == "open"
                          and server.breaker.open_reason == "fence_audit")
                if rsp is not None:
                    _trace.end_span(rsp, ok=ok,
                                    generation=server.generation)
                reply(piggyback({CONTROL_KEY: "reloaded", "ok": ok,
                                 "generation": server.generation}))
            continue
        rsp = None
        if _trace.tracing_enabled() and trace_ctx is not None:
            parents = trace_ctx.get("parents") or []
            par = parents[seq] if seq < len(parents) else None
            if par:
                # the frontend's serve.request span for this ordinal is
                # the parent; the trace id matches its sha-derived one,
                # so the two processes' spans join in one timeline
                rsp = _trace.start_span(
                    "worker.recv", trace_id=par[0], parent_id=par[1],
                    seq=seq)
        held.extend(server.submit_line_routed(line, origin=seq))
        if rsp is not None:
            _trace.end_span(rsp)
        seq += 1
    # EOF: drain the tail (a frontend that closes our stdin without a
    # final flush still gets every admitted request answered)
    emit(held)
    if poll_on_flush:
        server.poll_reload()
    while server._queue:
        emit(server.drain_routed())
    flush_out()
    server.close()
    return _obs.serve_summary_from_registry()


# -- frontend side ------------------------------------------------------------

class Replica:
    """One worker (spawned subprocess or remote TCP peer) + its ledger."""

    #: capability flag the dispatcher checks before prepending a trace
    #: prologue frame to a batch (test stubs lack it -> plain batches)
    accepts_trace_frames = True

    def __init__(self, idx: int, cmd: list, env: dict | None = None, *,
                 io_timeout_s: float = DEFAULT_IO_TIMEOUT_S):
        self.idx = int(idx)
        self.cmd = list(cmd)
        self.host = "local"
        self.proc = subprocess.Popen(
            self.cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, env=env)
        self.transport = PipeTransport(self.proc, io_timeout_s=io_timeout_s)
        self._init_ledger()

    @classmethod
    def connect(cls, idx: int, addr, *,
                io_timeout_s: float = DEFAULT_IO_TIMEOUT_S,
                attempts: int = 5, backoff_s: float = 0.05,
                sleep=None) -> "Replica":
        """Attach to a ``serve --worker --listen`` process on any host.
        ``addr`` is a ``(host, port)`` pair; dialing retries with
        exponential backoff (the worker may still be loading its
        checkpoint), and exhaustion raises the last ``OSError`` stamped
        ``phase="connect"``."""
        self = cls.__new__(cls)
        self.idx = int(idx)
        self.cmd = None
        self.host = f"{addr[0]}:{int(addr[1])}"
        self.proc = None
        kw = {} if sleep is None else {"sleep": sleep}
        self.transport = TcpTransport.connect(
            (addr[0], int(addr[1])), io_timeout_s=io_timeout_s,
            attempts=attempts, backoff_s=backoff_s, **kw)
        self._init_ledger()
        _obs.record_transport_reconnects(
            self.idx, self.transport.counters["reconnects"])
        return self

    def _init_ledger(self) -> None:
        self.quarantined = False
        self.dead = False      # transport saw EOF/broken pipe/reset
        self.wedged = False    # transport deadline or heartbeat expired
        #: outcome -> responses DELIVERED to clients off this replica
        #: (a quarantined fence-audit batch is not delivered, by design)
        self.delivered: dict[str, int] = {}
        #: router state: smoothed batch wall (None until the first batch
        #: lands — fresh workers outrank everyone so each gets fed early)
        self.ewma_wall: float | None = None
        self.idle_rounds = 0
        #: monotonic stamp of the last successful exchange; None until
        #: first contact (a worker still importing/loading its checkpoint
        #: must not be heartbeat-probed into a false quarantine)
        self.last_io_t: float | None = None
        self.heartbeat_misses = 0
        #: requests re-dispatched AWAY from this replica after it failed
        self.redispatches = 0
        #: perf_counter offset estimate (peer - local, µs) and half-RTT
        #: uncertainty, refreshed whenever a tighter probe lands; spans
        #: the worker ships are corrected by the negated offset
        self.clock_offset_us: float | None = None
        self.clock_uncertainty_us: float = 0.0

    @property
    def alive(self) -> bool:
        if self.quarantined or self.dead or self.wedged:
            return False
        return self.proc is None or self.proc.poll() is None

    # -- transport-failure bookkeeping ---------------------------------------
    def _transport_failed(self, e: TransportError) -> ReplicaDeadError:
        if isinstance(e, TransportTimeout):
            self.wedged = True
            _obs.record_transport_timeout(self.idx, e.phase)
            return ReplicaWedgedError(
                f"replica {self.idx} ({self.host}): {e}")
        self.dead = True
        return ReplicaDeadError(f"replica {self.idx} ({self.host}): {e}")

    def _gone(self, what: str) -> ReplicaDeadError:
        self.dead = True
        rc = self.proc.poll() if self.proc is not None else None
        return ReplicaDeadError(
            f"replica {self.idx} ({self.host}): {what} (rc {rc})")

    def _recv_obj(self, timeout_s: float | None, what: str) -> dict:
        """One parsed frame off the transport; failures mark this replica
        dead/wedged and raise the matching error."""
        try:
            raw = self.transport.recv_line(timeout_s)
        except TransportError as e:
            raise self._transport_failed(e) from e
        if raw is None:
            raise self._gone(f"EOF {what}")
        try:
            obj = json.loads(raw)
        except ValueError as e:
            raise self._gone(f"torn output line {what}") from e
        return obj

    # -- the wire calls (all I/O deadline-bearing; mfmsync: these run
    # under the coalescer lock, two levels above the raw fd waits) -----------
    def _absorb_reply_telemetry(self, obj: dict, t0: float,
                                t1: float) -> None:
        """Fold a control reply's piggyback into local state: refresh the
        clock-offset estimate when this probe bounds it at least as tight
        as the current one (ping RTTs beat batch walls), then merge any
        shipped spans into the local ring shifted by the NEGATED offset
        (the probe measures peer - local) with the exchange bracket
        ``(t0, t1)`` as the skew-sanity window."""
        clock = obj.get("clock_us")
        if isinstance(clock, (int, float)):
            off, unc = _trace.clock_offset_from_probe(t0, t1, float(clock))
            if (self.clock_offset_us is None
                    or unc <= self.clock_uncertainty_us):
                self.clock_offset_us = off
                self.clock_uncertainty_us = unc
        shipped = obj.get("spans")
        if shipped:
            _trace.ingest_foreign_spans(
                shipped, offset_us=-(self.clock_offset_us or 0.0),
                uncertainty_us=self.clock_uncertainty_us,
                window_us=(t0 * 1e6, t1 * 1e6), worker=self.idx)

    def run_batch(self, lines: list) -> dict:
        """Send one batch + flush, collect the envelopes.  Returns
        ``{seq: resp}``; raises :class:`ReplicaDeadError` /
        :class:`ReplicaWedgedError` on a broken or silent worker."""
        t0 = time.monotonic()
        try:
            self.transport.send_lines(
                list(lines) + [json.dumps({CONTROL_KEY: "flush"})])
        except TransportError as e:
            raise self._transport_failed(e) from e
        resps: dict = {}
        while True:
            obj = self._recv_obj(None, "mid-batch")
            if obj.get(CONTROL_KEY) == "flushed":
                flushed = obj
                break
            resps[int(obj["seq"])] = obj["resp"]
        t1 = time.monotonic()
        wall = t1 - t0
        self.ewma_wall = (wall if self.ewma_wall is None
                          else EWMA_ALPHA * wall
                          + (1.0 - EWMA_ALPHA) * self.ewma_wall)
        self.last_io_t = t1
        self._absorb_reply_telemetry(flushed, t0, t1)
        return resps

    def ping(self, timeout_s: float | None = None) -> None:
        """One heartbeat round trip; a miss marks this replica wedged.
        Doubling as the clock probe: the pong's ``clock_us`` against the
        tight ping RTT is the best offset estimate this replica gets."""
        t0 = time.monotonic()
        try:
            self.transport.send_frame({CONTROL_KEY: "ping"})
            raw = self.transport.recv_line(timeout_s)
        except TransportTimeout as e:
            self.heartbeat_misses += 1
            _obs.record_heartbeat_miss(self.idx)
            raise self._transport_failed(e) from e
        except TransportError as e:
            raise self._transport_failed(e) from e
        if raw is None:
            raise self._gone("EOF on heartbeat")
        try:
            obj = json.loads(raw)
        except ValueError as e:
            raise self._gone("torn heartbeat reply") from e
        if obj.get(CONTROL_KEY) != "pong":
            raise self._gone(f"bad heartbeat reply {raw[:64]!r}")
        t1 = time.monotonic()
        self.last_io_t = t1
        self._absorb_reply_telemetry(obj, t0, t1)

    def scrape(self, timeout_s: float | None = None) -> dict:
        """Live observability shard: the worker's serve summary + metrics
        snapshot, for the frontend's mid-run ``/metrics`` merge."""
        try:
            self.transport.send_frame({CONTROL_KEY: "metrics"})
        except TransportError as e:
            raise self._transport_failed(e) from e
        obj = self._recv_obj(timeout_s, "on metrics scrape")
        self.last_io_t = time.monotonic()
        return obj

    def reload_worker(self, timeout_s: float | None = None) -> dict:
        """One rolling-rollout step: tell the worker to re-fence NOW and
        report ``{"ok": ..., "generation": ...}``."""
        t0 = time.monotonic()
        try:
            self.transport.send_frame({CONTROL_KEY: "reload"})
        except TransportError as e:
            raise self._transport_failed(e) from e
        obj = self._recv_obj(timeout_s, "on reload")
        t1 = time.monotonic()
        self.last_io_t = t1
        self._absorb_reply_telemetry(obj, t0, t1)
        return obj

    def transport_counters(self) -> dict:
        """The manifest's per-replica transport block."""
        c = dict(self.transport.counters)
        c["failure_phases"] = dict(c["failure_phases"])
        c["heartbeat_misses"] = self.heartbeat_misses
        c["redispatches"] = self.redispatches
        return c

    def close(self, timeout: float = 30.0) -> int | None:
        """Graceful drain-out: half-closing the write side lets the
        worker answer its tail and write its manifest shard.  A wedged
        worker cannot drain — its process is killed outright.  Returns
        the exit code (None for a TCP replica, whose process belongs to
        another host)."""
        self.transport.close()
        if self.proc is None:
            # TCP: drain the tail so the remote worker's final writes
            # never block, then drop the socket; it writes its own shard
            try:
                while self.transport.recv_line(min(timeout, 5.0)) is not None:
                    pass
            except TransportError:
                pass
            self.transport.abort()
            return None
        if self.wedged:
            self.proc.kill()
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        return self.proc.poll()


def worker_cmd(state_path: str, *, worker_id: int, policy_args=(),
               python=None) -> list:
    """The ``mfm-tpu serve --worker`` argv for one replica."""
    import sys
    py = python or sys.executable
    return ([py, "-m", "mfm_tpu.cli", "serve", str(state_path),
             "--worker", "--worker-id", str(worker_id)]
            + list(policy_args))


def replica_env(idx: int, base_env=None) -> dict:
    """Worker environment with chaos-kill targeting: when
    ``MFM_CHAOS_KILL_REPLICA`` names this replica's index, the
    ``MFM_CHAOS_KILL``/``MFM_CHAOS_KILL_MATCH`` pair passes through;
    every other worker (and the front end, which never drains) runs
    clean — the drill kills exactly one replica."""
    env = dict(base_env if base_env is not None else os.environ)
    target = env.pop("MFM_CHAOS_KILL_REPLICA", None)
    if target is not None and int(target) != int(idx):
        env.pop("MFM_CHAOS_KILL", None)
        env.pop("MFM_CHAOS_KILL_MATCH", None)
    return env


class FleetServer(Coalescer):
    """The fleet dispatcher: a :class:`Coalescer` whose flush sends each
    batch to a worker replica instead of draining locally.

    ``server`` is the ADMISSION QueryServer (same engine/policy as the
    workers, but it never drains — its queue is the coalescing pool and
    its guards/shed/dead-letter run in-process so rejects never cost a
    pipe round trip).

    Args (beyond :class:`Coalescer`):
      heartbeat_s: a healthy replica idle this long is pinged before it
        gets another batch; a missed pong quarantines it (0 = off).
      heartbeat_timeout_s: how long a pong (or a live scrape) may take.
      rollout_check: optional zero-cost pointer probe returning the
        current checkpoint generation.  When set, the fleet is in
        ROLLING ROLLOUT mode: a generation move re-fences one worker at
        a time (see :meth:`_roll_fleet`) instead of letting everything
        self-poll.
    """

    #: dispatches a healthy replica may sit unpicked before the router
    #: must feed it regardless of EWMA rank (starvation guard — also
    #: what keeps slow-but-correct workers exercising their fence)
    starve_rounds = 4

    def __init__(self, server, replicas: list, *, linger_s: float = 0.01,
                 clock=None, deliver=None, cache=None,
                 heartbeat_s: float = 5.0,
                 heartbeat_timeout_s: float = 2.0,
                 rollout_check=None):
        super().__init__(server, linger_s=linger_s,
                         clock=clock or time.monotonic, deliver=deliver,
                         cache=cache)
        self.replicas = list(replicas)
        self.accepted_total = 0   # requests popped for dispatch
        #: outcome -> responses the FRONT END answered locally (deadline
        #: expiry in its queue, no-healthy-replica outage, dropped seq);
        #: merged into the fleet manifest so the delivery audit still
        #: balances — every accepted request's response is in exactly one
        #: ledger, a replica's or this one
        self.local_delivered: dict[str, int] = {}
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._rollout_check = rollout_check
        # the generation the whole fleet last agreed on (rollout mode);
        # at construction every worker just loaded the pointed-at state
        self._fleet_generation = (rollout_check()
                                  if rollout_check is not None else None)

    # -- reload discipline ---------------------------------------------------
    # (callers hold self._lock, via Coalescer.submit/poll/flush/stop)
    def _poll_reload_locked(self) -> None:
        self._poll_generation()

    def _poll_generation(self) -> None:
        """The fleet's per-flush reload point.  Plain ``--watch`` fleets
        poll the admission server directly (workers self-poll too, and
        the response-cache fence rides the admission reload); a rollout
        fleet peeks the pointer and rolls workers one at a time."""
        if self._rollout_check is None:
            self.server.poll_reload()
            return
        gen = self._rollout_check()
        if gen is None or gen == self._fleet_generation:
            return
        self._roll_fleet(gen)

    def _roll_fleet(self, gen) -> None:
        """Rolling zero-downtime rollout: re-fence ONE worker at a time
        behind the generation fence.  Runs between batches (under the
        coalescer lock), so no batch ever straddles a generation.  The
        admission engine and the response-cache fence move LAST, only
        when every surviving worker reports ``gen`` — the fence the
        cache keys on can never run ahead of the fleet."""
        agreed = True
        for w in self.replicas:
            if not w.alive:
                continue
            reload_worker = getattr(w, "reload_worker", None)
            if reload_worker is None:
                continue
            try:
                rep = reload_worker()
            except ReplicaWedgedError:
                _obs.record_replica_quarantine()
                continue
            except ReplicaDeadError:
                _obs.record_replica_death()
                continue
            _obs.record_rollout_step()
            if not rep.get("ok", False):
                # its new generation failed the fence audit: the worker
                # is already rejecting (breaker open) — drain it out
                w.quarantined = True
                _obs.record_replica_quarantine()
                _frec.record_event("fence_audit_quarantine",
                                   replica=w.idx, generation=gen,
                                   during="rollout")
                _frec.trigger_dump("fence_audit",
                                   state=self._flightrec_state())
                continue
            if rep.get("generation") not in (None, gen):
                # pointer moved again mid-roll; re-roll next flush
                agreed = False
        if agreed:
            self.server.poll_reload()
            self._fleet_generation = gen

    # -- routing -------------------------------------------------------------
    def _next_replica(self):
        """Lowest-EWMA healthy worker, with two overrides: a FRESH worker
        (no batch yet) outranks everyone — each replica gets fed early,
        which is also what keeps deterministic drills deterministic for
        the first full cycle — and a worker starved past
        ``starve_rounds`` dispatches is fed regardless of rank."""
        healthy = [w for w in self.replicas if w.alive]
        if not healthy:
            return None
        starved = [w for w in healthy
                   if getattr(w, "idle_rounds", 0) >= self.starve_rounds]
        if starved:
            pick = max(starved,
                       key=lambda w: (getattr(w, "idle_rounds", 0), -w.idx))
        else:
            fresh = [w for w in healthy
                     if getattr(w, "ewma_wall", None) is None]
            pick = min(fresh or healthy,
                       key=lambda w: (getattr(w, "ewma_wall", None) or 0.0,
                                      w.idx))
        for w in healthy:
            w.idle_rounds = (0 if w is pick
                             else getattr(w, "idle_rounds", 0) + 1)
        return pick

    def _heartbeat_ok(self, w) -> bool:
        """Probe a long-idle replica before trusting it with a batch.
        Never probes a worker that has not answered ANYTHING yet (it may
        legitimately still be loading its checkpoint)."""
        ping = getattr(w, "ping", None)
        if ping is None or self.heartbeat_s <= 0:
            return True
        last = getattr(w, "last_io_t", None)
        if last is None or time.monotonic() - last < self.heartbeat_s:
            return True
        try:
            ping(self.heartbeat_timeout_s)
        except ReplicaWedgedError:
            _obs.record_replica_quarantine()
            return False
        except ReplicaDeadError:
            _obs.record_replica_death()
            return False
        return True

    # callers hold self._lock (Coalescer.submit/poll/flush/stop take it)
    def _flush_locked(self, trigger: str) -> list:
        out = []
        now = self._clock()
        lingered = (now - self._oldest_t) if self._oldest_t is not None else 0.0
        while self.server._queue:
            # move the fence HERE too (workers reload on their own, or
            # one at a time under --rollout): the admission engine,
            # health stamp, and the response-cache fence must track the
            # fleet, or the front-end cache would keep answering from a
            # retired generation after a hot reload
            self._poll_generation()
            batch = []
            while (self.server._queue
                   and len(batch) < self.server.policy.batch_max):
                batch.append(self.server._queue.popleft())
            _obs.record_queue_depth(len(self.server._queue))
            _obs.record_coalesce_flush(len(batch), bucket_for(len(batch)),
                                       trigger, lingered)
            lingered = 0.0
            self.accepted_total += len(batch)
            # enforce deadlines HERE, not just in the worker: workers
            # re-stamp deadlines at their own enqueue time, so time spent
            # lingering or queued at the front end would otherwise never
            # count against a request's budget — same check drain() runs
            live = []
            for r in batch:
                if now > r.deadline_t:
                    out.append(self._local_deadline(r))
                else:
                    live.append(r)
            if live:
                out.extend(self._dispatch(live))
        self._oldest_t = None
        return out

    def _count_local(self, outcome: str) -> None:
        self.local_delivered[outcome] = \
            self.local_delivered.get(outcome, 0) + 1

    def _local_error(self, r, detail: str) -> tuple:
        _obs.record_query_outcome("error")
        self._count_local("error")
        if r.span is not None:
            _trace.end_span(r.span, outcome="error")
        return (r.origin, self.server._stamp(
            {"id": r.rid, "ok": False, "outcome": "error",
             "detail": detail},
            scenario_id=r.scenario, trace_id=r.trace_id))

    def _local_deadline(self, r) -> tuple:
        _obs.record_query_outcome("deadline")
        self._count_local("deadline")
        if r.span is not None:
            _trace.end_span(r.span, outcome="deadline")
        return (r.origin, self.server._stamp(
            {"id": r.rid, "ok": False, "outcome": "deadline"},
            scenario_id=r.scenario, trace_id=r.trace_id))

    def _flightrec_state(self) -> dict:
        """The live-context block a triggered flight-recorder dump
        bundles: breaker, rollout generation, per-replica ledgers."""
        b = self.server.breaker
        return {
            "breaker": {"state": b.state, "open_reason": b.open_reason},
            "fleet_generation": self._fleet_generation,
            "accepted_total": self.accepted_total,
            "replicas": [
                {"replica": w.idx, "host": getattr(w, "host", "local"),
                 "alive": bool(getattr(w, "alive", True)),
                 "quarantined": bool(getattr(w, "quarantined", False)),
                 "wedged": bool(getattr(w, "wedged", False)),
                 "dead": bool(getattr(w, "dead", False)),
                 "delivered_total": sum(getattr(w, "delivered",
                                                {}).values())}
                for w in self.replicas],
        }

    def _dispatch(self, batch: list) -> list:
        lines = [r.line for r in batch]
        head = batch[0]
        while True:
            w = self._next_replica()
            if w is None:
                _frec.record_event("fleet_outage", trace_id=head.trace_id,
                                   n=len(lines))
                return [self._local_error(r, "no healthy replicas")
                        for r in batch]
            if not self._heartbeat_ok(w):
                continue   # quarantined before the batch left — no loss
            _obs.record_fleet_dispatch(w.idx, len(lines))
            _frec.record_event("dispatch", trace_id=head.trace_id,
                               replica=w.idx, n=len(lines))
            dsp = None
            wire = lines
            if _trace.tracing_enabled():
                # the dispatch span is the worker.batch span's parent;
                # each request's admission span parents its worker.recv
                dsp = _trace.start_span(
                    "fleet.dispatch", trace_id=head.trace_id,
                    parent_id=(head.span.span_id
                               if head.span is not None else None),
                    replica=w.idx, n=len(lines))
                if getattr(w, "accepts_trace_frames", False):
                    payload = {"op": "batch", "trace": {
                        "dispatch": [dsp.trace_id, dsp.span_id],
                        "parents": [
                            [r.trace_id,
                             (r.span.span_id if r.span is not None
                              else None)]
                            for r in batch]}}
                    wire = [json.dumps({CONTROL_KEY: payload},
                                       sort_keys=True)] + lines
            try:
                resps = w.run_batch(wire)
            except ReplicaWedgedError:
                # alive-but-frozen mid-batch: quarantine exactly like a
                # death and re-dispatch; close() kills it at shutdown
                w.redispatches = getattr(w, "redispatches", 0) + len(lines)
                _obs.record_replica_quarantine()
                _obs.record_fleet_redispatch(len(lines))
                if dsp is not None:
                    _trace.end_span(dsp, outcome="wedged")
                _frec.record_event("wedge_quarantine",
                                   trace_id=head.trace_id, replica=w.idx,
                                   n=len(lines))
                _frec.trigger_dump("wedge_quarantine",
                                   trace_id=head.trace_id,
                                   state=self._flightrec_state())
                continue
            except ReplicaDeadError:
                w.redispatches = getattr(w, "redispatches", 0) + len(lines)
                _obs.record_replica_death()
                _obs.record_fleet_redispatch(len(lines))
                if dsp is not None:
                    _trace.end_span(dsp, outcome="dead")
                _frec.record_event("replica_death",
                                   trace_id=head.trace_id, replica=w.idx,
                                   n=len(lines))
                continue
            if (len(resps) == len(lines) and resps and
                    all(isinstance(v, dict)
                        and v.get("breaker") == "fence_audit"
                        for v in resps.values())):
                # the replica's own reload failed its fence audit: drain
                # it out (no more batches; graceful close at shutdown so
                # it still writes its manifest shard) and re-dispatch
                w.quarantined = True
                w.redispatches = getattr(w, "redispatches", 0) + len(lines)
                _obs.record_replica_quarantine()
                _obs.record_fleet_redispatch(len(lines))
                if dsp is not None:
                    _trace.end_span(dsp, outcome="fence_audit")
                _frec.record_event("fence_audit_quarantine",
                                   trace_id=head.trace_id, replica=w.idx,
                                   n=len(lines))
                _frec.trigger_dump("fence_audit", trace_id=head.trace_id,
                                   state=self._flightrec_state())
                continue
            if dsp is not None:
                _trace.end_span(dsp, outcome="ok")
            pairs = []
            for i, r in enumerate(batch):
                resp = resps.get(i)
                if resp is None:
                    pairs.append(self._local_error(
                        r, f"replica {w.idx} dropped seq {i}"))
                    continue
                outcome = str(resp.get("outcome", "error"))
                _obs.record_query_outcome(outcome)
                w.delivered[outcome] = w.delivered.get(outcome, 0) + 1
                if r.span is not None:
                    _trace.end_span(r.span, outcome=outcome)
                pairs.append((r.origin, resp))
            return pairs

    # -- live observability ---------------------------------------------------
    def scrape_fleet(self) -> list:
        """Live per-worker shards for the frontend's mid-run ``/metrics``
        and ``/healthz`` merge — each marked by replica ordinal.  Runs
        the probes under the coalescer lock (never mid-batch); a worker
        that fails its scrape is quarantined like any transport failure."""
        shards = []
        with self._lock:
            for w in self.replicas:
                entry = {"replica": w.idx,
                         "host": getattr(w, "host", "local"),
                         "alive": bool(w.alive),
                         "quarantined": bool(getattr(w, "quarantined",
                                                     False)),
                         "wedged": bool(getattr(w, "wedged", False))}
                tc = getattr(w, "transport_counters", None)
                if callable(tc):
                    entry["transport"] = tc()
                scrape = getattr(w, "scrape", None)
                if entry["alive"] and callable(scrape):
                    try:
                        obj = scrape(self.heartbeat_timeout_s)
                    except ReplicaDeadError:
                        obj = None
                        entry["alive"] = bool(w.alive)
                    if isinstance(obj, dict):
                        entry["summary"] = obj.get("summary")
                        entry["metrics"] = obj.get("metrics")
                shards.append(entry)
        return shards

    def close_replicas(self) -> None:
        for w in self.replicas:
            w.close()


# -- merged manifest ----------------------------------------------------------

def build_fleet_manifest(frontend_summary: dict, fleet,
                         manifest_dir: str) -> dict:
    """Merge the front end's summary with every replica's ledger + its
    manifest shard (a SIGKILLed worker has no shard — that IS the loss
    the manifest counts).  The ``audit`` block is the doctor invariant:
    per-replica delivered outcome counts plus the front end's own
    locally-answered ledger (deadline expiry at the front end, outage
    errors, dropped seqs — all well-formed responses clients DID receive)
    must sum to the accepted count.  Each replica also carries its
    transport counters (reconnects, heartbeat misses, redispatches, I/O
    timeouts by phase), totalled in the top-level ``transport`` block."""
    from mfm_tpu.obs.manifest import ManifestError, read_run_manifest
    reps = []
    outcomes_sum = 0
    totals = {"reconnects": 0, "heartbeat_misses": 0, "redispatches": 0,
              "io_timeouts": 0}
    for w in fleet.replicas:
        proc = getattr(w, "proc", None)
        rc = proc.poll() if proc is not None else None
        shard_path = os.path.join(manifest_dir,
                                  WORKER_MANIFEST_FMT.format(idx=w.idx))
        shard = None
        try:
            shard = read_run_manifest(shard_path).get("serve")
        except (ManifestError, OSError):
            pass
        total = sum(w.delivered.values())
        outcomes_sum += total
        tcfn = getattr(w, "transport_counters", None)
        tc = tcfn() if callable(tcfn) else None
        if isinstance(tc, dict):
            totals["reconnects"] += int(tc.get("reconnects", 0))
            totals["heartbeat_misses"] += int(tc.get("heartbeat_misses", 0))
            totals["redispatches"] += int(tc.get("redispatches", 0))
            totals["io_timeouts"] += (int(tc.get("send_timeouts", 0))
                                      + int(tc.get("recv_timeouts", 0)))
        reps.append({
            "replica": w.idx,
            "host": getattr(w, "host", "local"),
            "exit_code": rc,
            "lost": bool(getattr(w, "dead", False)
                         or (rc is not None and rc != 0)),
            "wedged": bool(getattr(w, "wedged", False)),
            "quarantined": bool(w.quarantined),
            "outcomes": dict(sorted(w.delivered.items())),
            "outcomes_total": total,
            "transport": tc,
            "manifest_shard": (WORKER_MANIFEST_FMT.format(idx=w.idx)
                               if shard is not None else None),
            "worker_summary": shard,
        })
    accepted = int(fleet.accepted_total)
    local = dict(sorted(getattr(fleet, "local_delivered", {}).items()))
    local_total = sum(local.values())
    slo = (frontend_summary.get("slo")
           if isinstance(frontend_summary, dict) else None)
    return {
        "schema": 1,
        "frontend": frontend_summary,
        "slo": slo,
        "flightrec": {"armed": _frec.armed_path() is not None,
                      "events": len(_frec.events())},
        "accepted_total": accepted,
        "replicas": reps,
        "transport": totals,
        "frontend_local": {
            "outcomes": local,
            "outcomes_total": local_total,
        },
        "audit": {
            "replica_outcomes_sum": outcomes_sum,
            "frontend_local_total": local_total,
            "delivered_total": outcomes_sum + local_total,
            "accepted_total": accepted,
            "consistent": outcomes_sum + local_total == accepted,
        },
    }
