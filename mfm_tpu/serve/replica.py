"""Worker replicas: N serving processes behind one coalescing front end,
sharing the generation-fenced checkpoint store.

Process model
-------------
The front end (the :class:`FleetServer` below, usually wrapped by
``serve/frontend.py``) runs ADMISSION only: request guards, dead-letter
quarantine, shed-oldest backpressure and deadline stamping through its own
:class:`~mfm_tpu.serve.server.QueryServer` — which it never drains.
Admitted raw lines pool under the coalescer's linger budget, then each
flush round-robins one batch to a worker replica over a pipe.

Workers are ``mfm-tpu serve --worker`` subprocesses.  Each loads the SAME
fenced checkpoint (so re-parsing an admitted line is deterministic),
polls the pointer between batches for zero-downtime hot reload, and
answers with the unchanged batched drain path — which is why fleet
responses stay bitwise-identical per request id to the single-process
loop.

Wire protocol (JSONL both ways, ``__fleet__`` is the control key —
reserved at ADMISSION: ``parse_request`` dead-letters any request
carrying it, and a worker accepts a control frame only when the parsed
object is exactly ``{"__fleet__": ...}``, so a client can never spoof a
flush or shift response ordinals):

- frontend -> worker: admitted request lines verbatim, then
  ``{"__fleet__": "flush"}`` to drain the batch.
- worker -> frontend: one envelope ``{"seq": i, "resp": {...}}`` per line
  (``seq`` = the line's ordinal within the current batch — request ids
  need not be unique, ordinals are), then
  ``{"__fleet__": "flushed", "n": k}``.

Failure semantics
-----------------
- A worker that DIES mid-batch (crash, SIGKILL — detected as EOF or a
  broken pipe) loses nothing but its in-flight batch: the batch is
  re-dispatched to the next healthy replica, the death and re-dispatch
  are counted, and the checkpoint bytes are untouched (workers only ever
  read the store).
- A worker that fails its FENCE AUDIT on reload force-opens its own
  breaker, so the whole batch comes back ``rejected`` with
  ``breaker == "fence_audit"``.  The front end does NOT deliver those: the
  replica is quarantined — drained out, never killed mid-batch — and the
  batch re-dispatches to a replica that still passes its audit.
- With NO healthy replica left, queued work answers ``error`` locally
  (clients see a well-formed response, the merged manifest shows the
  outage).

At shutdown each worker writes its own serve manifest shard
(``serve_manifest.r{i}.json`` beside the checkpoint); the front end merges
them with its own summary into ``fleet_manifest.json``, whose audit
invariant — per-replica delivered outcome counts plus the front end's
locally-answered ledger sum to the accepted count — is what
``mfm-tpu doctor --serve`` checks.
"""

from __future__ import annotations

import json
import os
import subprocess

from mfm_tpu.obs import instrument as _obs
from mfm_tpu.obs import trace as _trace
from mfm_tpu.serve.coalesce import Coalescer
from mfm_tpu.serve.query import bucket_for
from mfm_tpu.serve.server import FLEET_CONTROL_KEY as CONTROL_KEY

#: per-replica manifest shard name beside the checkpoint
WORKER_MANIFEST_FMT = "serve_manifest.r{idx}.json"
FLEET_MANIFEST_NAME = "fleet_manifest.json"


class ReplicaDeadError(RuntimeError):
    """The worker's pipe broke mid-batch (crash/SIGKILL)."""


def _control_frame(line: str) -> dict | None:
    """Parse ``line`` as a control frame, or None if it is a request.

    Only an object that is EXACTLY ``{"__fleet__": ...}`` counts:
    admission already dead-letters any request carrying the reserved key
    (``parse_request``), and the strict shape here is the second wall —
    a line that somehow reaches a worker with ``__fleet__`` among other
    keys falls through to normal admission (consuming its seq ordinal)
    instead of flushing mid-batch or silently shifting ordinals, either
    of which would desync the pipe and route responses to the wrong
    clients."""
    if CONTROL_KEY not in line[:16]:
        return None
    try:
        obj = json.loads(line)
    except ValueError:
        return None
    if isinstance(obj, dict) and set(obj) == {CONTROL_KEY}:
        return obj
    return None


# -- worker side --------------------------------------------------------------

def run_worker(server, in_fp, out_fp) -> dict:
    """The worker-side loop: admitted lines in, seq envelopes out.

    ``server`` is a fully-wired :class:`QueryServer` (engine off the
    fenced checkpoint, ``reload_fn`` polling the pointer).  Returns the
    worker's serve summary for its manifest shard."""

    def emit(pairs):
        for origin, resp in pairs:
            out_fp.write(json.dumps({"seq": origin, "resp": resp},
                                    sort_keys=True) + "\n")

    def flush_out():
        out_fp.flush()
        if server.policy.fsync_emits:
            try:
                os.fsync(out_fp.fileno())
            except (OSError, ValueError):
                pass

    # Immediate responses (worker-side rejections, shed notices) BUFFER
    # until the flush control: the front end writes its whole batch before
    # it starts reading, so a worker that wrote envelopes mid-batch could
    # fill the stdout pipe while the front end fills stdin — a deadlock.
    # Holding writes until flush makes the pipe strictly half-duplex.
    seq = 0
    held: list = []
    for line in in_fp:
        line = line.strip()
        if not line:
            continue
        ctl = _control_frame(line)
        if ctl is not None:
            if ctl[CONTROL_KEY] == "flush":
                n_batch = seq
                emit(held)
                held = []
                server.poll_reload()
                while server._queue:
                    emit(server.drain_routed())
                out_fp.write(json.dumps(
                    {CONTROL_KEY: "flushed", "n": n_batch},
                    sort_keys=True) + "\n")
                flush_out()
                seq = 0   # seq is an ordinal WITHIN a batch
            continue
        held.extend(server.submit_line_routed(line, origin=seq))
        seq += 1
    # EOF: drain the tail (a frontend that closes our stdin without a
    # final flush still gets every admitted request answered)
    emit(held)
    server.poll_reload()
    while server._queue:
        emit(server.drain_routed())
    flush_out()
    server.close()
    return _obs.serve_summary_from_registry()


# -- frontend side ------------------------------------------------------------

class Replica:
    """One worker subprocess + its delivery ledger."""

    def __init__(self, idx: int, cmd: list, env: dict | None = None):
        self.idx = int(idx)
        self.cmd = list(cmd)
        self.proc = subprocess.Popen(
            self.cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, env=env)
        self.quarantined = False
        #: outcome -> responses DELIVERED to clients off this replica
        #: (a quarantined fence-audit batch is not delivered, by design)
        self.delivered: dict[str, int] = {}

    @property
    def alive(self) -> bool:
        return not self.quarantined and self.proc.poll() is None

    def run_batch(self, lines: list) -> dict:
        """Send one batch + flush, block for the envelopes.  Returns
        ``{seq: resp}``; raises :class:`ReplicaDeadError` on a broken
        pipe / EOF / torn output line."""
        try:
            for ln in lines:
                self.proc.stdin.write(ln + "\n")
            self.proc.stdin.write(
                json.dumps({CONTROL_KEY: "flush"}) + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            raise ReplicaDeadError(f"replica {self.idx}: {e}") from e
        resps: dict = {}
        while True:
            raw = self.proc.stdout.readline()
            if not raw:
                raise ReplicaDeadError(
                    f"replica {self.idx}: EOF mid-batch (pid "
                    f"{self.proc.pid}, rc {self.proc.poll()})")
            try:
                obj = json.loads(raw)
            except ValueError as e:
                raise ReplicaDeadError(
                    f"replica {self.idx}: torn output line") from e
            if obj.get(CONTROL_KEY) == "flushed":
                return resps
            resps[int(obj["seq"])] = obj["resp"]

    def close(self, timeout: float = 30.0) -> int | None:
        """Graceful drain-out: EOF on stdin lets the worker answer its
        tail and write its manifest shard.  Returns the exit code."""
        try:
            if self.proc.stdin and not self.proc.stdin.closed:
                self.proc.stdin.close()
        except (BrokenPipeError, OSError):
            pass
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        return self.proc.poll()


def worker_cmd(state_path: str, *, worker_id: int, policy_args=(),
               python=None) -> list:
    """The ``mfm-tpu serve --worker`` argv for one replica."""
    import sys
    py = python or sys.executable
    return ([py, "-m", "mfm_tpu.cli", "serve", str(state_path),
             "--worker", "--worker-id", str(worker_id)]
            + list(policy_args))


def replica_env(idx: int, base_env=None) -> dict:
    """Worker environment with chaos-kill targeting: when
    ``MFM_CHAOS_KILL_REPLICA`` names this replica's index, the
    ``MFM_CHAOS_KILL``/``MFM_CHAOS_KILL_MATCH`` pair passes through;
    every other worker (and the front end, which never drains) runs
    clean — the drill kills exactly one replica."""
    env = dict(base_env if base_env is not None else os.environ)
    target = env.pop("MFM_CHAOS_KILL_REPLICA", None)
    if target is not None and int(target) != int(idx):
        env.pop("MFM_CHAOS_KILL", None)
        env.pop("MFM_CHAOS_KILL_MATCH", None)
    return env


class FleetServer(Coalescer):
    """The fleet dispatcher: a :class:`Coalescer` whose flush sends each
    batch to a worker replica instead of draining locally.

    ``server`` is the ADMISSION QueryServer (same engine/policy as the
    workers, but it never drains — its queue is the coalescing pool and
    its guards/shed/dead-letter run in-process so rejects never cost a
    pipe round trip)."""

    def __init__(self, server, replicas: list, *, linger_s: float = 0.01,
                 clock=None, deliver=None, cache=None):
        import time
        super().__init__(server, linger_s=linger_s,
                         clock=clock or time.monotonic, deliver=deliver,
                         cache=cache)
        self.replicas = list(replicas)
        self.accepted_total = 0   # requests popped for dispatch
        #: outcome -> responses the FRONT END answered locally (deadline
        #: expiry in its queue, no-healthy-replica outage, dropped seq);
        #: merged into the fleet manifest so the delivery audit still
        #: balances — every accepted request's response is in exactly one
        #: ledger, a replica's or this one
        self.local_delivered: dict[str, int] = {}
        self._rr = 0

    # callers hold self._lock (Coalescer.submit/poll/flush/stop take it)
    def _flush_locked(self, trigger: str) -> list:
        out = []
        now = self._clock()
        lingered = (now - self._oldest_t) if self._oldest_t is not None else 0.0
        while self.server._queue:
            # poll the checkpoint pointer HERE too (workers reload on
            # their own): the admission engine, health stamp, and the
            # response-cache fence must move with the fleet, or the
            # front-end cache would keep answering from a retired
            # generation after a hot reload
            self.server.poll_reload()
            batch = []
            while (self.server._queue
                   and len(batch) < self.server.policy.batch_max):
                batch.append(self.server._queue.popleft())
            _obs.record_queue_depth(len(self.server._queue))
            _obs.record_coalesce_flush(len(batch), bucket_for(len(batch)),
                                       trigger, lingered)
            lingered = 0.0
            self.accepted_total += len(batch)
            # enforce deadlines HERE, not just in the worker: workers
            # re-stamp deadlines at their own enqueue time, so time spent
            # lingering or queued at the front end would otherwise never
            # count against a request's budget — same check drain() runs
            live = []
            for r in batch:
                if now > r.deadline_t:
                    out.append(self._local_deadline(r))
                else:
                    live.append(r)
            if live:
                out.extend(self._dispatch(live))
        self._oldest_t = None
        return out

    def _next_replica(self):
        n = len(self.replicas)
        for _ in range(n):
            w = self.replicas[self._rr % n]
            self._rr += 1
            if w.alive:
                return w
        return None

    def _count_local(self, outcome: str) -> None:
        self.local_delivered[outcome] = \
            self.local_delivered.get(outcome, 0) + 1

    def _local_error(self, r, detail: str) -> tuple:
        _obs.record_query_outcome("error")
        self._count_local("error")
        if r.span is not None:
            _trace.end_span(r.span, outcome="error")
        return (r.origin, self.server._stamp(
            {"id": r.rid, "ok": False, "outcome": "error",
             "detail": detail},
            scenario_id=r.scenario, trace_id=r.trace_id))

    def _local_deadline(self, r) -> tuple:
        _obs.record_query_outcome("deadline")
        self._count_local("deadline")
        if r.span is not None:
            _trace.end_span(r.span, outcome="deadline")
        return (r.origin, self.server._stamp(
            {"id": r.rid, "ok": False, "outcome": "deadline"},
            scenario_id=r.scenario, trace_id=r.trace_id))

    def _dispatch(self, batch: list) -> list:
        lines = [r.line for r in batch]
        while True:
            w = self._next_replica()
            if w is None:
                return [self._local_error(r, "no healthy replicas")
                        for r in batch]
            _obs.record_fleet_dispatch(w.idx, len(lines))
            try:
                resps = w.run_batch(lines)
            except ReplicaDeadError:
                _obs.record_replica_death()
                _obs.record_fleet_redispatch(len(lines))
                continue
            if (len(resps) == len(lines) and resps and
                    all(isinstance(v, dict)
                        and v.get("breaker") == "fence_audit"
                        for v in resps.values())):
                # the replica's own reload failed its fence audit: drain
                # it out (no more batches; graceful close at shutdown so
                # it still writes its manifest shard) and re-dispatch
                w.quarantined = True
                _obs.record_replica_quarantine()
                _obs.record_fleet_redispatch(len(lines))
                continue
            pairs = []
            for i, r in enumerate(batch):
                resp = resps.get(i)
                if resp is None:
                    pairs.append(self._local_error(
                        r, f"replica {w.idx} dropped seq {i}"))
                    continue
                outcome = str(resp.get("outcome", "error"))
                _obs.record_query_outcome(outcome)
                w.delivered[outcome] = w.delivered.get(outcome, 0) + 1
                if r.span is not None:
                    _trace.end_span(r.span, outcome=outcome)
                pairs.append((r.origin, resp))
            return pairs

    def close_replicas(self) -> None:
        for w in self.replicas:
            w.close()


# -- merged manifest ----------------------------------------------------------

def build_fleet_manifest(frontend_summary: dict, fleet,
                         manifest_dir: str) -> dict:
    """Merge the front end's summary with every replica's ledger + its
    manifest shard (a SIGKILLed worker has no shard — that IS the loss
    the manifest counts).  The ``audit`` block is the doctor invariant:
    per-replica delivered outcome counts plus the front end's own
    locally-answered ledger (deadline expiry at the front end, outage
    errors, dropped seqs — all well-formed responses clients DID receive)
    must sum to the accepted count."""
    from mfm_tpu.obs.manifest import ManifestError, read_run_manifest
    reps = []
    outcomes_sum = 0
    for w in fleet.replicas:
        rc = w.proc.poll()
        shard_path = os.path.join(manifest_dir,
                                  WORKER_MANIFEST_FMT.format(idx=w.idx))
        shard = None
        try:
            shard = read_run_manifest(shard_path).get("serve")
        except (ManifestError, OSError):
            pass
        total = sum(w.delivered.values())
        outcomes_sum += total
        reps.append({
            "replica": w.idx,
            "exit_code": rc,
            "lost": bool(rc is not None and rc != 0),
            "quarantined": bool(w.quarantined),
            "outcomes": dict(sorted(w.delivered.items())),
            "outcomes_total": total,
            "manifest_shard": (WORKER_MANIFEST_FMT.format(idx=w.idx)
                               if shard is not None else None),
            "worker_summary": shard,
        })
    accepted = int(fleet.accepted_total)
    local = dict(sorted(getattr(fleet, "local_delivered", {}).items()))
    local_total = sum(local.values())
    return {
        "schema": 1,
        "frontend": frontend_summary,
        "accepted_total": accepted,
        "replicas": reps,
        "frontend_local": {
            "outcomes": local,
            "outcomes_total": local_total,
        },
        "audit": {
            "replica_outcomes_sum": outcomes_sum,
            "frontend_local_total": local_total,
            "delivered_total": outcomes_sum + local_total,
            "accepted_total": accepted,
            "consistent": outcomes_sum + local_total == accepted,
        },
    }
