"""Production-shaped request loop around :class:`~mfm_tpu.serve.query.QueryEngine`.

The model side of the stack is hardened (quarantine, fenced checkpoints,
chaos harness); this module hardens the REQUEST side.  Everything here is
strictly host-side — JSON decoding, deques, clocks — and mfmlint R7 treats
this module as host-only: nothing in it may be reached from traced code.
The only device work is the one vmapped, donated jit inside
``QueryEngine.query``, called once per drained batch.

Four layers, mirroring the per-date guards of :mod:`mfm_tpu.serve.guard`:

1. **Request guards** — schema/dtype validation, NaN/short-weight
   rejection, unknown-factor mapping, all folded into a per-request reason
   bitmask (``REQ_REASON_*``, its own namespace decoded by the shared
   :func:`mfm_tpu.serve._checks.names_of_mask`).  Malformed requests are
   quarantined to a dead-letter JSONL instead of killing the batch.
2. **Admission control + deadlines** — a bounded queue with explicit
   backpressure: overflow sheds the OLDEST queued work with a counted
   ``shed`` outcome (latency stays bounded; the newest requests are the
   ones still worth answering).  Every request carries a deadline budget;
   work that expires in the queue is answered ``deadline``, never computed.
3. **Degraded serving** — every response is stamped with the served
   covariance's staleness and the ``obs/health.py`` verdict; a
   :class:`CircuitBreaker` flips the loop to reject-with-retry-after when
   health degrades past the policy threshold, the checkpoint fails its
   fence audit on reload, or batches keep failing.
4. **Chaos hooks** — ``chaos_point("serve.after_batch", ...)`` fires after
   every drained batch, so tools/faultinject.py can SIGKILL the loop
   mid-stream and assert deterministic recovery.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import math
import os
import threading
import time
from typing import Callable

import numpy as np

from mfm_tpu.obs import flightrec as _frec
from mfm_tpu.obs import instrument as _obs
from mfm_tpu.obs import trace as _trace
from mfm_tpu.serve._checks import combine_reason_bits, mad_outlier_cells, \
    names_of_mask
from mfm_tpu.utils.chaos import chaos_point

# request-guard reason bitmask — its own namespace, deliberately disjoint
# from serve/guard.py's per-date bits (a dead-letter record and a
# quarantined date are different animals; sharing decode machinery via
# serve/_checks.py is what keeps the two layers from drifting)
REQ_REASON_SCHEMA = 1            # not a JSON object / missing required keys
REQ_REASON_DTYPE = 2             # weights not coercible to finite floats
REQ_REASON_NAN_WEIGHT = 4        # NaN/Inf weight entries
REQ_REASON_SHORT_WEIGHTS = 8     # wrong length / empty weight vector
REQ_REASON_UNKNOWN_FACTOR = 16   # dict weight key not in the engine's space
REQ_REASON_UNKNOWN_BENCHMARK = 32
REQ_REASON_WEIGHT_OUTLIER = 64   # |w - med| > mad_k * MAD (policy-gated)
REQ_REASON_UNKNOWN_SCENARIO = 128  # scenario tag not in the served table
REQ_REASON_BAD_CONSTRUCT = 256   # construct solver unknown / unsupported
                                 # space / bad hedge factors or hmax
REQ_REASON_BAD_SWEEP = 512       # sweep spec unknown sampler / out-of-bound
                                 # n, chunk, top_k or bins

_REQ_REASON_NAMES = (
    (REQ_REASON_SCHEMA, "schema"),
    (REQ_REASON_DTYPE, "dtype"),
    (REQ_REASON_NAN_WEIGHT, "nan_weight"),
    (REQ_REASON_SHORT_WEIGHTS, "short_weights"),
    (REQ_REASON_UNKNOWN_FACTOR, "unknown_factor"),
    (REQ_REASON_UNKNOWN_BENCHMARK, "unknown_benchmark"),
    (REQ_REASON_WEIGHT_OUTLIER, "weight_outlier"),
    (REQ_REASON_UNKNOWN_SCENARIO, "unknown_scenario"),
    (REQ_REASON_BAD_CONSTRUCT, "bad_construct"),
    (REQ_REASON_BAD_SWEEP, "bad_sweep"),
)

#: sweep request bounds — a sweep is a whole streaming batch job riding
#: one request, so admission caps every size knob (the CLI is the road
#: for million-scenario runs; serving answers bounded exploratory sweeps)
SWEEP_SAMPLERS = ("uniform", "sobol", "grid")
SWEEP_MAX_N = 262144
SWEEP_MAX_CHUNK = 16384
SWEEP_MAX_TOP_K = 64
SWEEP_MAX_BINS = 256

#: construct request vocabulary (mfm_tpu/grad/construct.py solvers); the
#: import is deferred to keep this host-only module's import cost flat —
#: grad pulls the kernel modules in
CONSTRUCT_SOLVERS = ("min_vol", "risk_parity", "hedge")

#: JSONL key reserved for the fleet wire protocol (serve/replica.py).
#: Admission REJECTS any request carrying it, so admitted lines can be
#: forwarded to a worker replica verbatim without frame escaping — a
#: client can never smuggle a control frame past the front end.
FLEET_CONTROL_KEY = "__fleet__"


def req_reason_names(mask: int) -> list[str]:
    """Human-readable names of the bits set in a request-reason mask."""
    return names_of_mask(mask, _REQ_REASON_NAMES)


@dataclasses.dataclass(frozen=True)
class ServePolicy:
    """Admission/deadline/breaker knobs of the query loop.

    Frozen + hashable like :class:`mfm_tpu.config.QuarantinePolicy`: the
    policy is part of a serve run's identity (manifests record it), and a
    mutable policy mid-run would make shed/deadline outcomes unreplayable.

    Attributes:
      queue_max: admission bound; an arriving request beyond it sheds the
        OLDEST queued request (counted ``shed`` outcome).
      batch_max: most requests drained into one device batch (the padded
        bucket is ``bucket_for`` of the true size).
      default_deadline_s: per-request deadline budget when the request
        doesn't carry its own ``deadline_s``.
      breaker_failures: consecutive batch failures that open the breaker.
      breaker_cooldown_s: open -> half-open cooldown; also the
        ``retry_after_s`` stamped on rejected responses.
      weight_mad_k: MAD multiple beyond which a weight entry is an outlier
        (shared formula with the slab guards); 0 disables the check.
      breaker_on_degraded: force the breaker open while the model health
        verdict is "degraded".
      fsync_emits: fsync the response stream after every emitted event
        batch.  The per-emit ``flush()`` already makes responses durable
        against the PYTHON buffer (a SIGKILLed loop loses nothing it
        wrote); fsync extends that through the OS page cache, so emitted
        responses also survive a power cut.  Off by default — an fsync per
        drain is an I/O wall the pipe-to-consumer deployment doesn't need.
    """

    queue_max: int = 4096
    batch_max: int = 1024
    default_deadline_s: float = 1.0
    breaker_failures: int = 3
    breaker_cooldown_s: float = 5.0
    weight_mad_k: float = 0.0
    breaker_on_degraded: bool = True
    fsync_emits: bool = False

    def __post_init__(self):
        if self.queue_max < 1:
            raise ValueError(f"queue_max must be >= 1, got {self.queue_max}")
        if self.batch_max < 1:
            raise ValueError(f"batch_max must be >= 1, got {self.batch_max}")
        if self.default_deadline_s <= 0:
            raise ValueError("default_deadline_s must be > 0, got "
                             f"{self.default_deadline_s}")
        if self.breaker_failures < 1:
            raise ValueError("breaker_failures must be >= 1, got "
                             f"{self.breaker_failures}")
        if self.breaker_cooldown_s < 0:
            raise ValueError("breaker_cooldown_s must be >= 0, got "
                             f"{self.breaker_cooldown_s}")
        if self.weight_mad_k < 0:
            raise ValueError(f"weight_mad_k must be >= 0, got "
                             f"{self.weight_mad_k}")

    def identity(self) -> tuple:
        return tuple(getattr(self, f.name)
                     for f in dataclasses.fields(self))


class CircuitBreaker:
    """closed -> open -> half_open -> closed breaker with injectable clock.

    ``closed``: all traffic admitted; ``failures`` consecutive
    :meth:`record_failure` calls open it.  ``open``: everything rejected
    with a retry-after until ``cooldown_s`` elapses, then the next
    :meth:`allow` admits ONE probe (half_open).  ``half_open``: probe
    success closes, probe failure re-opens (cooldown restarts).
    :meth:`force_open` is the degraded-health / fence-audit path — it
    records why, and the reason rides on rejected responses.

    Thread-safe: every state transition and counter bump happens under one
    internal lock.  The fleet front end (serve/frontend.py) admits requests
    from N connection threads while the drain loop records batch outcomes —
    an unlocked ``_consecutive += 1`` under that interleaving can lose
    failures and never open the breaker.
    """

    def __init__(self, failures: int = 3, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self._threshold = int(failures)
        self._cooldown = float(cooldown_s)
        self._clock = clock
        self._lock = threading.RLock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self.open_reason: str | None = None
        _obs.record_breaker_state(self._state)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _to(self, state: str) -> None:
        # callers hold self._lock
        if state != self._state:
            self._state = state
            _obs.record_breaker_state(state)

    def allow(self) -> bool:
        """Admit a request?  May transition open -> half_open."""
        with self._lock:
            if self._state == "open":
                if self._clock() - self._opened_at >= self._cooldown:
                    self._to("half_open")
                    return True
                return False
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state == "half_open":
                self.open_reason = None
                self._to("closed")

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            trip = (self._state == "half_open"
                    or self._consecutive >= self._threshold)
        if trip:
            # force_open runs OUTSIDE this frame's lock hold so its
            # flight-recorder dump (file I/O, registry/ring locks) never
            # happens under the breaker lock; the RLock makes the nested
            # call safe but mfmsync S3 (blocking under lock) would not be
            self.force_open("failures")

    def force_open(self, reason: str) -> None:
        with self._lock:
            was_open = self._state == "open"
            self._consecutive = 0
            self._opened_at = self._clock()
            self.open_reason = reason
            # re-arm the cooldown even if already open (repeated force_open
            # keeps rejecting); only a transition tallies breaker_open_total
            self._to("open")
        if not was_open:
            # postmortem on the TRANSITION only (a breaker that stays
            # open re-arms without re-dumping): the ring's newest
            # trace-stamped event — the batch_error that tripped us —
            # becomes the dump's triggering trace id
            _frec.record_event("breaker_open", reason=reason)
            _frec.trigger_dump("breaker_open", state={
                "breaker": {"state": "open", "open_reason": reason}})

    def retry_after(self) -> float:
        with self._lock:
            if self._state != "open":
                return 0.0
            return max(0.0,
                       self._cooldown - (self._clock() - self._opened_at))


class _Request:
    __slots__ = ("rid", "weights", "bidx", "enq_t", "deadline_t", "scenario",
                 "trace_id", "span", "construct", "sweep", "origin", "line")

    def __init__(self, rid, weights, bidx, enq_t, deadline_t, scenario=None,
                 trace_id=None, span=None, construct=None, sweep=None,
                 origin=None, line=None):
        self.rid = rid
        self.weights = weights
        self.bidx = bidx
        self.enq_t = enq_t
        self.deadline_t = deadline_t
        self.scenario = scenario
        self.trace_id = trace_id
        self.span = span
        self.construct = construct
        self.sweep = sweep
        # origin: an opaque routing token (connection handle, replica
        # dispatch ordinal) stamped by the fleet layer; None on the plain
        # single-stream loop.  line: the raw admitted request bytes — the
        # fleet dispatcher forwards them verbatim to a worker replica.
        self.origin = origin
        self.line = line


def _line_trace_id(line: str) -> str:
    """Host-generated trace id for a request that didn't bring one:
    derived from the request BYTES, not os.urandom, so a replayed stream
    reuses the same ids and the chaos plans' bitwise-prefix contract on
    the response stream survives tracing."""
    return hashlib.sha256(line.encode("utf-8", "replace")).hexdigest()[:32]


def _parse_construct(raw, engine):
    """Decode + guard a request's ``construct`` block.  Accepts the string
    shorthand (``"min_vol"``) or an object (``{"solver": "hedge",
    "hedge_factors": [...], "hmax": 0.5}``).  Returns
    ``(spec_dict_or_None, reason_bits, detail)`` — the spec dict is what
    rides on the queued request into the drain-side solver dispatch."""
    if isinstance(raw, str):
        raw = {"solver": raw}
    if not isinstance(raw, dict):
        return None, REQ_REASON_BAD_CONSTRUCT, \
            "construct must be a solver name or an object"
    solver = raw.get("solver")
    if solver not in CONSTRUCT_SOLVERS:
        return None, REQ_REASON_BAD_CONSTRUCT, \
            f"unknown construct solver {solver!r}; have " \
            f"{list(CONSTRUCT_SOLVERS)}"
    if engine.space != "factor":
        return None, REQ_REASON_BAD_CONSTRUCT, \
            "construction runs in factor space (engine serves " \
            f"{engine.space!r})"
    spec = {"solver": str(solver), "hedge_mask": None, "hmax": 1.0}
    if solver == "hedge":
        factors = raw.get("hedge_factors")
        if factors is not None:
            if not isinstance(factors, (list, tuple)) or not factors:
                return None, REQ_REASON_BAD_CONSTRUCT, \
                    "hedge_factors must be a non-empty list"
            unknown = [str(f) for f in factors
                       if str(f) not in engine.factor_index]
            if unknown:
                return None, REQ_REASON_BAD_CONSTRUCT, \
                    f"hedge_factors outside the engine's space: " \
                    f"{sorted(unknown)[:5]}"
            mask_vec = np.zeros(engine.N, np.float64)
            for f in factors:
                mask_vec[engine.factor_index[str(f)]] = 1.0
            spec["hedge_mask"] = mask_vec
        try:
            hmax = float(raw.get("hmax", 1.0))
            if not (np.isfinite(hmax) and hmax > 0):
                raise ValueError(hmax)
        except (TypeError, ValueError):
            return None, REQ_REASON_BAD_CONSTRUCT, \
                f"bad hmax {raw.get('hmax')!r} (need finite > 0)"
        spec["hmax"] = hmax
    return spec, 0, ""


def _parse_sweep(raw, engine):
    """Decode + guard a request's ``sweep`` block.  Accepts ``true`` (all
    defaults) or an object with ``sampler`` / ``n`` / ``seed`` / ``chunk``
    / ``top_k`` / ``bins``.  Every size knob is bounded at admission — a
    sweep is a streaming batch job riding one request line, and the
    drain must stay O(bounded) per request.  Returns ``(spec_dict_or_None,
    reason_bits, detail)``."""
    if raw is True:
        raw = {}
    if not isinstance(raw, dict):
        return None, REQ_REASON_BAD_SWEEP, \
            "sweep must be true or an object"
    if engine.space != "factor":
        return None, REQ_REASON_BAD_SWEEP, \
            f"sweeps run in factor space (engine serves {engine.space!r})"
    sampler = str(raw.get("sampler", "uniform"))
    if sampler not in SWEEP_SAMPLERS:
        return None, REQ_REASON_BAD_SWEEP, \
            f"unknown sweep sampler {sampler!r}; have {list(SWEEP_SAMPLERS)}"
    spec = {"sampler": sampler}
    for key, default, lo, hi in (("n", 4096, 1, SWEEP_MAX_N),
                                 ("chunk", 1024, 1, SWEEP_MAX_CHUNK),
                                 ("top_k", 8, 1, SWEEP_MAX_TOP_K),
                                 ("bins", 64, 8, SWEEP_MAX_BINS),
                                 ("seed", 0, 0, 2 ** 31 - 1)):
        v = raw.get(key, default)
        try:
            iv = int(v)
            if isinstance(v, float) and v != iv:
                raise ValueError(v)
            if not (lo <= iv <= hi):
                raise ValueError(iv)
        except (TypeError, ValueError):
            return None, REQ_REASON_BAD_SWEEP, \
                f"bad sweep {key} {v!r} (need int in [{lo}, {hi}])"
        spec[key] = iv
    return spec, 0, ""


def parse_request(line: str, engine, policy: ServePolicy, scenarios=None):
    """Decode + guard one JSONL request.

    Returns ``(fields_or_None, reason_mask, detail)``: a zero mask means
    the request is admissible and ``fields`` is ``(rid, weights (D,)
    float, bidx int, deadline_s float, scenario str|None, trace_id
    str|None, construct dict|None, sweep dict|None)``; a nonzero mask
    means dead-letter
    (``detail`` says what tripped, ``rid`` may still be recoverable and
    is returned inside ``detail``-bearing fields as None).  ``trace_id``
    is the caller's own when the request JSON carries one, else None (the
    server derives a deterministic one at admission).  ``scenarios``: the
    served scenario table (names only are consulted); a ``scenario`` tag
    outside it — including ANY tag when no table is served — is
    ``unknown_scenario``.  ``construct`` asks for a portfolio-construction
    solve instead of a risk query (the weights become the warm start /
    base book); :func:`_parse_construct` guards its vocabulary.
    """
    mask = 0
    rid = None
    try:
        obj = json.loads(line)
    except (ValueError, TypeError) as e:
        return None, REQ_REASON_SCHEMA, f"bad json: {e}"
    if not isinstance(obj, dict):
        return None, REQ_REASON_SCHEMA, "request must be a JSON object"
    rid = obj.get("id")
    scenario = obj.get("scenario")
    if scenario is not None:
        scenario = str(scenario)
    trace_id = obj.get("trace_id")
    if trace_id is not None:
        trace_id = str(trace_id)
    if FLEET_CONTROL_KEY in obj:
        return (rid, None, 0, 0.0, scenario, trace_id, None, None), \
            REQ_REASON_SCHEMA, \
            f"reserved key {FLEET_CONTROL_KEY!r} (fleet control namespace)"
    raw_w = obj.get("weights")
    if raw_w is None:
        return (rid, None, 0, 0.0, scenario, trace_id, None, None), \
            REQ_REASON_SCHEMA, "missing 'weights'"

    detail = ""
    if scenario is not None and scenario not in (scenarios or {}):
        mask |= REQ_REASON_UNKNOWN_SCENARIO
        have = sorted(scenarios) if scenarios else []
        detail = f"unknown scenario {scenario!r} (serving " \
            f"{have[:5] if have else 'no scenario table'})"
    construct = None
    raw_c = obj.get("construct")
    if raw_c is not None:
        construct, c_bits, c_detail = _parse_construct(raw_c, engine)
        if c_bits:
            mask |= c_bits
            detail = detail or c_detail
    sweep = None
    raw_s = obj.get("sweep")
    if raw_s is not None and raw_s is not False:
        sweep, s_bits, s_detail = _parse_sweep(raw_s, engine)
        if s_bits:
            mask |= s_bits
            detail = detail or s_detail
        elif construct is not None:
            sweep = None
            mask |= REQ_REASON_BAD_SWEEP
            detail = detail or \
                "a request is a sweep OR a construct solve, not both"
    if isinstance(raw_w, dict):
        # name-keyed weights: map onto the engine's own axis order.  In
        # factor space the keys are factor names; in stock space stock ids.
        names = (engine.stocks if engine.space == "stock" and engine.stocks
                 else engine.factor_names if engine.space == "factor"
                 else None)
        if names is None:
            return (rid, None, 0, 0.0, scenario, trace_id, None, None), \
                REQ_REASON_SCHEMA, \
                "dict weights need a named axis (engine has no stock ids)"
        index = (engine.factor_index if engine.space == "factor"
                 else {n: i for i, n in enumerate(names)})
        w = np.zeros(engine.N, np.float64)
        unknown = [k for k in raw_w if k not in index]
        if unknown:
            mask |= REQ_REASON_UNKNOWN_FACTOR
            detail = f"unknown names: {sorted(unknown)[:5]}"
        else:
            try:
                for k, v in raw_w.items():
                    w[index[k]] = float(v)
            except (TypeError, ValueError) as e:
                mask |= REQ_REASON_DTYPE
                detail = f"non-numeric weight: {e}"
    else:
        try:
            w = np.asarray(raw_w, np.float64)
        except (TypeError, ValueError) as e:
            w = None
            mask |= REQ_REASON_DTYPE
            detail = f"weights not coercible: {e}"
        if w is not None and (w.ndim != 1 or
                              not np.issubdtype(w.dtype, np.number)):
            mask |= REQ_REASON_DTYPE if w.ndim == 1 else \
                REQ_REASON_SHORT_WEIGHTS
            detail = detail or f"weights must be a flat numeric list, got " \
                f"ndim={w.ndim} dtype={w.dtype}"
            w = None

    if w is not None and not (mask & (REQ_REASON_DTYPE |
                                      REQ_REASON_UNKNOWN_FACTOR)):
        flags = []
        if w.shape != (engine.N,):
            flags.append((True, REQ_REASON_SHORT_WEIGHTS))
            detail = f"expected {engine.N} weights, got {w.shape[0]}"
        elif not np.isfinite(w).all():
            flags.append((True, REQ_REASON_NAN_WEIGHT))
            detail = f"{int((~np.isfinite(w)).sum())} non-finite weights"
        elif policy.weight_mad_k > 0 and w.shape[0] >= 4:
            # same MAD formula as the traced slab guard (serve/_checks.py)
            out = mad_outlier_cells(w.astype(np.float64),
                                    policy.weight_mad_k, np)
            if bool(out.any()):
                flags.append((True, REQ_REASON_WEIGHT_OUTLIER))
                detail = f"{int(out.sum())} weight outliers beyond " \
                    f"{policy.weight_mad_k} MAD"
        mask |= int(combine_reason_bits(flags, np))

    bidx = 0
    bench = obj.get("benchmark")
    if bench is not None:
        bidx = engine.benchmark_index.get(str(bench), -1)
        if bidx < 0:
            mask |= REQ_REASON_UNKNOWN_BENCHMARK
            detail = detail or f"unknown benchmark {bench!r} (have " \
                f"{sorted(engine.benchmark_index)})"
            bidx = 0
    try:
        deadline_s = float(obj.get("deadline_s", policy.default_deadline_s))
        if not (deadline_s > 0):
            raise ValueError(deadline_s)
    except (TypeError, ValueError):
        mask |= REQ_REASON_SCHEMA
        detail = detail or f"bad deadline_s {obj.get('deadline_s')!r}"
        deadline_s = policy.default_deadline_s
    return (rid, w, bidx, deadline_s, scenario, trace_id, construct,
            sweep), int(mask), detail


class QueryServer:
    """The batched request loop: admit -> queue -> drain -> respond.

    Args:
      engine: the :class:`QueryEngine` to answer with (swappable under
        load via :meth:`swap` / ``reload_fn``).
      policy: :class:`ServePolicy` (admission, deadlines, breaker).
      health: the model-health verdict string stamped on every response
        ("ok" | "degraded" | "unknown" — ``obs/health.py``'s vocabulary);
        "degraded" force-opens the breaker when the policy says so.
      dead_letter_path: JSONL file collecting guarded-out requests.
      clock: monotonic clock (injectable for deterministic tests).
      reload_fn: optional zero-arg callable polled between batches; it
        returns None (no change) or ``{"engine": ..., "health": ...}``; a
        fence-audit failure (ArtifactCorrupt/Stale) force-opens the
        breaker instead of serving a checkpoint that failed its audit.
      scenarios: optional ``{name: QueryEngine}`` table of stressed
        engines (``ScenarioEngine.query_engines``).  A request carrying
        ``"scenario": name`` is answered from that engine; requests with
        no tag run the exact baseline path (bitwise-unchanged), and tags
        outside the table dead-letter with ``unknown_scenario``.
      warm_index: optional :class:`~mfm_tpu.serve.cache.WarmStartIndex`.
        When set, a construct request whose book is a near miss of a
        previously solved one seeds the solver's warm-start blend with
        the cached solution at a reduced step budget; the response
        records the parity contract (``warm_start``).  Cold solves are
        byte-for-byte unchanged (no extra field), so every bitwise
        contract holds whenever the index finds nothing.
    """

    def __init__(self, engine, policy: ServePolicy | None = None, *,
                 health: str = "unknown", dead_letter_path=None,
                 clock: Callable[[], float] = time.monotonic,
                 reload_fn=None, scenarios=None, warm_index=None):
        self.engine = engine
        self.scenarios: dict = dict(scenarios or {})
        self.policy = policy or ServePolicy()
        self.health = str(health)
        self.breaker = CircuitBreaker(self.policy.breaker_failures,
                                      self.policy.breaker_cooldown_s,
                                      clock=clock)
        self._clock = clock
        self._queue: collections.deque[_Request] = collections.deque()
        self._batch_i = 0
        self._dead_path = dead_letter_path
        self._dead_fp = None
        self._reload_fn = reload_fn
        self.warm_index = warm_index
        #: checkpoint generation currently served (None = untracked);
        #: moved by swap() so the fleet's rolling rollout can verify a
        #: worker landed on the target fence before routing to it again
        self.generation: int | None = None
        if self.health == "degraded" and self.policy.breaker_on_degraded:
            self.breaker.force_open("health_degraded")

    # -- degraded serving ----------------------------------------------------
    def _stamp(self, resp: dict, scenario_id: str | None = None,
               engine=None, trace_id: str | None = None) -> dict:
        eng = engine if engine is not None else self.engine
        resp["scenario_id"] = scenario_id
        resp["staleness"] = int(eng.staleness)
        resp["health"] = self.health
        resp["degraded"] = bool(eng.staleness > 0
                                or self.health != "ok")
        resp["trace_id"] = trace_id
        return resp

    def swap(self, engine=None, health: str | None = None,
             generation: int | None = None) -> None:
        """Hot-swap the served engine / health verdict (checkpoint reload
        under load).  Degraded health force-opens the breaker; a recovery
        to "ok" lets the normal cooldown -> half-open -> closed path run
        (no instant flap back to closed)."""
        if engine is not None:
            self.engine = engine
        if generation is not None:
            self.generation = int(generation)
        if health is not None:
            self.health = str(health)
            if self.health == "degraded" and self.policy.breaker_on_degraded:
                self.breaker.force_open("health_degraded")

    def poll_reload(self) -> None:
        """Between-batch checkpoint watch: apply ``reload_fn``'s swap, or
        force the breaker open if the new checkpoint fails its fence
        audit."""
        if self._reload_fn is None:
            return
        from mfm_tpu.data.artifacts import ArtifactCorruptError, \
            ArtifactStaleError
        try:
            upd = self._reload_fn()
        except (ArtifactCorruptError, ArtifactStaleError):
            self.breaker.force_open("fence_audit")
            return
        if upd:
            self.swap(engine=upd.get("engine"), health=upd.get("health"),
                      generation=upd.get("generation"))

    # -- dead letter ---------------------------------------------------------
    def _dead_letter(self, rid, mask: int, detail: str, line: str,
                     extra: dict | None = None) -> None:
        if self._dead_path is None:
            return
        rec = {"id": rid, "reasons": req_reason_names(mask), "mask": int(mask),
               "detail": detail, "line": line[:2048]}
        if extra:
            rec.update(extra)
        if self._dead_fp is None:
            self._dead_fp = open(self._dead_path, "a", encoding="utf-8")
        self._dead_fp.write(json.dumps(rec, sort_keys=True) + "\n")
        self._dead_fp.flush()

    # -- admission -----------------------------------------------------------
    def submit_line(self, line: str) -> list[dict]:
        """Admit one JSONL request.  Returns the IMMEDIATE responses this
        event produced (rejection, dead-letter ack, shed notices for
        displaced older work); an admitted request answers later, at
        drain."""
        return [resp for _, resp in self.submit_line_routed(line)]

    def submit_line_routed(self, line: str, origin=None) -> list[tuple]:
        """:meth:`submit_line` with response routing: every immediate
        response comes back as ``(origin, resp)``, where the origin is the
        one the RESPONSE's request was admitted with — a shed notice
        carries the DISPLACED (older) request's origin, which may belong
        to a different connection than the line that triggered it.  The
        fleet front end routes each response to its own connection off
        this pairing; the single-stream loop passes ``origin=None`` and
        ignores it."""
        out = []
        if not self.breaker.allow():
            _obs.record_query_outcome("rejected")
            return [(origin, self._stamp({
                "id": _peek_id(line), "ok": False, "outcome": "rejected",
                "retry_after_s": round(self.breaker.retry_after(), 3),
                "breaker": self.breaker.open_reason or "open"},
                trace_id=_peek_trace_id(line) or _line_trace_id(line)))]
        fields, mask, detail = parse_request(line, self.engine, self.policy,
                                             scenarios=self.scenarios)
        if mask:
            rid = fields[0] if fields else None
            scen = fields[4] if fields else None
            tid = (fields[5] if fields else None) or _line_trace_id(line)
            self._dead_letter(rid, mask, detail, line,
                              extra={"scenario_id": scen, "trace_id": tid})
            _obs.record_query_outcome("dead_letter")
            return [(origin, self._stamp({"id": rid, "ok": False,
                                          "outcome": "dead_letter",
                                          "reasons": req_reason_names(mask),
                                          "detail": detail}, scenario_id=scen,
                                         trace_id=tid))]
        rid, w, bidx, deadline_s, scen, tid, construct, sweep = fields
        if tid is None:
            tid = _line_trace_id(line)
        now = self._clock()
        # request span opens at admission and ends with the final outcome
        # (possibly batches later) — the explicit start/end half of the API
        sp = _trace.start_span("serve.request", trace_id=tid, parent_id=None,
                               request_id=rid, scenario=scen)
        self._queue.append(_Request(rid, w, bidx, now, now + deadline_s,
                                    scenario=scen, trace_id=tid, span=sp,
                                    construct=construct, sweep=sweep,
                                    origin=origin, line=line))
        # bounded queue: shedding drops the OLDEST queued work first —
        # under overload the head of the queue is the request whose
        # deadline is nearest death; the freshest work is the most useful
        while len(self._queue) > self.policy.queue_max:
            old = self._queue.popleft()
            _obs.record_shed()
            _obs.record_query_outcome("shed")
            if old.span is not None:
                _trace.end_span(old.span, outcome="shed")
            out.append((old.origin, self._stamp({"id": old.rid, "ok": False,
                                                 "outcome": "shed"},
                                                scenario_id=old.scenario,
                                                trace_id=old.trace_id)))
        _obs.record_queue_depth(len(self._queue))
        return out

    # -- drain ---------------------------------------------------------------
    def drain(self) -> list[dict]:
        """Answer up to ``batch_max`` queued requests in ONE device batch.

        Deadline-expired requests are answered ``deadline`` without
        touching the device.  A batch failure tallies the breaker; the
        chaos point fires after every drained batch (crash-recovery plans
        key on its deterministic ``batch{i}`` path)."""
        return [resp for _, resp in self.drain_routed()]

    def drain_routed(self) -> list[tuple]:
        """:meth:`drain` with response routing: ``(origin, resp)`` pairs,
        each response paired with the origin its request was admitted
        with (see :meth:`submit_line_routed`)."""
        taken = []
        while self._queue and len(taken) < self.policy.batch_max:
            taken.append(self._queue.popleft())
        _obs.record_queue_depth(len(self._queue))
        if not taken:
            return []
        now = self._clock()
        live, out = [], []
        for r in taken:
            if now > r.deadline_t:
                _obs.record_query_outcome("deadline")
                if r.span is not None:
                    _trace.end_span(r.span, outcome="deadline")
                out.append((r.origin,
                            self._stamp({"id": r.rid, "ok": False,
                                         "outcome": "deadline"},
                                        scenario_id=r.scenario,
                                        trace_id=r.trace_id)))
            else:
                live.append(r)
        if not live:
            return out
        if not self.breaker.allow():
            # breaker opened between admission and drain (forced open by a
            # failed reload / degraded health): reject the queued work
            for r in live:
                _obs.record_query_outcome("rejected")
                if r.span is not None:
                    _trace.end_span(r.span, outcome="rejected")
                out.append((r.origin, self._stamp({
                    "id": r.rid, "ok": False, "outcome": "rejected",
                    "retry_after_s": round(self.breaker.retry_after(), 3),
                    "breaker": self.breaker.open_reason or "open"},
                    scenario_id=r.scenario, trace_id=r.trace_id)))
            return out
        # group by scenario tag, first-appearance order: the None group is
        # the exact pre-scenario path (one stack, one engine.query) so
        # untagged traffic stays bitwise-identical; each tagged group runs
        # the same batched path against its stressed engine
        groups: dict = {}
        for r in live:
            groups.setdefault(r.scenario, []).append(r)
        for scen, grp in groups.items():
            engine = self.engine if scen is None else self.scenarios.get(scen)
            if engine is None:
                # table swapped between admission and drain
                for r in grp:
                    _obs.record_query_outcome("error")
                    if r.span is not None:
                        _trace.end_span(r.span, outcome="error")
                    out.append((r.origin, self._stamp(
                        {"id": r.rid, "ok": False, "outcome": "error",
                         "detail": f"scenario {scen!r} no longer served"},
                        scenario_id=scen, trace_id=r.trace_id)))
                continue
            # split risk queries from construction solves: the query
            # sub-batch runs the exact pre-construct path (one stack, one
            # engine.query — untagged risk traffic stays bitwise-identical),
            # each (solver, hmax) construct sub-batch runs its own donated
            # grad kernel against the SAME engine's covariance (so
            # scenario-tagged construction solves against the stressed world)
            qgrp = [r for r in grp
                    if r.construct is None and r.sweep is None]
            sgrp = [r for r in grp if r.sweep is not None]
            cgrps: dict = {}
            for r in grp:
                if r.construct is not None:
                    key = (r.construct["solver"], r.construct["hmax"])
                    cgrps.setdefault(key, []).append(r)
            if qgrp:
                out.extend(self._drain_query(engine, scen, qgrp))
            for (solver, hmax), cg in cgrps.items():
                out.extend(self._drain_construct(engine, scen, solver,
                                                 hmax, cg))
            if sgrp:
                out.extend(self._drain_sweep(engine, scen, sgrp))
        chaos_point("serve.after_batch", f"batch{self._batch_i}")
        self._batch_i += 1
        return out

    def _drain_query(self, engine, scen, grp) -> list[tuple]:
        """Answer one scenario group's risk queries in ONE device batch.
        Returns routed ``(origin, resp)`` pairs."""
        out = []
        W = np.stack([r.weights for r in grp]).astype(engine.dtype)
        bench = [r.bidx for r in grp]
        # batch-execution child span: joins the first member's trace as
        # a child of its request span; every member's trace_id rides in
        # args (capped) so any slow request can be joined to its batch
        head = grp[0]
        bsp = _trace.start_span(
            "serve.batch", trace_id=head.trace_id,
            parent_id=(head.span.span_id if head.span else None),
            batch=self._batch_i, scenario=scen, n=len(grp),
            trace_ids=[r.trace_id for r in grp[:32]])
        t0 = time.perf_counter()
        try:
            res = engine.query(W, bench=bench)
        except Exception as e:   # noqa: BLE001 — any batch failure trips
            _trace.end_span(bsp, outcome="error")
            # event BEFORE record_failure: if this failure trips the
            # breaker, the dump's triggering trace id is this batch's
            _frec.record_event("batch_error", trace_id=head.trace_id,
                               kind_of="query", scenario=scen, n=len(grp),
                               detail=str(e)[:200])
            self.breaker.record_failure()
            for r in grp:
                _obs.record_query_outcome("error")
                if r.span is not None:
                    _trace.end_span(r.span, outcome="error")
                out.append((r.origin,
                            self._stamp({"id": r.rid, "ok": False,
                                         "outcome": "error",
                                         "detail": str(e)[:500]},
                                        scenario_id=scen, engine=engine,
                                        trace_id=r.trace_id)))
            return out
        dt = time.perf_counter() - t0
        _trace.end_span(bsp, outcome="ok")
        self.breaker.record_success()
        _obs.record_query_batch(len(grp), dt)
        done = self._clock()
        for i, r in enumerate(grp):
            _obs.record_query_outcome("ok")
            _obs.record_query_latency(max(0.0, done - r.enq_t))
            if r.span is not None:
                _trace.end_span(r.span, outcome="ok",
                                batch=self._batch_i)
            resp = {"id": r.rid, "ok": True, "outcome": "ok",
                    "total_vol": float(res.total_vol[i]),
                    "factor_var": float(res.factor_var[i]),
                    "specific_var": float(res.specific_var[i]),
                    "contribution": np.asarray(
                        res.contribution[i]).tolist(),
                    "marginal": np.asarray(res.marginal[i]).tolist()}
            if r.bidx > 0:
                resp["active_risk"] = float(res.active_risk[i])
                resp["beta"] = float(res.beta[i])
            out.append((r.origin, self._stamp(resp, scenario_id=scen,
                                              engine=engine,
                                              trace_id=r.trace_id)))
        return out

    def _drain_construct(self, engine, scen, solver, hmax, grp) -> list[tuple]:
        """Answer one (solver, hmax) construct sub-batch in ONE donated
        jit call (the grad/construct.py kernels, padded to the portfolio
        bucket — <= 1 compile per (solver, bucket) in steady state), with
        the query path's breaker / outcome / span semantics.

        With a :attr:`warm_index`, requests whose books are near misses
        of previously solved ones split into a second solve seeded from
        the cached solutions at a reduced step budget (same kernel,
        ``steps`` is a traced operand — no new compile).  Cold results
        feed the index; warm results never do (no warm-from-warm
        chaining).  Returns routed ``(origin, resp)`` pairs."""
        from mfm_tpu.grad.engine import GradEngine, MINVOL_STEPS, \
            RISKPARITY_STEPS
        out = []
        head = grp[0]
        bsp = _trace.start_span(
            "serve.construct", trace_id=head.trace_id,
            parent_id=(head.span.span_id if head.span else None),
            batch=self._batch_i, scenario=scen, solver=solver, n=len(grp),
            trace_ids=[r.trace_id for r in grp[:32]])
        full_steps = {"min_vol": MINVOL_STEPS,
                      "risk_parity": RISKPARITY_STEPS}.get(solver)
        seeds = [None] * len(grp)
        if self.warm_index is not None and full_steps is not None:
            for j, r in enumerate(grp):
                seeds[j] = self.warm_index.nearest(solver, hmax, r.weights)
        cold = [j for j in range(len(grp)) if seeds[j] is None]
        warm = [j for j in range(len(grp)) if seeds[j] is not None]
        warm_steps = (max(1, full_steps // self.warm_index.STEPS_DIVISOR)
                      if warm else None)
        t0 = time.perf_counter()
        try:
            ge = GradEngine(np.asarray(engine._cov),
                            factor_names=engine.factor_names,
                            staleness=engine.staleness, dtype=engine.dtype)
            results: dict = {}
            if cold:
                W = np.stack([grp[j].weights
                              for j in cold]).astype(engine.dtype)
                hmask = None
                if solver == "hedge":
                    hmask = np.stack([
                        grp[j].construct["hedge_mask"]
                        if grp[j].construct["hedge_mask"] is not None
                        else np.ones(ge.K) for j in cold]).astype(engine.dtype)
                res = ge.construct_solve(solver, W, hedge_mask=hmask,
                                         hmax=hmax)
                for i, j in enumerate(cold):
                    results[j] = (res["weights"][i], res["vols"][i],
                                  res["diag"][i], False)
            if warm:
                Wseed = np.stack([seeds[j]
                                  for j in warm]).astype(engine.dtype)
                res = ge.construct_solve(solver, Wseed, hmax=hmax,
                                         steps=warm_steps)
                for i, j in enumerate(warm):
                    results[j] = (res["weights"][i], res["vols"][i],
                                  res["diag"][i], True)
        except Exception as e:   # noqa: BLE001 — any batch failure trips
            _trace.end_span(bsp, outcome="error")
            _frec.record_event("batch_error", trace_id=head.trace_id,
                               kind_of="construct", scenario=scen,
                               n=len(grp), detail=str(e)[:200])
            self.breaker.record_failure()
            for r in grp:
                _obs.record_query_outcome("error")
                if r.span is not None:
                    _trace.end_span(r.span, outcome="error")
                out.append((r.origin,
                            self._stamp({"id": r.rid, "ok": False,
                                         "outcome": "error",
                                         "kind": "construct",
                                         "detail": str(e)[:500]},
                                        scenario_id=scen, engine=engine,
                                        trace_id=r.trace_id)))
            return out
        dt = time.perf_counter() - t0
        _trace.end_span(bsp, outcome="ok")
        self.breaker.record_success()
        _obs.record_query_batch(len(grp), dt)
        done = self._clock()
        for i, r in enumerate(grp):
            _obs.record_query_outcome("ok")
            _obs.record_query_latency(max(0.0, done - r.enq_t))
            if r.span is not None:
                _trace.end_span(r.span, outcome="ok", batch=self._batch_i)
            w_i, vol_i, diag_i, warmed = results[i]
            resp = {"id": r.rid, "ok": True, "outcome": "ok",
                    "kind": "construct", "solver": solver,
                    "weights": np.asarray(w_i).tolist(),
                    "total_vol": float(vol_i)}
            diag = np.asarray(diag_i)
            resp["diag"] = diag.tolist() if diag.ndim else float(diag)
            if warmed:
                # the parity contract: a seeded solve converged to the
                # same optimum statistically, not bitwise — recorded,
                # never silently passed off as an exact computation
                resp["warm_start"] = {"used": True, "steps": warm_steps,
                                      "steps_saved": full_steps - warm_steps,
                                      "parity": "seeded"}
                self.warm_index.record_use(warm_steps,
                                           full_steps - warm_steps)
            elif self.warm_index is not None and full_steps is not None:
                self.warm_index.add(solver, hmax, r.weights,
                                    np.asarray(w_i))
            out.append((r.origin,
                        self._stamp(resp, scenario_id=scen, engine=engine,
                                    trace_id=r.trace_id)))
        return out

    def _drain_sweep(self, engine, scen, grp) -> list[tuple]:
        """Answer one scenario group's sweep requests.  Requests sharing
        an identical (admission-bounded) sweep spec batch their books
        into ONE streaming sweep — the chunk kernel already carries B
        books per lane, so co-sweeping is free; distinct specs run
        sequentially.  Scenario-tagged sweeps stream against the stressed
        engine's covariance (the same world their queries answer from).
        No refinement in the serving path — bounded exploratory sweeps
        only; the CLI owns the gradient-refined deep runs.  Returns
        routed ``(origin, resp)`` pairs."""
        from mfm_tpu.grad.engine import ShockBall
        from mfm_tpu.scenario.sweep import (
            GridSampler, SobolSampler, SweepEngine, UniformSampler,
        )
        out = []
        head = grp[0]
        bsp = _trace.start_span(
            "serve.sweep", trace_id=head.trace_id,
            parent_id=(head.span.span_id if head.span else None),
            batch=self._batch_i, scenario=scen, n=len(grp),
            trace_ids=[r.trace_id for r in grp[:32]])
        by_spec: dict = {}
        for r in grp:
            by_spec.setdefault(tuple(sorted(r.sweep.items())), []).append(r)
        t0 = time.perf_counter()
        try:
            se = SweepEngine(np.asarray(engine._cov),
                             factor_names=engine.factor_names,
                             staleness=engine.staleness, dtype=engine.dtype)
            results: dict = {}
            for key, rs in by_spec.items():
                spec = dict(key)
                ball = ShockBall()
                if spec["sampler"] == "grid":
                    side = max(2, int(math.isqrt(spec["n"])))
                    sampler = GridSampler(ball, se.K, n_vol=side,
                                          n_corr=side)
                elif spec["sampler"] == "sobol":
                    sampler = SobolSampler(ball, se.K, spec["n"],
                                           seed=spec["seed"])
                else:
                    sampler = UniformSampler(ball, se.K, spec["n"],
                                             seed=spec["seed"])
                W = np.stack([r.weights for r in rs])
                res = se.sweep(W, sampler, chunk=spec["chunk"],
                               top_k=spec["top_k"], bins=spec["bins"],
                               ball=ball, refine=None)
                for i, r in enumerate(rs):
                    results[id(r)] = (res.books[i], res.counts, res.sampler)
        except Exception as e:   # noqa: BLE001 — any batch failure trips
            _trace.end_span(bsp, outcome="error")
            _frec.record_event("batch_error", trace_id=head.trace_id,
                               kind_of="sweep", scenario=scen,
                               n=len(grp), detail=str(e)[:200])
            self.breaker.record_failure()
            for r in grp:
                _obs.record_query_outcome("error")
                if r.span is not None:
                    _trace.end_span(r.span, outcome="error")
                out.append((r.origin,
                            self._stamp({"id": r.rid, "ok": False,
                                         "outcome": "error",
                                         "kind": "sweep",
                                         "detail": str(e)[:500]},
                                        scenario_id=scen, engine=engine,
                                        trace_id=r.trace_id)))
            return out
        dt = time.perf_counter() - t0
        _trace.end_span(bsp, outcome="ok")
        self.breaker.record_success()
        _obs.record_query_batch(len(grp), dt)
        done = self._clock()
        for r in grp:
            book, counts, sampler_d = results[id(r)]
            _obs.record_query_outcome("ok")
            _obs.record_query_latency(max(0.0, done - r.enq_t))
            if r.span is not None:
                _trace.end_span(r.span, outcome="ok", batch=self._batch_i)
            resp = {"id": r.rid, "ok": True, "outcome": "ok",
                    "kind": "sweep", "book": book, "counts": counts,
                    "sampler": sampler_d}
            out.append((r.origin,
                        self._stamp(resp, scenario_id=scen, engine=engine,
                                    trace_id=r.trace_id)))
        return out

    # -- the loop ------------------------------------------------------------
    def run(self, lines, out_fp, *, gulp: bool = False, cache=None) -> dict:
        """Serve a JSONL stream: one request per line in, one response per
        event out.  ``gulp`` reads ALL input before the first drain — the
        deterministic overload mode (queue-overflow chaos plans and tests
        need shedding to depend only on the input, not on drain timing).
        ``cache`` (a :class:`~mfm_tpu.serve.cache.ResponseCache`) answers
        repeat bodies from the cached response re-stamped with the
        caller's id/trace id, skipping admission — same semantics as the
        coalescer's cache seat, bypassed whenever the breaker is not
        closed.  Returns the final serve summary (the manifest block)."""
        if cache is not None:
            # deferred: serve/cache.py imports this module (no cycle)
            from mfm_tpu.serve.cache import CacheFill

        def emit(pairs):
            # flush per event batch: an emitted response is durable even if
            # the process is SIGKILLed before the next drain (the chaos
            # kill plans assert the survivor prefix replays bitwise).
            # fsync_emits extends that durability through the OS page
            # cache — flush alone only empties the Python-level buffer.
            if cache is not None:
                pairs = cache.absorb(pairs)
            for _, r in pairs:
                out_fp.write(json.dumps(r, sort_keys=True) + "\n")
            if pairs:
                out_fp.flush()
                if self.policy.fsync_emits:
                    try:
                        os.fsync(out_fp.fileno())
                    except (OSError, ValueError):
                        pass  # not a real file (StringIO, closed pipe)

        last_poll = -float("inf")
        for line in lines:
            line = line.strip()
            if not line:
                continue
            origin = None
            if cache is not None:
                # drains poll the watch, but an all-hits streak never
                # drains — bound the hit path's fence staleness too
                # (0.05 s: the coalescer's default linger scale)
                now = self._clock()
                if now - last_poll >= 0.05:
                    last_poll = now
                    self.poll_reload()
            if cache is not None and self.breaker.state == "closed":
                resp, token = cache.lookup(line)
                if resp is not None:
                    if _trace.tracing_enabled():
                        # a hit never opens a serve.request span — this
                        # child marks the short-circuit on the timeline
                        _trace.end_span(_trace.start_span(
                            "cache.hit", trace_id=resp.get("trace_id"),
                            request_id=resp.get("id")))
                    emit([(None, resp)])
                    continue
                if token is not None:
                    origin = CacheFill(None, token)
            emit(self.submit_line_routed(line, origin))
            if not gulp and len(self._queue) >= self.policy.batch_max:
                self.poll_reload()
                emit(self.drain_routed())
        while self._queue:
            self.poll_reload()
            emit(self.drain_routed())
        out_fp.flush()
        self.close()
        return _obs.serve_summary_from_registry()

    def close(self) -> None:
        if self._dead_fp is not None:
            self._dead_fp.close()
            self._dead_fp = None


def _peek_id(line: str):
    """Best-effort request id off a line we're rejecting unparsed."""
    try:
        obj = json.loads(line)
        return obj.get("id") if isinstance(obj, dict) else None
    except (ValueError, TypeError):
        return None


def _peek_trace_id(line: str):
    """Best-effort caller trace id off a line we're rejecting unparsed."""
    try:
        obj = json.loads(line)
    except (ValueError, TypeError):
        return None
    tid = obj.get("trace_id") if isinstance(obj, dict) else None
    return str(tid) if tid is not None else None
