"""Shared guard primitives: MAD outlier math + reason-bitmask plumbing.

Two guard layers watch the serving stack and both need the same two
primitives: the slab guards (:mod:`mfm_tpu.serve.guard`, traced inside the
fused update jit) and the request guards (:mod:`mfm_tpu.serve.server`,
host-side numpy over decoded JSONL).  Before this module each layer had its
own copy of the MAD threshold and bit-OR folding — one tuned constant or
NaN-handling fix applied to one layer silently forks the other.  Every
helper here takes the array namespace (``jnp`` from traced code, ``np``
from host code) as an explicit ``xp`` argument, so there is exactly ONE
formula per check and the backends cannot drift.

Nothing here imports jax: the traced caller passes its own ``jnp``, which
keeps this module importable from host-only tooling (mfmlint, faultinject)
without touching a backend.
"""

from __future__ import annotations


def mad_outlier_cells(x_use, mad_k, xp):
    """Boolean mask of cross-sectional MAD outliers in ``x_use``.

    ``x_use`` holds the values under test with every excluded cell already
    NaN (NaN never flags: comparisons with NaN are False).  A degenerate
    MAD of 0 — a constant cross-section — disables the check (threshold
    +inf) rather than flagging every cell.  Works identically under numpy
    and jax.numpy; the traced slab guard and the host-side request guard
    call this exact function.
    """
    med = xp.nanmedian(x_use)
    mad = xp.nanmedian(xp.abs(x_use - med))
    thresh = xp.where(mad > 0, mad_k * mad, xp.inf)
    return xp.abs(x_use - med) > thresh


def combine_reason_bits(flag_bit_pairs, xp):
    """OR ``bit`` into a uint32 mask for every true ``flag``.

    ``flag_bit_pairs`` is an iterable of ``(flag, bit)`` where ``flag`` is
    a boolean scalar (traced or host) and ``bit`` an int reason constant.
    Returns the uint32 bitmask; the zero-case dtype stays uint32 under both
    backends (the slab guard stores these in a (T,) uint32 accumulator).
    """
    mask = xp.uint32(0)
    for flag, bit in flag_bit_pairs:
        mask = mask | xp.where(flag, xp.uint32(bit), xp.uint32(0))
    return mask


def names_of_mask(mask: int, table) -> list:
    """Human-readable names of the bits set in ``mask``.

    ``table`` is the layer's ``((bit, name), ...)`` registry — each guard
    layer owns its bit namespace, this owns the decoding."""
    return [name for bit, name in table if int(mask) & bit]
