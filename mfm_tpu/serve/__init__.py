"""Serving-hardening layer: input guards + degraded-mode quarantine.

The daily-update path (``RiskModel.update``) trusts its inputs; a live feed
does not deserve that trust.  This package holds the jit-traceable per-date
health checks (:mod:`mfm_tpu.serve.guard`) the guarded update step runs on
every appended slab before the date is allowed into the EWMA carries.
"""

from mfm_tpu.serve.guard import (  # noqa: F401
    REASON_NAN_DENSITY,
    REASON_UNIVERSE_COLLAPSE,
    REASON_RET_OUTLIER,
    REASON_CAP_NONPOS,
    REASON_DATE_ORDER,
    GuardReport,
    guard_ring_init,
    guard_slab,
    host_date_reasons,
    reason_names,
)
