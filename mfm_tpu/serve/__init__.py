"""Serving layer: input guards, degraded-mode quarantine, and the
consumer-facing batched portfolio-query service.

Two guard surfaces protect the two directions of the serving stack:

- the MODEL side — :mod:`mfm_tpu.serve.guard`'s jit-traceable per-date
  health checks run on every appended slab before a date may enter the
  EWMA carries (quarantine + staleness-stamped degraded covariance);
- the REQUEST side — :mod:`mfm_tpu.serve.server`'s host-side request
  guards, admission control, deadlines, load shedding, and circuit
  breaker around :mod:`mfm_tpu.serve.query`'s one-vmapped-jit batch
  engine.

:mod:`mfm_tpu.serve._checks` holds the formula primitives both guard
layers share (MAD outliers, reason-bitmask plumbing) so they cannot
drift.

The fleet layer stacks on top of the single loop:
:mod:`mfm_tpu.serve.cache` answers repeated request bodies from a
bounded content-addressed response cache fenced on checkpoint
generation + scenario spec hash, :mod:`mfm_tpu.serve.coalesce` merges
concurrent submissions into the bucket ladder under a linger budget,
:mod:`mfm_tpu.serve.frontend` accepts concurrent socket/HTTP
connections, :mod:`mfm_tpu.serve.replica` runs N worker processes
behind the fenced checkpoint store, and :mod:`mfm_tpu.serve.transport`
carries the worker wire protocol over deadline-bearing pipe/TCP
transports so the fleet spans hosts and survives wedged workers
(docs/SERVING.md §"Fleet", §9 "Response cache", §10 "Multi-host
fleets").
"""

from mfm_tpu.serve.guard import (  # noqa: F401
    REASON_NAN_DENSITY,
    REASON_UNIVERSE_COLLAPSE,
    REASON_RET_OUTLIER,
    REASON_CAP_NONPOS,
    REASON_DATE_ORDER,
    GuardReport,
    guard_ring_init,
    guard_slab,
    host_date_reasons,
    reason_names,
)
from mfm_tpu.serve.query import (  # noqa: F401
    QueryEngine,
    QueryOutputs,
    bucket_for,
)
from mfm_tpu.serve.server import (  # noqa: F401
    CircuitBreaker,
    QueryServer,
    ServePolicy,
    parse_request,
    req_reason_names,
)
from mfm_tpu.serve.cache import (  # noqa: F401
    CacheFill,
    ResponseCache,
    WarmStartIndex,
    cacheable_response,
)
from mfm_tpu.serve.coalesce import Coalescer  # noqa: F401
from mfm_tpu.serve.frontend import SocketFrontend  # noqa: F401
from mfm_tpu.serve.replica import (  # noqa: F401
    FleetServer,
    Replica,
    ReplicaDeadError,
    ReplicaWedgedError,
    run_worker,
)
from mfm_tpu.serve.transport import (  # noqa: F401
    PipeTransport,
    TcpTransport,
    TransportClosed,
    TransportError,
    TransportTimeout,
    serve_worker_socket,
)
