"""RiskModel — the TPU-native equivalent of the reference's ``MFM`` driver.

The reference (``Barra-master/mfm/MFM.py``) loops Python over dates four
times (regression, Newey-West, eigen adjustment, vol regime).  Here each
stage is one jitted, batched call over the whole (T, N) panel:

    rm = RiskModel(ret, cap, styles, industry, valid, n_industries=P)
    out = rm.run(key)       # or stage-by-stage like the reference

Stages:
  1. ``reg_by_time``        — vmapped constrained WLS (``MFM.py:48-76``)
  2. ``newey_west_by_time`` — expanding EWMA scan (``MFM.py:80-101``)
  3. ``eigen_risk_adj_by_time`` — batched MC eigen adjustment (``MFM.py:105-126``)
  4. ``vol_regime_adj_by_time`` — masked EWMA scan (``MFM.py:130-167``)

The date axis of stages 1 and 3 (the embarrassingly parallel ones) shards
over the mesh 'date' axis; the stock axis of stage 1 can shard over 'stock',
turning the normal-equation reductions into XLA psums over ICI.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mfm_tpu.config import RiskModelConfig
from mfm_tpu.models.eigen import (
    auto_eigen_chunk,
    draw_bucket,
    eigen_carry_init,
    eigen_risk_adjust_by_time,
    eigen_risk_adjust_incremental,
    sim_sweeps_for,
    simulated_eigen_covs,
    simulated_eigen_draws,
)
from mfm_tpu.models.newey_west import (
    newey_west_expanding,
    newey_west_expanding_resume,
)
from mfm_tpu.models.vol_regime import (
    vol_regime_adjust_by_time,
    vol_regime_adjust_resume,
)
from mfm_tpu.models.bias import eigenfactor_bias_stat
from mfm_tpu.ops.xreg import regress_panel
from mfm_tpu.parallel.mesh import constrain_cross_section
from mfm_tpu.serve.guard import GuardReport, guard_slab


class RiskModelOutputs(NamedTuple):
    factor_ret: jax.Array        # (T, K) [country | industries | styles]
    specific_ret: jax.Array      # (T, N), NaN outside the per-date universe
    r2: jax.Array                # (T,)
    nw_cov: jax.Array            # (T, K, K)
    nw_valid: jax.Array          # (T,)
    eigen_cov: jax.Array         # (T, K, K), NaN where invalid
    eigen_valid: jax.Array       # (T,)
    vr_cov: jax.Array            # (T, K, K)
    lamb: jax.Array              # (T,) volatility multiplier series


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RiskModelState:
    """The resumable checkpoint of the whole risk stack at some date T0.

    Holds the exact scan intermediates of the two recursive stages — the
    Newey-West EWMA carry (``nw_init_carry``'s ``(t, S, A, Z, Ps, hs, gs,
    Slags, xlags)`` tuple) and the vol-regime ``(num, den)`` EWMA sums —
    plus the frozen eigen Monte-Carlo inputs (``sim_covs`` and its declared
    ``sim_length``) so the simulated-covariance draw stays pinned as T grows
    past init, and a config/shape identity stamp so a checkpoint refuses to
    resume under a model that would silently change the math.  Because the
    carries are exact, :meth:`RiskModel.update` from this state is bitwise
    equal to the corresponding suffix of a full-history run.

    Registered as a pytree: the array state (carries + sim_covs) flattens
    into children, everything identity-like rides in static aux_data — so
    ``jax.tree_util.tree_map`` copies work and jit cache keys stay stable.

    ``eigen_batch_hint`` pins the simulated-eigh solver dispatch to the
    init-time ``T * M`` batch (the "solver dispatch pinned at init"
    doctrine): a one-date slab dispatches exactly like the history it
    extends, and the hint never changes across updates so the update step
    never retraces.  The bitwise contract is stated for the default solver
    dispatch (``MFM_EIGH_CPU_JACOBI_BATCH`` unset); forcing a batch
    threshold between slab and history sizes would flip the solver the way
    it already does for the chunked stream.
    """

    nw_carry: tuple
    vr_num: jax.Array
    vr_den: jax.Array
    sim_covs: jax.Array | None
    sim_length: int | None
    eigen_batch_hint: int
    stamp: tuple
    last_date: str | None = None
    #: degraded-mode serving state (all five together, None when the state
    #: was built without quarantine — serve/guard.py): the last healthy
    #: vol-regime covariance, its age in dates, the cumulative quarantined
    #: count, and the trailing-universe ring the collapse check medians over
    last_good_cov: jax.Array | None = None   # (K, K)
    staleness: jax.Array | None = None       # s32 scalar
    quarantine_count: jax.Array | None = None  # s32 scalar
    guard_ring: jax.Array | None = None      # (universe_window,)
    guard_ring_pos: jax.Array | None = None  # s32 scalar
    #: incremental-eigen carry (config.eigen_incremental; all four together,
    #: None otherwise, and sim_covs is None in that mode): the frozen
    #: per-column draw tensor (models/eigen.py::simulated_eigen_draws) and
    #: the exact raw prefix moments (R, p, n) of the columns consumed so
    #: far.  sim_length then mirrors the host-side date count (the draw
    #: cursor's upper bound, used for bucket rollover and the static sweep
    #: tier) rather than a frozen draw length.
    eig_draws: jax.Array | None = None       # (M, K, bucket)
    eig_R: jax.Array | None = None           # (M, K, K)
    eig_p: jax.Array | None = None           # (M, K)
    eig_n: jax.Array | None = None           # s32 scalar

    def tree_flatten(self):
        children = (self.nw_carry, self.vr_num, self.vr_den, self.sim_covs,
                    self.last_good_cov, self.staleness,
                    self.quarantine_count, self.guard_ring,
                    self.guard_ring_pos, self.eig_draws, self.eig_R,
                    self.eig_p, self.eig_n)
        aux = (self.sim_length, self.eigen_batch_hint, self.stamp,
               self.last_date)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        (nw_carry, vr_num, vr_den, sim_covs, last_good_cov, staleness,
         quarantine_count, guard_ring, guard_ring_pos, eig_draws, eig_R,
         eig_p, eig_n) = children
        sim_length, eigen_batch_hint, stamp, last_date = aux
        return cls(nw_carry, vr_num, vr_den, sim_covs,
                   sim_length=sim_length, eigen_batch_hint=eigen_batch_hint,
                   stamp=stamp, last_date=last_date,
                   last_good_cov=last_good_cov, staleness=staleness,
                   quarantine_count=quarantine_count, guard_ring=guard_ring,
                   guard_ring_pos=guard_ring_pos, eig_draws=eig_draws,
                   eig_R=eig_R, eig_p=eig_p, eig_n=eig_n)

    @property
    def t(self) -> int:
        """Number of dates folded into the state so far."""
        return int(self.nw_carry[0])

    @property
    def guarded(self) -> bool:
        """True when the state carries degraded-mode serving leaves."""
        return self.last_good_cov is not None


@dataclasses.dataclass
class RiskModel:
    """Batched Barra-style risk model over a dense masked panel.

    Args mirror the reference's data contract (``MFM.py:18-26``: date,
    stocknames, capital, ret, P industry dummies, Q style factors), in dense
    form:

      ret:      (T, N) next-period returns (the t+1-shifted label the
                assembly stage produces, ``Barra_factor_cal/main.py:99``).
      cap:      (T, N) market caps.
      styles:   (T, N, Q) style exposures.
      industry: (T, N) int codes in [0, P), -1/invalid for missing.
      valid:    (T, N) bool universe mask (the reference's drop-any-NaN rows,
                ``demo.py:25-27``).
    """

    ret: jax.Array
    cap: jax.Array
    styles: jax.Array
    industry: jax.Array
    valid: jax.Array
    n_industries: int
    config: RiskModelConfig = dataclasses.field(default_factory=RiskModelConfig)
    factor_names: Sequence[str] | None = None

    def __post_init__(self):
        # Panels feed the fused jits with donate_argnums.  A raw numpy input
        # must become a JAX-OWNED buffer here: on CPU ``jnp.asarray`` can
        # zero-copy alias the caller's numpy memory (alignment permitting),
        # and donating an aliased buffer corrupts outputs nondeterministically.
        # ``jnp.array`` copies; tracers/jax arrays pass through untouched.
        for f in ("ret", "cap", "styles", "industry", "valid"):
            v = getattr(self, f)
            if isinstance(v, np.ndarray):
                object.__setattr__(self, f, jnp.array(v))
        # Under an ambient ('date','stock') mesh, gather the stock axis to
        # the date-parallel layout ONCE here — every cross-sectional
        # reduction downstream stays device-local, which is what makes the
        # sharded run bitwise-equal to the single-device one (the mesh
        # doctrine's bitwise rule, parallel/mesh.constrain_cross_section).
        panels = constrain_cross_section(
            self.ret, self.cap, self.styles, self.industry, self.valid)
        for f, v in zip(("ret", "cap", "styles", "industry", "valid"), panels):
            object.__setattr__(self, f, v)
        self.T, self.N = self.ret.shape
        self.Q = self.styles.shape[-1]
        self.K = 1 + self.n_industries + self.Q

    # -- stage 1 -----------------------------------------------------------
    def reg_by_time(self):
        res = regress_panel(
            self.ret, self.cap, self.styles, self.industry, self.valid,
            n_industries=self.n_industries,
        )
        return res.factor_ret, res.specific_ret, res.r2

    # -- stage 2 -----------------------------------------------------------
    def newey_west_by_time(self, factor_ret):
        return newey_west_expanding(
            factor_ret, q=self.config.nw_lags, half_life=self.config.nw_half_life,
            min_valid=self.K, method=self.config.nw_method,
        )

    # -- stage 3 -----------------------------------------------------------
    def eigen_risk_adj_by_time(self, nw_cov, nw_valid, key=None, sim_covs=None,
                               sim_length=None, batch_hint=None):
        # ``sim_length`` lets callers that inject sim_covs declare the draw
        # count behind them, enabling the production auto-sweep path (e.g.
        # tools/tpu_parity.py).  Undeclared (None) means full sweep count.
        sim_len = sim_length
        if sim_covs is None:
            if key is None:
                key = jax.random.key(self.config.seed)
            sim_len = self.config.eigen_sim_length or self.T
            sim_covs = simulated_eigen_covs(
                key, self.K, sim_len, self.config.eigen_n_sims,
                dtype=nw_cov.dtype, mc_dtype=self.config.eigen_mc_dtype,
            )
        # value validation happens in RiskModelConfig.__post_init__; "auto"
        # (None here) lets eigen_risk_adjust_by_time derive the sweep cap
        # from sim_length via sim_sweeps_for
        sweeps = self.config.eigen_sim_sweeps
        if sweeps == "auto":
            sweeps = None
        return eigen_risk_adjust_by_time(
            nw_cov, nw_valid, sim_covs, self.config.eigen_scale_coef,
            sim_sweeps=sweeps, sim_length=sim_len,
            chunk=self._resolve_eigen_chunk(sim_covs.shape[0],
                                            nw_cov.dtype.itemsize),
            batch_hint=batch_hint,
            mc_dtype=self.config.eigen_mc_dtype,
        )

    def _resolve_eigen_chunk(self, n_sims: int, itemsize: int) -> int | None:
        """config.eigen_chunk -> a concrete date-chunk size (or None).

        "auto" consults live memory headroom, so resolution happens at trace
        time, once per compile (models.eigen.auto_eigen_chunk).  Under
        ``eigen_mc_dtype`` the streamed G transient is assembled in the MC
        dtype, so its itemsize (2 for bf16) sizes the chunk, not the
        compute dtype's.
        """
        c = self.config.eigen_chunk
        if c == "auto":
            if self.config.eigen_mc_dtype is not None:
                itemsize = jnp.dtype(self.config.eigen_mc_dtype).itemsize
            return auto_eigen_chunk(self.T, n_sims, self.K, itemsize)
        return c

    # -- incremental-eigen (config.eigen_incremental) helpers ---------------
    def _eigen_sweeps(self, count: int) -> int:
        """Static Jacobi sweep cap for the simulated eighs at ``count``
        consumed draw columns — resolved HOST-side (it keys the jit cache),
        so the fused steps retrace only at the rare sim_sweeps_for tier
        boundaries (4K / 32K), never per update."""
        sweeps = self.config.eigen_sim_sweeps
        if sweeps == "auto":
            return sim_sweeps_for(self.K, self.ret.dtype, count)
        return sweeps

    def _fresh_eigen_draws(self, count: int) -> jax.Array:
        """The (M, K, bucket(count)) per-column draw tensor.  Prefix-stable
        by construction (simulated_eigen_draws), so a bucket rollover
        regenerates every already-consumed column bitwise."""
        return simulated_eigen_draws(
            jax.random.key(self.config.seed), self.K, draw_bucket(count),
            self.config.eigen_n_sims, dtype=self.ret.dtype,
            mc_dtype=self.config.eigen_mc_dtype)

    def _advance_eigen_host(self, state) -> tuple:
        """Host-side incremental-eigen bookkeeping for one update: advance
        the date-count mirror by the slab length, roll the draw bucket over
        when the mirror outgrows it (prefix-stable regeneration — every
        already-consumed column reproduces bitwise), and resolve the static
        sweep cap.  Returns ``(eig_draws, eigen_sweeps, sim_length)``;
        outside incremental mode it passes the state's values through
        untouched (eig_draws None, sweeps None)."""
        if not self.config.eigen_incremental:
            return state.eig_draws, None, state.sim_length
        mirror = state.sim_length + self.T
        eig_draws = state.eig_draws
        if mirror > eig_draws.shape[-1]:
            eig_draws = self._fresh_eigen_draws(mirror)
        return eig_draws, self._eigen_sweeps(mirror), mirror

    # -- stage 4 -----------------------------------------------------------
    def vol_regime_adj_by_time(self, factor_ret, eigen_cov, eigen_valid):
        return vol_regime_adjust_by_time(
            factor_ret, eigen_cov, eigen_valid,
            half_life=self.config.vol_regime_half_life,
        )

    # -- full pipeline ------------------------------------------------------
    def run(self, key=None, sim_covs=None, sim_length=None) -> RiskModelOutputs:
        factor_ret, specific_ret, r2 = self.reg_by_time()
        nw_cov, nw_valid = self.newey_west_by_time(factor_ret)
        if self.config.eigen_incremental:
            # causal eigen: same outputs as init_state's full-history run
            # (the serving contract incremental mode is defined by)
            if sim_covs is not None or key is not None:
                raise ValueError(
                    "eigen_incremental=True derives its draws from "
                    "config.seed (they are part of the resumable identity) "
                    "— injected key/sim_covs would break the bitwise-suffix "
                    "contract")
            eigen_cov, eigen_valid, _ = eigen_risk_adjust_incremental(
                nw_cov, nw_valid, self._fresh_eigen_draws(self.T),
                eigen_carry_init(self.config.eigen_n_sims, self.K,
                                 nw_cov.dtype),
                self.config.eigen_scale_coef,
                sim_sweeps=self._eigen_sweeps(self.T),
                chunk=self._resolve_eigen_chunk(self.config.eigen_n_sims,
                                                nw_cov.dtype.itemsize),
                mc_dtype=self.config.eigen_mc_dtype,
            )
        else:
            eigen_cov, eigen_valid = self.eigen_risk_adj_by_time(
                nw_cov, nw_valid, key=key, sim_covs=sim_covs,
                sim_length=sim_length
            )
        vr_cov, lamb = self.vol_regime_adj_by_time(factor_ret, eigen_cov, eigen_valid)
        return RiskModelOutputs(
            factor_ret, specific_ret, r2,
            nw_cov, nw_valid, eigen_cov, eigen_valid, vr_cov, lamb,
        )

    def run_fused(self, key=None, sim_covs=None, sim_length=None) -> RiskModelOutputs:
        """The whole four-stage pipeline as ONE jitted XLA program.

        Same math and outputs as :meth:`run`, but regression, Newey-West,
        eigen adjustment and vol regime fuse into a single compiled step —
        no host round-trips between stages, and the five panel inputs are
        donated so XLA reuses their buffers for intermediates/outputs (on
        backends that support donation; CPU ignores it with a warning,
        which we silence).  After a donating call the instance's panel
        arrays may be invalidated on device backends — treat ``run_fused``
        as consuming the model.

        ``sim_covs`` is resolved on the host first (one tiny (M, K, K)
        computation), so the compiled program is a pure function of the
        panel — the jit cache keys only on shapes, config and sim_length.
        """
        sim_len = sim_length
        if self.config.eigen_incremental:
            # run() generates the per-column draws in-graph from config.seed
            # and refuses injected key/sim_covs — nothing to resolve here
            sim_covs, sim_len = None, None
        elif sim_covs is None:
            if key is None:
                key = jax.random.key(self.config.seed)
            sim_len = self.config.eigen_sim_length or self.T
            sim_covs = simulated_eigen_covs(
                key, self.K, sim_len, self.config.eigen_n_sims,
                dtype=self.ret.dtype, mc_dtype=self.config.eigen_mc_dtype,
            )
        import warnings

        with warnings.catch_warnings():
            # CPU has no donation support; the "donated buffers were not
            # usable" warning is expected there, not actionable
            warnings.filterwarnings(
                "ignore", message=".*[Dd]onat.*", category=UserWarning)
            return _fused_risk_step(
                self.ret, self.cap, self.styles, self.industry, self.valid,
                sim_covs, n_industries=self.n_industries, config=self.config,
                sim_length=sim_len,
            )

    # -- incremental daily-update path --------------------------------------
    def _run_carried(self, sim_covs, sim_length, nw_carry=None, vr_carry=None,
                     eigen_batch_hint=None, dyn_length=None, skip_mask=None,
                     eig_draws=None, eig_carry=None, eigen_sweeps=None):
        """:meth:`run` with resumable scans: same four stages, but Newey-West
        and vol-regime run through their ``*_resume`` forms so the exact EWMA
        carries come out alongside the outputs.  With ``None`` carries this
        IS the full-history run (the resume forms default to the empty-history
        state); with carries from a previous call it continues that history,
        bitwise.  ``skip_mask`` ((T,) bool, None = no guards, the exact
        pre-guard graph) excises quarantined dates from both recursions and
        forces their ``nw_valid`` False so the eigen/vol-regime stages treat
        them as invalid.

        Under ``config.eigen_incremental`` the eigen stage runs its causal
        form instead (``eig_draws`` + the ``eig_carry`` raw prefix moments,
        ``eigen_sweeps`` the host-resolved static sweep cap), and the
        returned 4-tuple's last element is the advanced eigen carry (None
        otherwise).  ``skip_mask`` excises dates from the eigen draw cursor
        exactly like the EWMA carries."""
        if self.T == 1:
            # XLA collapses a unit date batch into a different (gemv)
            # lowering of the residual matvec — 1 ulp off the batched
            # program (any batch >= 2 matches the full history per-date).
            # Duplicate the date and keep lane 0: vmapped lanes are
            # independent, so this pins the batched lowering exactly.
            dup = lambda a: jnp.concatenate([a, a], axis=0)
            res = regress_panel(
                dup(self.ret), dup(self.cap), dup(self.styles),
                dup(self.industry), dup(self.valid),
                n_industries=self.n_industries,
            )
            factor_ret, specific_ret, r2 = (
                res.factor_ret[:1], res.specific_ret[:1], res.r2[:1])
        else:
            factor_ret, specific_ret, r2 = self.reg_by_time()
        nw_cov, nw_valid, nw_carry_out = newey_west_expanding_resume(
            factor_ret, q=self.config.nw_lags,
            half_life=self.config.nw_half_life, min_valid=self.K,
            carry=nw_carry, dyn_length=dyn_length, skip_mask=skip_mask,
        )
        eig_carry_out = None
        if self.config.eigen_incremental:
            if self.T == 1:
                # same unit-batch pinning as the regression above — but the
                # duplicate lane is marked skip=True, so it consumes no draw
                # column and the carry after the two-lane scan equals the
                # carry after lane 0 alone, bitwise
                esk = (jnp.zeros((1,), bool) if skip_mask is None
                       else skip_mask)
                ec, ev, eig_carry_out = eigen_risk_adjust_incremental(
                    jnp.concatenate([nw_cov, nw_cov], axis=0),
                    jnp.concatenate([nw_valid, nw_valid], axis=0),
                    eig_draws, eig_carry, self.config.eigen_scale_coef,
                    sim_sweeps=eigen_sweeps, batch_hint=eigen_batch_hint,
                    skip_mask=jnp.concatenate([esk, jnp.ones((1,), bool)]),
                    mc_dtype=self.config.eigen_mc_dtype,
                )
                eigen_cov, eigen_valid = ec[:1], ev[:1]
            else:
                eigen_cov, eigen_valid, eig_carry_out = (
                    eigen_risk_adjust_incremental(
                        nw_cov, nw_valid, eig_draws, eig_carry,
                        self.config.eigen_scale_coef,
                        sim_sweeps=eigen_sweeps,
                        chunk=self._resolve_eigen_chunk(
                            eig_draws.shape[0], nw_cov.dtype.itemsize),
                        batch_hint=eigen_batch_hint, skip_mask=skip_mask,
                        mc_dtype=self.config.eigen_mc_dtype,
                    ))
        elif self.T == 1:
            # same unit-batch pinning as the regression above, for the
            # per-date eigen MC
            eigen_cov, eigen_valid = self.eigen_risk_adj_by_time(
                jnp.concatenate([nw_cov, nw_cov], axis=0),
                jnp.concatenate([nw_valid, nw_valid], axis=0),
                sim_covs=sim_covs, sim_length=sim_length,
                batch_hint=eigen_batch_hint,
            )
            eigen_cov, eigen_valid = eigen_cov[:1], eigen_valid[:1]
        else:
            eigen_cov, eigen_valid = self.eigen_risk_adj_by_time(
                nw_cov, nw_valid, sim_covs=sim_covs, sim_length=sim_length,
                batch_hint=eigen_batch_hint,
            )
        vr_cov, lamb, vr_carry_out = vol_regime_adjust_resume(
            factor_ret, eigen_cov, eigen_valid,
            half_life=self.config.vol_regime_half_life, carry=vr_carry,
            dyn_length=dyn_length, skip_mask=skip_mask,
        )
        outputs = RiskModelOutputs(
            factor_ret, specific_ret, r2,
            nw_cov, nw_valid, eigen_cov, eigen_valid, vr_cov, lamb,
        )
        return outputs, nw_carry_out, vr_carry_out, eig_carry_out

    def _stamp(self) -> tuple:
        """Identity of (shape, dtype, math config) a checkpoint must match."""
        return (self.n_industries, self.Q, self.N, str(self.ret.dtype),
                self.config.identity())

    def _require_scan_method(self, what: str):
        if self.config.nw_method != "scan":
            raise ValueError(
                f"{what} requires nw_method='scan' (the associative form has "
                f"no resumable carry); got {self.config.nw_method!r}"
            )

    def init_state(self, key=None, sim_covs=None, sim_length=None,
                   last_date: str | None = None):
        """Full-history run that also returns the resumable checkpoint.

        Returns ``(outputs, state)``: ``outputs`` is the same
        :class:`RiskModelOutputs` as :meth:`run_fused` (one fused, donated
        XLA program — treat the call as consuming the model's panels), and
        ``state`` is the :class:`RiskModelState` from which
        :meth:`update` appends further dates in O(1) per date.
        """
        self._require_scan_method("init_state")
        incremental = self.config.eigen_incremental
        sim_len = sim_length
        eig_draws = eig_R = eig_p = eig_n = None
        sweeps = None
        if incremental:
            if sim_covs is not None or key is not None:
                raise ValueError(
                    "eigen_incremental=True derives its draws from "
                    "config.seed (they are part of the resumable identity) "
                    "— injected key/sim_covs would break the bitwise-suffix "
                    "contract")
            # sim_length becomes the host-side date-count mirror: the draw
            # cursor's upper bound, driving bucket rollover and the static
            # sweep tier.  The fused step gets sim_length=None so the jit
            # cache never keys on the growing count.
            sim_len = self.T
            eig_draws = self._fresh_eigen_draws(self.T)
            eig_R, eig_p, eig_n = eigen_carry_init(
                self.config.eigen_n_sims, self.K, self.ret.dtype)
            sweeps = self._eigen_sweeps(self.T)
            hint = self.T * self.config.eigen_n_sims
        else:
            if sim_covs is None:
                if key is None:
                    key = jax.random.key(self.config.seed)
                sim_len = self.config.eigen_sim_length or self.T
                sim_covs = simulated_eigen_covs(
                    key, self.K, sim_len, self.config.eigen_n_sims,
                    dtype=self.ret.dtype,
                    mc_dtype=self.config.eigen_mc_dtype,
                )
            hint = self.T * int(sim_covs.shape[0])
        # the guard ring seeds from the history's universe sizes — read them
        # BEFORE the fused call donates (and may invalidate) self.valid
        guarded = self.config.quarantine.enabled
        if guarded:
            counts = np.asarray(jnp.sum(self.valid, axis=1)).astype(np.int64)
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=".*[Dd]onat.*", category=UserWarning)
            outputs, nw_carry, (vr_num, vr_den), eig_carry = _fused_init_step(
                self.ret, self.cap, self.styles, self.industry, self.valid,
                sim_covs, eig_draws, eig_R, eig_p, eig_n,
                n_industries=self.n_industries, config=self.config,
                sim_length=None if incremental else sim_len,
                eigen_batch_hint=hint, eigen_sweeps=sweeps,
            )
        if incremental:
            eig_R, eig_p, eig_n = eig_carry
        guard = {}
        if guarded:
            guard = self._seed_guard_state(outputs, counts)
        state = RiskModelState(
            nw_carry, vr_num, vr_den, sim_covs,
            sim_length=sim_len, eigen_batch_hint=hint,
            stamp=self._stamp(), last_date=last_date,
            eig_draws=eig_draws, eig_R=eig_R, eig_p=eig_p, eig_n=eig_n,
            **guard,
        )
        return outputs, state

    def _seed_guard_state(self, outputs, universe_counts) -> dict:
        """Degraded-mode leaves for a freshly fitted history (host-side:
        init is not latency-critical and the history is trusted — guards
        protect the *appended* dates).  The trailing-universe ring takes the
        last ``universe_window`` per-date valid counts; the last-good
        covariance is the final eigen-valid date's adjusted covariance."""
        pol = self.config.quarantine
        dtype = np.asarray(outputs.vr_cov).dtype
        W = pol.universe_window
        ring = np.full((W,), np.nan, dtype)
        tail = np.asarray(universe_counts, np.float64)[-W:]
        ring[: len(tail)] = tail.astype(dtype)
        pos = np.int32(len(tail) % W)
        ev = np.asarray(outputs.eigen_valid, bool)
        vr = np.asarray(outputs.vr_cov)
        good = np.nonzero(ev)[0]
        if good.size:
            last_good = vr[good[-1]].copy()
            staleness = np.int32(len(ev) - 1 - good[-1])
        else:
            last_good = np.full(vr.shape[1:], np.nan, dtype)
            staleness = np.int32(len(ev))
        # jnp.array: these leaves are donated by the next guarded update, so
        # they must be JAX-owned copies, not zero-copy views of the local
        # numpy scratch above (whose buffers die with this frame)
        return dict(
            last_good_cov=jnp.array(last_good),
            staleness=jnp.array(staleness, jnp.int32),
            quarantine_count=jnp.array(0, jnp.int32),
            guard_ring=jnp.array(ring),
            guard_ring_pos=jnp.array(pos, jnp.int32),
        )

    def update(self, state: RiskModelState, last_date: str | None = None):
        """Append this model's panel — the new date(s) only — to ``state``.

        The instance's ``(T, N)`` panels are the appended slab (one date or
        several); ``state`` is the checkpoint from :meth:`init_state` or a
        previous :meth:`update`.  Returns ``(outputs, new_state)`` where
        ``outputs`` covers only the slab dates.  One jitted step, panels and
        carries donated — the passed ``state``'s carry buffers may be
        invalidated on device backends; use the returned state.

        Because the carries are the exact scan intermediates and the eigen
        MC is per-date given the frozen ``sim_covs``, the outputs are
        **bitwise equal** to the corresponding suffix of a full-history run
        over the concatenated panel (tests/test_risk_state.py).  Cost is
        O(slab), independent of the history length already folded in.
        """
        self._require_scan_method("update")
        expect = self._stamp()
        if state.stamp != expect:
            raise ValueError(
                f"RiskModelState stamp mismatch: checkpoint carries "
                f"{state.stamp}, this model is {expect} — refusing to resume "
                f"under different shapes/dtype/math config"
            )
        eig_draws, sweeps, mirror = self._advance_eigen_host(state)
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=".*[Dd]onat.*", category=UserWarning)
            outputs, nw_carry, (vr_num, vr_den), eig_carry = \
                _fused_update_step(
                    self.ret, self.cap, self.styles, self.industry,
                    self.valid, state.sim_covs, state.nw_carry, state.vr_num,
                    state.vr_den, jnp.asarray(self.T, jnp.int32),
                    eig_draws, state.eig_R, state.eig_p, state.eig_n,
                    n_industries=self.n_industries, config=self.config,
                    sim_length=(None if self.config.eigen_incremental
                                else state.sim_length),
                    eigen_batch_hint=state.eigen_batch_hint,
                    eigen_sweeps=sweeps,
                )
        eig_R, eig_p, eig_n = (eig_carry if eig_carry is not None
                               else (None, None, None))
        new_state = RiskModelState(
            nw_carry, vr_num, vr_den, state.sim_covs,
            sim_length=mirror,
            eigen_batch_hint=state.eigen_batch_hint,
            stamp=state.stamp,
            last_date=state.last_date if last_date is None else last_date,
            # an unguarded update trusts the slab: degraded-mode leaves ride
            # along unchanged (use update_guarded to maintain them)
            last_good_cov=state.last_good_cov, staleness=state.staleness,
            quarantine_count=state.quarantine_count,
            guard_ring=state.guard_ring,
            guard_ring_pos=state.guard_ring_pos,
            eig_draws=eig_draws, eig_R=eig_R, eig_p=eig_p, eig_n=eig_n,
        )
        return outputs, new_state

    def update_guarded(self, state: RiskModelState, last_date: str | None = None,
                       pre_reasons=None, heal_mask=None):
        """:meth:`update` behind the serving guards (degraded mode).

        Health-checks every slab date (serve/guard.py) inside the same
        single jitted step, excises quarantined dates from the Newey-West /
        vol-regime carries (so the carry after (good, BAD, good) equals the
        carry after (good, good) bitwise), and maintains the degraded-mode
        serving state: the last healthy covariance, its staleness, the
        cumulative quarantine count and the trailing-universe ring.

        Returns ``(outputs, report, new_state)``: ``outputs`` is the raw
        :class:`RiskModelOutputs` over the slab (quarantined dates carry
        their discarded candidates, ``nw_valid``/``eigen_valid`` forced
        False there); ``report`` is the :class:`GuardReport` whose
        ``served_cov`` is what a reader should be handed — ``vr_cov``
        bitwise-untouched at healthy dates, the last healthy covariance at
        quarantined ones.  ``pre_reasons``: optional (T,) uint32 host-side
        verdicts (:func:`mfm_tpu.serve.guard.host_date_reasons`) OR-ed in.
        ``heal_mask``: optional (T,) bool forcing the verdict HEALTHY at
        the marked dates (quarantine counterfactuals, ``mfm_tpu.scenario``);
        ``None`` is the production path, bitwise-identical to omitting it.

        Requires a state built under a quarantine-enabled config
        (:meth:`init_state` seeds the guard leaves).  Same donation story
        as :meth:`update`: panels, carries and guard leaves are donated.
        """
        self._require_scan_method("update_guarded")
        if not self.config.quarantine.enabled:
            raise ValueError(
                "update_guarded requires config.quarantine.enabled=True "
                "(QuarantinePolicy on RiskModelConfig)")
        expect = self._stamp()
        if state.stamp != expect:
            raise ValueError(
                f"RiskModelState stamp mismatch: checkpoint carries "
                f"{state.stamp}, this model is {expect} — refusing to resume "
                f"under different shapes/dtype/math config"
            )
        if not state.guarded:
            raise ValueError(
                "state has no degraded-mode leaves — it was initialized "
                "without quarantine; re-run init_state under a "
                "quarantine-enabled config (the guards need the trailing-"
                "universe ring and last-good covariance seeded at init)")
        pre = (jnp.zeros((self.T,), jnp.uint32) if pre_reasons is None
               else jnp.asarray(pre_reasons, jnp.uint32))
        heal = (jnp.zeros((self.T,), bool) if heal_mask is None
                else jnp.asarray(heal_mask, bool))
        eig_draws, sweeps, mirror = self._advance_eigen_host(state)
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=".*[Dd]onat.*", category=UserWarning)
            outputs, report, nw_carry, (vr_num, vr_den), guard, eig_carry = \
                _fused_update_guarded_step(
                    self.ret, self.cap, self.styles, self.industry,
                    self.valid, state.sim_covs, state.nw_carry,
                    state.vr_num, state.vr_den, state.last_good_cov,
                    state.staleness, state.quarantine_count,
                    state.guard_ring, state.guard_ring_pos, pre, heal,
                    jnp.asarray(self.T, jnp.int32),
                    eig_draws, state.eig_R, state.eig_p, state.eig_n,
                    n_industries=self.n_industries, config=self.config,
                    sim_length=(None if self.config.eigen_incremental
                                else state.sim_length),
                    eigen_batch_hint=state.eigen_batch_hint,
                    eigen_sweeps=sweeps,
                )
        last_good, staleness, q_count, ring, ring_pos = guard
        eig_R, eig_p, eig_n = (eig_carry if eig_carry is not None
                               else (None, None, None))
        new_state = RiskModelState(
            nw_carry, vr_num, vr_den, state.sim_covs,
            sim_length=mirror,
            eigen_batch_hint=state.eigen_batch_hint,
            stamp=state.stamp,
            last_date=state.last_date if last_date is None else last_date,
            last_good_cov=last_good, staleness=staleness,
            quarantine_count=q_count, guard_ring=ring,
            guard_ring_pos=ring_pos,
            eig_draws=eig_draws, eig_R=eig_R, eig_p=eig_p, eig_n=eig_n,
        )
        return outputs, report, new_state

    def bias_stat(self, covs, valid, factor_ret, predlen: int = 1):
        """Eigenfactor bias statistic (``MFM.py:203-204``)."""
        return eigenfactor_bias_stat(covs, valid, factor_ret, predlen)

    # -- host-side sugar ----------------------------------------------------
    def names(self) -> list[str]:
        if self.factor_names is not None:
            return list(self.factor_names)
        return (
            ["country"]
            + [f"industry_{i}" for i in range(self.n_industries)]
            + [f"style_{i}" for i in range(self.Q)]
        )


def portfolio_vol(cov, x, w=None, specific_var=None):
    """Predicted portfolio volatility — the pure scalar the grad subsystem
    differentiates.

    ``sqrt(x' F x [+ sum(w^2 s^2)])`` with ``x`` the (K,) factor-exposure
    vector, ``F`` the (K, K) factor covariance, and the optional specific
    leg from (N,) holdings ``w`` against (N,) specific variances.  Deliberately
    un-jitted and closure-free: :mod:`mfm_tpu.grad` composes it under
    ``jax.grad`` / ``jax.vjp`` / ``vmap`` inside its own donated jits, and the
    serving path (serve/query.py) keeps its existing fused batch kernels.

    Note the sqrt: its gradient is unbounded at vol == 0, which only occurs
    for all-zero pad lanes — grad consumers pad with zero portfolios and trim
    before anything reads those lanes (docs/DIFFERENTIABLE.md).
    """
    var = x @ (cov @ x)
    if w is not None and specific_var is not None:
        var = var + jnp.sum(w * w * specific_var)
    return jnp.sqrt(var)


# module-level so the compile cache is shared across RiskModel instances of
# the same shape/config; RiskModelConfig is frozen-hashable by design
# (config.py), making it a valid static argument.  The five panel operands
# are donated — the regression consumes them in one pass, so XLA can retire
# their buffers into the (T, N)-sized outputs instead of holding both.
@functools.partial(
    jax.jit,
    static_argnames=("n_industries", "config", "sim_length"),
    donate_argnums=(0, 1, 2, 3, 4),
)
def _fused_risk_step(ret, cap, styles, industry, valid, sim_covs, *,
                     n_industries, config, sim_length):
    m = RiskModel(ret, cap, styles, industry, valid,
                  n_industries=n_industries, config=config)
    return m.run(sim_covs=sim_covs, sim_length=sim_length)


# the incremental path's two steps.  Same donation story as the fused step;
# ``eigen_batch_hint`` is static because it gates solver dispatch
# (ops/eigh.py) — it is frozen in the state at init, so the update step
# compiles once per slab shape and never retraces as the history grows.
# ``eigen_sweeps`` (config.eigen_incremental only) is the host-resolved
# static Jacobi sweep cap — it moves only at the rare sim_sweeps_for tier
# boundaries, so steady state stays at <= 1 compile.  The eigen raw-moment
# carry (eig_R, eig_p, eig_n) is donated like the EWMA carries; eig_draws
# is NOT (the host threads the frozen draw tensor into every next update,
# like sim_covs).  All four are None pytrees outside incremental mode, so
# their argnums donate nothing there.
@functools.partial(
    jax.jit,
    static_argnames=("n_industries", "config", "sim_length",
                     "eigen_batch_hint", "eigen_sweeps"),
    donate_argnums=(0, 1, 2, 3, 4, 7, 8, 9),
)
def _fused_init_step(ret, cap, styles, industry, valid, sim_covs,
                     eig_draws, eig_R, eig_p, eig_n, *,
                     n_industries, config, sim_length, eigen_batch_hint,
                     eigen_sweeps=None):
    m = RiskModel(ret, cap, styles, industry, valid,
                  n_industries=n_industries, config=config)
    eig_carry = None if eig_R is None else (eig_R, eig_p, eig_n)
    return m._run_carried(sim_covs, sim_length,
                          eigen_batch_hint=eigen_batch_hint,
                          eig_draws=eig_draws, eig_carry=eig_carry,
                          eigen_sweeps=eigen_sweeps)


# carries are donated too (argnums 6-8, and the eigen moments 11-13): XLA
# retires the old state's buffers straight into the new state's.  sim_covs
# (argnum 5) and eig_draws (argnum 10) are NOT donated — the host keeps the
# reference and threads it unchanged into every next update.
# ``t_count`` (== T, the slab length) is a DEVICE operand, not static: its
# only job is to make the scan trip counts dynamic so XLA cannot inline a
# one-date loop body into the surrounding program (see
# newey_west_expanding_resume's dyn_length).
@functools.partial(
    jax.jit,
    static_argnames=("n_industries", "config", "sim_length",
                     "eigen_batch_hint", "eigen_sweeps"),
    donate_argnums=(0, 1, 2, 3, 4, 6, 7, 8, 11, 12, 13),
)
def _fused_update_step(ret, cap, styles, industry, valid, sim_covs,
                       nw_carry, vr_num, vr_den, t_count,
                       eig_draws, eig_R, eig_p, eig_n, *,
                       n_industries, config, sim_length, eigen_batch_hint,
                       eigen_sweeps=None):
    m = RiskModel(ret, cap, styles, industry, valid,
                  n_industries=n_industries, config=config)
    eig_carry = None if eig_R is None else (eig_R, eig_p, eig_n)
    return m._run_carried(sim_covs, sim_length,
                          nw_carry=nw_carry, vr_carry=(vr_num, vr_den),
                          eigen_batch_hint=eigen_batch_hint,
                          dyn_length=t_count, eig_draws=eig_draws,
                          eig_carry=eig_carry, eigen_sweeps=eigen_sweeps)


def _serve_degraded(vr_cov, eigen_valid, quarantined, last_good, staleness,
                    dyn_length):
    """Degraded-mode serving scan: thread (last_good, staleness) through the
    slab dates in order.  A healthy eigen-valid date refreshes last_good and
    zeroes the age; a quarantined date is served last_good at age+1; healthy
    dates are served their own vr_cov bitwise-untouched (the select picks
    the computed value — no re-math)."""
    T = vr_cov.shape[0]

    def body(i, state):
        last_good, age, served_acc, stale_acc = state
        q_t = jax.lax.dynamic_index_in_dim(quarantined, i, 0, keepdims=False)
        cov_t = jax.lax.dynamic_index_in_dim(vr_cov, i, 0, keepdims=False)
        ev_t = jax.lax.dynamic_index_in_dim(eigen_valid, i, 0, keepdims=False)
        served_t = jnp.where(q_t, last_good, cov_t)
        stale_t = jnp.where(q_t, age + jnp.int32(1), jnp.int32(0))
        healthy = ~q_t & ev_t
        last_good = jnp.where(healthy, cov_t, last_good)
        age = jnp.where(healthy, jnp.int32(0), age + jnp.int32(1))
        served_acc = jax.lax.dynamic_update_index_in_dim(
            served_acc, served_t, i, 0)
        stale_acc = jax.lax.dynamic_update_index_in_dim(
            stale_acc, stale_t, i, 0)
        return last_good, age, served_acc, stale_acc

    hi = (jnp.int32(T) if dyn_length is None
          else dyn_length.astype(jnp.int32))
    return jax.lax.fori_loop(
        jnp.int32(0), hi, body,
        (last_good, staleness.astype(jnp.int32),
         jnp.zeros_like(vr_cov), jnp.zeros((T,), jnp.int32)),
    )


# the guarded serving step: guards, the carried four stages with quarantined
# dates excised, and the degraded-mode serving scan — still ONE compiled
# program (the steady-state serving loop stays at <= 1 compile).  Donation
# adds the guard-state operands (9-13) and the eigen moments (18-20);
# sim_covs (5), pre_reasons (14), heal_mask (15) and eig_draws (17) stay
# host-owned.  Quarantined dates consume NO eigen draw column (the same
# skip_mask that excises them from the EWMA carries), so the eigen carry
# after (good, BAD, good) equals the carry after (good, good) bitwise.
@functools.partial(
    jax.jit,
    static_argnames=("n_industries", "config", "sim_length",
                     "eigen_batch_hint", "eigen_sweeps"),
    donate_argnums=(0, 1, 2, 3, 4, 6, 7, 8, 9, 10, 11, 12, 13, 18, 19, 20),
)
def _fused_update_guarded_step(ret, cap, styles, industry, valid, sim_covs,
                               nw_carry, vr_num, vr_den, last_good, staleness,
                               q_count, ring, ring_pos, pre_reasons, heal_mask,
                               t_count, eig_draws, eig_R, eig_p, eig_n, *,
                               n_industries, config, sim_length,
                               eigen_batch_hint, eigen_sweeps=None):
    # guard coverage counts reduce over the stock axis — gather it to the
    # date-local layout first so the guarded verdicts (and therefore the
    # excision masks) are bitwise-identical to the unsharded program
    ret, cap, styles, industry, valid = constrain_cross_section(
        ret, cap, styles, industry, valid)
    quarantined, reasons, ring, ring_pos = guard_slab(
        ret, cap, valid, ring, ring_pos, config.quarantine,
        pre_reasons=pre_reasons, heal_mask=heal_mask)
    m = RiskModel(ret, cap, styles, industry, valid,
                  n_industries=n_industries, config=config)
    eig_carry = None if eig_R is None else (eig_R, eig_p, eig_n)
    outputs, nw_carry_out, vr_carry_out, eig_carry_out = m._run_carried(
        sim_covs, sim_length,
        nw_carry=nw_carry, vr_carry=(vr_num, vr_den),
        eigen_batch_hint=eigen_batch_hint, dyn_length=t_count,
        skip_mask=quarantined, eig_draws=eig_draws, eig_carry=eig_carry,
        eigen_sweeps=eigen_sweeps)
    last_good, staleness, served, stale_series = _serve_degraded(
        outputs.vr_cov, outputs.eigen_valid, quarantined, last_good,
        staleness, t_count)
    q_count = q_count + jnp.sum(quarantined.astype(jnp.int32))
    report = GuardReport(quarantined, reasons, stale_series, served)
    return (outputs, report, nw_carry_out, vr_carry_out,
            (last_good, staleness, q_count, ring, ring_pos), eig_carry_out)
