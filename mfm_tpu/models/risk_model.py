"""RiskModel — the TPU-native equivalent of the reference's ``MFM`` driver.

The reference (``Barra-master/mfm/MFM.py``) loops Python over dates four
times (regression, Newey-West, eigen adjustment, vol regime).  Here each
stage is one jitted, batched call over the whole (T, N) panel:

    rm = RiskModel(ret, cap, styles, industry, valid, n_industries=P)
    out = rm.run(key)       # or stage-by-stage like the reference

Stages:
  1. ``reg_by_time``        — vmapped constrained WLS (``MFM.py:48-76``)
  2. ``newey_west_by_time`` — expanding EWMA scan (``MFM.py:80-101``)
  3. ``eigen_risk_adj_by_time`` — batched MC eigen adjustment (``MFM.py:105-126``)
  4. ``vol_regime_adj_by_time`` — masked EWMA scan (``MFM.py:130-167``)

The date axis of stages 1 and 3 (the embarrassingly parallel ones) shards
over the mesh 'date' axis; the stock axis of stage 1 can shard over 'stock',
turning the normal-equation reductions into XLA psums over ICI.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mfm_tpu.config import RiskModelConfig
from mfm_tpu.models.eigen import (
    auto_eigen_chunk,
    eigen_risk_adjust_by_time,
    simulated_eigen_covs,
)
from mfm_tpu.models.newey_west import newey_west_expanding
from mfm_tpu.models.vol_regime import vol_regime_adjust_by_time
from mfm_tpu.models.bias import eigenfactor_bias_stat
from mfm_tpu.ops.xreg import regress_panel


class RiskModelOutputs(NamedTuple):
    factor_ret: jax.Array        # (T, K) [country | industries | styles]
    specific_ret: jax.Array      # (T, N), NaN outside the per-date universe
    r2: jax.Array                # (T,)
    nw_cov: jax.Array            # (T, K, K)
    nw_valid: jax.Array          # (T,)
    eigen_cov: jax.Array         # (T, K, K), NaN where invalid
    eigen_valid: jax.Array       # (T,)
    vr_cov: jax.Array            # (T, K, K)
    lamb: jax.Array              # (T,) volatility multiplier series


@dataclasses.dataclass
class RiskModel:
    """Batched Barra-style risk model over a dense masked panel.

    Args mirror the reference's data contract (``MFM.py:18-26``: date,
    stocknames, capital, ret, P industry dummies, Q style factors), in dense
    form:

      ret:      (T, N) next-period returns (the t+1-shifted label the
                assembly stage produces, ``Barra_factor_cal/main.py:99``).
      cap:      (T, N) market caps.
      styles:   (T, N, Q) style exposures.
      industry: (T, N) int codes in [0, P), -1/invalid for missing.
      valid:    (T, N) bool universe mask (the reference's drop-any-NaN rows,
                ``demo.py:25-27``).
    """

    ret: jax.Array
    cap: jax.Array
    styles: jax.Array
    industry: jax.Array
    valid: jax.Array
    n_industries: int
    config: RiskModelConfig = dataclasses.field(default_factory=RiskModelConfig)
    factor_names: Sequence[str] | None = None

    def __post_init__(self):
        self.T, self.N = self.ret.shape
        self.Q = self.styles.shape[-1]
        self.K = 1 + self.n_industries + self.Q

    # -- stage 1 -----------------------------------------------------------
    def reg_by_time(self):
        res = regress_panel(
            self.ret, self.cap, self.styles, self.industry, self.valid,
            n_industries=self.n_industries,
        )
        return res.factor_ret, res.specific_ret, res.r2

    # -- stage 2 -----------------------------------------------------------
    def newey_west_by_time(self, factor_ret):
        return newey_west_expanding(
            factor_ret, q=self.config.nw_lags, half_life=self.config.nw_half_life,
            min_valid=self.K, method=self.config.nw_method,
        )

    # -- stage 3 -----------------------------------------------------------
    def eigen_risk_adj_by_time(self, nw_cov, nw_valid, key=None, sim_covs=None,
                               sim_length=None):
        # ``sim_length`` lets callers that inject sim_covs declare the draw
        # count behind them, enabling the production auto-sweep path (e.g.
        # tools/tpu_parity.py).  Undeclared (None) means full sweep count.
        sim_len = sim_length
        if sim_covs is None:
            if key is None:
                key = jax.random.key(self.config.seed)
            sim_len = self.config.eigen_sim_length or self.T
            sim_covs = simulated_eigen_covs(
                key, self.K, sim_len, self.config.eigen_n_sims,
                dtype=nw_cov.dtype,
            )
        # value validation happens in RiskModelConfig.__post_init__; "auto"
        # (None here) lets eigen_risk_adjust_by_time derive the sweep cap
        # from sim_length via sim_sweeps_for
        sweeps = self.config.eigen_sim_sweeps
        if sweeps == "auto":
            sweeps = None
        return eigen_risk_adjust_by_time(
            nw_cov, nw_valid, sim_covs, self.config.eigen_scale_coef,
            sim_sweeps=sweeps, sim_length=sim_len,
            chunk=self._resolve_eigen_chunk(sim_covs.shape[0],
                                            nw_cov.dtype.itemsize),
        )

    def _resolve_eigen_chunk(self, n_sims: int, itemsize: int) -> int | None:
        """config.eigen_chunk -> a concrete date-chunk size (or None).

        "auto" consults live memory headroom, so resolution happens at trace
        time, once per compile (models.eigen.auto_eigen_chunk).
        """
        c = self.config.eigen_chunk
        if c == "auto":
            return auto_eigen_chunk(self.T, n_sims, self.K, itemsize)
        return c

    # -- stage 4 -----------------------------------------------------------
    def vol_regime_adj_by_time(self, factor_ret, eigen_cov, eigen_valid):
        return vol_regime_adjust_by_time(
            factor_ret, eigen_cov, eigen_valid,
            half_life=self.config.vol_regime_half_life,
        )

    # -- full pipeline ------------------------------------------------------
    def run(self, key=None, sim_covs=None, sim_length=None) -> RiskModelOutputs:
        factor_ret, specific_ret, r2 = self.reg_by_time()
        nw_cov, nw_valid = self.newey_west_by_time(factor_ret)
        eigen_cov, eigen_valid = self.eigen_risk_adj_by_time(
            nw_cov, nw_valid, key=key, sim_covs=sim_covs, sim_length=sim_length
        )
        vr_cov, lamb = self.vol_regime_adj_by_time(factor_ret, eigen_cov, eigen_valid)
        return RiskModelOutputs(
            factor_ret, specific_ret, r2,
            nw_cov, nw_valid, eigen_cov, eigen_valid, vr_cov, lamb,
        )

    def run_fused(self, key=None, sim_covs=None, sim_length=None) -> RiskModelOutputs:
        """The whole four-stage pipeline as ONE jitted XLA program.

        Same math and outputs as :meth:`run`, but regression, Newey-West,
        eigen adjustment and vol regime fuse into a single compiled step —
        no host round-trips between stages, and the five panel inputs are
        donated so XLA reuses their buffers for intermediates/outputs (on
        backends that support donation; CPU ignores it with a warning,
        which we silence).  After a donating call the instance's panel
        arrays may be invalidated on device backends — treat ``run_fused``
        as consuming the model.

        ``sim_covs`` is resolved on the host first (one tiny (M, K, K)
        computation), so the compiled program is a pure function of the
        panel — the jit cache keys only on shapes, config and sim_length.
        """
        sim_len = sim_length
        if sim_covs is None:
            if key is None:
                key = jax.random.key(self.config.seed)
            sim_len = self.config.eigen_sim_length or self.T
            sim_covs = simulated_eigen_covs(
                key, self.K, sim_len, self.config.eigen_n_sims,
                dtype=self.ret.dtype,
            )
        import warnings

        with warnings.catch_warnings():
            # CPU has no donation support; the "donated buffers were not
            # usable" warning is expected there, not actionable
            warnings.filterwarnings(
                "ignore", message=".*[Dd]onat.*", category=UserWarning)
            return _fused_risk_step(
                self.ret, self.cap, self.styles, self.industry, self.valid,
                sim_covs, n_industries=self.n_industries, config=self.config,
                sim_length=sim_len,
            )

    def bias_stat(self, covs, valid, factor_ret, predlen: int = 1):
        """Eigenfactor bias statistic (``MFM.py:203-204``)."""
        return eigenfactor_bias_stat(covs, valid, factor_ret, predlen)

    # -- host-side sugar ----------------------------------------------------
    def names(self) -> list[str]:
        if self.factor_names is not None:
            return list(self.factor_names)
        return (
            ["country"]
            + [f"industry_{i}" for i in range(self.n_industries)]
            + [f"style_{i}" for i in range(self.Q)]
        )


# module-level so the compile cache is shared across RiskModel instances of
# the same shape/config; RiskModelConfig is frozen-hashable by design
# (config.py), making it a valid static argument.  The five panel operands
# are donated — the regression consumes them in one pass, so XLA can retire
# their buffers into the (T, N)-sized outputs instead of holding both.
@functools.partial(
    jax.jit,
    static_argnames=("n_industries", "config", "sim_length"),
    donate_argnums=(0, 1, 2, 3, 4),
)
def _fused_risk_step(ret, cap, styles, industry, valid, sim_covs, *,
                     n_industries, config, sim_length):
    m = RiskModel(ret, cap, styles, industry, valid,
                  n_industries=n_industries, config=config)
    return m.run(sim_covs=sim_covs, sim_length=sim_length)
