"""Model-health statistics: eigenfactor bias stat and Bayesian specific-vol
shrinkage.

- :func:`eigenfactor_bias_stat` — the USE4 acceptance test comparing predicted
  eigen-portfolio volatility to realized returns
  (``Barra-master/mfm/utils.py:97-117``).
- :func:`bayes_shrink` — cap-decile Bayesian shrinkage of specific volatility
  (``utils.py:133-168``; defined in the reference but never wired into a
  driver — included here for completeness, SURVEY.md §7.2 step 5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.utils.prec import highest_matmul_precision


@highest_matmul_precision
def eigenfactor_bias_stat(
    covs: jax.Array,
    valid: jax.Array,
    factor_ret: jax.Array,
    predlen: int = 1,
) -> jax.Array:
    """Bias statistic of the eigenfactor portfolios.

    Contract (``utils.py:97-117``): for each date i, eigendecompose cov_i,
    normalize each eigenvector to sum 1 (portfolio weights), predicted vol
    ``sigma = sqrt(predlen * diag(U' cov U))``, realized return over the next
    ``predlen`` dates compounded, b_i = U' r / sigma, and the statistic is the
    per-factor std of b over dates (population std, ``np.std``).

    Dates with invalid covariances are skipped (the reference's bare
    ``except: pass``).  Returns (K,) bias statistics.
    """
    T, K = factor_ret.shape
    dtype = factor_ret.dtype
    eye = jnp.eye(K, dtype=dtype)
    safe = jnp.where(valid[:, None, None], covs, eye)

    # compounded realized returns over (i, i+predlen]: computed from cumsums of
    # log1p so the whole family is O(T K) (factor returns are close to 0;
    # matches (1+r).prod() - 1, utils.py:108)
    cs = jnp.cumsum(jnp.log1p(factor_ret), axis=0)
    cs = jnp.concatenate([jnp.zeros((1, K), dtype), cs], axis=0)  # (T+1, K)
    retlen = jnp.expm1(cs[predlen:] - cs[:-predlen])  # (T-predlen+1, K)
    retlen = retlen[1:]  # realized over (i, i+predlen], i = 0..T-predlen-1

    def one(cov):
        _, U = jnp.linalg.eigh(cov)
        U = U / jnp.sum(U, axis=0, keepdims=True)
        sigma = jnp.sqrt(predlen * jnp.einsum("ki,kl,li->i", U, cov, U))
        return U, sigma

    U_all, sig_all = jax.vmap(one)(safe[: T - predlen])
    b = jnp.einsum("tki,tk->ti", U_all, retlen) / sig_all  # (T-predlen, K)
    m = valid[: T - predlen]
    n = jnp.sum(m)
    bz = jnp.where(m[:, None], b, 0.0)
    mu = jnp.sum(bz, axis=0) / n
    var = jnp.sum(jnp.where(m[:, None], (b - mu) ** 2, 0.0), axis=0) / n
    return jnp.sqrt(var)


def bias_stats_summary(
    nw_cov, nw_valid, eigen_cov, eigen_valid, factor_ret,
    burn_in: int = 252,
) -> dict:
    """JSON-ready USE4 acceptance summary: bias statistics per eigenfactor
    rank, before (Newey-West) and after the eigen adjustment, over all valid
    dates and — when any exist — excluding the expanding-window burn-in,
    where near-singular early covariances make the smallest eigen-
    portfolios' predicted vol meaninglessly tiny and the full-sample max
    explodes.  Non-finite ranks become ``None`` (strict JSON) and are
    excluded from the aggregates rather than nulling them.
    """
    import numpy as np

    scopes = [("all_valid_dates", {
        "newey_west": eigenfactor_bias_stat(nw_cov, nw_valid, factor_ret),
        "eigen_adjusted": eigenfactor_bias_stat(
            eigen_cov, eigen_valid, factor_ret),
    })]
    if bool(np.asarray(nw_valid)[burn_in:].any()):
        t_ok = jnp.arange(factor_ret.shape[0]) >= burn_in
        scopes.append((f"after_burn_in_{burn_in}", {
            "newey_west": eigenfactor_bias_stat(
                nw_cov, nw_valid & t_ok, factor_ret),
            "eigen_adjusted": eigenfactor_bias_stat(
                eigen_cov, eigen_valid & t_ok, factor_ret),
        }))

    def _num(x):
        return round(float(x), 4) if np.isfinite(x) else None

    out: dict = {}
    for scope, stats in scopes:
        out[scope] = {}
        for label, b in stats.items():
            b = np.asarray(b)
            dev = np.abs(b[np.isfinite(b)] - 1)
            out[scope][label] = {
                "bias": [_num(x) for x in b],
                "mean_abs_dev_from_1": _num(np.mean(dev)) if dev.size else None,
                "max_abs_dev_from_1": _num(np.max(dev)) if dev.size else None,
            }
    return out


def plot_bias_stats(bias_by_label: dict, path: str) -> None:
    """Plot eigenfactor bias statistics per eigen-portfolio rank.

    The reference plots the bias statistic inside ``eigenfactor_bias_stat``
    itself (``mfm/utils.py:116``, the USE4 acceptance picture: bias ~ 1 after
    adjustment, U-shaped before).  Compute stays pure here; this renders any
    number of labelled bias arrays (e.g. {"newey_west": b0, "eigen_adjusted":
    b1}) to ``path``.  Renders through an explicit Agg canvas so the
    process-global matplotlib backend (a notebook's inline backend, say) is
    left untouched.
    """
    import numpy as np
    from matplotlib.backends.backend_agg import FigureCanvasAgg
    from matplotlib.figure import Figure

    fig = Figure(figsize=(7, 4))
    FigureCanvasAgg(fig)
    ax = fig.add_subplot()
    for label, b in bias_by_label.items():
        b = np.asarray(b)
        ax.plot(1 + np.arange(b.shape[0]), b, marker="o", ms=3, lw=1,
                label=label)
    ax.axhline(1.0, color="gray", lw=0.8, ls="--")
    ax.set_xlabel("eigenfactor rank")
    ax.set_ylabel("bias statistic")
    ax.legend()
    fig.tight_layout()
    fig.savefig(path, dpi=120)


@highest_matmul_precision
def portfolio_bias_stat(
    X: jax.Array,
    design_valid: jax.Array,
    covs: jax.Array,
    cov_valid: jax.Array,
    spec_vol: jax.Array,
    ret: jax.Array,
    weights: jax.Array,
):
    """Bias statistic of arbitrary test portfolios — the USE4 acceptance
    test in its headline form (random portfolios), which the reference
    implements only for eigenfactor portfolios (``utils.py:97-117``).

    For each base portfolio q and date t: weights are the q-th base vector
    restricted to date t's support (regression universe with a specific-vol
    estimate) and renormalized to sum 1; predicted variance is
    ``x'F_t x + sum_i w_i^2 sigma_i^2`` with ``x = X_t' w`` (the same
    decomposition as ``RiskPipelineResult.portfolio_risk``); the realized
    return is the t+1-labelled period return ``ret[t+1]`` of the held
    stocks (a holding with no t+1 observation contributes 0 — suspension),
    matching :func:`eigenfactor_bias_stat`'s cov_i -> return_(i+1)
    alignment.  The bias of portfolio q is the population std of
    ``z_t = r_t / sigma_pred_t`` over its valid dates; a well-calibrated
    model gives bias ~ 1.

    Args: ``X`` (T, N, K) per-date regression designs; ``design_valid``
    (T, N); ``covs`` (T, K, K) adjusted factor covariances; ``cov_valid``
    (T,); ``spec_vol`` (T, N) per-stock vol (NaN = no estimate);
    ``ret`` (T, N) t+1-labelled returns; ``weights`` (Q, N) nonnegative
    base weights.  Returns ``(z (Q, T-1), mask (Q, T-1))`` — compute the
    std under whatever date mask you need (full sample / burn-in-excluded)
    with :func:`bias_std`.
    """
    dtype = X.dtype
    K = X.shape[-1]
    support = design_valid & jnp.isfinite(spec_vol)
    sf = support.astype(dtype)
    s = jnp.einsum("tn,qn->qt", sf, weights)                    # (Q, T)
    s_safe = jnp.where(s > 0, s, 1.0)

    Xs = jnp.where(support[:, :, None], X, 0.0)
    x = jnp.einsum("tnk,qn->qtk", Xs, weights) / s_safe[..., None]
    covs_safe = jnp.where(cov_valid[:, None, None], covs,
                          jnp.eye(K, dtype=dtype))
    fvar = jnp.einsum("qtk,tkl,qtl->qt", x, covs_safe, x)
    sv = jnp.where(support, spec_vol, 0.0)
    svar = jnp.einsum("tn,qn->qt", sv * sv, weights * weights) / (s_safe ** 2)
    sigma = jnp.sqrt(fvar + svar)                               # (Q, T)

    # realized at formation date t = the held stocks' t+1-labelled returns,
    # with the formation-date weights (support is the FORMATION date's; a
    # holding with no t+1 observation contributes 0).  The effective weight
    # w[q,t,n] = weights[q,n] * support[t,n] is rank-1 in q, so the
    # contraction stays O(TN + QT) — no (Q, T, N) intermediate
    ret0 = jnp.where(jnp.isfinite(ret), ret, 0.0)
    r = jnp.einsum("tn,qn->qt", sf[:-1] * ret0[1:], weights) / s_safe[:, :-1]

    sig = sigma[:, :-1]
    ok = (cov_valid[:-1][None, :] & (s[:, :-1] > 0) & (sig > 0)
          & jnp.isfinite(sig))
    z = jnp.where(ok, r / jnp.where(ok, sig, 1.0), jnp.nan)
    return z, ok


def bias_std(z: jax.Array, mask: jax.Array, axis: int = -1) -> jax.Array:
    """Population std over masked entries (``np.std`` semantics, matching
    the reference's bias statistic; NaN where fewer than 2 valid)."""
    m = mask & jnp.isfinite(z)
    n = jnp.sum(m, axis=axis)
    zz = jnp.where(m, z, 0.0)
    mu = jnp.sum(zz, axis=axis) / jnp.maximum(n, 1)
    var = jnp.sum(jnp.where(m, (z - jnp.expand_dims(mu, axis)) ** 2, 0.0),
                  axis=axis) / jnp.maximum(n, 1)
    return jnp.where(n >= 2, jnp.sqrt(var), jnp.nan)


@highest_matmul_precision
def bayes_shrink(
    volatility: jax.Array,
    capital: jax.Array,
    ngroup: int = 10,
    q: float = 1.0,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Bayesian shrinkage of specific volatility toward cap-group means.

    Contract (``utils.py:133-168``): stocks are bucketed into ``ngroup``
    cap quantile groups; each group has cap-weighted mean vol m_g and
    equal-weight dispersion s_g = sqrt(mean((vol - m_g)^2)); the shrinkage
    intensity is ``v = q|vol - m_g| / (q|vol - m_g| + s_g)`` and the estimate
    ``v m_g + (1-v)|vol|``.

    Group assignment uses quantile edges (matching ``pd.qcut`` for distinct
    caps); ties across edges may bucket differently than pandas.

    ``mask`` (bool (N,), optional) restricts the universe: quantile edges,
    group means, and dispersions are computed over masked-in stocks only
    (the per-date ragged universe of :func:`mfm_tpu.models.specific.
    specific_risk_by_time`); masked-out entries return NaN.  ``mask=None``
    matches the reference's all-stocks behavior except in two degenerate
    cases where the reference emits NaN and this returns the limit value:
    a 0/0 shrinkage intensity (singleton group / zero dispersion at the
    group mean -> |vol| itself) and empty groups when N < ngroup.
    """
    dtype = volatility.dtype
    if mask is None:
        qs = jnp.quantile(capital, jnp.linspace(0.0, 1.0, ngroup + 1)[1:-1])
        mf = jnp.ones_like(volatility)
    else:
        # sanitize masked-out entries FIRST: NaN vol/cap under the mask is
        # the natural input, and 0 * NaN = NaN would otherwise poison every
        # group mean through the zeroed one-hot matmuls
        volatility = jnp.where(mask, volatility, 0.0)
        capital = jnp.where(mask, capital, 1.0)
        # masked quantile, linear interpolation over the n valid caps (the
        # same convention jnp.quantile uses over a full array)
        mf = mask.astype(dtype)
        n_valid = jnp.sum(mask)
        s = jnp.sort(jnp.where(mask, capital, jnp.inf))
        pos = jnp.linspace(0.0, 1.0, ngroup + 1)[1:-1] * (n_valid - 1)
        lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, capital.shape[0] - 1)
        hi = jnp.clip(jnp.ceil(pos).astype(jnp.int32), 0, capital.shape[0] - 1)
        frac = (pos - lo).astype(dtype)
        qs = s[lo] * (1.0 - frac) + s[hi] * frac
    group = jnp.searchsorted(qs, capital, side="left")  # (N,) in [0, ngroup)
    oh = (group[:, None]
          == jnp.arange(ngroup, dtype=jnp.int32)[None, :]).astype(dtype)  # (N, G)
    oh = oh * mf[:, None]
    cap_g = oh.T @ capital
    cnt_g = jnp.sum(oh, axis=0)
    # a group can be EMPTY when the universe is smaller than ngroup
    # (coincident quantile edges); no stock belongs to it, but a NaN mean
    # there would still poison every stock through 0*NaN in oh @ m_g
    m_g = jnp.where(cnt_g > 0,
                    (oh.T @ (volatility * capital))
                    / jnp.where(cap_g > 0, cap_g, 1.0), 0.0)
    dev2 = (volatility[:, None] - m_g[None, :]) ** 2 * oh
    s_g = jnp.where(cnt_g > 0,
                    jnp.sqrt(jnp.sum(dev2, axis=0)
                             / jnp.where(cnt_g > 0, cnt_g, 1.0)), 0.0)
    m_s = oh @ m_g
    s_s = oh @ s_g
    a = q * jnp.abs(volatility - m_s)
    # a == s == 0 (a singleton group, or vol exactly at its group mean with
    # zero dispersion) is 0/0 in the reference (utils.py:163); both shrink
    # targets coincide with |vol| there, so v = 0 is the value's limit
    v = jnp.where(a + s_s > 0, a / jnp.where(a + s_s > 0, a + s_s, 1.0), 0.0)
    out = v * m_s + (1.0 - v) * jnp.abs(volatility)
    if mask is not None:
        out = jnp.where(mask, out, jnp.nan)
    return out
