"""Model-health statistics: eigenfactor bias stat and Bayesian specific-vol
shrinkage.

- :func:`eigenfactor_bias_stat` — the USE4 acceptance test comparing predicted
  eigen-portfolio volatility to realized returns
  (``Barra-master/mfm/utils.py:97-117``).
- :func:`bayes_shrink` — cap-decile Bayesian shrinkage of specific volatility
  (``utils.py:133-168``; defined in the reference but never wired into a
  driver — included here for completeness, SURVEY.md §7.2 step 5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.utils.prec import highest_matmul_precision


@highest_matmul_precision
def eigenfactor_bias_stat(
    covs: jax.Array,
    valid: jax.Array,
    factor_ret: jax.Array,
    predlen: int = 1,
) -> jax.Array:
    """Bias statistic of the eigenfactor portfolios.

    Contract (``utils.py:97-117``): for each date i, eigendecompose cov_i,
    normalize each eigenvector to sum 1 (portfolio weights), predicted vol
    ``sigma = sqrt(predlen * diag(U' cov U))``, realized return over the next
    ``predlen`` dates compounded, b_i = U' r / sigma, and the statistic is the
    per-factor std of b over dates (population std, ``np.std``).

    Dates with invalid covariances are skipped (the reference's bare
    ``except: pass``).  Returns (K,) bias statistics.
    """
    T, K = factor_ret.shape
    dtype = factor_ret.dtype
    eye = jnp.eye(K, dtype=dtype)
    safe = jnp.where(valid[:, None, None], covs, eye)

    # compounded realized returns over (i, i+predlen]: computed from cumsums of
    # log1p so the whole family is O(T K) (factor returns are close to 0;
    # matches (1+r).prod() - 1, utils.py:108)
    cs = jnp.cumsum(jnp.log1p(factor_ret), axis=0)
    cs = jnp.concatenate([jnp.zeros((1, K), dtype), cs], axis=0)  # (T+1, K)
    retlen = jnp.expm1(cs[predlen:] - cs[:-predlen])  # (T-predlen+1, K)
    retlen = retlen[1:]  # realized over (i, i+predlen], i = 0..T-predlen-1

    def one(cov):
        _, U = jnp.linalg.eigh(cov)
        U = U / jnp.sum(U, axis=0, keepdims=True)
        sigma = jnp.sqrt(predlen * jnp.einsum("ki,kl,li->i", U, cov, U))
        return U, sigma

    U_all, sig_all = jax.vmap(one)(safe[: T - predlen])
    b = jnp.einsum("tki,tk->ti", U_all, retlen) / sig_all  # (T-predlen, K)
    m = valid[: T - predlen]
    n = jnp.sum(m)
    bz = jnp.where(m[:, None], b, 0.0)
    mu = jnp.sum(bz, axis=0) / n
    var = jnp.sum(jnp.where(m[:, None], (b - mu) ** 2, 0.0), axis=0) / n
    return jnp.sqrt(var)


@highest_matmul_precision
def bayes_shrink(
    volatility: jax.Array,
    capital: jax.Array,
    ngroup: int = 10,
    q: float = 1.0,
) -> jax.Array:
    """Bayesian shrinkage of specific volatility toward cap-group means.

    Contract (``utils.py:133-168``): stocks are bucketed into ``ngroup``
    cap quantile groups; each group has cap-weighted mean vol m_g and
    equal-weight dispersion s_g = sqrt(mean((vol - m_g)^2)); the shrinkage
    intensity is ``v = q|vol - m_g| / (q|vol - m_g| + s_g)`` and the estimate
    ``v m_g + (1-v)|vol|``.

    Group assignment uses quantile edges (matching ``pd.qcut`` for distinct
    caps); ties across edges may bucket differently than pandas.
    """
    dtype = volatility.dtype
    n = capital.shape[0]
    qs = jnp.quantile(capital, jnp.linspace(0.0, 1.0, ngroup + 1)[1:-1])
    group = jnp.searchsorted(qs, capital, side="left")  # (N,) in [0, ngroup)
    oh = (group[:, None] == jnp.arange(ngroup)[None, :]).astype(dtype)  # (N, G)
    cap_g = oh.T @ capital
    m_g = (oh.T @ (volatility * capital)) / cap_g  # cap-weighted group mean
    cnt_g = jnp.sum(oh, axis=0)
    dev2 = (volatility[:, None] - m_g[None, :]) ** 2 * oh
    s_g = jnp.sqrt(jnp.sum(dev2, axis=0) / cnt_g)
    m_s = oh @ m_g
    s_s = oh @ s_g
    a = q * jnp.abs(volatility - m_s)
    v = a / (a + s_s)
    return v * m_s + (1.0 - v) * jnp.abs(volatility)
