"""Newey-West factor-return covariance, single-shot and expanding.

Contract (``Barra-master/mfm/utils.py:16-50``): for a window of factor returns
x_0..x_{t-1} with exp-decay weights ``w_i ∝ 0.5**((t-1-i)/tau)`` normalized to
sum 1, demeaned by the weighted mean:

    Gamma_0  = sum_i w_i d_i d_i'
    Gamma_l  = sum_{i} w_{i+l} d_i d_{i+l}'          (weight of the later obs)
    V        = Gamma_0 + sum_{l=1..q} (1 - l/(1+q)) (Gamma_l + Gamma_l')

and the estimate is *invalid* when t <= q or t <= K (the reference raises and
stores an empty DataFrame, ``mfm/MFM.py:92-96``).

The reference recomputes the full window per date — O(T^2 K^2) Python list
comprehensions.  Every sum above is an exponentially-weighted cumulative sum,
so the whole expanding family is one ``lax.scan`` with EWMA recursions:
O(T K^2 q), no window rematerialization, numerically stable (no growing
weights), and the per-date output V_t is bitwise the same math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.utils.prec import highest_matmul_precision


@highest_matmul_precision
def newey_west(ret: jax.Array, q: int = 2, half_life: float = 252.0) -> jax.Array:
    """Single-window Newey-West covariance of (T, K) factor returns.

    Direct (non-scan) evaluation used for testing and one-off calls.
    """
    T, K = ret.shape
    dtype = ret.dtype
    w = 0.5 ** (jnp.arange(T - 1, -1, -1, dtype=dtype) / half_life)
    w = w / jnp.sum(w)
    mu = w @ ret
    d = ret - mu
    V = jnp.einsum("t,ti,tj->ij", w, d, d)
    for lag in range(1, q + 1):
        G = jnp.einsum("t,ti,tj->ij", w[lag:], d[: T - lag], d[lag:])
        V = V + (1.0 - lag / (1.0 + q)) * (G + G.T)
    return V


def nw_init_carry(K: int, q: int, dtype) -> tuple:
    """The scan state of :func:`newey_west_expanding_resume` before any date:
    ``(t, S, A, Z, Ps, hs, gs, Slags, xlags)`` at t = 0.  This tuple IS the
    resumable checkpoint of the expanding estimator — every sum it holds is
    exact, so resuming from it reproduces the uninterrupted scan bitwise.
    """
    zK = jnp.zeros((K,), dtype)
    zKK = jnp.zeros((K, K), dtype)
    return (
        jnp.asarray(0, jnp.int32),
        zK,
        zKK,
        jnp.asarray(0.0, dtype),
        tuple(zKK for _ in range(q)),
        tuple(zK for _ in range(q)),
        tuple(jnp.asarray(0.0, dtype) for _ in range(q)),
        tuple(zK for _ in range(q)),
        tuple(zK for _ in range(q)),
    )


@highest_matmul_precision
def newey_west_expanding(
    ret: jax.Array, q: int = 2, half_life: float = 252.0,
    min_valid: int | None = None, method: str = "scan",
):
    """All expanding-window Newey-West covariances in one pass.

    Returns ``(covs, valid)`` where ``covs[t]`` equals
    ``newey_west(ret[:t+1], q, half_life)`` and ``valid[t]`` is False when
    t+1 <= q or t+1 <= K (the reference's exception path).

    Derivation: with lam = 0.5**(1/tau) and unnormalized sums
        S_t   = sum_{i<t} lam^(t-1-i) x_i
        A_t   = sum_{i<t} lam^(t-1-i) x_i x_i'
        P^l_t = sum_{j=l}^{t-1} lam^(t-1-j) x_{j-l} x_j'
        Z_t   = sum_{i<t} lam^(t-1-i)
    the normalized, demeaned pieces are
        mu    = S/Z
        Gamma_0 = A/Z - mu mu'
        Gamma_l = (P^l - b^l mu' - mu a^l' + z^l mu mu') / Z
    where a^l = S - (head l terms), b^l = S_{t-l} (the lag-shifted first
    moment), z^l = Z - (head l terms); heads follow their own EWMA recursions.

    ``method``: "scan" runs the O(T) serial lax.scan (the single-chip
    default); "associative" evaluates the same EWMA recurrences with
    ``lax.associative_scan`` — O(log T) depth, the date axis stays sharded
    (the framework's sequence-parallel formulation, see
    :func:`newey_west_expanding_associative`).
    """
    if method == "associative":
        return newey_west_expanding_associative(ret, q, half_life, min_valid)
    if method != "scan":
        raise ValueError(f"method must be 'scan' or 'associative', got {method!r}")
    covs, valid, _ = newey_west_expanding_resume(ret, q, half_life, min_valid)
    return covs, valid


@highest_matmul_precision
def newey_west_expanding_resume(
    ret: jax.Array, q: int = 2, half_life: float = 252.0,
    min_valid: int | None = None, carry: tuple | None = None,
    dyn_length: jax.Array | None = None,
    skip_mask: jax.Array | None = None,
):
    """The "scan" method of :func:`newey_west_expanding`, checkpointable.

    Returns ``(covs, valid, carry_out)``.  ``carry`` resumes the expanding
    scan from a previous call's ``carry_out`` (default: the t = 0 state,
    :func:`nw_init_carry`): because the carry holds the exact EWMA sums of
    the recursion, running dates ``[0:T0]`` and then ``[T0:T]`` from the
    returned carry produces bitwise the same covariances as one
    uninterrupted pass — the incremental daily-update path of
    :meth:`mfm_tpu.models.risk_model.RiskModel.update`.  ``q``,
    ``half_life`` and ``min_valid`` must match across resumed calls (the
    carry is only meaningful under the same recursion constants).

    ``dyn_length`` (a traced s32 scalar equal to T) makes the loop bound
    dynamic: XLA's while-loop simplifier inlines trip-count-1 loops into
    the surrounding program, whose different fusion shifts the step math by
    an ulp — a dynamic bound keeps the body its own computation at any T,
    so a one-date update executes bitwise the same step as a long history.

    ``skip_mask`` (a (T,) bool, quarantine verdicts from serve/guard.py)
    excises dates from the recursion: at a masked date the carry passes
    through UNCHANGED — no decay, no ``t`` increment — selected per-leaf
    after the step, so the carry after (good, BAD, good) equals the carry
    after (good, good) bitwise and a NaN-poisoned date cannot reach the
    sums (``jnp.where`` never propagates NaN from the unselected branch).
    The masked date's stacked output V is the discarded candidate (its
    ``valid`` flag is forced False); callers serve a degraded value there.
    """
    T, K = ret.shape
    dtype = ret.dtype
    lam = jnp.asarray(0.5, dtype) ** (1.0 / half_life)
    kmin = K if min_valid is None else min_valid

    def step(carry, xt):
        (t, S, A, Z, Ps, hs, gs, Slags, xlags) = carry
        t = t + 1  # window length after including xt
        Snew = lam * S + xt
        Anew = lam * A + jnp.outer(xt, xt)
        Znew = lam * Z + 1.0
        Ps_new, hs_new, gs_new = [], [], []
        for li, lag in enumerate(range(1, q + 1)):
            xlag = xlags[lag - 1]  # x_{t-1-lag} (zero until it exists)
            Ps_new.append(lam * Ps[li] + jnp.outer(xlag, xt))
            hs_new.append(lam * hs[li] + jnp.where(t <= lag, 1.0, 0.0) * xt)
            gs_new.append(lam * gs[li] + jnp.where(t <= lag, 1.0, 0.0))

        mu = Snew / Znew
        V = Anew / Znew - jnp.outer(mu, mu)
        for li, lag in enumerate(range(1, q + 1)):
            a_l = Snew - hs_new[li]
            b_l = Slags[lag - 1]
            z_l = Znew - gs_new[li]
            G = (
                Ps_new[li]
                - jnp.outer(b_l, mu)
                - jnp.outer(mu, a_l)
                + z_l * jnp.outer(mu, mu)
            ) / Znew
            V = V + (1.0 - lag / (1.0 + q)) * (G + G.T)

        valid = (t > q) & (t > kmin)
        # shift lag registers: Slags[i] must hold S_{t-i-1+1}=S_{t-i} next step
        Slags_new = (Snew,) + Slags[:-1] if q > 0 else Slags
        xlags_new = (xt,) + xlags[:-1] if q > 0 else xlags
        new_carry = (t, Snew, Anew, Znew, tuple(Ps_new), tuple(hs_new),
                     tuple(gs_new), Slags_new, xlags_new)
        return new_carry, (V, valid)

    init = nw_init_carry(K, q, dtype) if carry is None else carry
    # the serial recursion gains nothing from a sharded date axis (use the
    # associative method for that); pin its input and stacked outputs
    # replicated per the layout doctrine
    from mfm_tpu.parallel.mesh import replicate_under_mesh

    ret_r = replicate_under_mesh(ret)
    skip_r = None if skip_mask is None else replicate_under_mesh(skip_mask)

    # s32-indexed fori_loop rather than lax.scan: scan's stacked-output
    # counter canonicalizes to s64 under x64 and trips the spmd partitioner's
    # s32 offset math when the stacking axis ends up sharded (see
    # vol_regime.py); the step math is unchanged, so V_t stays bitwise equal
    def body(i, state):
        carry, covs_acc, valid_acc = state
        xt = jax.lax.dynamic_index_in_dim(ret_r, i, 0, keepdims=False)
        new_carry, (V, v_ok) = step(carry, xt)
        if skip_r is not None:
            sk = jax.lax.dynamic_index_in_dim(skip_r, i, 0, keepdims=False)
            new_carry = jax.tree_util.tree_map(
                lambda old, new: jnp.where(sk, old, new), carry, new_carry)
            v_ok = v_ok & ~sk
        covs_acc = jax.lax.dynamic_update_index_in_dim(covs_acc, V, i, 0)
        valid_acc = jax.lax.dynamic_update_index_in_dim(valid_acc, v_ok, i, 0)
        return new_carry, covs_acc, valid_acc

    hi = jnp.int32(T) if dyn_length is None else dyn_length.astype(jnp.int32)
    carry_out, covs, valid = jax.lax.fori_loop(
        jnp.int32(0), hi, body,
        (init, jnp.zeros((T, K, K), dtype), jnp.zeros((T,), bool)),
    )
    covs, valid = replicate_under_mesh((covs, valid))
    return covs, valid, replicate_under_mesh(carry_out)


def newey_west_expanding_associative(
    ret: jax.Array, q: int = 2, half_life: float = 252.0,
    min_valid: int | None = None,
):
    """Expanding Newey-West via ``lax.associative_scan`` — the
    sequence-parallel formulation.

    Every sum in the derivation above is a first-order linear recurrence
    ``s_t = lam * s_{t-1} + u_t`` with a constant coefficient, so the whole
    state (Z, S, A, P^l, heads) packs into one vector per date and the prefix
    family evaluates with an associative combine
    ``(a1, b1) . (a2, b2) = (a1*a2, a2*b1 + b2)`` in O(log T) depth.  Under
    pjit with the date axis sharded this parallelizes across devices (the
    serial lax.scan cannot); it is the framework's analogue of
    sequence/context parallelism for the long-time-axis workloads
    (SURVEY.md §5 "long-context").

    The lag-shifted first moments b^l = S_{t-l} come from shifting the
    scanned S outputs — no lagged state is carried.
    """
    T, K = ret.shape
    dtype = ret.dtype
    lam = jnp.asarray(0.5, dtype) ** (1.0 / half_life)
    kmin = K if min_valid is None else min_valid
    # s32, not the x64-default s64: the spmd partitioner's shard-offset math
    # around a sharded date axis is s32, and mixed-width compares trip the
    # HLO verifier — same hardening as the serial scans' fori_loop counters
    tgrid = jnp.arange(1, T + 1, dtype=jnp.int32)

    def shift_rows(x, l):
        if l == 0:
            return x
        pad = jnp.zeros((l,) + x.shape[1:], dtype)
        return jnp.concatenate([pad, x[:-l]], axis=0)

    # per-date inject vectors for each recurrence
    injects = [
        jnp.ones((T, 1), dtype),                                     # Z
        ret,                                                         # S
        jnp.einsum("ti,tj->tij", ret, ret).reshape(T, K * K),        # A
    ]
    for lag in range(1, q + 1):
        xlag = shift_rows(ret, lag)                                  # x_{t-1-l}
        injects.append(jnp.einsum("ti,tj->tij", xlag, ret).reshape(T, K * K))
    for lag in range(1, q + 1):
        head_on = (tgrid <= lag).astype(dtype)[:, None]
        injects.append(head_on * ret)                                # h^l
        injects.append(head_on)                                      # g^l
    U = jnp.concatenate(injects, axis=1)                             # (T, D)

    a0 = jnp.full((T, 1), lam, dtype)

    def combine(e1, e2):
        a1, b1 = e1
        a2, b2 = e2
        return a1 * a2, a2 * b1 + b2

    _, states = jax.lax.associative_scan(combine, (a0, U), axis=0)

    # unpack per-date states
    off = 0
    def take(n):
        nonlocal off
        out = states[:, off:off + n]
        off += n
        return out

    Z = take(1)[:, 0]
    S = take(K)
    A = take(K * K).reshape(T, K, K)
    Ps = [take(K * K).reshape(T, K, K) for _ in range(q)]
    heads = [(take(K), take(1)[:, 0]) for _ in range(q)]

    mu = S / Z[:, None]
    V = A / Z[:, None, None] - jnp.einsum("ti,tj->tij", mu, mu)
    for li, lag in enumerate(range(1, q + 1)):
        h_l, g_l = heads[li]
        a_l = S - h_l
        b_l = shift_rows(S, lag)          # S_{t-l}
        z_l = Z - g_l
        G = (
            Ps[li]
            - jnp.einsum("ti,tj->tij", b_l, mu)
            - jnp.einsum("ti,tj->tij", mu, a_l)
            + z_l[:, None, None] * jnp.einsum("ti,tj->tij", mu, mu)
        ) / Z[:, None, None]
        V = V + (1.0 - lag / (1.0 + q)) * (G + jnp.swapaxes(G, -1, -2))

    valid = (tgrid > q) & (tgrid > kmin)
    return V, valid
