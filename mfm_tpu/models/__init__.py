"""The risk model: cross-sectional regression driver + covariance stack
(Newey-West, eigenfactor risk adjustment, volatility-regime adjustment,
bias statistics, Bayesian shrinkage)."""

from mfm_tpu.models.newey_west import (
    newey_west,
    newey_west_expanding,
    newey_west_expanding_resume,
)
from mfm_tpu.models.eigen import eigen_risk_adjust, eigen_risk_adjust_by_time
from mfm_tpu.models.vol_regime import (
    vol_regime_adjust_by_time,
    vol_regime_adjust_resume,
)
from mfm_tpu.models.bias import eigenfactor_bias_stat, bayes_shrink
from mfm_tpu.models.specific import ewma_specific_vol, specific_risk_by_time
from mfm_tpu.models.risk_model import (
    RiskModel,
    RiskModelOutputs,
    RiskModelState,
)

__all__ = [
    "newey_west",
    "newey_west_expanding",
    "newey_west_expanding_resume",
    "vol_regime_adjust_resume",
    "eigen_risk_adjust",
    "eigen_risk_adjust_by_time",
    "vol_regime_adjust_by_time",
    "eigenfactor_bias_stat",
    "bayes_shrink",
    "ewma_specific_vol",
    "specific_risk_by_time",
    "RiskModel",
    "RiskModelOutputs",
    "RiskModelState",
]
