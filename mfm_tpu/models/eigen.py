"""Eigenfactor risk adjustment (USE4), batched over dates.

Contract (``Barra-master/mfm/utils.py:55-92``): eigendecompose the factor
covariance F0 = U0 D0 U0'; simulate M sets of factor returns with the
eigen-variances, re-estimate and re-decompose each simulated covariance,
measure the per-eigenvalue bias v, scale ``v <- scale_coef*(v-1)+1``, and
rebuild ``F0_hat = U0 diag(v^2 * D0) U0'``.

TPU re-design (two structural wins over the reference's loop):

1. ``np.linalg.eig`` on a symmetric PSD matrix becomes ``jnp.linalg.eigh``
   (TPU has no general nonsymmetric eig; eigh is the correct reformulation).
2. The reference re-seeds ``np.random.seed(m+1)`` *identically for every
   date* (``utils.py:71-74``), so the M standard-normal draw matrices — and
   therefore their sample covariances C_m — are the same for all dates.  We
   precompute C_m = cov(N_m) once (M tiny KxK matrices) and per date form the
   simulated covariance as ``F_m = U0 diag(s) C_m diag(s) U0'`` with
   s = sqrt(D0), which equals ``np.cov(U0 @ (s * N_m))`` exactly.  The
   T-dates x M-sims Monte-Carlo loop (139k simulations of a (K, T) normal
   panel in the reference) collapses to M precomputed covariances plus
   batched KxK matmuls/eighs, vmapped over (dates, sims) and sharded over the
   date mesh axis.

Bitwise replication of the reference's draws is impossible by construction
(np.random's MT19937 + SVD-based multivariate_normal); golden tests inject
the draws, production uses ``jax.random`` (SURVEY.md §7.3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def simulated_eigen_covs(
    key: jax.Array, n_factors: int, sim_length: int, n_sims: int, dtype=jnp.float32
) -> jax.Array:
    """Sample covariances C_m of M standard-normal (K, T_sim) draws.

    Matches ``np.cov`` semantics: demean each row over the T_sim samples,
    normalize by (T_sim - 1).  Shape (M, K, K).
    """
    draws = jax.random.normal(key, (n_sims, n_factors, sim_length), dtype=dtype)
    d = draws - jnp.mean(draws, axis=-1, keepdims=True)
    return jnp.einsum("mkt,mlt->mkl", d, d) / (sim_length - 1)


def eigen_risk_adjust(
    cov: jax.Array,
    sim_covs: jax.Array,
    scale_coef: float = 1.4,
) -> jax.Array:
    """Adjust one KxK covariance given precomputed simulation covariances.

    ``sim_covs``: (M, K, K) sample covariances of standard-normal draws (unit
    variance per factor) — the eigen-variance scaling is applied here.
    """
    D0, U0 = jnp.linalg.eigh(cov)
    s = jnp.sqrt(jnp.maximum(D0, 0.0))
    B = U0 * s[None, :]  # (K, K): maps unit draws to simulated factor returns

    def one_sim(Cm):
        Fm = B @ Cm @ B.T  # == np.cov of simulated factor returns
        Dm, Um = jnp.linalg.eigh(Fm)
        Dm_hat = jnp.einsum("ki,kl,li->i", Um, cov, Um)  # diag(Um' F0 Um)
        return Dm_hat / Dm

    v2 = jnp.mean(jax.vmap(one_sim)(sim_covs), axis=0)  # (K,)
    v = jnp.sqrt(v2)
    v = scale_coef * (v - 1.0) + 1.0
    return (U0 * (v**2 * D0)[None, :]) @ U0.T


def eigen_risk_adjust_by_time(
    covs: jax.Array,
    valid: jax.Array,
    sim_covs: jax.Array,
    scale_coef: float = 1.4,
):
    """vmap of :func:`eigen_risk_adjust` over the date axis.

    ``covs``: (T, K, K); ``valid``: (T,) — dates whose Newey-West estimate was
    invalid stay invalid, and dates with a negative eigenvalue are marked
    invalid (the reference raises and stores an empty DataFrame,
    ``utils.py:67-68``, ``MFM.py:118-121``).
    Returns (adjusted covs (T, K, K) with NaN at invalid dates, valid (T,)).
    """
    dtype = covs.dtype
    eye = jnp.eye(covs.shape[-1], dtype=dtype)
    safe = jnp.where(valid[:, None, None], covs, eye)
    psd = jax.vmap(lambda c: jnp.linalg.eigvalsh(c)[0] >= 0)(safe)
    out = jax.vmap(lambda c: eigen_risk_adjust(c, sim_covs, scale_coef))(safe)
    ok = valid & psd
    out = jnp.where(ok[:, None, None], out, jnp.nan)
    return out, ok
