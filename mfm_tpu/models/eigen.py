"""Eigenfactor risk adjustment (USE4), batched over dates.

Contract (``Barra-master/mfm/utils.py:55-92``): eigendecompose the factor
covariance F0 = U0 D0 U0'; simulate M sets of factor returns with the
eigen-variances, re-estimate and re-decompose each simulated covariance,
measure the per-eigenvalue bias v, scale ``v <- scale_coef*(v-1)+1``, and
rebuild ``F0_hat = U0 diag(v^2 * D0) U0'``.

TPU re-design (four structural wins over the reference's loop):

1. ``np.linalg.eig`` on a symmetric PSD matrix becomes a *batched symmetric*
   eigh — and on TPU the VMEM-resident Pallas Jacobi kernel
   (:mod:`mfm_tpu.ops.eigh_pallas`), ~8x XLA's QDWH at this size.
2. The reference re-seeds ``np.random.seed(m+1)`` *identically for every
   date* (``utils.py:71-74``), so the M standard-normal draw matrices — and
   therefore their sample covariances C_m — are the same for all dates.  We
   precompute C_m = cov(N_m) once (M tiny KxK matrices); the simulated
   covariance of date t, sim m is ``F_m = B C_m B'`` with B = U0 sqrt(D0),
   which equals ``np.cov`` of the simulated returns exactly.
3. The whole Monte-Carlo runs in F0's **eigenbasis** — no KxK matmuls at
   all.  With s = sqrt(D0) and G_m = diag(s) C_m diag(s) (an *elementwise*
   scaling of C_m), F_m = U0 G_m U0'; if G_m = W L W' then F_m = (U0 W) L
   (U0 W)', so eigh(G_m) yields the simulated eigenvalues D_m = L directly,
   and the re-estimated true variances of the reference
   (``D_hat = diag(U_m' F0 U_m)``, ``utils.py:83``) collapse to
   ``D_hat_i = sum_k W_ki^2 D0_k``.  This replaces four O(T·M·K^3) matmul
   passes (forming F and projecting F0) with O(T·M·K^2) elementwise work;
   only the eighs remain.
4. All (T, M) decompositions run as ONE flat batch — no per-date dispatch.

Bitwise replication of the reference's draws is impossible by construction
(np.random's MT19937 + SVD-based multivariate_normal); golden tests inject
the draws, production uses ``jax.random`` (SURVEY.md §7.3).  Eigenvector
signs are canonicalized (largest component positive) so results are
bit-stable across backends/kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.ops.eigh import (
    _sweeps_for,
    batched_eigh,
    batched_eigh_weighted_diag,
)

from mfm_tpu.utils.prec import highest_matmul_precision


def _near_diagonal_sims(n_factors: int, sim_length: int | None) -> bool:
    """Whether G = diag(s) C_m diag(s) is near-diagonal: C_m = I +
    O(1/sqrt(sim_length)), so the premise needs sim_length >> K (4*K is the
    conservative cutoff).  ``sim_length=None`` (caller-injected sim_covs of
    unknown provenance) counts as not-near — the safe, sorted path."""
    return sim_length is not None and sim_length >= 4 * n_factors


def sim_sweeps_for(n_factors: int, dtype, sim_length: int) -> int:
    """Jacobi sweep cap for the simulated eighs, derived from K.

    The near-diagonal G matrices of this stage (see
    :func:`_near_diagonal_sims`) converge ~2 sweeps before the solver's
    general-matrix default (measured bitwise-equal at K=42, sim_length=200
    with 5 = default-2 sweeps; deviation at default-3).  With many more
    draws the off-diagonal mass shrinks as ~sqrt(1/sim_length) and one more
    sweep can go: at K=42, sim_length=1390, 4 = default-3 sweeps deviates
    only 1.5e-6 relative in the final adjusted covariance (measured
    2026-07-29; 3 sweeps deviates 5e-5, past the 1e-5 contract) at ~17%
    less stage wall-clock.  The deep tier engages at 32*K — just inside the
    measured point (33*K), not extrapolated toward the 4*K boundary where
    the error's steep sweep-sensitivity is unquantified.  Scaling with
    :func:`mfm_tpu.ops.eigh._sweeps_for` rather than pinning keeps those
    margins at larger K, where the default itself grows.  When the
    near-diagonality premise fails, the solver default is returned.
    """
    full = _sweeps_for(n_factors, dtype)
    if not _near_diagonal_sims(n_factors, sim_length):
        return full
    if sim_length >= 32 * n_factors:
        return max(4, full - 3)
    return max(5, full - 2)


@highest_matmul_precision
def simulated_eigen_covs(
    key: jax.Array, n_factors: int, sim_length: int, n_sims: int, dtype=jnp.float32
) -> jax.Array:
    """Sample covariances C_m of M standard-normal (K, T_sim) draws.

    Matches ``np.cov`` semantics: demean each row over the T_sim samples,
    normalize by (T_sim - 1).  Shape (M, K, K).
    """
    draws = jax.random.normal(key, (n_sims, n_factors, sim_length), dtype=dtype)
    d = draws - jnp.mean(draws, axis=-1, keepdims=True)
    return jnp.einsum("mkt,mlt->mkl", d, d) / (sim_length - 1)


# working-set accounting for the chunked Monte-Carlo: the G tensor itself
# plus XLA's eigh scratch (QDWH workspace is a few copies of the batch)
_CHUNK_WORKSPACE_FACTOR = 4
# host backends get a hard transient cap: LAPACK streams through chunks at
# identical total FLOPs, and a bounded working set keeps huge histories from
# thrashing the page cache (tools/eigh_cpu_ab.py for the solver A/B)
_CHUNK_HOST_BUDGET_BYTES = 256 * 1024 * 1024


def _memory_headroom_bytes(backend: str) -> int | None:
    """Free memory on the compute device (HBM stats) or host (MemAvailable)."""
    if backend in ("tpu", "axon", "gpu", "cuda", "rocm"):
        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"]) - int(stats.get("bytes_in_use", 0))
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return None


def auto_eigen_chunk(T: int, n_sims: int, n_factors: int, itemsize: int = 4,
                     backend: str | None = None) -> int | None:
    """Resolve ``eigen_chunk="auto"``: a date-chunk size for the eigen
    Monte-Carlo, or None to run the full (T, M) batch in one shot.

    The streamed transient is O(chunk * M * K^2); this sizes chunk from the
    backend's live memory headroom (device HBM stats on accelerators, host
    MemAvailable otherwise), keeping the full batch whenever it fits the
    budget.  Resolved at trace time — the decision is baked into the
    compiled program, like every other shape decision.
    """
    backend = backend or jax.default_backend()
    per_date = n_sims * n_factors * n_factors * itemsize * _CHUNK_WORKSPACE_FACTOR
    head = _memory_headroom_bytes(backend)
    if backend in ("tpu", "axon", "gpu", "cuda", "rocm"):
        # accelerator HBM: fit-or-chunk against half the free device memory
        budget = head // 2 if head else 4 * 1024 ** 3
    else:
        budget = (min(head // 4, _CHUNK_HOST_BUDGET_BYTES) if head
                  else _CHUNK_HOST_BUDGET_BYTES)
    if T * per_date <= budget:
        return None
    return int(max(1, min(T, budget // per_date)))


@highest_matmul_precision
def eigen_risk_adjust_by_time(
    covs: jax.Array,
    valid: jax.Array,
    sim_covs: jax.Array,
    scale_coef: float = 1.4,
    prefer_pallas: bool | None = None,
    sim_sweeps: int | None = None,
    sim_length: int | None = None,
    chunk: int | None = None,
    batch_hint: int | None = None,
):
    """Batched adjustment over the date axis.

    ``covs``: (T, K, K); ``valid``: (T,) — dates whose Newey-West estimate was
    invalid stay invalid, and dates with a negative eigenvalue are marked
    invalid (the reference raises and stores an empty DataFrame,
    ``utils.py:67-68``, ``MFM.py:118-121``).
    Returns (adjusted covs (T, K, K) with NaN at invalid dates, valid (T,)).

    ``sim_sweeps`` caps the Jacobi sweep count for the (T, M) *simulated*
    decompositions only (the dominant cost; the T-sized F0 eigh always runs
    at full precision).  Converged rotations are exact no-ops (apq below
    threshold gives c=1, s=0), so once convergence completes, extra sweeps
    change nothing: 5 sweeps is bitwise-equal to the solver-default 7 on the
    CSI300-class Wishart matrices of this stage at ~30% less wall-clock
    (measured; 4 sweeps deviates ~8e-3 in the kernel's off-diagonal
    residual, ~5e-4 in the final adjusted covariance).

    ``sim_length`` is the number of draws behind ``sim_covs``; it sizes the
    auto sweep cap (see :func:`sim_sweeps_for`).  The bias pairing itself is
    **rank-based and order-invariant**: ``Dm_hat`` is computed in whatever
    slot order the solver emits, then the scalar (Dm, Dm_hat) pairs are
    sorted by Dm, so ascending sim eigenvalues always pair with ascending
    D0 — identical semantics on the unsorted Pallas fast path and the
    always-ascending XLA/LAPACK fallback, even when sampling noise reorders
    near-degenerate eigenvalues (round-1 advisor finding).  The eigenvector
    batch itself is never sorted (that would be a full HBM round trip over
    (T*M, K, K)); only two (T, M, K) value tensors are.

    ``chunk`` streams the Monte-Carlo over the date axis: the (T, M, K, K)
    G transient — by far the largest allocation of the whole pipeline at
    production scale — is never materialized; instead ``lax.map`` runs the
    sim eighs over (chunk, M, K, K) slabs and accumulates only the (T, K)
    bias ratios.  ``None`` (or chunk >= T) keeps the single full batch.
    The per-date math is identical either way (same op sequence per slab,
    and the solver-dispatch batch is pinned to the full T*M batch size
    regardless of chunking), so chunked == unchunked exactly on the XLA
    path.  Use :func:`auto_eigen_chunk` to size it from live memory.

    ``batch_hint`` overrides that dispatch pin (default T*M): the
    incremental update path passes the INIT-time T*M so a one-date slab
    dispatches its sim eighs exactly like the full history it extends —
    slab-invariant the same way the chunk stream is chunk-invariant.
    """
    dtype = covs.dtype
    T = covs.shape[0]
    K = covs.shape[-1]
    M = sim_covs.shape[0]
    if batch_hint is None:
        batch_hint = T * M
    if sim_sweeps is None and sim_length is not None:
        sim_sweeps = sim_sweeps_for(K, dtype, sim_length)
    eye = jnp.eye(K, dtype=dtype)
    safe = jnp.where(valid[:, None, None], covs, eye)

    D0, U0 = batched_eigh(safe, prefer_pallas=prefer_pallas)  # (T,K), (T,K,K)
    psd = D0[..., 0] >= 0  # ascending order -> min eigenvalue first
    s = jnp.sqrt(jnp.maximum(D0, 0.0))

    # simulated covariances in F0's eigenbasis: G = diag(s) C_m diag(s), an
    # elementwise scaling (module docstring, point 3).  The sim eighs return
    # only (eigenvalues, D0-weighted squared-eigenvector diagonals): the
    # Pallas path reduces W against D0 inside the kernel, so the (T*M, K, K)
    # eigenvector batch never round-trips HBM and no separate einsum pass
    # reads it back; pairing is restored below by sorting the scalar
    # (Dm, Dm_hat) pairs.  Signs square away in W*W.
    # D_hat = diag(U_m' F0 U_m) with U_m = U0 W  ->  sum_k W_ki^2 D0_k
    def _sim_bias_v2(s_c, d0_c):
        """(c, K) sqrt-eigvals + eigvals -> (c, K) mean bias ratios v^2.

        The whole per-date Monte-Carlo for a slab of dates — the one body
        both the full-batch and the chunked path run, so their per-date op
        sequence (and hence their result) is identical by construction.
        """
        G = s_c[:, None, :, None] * sim_covs[None] * s_c[:, None, None, :]
        Dm, Dm_hat = batched_eigh_weighted_diag(
            G, d0_c[:, None, :], prefer_pallas=prefer_pallas,
            sweeps=sim_sweeps, batch_hint=batch_hint)
        # rank pairing, order-invariant across backends: i-th smallest sim
        # eigenvalue pairs with the i-th smallest D0 (D0 is already
        # ascending).  One variadic key-value sort: ~3x cheaper on TPU than
        # argsort + two take_along_axis gathers over the same (c, M, K)
        # tensors (measured 0.15 s at CSI300 scale); is_stable matches
        # jnp.argsort's tie order.
        Dm, Dm_hat = jax.lax.sort((Dm, Dm_hat), dimension=-1, num_keys=1,
                                  is_stable=True)
        # A numerically-zero sim eigenvalue (rank-deficient covariance:
        # D0_k = 0 zeroes G's k-th row/column, and LAPACK/Jacobi may emit 0
        # or -eps there) would make the ratio 0/0 or a huge spurious value —
        # substitute ratio 1 wherever |Dm| is below eps * lambda_max.  The
        # substituted value only shifts v in directions the rebuild then
        # scales by D0 ~ 0.
        eps = jnp.asarray(jnp.finfo(dtype).eps, dtype)
        thr = eps * jnp.max(jnp.abs(Dm), axis=-1, keepdims=True)
        degenerate = jnp.abs(Dm) <= thr
        ratio = jnp.where(degenerate, 1.0,
                          Dm_hat / jnp.where(degenerate, 1.0, Dm))
        # clamp: tiny-negative Dm just above thr could still push the mean
        # negative, and sqrt of a negative poisons the whole date with NaN
        return jnp.maximum(jnp.mean(ratio, axis=1), 0.0)  # (c, K)

    if chunk is None or chunk >= T:
        v2 = _sim_bias_v2(s, D0)  # (T, K)
    else:
        # stream: pad T up to a chunk multiple (padded dates carry s = 0,
        # whose G is all-zero -> every ratio hits the degenerate guard ->
        # v2 = 1; cropped below regardless), then map the slab body.  The
        # (T, K)-sized map operands/outputs are pinned replicated under any
        # ambient mesh — the serial stream gains nothing from sharding and
        # scan-stacked sharded outputs trip the s64/s32 partitioner bug
        # (see vol_regime.py).
        from mfm_tpu.parallel.mesh import replicate_under_mesh

        pad = (-T) % chunk
        s_p = jnp.pad(s, ((0, pad), (0, 0)))
        d0_p = jnp.pad(D0, ((0, pad), (0, 0)))
        n_chunks = (T + pad) // chunk
        s_p, d0_p = replicate_under_mesh((
            s_p.reshape(n_chunks, chunk, K), d0_p.reshape(n_chunks, chunk, K)))
        v2 = jax.lax.map(lambda args: _sim_bias_v2(*args), (s_p, d0_p))
        v2 = replicate_under_mesh(v2.reshape(n_chunks * chunk, K)[:T])

    v = scale_coef * (jnp.sqrt(v2) - 1.0) + 1.0

    out = jnp.einsum("tik,tk,tjk->tij", U0, v * v * D0, U0)
    ok = valid & psd
    out = jnp.where(ok[:, None, None], out, jnp.nan)
    return out, ok


def eigen_risk_adjust(
    cov: jax.Array,
    sim_covs: jax.Array,
    scale_coef: float = 1.4,
    prefer_pallas: bool | None = None,
) -> jax.Array:
    """Adjust one KxK covariance (the reference's ``eigen_risk_adj``,
    ``utils.py:55-92``)."""
    out, _ = eigen_risk_adjust_by_time(
        cov[None], jnp.ones((1,), bool), sim_covs, scale_coef,
        prefer_pallas=prefer_pallas,
    )
    return out[0]
