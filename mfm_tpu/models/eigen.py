"""Eigenfactor risk adjustment (USE4), batched over dates.

Contract (``Barra-master/mfm/utils.py:55-92``): eigendecompose the factor
covariance F0 = U0 D0 U0'; simulate M sets of factor returns with the
eigen-variances, re-estimate and re-decompose each simulated covariance,
measure the per-eigenvalue bias v, scale ``v <- scale_coef*(v-1)+1``, and
rebuild ``F0_hat = U0 diag(v^2 * D0) U0'``.

TPU re-design (three structural wins over the reference's loop):

1. ``np.linalg.eig`` on a symmetric PSD matrix becomes a *batched symmetric*
   eigh — and on TPU the VMEM-resident Pallas Jacobi kernel
   (:mod:`mfm_tpu.ops.eigh_pallas`), ~8x XLA's QDWH at this size.
2. The reference re-seeds ``np.random.seed(m+1)`` *identically for every
   date* (``utils.py:71-74``), so the M standard-normal draw matrices — and
   therefore their sample covariances C_m — are the same for all dates.  We
   precompute C_m = cov(N_m) once (M tiny KxK matrices) and per date form the
   simulated covariance as ``F_m = B C_m B'`` with B = U0 sqrt(D0), which
   equals ``np.cov`` of the simulated returns exactly.  The T x M Monte-Carlo
   loop (139k simulations of a (K, T) normal panel in the reference)
   collapses to M precomputed covariances plus batched KxK einsums/eighs.
3. All (T, M) decompositions run as ONE flat batch — no per-date dispatch.

Bitwise replication of the reference's draws is impossible by construction
(np.random's MT19937 + SVD-based multivariate_normal); golden tests inject
the draws, production uses ``jax.random`` (SURVEY.md §7.3).  Eigenvector
signs are canonicalized (largest component positive) so results are
bit-stable across backends/kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.ops.eigh import batched_eigh


def simulated_eigen_covs(
    key: jax.Array, n_factors: int, sim_length: int, n_sims: int, dtype=jnp.float32
) -> jax.Array:
    """Sample covariances C_m of M standard-normal (K, T_sim) draws.

    Matches ``np.cov`` semantics: demean each row over the T_sim samples,
    normalize by (T_sim - 1).  Shape (M, K, K).
    """
    draws = jax.random.normal(key, (n_sims, n_factors, sim_length), dtype=dtype)
    d = draws - jnp.mean(draws, axis=-1, keepdims=True)
    return jnp.einsum("mkt,mlt->mkl", d, d) / (sim_length - 1)


def eigen_risk_adjust_by_time(
    covs: jax.Array,
    valid: jax.Array,
    sim_covs: jax.Array,
    scale_coef: float = 1.4,
    prefer_pallas: bool | None = None,
):
    """Batched adjustment over the date axis.

    ``covs``: (T, K, K); ``valid``: (T,) — dates whose Newey-West estimate was
    invalid stay invalid, and dates with a negative eigenvalue are marked
    invalid (the reference raises and stores an empty DataFrame,
    ``utils.py:67-68``, ``MFM.py:118-121``).
    Returns (adjusted covs (T, K, K) with NaN at invalid dates, valid (T,)).
    """
    dtype = covs.dtype
    K = covs.shape[-1]
    eye = jnp.eye(K, dtype=dtype)
    safe = jnp.where(valid[:, None, None], covs, eye)

    D0, U0 = batched_eigh(safe, prefer_pallas=prefer_pallas)  # (T,K), (T,K,K)
    psd = D0[..., 0] >= 0  # ascending order -> min eigenvalue first
    s = jnp.sqrt(jnp.maximum(D0, 0.0))
    B = U0 * s[:, None, :]  # (T, K, K): maps unit draws to factor returns

    # simulated covariances for every (date, sim): F = B C_m B'.  The bias
    # ratios below are invariant to eigenvalue order and eigenvector signs,
    # so the sim decompositions skip sorting/canonicalization (saves a full
    # HBM pass over the (T*M, K, K) eigenvector batch)
    F = jnp.einsum("tik,mkl,tjl->tmij", B, sim_covs, B)
    Dm, Um = batched_eigh(F, prefer_pallas=prefer_pallas,
                          canonical_signs=False, sort=False)
    Dm_hat = jnp.einsum("tmki,tkl,tmli->tmi", Um, safe, Um)
    v2 = jnp.mean(Dm_hat / Dm, axis=1)  # (T, K)
    v = scale_coef * (jnp.sqrt(v2) - 1.0) + 1.0

    out = jnp.einsum("tik,tk,tjk->tij", U0, v * v * D0, U0)
    ok = valid & psd
    out = jnp.where(ok[:, None, None], out, jnp.nan)
    return out, ok


def eigen_risk_adjust(
    cov: jax.Array,
    sim_covs: jax.Array,
    scale_coef: float = 1.4,
    prefer_pallas: bool | None = None,
) -> jax.Array:
    """Adjust one KxK covariance (the reference's ``eigen_risk_adj``,
    ``utils.py:55-92``)."""
    out, _ = eigen_risk_adjust_by_time(
        cov[None], jnp.ones((1,), bool), sim_covs, scale_coef,
        prefer_pallas=prefer_pallas,
    )
    return out[0]
