"""Eigenfactor risk adjustment (USE4), batched over dates.

Contract (``Barra-master/mfm/utils.py:55-92``): eigendecompose the factor
covariance F0 = U0 D0 U0'; simulate M sets of factor returns with the
eigen-variances, re-estimate and re-decompose each simulated covariance,
measure the per-eigenvalue bias v, scale ``v <- scale_coef*(v-1)+1``, and
rebuild ``F0_hat = U0 diag(v^2 * D0) U0'``.

TPU re-design (four structural wins over the reference's loop):

1. ``np.linalg.eig`` on a symmetric PSD matrix becomes a *batched symmetric*
   eigh — and on TPU the VMEM-resident Pallas Jacobi kernel
   (:mod:`mfm_tpu.ops.eigh_pallas`), ~8x XLA's QDWH at this size.
2. The reference re-seeds ``np.random.seed(m+1)`` *identically for every
   date* (``utils.py:71-74``), so the M standard-normal draw matrices — and
   therefore their sample covariances C_m — are the same for all dates.  We
   precompute C_m = cov(N_m) once (M tiny KxK matrices); the simulated
   covariance of date t, sim m is ``F_m = B C_m B'`` with B = U0 sqrt(D0),
   which equals ``np.cov`` of the simulated returns exactly.
3. The whole Monte-Carlo runs in F0's **eigenbasis** — no KxK matmuls at
   all.  With s = sqrt(D0) and G_m = diag(s) C_m diag(s) (an *elementwise*
   scaling of C_m), F_m = U0 G_m U0'; if G_m = W L W' then F_m = (U0 W) L
   (U0 W)', so eigh(G_m) yields the simulated eigenvalues D_m = L directly,
   and the re-estimated true variances of the reference
   (``D_hat = diag(U_m' F0 U_m)``, ``utils.py:83``) collapse to
   ``D_hat_i = sum_k W_ki^2 D0_k``.  This replaces four O(T·M·K^3) matmul
   passes (forming F and projecting F0) with O(T·M·K^2) elementwise work;
   only the eighs remain.
4. All (T, M) decompositions run as ONE flat batch — no per-date dispatch.

Bitwise replication of the reference's draws is impossible by construction
(np.random's MT19937 + SVD-based multivariate_normal); golden tests inject
the draws, production uses ``jax.random`` (SURVEY.md §7.3).  Eigenvector
signs are canonicalized (largest component positive) wherever eigenvectors
are *exposed*; inside this stage every consumer of the F0 basis is
sign-invariant, so the canonicalization pass is skipped there (see the
``canonical_signs=False`` notes below).

Two opt-in variants ride the same stage (``RiskModelConfig``):

- ``eigen_mc_dtype="bfloat16"``: the draws and the scaled-cov assembly run
  in bf16 with f32 accumulation (``preferred_element_type``) and f32 eighs —
  gated by the eigenfactor-bias parity budget (``tools/parity_budget.json``,
  key ``eigen_mc_bf16``), not bitwise.
- ``eigen_incremental=True``: the Monte-Carlo becomes *causal* — date t's
  simulated covariances are estimated from exactly the draw columns
  available at date t, via the raw prefix moments ``(R, p, n)`` carried in
  :class:`~mfm_tpu.models.risk_model.RiskModelState`.  Draws are generated
  per-column (:func:`simulated_eigen_draws`), so growing the bucket never
  rewrites history, and the per-date recursion is strictly sequential, so a
  resumed slab is bitwise the suffix of the full-history run.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.ops.eigh import (
    _sweeps_for,
    batched_eigh,
    batched_eigh_weighted_diag,
)

from mfm_tpu.utils.prec import highest_matmul_precision


def _near_diagonal_sims(n_factors: int, sim_length: int | None) -> bool:
    """Whether G = diag(s) C_m diag(s) is near-diagonal: C_m = I +
    O(1/sqrt(sim_length)), so the premise needs sim_length >> K (4*K is the
    conservative cutoff).  ``sim_length=None`` (caller-injected sim_covs of
    unknown provenance) counts as not-near — the safe, sorted path."""
    return sim_length is not None and sim_length >= 4 * n_factors


def sim_sweeps_for(n_factors: int, dtype, sim_length: int) -> int:
    """Jacobi sweep cap for the simulated eighs, derived from K.

    The near-diagonal G matrices of this stage (see
    :func:`_near_diagonal_sims`) converge ~2 sweeps before the solver's
    general-matrix default (measured bitwise-equal at K=42, sim_length=200
    with 5 = default-2 sweeps; deviation at default-3).  With many more
    draws the off-diagonal mass shrinks as ~sqrt(1/sim_length) and one more
    sweep can go: at K=42, sim_length=1390, 4 = default-3 sweeps deviates
    only 1.5e-6 relative in the final adjusted covariance (measured
    2026-07-29; 3 sweeps deviates 5e-5, past the 1e-5 contract) at ~17%
    less stage wall-clock.  The deep tier engages at 32*K — just inside the
    measured point (33*K), not extrapolated toward the 4*K boundary where
    the error's steep sweep-sensitivity is unquantified.  Scaling with
    :func:`mfm_tpu.ops.eigh._sweeps_for` rather than pinning keeps those
    margins at larger K, where the default itself grows.  When the
    near-diagonality premise fails, the solver default is returned.
    """
    full = _sweeps_for(n_factors, dtype)
    if not _near_diagonal_sims(n_factors, sim_length):
        return full
    if sim_length >= 32 * n_factors:
        return max(4, full - 3)
    return max(5, full - 2)


@highest_matmul_precision
def simulated_eigen_covs(
    key: jax.Array, n_factors: int, sim_length: int, n_sims: int,
    dtype=jnp.float32, mc_dtype=None,
) -> jax.Array:
    """Sample covariances C_m of M standard-normal (K, T_sim) draws.

    Matches ``np.cov`` semantics: demean each row over the T_sim samples,
    normalize by (T_sim - 1).  Shape (M, K, K), always ``dtype``.

    ``mc_dtype`` (the ``eigen_mc_dtype`` knob): draws are generated in that
    dtype and the Gram contraction runs with ``dtype`` accumulation
    (``preferred_element_type`` — one dot-general, never a bf16 running
    sum, which would swamp at production sim lengths).  The mean is also
    accumulated in ``dtype`` and rounded back for the subtraction, so the
    demeaned samples stay in ``mc_dtype``.  ``None`` is the bitwise
    original path.
    """
    if mc_dtype is None:
        draws = jax.random.normal(
            key, (n_sims, n_factors, sim_length), dtype=dtype)
        d = draws - jnp.mean(draws, axis=-1, keepdims=True)
        return jnp.einsum("mkt,mlt->mkl", d, d) / (sim_length - 1)
    md = jnp.dtype(mc_dtype)
    draws = jax.random.normal(key, (n_sims, n_factors, sim_length), dtype=md)
    mu = jnp.mean(draws.astype(dtype), axis=-1, keepdims=True)
    d = draws - mu.astype(md)
    gram = jnp.einsum("mkt,mlt->mkl", d, d, preferred_element_type=dtype)
    return gram.astype(dtype) / (sim_length - 1)


def draw_bucket(T: int) -> int:
    """Power-of-two draw-bucket capacity >= T (floor 64).

    Incremental mode pre-generates the (M, K, bucket) draw tensor, so every
    compiled shape downstream changes only when the history crosses a power
    of two — the steady-state serving loop stays at <= 1 compile between
    (rare, logarithmically spaced) bucket rollovers.
    """
    b = 64
    while b < T:
        b *= 2
    return b


def simulated_eigen_draws(key: jax.Array, n_factors: int, bucket: int,
                          n_sims: int, dtype=jnp.float32,
                          mc_dtype=None) -> jax.Array:
    """The frozen (M, K, bucket) standard-normal draw tensor behind
    incremental mode, generated **per column**: column t comes from
    ``fold_in(key, t)``.

    Per-column generation is the load-bearing property: a bigger bucket is
    a strict prefix-extension of a smaller one (``jax.random.normal(key,
    (M, K, T))[..., :n]`` does NOT equal ``normal(key, (M, K, n))``, so a
    single monolithic draw would rewrite the already-consumed history on
    every bucket rollover and break the bitwise-suffix contract).
    """
    md = jnp.dtype(mc_dtype) if mc_dtype is not None else jnp.dtype(dtype)
    # R2: s32 iota — the column index only folds into the key
    cols = jax.vmap(
        lambda t: jax.random.normal(jax.random.fold_in(key, t),
                                    (n_sims, n_factors), dtype=md)
    )(jnp.arange(bucket, dtype=jnp.int32))
    return jnp.moveaxis(cols, 0, -1)  # (M, K, bucket)


def eigen_carry_init(n_sims: int, n_factors: int, dtype=jnp.float32) -> tuple:
    """The ``(R, p, n)`` raw prefix moments of incremental mode before any
    date: R (M, K, K) sum of per-column outer products, p (M, K) column sum,
    n (s32) columns consumed.  All-zero — the recursion is exact, so
    resuming from any checkpointed carry reproduces the uninterrupted run
    bitwise (same contract as ``vr_init_carry``)."""
    return (jnp.zeros((n_sims, n_factors, n_factors), dtype),
            jnp.zeros((n_sims, n_factors), dtype),
            jnp.zeros((), jnp.int32))


# working-set accounting for the chunked Monte-Carlo: the G tensor itself
# plus XLA's eigh scratch (QDWH workspace is a few copies of the batch)
_CHUNK_WORKSPACE_FACTOR = 4
# host backends get a hard transient cap: LAPACK streams through chunks at
# identical total FLOPs, and a bounded working set keeps huge histories from
# thrashing the page cache (tools/eigh_cpu_ab.py for the solver A/B)
_CHUNK_HOST_BUDGET_BYTES = 256 * 1024 * 1024


def _memory_headroom_bytes(backend: str) -> int | None:
    """Free memory on the compute device (HBM stats) or host (MemAvailable)."""
    if backend in ("tpu", "axon", "gpu", "cuda", "rocm"):
        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"]) - int(stats.get("bytes_in_use", 0))
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return None


def auto_eigen_chunk(T: int, n_sims: int, n_factors: int, itemsize: int = 4,
                     backend: str | None = None) -> int | None:
    """Resolve ``eigen_chunk="auto"``: a date-chunk size for the eigen
    Monte-Carlo, or None to run the full (T, M) batch in one shot.

    The streamed transient is O(chunk * M * K^2); this sizes chunk from the
    backend's live memory headroom (device HBM stats on accelerators, host
    MemAvailable otherwise), keeping the full batch whenever it fits the
    budget.  Resolved at trace time — the decision is baked into the
    compiled program, like every other shape decision.
    """
    backend = backend or jax.default_backend()
    per_date = n_sims * n_factors * n_factors * itemsize * _CHUNK_WORKSPACE_FACTOR
    head = _memory_headroom_bytes(backend)
    if backend in ("tpu", "axon", "gpu", "cuda", "rocm"):
        # accelerator HBM: fit-or-chunk against half the free device memory
        budget = head // 2 if head else 4 * 1024 ** 3
    else:
        budget = (min(head // 4, _CHUNK_HOST_BUDGET_BYTES) if head
                  else _CHUNK_HOST_BUDGET_BYTES)
    if T * per_date <= budget:
        return None
    return int(max(1, min(T, budget // per_date)))


def _bias_ratios(G, d0_c, dtype, prefer_pallas, sim_sweeps, batch_hint):
    """(c, M, K, K) scaled-Gram batch + (c, K) F0 eigenvalues -> (c, K) mean
    bias ratios v^2 — the one body every assembly variant (full batch,
    chunked stream, bf16, incremental) funnels into, so their per-date op
    sequence past assembly is identical by construction."""
    Dm, Dm_hat = batched_eigh_weighted_diag(
        G, d0_c[:, None, :], prefer_pallas=prefer_pallas,
        sweeps=sim_sweeps, batch_hint=batch_hint)
    # rank pairing, order-invariant across backends: i-th smallest sim
    # eigenvalue pairs with the i-th smallest D0 (D0 is already
    # ascending).  One variadic key-value sort: ~3x cheaper on TPU than
    # argsort + two take_along_axis gathers over the same (c, M, K)
    # tensors (measured 0.15 s at CSI300 scale); is_stable matches
    # jnp.argsort's tie order.
    Dm, Dm_hat = jax.lax.sort((Dm, Dm_hat), dimension=-1, num_keys=1,
                              is_stable=True)
    # A numerically-zero sim eigenvalue (rank-deficient covariance:
    # D0_k = 0 zeroes G's k-th row/column, and LAPACK/Jacobi may emit 0
    # or -eps there) would make the ratio 0/0 or a huge spurious value —
    # substitute ratio 1 wherever |Dm| is below eps * lambda_max.  The
    # substituted value only shifts v in directions the rebuild then
    # scales by D0 ~ 0.
    eps = jnp.asarray(jnp.finfo(dtype).eps, dtype)
    thr = eps * jnp.max(jnp.abs(Dm), axis=-1, keepdims=True)
    degenerate = jnp.abs(Dm) <= thr
    ratio = jnp.where(degenerate, 1.0,
                      Dm_hat / jnp.where(degenerate, 1.0, Dm))
    # clamp: tiny-negative Dm just above thr could still push the mean
    # negative, and sqrt of a negative poisons the whole date with NaN
    return jnp.maximum(jnp.mean(ratio, axis=1), 0.0)  # (c, K)


@highest_matmul_precision
def eigen_risk_adjust_by_time(
    covs: jax.Array,
    valid: jax.Array,
    sim_covs: jax.Array,
    scale_coef: float = 1.4,
    prefer_pallas: bool | None = None,
    sim_sweeps: int | None = None,
    sim_length: int | None = None,
    chunk: int | None = None,
    batch_hint: int | None = None,
    mc_dtype=None,
):
    """Batched adjustment over the date axis.

    ``covs``: (T, K, K); ``valid``: (T,) — dates whose Newey-West estimate was
    invalid stay invalid, and dates with a negative eigenvalue are marked
    invalid (the reference raises and stores an empty DataFrame,
    ``utils.py:67-68``, ``MFM.py:118-121``).
    Returns (adjusted covs (T, K, K) with NaN at invalid dates, valid (T,)).

    ``sim_sweeps`` caps the Jacobi sweep count for the (T, M) *simulated*
    decompositions only (the dominant cost; the T-sized F0 eigh always runs
    at full precision).  Converged rotations are exact no-ops (apq below
    threshold gives c=1, s=0), so once convergence completes, extra sweeps
    change nothing: 5 sweeps is bitwise-equal to the solver-default 7 on the
    CSI300-class Wishart matrices of this stage at ~30% less wall-clock
    (measured; 4 sweeps deviates ~8e-3 in the kernel's off-diagonal
    residual, ~5e-4 in the final adjusted covariance).

    ``sim_length`` is the number of draws behind ``sim_covs``; it sizes the
    auto sweep cap (see :func:`sim_sweeps_for`).  The bias pairing itself is
    **rank-based and order-invariant**: ``Dm_hat`` is computed in whatever
    slot order the solver emits, then the scalar (Dm, Dm_hat) pairs are
    sorted by Dm, so ascending sim eigenvalues always pair with ascending
    D0 — identical semantics on the unsorted Pallas fast path and the
    always-ascending XLA/LAPACK fallback, even when sampling noise reorders
    near-degenerate eigenvalues (round-1 advisor finding).  The eigenvector
    batch itself is never sorted (that would be a full HBM round trip over
    (T*M, K, K)); only two (T, M, K) value tensors are.

    ``chunk`` streams the Monte-Carlo over the date axis: the (T, M, K, K)
    G transient — by far the largest allocation of the whole pipeline at
    production scale — is never materialized; instead ``lax.map`` runs the
    sim eighs over (chunk, M, K, K) slabs and accumulates only the (T, K)
    bias ratios.  ``None`` (or chunk >= T) keeps the single full batch.
    The per-date math is identical either way (same op sequence per slab,
    and the solver-dispatch batch is pinned to the full T*M batch size
    regardless of chunking), so chunked == unchunked exactly on the XLA
    path.  Use :func:`auto_eigen_chunk` to size it from live memory.

    ``batch_hint`` overrides that dispatch pin (default T*M): the
    incremental update path passes the INIT-time T*M so a one-date slab
    dispatches its sim eighs exactly like the full history it extends —
    slab-invariant the same way the chunk stream is chunk-invariant.

    ``mc_dtype`` (the ``eigen_mc_dtype`` knob): assemble the (c, M, K, K)
    G transient in that dtype — sqrt-eigenvalue scale factors and sim_covs
    rounded once, the per-date outer-scale matrix formed as a dot-general,
    and ONE multiply over the big tensor instead of the default path's two
    chained broadcast multiplies — then cast to ``covs.dtype`` for the
    (always full-precision) eighs.  The restructure lives only on this
    non-default path: it changes rounding, so the ``None`` default keeps
    the original op sequence bitwise.
    """
    dtype = covs.dtype
    T = covs.shape[0]
    K = covs.shape[-1]
    M = sim_covs.shape[0]
    if batch_hint is None:
        batch_hint = T * M
    if sim_sweeps is None and sim_length is not None:
        sim_sweeps = sim_sweeps_for(K, dtype, sim_length)
    eye = jnp.eye(K, dtype=dtype)
    safe = jnp.where(valid[:, None, None], covs, eye)

    # canonical_signs=False: within this stage the F0 basis is sign-
    # invariant — s and psd read D0 only, and the rebuild einsum below
    # carries U0 quadratically (sign flips are exact FP negations that
    # square away term-by-term) — so skipping the canonicalization pass
    # (argmax + gather + multiply over (T, K, K)) is bitwise-identical on
    # every output while shaving the hot path.
    D0, U0 = batched_eigh(safe, prefer_pallas=prefer_pallas,
                          canonical_signs=False)  # (T,K), (T,K,K)
    psd = D0[..., 0] >= 0  # ascending order -> min eigenvalue first
    s = jnp.sqrt(jnp.maximum(D0, 0.0))

    # simulated covariances in F0's eigenbasis: G = diag(s) C_m diag(s), an
    # elementwise scaling (module docstring, point 3).  The sim eighs return
    # only (eigenvalues, D0-weighted squared-eigenvector diagonals): the
    # Pallas path reduces W against D0 inside the kernel, so the (T*M, K, K)
    # eigenvector batch never round-trips HBM and no separate einsum pass
    # reads it back; pairing is restored below by sorting the scalar
    # (Dm, Dm_hat) pairs.  Signs square away in W*W.
    # D_hat = diag(U_m' F0 U_m) with U_m = U0 W  ->  sum_k W_ki^2 D0_k
    md = None if mc_dtype is None else jnp.dtype(mc_dtype)
    sim_lo = None if md is None else sim_covs.astype(md)

    def _sim_bias_v2(s_c, d0_c, sc=None, sc_lo=None):
        """(c, K) sqrt-eigvals + eigvals -> (c, K) mean bias ratios v^2.

        The whole per-date Monte-Carlo for a slab of dates — the one body
        the full-batch, the chunked and the shard_map paths all run, so
        their per-date op sequence (and hence their result) is identical
        by construction.  ``sc``/``sc_lo`` default to the closed-over sim
        covariances; the shard_map path passes them as explicit replicated
        operands instead (shard_map bodies cannot close over traced
        values).
        """
        sc = sim_covs if sc is None else sc
        if md is None:
            G = s_c[:, None, :, None] * sc[None] * s_c[:, None, None, :]
        else:
            # mixed-precision assembly: the (c, K, K) outer-scale matrix is
            # one dot-general over the rounded scale factors, then a single
            # multiply forms the big (c, M, K, K) transient in mc_dtype —
            # cast up only at the eigh input
            sc_lo = sim_lo if sc_lo is None else sc_lo
            s_lo = s_c.astype(md)
            S = jnp.einsum("ck,cl->ckl", s_lo, s_lo)
            G = (S[:, None] * sc_lo[None]).astype(dtype)
        return _bias_ratios(G, d0_c, dtype, prefer_pallas, sim_sweeps,
                            batch_hint)

    def _v2_slab(s_c, d0_c, sc, sc_lo):
        """v2 over a (t, K) slab of dates, streaming by ``chunk`` when it
        bites — the per-DEVICE body of the shard_map path below.  No mesh
        pinning in here: inside shard_map every axis is manual/local."""
        t = s_c.shape[0]
        if chunk is None or chunk >= t:
            return _sim_bias_v2(s_c, d0_c, sc, sc_lo)
        pad = (-t) % chunk
        s_p = jnp.pad(s_c, ((0, pad), (0, 0)))
        d0_p = jnp.pad(d0_c, ((0, pad), (0, 0)))
        n_chunks = (t + pad) // chunk
        v2 = jax.lax.map(
            lambda args: _sim_bias_v2(*args, sc, sc_lo),
            (s_p.reshape(n_chunks, chunk, K),
             d0_p.reshape(n_chunks, chunk, K)))
        return v2.reshape(n_chunks * chunk, K)[:t]

    from mfm_tpu.parallel.mesh import _ambient_mesh, replicate_under_mesh

    mesh = _ambient_mesh()
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    if n_dev > 1:
        # Device-parallel Monte-Carlo: shard the (T, M, K, K) eigh batch's
        # date axis over the WHOLE mesh via shard_map — each device runs
        # the per-date body on its contiguous date block, so every eigh
        # stays device-local and the result is bitwise-equal to the
        # single-device batch (the same slab-invariance argument as the
        # chunk stream: identical per-date op sequence, solver dispatch
        # pinned by batch_hint).  Padded dates carry s = 0 -> all-zero G ->
        # every ratio hits the degenerate guard -> v2 = 1; cropped below.
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as _P

        padT = (-T) % n_dev
        s_p = jnp.pad(s, ((0, padT), (0, 0)))
        d0_p = jnp.pad(D0, ((0, padT), (0, 0)))
        date_spec = _P(tuple(mesh.axis_names))
        rep = _P()
        v2 = shard_map(
            _v2_slab, mesh=mesh,
            in_specs=(date_spec, date_spec, rep, rep),
            out_specs=date_spec,
            check_rep=False,
        )(s_p, d0_p, sim_covs,
          sim_covs if sim_lo is None else sim_lo)
        v2 = replicate_under_mesh(v2[:T])
    elif chunk is None or chunk >= T:
        v2 = _sim_bias_v2(s, D0)  # (T, K)
    else:
        # stream: pad T up to a chunk multiple (padded dates carry s = 0,
        # whose G is all-zero -> every ratio hits the degenerate guard ->
        # v2 = 1; cropped below regardless), then map the slab body.  The
        # (T, K)-sized map operands/outputs are pinned replicated under any
        # ambient mesh — the serial stream gains nothing from sharding and
        # scan-stacked sharded outputs trip the s64/s32 partitioner bug
        # (see vol_regime.py).
        pad = (-T) % chunk
        s_p = jnp.pad(s, ((0, pad), (0, 0)))
        d0_p = jnp.pad(D0, ((0, pad), (0, 0)))
        n_chunks = (T + pad) // chunk
        s_p, d0_p = replicate_under_mesh((
            s_p.reshape(n_chunks, chunk, K), d0_p.reshape(n_chunks, chunk, K)))
        v2 = jax.lax.map(lambda args: _sim_bias_v2(*args), (s_p, d0_p))
        v2 = replicate_under_mesh(v2.reshape(n_chunks * chunk, K)[:T])

    v = scale_coef * (jnp.sqrt(v2) - 1.0) + 1.0

    out = jnp.einsum("tik,tk,tjk->tij", U0, v * v * D0, U0)
    ok = valid & psd
    out = jnp.where(ok[:, None, None], out, jnp.nan)
    return out, ok


@highest_matmul_precision
def eigen_risk_adjust_incremental(
    covs: jax.Array,
    valid: jax.Array,
    draws: jax.Array,
    carry: tuple,
    scale_coef: float = 1.4,
    *,
    prefer_pallas: bool | None = None,
    sim_sweeps: int | None = None,
    chunk: int | None = None,
    batch_hint: int | None = None,
    skip_mask: jax.Array | None = None,
    mc_dtype=None,
):
    """Causal (expanding-draw) eigen adjustment — the incremental mode.

    The default stage estimates ONE set of simulated covariances from
    ``sim_length`` draws and applies it to every date — date t's bias then
    depends on the total panel length, so a checkpoint has to freeze
    ``sim_covs`` and serve stale-count sims forever.  Here the Monte-Carlo
    is *causal* instead: each non-skipped date consumes the next column of
    the frozen per-column ``draws`` tensor (:func:`simulated_eigen_draws`)
    and folds it into the raw prefix moments ``carry = (R, p, n)``
    (:func:`eigen_carry_init`) BEFORE its own bias is measured, so date t's
    simulated covariances ``C_m(t) = (R - p p'/n) / (n - 1)`` estimate from
    exactly the draw prefix available at date t.  Because the moment
    recursion is strictly sequential (a ``fori_loop`` inside a chunk
    ``scan`` — never a parallel prefix) and the carry is exact, a slab
    resumed from a checkpointed carry is **bitwise** the suffix of the
    full-history run, chunk- and slab-boundary-invariant — the same
    contract as the Newey-West and vol-regime carries.

    ``C_m(t)`` is the one-pass raw-moment form of ``np.cov`` (draws are
    standard normal, mean ~0, so the classic cancellation hazard is absent);
    dates with n < 2 get the identity substitute (they are Newey-West-
    invalid anyway — min_valid >= K — so the value is never served).

    ``skip_mask`` ((T,) bool) excises dates exactly like the NW/vol-regime
    carries: a skipped date consumes no draw column and leaves (R, p, n)
    bitwise untouched, so (good, BAD, good) matches (good, good).  Padded
    chunk-tail dates ride the same mechanism.

    ``sim_sweeps`` must be resolved by the CALLER (host-side, from the
    running count via :func:`sim_sweeps_for`) — it is a static solver knob
    and this function sees only traced counts.

    Returns ``(out, ok, carry_out)``.
    """
    dtype = covs.dtype
    T = covs.shape[0]
    K = covs.shape[-1]
    M = draws.shape[0]
    if batch_hint is None:
        batch_hint = T * M
    eye = jnp.eye(K, dtype=dtype)
    safe = jnp.where(valid[:, None, None], covs, eye)

    # sign-invariant F0 basis, same argument as eigen_risk_adjust_by_time
    D0, U0 = batched_eigh(safe, prefer_pallas=prefer_pallas,
                          canonical_signs=False)
    psd = D0[..., 0] >= 0
    s = jnp.sqrt(jnp.maximum(D0, 0.0))
    skip = (jnp.zeros((T,), bool) if skip_mask is None
            else skip_mask.astype(bool))

    md = None if mc_dtype is None else jnp.dtype(mc_dtype)

    def _chunk_body(mom, xs):
        R, p, n = mom
        s_c, d0_c, skip_c = xs  # (c, K), (c, K), (c,)
        c = s_c.shape[0]

        def date_step(i, st):
            R, p, n, Cs = st
            sk = jax.lax.dynamic_index_in_dim(skip_c, i, 0, keepdims=False)
            # column n is the next unconsumed draw (dynamic_slice clamps the
            # unreachable-by-construction overflow read; risk_model rolls
            # the bucket before it can fill).  bf16 draws cast up exactly —
            # the moments always accumulate in the compute dtype.
            x = jax.lax.dynamic_index_in_dim(
                draws, n, 2, keepdims=False).astype(dtype)
            # optimization_barrier pins the mul->add/sub rounding chains:
            # XLA CPU forms FMAs opportunistically and PER COMPILATION, so
            # without the barriers a different chunk/slab shape can contract
            # `R + x x'` or `R - p p'/n` into a single-rounding FMA and
            # break the bitwise chunk/slab invariance this mode promises
            # (observed: 1-ulp moment drift amplified through the
            # ill-conditioned early-date eighs).
            o = jax.lax.optimization_barrier(x[:, :, None] * x[:, None, :])
            R1 = jnp.where(sk, R, R + o)
            p1 = jnp.where(sk, p, p + x)
            n1 = jnp.where(sk, n, n + jnp.int32(1))
            nf = n1.astype(dtype)
            mu = p1 / jnp.maximum(nf, 1.0)
            pp = jax.lax.optimization_barrier(
                mu[:, :, None] * p1[:, None, :])
            Craw = (R1 - pp) / jnp.maximum(nf - 1.0, 1.0)
            C = jnp.where(n1 >= jnp.int32(2), Craw,
                          jnp.broadcast_to(eye, Craw.shape))
            Cs = jax.lax.dynamic_update_index_in_dim(Cs, C, i, 0)
            return R1, p1, n1, Cs

        # R2: explicit s32 bounds, like every traced loop counter here
        R, p, n, Cs = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(c), date_step,
            (R, p, n, jnp.zeros((c, M, K, K), dtype)))
        if md is None:
            G = s_c[:, None, :, None] * Cs * s_c[:, None, None, :]
        else:
            s_lo = s_c.astype(md)
            S = jnp.einsum("ck,cl->ckl", s_lo, s_lo)
            G = (S[:, None] * Cs.astype(md)).astype(dtype)
        v2_c = _bias_ratios(G, d0_c, dtype, prefer_pallas, sim_sweeps,
                            batch_hint)
        return (R, p, n), v2_c

    if chunk is None or chunk >= T:
        mom, v2 = _chunk_body(tuple(carry), (s, D0, skip))
    else:
        from mfm_tpu.parallel.mesh import replicate_under_mesh

        pad = (-T) % chunk
        # padded tail dates are skip=True: they must consume no draw column
        s_p = jnp.pad(s, ((0, pad), (0, 0)))
        d0_p = jnp.pad(D0, ((0, pad), (0, 0)))
        skip_p = jnp.pad(skip, ((0, pad),), constant_values=True)
        n_chunks = (T + pad) // chunk
        s_p, d0_p, skip_p = replicate_under_mesh((
            s_p.reshape(n_chunks, chunk, K),
            d0_p.reshape(n_chunks, chunk, K),
            skip_p.reshape(n_chunks, chunk)))
        mom, v2s = jax.lax.scan(_chunk_body, tuple(carry),
                                (s_p, d0_p, skip_p))
        v2 = replicate_under_mesh(v2s.reshape(n_chunks * chunk, K)[:T])

    v = scale_coef * (jnp.sqrt(v2) - 1.0) + 1.0
    out = jnp.einsum("tik,tk,tjk->tij", U0, v * v * D0, U0)
    ok = valid & psd
    out = jnp.where(ok[:, None, None], out, jnp.nan)
    return out, ok, mom


def eigen_risk_adjust(
    cov: jax.Array,
    sim_covs: jax.Array,
    scale_coef: float = 1.4,
    prefer_pallas: bool | None = None,
) -> jax.Array:
    """Adjust one KxK covariance (the reference's ``eigen_risk_adj``,
    ``utils.py:55-92``)."""
    out, _ = eigen_risk_adjust_by_time(
        cov[None], jnp.ones((1,), bool), sim_covs, scale_coef,
        prefer_pallas=prefer_pallas,
    )
    return out[0]
