"""Specific-risk model: EWMA specific volatility + Bayesian shrinkage.

The reference defines ``bayes_shrink`` (``Barra-master/mfm/utils.py:133-168``)
but never calls it (SURVEY.md §7.3); its drivers stop at factor covariances
plus raw specific returns (``demo.py:60-94``).  This module completes the
USE4 specific-risk stage that shrinkage exists for:

1. :func:`ewma_specific_vol` — per-stock EWMA volatility of specific
   returns, the same restricted-renormalized half-life machinery as the
   factor vol-regime stage (``MFM.py:158-159``), masked over each stock's
   valid dates.
2. :func:`specific_risk_by_time` — the vol panel shrunk per date toward
   cap-decile group means (``utils.py:133-168``, masked to the per-date
   universe).

The portfolio-level combination sigma_p^2 = x'Fx + sum w_i^2 sigma_i^2 —
the model's end use — lives on
:meth:`mfm_tpu.pipeline.RiskPipelineResult.portfolio_risk`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.models.bias import bayes_shrink


def ewma_specific_vol(
    specific_ret: jax.Array,
    half_life: float = 42.0,
    min_periods: int = 10,
):
    """Per-stock EWMA volatility of specific returns.

    specific_ret: (T, N), NaN outside each date's universe.  For each (t, n),
    ``vol = sqrt(sum_i w_i u_i^2 / sum_i w_i)`` over stock n's valid dates
    i <= t with exp-decay weights of the given half-life (the vol-regime
    stage's restricted renormalized EWMA, ``MFM.py:158-159``, applied per
    stock).  Dates with fewer than ``min_periods`` valid observations so far
    are NaN.  Returns (T, N).
    """
    dtype = specific_ret.dtype
    lam = jnp.asarray(0.5, dtype) ** (1.0 / half_life)
    m = jnp.isfinite(specific_ret)
    u2 = jnp.where(m, specific_ret, 0.0) ** 2
    mf = m.astype(dtype)

    def step(carry, inp):
        num, den, cnt = carry
        x2, ok = inp
        num = lam * num + ok * x2
        den = lam * den + ok
        cnt = cnt + ok
        var = jnp.where((cnt >= min_periods) & (den > 0),
                        num / jnp.maximum(den, 1e-30), jnp.nan)
        return (num, den, cnt), var

    zero = jnp.zeros(specific_ret.shape[1], dtype)
    _, var = jax.lax.scan(step, (zero, zero, zero), (u2, mf))
    return jnp.sqrt(var)


def specific_risk_by_time(
    specific_ret: jax.Array,
    cap: jax.Array,
    half_life: float = 42.0,
    ngroup: int = 10,
    q: float = 1.0,
    min_periods: int = 10,
):
    """(T, N) specific-risk panel: EWMA vol, then per-date Bayesian
    shrinkage toward cap-group means over that date's valid universe.

    Returns (raw_vol (T, N), shrunk_vol (T, N)); cells with no vol estimate
    yet (or no cap) are NaN in both.
    """
    vol = ewma_specific_vol(specific_ret, half_life, min_periods)
    mask = jnp.isfinite(vol) & jnp.isfinite(cap) & (cap > 0)

    def one(v, c, m):
        return bayes_shrink(v, c, ngroup=ngroup, q=q, mask=m)

    shrunk = jax.vmap(one)(vol, cap, mask)
    return jnp.where(mask, vol, jnp.nan), jnp.where(mask, shrunk, jnp.nan)
