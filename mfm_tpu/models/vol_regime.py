"""Volatility regime adjustment (USE4), as a single masked scan.

Contract (``Barra-master/mfm/MFM.py:130-167``):
- per-date cross-sectional bias statistic
  ``B_t = sqrt(mean_k(f_{t,k}^2 / sigma^2_{t,k}))`` with sigma^2 the diagonal
  of the (eigen-adjusted) covariance at the same date (``MFM.py:149``);
- exp-decay weights with half-life tau over dates, restricted to dates whose
  variance row has no NaN, renormalized (``MFM.py:158-159``);
- factor-volatility multiplier ``lambda_t = sqrt(sum_i w_i B_i^2)`` over
  i <= t (``MFM.py:160``), and the adjusted covariance is
  ``cov_t * lambda_t^2`` (``MFM.py:163``).

The reference recomputes the weighted sum per date (O(T^2)); the restricted
renormalized EWMA is two scalar EWMA recursions — one scan, O(T).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def vr_init_carry(dtype) -> tuple:
    """The ``(num, den)`` EWMA state of the restricted renormalized weighted
    sum before any date — the resumable checkpoint of this stage (both sums
    are exact, so resuming reproduces the uninterrupted scan bitwise)."""
    return (jnp.asarray(0.0, dtype), jnp.asarray(0.0, dtype))


def vol_regime_adjust_by_time(
    factor_ret: jax.Array,
    covs: jax.Array,
    valid: jax.Array,
    half_life: float = 42.0,
):
    """Args:
      factor_ret: (T, K) raw factor returns from the cross-sectional stage.
      covs: (T, K, K) eigen-adjusted covariances (NaN at invalid dates).
      valid: (T,) validity of each covariance.

    Returns (adjusted_covs (T,K,K), lamb (T,)).
    """
    adj, lamb, _ = vol_regime_adjust_resume(factor_ret, covs, valid, half_life)
    return adj, lamb


def vol_regime_adjust_resume(
    factor_ret: jax.Array,
    covs: jax.Array,
    valid: jax.Array,
    half_life: float = 42.0,
    carry: tuple | None = None,
    dyn_length: jax.Array | None = None,
    skip_mask: jax.Array | None = None,
):
    """:func:`vol_regime_adjust_by_time`, checkpointable.

    Returns ``(adjusted_covs, lamb, carry_out)``; ``carry`` resumes the
    ``(num, den)`` EWMA recursion from a previous call's ``carry_out``
    (default: the empty-history state, :func:`vr_init_carry`).  Because the
    carry holds the exact scan sums, dates ``[0:T0]`` then ``[T0:T]`` from
    the returned carry match one uninterrupted pass bitwise — the
    incremental daily-update path.  ``half_life`` must match across resumed
    calls.  ``dyn_length`` (traced s32 scalar == T) keeps the loop bound
    dynamic so XLA cannot inline a trip-count-1 loop into the surrounding
    program and shift the step math by an ulp (see newey_west.py).

    ``skip_mask`` ((T,) bool, quarantine verdicts) excises dates from the
    EWMA: at a masked date ``(num, den)`` pass through UNCHANGED — note
    this is *stronger* than an invalid date (``ok`` False), which still
    decays both sums (time-decay semantics, MFM.py:158); a quarantined
    date is removed from the time axis entirely so (good, BAD, good)
    matches (good, good) bitwise.  The masked date's stored multiplier is
    the frozen carry's ratio (the value a degraded-mode reader would see).
    """
    dtype = factor_ret.dtype
    lam = jnp.asarray(0.5, dtype) ** (1.0 / half_life)
    var = jnp.diagonal(covs, axis1=-2, axis2=-1)  # (T, K)
    ok = valid & jnp.all(jnp.isfinite(var), axis=-1)
    B2 = jnp.mean(factor_ret**2 / var, axis=-1)  # (T,) B_t^2
    B2z = jnp.where(ok, B2, 0.0)
    okf = ok.astype(dtype)

    # tiny (T,) series: replicated per the layout doctrine (see mesh.py) —
    # the serial recursion cannot use a sharded date axis anyway
    from mfm_tpu.parallel.mesh import replicate_under_mesh

    B2z, okf = replicate_under_mesh((B2z, okf))
    skf = None if skip_mask is None else replicate_under_mesh(skip_mask)
    T = B2z.shape[0]

    # s32-indexed fori_loop rather than lax.scan: scan's stacked-output
    # counter canonicalizes to s64 under x64, and XLA's spmd partitioner
    # emits s32 shard-offset math around the dynamic_update_slice — the HLO
    # verifier rejects the mixed compare when the stacking axis is sharded
    def body(i, state):
        num, den, out = state
        b2 = jax.lax.dynamic_index_in_dim(B2z, i, 0, keepdims=False)
        okv = jax.lax.dynamic_index_in_dim(okf, i, 0, keepdims=False)
        num_new = lam * num + okv * b2
        den_new = lam * den + okv
        if skf is not None:
            sk = jax.lax.dynamic_index_in_dim(skf, i, 0, keepdims=False)
            num_new = jnp.where(sk, num, num_new)
            den_new = jnp.where(sk, den, den_new)
        # before any valid date numpy sums over empty arrays yield 0.0
        # (MFM.py:159-160), not NaN
        val = jnp.where(den_new > 0, num_new / den_new, 0.0)
        return (num_new, den_new,
                jax.lax.dynamic_update_index_in_dim(out, val, i, 0))

    num0, den0 = vr_init_carry(dtype) if carry is None else carry
    hi = jnp.int32(T) if dyn_length is None else dyn_length.astype(jnp.int32)
    num, den, fvm2 = jax.lax.fori_loop(
        jnp.int32(0), hi, body,
        (num0, den0, jnp.zeros((T,), dtype)),
    )
    fvm2 = replicate_under_mesh(fvm2)
    lamb = jnp.sqrt(fvm2)
    return covs * fvm2[:, None, None], lamb, replicate_under_mesh((num, den))
