"""Base-covariance resolvers for replay and counterfactual scenarios.

Two of the spec kinds cannot be expressed as a covariance transform — they
change WHICH world the shock applies to:

- **Historical replay**: the base becomes the covariance the model had
  fitted through a named stretch of panel history.
- **Quarantine counterfactual**: the base becomes the served covariance
  of a REAL guarded re-run with chosen verdicts flipped — not an
  approximation of the guards, the actual ``update_guarded`` graph with
  the ``pre_reasons`` / ``heal_mask`` operands set.  "Counterfactual
  equals a real re-run with flipped verdicts" is therefore true by
  construction, and tests/test_scenario.py pins it bitwise.

Both resolve HOST-SIDE, per scenario, before the one batched jit — the
kernel only ever sees (S, K, K) base covariances.  This module builds the
two injectables :class:`mfm_tpu.scenario.engine.ScenarioEngine` takes
(``replay_lookup`` / ``counterfactual_fn``) from the artifacts the repo
already produces: a pipeline result's per-date covariance series and an
appended slab + its pre-update checkpoint.
"""

from __future__ import annotations

import numpy as np


def clone_state(state):
    """Deep-copy a ``RiskModelState``'s array leaves (aux rides along).

    ``update_guarded`` DONATES the checkpoint's carries and guard leaves;
    a counterfactual must re-run against a copy so the real serving state
    stays live.  ``jnp.array`` copies each leaf into a fresh JAX-owned
    buffer (safe to donate)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.array, state)


def make_replay_lookup(dates, covs, valid=None):
    """``(start, end) -> (K, K) | None`` over a per-date covariance series.

    ``dates``: the history's date labels (compared as normalized strings,
    the :func:`mfm_tpu.pipeline.date_stamp` order).  ``covs``: (T, K, K)
    fitted covariances (e.g. ``outputs.vr_cov`` or the guard report's
    ``served_cov``).  ``valid``: optional (T,) bool (e.g. ``eigen_valid``)
    — invalid dates never resolve.  The window resolves to the LAST valid
    date inside it: the covariance fitted through that stretch.
    """
    from mfm_tpu.pipeline import date_stamp

    labels = [date_stamp(d) for d in dates]
    covs = np.asarray(covs)
    ok = (np.ones(len(labels), bool) if valid is None
          else np.asarray(valid, bool))
    if covs.ndim != 3 or covs.shape[0] != len(labels) or \
            ok.shape != (len(labels),):
        raise ValueError(f"need (T, K, K) covs + T dates (+ optional (T,) "
                         f"valid); got covs {covs.shape} over "
                         f"{len(labels)} dates")

    def lookup(start, end):
        start, end = date_stamp(start), date_stamp(end)
        hits = [i for i, d in enumerate(labels)
                if start <= d <= end and ok[i]]
        if not hits:
            return None
        return covs[hits[-1]]

    return lookup


def replay_lookup_from_result(result):
    """Replay resolver off a :class:`~mfm_tpu.pipeline.RiskPipelineResult`:
    the guard report's ``served_cov`` series when the run was guarded
    (what was actually servable on each date), else the raw ``vr_cov``
    gated on ``eigen_valid``."""
    if result.report is not None:
        return make_replay_lookup(
            result.arrays.dates, np.asarray(result.report.served_cov),
            valid=~np.asarray(result.report.quarantined, bool))
    return make_replay_lookup(
        result.arrays.dates, np.asarray(result.outputs.vr_cov),
        valid=np.asarray(result.outputs.eigen_valid, bool))


def make_counterfactual_fn(model, state, dates):
    """``(flip_quarantine, flip_heal) -> (K, K)`` via a real guarded re-run.

    ``model``: the :class:`~mfm_tpu.models.risk_model.RiskModel` over the
    appended slab (its panels are snapshotted to host numpy here, so the
    closure survives the donating re-runs).  ``state``: the checkpoint
    BEFORE that slab.  ``dates``: the slab's date labels, in order.

    Each call re-runs ``update_guarded`` on fresh copies with
    ``pre_reasons`` carrying :data:`~mfm_tpu.serve.guard.REASON_FORCED`
    at the force-quarantined dates and ``heal_mask`` True at the
    force-healed ones, and returns the served covariance at the final
    slab date — exactly what that world would have handed the query
    layer.  Unknown flip dates raise ``ValueError`` (the engine rejects
    that scenario, batchmates unaffected).
    """
    from mfm_tpu.models.risk_model import RiskModel
    from mfm_tpu.pipeline import date_stamp
    from mfm_tpu.serve.guard import REASON_FORCED

    labels = [date_stamp(d) for d in dates]
    if len(labels) != model.T:
        raise ValueError(f"{len(labels)} slab dates for a T={model.T} model")
    # host snapshots: update_guarded donates the panels, so each re-run
    # builds a fresh RiskModel from these (RiskModel copies numpy inputs
    # into JAX-owned buffers)
    panels = {f: np.asarray(getattr(model, f))
              for f in ("ret", "cap", "styles", "industry", "valid")}
    n_industries, config = model.n_industries, model.config

    def counterfactual(flip_quarantine, flip_heal):
        fq = {date_stamp(d) for d in flip_quarantine}
        fh = {date_stamp(d) for d in flip_heal}
        unknown = sorted((fq | fh) - set(labels))
        if unknown:
            raise ValueError(f"counterfactual flips dates outside the "
                             f"slab: {unknown[:5]} (slab is "
                             f"{labels[0]}..{labels[-1]})")
        pre = np.zeros(len(labels), np.uint32)
        heal = np.zeros(len(labels), bool)
        for i, d in enumerate(labels):
            if d in fq:
                pre[i] = REASON_FORCED
            if d in fh:
                heal[i] = True
        m = RiskModel(panels["ret"], panels["cap"], panels["styles"],
                      panels["industry"], panels["valid"],
                      n_industries=n_industries, config=config)
        _, report, _ = m.update_guarded(clone_state(state),
                                        pre_reasons=pre, heal_mask=heal)
        return np.asarray(report.served_cov[-1])

    return counterfactual
