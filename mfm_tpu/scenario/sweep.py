"""SweepEngine — stream 10^6 shock worlds through fixed-size aggregates.

:mod:`mfm_tpu.scenario.engine` answers S what-if worlds by MATERIALIZING
every lane's (K, K) shocked covariance to host numpy — the right shape
for a drill report, catastrophic for a million-scenario search (~4 GB of
transfer for answers that are scalars).  This module is the streaming
counterpart (ROADMAP "A million scenarios"): host-side spec GENERATORS
feed chunks of C dense shock lanes into one donated jit
(:func:`mfm_tpu.scenario.kernel.sweep_chunk`) that folds each chunk into
a fixed-size carry — per-book top-k worst (vol, theta) entries, a
fixed-bin vol histogram (the quantile sketch) and admission counters —
so nothing S-shaped ever exists on device or host.

The perf lever is the HOST-CERTIFIED PSD gate: the stressed matrix
``diag(sigma_s) C'(cb) diag(sigma_s)`` shares PSD-ness with the clipped
stressed correlation ``C'(cb)`` whenever ``sigma_s`` is strictly
positive (congruence preserves inertia — Sylvester), and ``C'(cb)``
depends only on the scalar ``corr_beta``.  Samplers emit corr_beta on a
small quantized lattice; the engine certifies each (base, level) pair
ONCE with a K x K host eigh, and certified lanes then run stress +
quadratic form with no decomposition at all.  Lanes the certificate
cannot vouch for (stressed correlation within :data:`PSD_CERT_TOL` of
singular or past it, or stressed vols so ill-scaled that the serving
gate's compute-dtype eigh could see a different sign than the f64
certificate — the :data:`SWEEP_EIGH_GUARD` margin) are "offenders",
buffered and routed through the EXACT serving
path — :func:`scenario_batch`'s per-lane eigh gate — then folded into
the same carry by :func:`sweep_merge` with their true post-projection
vols.  Streaming aggregates are therefore exact, not approximate: the
top-k table bitwise-matches the materializing reference on small S
(tests/test_sweep.py), offenders and projections included.

Grad-guided refinement closes the loop: the coarse top-k thetas seed
``reverse_stress_batch`` (mfm_tpu/grad/reverse.py, used verbatim), the
refined optima anchor a dense local re-sweep, and both refined lane
families merge into the same carry — so the final worst case can only
IMPROVE on the coarse top-1 (merge monotonicity), and it round-trips to
a replayable :class:`ScenarioSpec` exactly like ``GradEngine``'s.

Host-side orchestration only (an mfmlint R7 host-only barrier, like
engine.py): all device math lives in scenario/kernel.py and
grad/reverse.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from mfm_tpu.obs import instrument as _obs
from mfm_tpu.scenario.engine import ScenarioEngine
from mfm_tpu.scenario.kernel import (
    _init_sweep_carry,
    scenario_batch,
    sweep_chunk,
    sweep_merge,
)
from mfm_tpu.scenario.spec import PRESETS, ScenarioSpec, validate_spec
from mfm_tpu.serve.query import bucket_for
from mfm_tpu.utils.chaos import chaos_point

#: a (base, corr_beta-level) pair certifies PSD only when the stressed
#: correlation's smallest eigenvalue clears this margin — eigenvalues of
#: a correlation matrix are O(1), so 1e-4 dwarfs both the f64 host eigh
#: error and the compute-dtype divergence of the device-side stress.
#: Anything inside the band is an offender (exact path), never a guess.
PSD_CERT_TOL = 1e-4

#: Sylvester gives lam_min(cov_s) >= lam_min(C') * min(sigma_s)^2 while
#: the serving gate's compute-dtype eigh observes it with error
#: O(eps * lam_max(cov_s)) <= O(eps * lam_max(C') * max(sigma_s)^2); a
#: lane is certified only when the bound clears that noise floor by this
#: factor, so "certified" and "serving leaves it unprojected" are the
#: same set of lanes (measured headroom on bench shapes is >1000x — 64
#: keeps the band conservative without routing healthy lanes to the
#: exact path).
SWEEP_EIGH_GUARD = 64.0

#: offender lanes buffered host-side flush through the exact path at this
#: ladder rung (bucket_for(128) == 128 — one compile, reused every flush)
OFFENDER_CHUNK = 128

SWEEP_MANIFEST_SCHEMA_VERSION = 1
SWEEP_MANIFEST_NAME = "sweep_manifest.json"


class SweepManifestError(RuntimeError):
    """A sweep manifest exists but is unreadable, schema-incompatible, or
    internally inconsistent."""


# -- theta <-> spec -----------------------------------------------------------

def theta_to_spec(theta, factor_names, name: str,
                  replay=None) -> ScenarioSpec:
    """A dense shock vector ``[shift(K) | scale(K) | vol_mult |
    corr_beta]`` back to declarative :class:`ScenarioSpec` form — the
    same round trip ``GradEngine`` performs, exposed module-level so
    sweep manifests and tests share one canonical encoding (spec hashes
    are comparable across subsystems)."""
    K = len(factor_names)
    th = np.asarray(theta, np.float64)
    return ScenarioSpec(
        name=name,
        shift=tuple((factor_names[j], float(th[j]))
                    for j in range(K) if th[j] != 0.0),
        scale=tuple((factor_names[j], float(th[K + j]))
                    for j in range(K) if th[K + j] != 1.0),
        vol_mult=float(th[2 * K]),
        corr_beta=float(th[2 * K + 1]),
        replay=replay,
    )


# -- host-side spec generators ------------------------------------------------
#
# A sampler is an iterator factory, never a list: ``blocks(chunk)`` yields
# ``(thetas (c, 2K+2) float64, base_idx (c,) int32, cb_level (c,) int32)``
# host arrays with c <= chunk, deterministically for a fixed (seed, n,
# chunk).  ``cb_values`` is the sampler's corr_beta lattice (what the
# engine certifies); ``windows`` its replay windows (base_idx b > 0 means
# windows[b - 1], resolved through the engine's replay_lookup).


def _identity_theta(K: int) -> np.ndarray:
    th = np.zeros(2 * K + 2, np.float64)
    th[K:2 * K] = 1.0
    th[2 * K] = 1.0
    return th


class GridSampler:
    """Deterministic grid over the (vol_mult, corr_beta) plane of the
    shock box — vol shifts/scales stay neutral.  The regime-stress
    slice a risk desk reads first, and the cheapest full-coverage
    smoke of the streaming machinery."""

    kind = "grid"

    def __init__(self, ball, K: int, *, n_vol: int = 32, n_corr: int = 32):
        if n_vol < 1 or n_corr < 1:
            raise ValueError("grid needs n_vol >= 1 and n_corr >= 1")
        self.ball = ball
        self.K = int(K)
        self.n_vol = int(n_vol)
        self.n_corr = int(n_corr)
        self.vol_values = np.linspace(ball.vol_mult_lo, ball.vol_mult_hi,
                                      self.n_vol)
        self.cb_values = np.linspace(ball.corr_beta_lo, ball.corr_beta_hi,
                                     self.n_corr)
        self.windows = ()
        self.n = self.n_vol * self.n_corr

    def blocks(self, chunk: int):
        ident = _identity_theta(self.K)
        for start in range(0, self.n, chunk):
            idx = np.arange(start, min(start + chunk, self.n))
            vi, ci = idx // self.n_corr, idx % self.n_corr
            th = np.tile(ident, (len(idx), 1))
            th[:, 2 * self.K] = self.vol_values[vi]
            th[:, 2 * self.K + 1] = self.cb_values[ci]
            yield (th, np.zeros(len(idx), np.int32), ci.astype(np.int32))

    def describe(self) -> dict:
        return {"kind": self.kind, "n": self.n, "n_vol": self.n_vol,
                "n_corr": self.n_corr, "ball": self.ball.to_dict()}


class UniformSampler:
    """Seeded uniform draws over the whole shock box, corr_beta
    quantized to ``cb_levels`` lattice points (the certification
    contract).  Byte-deterministic for a fixed (seed, n, chunk)."""

    kind = "uniform"

    def __init__(self, ball, K: int, n: int, *, seed: int = 0,
                 cb_levels: int = 33):
        if n < 1:
            raise ValueError("need n >= 1 scenarios")
        if cb_levels < 1:
            raise ValueError("need cb_levels >= 1")
        self.ball = ball
        self.K = int(K)
        self.n = int(n)
        self.seed = int(seed)
        self.cb_values = np.linspace(ball.corr_beta_lo, ball.corr_beta_hi,
                                     int(cb_levels))
        self.windows = ()

    def _draw(self, rng, c: int):
        K = self.K
        b = self.ball
        th = np.empty((c, 2 * K + 2), np.float64)
        th[:, :K] = rng.uniform(-b.shift_max, b.shift_max, (c, K))
        th[:, K:2 * K] = rng.uniform(1.0 - b.scale_range,
                                     1.0 + b.scale_range, (c, K))
        th[:, 2 * K] = rng.uniform(b.vol_mult_lo, b.vol_mult_hi, c)
        lv = rng.integers(0, len(self.cb_values), c).astype(np.int32)
        th[:, 2 * K + 1] = self.cb_values[lv]
        return th, lv

    def blocks(self, chunk: int):
        rng = np.random.default_rng(self.seed)
        done = 0
        while done < self.n:
            c = min(chunk, self.n - done)
            th, lv = self._draw(rng, c)
            done += c
            yield th, np.zeros(c, np.int32), lv

    def describe(self) -> dict:
        return {"kind": self.kind, "n": self.n, "seed": self.seed,
                "cb_levels": len(self.cb_values),
                "ball": self.ball.to_dict()}


class SobolSampler(UniformSampler):
    """Low-discrepancy Sobol' draws over the shock box (scipy.stats.qmc,
    scrambled with the seed).  Falls back to the seeded uniform stream
    when scipy's qmc module is unavailable — ``describe()`` records
    which engine actually ran, so manifests stay honest."""

    kind = "sobol"

    def __init__(self, ball, K: int, n: int, *, seed: int = 0,
                 cb_levels: int = 33):
        super().__init__(ball, K, n, seed=seed, cb_levels=cb_levels)
        try:
            from scipy.stats import qmc
            self._qmc = qmc.Sobol(d=2 * K + 2, scramble=True, seed=seed)
        except Exception:   # noqa: BLE001 — gate the optional dep
            self._qmc = None

    def blocks(self, chunk: int):
        if self._qmc is None:
            yield from super().blocks(chunk)
            return
        K, b = self.K, self.ball
        lo = np.asarray([-b.shift_max] * K + [1.0 - b.scale_range] * K
                        + [b.vol_mult_lo, 0.0])
        hi = np.asarray([b.shift_max] * K + [1.0 + b.scale_range] * K
                        + [b.vol_mult_hi, 1.0])
        done = 0
        while done < self.n:
            c = min(chunk, self.n - done)
            u = self._qmc.random(c)
            th = lo + u * (hi - lo)
            # last dim draws a LEVEL, not a value: quantize to the lattice
            lv = np.minimum((th[:, -1] * len(self.cb_values)).astype(np.int32),
                            len(self.cb_values) - 1)
            th[:, -1] = self.cb_values[lv]
            done += c
            yield th, np.zeros(c, np.int32), lv

    def describe(self) -> dict:
        d = super().describe()
        d["kind"] = self.kind
        d["qmc"] = "sobol" if self._qmc is not None else "uniform-fallback"
        return d


def monthly_replay_windows(dates) -> list:
    """One (start, end) replay window per calendar month present in the
    panel's own date labels — the auto-generated historical-replay
    library.  ``dates`` is any sequence numpy parses as datetime64[D]."""
    days = np.asarray(list(dates), dtype="datetime64[D]")
    if days.size == 0:
        return []
    months = days.astype("datetime64[M]")
    out = []
    for m in np.unique(months):
        in_m = days[months == m]
        out.append((str(in_m.min()), str(in_m.max())))
    return out


class ReplaySampler:
    """The historical-replay library as a sweep: one IDENTITY lane per
    window — each month's fitted covariance served back untouched, the
    streaming analog of a replay drill (compose with
    :func:`monthly_replay_windows`)."""

    kind = "replay"

    def __init__(self, windows, K: int):
        self.windows = tuple((str(a), str(b)) for a, b in windows)
        if not self.windows:
            raise ValueError("replay sweep needs at least one window")
        self.K = int(K)
        self.n = len(self.windows)
        self.cb_values = np.zeros(1)
        self.ball = None

    def blocks(self, chunk: int):
        ident = _identity_theta(self.K)
        for start in range(0, self.n, chunk):
            c = min(chunk, self.n - start)
            yield (np.tile(ident, (c, 1)),
                   np.arange(start + 1, start + 1 + c, dtype=np.int32),
                   np.zeros(c, np.int32))

    def describe(self) -> dict:
        return {"kind": self.kind, "n": self.n,
                "windows": [list(w) for w in self.windows]}


class _LocalSampler:
    """Internal: seeded uniform draws in a sub-box around refinement
    centers (one center per book), corr_beta snapped to a fresh local
    lattice.  Drives the dense local re-sweep after the gradient
    ascent."""

    kind = "local"

    def __init__(self, ball, centers, K: int, n_per: int, *, span: float,
                 seed: int, cb_levels: int = 9):
        self.ball = ball
        self.K = int(K)
        self.centers = np.asarray(centers, np.float64)   # (B, 2K+2)
        self.n_per = int(n_per)
        self.span = float(span)
        self.seed = int(seed)
        self.n = self.n_per * len(self.centers)
        self.windows = ()
        lo, hi = ball.bounds(K)
        self._lo = np.asarray(lo)
        self._hi = np.asarray(hi)
        cbs = self.centers[:, -1]
        half = span * (ball.corr_beta_hi - ball.corr_beta_lo)
        self.cb_values = np.unique(np.clip(
            np.concatenate([np.linspace(c - half, c + half, cb_levels)
                            for c in cbs]),
            ball.corr_beta_lo, ball.corr_beta_hi))

    def blocks(self, chunk: int):
        rng = np.random.default_rng((self.seed, 0x5EEB))
        width = self.span * (self._hi - self._lo)
        for center in self.centers:
            done = 0
            while done < self.n_per:
                c = min(chunk, self.n_per - done)
                th = center + rng.uniform(-1.0, 1.0,
                                          (c, len(center))) * width
                th = np.clip(th, self._lo, self._hi)
                # snap corr_beta to the certified local lattice
                lv = np.abs(th[:, -1:] - self.cb_values[None, :]).argmin(1)
                lv = lv.astype(np.int32)
                th[:, -1] = self.cb_values[lv]
                done += c
                yield th, np.zeros(c, np.int32), lv


# -- the streaming engine -----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SweepResult:
    """One sweep's full answer — fixed-size regardless of S.

    ``books``: per-portfolio dicts (label, base vol, top-k table with
    specs + hashes, histogram sketch); ``counts``: admission/offender
    tallies; ``refined``: per-book refinement blocks or None.
    """

    books: list
    counts: dict
    sampler: dict
    refined: list | None
    chunk: int
    chunk_bucket: int
    top_k: int
    bins: int
    hist_span: float
    seconds: float

    def to_dict(self) -> dict:
        return {
            "books": self.books,
            "counts": self.counts,
            "sampler": self.sampler,
            "refined": self.refined,
            "chunk": self.chunk,
            "chunk_bucket": self.chunk_bucket,
            "top_k": self.top_k,
            "bins": self.bins,
            "hist_span": self.hist_span,
        }


class SweepEngine:
    """Streaming million-scenario sweeps against one served covariance.

    Composes a :class:`ScenarioEngine` for base resolution, admission
    doctrine and the final replay round trip — a sweep is the same
    what-if surface at a different aspect ratio (constructor and
    ``from_risk_state`` guards match).

    Args mirror :class:`ScenarioEngine`; ``mesh`` optionally shards the
    chunk axis over the PR 11 ``('date', 'stock')`` device mesh (carry,
    books and base library stay replicated — the chunk axis is the only
    large one).
    """

    def __init__(self, cov, *, factor_names=None, staleness: int = 0,
                 dtype=None, replay_lookup=None, mesh=None):
        self._scen = ScenarioEngine(cov, factor_names=factor_names,
                                    staleness=staleness, dtype=dtype,
                                    replay_lookup=replay_lookup)
        self.K = self._scen.K
        self.dtype = self._scen.dtype
        self.cov = self._scen.cov
        self.factor_names = self._scen.factor_names
        self.factor_index = self._scen.factor_index
        self.staleness = self._scen.staleness
        self.replay_lookup = replay_lookup
        self.mesh = mesh
        self._chunk_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            axes = tuple(mesh.axis_names)
            self._chunk_sharding = NamedSharding(mesh, PartitionSpec(axes))

    @classmethod
    def from_risk_state(cls, state, meta=None, dtype=None,
                        replay_lookup=None, mesh=None):
        """Engine over a guarded checkpoint, with the
        ``ScenarioEngine.from_risk_state`` contract (factor names off
        the meta, refuse unguarded states)."""
        scen = ScenarioEngine.from_risk_state(state, meta, dtype=dtype,
                                              replay_lookup=replay_lookup)
        return cls(scen.cov, factor_names=scen.factor_names,
                   staleness=scen.staleness, dtype=scen.dtype,
                   replay_lookup=replay_lookup, mesh=mesh)

    # -- host certification ---------------------------------------------------
    def _stressed_corrs(self, base: np.ndarray,
                        cb_values: np.ndarray) -> np.ndarray:
        """(V, K, K) float64 stressed correlations of one base, one per
        corr_beta lattice level — EXACTLY the kernel's correlation math
        (same clip, same diag re-pin), evaluated at the compute-dtype
        value of each level."""
        var = np.diagonal(base).astype(np.float64)
        sigma = np.sqrt(np.maximum(var, 0))
        denom = np.outer(sigma, sigma)
        with np.errstate(invalid="ignore", divide="ignore"):
            corr = np.where(denom > 0, base.astype(np.float64) / denom, 0.0)
        eye = np.eye(self.K)
        corr = corr * (1.0 - eye) + eye
        cbs = np.asarray(cb_values, self.dtype).astype(np.float64)
        corr_s = np.clip(corr[None] * (1.0 + cbs[:, None, None]), -1.0, 1.0)
        return corr_s * (1.0 - eye) + eye

    def _certify(self, base_lib: np.ndarray, cb_values: np.ndarray):
        """``(lam_min, lam_max)`` — two (L, V) float64 arrays of the
        stressed correlations' extreme eigenvalues, one row per base,
        one column per corr_beta lattice level.  The Sylvester
        certificate: a lane at a level with ``lam_min > PSD_CERT_TOL``
        (plus the per-lane :data:`SWEEP_EIGH_GUARD` conditioning margin)
        skips the device eigh entirely."""
        L, V = len(base_lib), len(cb_values)
        lam_min = np.zeros((L, V))
        lam_max = np.zeros((L, V))
        for li, base in enumerate(base_lib):
            corr_s = self._stressed_corrs(base, cb_values)
            lam = np.linalg.eigvalsh(corr_s)    # batched host eigh, (V, K)
            lam_min[li] = lam[:, 0]
            lam_max[li] = lam[:, -1]
        return lam_min, lam_max

    # -- the streaming loop ---------------------------------------------------
    def sweep(self, portfolios, sampler, *, chunk: int = 8192,
              top_k: int = 16, bins: int = 64, hist_span: float = 8.0,
              labels=None, ball=None, refine: dict | None = None,
              offender_chunk: int = OFFENDER_CHUNK) -> SweepResult:
        """Stream every scenario the sampler generates through the
        aggregate carry; optionally refine with reverse-stress ascent.

        Args:
          portfolios: (B, K) factor-exposure rows (or one (K,) vector).
          sampler: a spec generator (Grid/Uniform/Sobol/ReplaySampler).
          chunk: scenarios per donated jit call (padded to its bucket).
            One dispatch + one transfer per chunk; inside the jit the
            kernel scans ``SWEEP_SUBCHUNK``-sized slices so the stressed
            stack stays cache-resident however large the chunk is.
          top_k: worst entries kept per book.
          bins: histogram bins; the sketch spans ``[0, hist_span *
            base_vol)`` per book with a saturating top bin.
          labels: book labels for the manifest (default ``book{i}``).
          ball: admissibility box for refinement seeds/bounds (defaults
            to the sampler's, else the standard ``ShockBall``).
          refine: None to skip, or options for the grad-guided loop:
            ``steps`` / ``step`` (ascent schedule), ``n_local`` (dense
            local draws per book), ``local_span`` (sub-box half-width as
            a fraction of each axis), ``seed``, ``ball`` (override box
            for the ascent/local stage — lets a tame coarse sampler
            pair with the full preset-covering ``ShockBall``).
          offender_chunk: exact-path flush rung for uncertified lanes.

        Returns a :class:`SweepResult`; obs counters under
        ``mfm_sweep_*`` record the run.
        """
        t0 = time.perf_counter()
        xs = np.atleast_2d(np.asarray(portfolios, self.dtype))
        if xs.ndim != 2 or xs.shape[1] != self.K:
            raise ValueError(f"portfolios must be (B, {self.K}), got "
                             f"{xs.shape}")
        B = xs.shape[0]
        labels = ([f"book{i}" for i in range(B)] if labels is None
                  else [str(x) for x in labels])
        if len(labels) != B:
            raise ValueError(f"{len(labels)} labels for B={B} books")
        if ball is None:
            ball = getattr(sampler, "ball", None)
        if ball is None:
            from mfm_tpu.grad.engine import ShockBall
            ball = ShockBall()
        chunk = int(chunk)
        if chunk < 1:
            raise ValueError("chunk must be >= 1")

        # resolve the base library host-side, once: row 0 is the served
        # cov; unresolvable replay windows keep a row of None and every
        # lane pointing at one is rejected (never silently retargeted)
        windows = list(getattr(sampler, "windows", ()) or ())
        base_lib, window_problems = [self.cov], []
        for w in windows:
            resolved = None
            if self.replay_lookup is None:
                window_problems.append(f"{w!r}: engine has no history")
            else:
                try:
                    resolved = self.replay_lookup(*w)
                    if resolved is None:
                        window_problems.append(f"{w!r}: not in the "
                                               "engine's history")
                except Exception as e:   # noqa: BLE001 — reject, don't die
                    window_problems.append(f"{w!r}: {e}")
            base_lib.append(None if resolved is None
                            else np.asarray(resolved, self.dtype))
        lib_rows = [i for i, b in enumerate(base_lib) if b is not None]
        row_of = np.full(len(base_lib), -1, np.int32)
        row_of[lib_rows] = np.arange(len(lib_rows), dtype=np.int32)
        lib_np = np.stack([base_lib[i] for i in lib_rows])
        sigma_lib = np.sqrt(np.maximum(
            np.diagonal(lib_np, axis1=1, axis2=2), 0)).astype(self.dtype)

        cb_values = np.asarray(sampler.cb_values, np.float64)
        cert = self._certify(lib_np, cb_values)

        # deterministic sketch edges: [0, span * base vol) per book
        vol0 = np.sqrt(np.einsum("bi,ij,bj->b",
                                 xs.astype(np.float64),
                                 self.cov.astype(np.float64),
                                 xs.astype(np.float64)))
        lo = np.zeros(B, self.dtype)
        width = np.maximum(hist_span * vol0 / bins,
                           np.finfo(self.dtype).tiny).astype(self.dtype)

        dev = {
            "lib": self._put(jnp.asarray(lib_np)),
            "xs": self._put(jnp.asarray(xs)),
            "lo": self._put(jnp.asarray(lo)),
            "width": self._put(jnp.asarray(width)),
        }
        th_w = 2 * self.K + 2
        carry = _init_sweep_carry(B, int(top_k), th_w, int(bins),
                                  self.dtype)
        bucket = bucket_for(chunk)

        state = {"src": 0, "chunks": 0, "off_n": 0, "off_total": 0,
                 "off_th": [], "off_row": [], "off_src": []}
        for th64, bidx, lv in sampler.blocks(chunk):
            carry = self._fold_block(carry, dev, th64, bidx, lv, cert,
                                     row_of, sigma_lib, bucket, state)
            while state["off_n"] >= offender_chunk:
                carry = self._flush_offenders(carry, dev, lib_np, state,
                                              offender_chunk)
        n_coarse = state["src"]

        refined_blocks = None
        if refine is not None:
            carry, refined_blocks = self._refine(
                carry, dev, lib_np, xs, ball, refine, chunk, state=state,
                offender_chunk=offender_chunk)
        if state["off_n"]:
            carry = self._flush_offenders(carry, dev, lib_np, state,
                                          state["off_n"])

        # ONE host transfer for the whole sweep: the fixed-size carry
        host = [np.asarray(leaf) for leaf in carry]
        top_vol, top_theta, top_src, top_base, hist, counts = host
        n_ok, n_rejected, n_projected = (int(x) for x in counts)
        seconds = time.perf_counter() - t0

        books = self._book_tables(labels, xs, vol0, top_vol, top_theta,
                                  top_src, top_base, hist, lo, width,
                                  lib_rows, windows, n_coarse)
        if refined_blocks is not None:
            for b, blk in zip(books, refined_blocks):
                blk["vol_final_top1"] = b["top"][0]["vol"] if b["top"] \
                    else None
                blk["improved"] = (blk["vol_final_top1"] is not None
                                  and blk["vol_final_top1"]
                                  >= blk["vol_coarse_top1"])
        counts_d = {
            "n_scenarios": n_ok + n_rejected,
            "n_ok": n_ok,
            "n_rejected": n_rejected,
            "n_psd_projected": n_projected,
            "n_offenders": state["off_total"],
            "n_chunks": state["chunks"],
            "n_coarse": n_coarse,
        }
        _obs.record_sweep(n_ok, n_rejected, state["chunks"], seconds)
        if state["off_total"]:
            _obs.record_sweep_offenders(state["off_total"])
        if n_projected:
            _obs.record_sweep_projections(n_projected)
        sampler_d = dict(sampler.describe())
        if window_problems:
            sampler_d["window_problems"] = window_problems
        return SweepResult(books=books, counts=counts_d, sampler=sampler_d,
                           refined=refined_blocks, chunk=chunk,
                           chunk_bucket=bucket, top_k=int(top_k),
                           bins=int(bins), hist_span=float(hist_span),
                           seconds=seconds)

    # -- one block through the hot path --------------------------------------
    def _put(self, arr, chunk_axis: bool = False):
        if self._chunk_sharding is not None and chunk_axis:
            return jax.device_put(arr, self._chunk_sharding)
        return arr

    def _fold_block(self, carry, dev, th64, bidx, lv, cert, row_of,
                    sigma_lib, bucket, state, force_offender=None):
        """Admit, certify and fold one sampler block; buffer offenders."""
        K = self.K
        c = len(th64)
        th = np.asarray(th64, self.dtype)
        bidx = np.asarray(bidx, np.int32)
        finite = np.isfinite(th).all(axis=1)
        valid = (finite
                 & (th[:, K:2 * K] >= 0).all(axis=1)
                 & (th[:, 2 * K] > 0)
                 & (th[:, 2 * K + 1] > -1))
        in_lib = (bidx >= 0) & (bidx < len(row_of))
        row = row_of[np.where(in_lib, bidx, 0)]
        valid &= in_lib & (row >= 0)
        row = np.where(row >= 0, row, 0).astype(np.int32)

        ident = ((th[:, :K] == 0).all(axis=1)
                 & (th[:, K:2 * K] == 1).all(axis=1)
                 & (th[:, 2 * K] == 1) & (th[:, 2 * K + 1] == 0))
        lam_min, lam_max = cert
        lvc = np.clip(lv, 0, lam_min.shape[1] - 1)
        lam_lo, lam_hi = lam_min[row, lvc], lam_max[row, lvc]
        sig_s = np.maximum(sigma_lib[row] * th[:, K:2 * K]
                           + th[:, :K], 0) * th[:, 2 * K:2 * K + 1]
        s_lo = sig_s.min(axis=1).astype(np.float64)
        s_hi = sig_s.max(axis=1).astype(np.float64)
        eps = float(np.finfo(self.dtype).eps)
        certified = ((lam_lo > PSD_CERT_TOL)
                     & (lam_lo * s_lo ** 2
                        > SWEEP_EIGH_GUARD * eps * lam_hi * s_hi ** 2))
        clean = valid & (ident | certified)
        if force_offender is not None:
            clean &= ~force_offender
        offender = valid & ~clean
        reject = ~valid

        src = state["src"] + np.arange(c, dtype=np.int32)
        state["src"] += c
        if offender.any():
            state["off_th"].append(th[offender])
            state["off_row"].append(row[offender])
            state["off_src"].append(src[offender])
            state["off_n"] += int(offender.sum())
            state["off_total"] += int(offender.sum())

        if not clean.any() and not reject.any():
            # nothing for the hot path to fold (e.g. an all-offender
            # ascent block) — the buffered lanes merge at flush time
            return carry

        pad = bucket - c
        if pad:
            th = np.concatenate([th, np.zeros((pad, th.shape[1]),
                                              self.dtype)])
            row = np.concatenate([row, np.zeros(pad, np.int32)])
            src = np.concatenate([src, np.full(pad, -1, np.int32)])
            clean = np.concatenate([clean, np.zeros(pad, bool)])
            reject = np.concatenate([reject, np.zeros(pad, bool)])
            ident = np.concatenate([ident, np.zeros(pad, bool)])
        state["chunks"] += 1
        return sweep_chunk(
            carry, dev["lib"], dev["xs"],
            self._put(jnp.asarray(th), chunk_axis=True),
            self._put(jnp.asarray(row), chunk_axis=True),
            self._put(jnp.asarray(src), chunk_axis=True),
            self._put(jnp.asarray(clean), chunk_axis=True),
            self._put(jnp.asarray(reject), chunk_axis=True),
            self._put(jnp.asarray(ident & clean), chunk_axis=True),
            dev["lo"], dev["width"])

    def _flush_offenders(self, carry, dev, lib_np, state, m):
        """Run m buffered offender lanes through the EXACT serving path
        (scenario_batch's per-lane eigh gate) and merge their true
        post-projection vols into the carry."""
        th = np.concatenate(state["off_th"])
        row = np.concatenate(state["off_row"])
        src = np.concatenate(state["off_src"])
        state["off_th"] = [th[m:]] if len(th) > m else []
        state["off_row"] = [row[m:]] if len(row) > m else []
        state["off_src"] = [src[m:]] if len(src) > m else []
        state["off_n"] = max(len(th) - m, 0)
        th, row, src = th[:m], row[:m], src[:m]

        K = self.K
        bucket = bucket_for(m)
        pad = bucket - m
        take = np.concatenate([np.ones(m, bool), np.zeros(pad, bool)])
        if pad:
            th = np.concatenate([th, np.tile(
                _identity_theta(K).astype(self.dtype), (pad, 1))])
            row = np.concatenate([row, np.zeros(pad, np.int32)])
            src = np.concatenate([src, np.full(pad, -1, np.int32)])
        covs, projected, _ = scenario_batch(
            jnp.asarray(lib_np[row]),
            jnp.asarray(th[:, :K]), jnp.asarray(th[:, K:2 * K]),
            jnp.asarray(th[:, 2 * K]), jnp.asarray(th[:, 2 * K + 1]),
            jnp.asarray(~take))
        state["chunks"] += 1
        return sweep_merge(carry, covs, dev["xs"], jnp.asarray(th),
                           jnp.asarray(src), jnp.asarray(row),
                           jnp.asarray(take), projected,
                           dev["lo"], dev["width"])

    # -- grad-guided refinement ----------------------------------------------
    def _refine(self, carry, dev, lib_np, xs, ball, refine, chunk, *,
                state, offender_chunk):
        """Coarse top-k thetas -> reverse-stress ascent -> dense local
        re-sweep, all merged back into the SAME carry (so the final
        worst can only improve on the coarse top-1)."""
        from mfm_tpu.grad.engine import REVERSE_STEP, REVERSE_STEPS
        from mfm_tpu.grad.reverse import reverse_stress_batch
        steps = int(refine.get("steps", REVERSE_STEPS))
        step = float(refine.get("step", REVERSE_STEP))
        n_local = int(refine.get("n_local", 512))
        local_span = float(refine.get("local_span", 0.05))
        seed = int(refine.get("seed", 0))
        ball = refine.get("ball") or ball

        K = self.K
        B, k = xs.shape[0], int(np.asarray(carry[0]).shape[1])
        top_theta = np.asarray(carry[1])
        top_src = np.asarray(carry[2])
        top_base = np.asarray(carry[3])
        coarse_top1 = np.asarray(carry[0])[:, 0].astype(np.float64)

        # seeds: each book's top thetas over the SHARED base (ascent runs
        # against self.cov; replay-based entries keep their coarse rank
        # but cannot seed a gradient against a different base)
        ident = _identity_theta(K).astype(self.dtype)
        P = B * k
        theta0 = np.tile(ident, (P, 1))
        xs_rep = np.repeat(xs, k, axis=0)
        seed_counts = []
        for b in range(B):
            mask = (top_src[b] >= 0) & (top_base[b] == 0)
            seed_counts.append(int(mask.sum()))
            for j in np.nonzero(mask)[0]:
                theta0[b * k + j] = top_theta[b, j]
        bucket = bucket_for(P)
        pad = bucket - P
        if pad:
            theta0 = np.concatenate([theta0, np.tile(ident, (pad, 1))])
            xs_rep = np.concatenate([xs_rep, np.zeros((pad, K),
                                                      self.dtype)])
        lo_b, hi_b = ball.bounds(K)
        theta_star, vol_star, _ = reverse_stress_batch(
            jnp.asarray(self.cov), jnp.asarray(xs_rep),
            jnp.asarray(theta0.astype(self.dtype)),
            jnp.asarray(np.asarray(lo_b, self.dtype)),
            jnp.asarray(np.asarray(hi_b, self.dtype)),
            jnp.asarray(np.asarray(step, self.dtype)),
            jnp.asarray(steps, jnp.int32))
        theta_star = np.asarray(theta_star)[:P]
        vol_star = np.asarray(vol_star)[:P].astype(np.float64)

        # fold the ascent endpoints through the EXACT path (their
        # corr_beta is continuous — no lattice certificate applies)
        row_of = np.arange(len(lib_np), dtype=np.int32)
        sigma_lib = np.sqrt(np.maximum(
            np.diagonal(lib_np, axis1=1, axis2=2), 0)).astype(self.dtype)
        no_cert = (np.zeros((len(lib_np), 1)), np.ones((len(lib_np), 1)))
        carry = self._fold_block(
            carry, dev, theta_star.astype(np.float64),
            np.zeros(P, np.int32), np.zeros(P, np.int32), no_cert,
            row_of, sigma_lib, bucket_for(P), state,
            force_offender=np.ones(P, bool))

        # dense local re-sweep around each book's best refined theta
        centers = np.empty((B, 2 * K + 2), np.float64)
        ascent_best = np.empty(B, np.float64)
        for b in range(B):
            lane = b * k + int(np.argmax(vol_star[b * k:(b + 1) * k]))
            centers[b] = theta_star[lane]
            ascent_best[b] = float(vol_star[lane])
        local = _LocalSampler(ball, centers, K, n_local, span=local_span,
                              seed=seed)
        cert = self._certify(lib_np, local.cb_values)
        bucket = bucket_for(min(chunk, max(local.n_per, 1)))
        for th64, bidx, lv in local.blocks(min(chunk, bucket)):
            carry = self._fold_block(carry, dev, th64, bidx, lv, cert,
                                     row_of, sigma_lib, bucket, state)
            while state["off_n"] >= offender_chunk:
                carry = self._flush_offenders(carry, dev, lib_np, state,
                                              offender_chunk)

        blocks = []
        for b in range(B):
            spec = theta_to_spec(centers[b], self.factor_names,
                                 f"sweep-refined-{b}")
            admissible = (ball.contains(centers[b], K)
                          and not validate_spec(spec, self.factor_names)
                          and self._stressed_psd(centers[b]))
            blocks.append({
                "seed_count": seed_counts[b],
                "ascent_steps": steps,
                "n_local": n_local,
                "local_span": local_span,
                "vol_coarse_top1": float(coarse_top1[b]),
                "vol_ascent_best": float(ascent_best[b]),
                "theta_spec": spec.to_dict(),
                "theta_spec_hash": spec.spec_hash(),
                "admissible": bool(admissible),
            })
        return carry, blocks

    def _stressed_psd(self, theta) -> bool:
        """Host check mirroring ``GradEngine._stressed_psd``: the refined
        worst case, pushed through the REAL serving stress + gated
        projection, stays PSD at compute dtype."""
        from mfm_tpu.scenario.kernel import psd_project, stress_cov
        K = self.K
        t = jnp.asarray(np.asarray(theta, self.dtype))
        cov_p, _, _ = psd_project(stress_cov(
            jnp.asarray(self.cov), t[:K], t[K:2 * K], t[2 * K],
            t[2 * K + 1]))
        lam = np.linalg.eigvalsh(np.asarray(cov_p, np.float64))
        eps = float(np.finfo(self.dtype).eps)
        return bool(lam[0] >= -K * eps * max(lam[-1], 0.0))

    # -- result assembly ------------------------------------------------------
    def _book_tables(self, labels, xs, vol0, top_vol, top_theta, top_src,
                     top_base, hist, lo, width, lib_rows, windows,
                     n_coarse):
        books = []
        neg = np.finfo(self.dtype).min
        for b, label in enumerate(labels):
            entries = []
            for j in range(top_vol.shape[1]):
                if top_src[b, j] < 0 or top_vol[b, j] <= neg / 2:
                    continue
                orig = lib_rows[int(top_base[b, j])]
                window = list(windows[orig - 1]) if orig > 0 else None
                spec = theta_to_spec(
                    top_theta[b, j], self.factor_names,
                    f"sweep-{int(top_src[b, j])}",
                    replay=tuple(window) if window else None)
                src_i = int(top_src[b, j])
                entries.append({
                    "rank": len(entries),
                    "vol": float(top_vol[b, j]),
                    "src": src_i,
                    "origin": "coarse" if src_i < n_coarse else "refined",
                    "base_window": window,
                    "spec": spec.to_dict(),
                    "spec_hash": spec.spec_hash(),
                })
            books.append({
                "label": label,
                "vol_base": float(vol0[b]),
                "top": entries,
                "hist": {
                    "lo": float(lo[b]),
                    "bin_width": float(width[b]),
                    "counts": [int(x) for x in hist[b]],
                },
            })
        return books

    # -- dominance vs the preset catalog --------------------------------------
    def preset_dominance(self, result: SweepResult, portfolios) -> list:
        """Per-book check that the sweep's worst case dominates every
        preset drill, through the REAL materializing engine (the presets
        run as ordinary forward scenarios).  Returns one dict per book;
        the manifest embeds it and bench asserts it."""
        xs = np.atleast_2d(np.asarray(portfolios, np.float64))
        drills = self._scen.run([PRESETS[n] for n in sorted(PRESETS)])
        out = []
        for b, book in enumerate(result.books):
            worst = book["top"][0]["vol"] if book["top"] else None
            rows = []
            for r in drills:
                if not r.ok:
                    rows.append({"preset": r.spec.name, "vol": None,
                                 "dominated": False})
                    continue
                v = float(np.sqrt(xs[b] @ np.asarray(r.cov, np.float64)
                                  @ xs[b]))
                rows.append({
                    "preset": r.spec.name,
                    "vol": v,
                    "dominated": bool(worst is not None
                                      and worst >= v * (1 - 1e-5)),
                })
            out.append({"label": book["label"], "vol_worst": worst,
                        "presets": rows,
                        "dominates_all": all(r["dominated"] for r in rows)})
        return out


# -- the sweep manifest -------------------------------------------------------

def sweep_manifest_path_for(artifact_dir: str) -> str:
    """The sweep-manifest slot inside an artifact directory."""
    return os.path.join(artifact_dir, SWEEP_MANIFEST_NAME)


def build_sweep_manifest(result: SweepResult, *, stamp_json=None,
                         backend=None, staleness: int | None = None,
                         dominance: list | None = None,
                         summary: dict | None = None) -> dict:
    """Assemble the manifest dict (pure; :func:`write_sweep_manifest`
    persists).  Deterministic except for ``summary`` (the obs block) —
    byte-comparing two manifests modulo ``summary`` IS the replay check
    the ``sweep-kill-mid-stream`` chaos plan runs."""
    return {
        "schema_version": SWEEP_MANIFEST_SCHEMA_VERSION,
        "kind": "sweep_manifest",
        "config_stamp": stamp_json,
        "backend": backend,
        "staleness": staleness,
        "sweep": result.to_dict(),
        "dominance": dominance,
        "summary": summary or {},
    }


def write_sweep_manifest(path: str, manifest: dict) -> str:
    """Atomic write (tmp -> fsync -> chaos point -> rename -> dir fsync);
    ``path`` may be the artifact directory.  Returns the final path."""
    if os.path.isdir(path):
        path = os.path.join(path, SWEEP_MANIFEST_NAME)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=1, sort_keys=True, default=str)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    chaos_point("sweep_manifest.after_tmp", path)
    os.replace(tmp, path)
    try:
        fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - exotic filesystems
        pass
    return path


def read_sweep_manifest(path: str) -> dict:
    """Load + schema-check a sweep manifest (``path`` may be its
    directory).  Raises :class:`SweepManifestError` on unreadable / torn
    JSON or schema/kind mismatch."""
    if os.path.isdir(path):
        path = os.path.join(path, SWEEP_MANIFEST_NAME)
    try:
        with open(path, encoding="utf-8") as fh:
            m = json.load(fh)
    except OSError as e:
        raise SweepManifestError(
            f"{path}: unreadable sweep manifest ({e})") from e
    except ValueError as e:
        raise SweepManifestError(
            f"{path}: sweep manifest is not valid JSON ({e}) — torn "
            "write?") from e
    if not isinstance(m, dict):
        raise SweepManifestError(f"{path}: sweep manifest is not a JSON "
                                 "object")
    if m.get("schema_version") != SWEEP_MANIFEST_SCHEMA_VERSION:
        raise SweepManifestError(
            f"{path}: sweep manifest schema_version "
            f"{m.get('schema_version')!r} unsupported (expected "
            f"{SWEEP_MANIFEST_SCHEMA_VERSION})")
    if m.get("kind") != "sweep_manifest":
        raise SweepManifestError(
            f"{path}: kind {m.get('kind')!r} is not a sweep manifest")
    if not isinstance(m.get("sweep"), dict):
        raise SweepManifestError(f"{path}: sweep manifest has no sweep "
                                 "block")
    return m


def audit_sweep_manifest(path: str) -> tuple:
    """Deep audit for ``mfm-tpu doctor --scenarios``.

    Returns ``(problems, warnings)``.  Problems: count fields that don't
    add up, per-book top tables out of order or with spec hashes that
    don't recompute from the embedded spec, histograms whose mass
    disagrees with ``n_ok``, refinement blocks claiming improvement the
    entries contradict.  Warnings: rejected lanes, unresolvable replay
    windows, refined worst cases that failed admissibility.
    """
    m = read_sweep_manifest(path)
    problems, warnings = [], []
    sw = m["sweep"]
    counts = sw.get("counts", {})
    n_ok = counts.get("n_ok")
    if counts.get("n_scenarios") != (counts.get("n_ok", 0)
                                     + counts.get("n_rejected", 0)):
        problems.append("counts: n_scenarios != n_ok + n_rejected "
                        f"({counts})")
    if counts.get("n_rejected"):
        warnings.append(f"{counts['n_rejected']} lane(s) rejected")
    for wp in (sw.get("sampler", {}).get("window_problems") or ()):
        warnings.append(f"replay window unresolved: {wp}")
    for bi, book in enumerate(sw.get("books", ())):
        label = f"books[{bi}]"
        hist = book.get("hist", {})
        mass = sum(hist.get("counts", ()))
        if n_ok is not None and mass != n_ok:
            problems.append(f"{label}: histogram mass {mass} != n_ok "
                            f"{n_ok}")
        prev = None
        for e in book.get("top", ()):
            if prev is not None and e["vol"] > prev:
                problems.append(f"{label}: top table out of order at "
                                f"rank {e.get('rank')}")
            prev = e["vol"]
            try:
                spec = ScenarioSpec.from_dict(e["spec"])
            except (ValueError, TypeError, KeyError, IndexError) as exc:
                problems.append(f"{label} rank {e.get('rank')}: embedded "
                                f"spec does not parse ({exc})")
                continue
            if spec.spec_hash() != e.get("spec_hash"):
                problems.append(
                    f"{label} rank {e.get('rank')}: spec hash mismatch — "
                    f"recorded {str(e.get('spec_hash'))[:12]}…, recomputed "
                    f"{spec.spec_hash()[:12]}…")
    for bi, blk in enumerate(sw.get("refined") or ()):
        label = f"refined[{bi}]"
        final = blk.get("vol_final_top1")
        coarse = blk.get("vol_coarse_top1")
        if blk.get("improved") and final is not None and coarse is not None \
                and final < coarse:
            problems.append(f"{label}: claims improved but final "
                            f"{final} < coarse {coarse}")
        if not blk.get("admissible", True):
            warnings.append(f"{label}: refined worst case failed "
                            "admissibility")
    dom = m.get("dominance")
    if dom:
        for row in dom:
            if not row.get("dominates_all"):
                warnings.append(f"book {row.get('label')!r} does not "
                                "dominate every preset drill")
    return problems, warnings
