"""The batched scenario kernel: S covariance shocks in ONE donated jit.

Every scenario kind :mod:`mfm_tpu.scenario.spec` can express reduces, by
the time it reaches the device, to the same lane shape: a base covariance
(what world the shock applies to — today's served matrix, a historical
replay, a quarantine counterfactual) plus four dense shock operands.  The
kernel vmaps one lane function over the S axis, so a batch of S scenarios
IS S independent single runs:

- every per-lane op is elementwise or a within-lane contraction (the
  eigendecomposition and its reconstruction) — nothing contracts across
  the S axis, so lane i's bytes cannot depend on its batchmates;
- the identity lane is a ``jnp.where`` passthrough of the UNTOUCHED base
  covariance, not an algebraic no-op (``cov / sigma sigma' * sigma
  sigma'`` is not bitwise-stable) — the identity scenario is
  bitwise-equal to the unshocked baseline by construction.

Those two properties are the subsystem's correctness anchor
(tests/test_scenario.py proves both; tools/faultinject.py's
``scenario-poison-spec`` plan re-proves lane isolation under rejected
batchmates).

Lane math, in order (PAPER.md's USE4 vocabulary):

1. split the base covariance into vols and correlations,
2. per-factor vol shocks ``sigma' = max(sigma * scale + shift, 0)``,
3. the vol-regime multiplier override ``sigma' *= vol_mult`` (the
   scenario analog of the lambda_F series of stage 4),
4. correlation stress: off-diagonals scaled by ``1 + corr_beta`` and
   clipped to [-1, 1] (corr-meltup / diversification-collapse drills),
5. gated PSD projection: eigendecompose, clamp eigenvalues to a small
   relative floor, reconstruct — only where the stressed matrix went
   indefinite (the clip in step 4 can break PSD-ness; a projected lane is
   flagged so obs/ can count activations).

Shapes are padded to geometric S-buckets by the engine (the query-engine
bucket discipline, serve/query.py), so the steady state holds <= 1
compile per bucket — ``assert_max_compiles`` enforced in tests and bench.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def stress_cov(cov, shift, scale, vol_mult, corr_beta):
    """Steps 1-4 of the lane math: the stressed covariance BEFORE the PSD
    gate.  Shared by the serving kernel below and by the grad subsystem
    (:mod:`mfm_tpu.grad`), and differentiable w.r.t. every SHOCK operand
    (shift / scale / vol_mult / corr_beta).  ``cov`` is a constant under
    every grad surface — the vol split divides by ``outer(sigma, sigma)``
    inside a ``jnp.where``, which is only vjp-safe for cotangents that
    never reach the base-covariance branch.

    Args mirror :func:`_one_scenario`; returns ``cov_s (K, K)``.
    """
    dtype = cov.dtype
    K = cov.shape[0]
    eye = jnp.eye(K, dtype=dtype)
    one = jnp.asarray(1.0, dtype)

    var = jnp.diagonal(cov)
    sigma = jnp.sqrt(jnp.maximum(var, 0))
    denom = jnp.outer(sigma, sigma)
    corr = jnp.where(denom > 0, cov / denom, jnp.zeros((), dtype))
    corr = corr * (one - eye) + eye
    corr_s = jnp.clip(corr * (one + corr_beta), -one, one)
    corr_s = corr_s * (one - eye) + eye
    sigma_s = jnp.maximum(sigma * scale + shift, 0) * vol_mult
    return corr_s * jnp.outer(sigma_s, sigma_s)


def psd_project(cov_s):
    """Step 5, the gated PSD projection, in its GRAD-SAFE form.

    Forward outputs are value-identical to the serving gate inlined in
    :func:`_one_scenario` (same eigh primitive, same clamp floor, same
    reconstruction — when the gate fires the eigh input is bitwise
    ``cov_s``, when it doesn't the output IS ``cov_s``), but the gating is
    restructured so reverse-mode AD through it stays finite:

    - the gate value comes from ``eigvalsh(stop_gradient(cov_s))`` — the
      gate is a DECISION, not a differentiable quantity, and eigh's vjp on
      a matrix with (near-)repeated eigenvalues divides by ``w_i - w_j``;
    - the eigh whose vectors rebuild the projection runs on
      ``where(needs, cov_s, GENERIC)`` with GENERIC a fixed matrix with
      well-separated eigenvalues (diag(1..K)), so when the projection is
      NOT selected the zero cotangent flowing into the unselected branch
      multiplies finite eigh-vjp factors instead of the inf/NaN a
      degenerate ``cov_s`` would produce (the classic where-NaN trap).

    The serving kernel keeps its single-eigh inline gate (this form costs
    a second eigendecomposition — the gate eigh and the projection eigh —
    which the forward-only hot path does not want to pay); the grad
    subsystem composes THIS function.  tests/test_grad.py pins the
    forward parity between the two.

    Returns ``(cov_psd, needs, min_eig)`` exactly like the inline gate.
    """
    dtype = cov_s.dtype
    K = cov_s.shape[0]
    w_gate = jnp.linalg.eigvalsh(lax.stop_gradient(cov_s))
    min_eig = w_gate[0]
    needs = min_eig < 0
    generic = jnp.diag(jnp.arange(1, K + 1, dtype=jnp.int32).astype(dtype))
    w, V = jnp.linalg.eigh(jnp.where(needs, cov_s, generic))
    floor = jnp.maximum(w[-1], 0) * (K * jnp.finfo(dtype).eps)
    w_cl = jnp.maximum(w, floor)
    proj = (V * w_cl) @ V.T
    proj = 0.5 * (proj + proj.T)
    return jnp.where(needs, proj, cov_s), needs, min_eig


def _one_scenario(cov, shift, scale, vol_mult, corr_beta, passthrough):
    """Shock ONE covariance lane; vmapped over S by :func:`scenario_batch`.

    Args:
      cov: (K, K) base covariance (compute dtype).
      shift: (K,) additive vol deltas (0 = untouched).
      scale: (K,) multiplicative vol scales (1 = untouched).
      vol_mult: scalar vol-regime multiplier override (1 = untouched).
      corr_beta: scalar off-diagonal stress (0 = untouched; rho' =
        clip(rho * (1 + corr_beta), -1, 1)).
      passthrough: scalar bool — True serves ``cov`` back bitwise-untouched
        (identity scenarios, rejected specs, pad lanes).

    Returns ``(cov_out (K, K), psd_projected bool, min_eig_stressed)``
    where ``min_eig_stressed`` is the smallest eigenvalue of the stressed
    matrix BEFORE projection (the audit number the manifest records).
    """
    dtype = cov.dtype
    K = cov.shape[0]
    cov_s = stress_cov(cov, shift, scale, vol_mult, corr_beta)

    # gated PSD projection.  The eigh runs unconditionally (the gate needs
    # min_eig and K is small); the clamp floor is a small RELATIVE floor —
    # eigenvalues of the reconstructed matrix differ from the clamped ones
    # by O(eps * ||cov||), so clamping at exactly 0 could leave the result
    # indefinite at compute dtype.  K * eps * lambda_max dominates that
    # reconstruction error, keeping min-eig >= 0 at compute dtype.
    w, V = jnp.linalg.eigh(cov_s)
    min_eig = w[0]
    floor = jnp.maximum(w[-1], 0) * (K * jnp.finfo(dtype).eps)
    w_cl = jnp.maximum(w, floor)
    proj = (V * w_cl) @ V.T
    proj = 0.5 * (proj + proj.T)
    needs = min_eig < 0
    cov_out = jnp.where(needs, proj, cov_s)
    cov_out = jnp.where(passthrough, cov, cov_out)
    return cov_out, needs & ~passthrough, jnp.where(passthrough,
                                                    jnp.zeros((), dtype),
                                                    min_eig)


# Donated jit for the whole batch: every operand is freshly assembled per
# run by the engine (base covs resolved host-side, shock vectors densified
# from the specs).  Only the operands whose shape+dtype an output can
# actually alias are donated — cov (S, K, K) into cov_out and one (S,)
# float into min_eig_stressed; donating the rest would just warn.  The jit
# keys on the padded bucket shape only — <= 1 compile per S-bucket in
# steady state.
@partial(jax.jit, donate_argnums=(0, 3))
def scenario_batch(base_cov, shift, scale, vol_mult, corr_beta, passthrough):
    """Shock S covariance lanes in one compiled program.

    Args are the (S, ...) stacks of :func:`_one_scenario`'s operands.
    Returns ``(covs (S, K, K), psd_projected (S,), min_eig_stressed (S,))``.
    """
    return jax.vmap(_one_scenario)(base_cov, shift, scale, vol_mult,
                                   corr_beta, passthrough)


# -- streaming sweep kernels (scenario/sweep.py) ------------------------------
#
# The sweep engine answers "worst portfolio vol over 10^6 shock worlds"
# WITHOUT ever materializing an (S, K, K) stack: each donated call folds a
# chunk of C stressed lanes into a fixed-size aggregate carry (per-book
# top-k worst table + fixed-bin vol histogram + counters).  The decisive
# perf property is that the hot chunk kernel does NO eigendecomposition:
# PSD-ness of the stressed matrix ``diag(sigma_s) C'(cb) diag(sigma_s)`` is
# congruence-invariant (Sylvester's law of inertia) whenever sigma_s is
# strictly positive, so it depends ONLY on the clipped stressed correlation
# ``C'(cb)`` — a pure function of the scalar corr_beta.  The host quantizes
# corr_beta to a small lattice, certifies each (base, level) pair once with
# a cheap K x K eigh, and routes the rare uncertified lanes ("offenders")
# through the exact :func:`scenario_batch` path + :func:`sweep_merge`.


def book_vols(covs, xs):
    """(B, C) portfolio vols of every book against every lane covariance.

    Deliberately un-jitted (like ``portfolio_vol`` itself): both sweep
    jits inline it, and the parity tests jit it standalone over
    MATERIALIZED engine covs — the double-vmapped contraction lowers to
    the same dot either way, which is what makes the streaming top-k
    bitwise-comparable to the materializing reference."""
    from mfm_tpu.models.risk_model import portfolio_vol
    return jax.vmap(lambda x: jax.vmap(
        lambda c: portfolio_vol(c, x))(covs))(xs)


def _init_sweep_carry(n_books: int, top_k: int, n_theta: int, bins: int,
                      dtype):
    """Fresh aggregate carry for one sweep (host helper, not jitted).

    The carry is a flat tuple (a pytree jax donates whole):

    - ``top_vol (B, k)``: per-book worst vols, descending; -inf = empty.
    - ``top_theta (B, k, TH)``: the dense theta behind each entry
      (``[shift(K) | scale(K) | vol_mult | corr_beta]`` — the grad
      subsystem's layout, so seeds feed ``reverse_stress_batch`` as-is).
    - ``top_src (B, k) i32``: global scenario index (replayable identity).
    - ``top_base (B, k) i32``: base-library row the lane stressed.
    - ``hist (B, bins) i32``: fixed-bin vol histogram (the quantile
      sketch; bin edges live host-side, deterministic per sweep).
    - ``counts (3,) i32``: [n_ok, n_rejected, n_projected].
    """
    neg = jnp.finfo(dtype).min
    return (jnp.full((n_books, top_k), neg, dtype=dtype),
            jnp.zeros((n_books, top_k, n_theta), dtype=dtype),
            jnp.full((n_books, top_k), -1, dtype=jnp.int32),
            jnp.full((n_books, top_k), -1, dtype=jnp.int32),
            jnp.zeros((n_books, bins), dtype=jnp.int32),
            jnp.zeros((3,), dtype=jnp.int32))


def _merge_into_carry(carry, vols, thetas, src, base_idx, take, reject,
                      projected, lo, width):
    """Fold one chunk's lane vols into the carry (shared by both sweep
    jits).  ``vols (B, C)``; lane masks are (C,) — a lane is merged for
    every book or none.

    The top-k merge is a fixed-size ``lax.top_k`` over the concatenation
    [carried k | C chunk lanes]: ties keep the LOWER index, so carried
    (older) entries win over chunk lanes and earlier lanes win within a
    chunk — fully deterministic, order-independent only up to the
    documented first-seen tie rule.  No (B, C, TH) broadcast is ever
    built: thetas gather through the chunk-lane index only.
    """
    top_vol, top_theta, top_src, top_base, hist, counts = carry
    dtype = top_vol.dtype
    k = top_vol.shape[1]
    C = vols.shape[1]
    neg = jnp.finfo(dtype).min
    masked = jnp.where(take[None, :], vols, neg)

    allv = jnp.concatenate([top_vol, masked], axis=1)       # (B, k + C)
    new_vol, sel = lax.top_k(allv, k)                        # (B, k)
    from_chunk = sel >= k
    chunk_i = jnp.clip(sel - k, 0, C - 1)                    # (B, k)
    old_i = jnp.clip(sel, 0, k - 1)

    new_theta = jnp.where(
        from_chunk[:, :, None], thetas[chunk_i],
        jnp.take_along_axis(top_theta, old_i[:, :, None], axis=1))
    new_src = jnp.where(from_chunk, src[chunk_i],
                        jnp.take_along_axis(top_src, old_i, axis=1))
    new_base = jnp.where(from_chunk, base_idx[chunk_i],
                         jnp.take_along_axis(top_base, old_i, axis=1))

    # quantile sketch: per-book fixed bins [lo, lo + bins * width); the
    # open top edge clips into the last bin (documented saturating bin)
    bins = hist.shape[1]
    bi = jnp.clip(((vols - lo[:, None]) / width[:, None]).astype(jnp.int32),
                  0, bins - 1)
    n_books = hist.shape[0]
    hist = hist.at[jnp.arange(n_books, dtype=jnp.int32)[:, None], bi].add(
        take[None, :].astype(jnp.int32))

    # pin the accumulation dtype: under x64 jnp.sum of i32 follows NumPy
    # up to i64, which would flip the scan-carry type between modes
    counts = counts + jnp.stack([
        jnp.sum(take, dtype=jnp.int32),
        jnp.sum(reject, dtype=jnp.int32),
        jnp.sum(projected & take, dtype=jnp.int32)])
    return (new_vol, new_theta, new_src, new_base, hist, counts)


#: in-jit sub-chunk length: sweep_chunk folds a C-lane chunk as a
#: lax.scan over C / SWEEP_SUBCHUNK slices so each slice's (sub, K, K)
#: stressed stack stays cache-resident (measured ~3x over one C-wide
#: pass once C * K * K spills the LLC) while the HOST still pays one
#: dispatch + one transfer per C lanes.  Scanning slices in order makes
#: the fold bitwise-identical to C / sub sequential small chunks — the
#: merge sees the same lanes in the same order.
SWEEP_SUBCHUNK = 2048


@partial(jax.jit, donate_argnums=(0,))
def sweep_chunk(carry, base_lib, xs, thetas, base_idx, src,
                take, reject, passthrough, lo, width):
    """Fold one chunk of C HOST-CERTIFIED lanes into the donated carry.

    Every ``take`` lane is pre-certified PSD by the host inertia gate
    (sweep.py), so the lane math is stress + quadratic form only — no
    eigh anywhere on this path.  Lane vols reuse the exact serving
    building blocks (:func:`stress_cov` + ``portfolio_vol``) so small-S
    streaming results are BITWISE-comparable to the materializing
    reference; passthrough (identity-theta) lanes select the precomputed
    per-base vols instead, mirroring the serving kernel's untouched-base
    passthrough guarantee.  Chunks larger than :data:`SWEEP_SUBCHUNK`
    fold as an in-jit scan over cache-sized slices (see above) — same
    lanes, same order, same bits.

    Args:
      carry: aggregate tuple from :func:`_init_sweep_carry` (donated).
      base_lib: (L, K, K) resolved base covariances (row 0 = served cov,
        rows 1.. = replay library; per-book vols of the UNSTRESSED bases
        are recomputed in-jit — L is tiny next to C and keeping the
        computation inside preserves the bitwise contract).
      xs: (B, K) book exposure vectors.
      thetas: (C, 2K + 2) dense shock lanes (grad layout).
      base_idx: (C,) i32 base-library row per lane.
      src: (C,) i32 global scenario index per lane.
      take / reject / passthrough: (C,) bool lane masks (pad lanes are
        neither taken nor rejected).
      lo / width: (B,) histogram bin origin / width at compute dtype.
    """
    K = base_lib.shape[-1]
    C = thetas.shape[0]
    base_vols = book_vols(base_lib, xs)                      # (B, L)

    def fold(carry, blk):
        th, bi, s, tk, rj, pt = blk
        bases = base_lib[bi]                                 # (sub, K, K)
        covs = jax.vmap(stress_cov)(bases, th[:, :K], th[:, K:2 * K],
                                    th[:, 2 * K], th[:, 2 * K + 1])
        vols = book_vols(covs, xs)                           # (B, sub)
        vols = jnp.where(pt[None, :], base_vols[:, bi], vols)
        projected = jnp.zeros(th.shape[0], dtype=bool)       # certified PSD
        return _merge_into_carry(carry, vols, th, s, bi, tk, rj,
                                 projected, lo, width), None

    sub = SWEEP_SUBCHUNK if C % SWEEP_SUBCHUNK == 0 else C
    n = max(C // sub, 1)
    blocks = (thetas.reshape(n, sub, -1), base_idx.reshape(n, sub),
              src.reshape(n, sub), take.reshape(n, sub),
              reject.reshape(n, sub), passthrough.reshape(n, sub))
    carry, _ = lax.scan(fold, carry, blocks)
    return carry


@partial(jax.jit, donate_argnums=(0,))
def sweep_merge(carry, covs, xs, thetas, src, base_idx, take, projected,
                lo, width):
    """Fold M OFFENDER lanes (already shocked + PSD-gated by
    :func:`scenario_batch`) into the donated carry.

    ``covs (M, K, K)`` are the exact-path outputs; this jit only takes
    the quadratic forms and runs the identical merge, so offender lanes
    land in the same top-k/histogram/counters as certified ones — with
    their true post-projection vols and their ``projected`` flags
    counted."""
    vols = book_vols(covs, xs)                               # (B, M)
    reject = jnp.zeros(thetas.shape[0], dtype=bool)
    return _merge_into_carry(carry, vols, thetas, src, base_idx, take,
                             reject, projected, lo, width)
