"""The batched scenario kernel: S covariance shocks in ONE donated jit.

Every scenario kind :mod:`mfm_tpu.scenario.spec` can express reduces, by
the time it reaches the device, to the same lane shape: a base covariance
(what world the shock applies to — today's served matrix, a historical
replay, a quarantine counterfactual) plus four dense shock operands.  The
kernel vmaps one lane function over the S axis, so a batch of S scenarios
IS S independent single runs:

- every per-lane op is elementwise or a within-lane contraction (the
  eigendecomposition and its reconstruction) — nothing contracts across
  the S axis, so lane i's bytes cannot depend on its batchmates;
- the identity lane is a ``jnp.where`` passthrough of the UNTOUCHED base
  covariance, not an algebraic no-op (``cov / sigma sigma' * sigma
  sigma'`` is not bitwise-stable) — the identity scenario is
  bitwise-equal to the unshocked baseline by construction.

Those two properties are the subsystem's correctness anchor
(tests/test_scenario.py proves both; tools/faultinject.py's
``scenario-poison-spec`` plan re-proves lane isolation under rejected
batchmates).

Lane math, in order (PAPER.md's USE4 vocabulary):

1. split the base covariance into vols and correlations,
2. per-factor vol shocks ``sigma' = max(sigma * scale + shift, 0)``,
3. the vol-regime multiplier override ``sigma' *= vol_mult`` (the
   scenario analog of the lambda_F series of stage 4),
4. correlation stress: off-diagonals scaled by ``1 + corr_beta`` and
   clipped to [-1, 1] (corr-meltup / diversification-collapse drills),
5. gated PSD projection: eigendecompose, clamp eigenvalues to a small
   relative floor, reconstruct — only where the stressed matrix went
   indefinite (the clip in step 4 can break PSD-ness; a projected lane is
   flagged so obs/ can count activations).

Shapes are padded to geometric S-buckets by the engine (the query-engine
bucket discipline, serve/query.py), so the steady state holds <= 1
compile per bucket — ``assert_max_compiles`` enforced in tests and bench.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def stress_cov(cov, shift, scale, vol_mult, corr_beta):
    """Steps 1-4 of the lane math: the stressed covariance BEFORE the PSD
    gate.  Shared by the serving kernel below and by the grad subsystem
    (:mod:`mfm_tpu.grad`), and differentiable w.r.t. every SHOCK operand
    (shift / scale / vol_mult / corr_beta).  ``cov`` is a constant under
    every grad surface — the vol split divides by ``outer(sigma, sigma)``
    inside a ``jnp.where``, which is only vjp-safe for cotangents that
    never reach the base-covariance branch.

    Args mirror :func:`_one_scenario`; returns ``cov_s (K, K)``.
    """
    dtype = cov.dtype
    K = cov.shape[0]
    eye = jnp.eye(K, dtype=dtype)
    one = jnp.asarray(1.0, dtype)

    var = jnp.diagonal(cov)
    sigma = jnp.sqrt(jnp.maximum(var, 0))
    denom = jnp.outer(sigma, sigma)
    corr = jnp.where(denom > 0, cov / denom, jnp.zeros((), dtype))
    corr = corr * (one - eye) + eye
    corr_s = jnp.clip(corr * (one + corr_beta), -one, one)
    corr_s = corr_s * (one - eye) + eye
    sigma_s = jnp.maximum(sigma * scale + shift, 0) * vol_mult
    return corr_s * jnp.outer(sigma_s, sigma_s)


def psd_project(cov_s):
    """Step 5, the gated PSD projection, in its GRAD-SAFE form.

    Forward outputs are value-identical to the serving gate inlined in
    :func:`_one_scenario` (same eigh primitive, same clamp floor, same
    reconstruction — when the gate fires the eigh input is bitwise
    ``cov_s``, when it doesn't the output IS ``cov_s``), but the gating is
    restructured so reverse-mode AD through it stays finite:

    - the gate value comes from ``eigvalsh(stop_gradient(cov_s))`` — the
      gate is a DECISION, not a differentiable quantity, and eigh's vjp on
      a matrix with (near-)repeated eigenvalues divides by ``w_i - w_j``;
    - the eigh whose vectors rebuild the projection runs on
      ``where(needs, cov_s, GENERIC)`` with GENERIC a fixed matrix with
      well-separated eigenvalues (diag(1..K)), so when the projection is
      NOT selected the zero cotangent flowing into the unselected branch
      multiplies finite eigh-vjp factors instead of the inf/NaN a
      degenerate ``cov_s`` would produce (the classic where-NaN trap).

    The serving kernel keeps its single-eigh inline gate (this form costs
    a second eigendecomposition — the gate eigh and the projection eigh —
    which the forward-only hot path does not want to pay); the grad
    subsystem composes THIS function.  tests/test_grad.py pins the
    forward parity between the two.

    Returns ``(cov_psd, needs, min_eig)`` exactly like the inline gate.
    """
    dtype = cov_s.dtype
    K = cov_s.shape[0]
    w_gate = jnp.linalg.eigvalsh(lax.stop_gradient(cov_s))
    min_eig = w_gate[0]
    needs = min_eig < 0
    generic = jnp.diag(jnp.arange(1, K + 1, dtype=jnp.int32).astype(dtype))
    w, V = jnp.linalg.eigh(jnp.where(needs, cov_s, generic))
    floor = jnp.maximum(w[-1], 0) * (K * jnp.finfo(dtype).eps)
    w_cl = jnp.maximum(w, floor)
    proj = (V * w_cl) @ V.T
    proj = 0.5 * (proj + proj.T)
    return jnp.where(needs, proj, cov_s), needs, min_eig


def _one_scenario(cov, shift, scale, vol_mult, corr_beta, passthrough):
    """Shock ONE covariance lane; vmapped over S by :func:`scenario_batch`.

    Args:
      cov: (K, K) base covariance (compute dtype).
      shift: (K,) additive vol deltas (0 = untouched).
      scale: (K,) multiplicative vol scales (1 = untouched).
      vol_mult: scalar vol-regime multiplier override (1 = untouched).
      corr_beta: scalar off-diagonal stress (0 = untouched; rho' =
        clip(rho * (1 + corr_beta), -1, 1)).
      passthrough: scalar bool — True serves ``cov`` back bitwise-untouched
        (identity scenarios, rejected specs, pad lanes).

    Returns ``(cov_out (K, K), psd_projected bool, min_eig_stressed)``
    where ``min_eig_stressed`` is the smallest eigenvalue of the stressed
    matrix BEFORE projection (the audit number the manifest records).
    """
    dtype = cov.dtype
    K = cov.shape[0]
    cov_s = stress_cov(cov, shift, scale, vol_mult, corr_beta)

    # gated PSD projection.  The eigh runs unconditionally (the gate needs
    # min_eig and K is small); the clamp floor is a small RELATIVE floor —
    # eigenvalues of the reconstructed matrix differ from the clamped ones
    # by O(eps * ||cov||), so clamping at exactly 0 could leave the result
    # indefinite at compute dtype.  K * eps * lambda_max dominates that
    # reconstruction error, keeping min-eig >= 0 at compute dtype.
    w, V = jnp.linalg.eigh(cov_s)
    min_eig = w[0]
    floor = jnp.maximum(w[-1], 0) * (K * jnp.finfo(dtype).eps)
    w_cl = jnp.maximum(w, floor)
    proj = (V * w_cl) @ V.T
    proj = 0.5 * (proj + proj.T)
    needs = min_eig < 0
    cov_out = jnp.where(needs, proj, cov_s)
    cov_out = jnp.where(passthrough, cov, cov_out)
    return cov_out, needs & ~passthrough, jnp.where(passthrough,
                                                    jnp.zeros((), dtype),
                                                    min_eig)


# Donated jit for the whole batch: every operand is freshly assembled per
# run by the engine (base covs resolved host-side, shock vectors densified
# from the specs).  Only the operands whose shape+dtype an output can
# actually alias are donated — cov (S, K, K) into cov_out and one (S,)
# float into min_eig_stressed; donating the rest would just warn.  The jit
# keys on the padded bucket shape only — <= 1 compile per S-bucket in
# steady state.
@partial(jax.jit, donate_argnums=(0, 3))
def scenario_batch(base_cov, shift, scale, vol_mult, corr_beta, passthrough):
    """Shock S covariance lanes in one compiled program.

    Args are the (S, ...) stacks of :func:`_one_scenario`'s operands.
    Returns ``(covs (S, K, K), psd_projected (S,), min_eig_stressed (S,))``.
    """
    return jax.vmap(_one_scenario)(base_cov, shift, scale, vol_mult,
                                   corr_beta, passthrough)
