"""Declarative scenario specs: what-if worlds as JSON-round-trippable data.

A :class:`ScenarioSpec` names ONE hypothetical world to re-price risk
under.  It is pure declaration — no arrays, no device state — so specs
live in version control, ride in manifests, and hash stably
(:meth:`ScenarioSpec.spec_hash` is the audit key ``mfm-tpu doctor
--scenarios`` recomputes).  Five orthogonal axes, composable in one spec:

- **Factor vol shocks** (``shift`` / ``scale``): per-factor additive
  deltas and multiplicative scales on the factor volatilities — "energy
  vol doubles", "momentum vol +5 points".
- **Vol-regime override** (``vol_mult``): a global multiplier on every
  factor vol, the scenario analog of the stage-4 lambda_F series
  (PAPER.md) — "the whole market runs 3x hot".
- **Correlation stress** (``corr_beta``): off-diagonal correlations
  scaled by ``1 + corr_beta`` and clipped to [-1, 1] —
  diversification-collapse / melt-up drills.  May break PSD-ness; the
  kernel's gated projection repairs it and flags the lane.
- **Historical replay** (``replay``): splice a named stretch of panel
  history — the base covariance becomes the one the model had fitted
  through that window (resolved host-side from a pipeline result).
- **Quarantine counterfactual** (``flip_quarantine`` / ``flip_heal``):
  re-run the guarded update with chosen verdicts flipped — "what if the
  guards had (not) quarantined date d?" — via the ``pre_reasons`` /
  ``heal_mask`` operands of ``RiskModel.update_guarded``.

The all-defaults spec is the IDENTITY scenario: the engine serves the
base covariance back bitwise-untouched (the subsystem's correctness
anchor).  Build specs with :class:`ScenarioBuilder` or start from the
:data:`PRESETS` catalog (docs/SCENARIOS.md describes each drill).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math

import jax

#: manifest / JSON schema version of the spec wire format
SPEC_SCHEMA_VERSION = 1


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """One named what-if world (frozen, hashable, JSON-round-trippable).

    Attributes:
      name: unique id of the scenario inside a batch (manifest key, the
        ``scenario`` field of serve requests).
      shift: ``((factor, vol_delta), ...)`` additive vol shocks.
      scale: ``((factor, vol_scale), ...)`` multiplicative vol scales.
      vol_mult: global vol-regime multiplier override (1.0 = untouched).
      corr_beta: off-diagonal correlation stress (0.0 = untouched).
      replay: optional ``(start_date, end_date)`` historical window whose
        fitted covariance replaces today's as the shock base.
      flip_quarantine: dates whose guard verdict is forced QUARANTINED.
      flip_heal: dates whose guard verdict is forced HEALTHY.
    """

    name: str
    shift: tuple = ()
    scale: tuple = ()
    vol_mult: float = 1.0
    corr_beta: float = 0.0
    replay: tuple | None = None
    flip_quarantine: tuple = ()
    flip_heal: tuple = ()

    # a spec is static declaration: flatten with no array leaves so specs
    # ride through tree_map / jit-static plumbing untouched
    def tree_flatten(self):
        return (), self

    @classmethod
    def tree_unflatten(cls, aux, children):
        return aux

    def __post_init__(self):
        # normalize the container fields to hashable tuples so specs built
        # from JSON lists and from the builder compare/hash identically
        object.__setattr__(self, "shift", _pairs(self.shift))
        object.__setattr__(self, "scale", _pairs(self.scale))
        object.__setattr__(self, "vol_mult", float(self.vol_mult))
        object.__setattr__(self, "corr_beta", float(self.corr_beta))
        if self.replay is not None:
            object.__setattr__(
                self, "replay",
                (str(self.replay[0]), str(self.replay[1])))
        object.__setattr__(self, "flip_quarantine",
                           tuple(str(d) for d in self.flip_quarantine))
        object.__setattr__(self, "flip_heal",
                           tuple(str(d) for d in self.flip_heal))

    # -- identity ------------------------------------------------------------
    @classmethod
    def identity(cls, name: str = "identity") -> "ScenarioSpec":
        """The no-op scenario: served back bitwise-equal to the baseline."""
        return cls(name=name)

    @property
    def shocks_identity(self) -> bool:
        """True when the covariance TRANSFORM is a no-op (the base may
        still be a replay / counterfactual world)."""
        return (not self.shift and not self.scale
                and self.vol_mult == 1.0 and self.corr_beta == 0.0)

    @property
    def is_identity(self) -> bool:
        """True for the full no-op: identity transform on today's world."""
        return (self.shocks_identity and self.replay is None
                and not self.flip_quarantine and not self.flip_heal)

    @property
    def kinds(self) -> tuple:
        """The spec axes actually in play (manifest / CLI display)."""
        out = []
        if self.shift or self.scale:
            out.append("vol_shock")
        if self.vol_mult != 1.0:
            out.append("vol_regime")
        if self.corr_beta != 0.0:
            out.append("corr_stress")
        if self.replay is not None:
            out.append("replay")
        if self.flip_quarantine or self.flip_heal:
            out.append("counterfactual")
        return tuple(out) or ("identity",)

    # -- JSON round trip -----------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "schema_version": SPEC_SCHEMA_VERSION,
            "name": self.name,
            "shift": [[f, v] for f, v in self.shift],
            "scale": [[f, v] for f, v in self.scale],
            "vol_mult": self.vol_mult,
            "corr_beta": self.corr_beta,
            "replay": None if self.replay is None else list(self.replay),
            "flip_quarantine": list(self.flip_quarantine),
            "flip_heal": list(self.flip_heal),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        if not isinstance(d, dict):
            raise ValueError(f"spec must be a JSON object, got {type(d)}")
        ver = d.get("schema_version", SPEC_SCHEMA_VERSION)
        if ver != SPEC_SCHEMA_VERSION:
            raise ValueError(f"unsupported spec schema_version {ver!r} "
                             f"(this build reads {SPEC_SCHEMA_VERSION})")
        if "name" not in d:
            raise ValueError("spec is missing 'name'")
        replay = d.get("replay")
        return cls(
            name=str(d["name"]),
            shift=_pairs(d.get("shift", ())),
            scale=_pairs(d.get("scale", ())),
            vol_mult=d.get("vol_mult", 1.0),
            corr_beta=d.get("corr_beta", 0.0),
            replay=None if replay is None else (replay[0], replay[1]),
            flip_quarantine=tuple(d.get("flip_quarantine", ())),
            flip_heal=tuple(d.get("flip_heal", ())),
        )

    def to_json(self) -> str:
        """Canonical JSON (sorted keys, tight separators) — the byte
        stream :meth:`spec_hash` digests, so hash equality IS spec
        equality."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "ScenarioSpec":
        return cls.from_dict(json.loads(s))

    def spec_hash(self) -> str:
        """sha256 of the canonical JSON — the manifest audit key."""
        return hashlib.sha256(self.to_json().encode("utf-8")).hexdigest()


def _pairs(items) -> tuple:
    """Normalize ``[[factor, value], ...]`` / dicts to a sorted tuple of
    ``(str, float)`` pairs (canonical order => canonical hash)."""
    if isinstance(items, dict):
        items = items.items()
    out = []
    for it in items:
        f, v = it
        out.append((str(f), float(v)))
    return tuple(sorted(out))


def validate_spec(spec: ScenarioSpec, factor_names=None) -> list:
    """Host-side admission guard for one spec; returns the problem list
    (empty = admissible).

    Mirrors the request guards of serve/server.py: a poisoned spec (NaN
    shock, ``corr_beta`` past the -1 pole, non-positive ``vol_mult``,
    unknown factor) is REJECTED per-scenario — the engine substitutes a
    passthrough lane so batchmates' bytes are untouched (the
    ``scenario-poison-spec`` chaos plan proves it).
    """
    problems = []
    if not isinstance(spec.name, str) or not spec.name:
        problems.append("name must be a non-empty string")
    known = None if factor_names is None else set(map(str, factor_names))
    for label, pairs in (("shift", spec.shift), ("scale", spec.scale)):
        for f, v in pairs:
            if not math.isfinite(v):
                problems.append(f"{label}[{f!r}] is non-finite ({v!r})")
            elif label == "scale" and v < 0:
                problems.append(f"scale[{f!r}] must be >= 0, got {v}")
            if known is not None and f not in known:
                problems.append(f"{label} names unknown factor {f!r}")
    if not (math.isfinite(spec.vol_mult) and spec.vol_mult > 0):
        problems.append(f"vol_mult must be finite and > 0, got "
                        f"{spec.vol_mult!r}")
    if not math.isfinite(spec.corr_beta) or spec.corr_beta <= -1.0:
        problems.append(f"corr_beta must be finite and > -1, got "
                        f"{spec.corr_beta!r}")
    if spec.replay is not None and not (spec.replay[0] <= spec.replay[1]):
        problems.append(f"replay window is reversed: {spec.replay!r}")
    both = set(spec.flip_quarantine) & set(spec.flip_heal)
    if both:
        problems.append(f"dates flipped both ways: {sorted(both)[:5]}")
    return problems


class ScenarioBuilder:
    """Chainable spec builder::

        spec = (ScenarioBuilder("energy-shock")
                .shock("industry_7", mult=2.0)
                .vol_regime(1.5)
                .correlation(0.3)
                .build())
    """

    def __init__(self, name: str):
        self._name = str(name)
        self._shift: dict = {}
        self._scale: dict = {}
        self._vol_mult = 1.0
        self._corr_beta = 0.0
        self._replay = None
        self._flip_q: list = []
        self._flip_h: list = []

    def shock(self, factor: str, add: float = 0.0,
              mult: float = 1.0) -> "ScenarioBuilder":
        """Shock one factor's vol: ``sigma' = sigma * mult + add``."""
        f = str(factor)
        if add:
            self._shift[f] = self._shift.get(f, 0.0) + float(add)
        if mult != 1.0:
            self._scale[f] = self._scale.get(f, 1.0) * float(mult)
        return self

    def vol_regime(self, mult: float) -> "ScenarioBuilder":
        """Override the global vol-regime multiplier."""
        self._vol_mult = float(mult)
        return self

    def correlation(self, beta: float) -> "ScenarioBuilder":
        """Stress off-diagonal correlations by ``1 + beta``."""
        self._corr_beta = float(beta)
        return self

    def replay(self, start: str, end: str) -> "ScenarioBuilder":
        """Use the covariance fitted through [start, end] as the base."""
        self._replay = (str(start), str(end))
        return self

    def flip(self, date: str, heal: bool = False) -> "ScenarioBuilder":
        """Flip date's quarantine verdict (``heal=True`` forces HEALTHY,
        else forces QUARANTINED)."""
        (self._flip_h if heal else self._flip_q).append(str(date))
        return self

    def build(self) -> ScenarioSpec:
        return ScenarioSpec(
            name=self._name,
            shift=tuple(self._shift.items()),
            scale=tuple(self._scale.items()),
            vol_mult=self._vol_mult,
            corr_beta=self._corr_beta,
            replay=self._replay,
            flip_quarantine=tuple(self._flip_q),
            flip_heal=tuple(self._flip_h),
        )


#: the preset drill catalog (docs/SCENARIOS.md).  Analogs, not replays:
#: each encodes the SHAPE of a historical stress (how much vol, how much
#: correlation melt-up) as a pure covariance transform, so it applies to
#: any checkpoint without that history on disk.
PRESETS = {
    "crash-2015-analog": ScenarioSpec(
        name="crash-2015-analog", vol_mult=2.2, corr_beta=0.35),
    "covid-2020-analog": ScenarioSpec(
        name="covid-2020-analog", vol_mult=3.1, corr_beta=0.55),
    "corr-meltup": ScenarioSpec(
        name="corr-meltup", corr_beta=0.9),
}

PRESET_NOTES = {
    "crash-2015-analog": "2015-style drawdown: vols ~2.2x, correlations "
                         "+35% toward 1 (diversification thins)",
    "covid-2020-analog": "2020-crash analog: vols ~3.1x, correlations "
                         "+55% toward 1 (the fastest regime flip on "
                         "record)",
    "corr-meltup": "pure correlation melt-up at unchanged vols — the "
                   "stress that breaks PSD-ness and exercises the "
                   "projection path",
}


def preset(name: str) -> ScenarioSpec:
    """Look up a preset spec by name (raises KeyError with the catalog)."""
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(f"unknown preset {name!r}; have "
                       f"{sorted(PRESETS)}") from None
