"""ScenarioEngine — run S what-if worlds against one checkpoint, batched.

Host-side orchestration around :mod:`mfm_tpu.scenario.kernel`'s one
donated jit (this module is an mfmlint R7 host-only barrier, like
serve/server.py: validation, base-cov resolution, obs recording and
manifest assembly are host work by design).  The run protocol:

1. **Admit** every spec through :func:`mfm_tpu.scenario.spec.validate_spec`
   — a poisoned spec (NaN shock, corr_beta past the -1 pole, unknown
   factor) is rejected PER-SCENARIO and its lane becomes a passthrough,
   so batchmates' bytes are untouched.
2. **Resolve** each admissible spec's base covariance host-side: today's
   served matrix by default, a historical window's fitted matrix for
   replay specs, a real guarded re-run with flipped verdicts for
   quarantine counterfactuals (``replay_lookup`` / ``counterfactual_fn``
   injectables — :mod:`mfm_tpu.scenario.counterfactual` builds both).
3. **Batch** all lanes into the geometric S-bucket (serve/query.py's
   ladder), pad with passthrough lanes, and run the ONE donated jit —
   <= 1 compile per bucket in steady state.
4. **Report**: per-scenario :class:`ScenarioResult` (shocked covariance,
   vol deltas, PSD-projection flag) + obs counters/histograms; the CLI
   layer persists the batch as an atomic ``scenario_manifest.json``
   (:mod:`mfm_tpu.scenario.manifest`).

Bitwise contracts (tests/test_scenario.py): the identity spec returns the
base covariance byte-for-byte, and a batch of S equals S single runs —
the kernel is lane-independent and the bucket padding is passthrough
lanes, never math.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

import jax.numpy as jnp

from mfm_tpu.obs import instrument as _obs
from mfm_tpu.scenario.kernel import scenario_batch
from mfm_tpu.scenario.spec import ScenarioSpec, validate_spec
from mfm_tpu.serve.query import bucket_for


@dataclasses.dataclass(frozen=True)
class ScenarioResult:
    """One scenario's answer inside a batch.

    ``status`` is ``"ok"`` or ``"rejected"`` (``problems`` says why; a
    rejected lane computes nothing and contaminates nothing).  For ok
    lanes: ``cov`` is the shocked (K, K) covariance, ``factor_vol`` /
    ``base_factor_vol`` the per-factor vols after/before (their
    difference is the manifest's vol-delta block), ``psd_projected``
    whether the gated projection fired, ``min_eig_stressed`` the smallest
    eigenvalue BEFORE projection.
    """

    spec: ScenarioSpec
    status: str
    problems: tuple = ()
    cov: np.ndarray | None = None
    base_factor_vol: np.ndarray | None = None
    factor_vol: np.ndarray | None = None
    psd_projected: bool = False
    min_eig_stressed: float = 0.0

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    def vol_delta(self) -> np.ndarray | None:
        """Per-factor vol change (after - before); None when rejected."""
        if not self.ok:
            return None
        return self.factor_vol - self.base_factor_vol


class ScenarioEngine:
    """Batched scenario runs against one served covariance.

    Args:
      cov: (K, K) baseline served covariance (e.g. ``state.last_good_cov``
        — what the identity scenario returns bitwise).
      factor_names: K names defining the shock-key space (defaults to
        ``f0..f{K-1}``; unknown factors in a spec reject that spec).
      staleness: dates since ``cov`` was fit (rides into manifests).
      dtype: compute dtype (defaults to ``cov``'s).
      replay_lookup: optional ``(start, end) -> (K, K) | None`` resolving
        a historical window to its fitted covariance; ``None`` rejects
        replay specs as unsupported.
      counterfactual_fn: optional ``(flip_quarantine, flip_heal) -> (K, K)``
        running the REAL guarded update with flipped verdicts; ``None``
        rejects counterfactual specs as unsupported.
    """

    def __init__(self, cov, *, factor_names=None, staleness: int = 0,
                 dtype=None, replay_lookup=None, counterfactual_fn=None):
        cov = np.asarray(cov)
        if cov.ndim != 2 or cov.shape[0] != cov.shape[1]:
            raise ValueError(f"cov must be (K, K), got {cov.shape}")
        if not np.isfinite(cov).all():
            raise ValueError("baseline covariance contains non-finite "
                             "entries — refuse to build a scenario engine "
                             "on it")
        self.dtype = np.dtype(dtype) if dtype is not None else cov.dtype
        self.K = int(cov.shape[0])
        self.cov = cov.astype(self.dtype)
        self.factor_names = ([f"f{i}" for i in range(self.K)]
                             if factor_names is None
                             else list(map(str, factor_names)))
        if len(self.factor_names) != self.K:
            raise ValueError(f"{len(self.factor_names)} factor names for "
                             f"K={self.K}")
        self.factor_index = {n: i for i, n in enumerate(self.factor_names)}
        self.staleness = int(staleness)
        self.replay_lookup = replay_lookup
        self.counterfactual_fn = counterfactual_fn

    @classmethod
    def from_risk_state(cls, state, meta=None, dtype=None,
                        replay_lookup=None, counterfactual_fn=None):
        """Engine over a guarded ``RiskModelState`` checkpoint's served
        covariance — the same contract as ``QueryEngine.from_risk_state``
        (factor names off the checkpoint meta, refuse unguarded states)."""
        if not getattr(state, "guarded", False):
            raise ValueError(
                "state has no served covariance — scenarios shock the "
                "guarded checkpoint's last_good_cov; re-run the pipeline "
                "with quarantine enabled")
        names = None
        if meta and "style_names" in meta and "industry_codes" in meta:
            names = (["country"] + [str(c) for c in meta["industry_codes"]]
                     + [str(s) for s in meta["style_names"]])
        cov = np.asarray(state.last_good_cov)
        if names is not None and len(names) != cov.shape[0]:
            names = None
        return cls(cov, factor_names=names,
                   staleness=int(np.asarray(state.staleness)), dtype=dtype,
                   replay_lookup=replay_lookup,
                   counterfactual_fn=counterfactual_fn)

    # -- per-spec admission / resolution -------------------------------------
    def _resolve(self, spec: ScenarioSpec):
        """One spec -> (base_cov | None, problems).  Everything host-side;
        a problem list means the lane is rejected (passthrough)."""
        problems = list(validate_spec(spec, self.factor_names))
        if problems:
            return None, problems
        wants_replay = spec.replay is not None
        wants_cf = bool(spec.flip_quarantine or spec.flip_heal)
        if wants_replay and wants_cf:
            return None, ["replay and counterfactual compose ambiguously "
                          "— split into two scenarios"]
        base = self.cov
        if wants_replay:
            if self.replay_lookup is None:
                return None, ["replay spec but the engine has no history "
                              "(build it with replay_lookup)"]
            try:
                base = self.replay_lookup(*spec.replay)
            except Exception as e:   # noqa: BLE001 — reject, don't poison
                return None, [f"replay resolution failed: {e}"]
            if base is None:
                return None, [f"replay window {spec.replay!r} not in the "
                              "engine's history"]
        elif wants_cf:
            if self.counterfactual_fn is None:
                return None, ["counterfactual spec but the engine has no "
                              "slab context (build it with "
                              "counterfactual_fn)"]
            try:
                base = self.counterfactual_fn(spec.flip_quarantine,
                                              spec.flip_heal)
            except Exception as e:   # noqa: BLE001 — reject, don't poison
                return None, [f"counterfactual re-run failed: {e}"]
        base = np.asarray(base, self.dtype)
        if base.shape != (self.K, self.K):
            return None, [f"resolved base covariance is {base.shape}, "
                          f"need ({self.K}, {self.K})"]
        if not np.isfinite(base).all():
            return None, ["resolved base covariance has non-finite entries"]
        return base, []

    def _shock_vectors(self, spec: ScenarioSpec):
        shift = np.zeros(self.K, self.dtype)
        scale = np.ones(self.K, self.dtype)
        for f, v in spec.shift:
            shift[self.factor_index[f]] += v
        for f, v in spec.scale:
            scale[self.factor_index[f]] *= v
        return shift, scale

    # -- the batched run -----------------------------------------------------
    def run(self, specs, bucket: int | None = None) -> list:
        """Run S scenarios in ONE donated jit call.

        ``specs``: iterable of :class:`ScenarioSpec` (names must be unique
        — the manifest and the serve-side scenario table key on them).
        ``bucket`` pins the padded batch shape (tests / steady-state
        loops); default is :func:`bucket_for` of S.  Returns a list of
        :class:`ScenarioResult` in input order.
        """
        specs = list(specs)
        S = len(specs)
        if S < 1:
            raise ValueError("need at least one scenario spec")
        names = [s.name for s in specs]
        if len(set(names)) != S:
            dup = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"duplicate scenario names in batch: {dup[:5]}")
        B = bucket_for(S) if bucket is None else int(bucket)
        if B < S:
            raise ValueError(f"bucket {B} < batch size {S}")

        shift = np.zeros((B, self.K), self.dtype)
        scale = np.ones((B, self.K), self.dtype)
        vol_mult = np.ones((B,), self.dtype)
        corr_beta = np.zeros((B,), self.dtype)
        passthrough = np.ones((B,), bool)   # pad lanes stay passthrough

        lane_problems: list = []
        base_rows: dict = {}   # lane -> base override (replay / cf lanes)
        for i, spec in enumerate(specs):
            cov_i, problems = self._resolve(spec)
            lane_problems.append(tuple(problems))
            if problems:
                continue   # rejected: the lane stays a passthrough no-op
            if cov_i is not self.cov:
                base_rows[i] = cov_i
            shift[i], scale[i] = self._shock_vectors(spec)
            vol_mult[i] = spec.vol_mult
            corr_beta[i] = spec.corr_beta
            # identity TRANSFORM lanes pass the base through bitwise (the
            # correctness anchor); shocked lanes compute
            passthrough[i] = spec.shocks_identity

        # the common batch shares self.cov on every lane: keep the base a
        # broadcast VIEW (jnp.array below copies host->device regardless,
        # so the dense (B, K, K) host materialization was pure waste) and
        # only densify when a replay/counterfactual lane overrides its row
        base = np.broadcast_to(self.cov, (B, self.K, self.K))
        if base_rows:
            base = base.copy()
            for i, cov_i in base_rows.items():
                base[i] = cov_i

        base_vols = np.sqrt(np.maximum(
            np.diagonal(base[:S], axis1=1, axis2=2), 0)).astype(self.dtype)
        t0 = time.perf_counter()
        covs, projected, min_eig = scenario_batch(
            jnp.array(base), jnp.array(shift), jnp.array(scale),
            jnp.array(vol_mult), jnp.array(corr_beta),
            jnp.array(passthrough))
        # materialize before closing the span: np.asarray forces the
        # async dispatch, so the histogram measures compute, not enqueue.
        # Crop BEFORE the host transfer so a batch pinned into an
        # oversized bucket doesn't ship the full pad — but crop to the
        # LADDER rung covering S, not S itself: the device-side slice is
        # itself a tiny lowered program keyed on its output shape, so an
        # exact-S crop would retrace per distinct S and break the <= 1
        # compile/bucket steady state.  Rung-quantized crops key on
        # (bucket, rung) pairs only, and the default-bucket path (B ==
        # bucket_for(S)) never slices at all.
        S_q = min(bucket_for(S), B)
        if S_q < B:
            covs, projected, min_eig = (covs[:S_q], projected[:S_q],
                                        min_eig[:S_q])
        covs = np.asarray(covs)[:S]
        projected = np.asarray(projected)[:S]
        min_eig = np.asarray(min_eig)[:S]
        dt = time.perf_counter() - t0

        results = []
        n_ok = n_rejected = 0
        for i, spec in enumerate(specs):
            if lane_problems[i]:
                n_rejected += 1
                results.append(ScenarioResult(
                    spec=spec, status="rejected",
                    problems=lane_problems[i]))
                continue
            n_ok += 1
            cov_i = covs[i]
            results.append(ScenarioResult(
                spec=spec, status="ok",
                cov=cov_i,
                base_factor_vol=base_vols[i],
                factor_vol=np.sqrt(np.maximum(np.diagonal(cov_i), 0)),
                psd_projected=bool(projected[i]),
                min_eig_stressed=float(min_eig[i]),
            ))
        _obs.record_scenario_batch(S, dt)
        if n_ok:
            _obs.record_scenario_outcome("ok", n_ok)
        if n_rejected:
            _obs.record_scenario_outcome("rejected", n_rejected)
        n_proj = int(projected.sum())
        if n_proj:
            _obs.record_psd_projections(n_proj)
        return results

    # -- serve-side sugar ----------------------------------------------------
    def query_engines(self, results, template) -> dict:
        """``{scenario_name: QueryEngine}`` over a batch's ok results —
        the table ``QueryServer`` answers scenario-tagged requests from.
        ``template`` is the plain engine to clone (exposures, benchmarks
        and dtype ride along; only the covariance changes)."""
        return {r.spec.name: template.with_cov(r.cov,
                                               scenario_id=r.spec.name)
                for r in results if r.ok}
