"""Atomic scenario manifests + the doctor audit over them.

A scenario batch is evidence — "under covid-2020-analog this book runs
3.1x hot" drives real decisions — so its results persist with the same
discipline as checkpoints: ONE ``scenario_manifest.json`` written
atomically (tmp -> fsync -> chaos point -> rename -> dir fsync) next to
the artifacts it was computed against.  The chaos point
(``scenario_manifest.after_tmp``) lets tools/faultinject.py prove a
SIGKILL mid-write never leaves a torn manifest.

The manifest is DETERMINISTIC except for its ``summary`` block (obs
latency quantiles): per-scenario entries carry the full spec, its
canonical hash, the audit numbers (vol deltas, top factor swings, PSD
projection flags) — so byte-comparing two manifests modulo ``summary``
IS the bitwise-replay check the ``scenario-kill-mid-batch`` plan runs.

``mfm-tpu doctor --scenarios`` audits via :func:`audit_scenario_manifest`:
torn JSON, wrong schema/kind, and entries whose recomputed spec hash
disagrees with the recorded one (a mismatched manifest — results edited
or mixed from another run) all exit non-zero.

This module is an mfmlint R7 host-only barrier (pure JSON/filesystem).
"""

from __future__ import annotations

import json
import os

import numpy as np

from mfm_tpu.scenario.spec import ScenarioSpec
from mfm_tpu.utils.chaos import chaos_point

SCENARIO_MANIFEST_SCHEMA_VERSION = 1
SCENARIO_MANIFEST_NAME = "scenario_manifest.json"
#: factor-vol swings recorded per scenario (largest |delta| first)
TOP_SWINGS = 5


class ScenarioManifestError(RuntimeError):
    """A scenario manifest exists but is unreadable, schema-incompatible,
    or inconsistent with the specs it claims to record."""


def scenario_manifest_path_for(artifact_dir: str) -> str:
    """The scenario-manifest slot inside an artifact directory."""
    return os.path.join(artifact_dir, SCENARIO_MANIFEST_NAME)


def _entry(result, factor_names) -> dict:
    spec = result.spec
    e = {
        "name": spec.name,
        "kinds": list(spec.kinds),
        "spec": spec.to_dict(),
        "spec_hash": spec.spec_hash(),
        "status": result.status,
        "problems": list(result.problems),
    }
    if not result.ok:
        return e
    before = np.asarray(result.base_factor_vol, np.float64)
    after = np.asarray(result.factor_vol, np.float64)
    delta = after - before
    # "total vol" here is the vol of the equal-exposure unit portfolio's
    # factor part proxied by the trace — a portfolio-free scalar that
    # still moves when anything in the matrix does
    e.update({
        "psd_projected": bool(result.psd_projected),
        "min_eig_stressed": float(result.min_eig_stressed),
        "total_vol_before": float(np.sqrt(np.sum(before ** 2))),
        "total_vol_after": float(np.sqrt(np.sum(after ** 2))),
    })
    # top factor-contribution swings: the factors whose share of total
    # variance moved most (what a risk reader asks first: "what drove it")
    var_b, var_a = before ** 2, after ** 2
    share_b = var_b / max(float(var_b.sum()), 1e-300)
    share_a = var_a / max(float(var_a.sum()), 1e-300)
    order = np.argsort(-np.abs(delta))[:TOP_SWINGS]
    e["top_vol_swings"] = [
        {"factor": str(factor_names[i]), "vol_before": float(before[i]),
         "vol_after": float(after[i]), "vol_delta": float(delta[i]),
         "share_swing": float(share_a[i] - share_b[i])}
        for i in order]
    return e


def build_scenario_manifest(results, factor_names, *, stamp_json=None,
                            backend=None, summary: dict | None = None,
                            staleness: int | None = None,
                            sensitivities: dict | None = None) -> dict:
    """Assemble the manifest dict (pure; :func:`write_scenario_manifest`
    persists).  ``results``: a batch's :class:`ScenarioResult` list;
    ``summary``: the obs block (``scenario_summary_from_registry``) —
    the ONE volatile field, excluded from replay comparison;
    ``sensitivities``: optional name-keyed grad entries (``mfm-tpu grad
    sensitivity``) — each ok entry gains a deterministic ``sensitivity``
    block (exact ∂vol/∂shock + ∂vol/∂exposure rows), additive next to
    the hash-audited spec so replay comparison and
    :func:`audit_scenario_manifest` are untouched."""
    entries = [_entry(r, factor_names) for r in results]
    if sensitivities:
        for e in entries:
            s = sensitivities.get(e["name"])
            if s is not None and e["status"] == "ok":
                e["sensitivity"] = {k: v for k, v in s.items()
                                    if k not in ("name", "status",
                                                 "problems")}
    return {
        "schema_version": SCENARIO_MANIFEST_SCHEMA_VERSION,
        "kind": "scenario_manifest",
        "config_stamp": stamp_json,
        "backend": backend,
        "staleness": staleness,
        "n_scenarios": len(entries),
        "n_ok": sum(1 for e in entries if e["status"] == "ok"),
        "n_rejected": sum(1 for e in entries if e["status"] == "rejected"),
        "n_psd_projected": sum(1 for e in entries
                               if e.get("psd_projected")),
        "scenarios": entries,
        "summary": summary or {},
    }


def write_scenario_manifest(path: str, manifest: dict) -> str:
    """Atomic write (tmp -> fsync -> chaos point -> rename -> dir fsync);
    ``path`` may be the artifact directory.  Returns the final path."""
    if os.path.isdir(path):
        path = os.path.join(path, SCENARIO_MANIFEST_NAME)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=1, sort_keys=True, default=str)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    chaos_point("scenario_manifest.after_tmp", path)
    os.replace(tmp, path)
    try:
        fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - exotic filesystems
        pass
    return path


def read_scenario_manifest(path: str) -> dict:
    """Load + schema-check a scenario manifest (``path`` may be its
    directory).  Raises :class:`ScenarioManifestError` on unreadable /
    torn JSON, wrong ``schema_version`` or ``kind``, or a missing
    ``scenarios`` list."""
    if os.path.isdir(path):
        path = os.path.join(path, SCENARIO_MANIFEST_NAME)
    try:
        with open(path, encoding="utf-8") as fh:
            m = json.load(fh)
    except OSError as e:
        raise ScenarioManifestError(
            f"{path}: unreadable scenario manifest ({e})") from e
    except ValueError as e:
        raise ScenarioManifestError(
            f"{path}: scenario manifest is not valid JSON ({e}) — torn "
            "write?") from e
    if not isinstance(m, dict):
        raise ScenarioManifestError(
            f"{path}: scenario manifest is not a JSON object")
    if m.get("schema_version") != SCENARIO_MANIFEST_SCHEMA_VERSION:
        raise ScenarioManifestError(
            f"{path}: scenario manifest schema_version "
            f"{m.get('schema_version')!r} unsupported (expected "
            f"{SCENARIO_MANIFEST_SCHEMA_VERSION})")
    if m.get("kind") != "scenario_manifest":
        raise ScenarioManifestError(
            f"{path}: kind {m.get('kind')!r} is not a scenario manifest")
    if not isinstance(m.get("scenarios"), list):
        raise ScenarioManifestError(
            f"{path}: scenario manifest has no scenarios list")
    return m


def audit_scenario_manifest(path: str) -> tuple:
    """Deep audit for ``mfm-tpu doctor --scenarios``.

    Returns ``(problems, warnings)`` (lists of strings); an unreadable
    manifest raises :class:`ScenarioManifestError` (doctor reports it as
    corrupt).  Problems: per-entry recomputed spec hash disagreeing with
    the recorded one (mismatched manifest), malformed entries, duplicate
    names, count fields inconsistent with the entry list.  Warnings:
    rejected scenarios (legal, but a drill that asked for them should
    know).
    """
    m = read_scenario_manifest(path)
    problems, warnings = [], []
    seen = set()
    for i, e in enumerate(m["scenarios"]):
        label = f"scenarios[{i}]"
        if not isinstance(e, dict) or "spec" not in e or \
                "spec_hash" not in e or "name" not in e:
            problems.append(f"{label}: malformed entry (need name/spec/"
                            "spec_hash)")
            continue
        if e["name"] in seen:
            problems.append(f"{label}: duplicate scenario name "
                            f"{e['name']!r}")
        seen.add(e["name"])
        try:
            spec = ScenarioSpec.from_dict(e["spec"])
        except (ValueError, TypeError, KeyError, IndexError) as exc:
            problems.append(f"{label} ({e['name']!r}): embedded spec does "
                            f"not parse ({exc})")
            continue
        if spec.name != e["name"]:
            problems.append(f"{label}: entry name {e['name']!r} != spec "
                            f"name {spec.name!r}")
        if spec.spec_hash() != e["spec_hash"]:
            problems.append(
                f"{label} ({e['name']!r}): spec hash mismatch — manifest "
                f"records {str(e['spec_hash'])[:12]}…, the embedded spec "
                f"hashes to {spec.spec_hash()[:12]}… (results edited or "
                "mixed from another run)")
        if e.get("status") == "rejected":
            warnings.append(f"{e['name']!r} was rejected: "
                            f"{'; '.join(e.get('problems', [])[:2])}")
    n = len(m["scenarios"])
    n_ok = sum(1 for e in m["scenarios"]
               if isinstance(e, dict) and e.get("status") == "ok")
    if m.get("n_scenarios") != n or m.get("n_ok") != n_ok:
        problems.append(
            f"count fields disagree with the entry list (n_scenarios="
            f"{m.get('n_scenarios')} vs {n}, n_ok={m.get('n_ok')} vs "
            f"{n_ok})")
    return problems, warnings
