"""Scenario engine: batched stress tests over the served risk model.

The what-if surface of the stack (docs/SCENARIOS.md): declarative
:class:`ScenarioSpec` worlds — factor vol shocks, vol-regime overrides,
correlation stress, historical replays, quarantine counterfactuals —
compiled by :class:`ScenarioEngine` into ONE batched donated jit per
geometric S-bucket, with per-scenario rejection isolation and atomic
``scenario_manifest.json`` evidence audited by ``mfm-tpu doctor
--scenarios``.
"""

from mfm_tpu.scenario.counterfactual import (
    clone_state,
    make_counterfactual_fn,
    make_replay_lookup,
    replay_lookup_from_result,
)
from mfm_tpu.scenario.engine import ScenarioEngine, ScenarioResult
from mfm_tpu.scenario.kernel import scenario_batch
from mfm_tpu.scenario.manifest import (
    SCENARIO_MANIFEST_NAME,
    ScenarioManifestError,
    audit_scenario_manifest,
    build_scenario_manifest,
    read_scenario_manifest,
    scenario_manifest_path_for,
    write_scenario_manifest,
)
from mfm_tpu.scenario.sweep import (
    GridSampler,
    ReplaySampler,
    SobolSampler,
    SWEEP_MANIFEST_NAME,
    SweepEngine,
    SweepManifestError,
    SweepResult,
    UniformSampler,
    audit_sweep_manifest,
    build_sweep_manifest,
    monthly_replay_windows,
    read_sweep_manifest,
    sweep_manifest_path_for,
    theta_to_spec,
    write_sweep_manifest,
)
from mfm_tpu.scenario.spec import (
    PRESET_NOTES,
    PRESETS,
    ScenarioBuilder,
    ScenarioSpec,
    preset,
    validate_spec,
)

__all__ = [
    "GridSampler",
    "PRESETS",
    "PRESET_NOTES",
    "ReplaySampler",
    "SCENARIO_MANIFEST_NAME",
    "SWEEP_MANIFEST_NAME",
    "ScenarioBuilder",
    "ScenarioEngine",
    "ScenarioManifestError",
    "ScenarioResult",
    "ScenarioSpec",
    "SobolSampler",
    "SweepEngine",
    "SweepManifestError",
    "SweepResult",
    "UniformSampler",
    "audit_scenario_manifest",
    "audit_sweep_manifest",
    "build_scenario_manifest",
    "build_sweep_manifest",
    "clone_state",
    "make_counterfactual_fn",
    "make_replay_lookup",
    "monthly_replay_windows",
    "preset",
    "read_scenario_manifest",
    "read_sweep_manifest",
    "replay_lookup_from_result",
    "scenario_batch",
    "scenario_manifest_path_for",
    "sweep_manifest_path_for",
    "theta_to_spec",
    "validate_spec",
    "write_scenario_manifest",
    "write_sweep_manifest",
]
