"""Runtime contracts: compile-count and tracer-leak guards for tests.

The static half of the doctrine lives in ``mfm_tpu/lint.py``; this module
covers what AST analysis cannot see — whether a jitted step *actually*
retraces at runtime.  The incremental-serving win (daily ``update()`` at
~0.08 s vs ~19 s e2e) only holds while the state pytree keeps stable
shapes/dtypes, so tests pin the step to exactly one compilation:

    with assert_max_compiles(1):
        for day in days:
            state = model.update(state, panel_for(day))

Counting uses JAX's monitoring events rather than wrapping ``jit``: the
``/jax/core/compile/jaxpr_to_mlir_module_duration`` event fires once per
lowering, *including* when the persistent compilation cache satisfies the
backend compile — a cache hit is still a retrace and still a bug for these
contracts.
"""

from __future__ import annotations

import contextlib

import jax

# One lowering per distinct (function, shape/dtype signature): the right
# proxy for "did this step retrace".  backend_compile events would undercount
# under a warm persistent cache.
_COMPILE_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"


class CompileCounter:
    """Live count of jit lowerings observed while registered."""

    def __init__(self):
        self.count = 0
        self.events: list[str] = []

    def __call__(self, event: str, duration: float, **kwargs) -> None:
        if event == _COMPILE_EVENT:
            self.count += 1
            self.events.append(event)


@contextlib.contextmanager
def count_compiles():
    """Yield a :class:`CompileCounter` tracking lowerings inside the block."""
    from jax._src import monitoring

    counter = CompileCounter()
    monitoring.register_event_duration_secs_listener(counter)
    try:
        yield counter
    finally:
        unregister = getattr(
            monitoring, "_unregister_event_duration_listener_by_callback",
            None)
        if unregister is not None:
            unregister(counter)


@contextlib.contextmanager
def assert_max_compiles(n: int, what: str = ""):
    """Fail if the block triggers more than ``n`` jit lowerings.

    Use after a warmup call when asserting steady-state behaviour (eager ops
    on first-seen shapes also lower tiny programs, which count).
    """
    with count_compiles() as counter:
        yield counter
    if counter.count > n:
        label = f" in {what}" if what else ""
        raise AssertionError(
            f"expected at most {n} compilation(s){label}, observed "
            f"{counter.count} — a traced step is being retraced "
            "(shape/dtype drift in its inputs or state pytree?)")


@contextlib.contextmanager
def no_tracer_leaks():
    """Fail on tracers escaping their trace (wraps jax.checking_leaks)."""
    with jax.checking_leaks():
        yield
