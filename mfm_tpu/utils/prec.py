"""Matmul-precision scoping for the parity-critical compute path.

TPU MXU matmuls default to bf16 passes (~1e-3 relative error), which would
silently break the framework's 1e-5 parity contract with the float64
reference (first observed as ~2e-3 relative asymmetry in the final
covariance produced by the CLI demo).  Rather than mutating the process-wide
JAX default — which would leak a ~3-6x MXU slowdown into unrelated JAX code
that merely imports this package — every public compute function is wrapped
in :func:`highest_matmul_precision`, scoping full-f32 matmuls to ops traced
inside this framework.  The setting is deliberately not caller-overridable
(the decorator re-enters the context inside each function, so an enclosing
``jax.default_matmul_precision`` has no effect on package internals):
matmul precision here is part of the parity contract, not a tuning knob.
Callers' own ops outside these functions are untouched.
"""

from __future__ import annotations

import functools

import jax


def highest_matmul_precision(fn):
    """Trace ``fn``'s ops under full-precision (f32) MXU matmuls."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("highest"):
            return fn(*args, **kwargs)

    return wrapped
