"""Model-health report over a risk results directory.

The reference's quality control is notebook eyeballing: factor time-series
plots (``beta.ipynb`` cell 17, ``data_pre.ipynb`` cell 9), the R² saved per
date (``demo.py:70-72``), the λ multiplier series (``demo.py:90-94``), and
the eigenfactor bias picture (``mfm/utils.py:116``).  This module turns that
into a first-class driver: one JSON health summary plus one small-multiples
PNG, computed from the result tables the ``risk``/``pipeline`` subcommands
write (``factor_returns.csv``, ``r_squared.csv``, ``lambda.csv``, and — when
present — ``specific_returns.csv`` plus the optional JSON artifacts:
``bias_stats.json``, ``portfolio_bias.json``, ``portfolio_risk.json``,
``alpha_styles.json``).

Everything here is host-side pandas over small result tables; no JAX.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pandas as pd

# fixed-order categorical palette, assigned over the selected factors in the
# result table's own column order (deterministic for a given results set);
# factors beyond the palette fold into gray
_PALETTE = ["#3b6ccc", "#e2862d", "#2e9e77", "#c4534f", "#8b67c9", "#937264"]
_FOLD_COLOR = "#b8bcc4"
_ACCENT = "#3b6ccc"
_GRID = {"color": "#e4e6ea", "lw": 0.6}


def _read_series_table(results_dir: str, name: str) -> pd.DataFrame | None:
    path = os.path.join(results_dir, name)
    if not os.path.exists(path):
        return None
    df = pd.read_csv(path, index_col=0)
    df.index = pd.to_datetime(df.index.astype(str))
    return df


def load_results(results_dir: str) -> dict:
    """Read whatever result tables exist under ``results_dir``.

    Returns a dict with ``factor_returns`` / ``r_squared`` / ``lambda`` /
    ``specific_returns`` DataFrames (absent keys omitted) plus the parsed
    optional JSON artifacts when present: ``bias_stats`` /
    ``portfolio_bias`` / ``portfolio_risk`` / ``alpha_styles``.
    ``factor_returns`` is required — a results dir without it is not a
    risk-run output.
    """
    out = {}
    for key, fname in (("factor_returns", "factor_returns.csv"),
                       ("r_squared", "r_squared.csv"),
                       ("lambda", "lambda.csv"),
                       ("specific_returns", "specific_returns.csv")):
        df = _read_series_table(results_dir, fname)
        if df is not None:
            out[key] = df
    if "factor_returns" not in out:
        raise FileNotFoundError(
            f"{results_dir}/factor_returns.csv not found — run the `risk` or "
            "`pipeline` subcommand into this directory first")
    for key, fname in (("bias_stats", "bias_stats.json"),
                       ("portfolio_bias", "portfolio_bias.json"),
                       ("portfolio_risk", "portfolio_risk.json"),
                       ("alpha_styles", "alpha_styles.json")):
        path = os.path.join(results_dir, fname)
        if os.path.exists(path):
            with open(path) as fh:
                out[key] = json.load(fh)
    return out


def _num(x):
    x = float(x)
    return None if not np.isfinite(x) else round(x, 6)


def _bias_scope(bias_stats: dict) -> tuple[str | None, dict]:
    """Pick the scope to report from a ``bias_stats_summary`` dict: the
    burn-in-excluded one when present (keys are ``after_burn_in_{n}``,
    :func:`mfm_tpu.models.bias.bias_stats_summary`), else all valid dates."""
    for key in bias_stats:
        if key.startswith("after_burn_in"):
            return key, bias_stats[key]
    if "all_valid_dates" in bias_stats:
        return "all_valid_dates", bias_stats["all_valid_dates"]
    return None, {}


def model_health_summary(results_dir: str, ann_factor: int = 252,
                         roll_window: int = 63, res: dict | None = None) -> dict:
    """The three model-health metrics the reference tracks (R² per date,
    bias statistics, λ series; SURVEY §5 observability) plus per-factor
    return/vol attribution, as one JSON-able dict.  ``res``: an already-
    loaded :func:`load_results` dict, to avoid re-reading the tables."""
    res = load_results(results_dir) if res is None else res
    fr = res["factor_returns"]
    valid = fr.dropna(how="all")
    summary: dict = {
        "results_dir": os.path.abspath(results_dir),
        "dates": {"first": str(valid.index[0].date()),
                  "last": str(valid.index[-1].date()),
                  "count": int(len(valid))},
    }

    cum = valid.fillna(0.0).cumsum()
    vol = valid.std(ddof=1) * np.sqrt(ann_factor)
    per_factor = pd.DataFrame({
        "cum_return": cum.iloc[-1],
        "ann_vol": vol,
    }).sort_values("cum_return", ascending=False)
    summary["factors"] = {
        name: {"cum_return": _num(row.cum_return), "ann_vol": _num(row.ann_vol)}
        for name, row in per_factor.iterrows()
    }

    if "r_squared" in res:
        r2 = res["r_squared"].iloc[:, 0].dropna()
        recent = r2.tail(roll_window)
        summary["r2"] = {
            "mean": _num(r2.mean()), "median": _num(r2.median()),
            "p10": _num(r2.quantile(0.10)), "p90": _num(r2.quantile(0.90)),
            f"last_{roll_window}d_mean": _num(recent.mean()),
        }
    if "lambda" in res:
        lam = res["lambda"].iloc[:, 0].dropna()
        summary["lambda"] = {
            "last": _num(lam.iloc[-1]) if len(lam) else None,
            "mean": _num(lam.mean()), "min": _num(lam.min()),
            "max": _num(lam.max()),
        }
    if "specific_returns" in res:
        disp = res["specific_returns"].std(axis=1, ddof=1).dropna()
        summary["specific_dispersion"] = {
            "mean_xsec_std": _num(disp.mean()),
            "last": _num(disp.iloc[-1]) if len(disp) else None,
        }
    if "bias_stats" in res:
        scope_name, scope = _bias_scope(res["bias_stats"])
        summary["bias"] = {
            label: {"mean_abs_dev_from_1": d.get("mean_abs_dev_from_1")}
            for label, d in scope.items() if isinstance(d, dict)
        }
        summary["bias"]["scope"] = scope_name
    if "portfolio_bias" in res:
        scope_name, scope = _bias_scope(res["portfolio_bias"])
        summary["portfolio_bias"] = {
            "scope": scope_name,
            "n_portfolios": res["portfolio_bias"].get("n_portfolios"),
            "mean": scope.get("mean"),
            "median": scope.get("median"),
            "mean_abs_dev_from_1": scope.get("mean_abs_dev_from_1"),
        }
    if "portfolio_risk" in res:
        pr = res["portfolio_risk"]
        summary["portfolio_risk"] = {
            "date": pr.get("date"),
            "total_vol": pr.get("total_vol"),
            "factor_var": pr.get("factor_var"),
            "specific_var": pr.get("specific_var"),
        }
    if "alpha_styles" in res:
        summary["alpha_styles"] = {
            name: {"expression": d.get("expression"),
                   "mean_ic": d.get("mean_ic")}
            for name, d in res["alpha_styles"].items()
        }
    return summary


def _style(ax, title):
    ax.set_title(title, fontsize=9, loc="left")
    ax.grid(True, **_GRID)
    ax.set_axisbelow(True)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    ax.tick_params(labelsize=7)


def plot_model_health(results_dir: str, path: str, top_k: int = 6,
                      roll_window: int = 63, res: dict | None = None) -> None:
    """Render the health report as a 2×2 small-multiples PNG.

    Panels: cumulative factor returns (top ``top_k`` by |cum return|,
    direct-labelled; the rest folded as thin gray), the R² series with its
    rolling mean, the λ multiplier series, and the bias statistic per
    eigenfactor rank when ``bias_stats.json`` exists (per-factor annualized
    vol bars otherwise).  Uses an explicit Agg canvas so the process-global
    matplotlib backend is untouched (same idiom as
    :func:`mfm_tpu.models.bias.plot_bias_stats`).
    """
    from matplotlib.backends.backend_agg import FigureCanvasAgg
    from matplotlib.figure import Figure

    res = load_results(results_dir) if res is None else res
    fr = res["factor_returns"].dropna(how="all")
    cum = fr.fillna(0.0).cumsum()

    fig = Figure(figsize=(11, 7))
    FigureCanvasAgg(fig)
    axes = fig.subplots(2, 2)

    # (a) cumulative factor returns — identity in fixed palette order over
    # the selected factors, the rest folded into gray ("Other")
    ax = axes[0][0]
    order = cum.iloc[-1].abs().sort_values(ascending=False).index
    # selected factors keep the table's own column order so the palette
    # assignment is deterministic for a results set, not a rank artifact
    top = [c for c in cum.columns if c in set(order[:max(top_k, 0)])]
    for col in cum.columns:
        if col not in top:
            ax.plot(cum.index, cum[col], color=_FOLD_COLOR, lw=0.7, zorder=1)
    span = (float(cum[top].to_numpy().max() - cum[top].to_numpy().min()) or 1.0
            if top else 1.0)
    labelled_ys: list[float] = []
    for i, col in enumerate(top):
        c = _PALETTE[i % len(_PALETTE)]
        ax.plot(cum.index, cum[col], color=c, lw=1.6, zorder=2, label=col)
        y = float(cum[col].iloc[-1])
        # direct labels are selective: skip any that would collide with an
        # already-placed one (the legend still carries identity)
        if all(abs(y - y0) > 0.04 * span for y0 in labelled_ys):
            ax.annotate(f" {col}", (cum.index[-1], y), fontsize=7, color=c,
                        va="center")
            labelled_ys.append(y)
    if len(cum.columns) > len(top):
        ax.plot([], [], color=_FOLD_COLOR, lw=0.7,
                label=f"other ({len(cum.columns) - len(top)})")
    ax.legend(fontsize=6, loc="upper left", frameon=False)
    _style(ax, f"cumulative factor returns (top {len(top)} by |cum|)")

    # (b) R² per date + rolling mean
    ax = axes[0][1]
    if "r_squared" in res:
        r2 = res["r_squared"].iloc[:, 0]
        ax.plot(r2.index, r2, color=_FOLD_COLOR, lw=0.6)
        roll = r2.rolling(roll_window, min_periods=roll_window // 3).mean()
        ax.plot(roll.index, roll, color=_ACCENT, lw=1.6,
                label=f"{roll_window}d mean")
        ax.legend(fontsize=6, loc="upper left", frameon=False)
        ax.set_ylim(0, 1)
    _style(ax, "cross-sectional regression R²")

    # (c) λ multiplier series
    ax = axes[1][0]
    if "lambda" in res:
        lam = res["lambda"].iloc[:, 0]
        ax.plot(lam.index, lam, color=_ACCENT, lw=1.2)
        ax.axhline(1.0, color="#888", lw=0.8, ls="--")
    _style(ax, "vol-regime multiplier λ")

    # (d) bias per eigen rank when available, else annualized factor vols
    ax = axes[1][1]
    if "bias_stats" in res:
        scope_name, scope = _bias_scope(res["bias_stats"])
        for i, (label, d) in enumerate(sorted(scope.items())):
            if not isinstance(d, dict) or "bias" not in d:
                continue
            b = np.array([np.nan if v is None else v for v in d["bias"]])
            ax.plot(1 + np.arange(b.shape[0]), b, marker="o", ms=2.5, lw=1,
                    color=_PALETTE[i % len(_PALETTE)], label=label)
        ax.axhline(1.0, color="#888", lw=0.8, ls="--")
        from matplotlib.ticker import MaxNLocator
        ax.xaxis.set_major_locator(MaxNLocator(integer=True))
        ax.set_xlabel("eigenfactor rank", fontsize=7)
        ax.legend(fontsize=6, frameon=False)
        _style(ax, f"eigenfactor bias statistic by rank ({scope_name})")
    else:
        vol = (fr.std(ddof=1) * np.sqrt(252)).sort_values(ascending=False)[:10]
        ax.barh(np.arange(len(vol))[::-1], vol.to_numpy(), height=0.62,
                color=_ACCENT)
        ax.set_yticks(np.arange(len(vol))[::-1], vol.index, fontsize=6)
        _style(ax, "annualized factor vol (top 10)")

    fig.tight_layout()
    fig.savefig(path, dpi=120)
