"""Observability: structured logging, stage timing, determinism checks.

The reference's observability is bare prints and tqdm bars scattered through
every file (SURVEY.md §5); its "race detector" is nonexistence (single
thread).  Here:

- :func:`log` — structured JSON-lines logging with levels.
- :class:`StageTimer` — wall-clock per pipeline stage, with the host-transfer
  forcing required on async dispatch backends (on this TPU tunnel
  ``block_until_ready`` returns before execution finishes, so timing must
  force a scalar transfer).
- :func:`determinism_check` — runs a function twice and compares results
  bitwise; the batch-job replacement for a race detector (SURVEY.md §5:
  same-seed => bitwise-equal outputs).
- :func:`trace_annotation` — named ``jax.profiler`` trace spans.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp

def set_log_level(level: str) -> None:
    from mfm_tpu.obs.exporters import default_event_log

    default_event_log().set_level(level)


def log(level: str, event: str, **fields) -> None:
    """Structured JSONL event — now a thin shim over the
    :mod:`mfm_tpu.obs.exporters` event stream (stderr by default; a CLI run
    with ``--metrics-dir`` routes the same stream to ``events.jsonl``)."""
    from mfm_tpu.obs.exporters import emit_event

    emit_event(level, event, **fields)


def force(tree):
    """Force execution + tiny host transfer of a pytree of arrays.

    Returns the summed checksum over floating leaves (useful for timing and
    smoke assertions).  ALL array leaves are forced — int/bool arrays don't
    join the checksum, but on async-dispatch backends they must still be
    blocked on individually, or a pytree of only int leaves could return
    before execution completes.
    """
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "dtype")]
    float_leaves = [x for x in leaves
                    if jnp.issubdtype(x.dtype, jnp.floating)]
    for x in leaves:
        jax.block_until_ready(x)
    if not float_leaves:
        return 0.0
    total = sum(jnp.sum(jnp.where(jnp.isfinite(x), x, 0.0))
                for x in float_leaves)
    return float(np.asarray(total))


class StageTimer:
    """Accumulates wall-clock per named stage; emits a structured summary."""

    def __init__(self, name: str = "pipeline"):
        self.name = name
        self.stages: dict[str, float] = {}

    @contextlib.contextmanager
    def stage(self, name: str, result_holder=None):
        t0 = time.perf_counter()
        yield
        if result_holder is not None:
            force(result_holder)
        self.stages[name] = self.stages.get(name, 0.0) + time.perf_counter() - t0

    def summary(self) -> dict:
        total = sum(self.stages.values())
        return {"name": self.name, "total_s": round(total, 4),
                **{k: round(v, 4) for k, v in self.stages.items()}}

    def emit(self) -> None:
        log("info", "stage_timing", **self.summary())


def determinism_check(fn: Callable, *args, atol: float = 0.0) -> bool:
    """Run ``fn`` twice; True iff outputs agree within atol (0 = bitwise).

    With keyed jax.random and no data races there is no legitimate source of
    run-to-run divergence — this is the framework's sanitizer.
    """
    a = jax.tree_util.tree_leaves(fn(*args))
    b = jax.tree_util.tree_leaves(fn(*args))
    for x, y in zip(a, b):
        x = np.asarray(x)
        y = np.asarray(y)
        if atol == 0.0:
            same = np.array_equal(x, y, equal_nan=True)
        else:
            same = np.allclose(x, y, atol=atol, equal_nan=True)
        if not same:
            return False
    return True


def compiled_memory(fn: Callable, *args, static_argnames=()) -> dict:
    """Peak-memory breakdown of ``fn`` compiled for ``args``, in bytes.

    Lowers and compiles ``jax.jit(fn)`` (hits the persistent compile cache
    when warm) and reads XLA's buffer-assignment totals: ``temp_bytes`` is
    the transient high-water mark — the scratch the program needs beyond
    its inputs and outputs, exactly the quantity the eigen Monte-Carlo's
    chunked stream is designed to bound — and ``peak_bytes`` adds the
    argument/output residency for the whole-program figure.  Static
    analysis, so it costs a compile but no execution.
    """
    compiled = jax.jit(fn, static_argnames=static_argnames).lower(
        *args).compile()
    m = compiled.memory_analysis()
    if m is None:  # backends without buffer-assignment stats
        return {}
    temp = int(m.temp_size_in_bytes)
    arg = int(m.argument_size_in_bytes)
    out = int(m.output_size_in_bytes)
    alias = int(m.alias_size_in_bytes)
    return {
        "temp_bytes": temp,
        "argument_bytes": arg,
        "output_bytes": out,
        "alias_bytes": alias,
        "generated_code_bytes": int(m.generated_code_size_in_bytes),
        # aliased bytes live in the argument total; don't double-count them
        "peak_bytes": temp + arg + out - alias,
    }


@contextlib.contextmanager
def trace_annotation(name: str):
    """Named span visible in jax.profiler traces."""
    with jax.profiler.TraceAnnotation(name):
        yield
