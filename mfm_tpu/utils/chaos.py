"""Deterministic fault injection for the serving-hardening suite.

Production daily-batch systems fail on bad days and bad restarts, not bad
math.  This module makes both reproducible:

- **Crash points** (:func:`chaos_point`): named markers compiled into the
  checkpoint write path (data/artifacts.py).  Setting
  ``MFM_CHAOS_KILL=<point>`` in a subprocess's environment SIGKILLs the
  process AT that exact protocol step — a *deterministic* "kill -9 mid
  write", no racy timers.  ``MFM_CHAOS_KILL_MATCH`` optionally restricts
  the kill to paths containing a substring.  Zero cost when the variable
  is unset (one dict lookup).
- **Byte-level faults** (:func:`truncate_file`, :func:`corrupt_file`):
  seeded truncation / bit-flips on an existing checkpoint, modelling torn
  writes and silent media corruption.
- **Data faults** (:func:`poison_nan`, :func:`poison_outliers`,
  :func:`poison_universe`): seeded slab poisoning for the input-guard
  checks (serve/guard.py).
- **Transport faults** (:class:`FlakyStore`, :func:`flaky`): wrappers that
  fail the first N calls with a chosen exception — the retry-path drill
  for ``data/etl.py::with_retry``.
- **Fault plans** (:func:`plan_suite`): the named, seeded scenario matrix
  ``tools/faultinject.py`` and ``tests/test_chaos.py`` drive.

Everything here is host-side tooling: nothing imports jax, nothing is
traced, and the only coupling to the serving path is the two
``chaos_point`` call sites in ``save_artifact``.
"""

from __future__ import annotations

import dataclasses
import os
import signal

#: env var naming the crash point to SIGKILL at (e.g.
#: ``save_artifact.after_tmp``); optional ``MFM_CHAOS_KILL_MATCH`` narrows
#: to paths containing the given substring
KILL_ENV = "MFM_CHAOS_KILL"
KILL_MATCH_ENV = "MFM_CHAOS_KILL_MATCH"

#: the crash points compiled into the write protocol, in order
CRASH_POINTS = (
    "save_artifact.after_tmp",     # tmp durable, final file still the old one
    "save_artifact.after_rename",  # new file live, pointer not yet swapped
    "run_manifest.after_tmp",      # checkpoint live, manifest tmp not yet
                                   # renamed (obs/manifest.py)
    "serve.after_batch",           # query loop: batch i's responses emitted,
                                   # batch i+1 not yet drained; path is
                                   # "batch{i}" so MFM_CHAOS_KILL_MATCH pins
                                   # the kill to an exact batch
                                   # (serve/server.py)
    "scenario_manifest.after_tmp",  # scenario batch computed, manifest tmp
                                    # not yet renamed (scenario/manifest.py)
    "trace.after_tmp",             # Chrome-trace flush: tmp durable, final
                                   # trace.json not yet renamed (obs/trace.py)
    "grad_report.after_tmp",       # grad solve done, grad_report.json tmp
                                   # not yet renamed (grad/report.py)
    "sweep_manifest.after_tmp",    # streaming sweep done, sweep_manifest
                                   # tmp not yet renamed (scenario/sweep.py)
    "flightrec.after_tmp",         # flight-recorder dump: tmp durable, final
                                   # flightrec.json not yet renamed
                                   # (obs/flightrec.py)
)


def chaos_point(name: str, path: str = "") -> None:
    """SIGKILL this process iff ``MFM_CHAOS_KILL`` names this point (and
    ``MFM_CHAOS_KILL_MATCH``, when set, is a substring of ``path``).

    SIGKILL — not sys.exit, not an exception — because the contract under
    test is crash *atomicity*: no cleanup handler may run, exactly like a
    power cut or an OOM kill.
    """
    if os.environ.get(KILL_ENV) != name:
        return
    match = os.environ.get(KILL_MATCH_ENV)
    if match and match not in path:
        return
    os.kill(os.getpid(), signal.SIGKILL)


# -- byte-level checkpoint faults -------------------------------------------

def truncate_file(path: str, n_bytes: int) -> int:
    """Drop the last ``n_bytes`` of ``path`` (a torn tail write).  Returns
    the new size."""
    size = os.path.getsize(path)
    new = max(0, size - int(n_bytes))
    with open(path, "rb+") as f:
        f.truncate(new)
    return new


def corrupt_file(path: str, n_bytes: int, seed: int) -> list[int]:
    """Flip one bit in each of ``n_bytes`` seeded random positions of
    ``path`` (silent media corruption).  Returns the offsets touched."""
    import numpy as np

    size = os.path.getsize(path)
    rng = np.random.default_rng(seed)
    offsets = sorted(int(o) for o in
                     rng.choice(size, size=min(int(n_bytes), size),
                                replace=False))
    with open(path, "rb+") as f:
        for off in offsets:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ (1 << int(rng.integers(8)))]))
    return offsets


# -- slab data faults --------------------------------------------------------

def poison_nan(ret, dates, frac: float = 1.0, seed: int = 0):
    """NaN-poison a seeded ``frac`` of each listed date's return row.
    ``ret`` is modified in place ((T, N) float array); returns it."""
    import numpy as np

    rng = np.random.default_rng(seed)
    for t in dates:
        n = ret.shape[1]
        k = max(1, int(round(frac * n)))
        cols = rng.choice(n, size=k, replace=False)
        ret[t, cols] = np.nan
    return ret


def poison_outliers(ret, dates, magnitude: float = 5.0, frac: float = 0.3,
                    seed: int = 0):
    """Blow up a seeded ``frac`` of each listed date's returns to
    ``±magnitude`` (fat-finger / bad-split day).  In place; returns ret."""
    import numpy as np

    rng = np.random.default_rng(seed)
    for t in dates:
        n = ret.shape[1]
        k = max(1, int(round(frac * n)))
        cols = rng.choice(n, size=k, replace=False)
        ret[t, cols] = magnitude * rng.choice([-1.0, 1.0], size=k)
    return ret


def poison_universe(valid, dates, keep_frac: float = 0.2, seed: int = 0):
    """Collapse the listed dates' universes to a seeded ``keep_frac`` of
    their stocks (upstream join loss).  In place; returns valid."""
    import numpy as np

    rng = np.random.default_rng(seed)
    for t in dates:
        idx = np.nonzero(valid[t])[0]
        drop = rng.choice(idx, size=int(round((1 - keep_frac) * idx.size)),
                          replace=False)
        valid[t, drop] = False
    return valid


# -- transport faults --------------------------------------------------------

def flaky(fn, n_failures: int, exc_factory=ConnectionError):
    """Wrap ``fn`` to raise ``exc_factory(...)`` on its first ``n_failures``
    calls, then behave normally — the deterministic transient-error source
    for the ``with_retry`` drill."""
    state = {"left": int(n_failures)}

    def wrapped(*a, **kw):
        if state["left"] > 0:
            state["left"] -= 1
            raise exc_factory(f"chaos: injected transient failure "
                              f"({state['left']} more)")
        return fn(*a, **kw)

    return wrapped


class FlakyStore:
    """PanelStore proxy whose chosen methods fail the first N calls each
    with a transient error, then delegate — exercised against
    ``IncrementalUpdater``-style retry loops."""

    def __init__(self, inner, n_failures: int = 2,
                 methods: tuple = ("insert",), exc_factory=ConnectionError):
        self._inner = inner
        self._left = {m: int(n_failures) for m in methods}
        self._exc = exc_factory

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name not in self._left or not callable(attr):
            return attr

        def wrapped(*a, **kw):
            if self._left[name] > 0:
                self._left[name] -= 1
                raise self._exc(f"chaos: {name} transient failure")
            return attr(*a, **kw)

        return wrapped


# -- the seeded fault-plan matrix -------------------------------------------

@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One named, fully-seeded fault scenario.  ``kind`` selects the
    mechanism; ``params`` feed it; ``seed`` pins every random choice so a
    failing plan replays exactly."""

    name: str
    kind: str        # truncate | corrupt | kill | kill_manifest | nan_slab |
                     # outlier_slab | universe_slab | flaky_store |
                     # query_kill | query_poison | query_overflow |
                     # query_swap | query_steady | scenario_kill |
                     # scenario_poison | trace_kill | eigen_kill |
                     # shard_kill | grad_kill | fleet_kill |
                     # fleet_kill_host | fleet_wedge | flightrec_kill
    seed: int = 0
    params: tuple = ()   # ((key, value), ...) — hashable, printable

    def param(self, key, default=None):
        return dict(self.params).get(key, default)


def plan_suite(seed: int = 0) -> tuple:
    """The standard recovery matrix: every mechanism at least once, seeds
    derived from ``seed`` so the whole suite replays deterministically."""
    s = int(seed)
    return (
        FaultPlan("truncate-tail-64", "truncate", s + 1,
                  (("n_bytes", 64),)),
        FaultPlan("truncate-half", "truncate", s + 2,
                  (("frac", 0.5),)),
        FaultPlan("corrupt-8-bytes", "corrupt", s + 3,
                  (("n_bytes", 8),)),
        FaultPlan("kill-after-tmp", "kill", s + 4,
                  (("point", "save_artifact.after_tmp"),)),
        FaultPlan("kill-after-rename", "kill", s + 5,
                  (("point", "save_artifact.after_rename"),)),
        FaultPlan("nan-slab", "nan_slab", s + 6,
                  (("frac", 1.0),)),
        FaultPlan("outlier-slab", "outlier_slab", s + 7,
                  (("magnitude", 5.0), ("frac", 0.3))),
        FaultPlan("universe-collapse", "universe_slab", s + 8,
                  (("keep_frac", 0.2),)),
        FaultPlan("flaky-store", "flaky_store", s + 9,
                  (("n_failures", 2),)),
        FaultPlan("kill-at-manifest", "kill_manifest", s + 10,
                  (("point", "run_manifest.after_tmp"),)),
        # query-loop plans (tools/faultinject.py RUNNERS): the request-side
        # robustness matrix of the batched portfolio-query service
        FaultPlan("query-kill-mid-batch", "query_kill", s + 11,
                  (("point", "serve.after_batch"), ("match", "batch1"))),
        FaultPlan("query-poison-slab", "query_poison", s + 12,
                  (("n_poison", 6),)),
        FaultPlan("query-overflow-storm", "query_overflow", s + 13,
                  (("queue_max", 8), ("storm", 24))),
        FaultPlan("query-ckpt-swap", "query_swap", s + 14,
                  (("corrupt_bytes", 8),)),
        FaultPlan("query-steady-state", "query_steady", s + 15,
                  (("rounds", 6),)),
        # scenario-engine plans: manifest crash atomicity + per-lane
        # rejection isolation of the batched stress runner
        # (mfm_tpu/scenario/)
        FaultPlan("scenario-kill-mid-batch", "scenario_kill", s + 16,
                  (("point", "scenario_manifest.after_tmp"),)),
        FaultPlan("scenario-poison-spec", "scenario_poison", s + 17,
                  (("n_poison", 3),)),
        # tracing: SIGKILL mid trace-flush must leave no torn trace file
        # and an untouched (bitwise) checkpoint (obs/trace.py)
        FaultPlan("trace-kill-mid-flush", "trace_kill", s + 18,
                  (("point", "trace.after_tmp"),)),
        # incremental eigen (config.eigen_incremental): SIGKILL while the
        # eigen-carry checkpoint (eig_R/eig_p/eig_n + frozen draws) is
        # being saved — the prior generation must stay bitwise intact and
        # the replay must land on the fault-free carry
        FaultPlan("eigen-kill-mid-update", "eigen_kill", s + 19,
                  (("point", "save_artifact.after_tmp"),)),
        # sharded serving (PR 11): SIGKILL between the checkpoint's tmp
        # write and its rename while the append's ONE update step ran on
        # a ('date','stock') device mesh — sharding must change nothing
        # about the fence: the prior generation stays byte-identical on
        # disk and the replay lands bitwise on the fault-free run
        FaultPlan("shard-kill-mid-append", "shard_kill", s + 20,
                  (("point", "save_artifact.after_tmp"), ("mesh", "2x2"))),
        # differentiable risk (mfm_tpu/grad/): SIGKILL between the grad
        # report's tmp write and its rename — no torn grad_report.json,
        # checkpoint bytes untouched, clean re-run doctor-green
        FaultPlan("grad-kill-mid-solve", "grad_kill", s + 21,
                  (("point", "grad_report.after_tmp"),)),
        # serving fleet (PR 15): SIGKILL one of three worker replicas
        # after it computed a batch but before its envelopes reached the
        # pipe — the survivors keep answering (the front end re-dispatches
        # the dead replica's batch), every response is bitwise the
        # single-process replay's, the merged fleet manifest counts the
        # loss while its delivery audit balances, and the checkpoint's
        # bytes stay untouched
        FaultPlan("fleet-kill-replica", "fleet_kill", s + 22,
                  (("point", "serve.after_batch"), ("match", "batch1"),
                   ("replica", 1), ("replicas", 3))),
        # response cache (PR 16): hot-reload the checkpoint mid-stream
        # under a cache-fronted server — no post-reload response may
        # equal a pre-reload cached body (the generation fence makes the
        # old entries unreachable), and a SIGKILL mid-reload replays
        # bitwise against a cache-off run of the same stream
        FaultPlan("cache-stale-generation", "cache_stale", s + 23,
                  (("point", "save_artifact.after_tmp"),
                   ("repeats", 6))),
        # streaming sweeps (PR 17): SIGKILL a real `scenario sweep`
        # between the sweep manifest's tmp write and its rename — no
        # torn sweep_manifest.json, checkpoint bytes untouched, and a
        # clean seeded re-run lands byte-equal modulo the volatile obs
        # summary block
        FaultPlan("sweep-kill-mid-stream", "sweep_kill", s + 24,
                  (("point", "sweep_manifest.after_tmp"),)),
        # mfmsync race harness (PR 18): deterministic-interleaving
        # schedule drills.  A seeded cooperative scheduler
        # (mfm_tpu/utils/sched.py) serializes real threads through
        # instrumented lock/condition hooks, so each seed IS a hostile
        # interleaving — replayable bit-for-bit.  The coalescer drill
        # races T submitters against a flusher (then hammers a live
        # socket frontend) and requires responses bitwise == the
        # sequential loop per id; the cache drill storms hit/miss/put
        # while a fencer moves the generation mid-storm and requires
        # hits byte-equal cold, LRU bounds intact, and a monotone fence
        FaultPlan("sync-schedule-coalescer", "sync_schedule_coalescer",
                  s + 25, (("seeds", 10), ("threads", 3), ("n", 12),
                           ("hammer_threads", 4), ("hammer_n", 32))),
        FaultPlan("sync-schedule-cache", "sync_schedule_cache", s + 26,
                  (("seeds", 10), ("threads", 3), ("ops", 10),
                   ("bodies", 6), ("max_entries", 4),
                   ("max_bytes", 4096))),
        # multi-host fleet (PR 19): kill a whole simulated host mid-storm.
        # 2 hosts x 2 workers; both of host 1's workers die by SIGKILL
        # while another worker sits SIGSTOPped — wedged, not dead: its
        # pipes stay open but nothing ever answers, the failure mode an
        # EOF check cannot see.  The survivors must answer EVERY request
        # (compared by id — live feeding makes batch boundaries
        # timing-dependent) bitwise the fault-free replay's, the merged
        # manifest must count the dead as lost and the stopped as wedged
        # with a balanced delivery audit, and no flush may block past the
        # per-I/O deadline + heartbeat budget
        # n=96 (batch-max 8): 6 post-storm batches — enough dispatch
        # rounds that the starve_rounds guard provably routes the router
        # onto every undiscovered faulty worker before the stream ends
        FaultPlan("fleet-kill-host", "fleet_kill_host", s + 27,
                  (("hosts", 2), ("workers_per_host", 2),
                   ("kill_host", 1), ("wedge", 1), ("n", 96))),
        # one SIGSTOPped worker mid-storm, nothing killed: the heartbeat
        # ping (or the per-I/O deadline on its next batch) must
        # quarantine it within heartbeat_s + the I/O timeout, its batch
        # re-dispatches exactly like a death, and the wedge lands in the
        # transport counters (heartbeat_misses / io_timeouts) without
        # unbalancing the audit
        FaultPlan("fleet-wedge-worker", "fleet_wedge", s + 28,
                  (("replicas", 3), ("wedge", 1), ("n", 96))),
        # flight recorder (PR 20): SIGKILL between the flightrec dump's
        # tmp write and its rename — no torn flightrec.json (the prior
        # dump, if any, stays intact), checkpoint bytes untouched, and a
        # clean re-run's dump parses with the triggering trace id
        FaultPlan("flightrec-kill-mid-dump", "flightrec_kill", s + 29,
                  (("point", "flightrec.after_tmp"),)),
    )
