"""Deterministic-interleaving scheduler for race confirmation.

mfmsync (mfm_tpu/analysis/sync.py) reports lock-discipline hazards
statically; this module makes them *confirmable*.  A
:class:`DetScheduler` runs real ``threading.Thread`` workers but fully
serializes them: exactly one thread is ever runnable, and every context
switch happens at an instrumented yield point (lock acquire/release,
condition wait/notify, queue put/get, or an explicit
:meth:`~DetScheduler.yield_point`).  The switch decision is drawn from
``random.Random(seed)``, so a seed IS an interleaving — the same seed
replays the same schedule bit-for-bit, and sweeping seeds explores
adversarial schedules without ``sys.settrace`` overhead or flaky
sleep-based races.

The primitives (:class:`DetLock`, :class:`DetRLock`,
:class:`DetCondition`, :class:`DetQueue`) mirror the stdlib API surface
the serving fleet uses (``with lock:``, ``cond.wait(timeout)``,
``cond.notify_all()``, ``q.put/get``), so a harness can transplant them
into live objects::

    s = DetScheduler(seed=7)
    co._lock = DetRLock(s, "coalesce")
    co._wake = DetCondition(s, co._lock)
    s.spawn(lambda: co.submit(line), name="client-0")
    s.run()

Timed ``wait(timeout=...)`` calls model the adversary's spurious
wakeup: the waiter becomes schedulable immediately, because a timeout
can always fire before the notify.  Untimed waits genuinely require a
notify.  If no thread is runnable and some are still alive, ``run()``
raises :class:`DeadlockError` with a state dump — a deterministic
reproduction of the deadlock mfmsync's S2 rule predicts.

Used by the ``sync-schedule-coalescer`` / ``sync-schedule-cache``
faultinject plans and tests/test_mfmsync.py.  Stdlib-only.
"""

from __future__ import annotations

import random
import threading
from collections import deque


class DeadlockError(RuntimeError):
    """No runnable thread, but not all threads finished."""


class SchedulerError(RuntimeError):
    """Misuse of the scheduler (step-cap blown, bad release, ...)."""


class DetScheduler:
    """Seeded cooperative scheduler; one runnable thread at a time."""

    #: hard cap on context switches — a livelocked schedule fails loudly
    MAX_STEPS = 1_000_000

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._cv = threading.Condition()
        self._threads: dict[int, threading.Thread] = {}
        self._names: dict[int, str] = {}
        #: tid -> None (unconditionally runnable) or 0-arg predicate
        self._runnable: dict[int, object] = {}
        self._done: set[int] = set()
        self._failures: list = []
        self._current: int | None = None
        self._trace: list[str] = []
        self._labels: dict[int, str] = {}
        self._next_tid = 0
        self._tls = threading.local()

    # -- worker side ---------------------------------------------------------
    def spawn(self, fn, *args, name: str | None = None) -> int:
        """Register a worker.  It starts parked and only ever runs while
        the scheduler has elected it."""
        tid = self._next_tid
        self._next_tid += 1
        self._names[tid] = name or f"t{tid}"

        def body():
            self._tls.tid = tid
            with self._cv:
                while self._current != tid:
                    self._cv.wait()
            try:
                fn(*args)
            except BaseException as exc:  # surfaced by run()
                self._failures.append((self._names[tid], exc))
            finally:
                with self._cv:
                    self._done.add(tid)
                    self._runnable.pop(tid, None)
                    self._current = None
                    self._cv.notify_all()

        t = threading.Thread(target=body, name=self._names[tid], daemon=True)
        self._threads[tid] = t
        # S1 discipline: _runnable/_labels are written under _cv by the
        # workers; registration takes the same lock even though no
        # worker has started yet (spawn-while-running stays safe)
        with self._cv:
            self._runnable[tid] = None
            self._labels[tid] = "start"
        return tid

    def _me(self) -> int:
        tid = getattr(self._tls, "tid", None)
        if tid is None:
            raise SchedulerError("yield_point outside a spawned thread")
        return tid

    def yield_point(self, label: str, pred=None) -> None:
        """Park the calling worker and hand control back to the
        scheduler.  With ``pred``, the worker is only electable while
        ``pred()`` is true (lock free, item available, notified...)."""
        tid = self._me()
        with self._cv:
            self._runnable[tid] = pred
            self._labels[tid] = label
            self._current = None
            self._cv.notify_all()
            while self._current != tid:
                self._cv.wait()

    # -- scheduler side ------------------------------------------------------
    def _enabled(self) -> list[int]:
        out = []
        for tid in sorted(self._runnable):
            pred = self._runnable[tid]
            if pred is None or pred():
                out.append(tid)
        return out

    def run(self) -> list:
        """Drive every spawned worker to completion; returns the trace.
        Raises the first worker exception, or DeadlockError."""
        for t in self._threads.values():
            t.start()
        steps = 0
        while True:
            with self._cv:
                if len(self._done) == len(self._threads):
                    break
                enabled = self._enabled()
                if not enabled:
                    dump = ", ".join(
                        f"{self._names[t]}@{self._labels.get(t, '?')}"
                        for t in sorted(self._runnable))
                    raise DeadlockError(
                        f"seed={self.seed}: no runnable thread; "
                        f"blocked: [{dump}]")
                pick = enabled[self._rng.randrange(len(enabled))]
                self._trace.append(
                    f"{self._names[pick]}:{self._labels.get(pick, '?')}")
                self._runnable.pop(pick, None)
                self._current = pick
                self._cv.notify_all()
                while self._current is not None:
                    self._cv.wait()
            steps += 1
            if steps > self.MAX_STEPS:
                raise SchedulerError(f"seed={self.seed}: step cap blown")
        if self._failures:
            name, exc = self._failures[0]
            raise type(exc)(f"[worker {name}] {exc}") from exc
        return self.trace()

    def trace(self) -> list:
        """Decision log so far: 'name:label' per context switch.  Equal
        seeds produce equal traces — the determinism contract."""
        with self._cv:
            return list(self._trace)


class DetLock:
    """Non-reentrant lock with scheduler-visible acquire/release."""

    def __init__(self, sched: DetScheduler, name: str = "lock"):
        self._s = sched
        self.name = name
        self._owner: int | None = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = self._s._me()
        if self._owner == me:
            raise SchedulerError(f"{self.name}: re-acquire of "
                                 "non-reentrant DetLock (S2 confirmed)")
        self._s.yield_point(f"acquire:{self.name}",
                            pred=lambda: self._owner is None)
        self._owner = me
        return True

    def release(self) -> None:
        if self._owner != self._s._me():
            raise SchedulerError(f"{self.name}: release by non-owner")
        self._owner = None
        self._s.yield_point(f"release:{self.name}")

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class DetRLock(DetLock):
    """Reentrant variant (the coalescer uses RLock)."""

    def __init__(self, sched: DetScheduler, name: str = "rlock"):
        super().__init__(sched, name)
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = self._s._me()
        if self._owner == me:
            self._count += 1
            return True
        self._s.yield_point(f"acquire:{self.name}",
                            pred=lambda: self._owner is None)
        self._owner = me
        self._count = 1
        return True

    def release(self) -> None:
        if self._owner != self._s._me():
            raise SchedulerError(f"{self.name}: release by non-owner")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            self._s.yield_point(f"release:{self.name}")

    # condition support: hand the full recursion level over on wait()
    def _release_save(self):
        me, count = self._owner, self._count
        self._owner, self._count = None, 0
        return (me, count)

    def _acquire_restore(self, state) -> None:
        self._s.yield_point(f"reacquire:{self.name}",
                            pred=lambda: self._owner is None)
        self._owner, self._count = state


class DetCondition:
    """Condition over a Det(R)Lock.  Timed waits model the adversarial
    spurious wakeup (schedulable immediately); untimed waits require a
    notify."""

    def __init__(self, sched: DetScheduler, lock: DetLock | None = None):
        self._s = sched
        self._lock = lock if lock is not None else DetRLock(sched, "cond")
        self.name = f"cond({self._lock.name})"
        self._notified: set[int] = set()

    def _check_owned(self):
        if self._lock._owner != self._s._me():
            raise SchedulerError(f"{self.name}: used without holding "
                                 "its lock")

    def wait(self, timeout: float | None = None) -> bool:
        self._check_owned()
        me = self._s._me()
        if isinstance(self._lock, DetRLock):
            state = self._lock._release_save()
        else:
            self._lock._owner = None
            state = None
        if timeout is not None:
            # a timeout may always fire first: immediately electable
            self._s.yield_point(f"timedwait:{self.name}")
        else:
            self._s.yield_point(f"wait:{self.name}",
                                pred=lambda: me in self._notified)
        woke = me in self._notified
        self._notified.discard(me)
        if isinstance(self._lock, DetRLock):
            self._lock._acquire_restore(state)
        else:
            self._s.yield_point(f"reacquire:{self.name}",
                                pred=lambda: self._lock._owner is None)
            self._lock._owner = me
        return woke

    def _waiters(self) -> list[int]:
        pre = f"wait:{self.name}"
        tpre = f"timedwait:{self.name}"
        return [tid for tid, lab in self._s._labels.items()
                if tid in self._s._runnable and lab in (pre, tpre)]

    def notify(self, n: int = 1) -> None:
        self._check_owned()
        for tid in sorted(self._waiters())[:n]:
            self._notified.add(tid)

    def notify_all(self) -> None:
        self.notify(n=len(self._s._threads))

    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)

    def release(self):
        return self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False


class DetQueue:
    """Minimal instrumented queue: put parks when full (maxsize > 0),
    get parks when empty — each a scheduling decision point."""

    def __init__(self, sched: DetScheduler, maxsize: int = 0,
                 name: str = "queue"):
        self._s = sched
        self._max = maxsize
        self.name = name
        self._items: deque = deque()

    def put(self, item, block: bool = True, timeout=None) -> None:
        if self._max > 0:
            self._s.yield_point(f"put:{self.name}",
                                pred=lambda: len(self._items) < self._max)
        else:
            self._s.yield_point(f"put:{self.name}")
        self._items.append(item)

    def put_nowait(self, item) -> None:
        if self._max > 0 and len(self._items) >= self._max:
            raise SchedulerError(f"{self.name}: put_nowait on full queue")
        self._items.append(item)

    def get(self, block: bool = True, timeout=None):
        self._s.yield_point(f"get:{self.name}",
                            pred=lambda: len(self._items) > 0)
        return self._items.popleft()

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items
