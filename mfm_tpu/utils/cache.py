"""Persistent XLA compilation cache (per-machine, cross-process).

The config-5 batch — 1,000 alpha expressions in one jit — costs ~32.5 s of
XLA compile (BASELINE.md row 5), and chunking makes the total WORSE, so the
right fix is to pay the single-jit compile ONCE PER MACHINE instead of once
per process (round-4 VERDICT weak #6).  jax's persistent cache keys entries
by (optimized HLO, jaxlib version, XLA flags, device kind), so a cache hit
is exactly a re-compile of the same program on the same hardware — the CLI
and bench enable it by default.

Env override: ``MFM_COMPILATION_CACHE=/path`` relocates it,
``MFM_COMPILATION_CACHE=off`` disables it.
"""

from __future__ import annotations

import os

DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache", "mfm_tpu",
                           "xla")


def enable_persistent_compilation_cache(
    path: str | None = None, *, min_compile_secs: float = 1.0,
) -> str | None:
    """Point jax's compilation cache at a persistent directory.

    Returns the directory, or None when disabled (``off``/``none``/``0``).
    ``min_compile_secs`` skips trivially-recompilable programs; the cheap
    per-op jits stay out of the cache while every pipeline-scale program
    (the alpha batch, the risk step, the factor engine) lands in it.  Safe
    to call multiple times and before or after other jax.config updates;
    must run before the first compile to benefit it.
    """
    path = path or os.environ.get("MFM_COMPILATION_CACHE") or DEFAULT_DIR
    if str(path).lower() in ("0", "off", "none"):
        return None
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    try:
        # jax initializes the cache object lazily ONCE per process; a dir
        # configured after some earlier compile already initialized it
        # would silently keep writing to the old location
        from jax.experimental.compilation_cache import compilation_cache
        compilation_cache.reset_cache()
    except Exception:
        pass  # older jax: no reset hook; the config alone suffices there
    return path
