"""External factor cross-validation.

The reference's only external QC is a notebook comparison of its own size /
beta / momentum series against jqdatasdk's factor service for a single stock
(``beta.ipynb`` cells 29-30, SURVEY.md §4).  This generalizes that check to
a first-class tool: align two long-format factor tables on (date, stock) and
report per-factor agreement statistics over the full overlap, so a vendor
table (jqdatasdk export, Barra delivery, a previous run) can gate a
production run instead of an eyeballed plot.

Host-side pandas/NumPy — this is data QC, not TPU compute.
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def crosscheck_factors(
    ours: pd.DataFrame,
    external: pd.DataFrame,
    factors: list[str] | None = None,
    date_col: str = "trade_date",
    code_col: str = "ts_code",
) -> pd.DataFrame:
    """Per-factor agreement between two long (date, stock, factor...) tables.

    Returns a DataFrame indexed by factor with columns:

    - ``n_overlap``   rows where both sides have a finite value
    - ``pearson``     correlation over the overlap
    - ``rank_corr``   Spearman (rank) correlation — robust to the vendor
                      using a different winsorization/standardization
    - ``max_abs_diff`` / ``mean_abs_diff`` raw-value agreement (only
      meaningful when both sides use the same normalization)
    - ``coverage_ours`` / ``coverage_ext`` share of the union each side covers
    """
    if factors is None:
        skip = {date_col, code_col}
        # pd.api.types handles extension dtypes (StringDtype etc.) that
        # np.issubdtype cannot interpret
        factors = [c for c in ours.columns
                   if c not in skip and c in external.columns
                   and pd.api.types.is_numeric_dtype(ours[c])]
    else:
        missing = [f"{f} ({side})"
                   for side, df in (("ours", ours), ("external", external))
                   for f in factors if f not in df.columns]
        if missing:
            raise ValueError(f"factor columns not found: {missing}")
    # raw vendor pulls often repeat (date, code) rows; a cartesian merge
    # would silently double-weight them, so keep the first occurrence
    keys = [date_col, code_col]
    merged = ours[keys + factors].drop_duplicates(keys).merge(
        external[keys + factors].drop_duplicates(keys),
        on=keys, how="outer", suffixes=("_a", "_b"),
    )
    rows = {}
    for f in factors:
        # vendor tables carry string sentinels ('NULL', '--') — coerce to NaN
        a = pd.to_numeric(merged[f + "_a"], errors="coerce").to_numpy(float)
        b = pd.to_numeric(merged[f + "_b"], errors="coerce").to_numpy(float)
        both = np.isfinite(a) & np.isfinite(b)
        either = np.isfinite(a) | np.isfinite(b)
        n = int(both.sum())
        if n >= 2 and np.nanstd(a[both]) > 0 and np.nanstd(b[both]) > 0:
            pear = float(np.corrcoef(a[both], b[both])[0, 1])
            ra = pd.Series(a[both]).rank().to_numpy()
            rb = pd.Series(b[both]).rank().to_numpy()
            rank = float(np.corrcoef(ra, rb)[0, 1])
        else:
            pear = rank = np.nan
        diff = np.abs(a[both] - b[both])
        ne = int(either.sum())
        rows[f] = {
            "n_overlap": n,
            "pearson": pear,
            "rank_corr": rank,
            "max_abs_diff": float(np.max(diff)) if n else np.nan,
            "mean_abs_diff": float(np.mean(diff)) if n else np.nan,
            "coverage_ours": float(np.isfinite(a).sum() / ne) if ne else 0.0,
            "coverage_ext": float(np.isfinite(b).sum() / ne) if ne else 0.0,
        }
    out = pd.DataFrame.from_dict(rows, orient="index")
    out.index.name = "factor"
    return out
