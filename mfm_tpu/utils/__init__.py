"""Observability and misc utilities."""

from mfm_tpu.utils.obs import (
    StageTimer,
    log,
    set_log_level,
    determinism_check,
    trace_annotation,
    force,
)

__all__ = [
    "StageTimer",
    "log",
    "set_log_level",
    "determinism_check",
    "trace_annotation",
    "force",
]
