"""Observability and misc utilities.

:mod:`mfm_tpu.utils.report` (model-health summary + plots) and
:mod:`mfm_tpu.utils.crosscheck` (external factor comparison) are imported
lazily by their CLI drivers — they need pandas/matplotlib, which stay
optional for the pure-compute import path.
"""

from mfm_tpu.utils.obs import (
    StageTimer,
    log,
    set_log_level,
    determinism_check,
    trace_annotation,
    force,
)

__all__ = [
    "StageTimer",
    "log",
    "set_log_level",
    "determinism_check",
    "trace_annotation",
    "force",
]
