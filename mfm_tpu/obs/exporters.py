"""Exporters: JSONL event stream + Prometheus textfile exposition.

- :class:`EventLog` / :func:`emit_event` — the structured JSONL event
  stream superseding ``utils.obs.log``'s bare stderr prints: same
  one-JSON-object-per-line shape, but with a stable schema (``ts``,
  ``level``, ``event`` always present, in that key order) and an optional
  append-to-file sink so a serving loop leaves an auditable trail next to
  its checkpoints.
- :func:`render_prometheus` / :func:`parse_prometheus` — the textfile
  exposition format (``# HELP``/``# TYPE`` + samples, histogram
  ``_bucket{le=...}``/``_sum``/``_count``) and a minimal parser used by the
  round-trip tests and ``mfm-tpu metrics``.  Zero-dependency on purpose:
  node_exporter's textfile collector is the deployment story, no client
  library required.
- :func:`write_prometheus_textfile` — atomic write (tmp -> fsync ->
  rename), because the textfile collector may scrape mid-write otherwise.

Host-side only (mfmlint R7), like everything under ``mfm_tpu.obs``.
"""

from __future__ import annotations

import json
import math
import os
import sys
import threading
import time

from mfm_tpu.obs.metrics import Histogram, MetricsRegistry, REGISTRY

_LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}

#: JSONL schema: these keys open every record, in this order (the stability
#: contract tests/test_obs.py pins)
EVENT_REQUIRED_KEYS = ("ts", "level", "event")


class EventLog:
    """Append-only JSONL event sink (file when ``path`` given, else stderr)."""

    def __init__(self, path: str | None = None, min_level: str = "info"):
        self.path = path
        self.min_level = _LEVELS[min_level]
        self._lock = threading.Lock()
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def set_level(self, level: str) -> None:
        self.min_level = _LEVELS[level]

    def emit(self, level: str, event: str, **fields) -> None:
        if _LEVELS[level] < self.min_level:
            return
        rec = {"ts": round(time.time(), 3), "level": level, "event": event}
        for k in sorted(fields):
            if k not in rec:
                rec[k] = fields[k]
        line = json.dumps(rec, default=str)
        with self._lock:
            if self.path:
                with open(self.path, "a", encoding="utf-8") as fh:
                    fh.write(line + "\n")
            else:
                print(line, file=sys.stderr, flush=True)


#: process-default sink; :func:`route_events_to` repoints it
_DEFAULT_LOG = EventLog()


def emit_event(level: str, event: str, **fields) -> None:
    """Emit to the process-default sink (stderr until routed to a file)."""
    _DEFAULT_LOG.emit(level, event, **fields)


def route_events_to(path: str | None, min_level: str = "info") -> EventLog:
    """Point the process-default event stream at a JSONL file (None -> back
    to stderr).  Returns the new sink."""
    global _DEFAULT_LOG
    _DEFAULT_LOG = EventLog(path, min_level=min_level)
    return _DEFAULT_LOG


def default_event_log() -> EventLog:
    return _DEFAULT_LOG


# -- Prometheus textfile exposition ------------------------------------------

def _esc_label(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc_label(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(float(v))


def render_prometheus(registry: MetricsRegistry | None = None) -> str:
    """The registry in Prometheus text exposition format (version 0.0.4)."""
    reg = registry if registry is not None else REGISTRY
    lines = []
    for m in reg.metrics():
        if m.help_text:
            lines.append(f"# HELP {m.name} {m.help_text}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for key in sorted(m.series()):
            labels = dict(zip(m.labelnames, key))
            if isinstance(m, Histogram):
                st = m.series()[key]
                for le, c in m.cumulative(**labels):
                    bl = dict(labels)
                    bl["le"] = _fmt_value(le) if math.isinf(le) else repr(le)
                    lines.append(
                        f"{m.name}_bucket{_fmt_labels(bl)} {c}")
                lines.append(f"{m.name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(st.total)}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)} "
                             f"{st.count}")
            else:
                lines.append(f"{m.name}{_fmt_labels(labels)} "
                             f"{_fmt_value(m.series()[key])}")
    return "\n".join(lines) + ("\n" if lines else "")


def _parse_labels(s: str) -> dict:
    out, i = {}, 0
    while i < len(s):
        eq = s.index("=", i)
        name = s[i:eq].strip().strip(",")
        if s[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {s[eq:eq+8]!r}")
        j, buf = eq + 2, []
        while s[j] != '"':
            if s[j] == "\\":
                nxt = s[j + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
            else:
                buf.append(s[j])
                j += 1
        out[name] = "".join(buf)
        i = j + 1
    return out


def parse_prometheus(text: str) -> dict:
    """Minimal exposition-format parser for round-trip validation.

    Returns ``{family_name: {"type": str, "help": str, "samples":
    [(sample_name, labels_dict, value), ...]}}``.  Raises ValueError on
    malformed lines — which is the point: the textfile we ship must parse.
    """
    families: dict = {}
    current = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            families.setdefault(name, {"type": "untyped", "help": "",
                                       "samples": []})["help"] = help_text
            current = name
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                raise ValueError(f"line {lineno}: unknown metric type "
                                 f"{kind!r}")
            families.setdefault(name, {"type": "untyped", "help": "",
                                       "samples": []})["type"] = kind
            current = name
            continue
        if line.startswith("#"):
            continue
        # sample: name[{labels}] value
        brace = line.find("{")
        if brace != -1:
            close = line.rindex("}")
            sample_name = line[:brace]
            labels = _parse_labels(line[brace + 1:close])
            value_s = line[close + 1:].strip()
        else:
            sample_name, _, value_s = line.partition(" ")
            labels = {}
            value_s = value_s.strip()
        if not sample_name or not value_s:
            raise ValueError(f"line {lineno}: malformed sample {raw!r}")
        value = float(value_s.replace("+Inf", "inf").replace("-Inf", "-inf"))
        fam = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            base = sample_name[: -len(suffix)]
            if sample_name.endswith(suffix) and base in families \
                    and families[base]["type"] == "histogram":
                fam = base
                break
        if fam not in families:
            families[fam] = {"type": "untyped", "help": "", "samples": []}
        families[fam]["samples"].append((sample_name, labels, value))
        current = fam
    del current
    return families


def write_prometheus_textfile(path: str,
                              registry: MetricsRegistry | None = None) -> str:
    """Atomically write the exposition textfile (tmp -> fsync -> rename);
    returns the rendered text."""
    text = render_prometheus(registry)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return text
