"""SLO burn-rate engine: declarative objectives evaluated at scrape time.

Counters say what happened; an SLO says whether it was ACCEPTABLE, and a
burn rate says how fast the error budget is going.  This module holds
the serving stack's objectives as data (:class:`SloSpec`), evaluates
them at scrape time from the live :mod:`mfm_tpu.obs.metrics` registry —
no background thread, no new collection path — and derives the
two-window alert discipline of the SRE workbook:

- **fast window** (default 5 m): a burn rate >= ``FAST_BURN_THRESHOLD``
  (14.4 — the whole 30-day budget gone in ~2 days) is a page-now state
  (``fast_burn``); ``mfm-tpu doctor --serve`` fails on it.
- **slow window** (default 1 h): a burn rate >= ``SLOW_BURN_THRESHOLD``
  (3.0) is a ticket state (``slow_burn``); doctor warns.

Burn rate is ``(bad fraction in window) / (1 - objective)`` — 1.0 means
burning exactly the budget, sustainable forever; 14.4 means the monthly
budget dies in two days.  Because the engine samples CUMULATIVE counters
with timestamps and differences them over each window, it needs no
history beyond one slow window of scrape samples, and a process that is
scraped rarely degrades gracefully (the window shrinks to the data it
has rather than inventing a rate).

Three spec kinds cover the serving SLOs:

- ``availability`` — good = ``ok`` outcomes of
  ``mfm_query_requests_total``; objective is the minimum good fraction.
- ``p99_latency`` — good = requests at or under ``objective`` seconds,
  read off ``mfm_query_latency_seconds``'s cumulative buckets; the
  budget is the 1% tail by construction.
- ``staleness`` — good = scrape samples where
  ``mfm_served_cov_staleness`` is at or under ``objective`` dates (a
  gauge SLO: the bad fraction is bad *time*, sampled at scrapes).

A module-level engine slot (:func:`install`) lets the serve CLI arm one
engine per process; ``serve_summary_from_registry`` then carries the
evaluation into ``/healthz``, the manifests and ``doctor --serve``.

Host-only module (mfmlint R7): stdlib + the obs registry, nothing here
may be reached from traced code.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

from mfm_tpu.obs import instrument as _obs

#: fast-window burn that pages: the 30-day budget gone in ~2 days
FAST_BURN_THRESHOLD = 14.4
#: slow-window burn that files a ticket: budget gone in ~10 days
SLOW_BURN_THRESHOLD = 3.0

_SPEC_KINDS = ("availability", "p99_latency", "staleness")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One declarative objective.  ``objective`` means: minimum good
    fraction for ``availability`` (e.g. 0.99), maximum seconds for
    ``p99_latency``, maximum staleness dates for ``staleness``."""

    name: str
    kind: str
    objective: float
    description: str = ""

    def __post_init__(self):
        if self.kind not in _SPEC_KINDS:
            raise ValueError(f"unknown SLO kind {self.kind!r}; have "
                             f"{list(_SPEC_KINDS)}")
        if self.kind == "availability" and not 0.0 < self.objective < 1.0:
            raise ValueError(f"availability objective must be in (0, 1), "
                             f"got {self.objective}")
        if self.kind != "availability" and self.objective < 0:
            raise ValueError(f"{self.kind} objective must be >= 0, got "
                             f"{self.objective}")

    def budget(self) -> float:
        """The error budget the burn rate divides by.  Availability's is
        ``1 - objective``; the tail-latency and staleness SLOs use the
        p99 tail budget (1%) by convention."""
        if self.kind == "availability":
            return 1.0 - self.objective
        return 0.01


#: the serving stack's default objectives (docs/OBSERVABILITY.md §7)
DEFAULT_SLOS = (
    SloSpec("availability", "availability", 0.99,
            "99% of admitted requests answer ok"),
    SloSpec("p99-latency", "p99_latency", 0.5,
            "99% of answered requests within 500 ms enqueue-to-response"),
    SloSpec("staleness", "staleness", 5.0,
            "served covariance at most 5 dates stale"),
)


def _count_le(cum: list, bound: float) -> int:
    """Cumulative count at the first bucket bound >= ``bound`` (all
    observations when ``bound`` exceeds the last finite bucket)."""
    for le, c in cum:
        if le >= bound:
            return int(c)
    return int(cum[-1][1]) if cum else 0


class SloEngine:
    """Evaluate :class:`SloSpec` objectives over fast/slow windows.

    Args:
      specs: the objectives (default :data:`DEFAULT_SLOS`).
      clock: monotonic clock, injectable for deterministic tests.
      fast_window_s / slow_window_s: the two burn windows.

    Thread-safe: sampling and evaluation run under one lock (scrapes
    arrive from N frontend connection threads).
    """

    def __init__(self, specs=DEFAULT_SLOS, *,
                 clock=time.monotonic,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0):
        specs = tuple(specs)
        if not specs:
            raise ValueError("SloEngine needs at least one SloSpec")
        if not 0 < fast_window_s <= slow_window_s:
            raise ValueError(
                f"windows must satisfy 0 < fast <= slow, got "
                f"{fast_window_s}/{slow_window_s}")
        self.specs = specs
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._clock = clock
        self._lock = threading.Lock()
        #: (t, reading) samples, oldest first, pruned past slow_window
        self._samples: collections.deque = collections.deque()

    # -- sampling ------------------------------------------------------------
    def _read_registry(self) -> dict:
        outcomes = {k[0]: int(v)
                    for k, v in _obs.QUERY_REQUESTS_TOTAL.series().items()}
        total = sum(outcomes.values())
        cum = _obs.QUERY_LATENCY_SECONDS.cumulative()
        return {
            "total": total,
            "ok": outcomes.get("ok", 0),
            "lat_cum": [int(c) for _, c in cum],
            "lat_bounds": [le for le, _ in cum],
            "staleness": float(_obs.SERVED_COV_STALENESS.value()),
        }

    def sample(self, now: float | None = None) -> dict:
        """Take one timestamped registry reading (scrape-time hook);
        prunes samples older than the slow window.  Returns the
        reading."""
        t = self._clock() if now is None else float(now)
        reading = self._read_registry()
        with self._lock:
            self._samples.append((t, reading))
            # keep ONE sample beyond the slow window so a full-width
            # baseline survives pruning
            while (len(self._samples) >= 2
                   and t - self._samples[1][0] >= self.slow_window_s):
                self._samples.popleft()
        return reading

    def _baseline(self, now: float, window_s: float) -> tuple:
        """Newest sample at least ``window_s`` old (or the oldest one —
        a shrunk window beats an invented rate).  Callers hold no lock;
        the deque snapshot is taken under it."""
        with self._lock:
            samples = list(self._samples)
        if not samples:
            return None, 0.0
        base = samples[0]
        for t, reading in samples:
            if now - t >= window_s:
                base = (t, reading)
            else:
                break
        return base[1], max(0.0, now - base[0])

    # -- evaluation ----------------------------------------------------------
    def _bad_frac(self, spec: SloSpec, cur: dict, base: dict,
                  window_samples: list) -> float:
        if spec.kind == "availability":
            total = cur["total"] - base["total"]
            if total <= 0:
                return 0.0
            bad = total - (cur["ok"] - base["ok"])
            return max(0.0, min(1.0, bad / total))
        if spec.kind == "p99_latency":
            cur_cum = list(zip(cur["lat_bounds"], cur["lat_cum"]))
            base_cum = list(zip(base["lat_bounds"], base["lat_cum"]))
            n = (cur_cum[-1][1] if cur_cum else 0) - \
                (base_cum[-1][1] if base_cum else 0)
            if n <= 0:
                return 0.0
            good = _count_le(cur_cum, spec.objective) - \
                _count_le(base_cum, spec.objective)
            return max(0.0, min(1.0, (n - good) / n))
        # staleness: bad TIME fraction, sampled at scrapes
        if not window_samples:
            return 0.0
        bad = sum(1 for r in window_samples
                  if r["staleness"] > spec.objective)
        return bad / len(window_samples)

    def evaluate(self, now: float | None = None) -> dict:
        """Sample, then compute every SLO's two-window burn + state and
        mirror them onto the gauges.  Returns the summary block the
        manifests/healthz embed."""
        t = self._clock() if now is None else float(now)
        cur = self.sample(t)
        with self._lock:
            samples = list(self._samples)
        out = []
        worst = "ok"
        rank = {"ok": 0, "slow_burn": 1, "fast_burn": 2}
        for spec in self.specs:
            burns = {}
            for window_name, window_s in (("fast", self.fast_window_s),
                                          ("slow", self.slow_window_s)):
                base, _width = self._baseline(t, window_s)
                in_window = [r for st, r in samples if t - st <= window_s]
                if base is None:
                    burns[window_name] = 0.0
                    continue
                frac = self._bad_frac(spec, cur, base, in_window)
                burns[window_name] = round(frac / spec.budget(), 6)
            if burns["fast"] >= FAST_BURN_THRESHOLD:
                state = "fast_burn"
            elif burns["slow"] >= SLOW_BURN_THRESHOLD:
                state = "slow_burn"
            else:
                state = "ok"
            worst = worst if rank[worst] >= rank[state] else state
            _obs.record_slo_state(spec.name, state, burns["fast"],
                                  burns["slow"])
            out.append({
                "name": spec.name,
                "kind": spec.kind,
                "objective": spec.objective,
                "budget": spec.budget(),
                "burn_fast": burns["fast"],
                "burn_slow": burns["slow"],
                "state": state,
            })
        return {
            "schema": 1,
            "window_fast_s": self.fast_window_s,
            "window_slow_s": self.slow_window_s,
            "fast_burn_threshold": FAST_BURN_THRESHOLD,
            "slow_burn_threshold": SLOW_BURN_THRESHOLD,
            "slos": out,
            "worst_state": worst,
        }


# -- the process engine slot --------------------------------------------------

_engine_lock = threading.Lock()
_engine: SloEngine | None = None


def install(engine: SloEngine | None) -> None:
    """Arm (or with None, disarm) the process SLO engine.  The serve CLI
    installs one; ``serve_summary_from_registry`` then carries its
    evaluation everywhere the summary goes."""
    global _engine
    with _engine_lock:
        _engine = engine


def get_engine() -> SloEngine | None:
    with _engine_lock:
        return _engine


def reset_slo() -> None:
    """Disarm the engine (tests)."""
    install(None)


def installed_summary() -> dict | None:
    """Evaluate the installed engine, or None when disarmed."""
    engine = get_engine()
    if engine is None:
        return None
    return engine.evaluate()
