"""Model-health monitors: is the served risk model still trustworthy.

Three USE4-flavoured monitors computed from served outputs (reusing
``models/bias.py`` for the statistic itself):

- **rolling bias statistic** — eigenfactor bias stat over a trailing
  window; a well-calibrated model keeps it near 1, so the monitored value
  is the mean |b - 1| across eigenfactor ranks.
- **cross-sectional R² drift** — trailing-window mean of the regression R²
  against the run's own earlier baseline; a drop means the factor structure
  stopped explaining the cross-section.
- **factor-return outliers** — fraction of recent factor returns beyond a
  MAD-based z threshold computed from the full history (the serving guards
  watch raw *asset* returns; this watches the *fitted* factor returns,
  which is where a broken universe or bad regression shows up first).

Each monitor exports a gauge and contributes a check to the health verdict
(``{"status": "ok"|"degraded"|"unknown", "checks": {...}}``) that the run
manifest embeds and ``mfm-tpu doctor`` audits.

CLI-layer only by design: the bias statistic compiles its own small jax
programs, so this must never run inside the steady-state ≤1-compile update
path (pipeline/faultinject call ``update_guarded`` directly and stay
clean).  mfmlint R7 additionally forbids reaching any of this from traced
code.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from mfm_tpu.obs.metrics import REGISTRY, MetricsRegistry


@dataclasses.dataclass(frozen=True)
class HealthThresholds:
    """Alert thresholds; ``docs/OBSERVABILITY.md`` discusses tuning."""

    #: mean |bias - 1| across eigenfactor ranks over the trailing window
    bias_max_mean_abs_dev: float = 0.5
    #: trailing window (dates) for the rolling bias statistic
    bias_window: int = 120
    #: fewest valid window dates before the bias monitor reports at all
    bias_min_dates: int = 40
    #: allowed drop of trailing-mean R² below the baseline mean (absolute)
    r2_max_drop: float = 0.15
    #: trailing window (dates) for the R² mean
    r2_window: int = 60
    #: MAD-z beyond which a factor return counts as an outlier
    factor_ret_outlier_z: float = 8.0
    #: allowed fraction of outlier (date, factor) cells in the window
    factor_ret_max_outlier_frac: float = 0.01
    #: trailing window (dates) for the outlier fraction
    factor_ret_window: int = 60
    #: allowed quarantine rate over the run (quarantined / served dates)
    max_quarantine_rate: float = 0.02


def _check(value, threshold, ok, note: str | None = None) -> dict:
    rec = {
        "value": None if value is None or not math.isfinite(value)
        else round(float(value), 6),
        "threshold": threshold,
        "ok": bool(ok),
    }
    if note:
        rec["note"] = note
    return rec


def rolling_bias_check(outputs, thresholds: HealthThresholds) -> dict:
    """Mean |bias - 1| of the eigen-adjusted covariance over the trailing
    window (``models.bias.eigenfactor_bias_stat``)."""
    from mfm_tpu.models.bias import eigenfactor_bias_stat

    valid = np.asarray(outputs.eigen_valid).astype(bool)
    T = valid.shape[0]
    lo = max(0, T - int(thresholds.bias_window))
    n_valid = int(valid[lo:].sum())
    if n_valid < thresholds.bias_min_dates:
        return _check(None, thresholds.bias_max_mean_abs_dev, True,
                      note=f"only {n_valid} valid dates in window "
                           f"(need {thresholds.bias_min_dates}) — skipped")
    b = np.asarray(eigenfactor_bias_stat(
        outputs.eigen_cov[lo:], outputs.eigen_valid[lo:],
        outputs.factor_ret[lo:]))
    dev = np.abs(b[np.isfinite(b)] - 1.0)
    if dev.size == 0:
        return _check(None, thresholds.bias_max_mean_abs_dev, True,
                      note="no finite bias ranks — skipped")
    mean_dev = float(dev.mean())
    return _check(mean_dev, thresholds.bias_max_mean_abs_dev,
                  mean_dev <= thresholds.bias_max_mean_abs_dev)


def r2_drift_check(outputs, thresholds: HealthThresholds) -> dict:
    """Trailing-mean R² vs the pre-window baseline mean; the monitored
    value is ``baseline - recent`` (positive = explanatory power lost)."""
    r2 = np.asarray(outputs.r2, dtype=np.float64)
    finite = np.isfinite(r2)
    w = int(thresholds.r2_window)
    recent, base = r2[-w:][finite[-w:]], r2[:-w][finite[:-w]]
    if recent.size == 0 or base.size < w:
        return _check(None, thresholds.r2_max_drop, True,
                      note="history shorter than baseline+window — skipped")
    drop = float(base.mean() - recent.mean())
    return _check(drop, thresholds.r2_max_drop,
                  drop <= thresholds.r2_max_drop)


def factor_ret_outlier_check(outputs, thresholds: HealthThresholds) -> dict:
    """Fraction of trailing-window factor returns with MAD-z beyond the
    threshold, scale fit on the full history per factor."""
    fr = np.asarray(outputs.factor_ret, dtype=np.float64)
    finite = np.isfinite(fr)
    if not finite.any():
        return _check(None, thresholds.factor_ret_max_outlier_frac, True,
                      note="no finite factor returns — skipped")
    med = np.nanmedian(np.where(finite, fr, np.nan), axis=0)
    mad = np.nanmedian(np.abs(np.where(finite, fr, np.nan) - med), axis=0)
    scale = np.where(mad > 0, 1.4826 * mad, np.inf)  # degenerate -> no flags
    w = int(thresholds.factor_ret_window)
    z = np.abs(fr[-w:] - med) / scale
    cells = finite[-w:]
    n = int(cells.sum())
    if n == 0:
        return _check(None, thresholds.factor_ret_max_outlier_frac, True,
                      note="empty window — skipped")
    frac = float((z[cells] > thresholds.factor_ret_outlier_z).sum() / n)
    return _check(frac, thresholds.factor_ret_max_outlier_frac,
                  frac <= thresholds.factor_ret_max_outlier_frac)


def quarantine_rate_check(guard_summary: dict,
                          thresholds: HealthThresholds) -> dict:
    """Run-level quarantine rate vs threshold (off the guard verdict
    summary :func:`mfm_tpu.obs.instrument.guard_summary_from_registry`
    assembles)."""
    served = guard_summary.get("served_dates", 0)
    if not served:
        return _check(None, thresholds.max_quarantine_rate, True,
                      note="no guarded dates served — skipped")
    rate = float(guard_summary.get("quarantine_rate", 0.0))
    return _check(rate, thresholds.max_quarantine_rate,
                  rate <= thresholds.max_quarantine_rate)


def evaluate_health(outputs, thresholds: HealthThresholds | None = None,
                    registry: MetricsRegistry | None = None,
                    guard_summary: dict | None = None) -> dict:
    """Run all monitors over served outputs; export gauges; return the
    manifest's ``health`` verdict.

    ``status`` is ``degraded`` if any check with a value fails, ``unknown``
    if every check had to skip (short history), else ``ok``.
    """
    th = thresholds or HealthThresholds()
    reg = registry if registry is not None else REGISTRY
    checks = {
        "bias_mean_abs_dev": rolling_bias_check(outputs, th),
        "r2_drop": r2_drift_check(outputs, th),
        "factor_ret_outlier_frac": factor_ret_outlier_check(outputs, th),
    }
    if guard_summary is not None:
        checks["quarantine_rate"] = quarantine_rate_check(guard_summary, th)
    for name, rec in checks.items():
        if rec["value"] is not None:
            reg.gauge(f"mfm_health_{name}",
                      "model-health monitor (see docs/OBSERVABILITY.md)"
                      ).set_value(rec["value"])
    measured = [rec for rec in checks.values() if rec["value"] is not None]
    if not measured:
        status = "unknown"
    elif all(rec["ok"] for rec in measured):
        status = "ok"
    else:
        status = "degraded"
    reg.gauge("mfm_model_health",
              "1 healthy / 0 degraded / -1 unknown (short history)"
              ).set_value({"ok": 1.0, "degraded": 0.0}.get(status, -1.0))
    return {"status": status, "checks": checks}
