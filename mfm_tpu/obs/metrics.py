"""Metrics core: a process-wide registry of counters, gauges and histograms.

Zero-dependency (stdlib only), thread-safe, and strictly HOST-SIDE: nothing
here may be called from traced code (mfmlint rule R7 enforces the closure —
metrics record around the jit boundary, never inside it, so telemetry can
never add a compile or a host sync to the fused steps).

Design:

- A :class:`MetricsRegistry` owns named metrics; the module-level
  :data:`REGISTRY` is the process default (CLI entrypoints and the library
  instrumentation all share it, so one exporter snapshot sees everything).
- Metrics carry optional *label names*; each distinct label-value tuple is
  an independent series (Prometheus data model).  Label values are
  stringified at record time.
- Histograms use fixed upper bounds (cumulative on export, like Prometheus
  ``_bucket{le=...}``) plus exact sum/count; :meth:`Histogram.quantile_est`
  interpolates within buckets for test assertions and ops dashboards.
- ``enabled`` is a process-wide switch (:func:`set_enabled`): disabled
  recording is a no-op, which is what bench.py's ``telemetry_overhead_frac``
  measures against.

All mutation happens under one registry lock; record calls are a dict update
and two float adds — microseconds against the ~70 ms guarded update step
they instrument.
"""

from __future__ import annotations

import json
import threading
import time

#: default latency buckets (seconds) — spans the ~1 ms eager ops through the
#: ~20 s e2e pipeline, log-ish spacing
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_enabled = True


def set_enabled(on: bool) -> None:
    """Process-wide telemetry switch; disabled recording is a no-op."""
    global _enabled
    _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


def _label_key(labelnames, labels: dict) -> tuple:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared label names "
            f"{sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str, labelnames: tuple,
                 lock: threading.RLock):
        self.name = name
        self.help_text = help_text
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._series: dict[tuple, object] = {}

    def _key(self, labels: dict) -> tuple:
        return _label_key(self.labelnames, labels)

    def series(self) -> dict:
        """{label-value tuple -> recorded value} (shallow copy)."""
        with self._lock:
            return dict(self._series)


class Counter(_Metric):
    """Monotonically increasing float, per label set."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not _enabled:
            return
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up "
                             f"(inc({amount}))")
        k = self._key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + float(amount)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))


class Gauge(_Metric):
    """Last-write-wins float, per label set.

    The setter is ``set_value`` (not prometheus_client's ``set``): ``.set``
    is also the jnp ``x.at[i].set(v)`` spelling, and R7's conservative
    bare-name call resolution must never confuse an in-place array update
    inside a jitted step with a telemetry call.
    """

    kind = "gauge"

    def set_value(self, value: float, **labels) -> None:
        if not _enabled:
            return
        with self._lock:
            self._series[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not _enabled:
            return
        k = self._key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + float(amount)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))


class _HistState:
    __slots__ = ("counts", "total", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets   # per-bucket (non-cumulative)
        self.total = 0.0
        self.count = 0


class Histogram(_Metric):
    """Fixed-bucket histogram; buckets are upper bounds, +Inf implicit."""

    kind = "histogram"

    def __init__(self, name, help_text, labelnames, lock,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text, labelnames, lock)
        bs = tuple(float(b) for b in buckets)
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(f"{name}: buckets must be strictly increasing "
                             f"and non-empty ({bs})")
        self.buckets = bs

    def observe(self, value: float, **labels) -> None:
        if not _enabled:
            return
        v = float(value)
        k = self._key(labels)
        with self._lock:
            st = self._series.get(k)
            if st is None:
                st = self._series[k] = _HistState(len(self.buckets) + 1)
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            st.counts[i] += 1
            st.total += v
            st.count += 1

    def cumulative(self, **labels) -> list[tuple[float, int]]:
        """[(le, cumulative count), ...] ending with (inf, total count)."""
        with self._lock:
            st = self._series.get(self._key(labels))
            counts = list(st.counts) if st else [0] * (len(self.buckets) + 1)
        out, running = [], 0
        for le, c in zip(self.buckets + (float("inf"),), counts):
            running += c
            out.append((le, running))
        return out

    def quantile_est(self, q: float, **labels) -> float:
        """Bucket-interpolated quantile estimate (NaN when empty).

        Linear within a finite bucket; an answer in the +Inf bucket clamps
        to the last finite bound (the estimate's resolution floor).
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        cum = self.cumulative(**labels)
        n = cum[-1][1]
        if n == 0:
            return float("nan")
        target = q * n
        lo_bound, lo_cum = 0.0, 0
        for le, c in cum:
            if c >= target:
                if le == float("inf"):
                    return self.buckets[-1]
                width = c - lo_cum
                frac = (target - lo_cum) / width if width else 1.0
                return lo_bound + frac * (le - lo_bound)
            lo_bound, lo_cum = le, c
        return self.buckets[-1]


class MetricsRegistry:
    """Named metrics with declare-once semantics (re-declaring with the same
    type/labels returns the existing metric; a conflicting redeclaration
    raises — two call sites silently writing different shapes into one name
    is how dashboards lie)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict[str, _Metric] = {}

    def _declare(self, cls, name, help_text, labelnames, **kw):
        labelnames = tuple(labelnames)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already declared as {m.kind} with "
                        f"labels {m.labelnames} — conflicting redeclaration")
                return m
            m = cls(name, help_text, labelnames, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_text: str = "",
                labelnames: tuple = ()) -> Counter:
        return self._declare(Counter, name, help_text, labelnames)

    def gauge(self, name: str, help_text: str = "",
              labelnames: tuple = ()) -> Gauge:
        return self._declare(Gauge, name, help_text, labelnames)

    def histogram(self, name: str, help_text: str = "", labelnames: tuple = (),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._declare(Histogram, name, help_text, labelnames,
                             buckets=buckets)

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self) -> None:
        """Drop every metric (tests / bench repeat runs)."""
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> dict:
        """JSON-ready dump: {name: {type, help, labelnames, series: [...]}}.

        Histogram series carry cumulative ``buckets`` ([le, count] pairs,
        le=+Inf rendered as the string "+Inf" for strict JSON) plus exact
        sum/count.  This is the stable schema the run manifest embeds and
        ``mfm-tpu metrics diff`` consumes.
        """
        out = {}
        for m in self.metrics():
            series = []
            for key in sorted(m.series()):
                labels = dict(zip(m.labelnames, key))
                if isinstance(m, Histogram):
                    st = m.series()[key]
                    cum = m.cumulative(**labels)
                    series.append({
                        "labels": labels,
                        "buckets": [["+Inf" if le == float("inf") else le, c]
                                    for le, c in cum],
                        "sum": st.total,
                        "count": st.count,
                    })
                else:
                    series.append({"labels": labels,
                                   "value": m.series()[key]})
            out[m.name] = {"type": m.kind, "help": m.help_text,
                           "labelnames": list(m.labelnames), "series": series}
        return out

    def scalar_values(self) -> dict:
        """{name or name{k=v,...} -> value} for counters/gauges — the flat
        view bench.py assembles its JSON record from."""
        out = {}
        for m in self.metrics():
            if isinstance(m, Histogram):
                continue
            for key, v in sorted(m.series().items()):
                if m.labelnames:
                    lbl = ",".join(f"{n}={val}"
                                   for n, val in zip(m.labelnames, key))
                    out[f"{m.name}{{{lbl}}}"] = v
                else:
                    out[m.name] = v
        return out


#: the process-default registry — library instrumentation records here
REGISTRY = MetricsRegistry()


def snapshot_json(registry: MetricsRegistry | None = None) -> str:
    """The default registry's snapshot as stable, sorted JSON text."""
    reg = registry if registry is not None else REGISTRY
    return json.dumps({"schema": 1, "taken_at_unix": round(time.time(), 3),
                       "metrics": reg.snapshot()}, indent=1, sort_keys=True)
