"""Request-scoped tracing: spans, a bounded ring buffer, Chrome-trace export.

PR 5's counters say *how much*; this module says *where a specific request
or run spent its time*.  Zero-dependency (stdlib only), thread-safe, and
strictly HOST-SIDE like everything under ``mfm_tpu.obs`` (mfmlint R7):
spans open and close around the jit boundary, never inside it, so tracing
can never add a compile or a host sync to the fused steps.

Design:

- :func:`span` — context-manager span with monotonic-clock timing and
  trace/span/parent ids; nesting uses a thread-local stack, so a child
  opened on the same thread inherits its parent's trace automatically.
- :func:`start_span` / :func:`end_span` — the explicit pair for async
  boundaries (a serve request's span opens at admission and closes at
  response, batches apart), where a context manager cannot bracket the
  lifetime.
- A bounded in-memory ring buffer holds finished spans; overflow drops
  the OLDEST spans and tallies ``mfm_trace_dropped_total`` (a trace that
  silently forgets is worse than one that admits it).
- Exporters: :func:`render_chrome_trace` emits Chrome trace-event JSON
  (Perfetto-loadable ``{"traceEvents": [...]}``, complete "X" events),
  :func:`parse_chrome_trace` is the schema validator the tests and
  tooling round-trip through (the Prometheus parse-validator's sibling),
  :func:`write_chrome_trace` persists atomically (tmp -> fsync -> chaos
  point -> rename -> dir fsync, like the manifests), and
  :func:`export_spans_to_events` mirrors spans onto the PR 5 JSONL event
  stream.

Identifier format follows W3C trace-context sizing: ``trace_id`` is 16
random bytes hex, ``span_id`` 8 bytes hex — long enough to join across
manifests, dead letters and responses without coordination.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time

from mfm_tpu.obs.instrument import (
    TRACE_DROPPED_TOTAL,
    TRACE_SPANS_TOTAL,
    record_foreign_spans,
)
from mfm_tpu.utils.chaos import chaos_point

#: default ring capacity — ~1 MB of spans; a serve storm overflows it by
#: design (drop-oldest + counted) rather than growing without bound
DEFAULT_RING_CAPACITY = 4096

#: Chrome trace-event phases the validator accepts (we emit only "X" and
#: "M", but a hand-edited or foreign trace may carry the rest)
_CHROME_PHASES = frozenset("XBEiIMCbens")

_enabled = True
_lock = threading.Lock()
_ring: collections.deque = collections.deque()
_capacity = DEFAULT_RING_CAPACITY
_tls = threading.local()


def set_tracing(on: bool) -> None:
    """Process-wide tracing switch; disabled spans record nothing."""
    global _enabled
    _enabled = bool(on)


def tracing_enabled() -> bool:
    return _enabled


def set_ring_capacity(n: int) -> None:
    """Resize the span ring (existing overflow drops oldest, counted)."""
    global _capacity
    if int(n) < 1:
        raise ValueError(f"ring capacity must be >= 1, got {n}")
    with _lock:
        _capacity = int(n)
        _evict_locked()


def new_trace_id() -> str:
    """16 random bytes, hex — W3C trace-context sized."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """8 random bytes, hex."""
    return os.urandom(8).hex()


class Span:
    """One finished-or-open span.  ``start_us``/``dur_us`` are on the
    monotonic ``perf_counter`` clock (microseconds) — a consistent
    process-local timeline, which is all the Chrome trace format needs;
    ``wall_ts`` is the wall-clock open time for joining to JSONL events."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_us",
                 "dur_us", "wall_ts", "tid", "attrs")

    def __init__(self, name, trace_id, span_id, parent_id, start_us,
                 wall_ts, tid, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_us = start_us
        self.dur_us = None          # None until end_span
        self.wall_ts = wall_ts
        self.tid = tid
        self.attrs = attrs


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _evict_locked() -> None:
    dropped = 0
    while len(_ring) > _capacity:
        _ring.popleft()
        dropped += 1
    if dropped:
        TRACE_DROPPED_TOTAL.inc(dropped)


def start_span(name: str, *, trace_id: str | None = None,
               parent_id: str | None = None, **attrs) -> Span:
    """Open a span WITHOUT touching the thread-local nesting stack — the
    async half of the API (a serve request opens here at admission and
    :func:`end_span` closes it at response, possibly batches later).

    ``trace_id``/``parent_id`` default to the calling thread's current
    span when one is open (so an explicit span started under ``span()``
    still joins its trace), else a fresh trace begins.
    """
    st = _stack()
    cur = st[-1] if st else None
    if trace_id is None:
        trace_id = cur.trace_id if cur is not None else new_trace_id()
    if parent_id is None and cur is not None and cur.trace_id == trace_id:
        parent_id = cur.span_id
    return Span(str(name), str(trace_id), new_span_id(), parent_id,
                time.perf_counter() * 1e6, round(time.time(), 3),
                threading.get_ident(), dict(attrs))


def end_span(sp: Span, **attrs) -> Span:
    """Close a span: stamp its duration, merge late attrs, push it onto
    the ring (oldest dropped + counted past capacity).  Idempotence is
    the caller's job — ending twice records twice."""
    sp.dur_us = max(0.0, time.perf_counter() * 1e6 - sp.start_us)
    if attrs:
        # dict-merge operator, not .update(): the linter's conservative
        # bare-name call graph would join this to RiskModel.update and mark
        # every span-closing caller jax-touching
        sp.attrs |= attrs
    if not _enabled:
        return sp
    TRACE_SPANS_TOTAL.inc()
    with _lock:
        _ring.append(sp)
        _evict_locked()
    return sp


@contextlib.contextmanager
def span(name: str, *, trace_id: str | None = None,
         parent_id: str | None = None, **attrs):
    """Context-manager span: nests via the thread-local stack, so children
    opened inside inherit this trace; an exception ends the span with an
    ``error`` attr and propagates."""
    sp = start_span(name, trace_id=trace_id, parent_id=parent_id, **attrs)
    st = _stack()
    st.append(sp)
    try:
        yield sp
    except BaseException as e:
        end_span(sp, error=f"{type(e).__name__}: {e}"[:500])
        raise
    finally:
        st.pop()
    end_span(sp)


def current_trace_id() -> str | None:
    """The calling thread's open trace id, if any span is open."""
    st = _stack()
    return st[-1].trace_id if st else None


def spans() -> list:
    """Snapshot of the ring's finished spans, oldest first."""
    with _lock:
        return list(_ring)


def reset_tracing() -> None:
    """Drop every recorded span and this thread's nesting stack (tests)."""
    global _capacity
    with _lock:
        _ring.clear()
        _capacity = DEFAULT_RING_CAPACITY
    _tls.stack = []


# -- fleet-wire span merge ----------------------------------------------------
#
# A fleet worker's spans live in ITS process ring; these helpers move them
# across the ``__fleet__`` wire and into the frontend's ring so one Chrome
# trace shows the whole request timeline.  Worker clocks are perf_counter
# clocks with arbitrary epochs, so every merged span is shifted by a
# per-worker offset estimated from heartbeat RTT midpoints; the offset and
# its uncertainty (half the RTT) are stamped on the span, and a span whose
# corrected timeline still falls outside the frontend's observed dispatch
# window beyond that uncertainty is flagged ``clock_skew="uncorrectable"``
# — flagged, never reordered or clamped.

#: wire-form span fields (the JSONL payload piggybacked on flushed/pong
#: replies); ``tid`` rides along so per-thread lanes survive the merge
_WIRE_FIELDS = ("name", "trace_id", "span_id", "parent_id", "start_us",
                "dur_us", "wall_ts", "tid")


def wire_span(sp: Span) -> dict:
    """One finished span as a JSON-safe wire dict."""
    d = {k: getattr(sp, k) for k in _WIRE_FIELDS}
    d["attrs"] = dict(sp.attrs)
    return d


def drain_spans() -> list:
    """Destructively pop every finished span off the ring, in order, as
    wire dicts — the worker side of the piggyback (spans ship once, on
    the next flushed/pong reply, and stop occupying worker memory)."""
    with _lock:
        out = list(_ring)
        _ring.clear()
    return [wire_span(s) for s in out]


def clock_offset_from_probe(t0_s: float, t1_s: float,
                            peer_clock_us: float) -> tuple:
    """``(offset_us, uncertainty_us)`` from one probe round trip: the
    peer stamped ``peer_clock_us`` (its perf_counter, µs) somewhere
    between our send (``t0_s``) and receive (``t1_s``) perf_counter
    stamps.  The midpoint is the minimum-error estimate; half the RTT
    bounds the error.  Adding ``offset_us`` to a LOCAL timestamp maps it
    onto the peer's clock — so subtract it from peer timestamps (which
    is what :func:`ingest_foreign_spans` expects as its ``offset_us``,
    negated by the caller)."""
    mid_us = (float(t0_s) + float(t1_s)) / 2.0 * 1e6
    rtt_us = max(0.0, (float(t1_s) - float(t0_s)) * 1e6)
    return float(peer_clock_us) - mid_us, rtt_us / 2.0


def ingest_foreign_spans(span_dicts, *, offset_us: float = 0.0,
                         uncertainty_us: float = 0.0, window_us=None,
                         worker=None) -> list:
    """Merge wire-form spans from another process into this ring.

    ``offset_us`` is ADDED to each span's ``start_us`` to map it onto
    this process's perf_counter clock (callers that estimated
    ``peer - local`` via :func:`clock_offset_from_probe` pass the
    NEGATED estimate).  Every merged span is stamped with the correction
    (``clock_offset_us``/``clock_uncertainty_us`` and ``worker``), and a
    span whose corrected extent lies outside ``window_us`` (a local
    ``(lo_us, hi_us)`` bracket around the exchange that produced it) by
    more than the uncertainty is flagged ``clock_skew="uncorrectable"``
    — the timeline is preserved as corrected, never reordered.  Returns
    the ingested spans (empty when tracing is disabled)."""
    if not _enabled:
        return []
    out = []
    n_skew = 0
    for d in span_dicts or ():
        if not isinstance(d, dict) or not d.get("name"):
            continue
        try:
            start = float(d["start_us"]) + float(offset_us)
            dur = float(d.get("dur_us") or 0.0)
        except (KeyError, TypeError, ValueError):
            continue
        attrs = dict(d.get("attrs") or {})
        attrs["clock_offset_us"] = round(float(offset_us), 3)
        attrs["clock_uncertainty_us"] = round(float(uncertainty_us), 3)
        if worker is not None:
            attrs["worker"] = worker
        if window_us is not None:
            lo, hi = float(window_us[0]), float(window_us[1])
            slack = max(0.0, float(uncertainty_us))
            if start < lo - slack or start + dur > hi + slack:
                attrs["clock_skew"] = "uncorrectable"
                n_skew += 1
        sp = Span(str(d["name"]), str(d.get("trace_id")),
                  str(d.get("span_id") or new_span_id()),
                  d.get("parent_id"), start,
                  d.get("wall_ts"), int(d.get("tid") or 0), attrs)
        sp.dur_us = dur
        out.append(sp)
    if out:
        TRACE_SPANS_TOTAL.inc(len(out))
        record_foreign_spans(len(out), n_skew)
        with _lock:
            _ring.extend(out)
            _evict_locked()
    return out


# -- Chrome trace-event export ------------------------------------------------

def chrome_trace_events(span_list=None) -> list:
    """The ring (or an explicit span list) as Chrome trace-event dicts:
    complete ("X") events, µs timestamps, ids and attrs under ``args``."""
    pid = os.getpid()
    out = []
    for s in (spans() if span_list is None else span_list):
        args = {"trace_id": s.trace_id, "span_id": s.span_id}
        if s.parent_id:
            args["parent_id"] = s.parent_id
        for k in sorted(s.attrs):
            if k not in args:
                args[k] = s.attrs[k]
        out.append({"name": s.name, "cat": "mfm", "ph": "X",
                    "ts": round(s.start_us, 3),
                    "dur": round(s.dur_us or 0.0, 3),
                    "pid": pid, "tid": int(s.tid), "args": args})
    return out


def render_chrome_trace(span_list=None) -> str:
    """Perfetto-loadable JSON text: ``{"traceEvents": [...]}``."""
    return json.dumps({"traceEvents": chrome_trace_events(span_list),
                       "displayTimeUnit": "ms"},
                      sort_keys=True, default=str)


def parse_chrome_trace(text: str) -> list:
    """Schema-validate Chrome trace-event JSON; returns the event list.

    Accepts both the object form (``{"traceEvents": [...]}``) we emit and
    the bare-array form Perfetto also loads.  Raises ValueError on
    anything either consumer would choke on — which is the point: the
    trace we ship must load.
    """
    try:
        obj = json.loads(text)
    except ValueError as e:
        raise ValueError(f"not valid JSON ({e}) — torn trace file?") from e
    if isinstance(obj, dict):
        events = obj.get("traceEvents")
        if not isinstance(events, list):
            raise ValueError("object form lacks a traceEvents list")
    elif isinstance(obj, list):
        events = obj
    else:
        raise ValueError(f"trace must be an object or array, got "
                         f"{type(obj).__name__}")
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            raise ValueError(f"{where}: event is not an object")
        ph = ev.get("ph")
        if not isinstance(ph, str) or ph not in _CHROME_PHASES:
            raise ValueError(f"{where}: bad phase {ph!r}")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            raise ValueError(f"{where}: missing/empty name")
        if ph != "M":        # metadata events carry no timestamp
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                raise ValueError(f"{where}: bad ts {ts!r}")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                raise ValueError(f"{where}: {key} must be an int, got "
                                 f"{ev.get(key)!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(f"{where}: complete event needs dur >= 0, "
                                 f"got {dur!r}")
        if "args" in ev and not isinstance(ev["args"], dict):
            raise ValueError(f"{where}: args must be an object")
    return events


def write_chrome_trace(path: str, span_list=None) -> str:
    """Atomic trace flush (tmp -> fsync -> chaos point -> rename -> dir
    fsync), same discipline as the manifests — a SIGKILL mid-flush must
    never leave a torn trace file.  Returns the final path."""
    text = render_chrome_trace(span_list)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    chaos_point("trace.after_tmp", path)
    os.replace(tmp, path)
    try:
        fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - exotic filesystems
        pass
    return path


def export_spans_to_events(span_list=None, level: str = "info") -> int:
    """Mirror spans onto the JSONL event stream (one ``span`` event each,
    routed wherever ``route_events_to`` points).  Returns the count."""
    from mfm_tpu.obs.exporters import emit_event

    sl = spans() if span_list is None else span_list
    for s in sl:
        emit_event(level, "span", name=s.name, trace_id=s.trace_id,
                   span_id=s.span_id, parent_id=s.parent_id,
                   dur_s=round((s.dur_us or 0.0) / 1e6, 6),
                   **{f"attr_{k}": v for k, v in sorted(s.attrs.items())})
    return len(sl)
