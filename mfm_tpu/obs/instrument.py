"""Instrumentation: the metric catalog + host-side recording helpers.

This module is the single place where the serving stack's metric names are
declared (``docs/OBSERVABILITY.md`` mirrors this catalog).  Everything
records CONCRETE host values — numpy scalars off already-materialized jit
outputs, wall-clock spans around jit calls, filesystem events — never
tracers; recording around the jit boundary is what keeps the fused update
at ≤1 compile with telemetry on (and mfmlint R7 makes reaching these from
traced code a lint error).

Compile visibility reuses the :class:`~mfm_tpu.utils.contracts.CompileCounter`
lowering hook: :func:`watch_compiles` registers a process-lifetime listener
that tallies ``mfm_jit_compiles_total``, so a steady-state recompile shows
up on a dashboard instead of only in a test assertion.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

from mfm_tpu.obs.metrics import REGISTRY

# -- catalog ------------------------------------------------------------------

GUARD_REASON_TOTAL = REGISTRY.counter(
    "mfm_guard_reason_total",
    "guard trips by reason bit (one date may tally several reasons)",
    labelnames=("reason",))
QUARANTINED_DATES_TOTAL = REGISTRY.counter(
    "mfm_quarantined_dates_total", "dates excised by the quarantine policy")
SERVED_DATES_TOTAL = REGISTRY.counter(
    "mfm_served_dates_total", "dates served (healthy + degraded-mode)")
SERVED_COV_STALENESS = REGISTRY.gauge(
    "mfm_served_cov_staleness",
    "dates since the most recently served covariance was fit (0 = fresh)")
QUARANTINE_COUNT = REGISTRY.gauge(
    "mfm_quarantine_count", "quarantined dates in the last guarded step")
UPDATE_LATENCY = REGISTRY.histogram(
    "mfm_update_latency_seconds", "guarded/unguarded update step wall time")

STAGE_SECONDS = REGISTRY.gauge(
    "mfm_stage_seconds", "last wall time of a pipeline/risk stage",
    labelnames=("stage",))
COMPILED_BYTES = REGISTRY.gauge(
    "mfm_compiled_bytes",
    "compiled-program memory analysis (utils.obs.compiled_memory)",
    labelnames=("stage", "kind"))

CHECKPOINT_SAVES_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_saves_total", "fenced artifact saves")
CHECKPOINT_LOADS_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_loads_total", "fenced artifact loads")
CHECKPOINT_CORRUPT_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_corrupt_total",
    "checksum/fence verification failures on load")
CHECKPOINT_STALE_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_stale_total", "generation-fence rejections on load")
CHECKPOINT_HEAL_FORWARD_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_heal_forward_total",
    "pointer heal-forwards after a crash between rename and pointer swap")
CHECKPOINT_GENERATION = REGISTRY.gauge(
    "mfm_checkpoint_generation", "generation fence of the last save/load")
CHECKPOINT_SAVE_SECONDS = REGISTRY.histogram(
    "mfm_checkpoint_save_seconds", "artifact save wall time")
CHECKPOINT_LOAD_SECONDS = REGISTRY.histogram(
    "mfm_checkpoint_load_seconds", "artifact load wall time")

RETRY_ATTEMPTS_TOTAL = REGISTRY.counter(
    "mfm_retry_attempts_total", "with_retry attempts by outcome",
    labelnames=("outcome",))   # outcome: ok | retried | exhausted
RETRY_BACKOFF_SECONDS = REGISTRY.histogram(
    "mfm_retry_backoff_seconds", "with_retry sleep durations",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0))

JIT_COMPILES_TOTAL = REGISTRY.counter(
    "mfm_jit_compiles_total",
    "jit lowerings observed since watch_compiles() (steady state: flat)")
JIT_COMPILE_SECONDS = REGISTRY.histogram(
    "mfm_jit_compile_seconds",
    "per-executable lowering/compile wall (obs.profile.capture_compile_walls)",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0))

# -- tracing (obs/trace.py span ring) -----------------------------------------

TRACE_SPANS_TOTAL = REGISTRY.counter(
    "mfm_trace_spans_total", "spans finished and recorded to the trace ring")
TRACE_DROPPED_TOTAL = REGISTRY.counter(
    "mfm_trace_dropped_total",
    "oldest spans evicted by ring-buffer overflow (trace is lossy past "
    "capacity, but counted)")
TRACE_FOREIGN_SPANS_TOTAL = REGISTRY.counter(
    "mfm_trace_foreign_spans_total",
    "worker spans merged into this process's ring off the fleet wire "
    "(clock-offset corrected before insertion)")
TRACE_SKEW_UNCORRECTABLE_TOTAL = REGISTRY.counter(
    "mfm_trace_skew_uncorrectable_total",
    "merged foreign spans whose corrected timeline still fell outside "
    "the dispatch window beyond the offset uncertainty (flagged "
    "clock_skew=uncorrectable on the span, never reordered)")

# -- flight recorder (obs/flightrec.py postmortem ring) -----------------------

FLIGHTREC_EVENTS_TOTAL = REGISTRY.counter(
    "mfm_flightrec_events_total",
    "events recorded to the flight-recorder ring")
FLIGHTREC_DROPPED_TOTAL = REGISTRY.counter(
    "mfm_flightrec_dropped_total",
    "oldest flight-recorder events evicted by ring overflow")
FLIGHTREC_DUMPS_TOTAL = REGISTRY.counter(
    "mfm_flightrec_dumps_total",
    "atomic flightrec.json dumps by trigger",
    labelnames=("trigger",))   # breaker_open | wedge_quarantine |
#                                fence_audit | sigterm | manual

# -- SLO burn-rate engine (obs/slo.py) ----------------------------------------

SLO_BURN_RATE = REGISTRY.gauge(
    "mfm_slo_burn_rate",
    "error-budget burn rate per SLO and window (1.0 = burning exactly "
    "the budget; fast window trips paging, slow window trips tickets)",
    labelnames=("slo", "window"))   # window: fast | slow
SLO_STATE = REGISTRY.gauge(
    "mfm_slo_state",
    "SLO alert state (0 ok, 1 slow_burn, 2 fast_burn)",
    labelnames=("slo",))
SLO_BREACHES_TOTAL = REGISTRY.counter(
    "mfm_slo_breaches_total",
    "evaluations that found an SLO in a burning state",
    labelnames=("slo", "state"))

# -- query service (serve/server.py request loop) -----------------------------

QUERY_REQUESTS_TOTAL = REGISTRY.counter(
    "mfm_query_requests_total", "portfolio-query requests by final outcome",
    labelnames=("outcome",))   # ok | dead_letter | shed | rejected |
#                                deadline | error
QUERY_PORTFOLIOS_TOTAL = REGISTRY.counter(
    "mfm_query_portfolios_total", "portfolios answered (ok outcomes)")
QUERY_BATCH_SECONDS = REGISTRY.histogram(
    "mfm_query_batch_seconds", "device step wall time per drained batch")
QUERY_BATCH_SIZE = REGISTRY.histogram(
    "mfm_query_batch_size", "true (unpadded) portfolios per drained batch",
    buckets=(1, 2, 8, 32, 128, 512, 2048, 8192, 32768, 131072, 524288))
QUERY_LATENCY_SECONDS = REGISTRY.histogram(
    "mfm_query_latency_seconds",
    "enqueue-to-response wall time per answered request")
QUERY_QUEUE_DEPTH = REGISTRY.gauge(
    "mfm_query_queue_depth", "admission queue depth after the last event")
QUERY_SHED_TOTAL = REGISTRY.counter(
    "mfm_query_shed_total",
    "requests dropped (oldest-first) by queue-overflow load shedding")
BREAKER_OPEN_TOTAL = REGISTRY.counter(
    "mfm_breaker_open_total",
    "circuit-breaker transitions into the open state")
BREAKER_STATE = REGISTRY.gauge(
    "mfm_breaker_state", "circuit breaker state (0 closed, 1 half_open, "
    "2 open)")

_BREAKER_STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}
_BREAKER_CODE_STATE = {v: k for k, v in _BREAKER_STATE_CODE.items()}

# -- fleet layer (serve/coalesce.py, serve/frontend.py, serve/replica.py) -----

COALESCE_FLUSHES_TOTAL = REGISTRY.counter(
    "mfm_coalesce_flushes_total", "coalescer flushes by trigger",
    labelnames=("trigger",))   # full | linger | eof
COALESCE_BATCH_FILL = REGISTRY.histogram(
    "mfm_coalesce_batch_fill",
    "true queued requests / geometric bucket capacity per coalesced flush "
    "(1.0 = the jit dispatch was fully amortized)",
    buckets=(0.05, 0.1, 0.25, 0.5, 0.625, 0.75, 0.875, 1.0))
COALESCE_LINGER_SECONDS = REGISTRY.histogram(
    "mfm_coalesce_linger_seconds",
    "oldest-request wait inside the coalescer at flush time",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0))
FRONTEND_CONNECTIONS_TOTAL = REGISTRY.counter(
    "mfm_frontend_connections_total", "client connections accepted")
FLEET_DISPATCH_TOTAL = REGISTRY.counter(
    "mfm_fleet_dispatch_total",
    "admitted request lines dispatched to worker replicas",
    labelnames=("replica",))
FLEET_REPLICA_DEATHS_TOTAL = REGISTRY.counter(
    "mfm_fleet_replica_deaths_total",
    "worker replicas lost mid-serve (crash/SIGKILL — their in-flight "
    "batch is re-dispatched to survivors)")
FLEET_REPLICA_QUARANTINED_TOTAL = REGISTRY.counter(
    "mfm_fleet_replica_quarantined_total",
    "worker replicas drained out after failing their fence audit")
FLEET_REDISPATCH_TOTAL = REGISTRY.counter(
    "mfm_fleet_redispatch_total",
    "request lines re-dispatched after a replica death or quarantine")
FLEET_TRANSPORT_RECONNECTS_TOTAL = REGISTRY.counter(
    "mfm_fleet_transport_reconnects_total",
    "extra worker connect attempts beyond the first (with_retry backoff "
    "while the worker was still loading its checkpoint)",
    labelnames=("replica",))
FLEET_TRANSPORT_HEARTBEAT_MISSES_TOTAL = REGISTRY.counter(
    "mfm_fleet_transport_heartbeat_misses_total",
    "heartbeat pings a worker failed to answer within the deadline "
    "(wedged worker: quarantined, its batch re-dispatched)",
    labelnames=("replica",))
FLEET_TRANSPORT_IO_TIMEOUTS_TOTAL = REGISTRY.counter(
    "mfm_fleet_transport_io_timeouts_total",
    "per-I/O deadline expiries on worker reads/writes by failure phase "
    "(connect = never attached, batch = lost mid-batch)",
    labelnames=("replica", "phase"))
FLEET_ROLLOUT_STEPS_TOTAL = REGISTRY.counter(
    "mfm_fleet_rollout_steps_total",
    "single-worker re-fence steps completed by rolling checkpoint "
    "rollouts (one per worker per generation crossed)")

# -- response cache (serve/cache.py content-addressed reuse) ------------------

CACHE_HITS_TOTAL = REGISTRY.counter(
    "mfm_cache_hits_total",
    "response-cache hits (cached body re-stamped with the caller's "
    "id/trace_id)")
CACHE_MISSES_TOTAL = REGISTRY.counter(
    "mfm_cache_misses_total",
    "response-cache misses (request rode the cold path)")
CACHE_EVICTIONS_TOTAL = REGISTRY.counter(
    "mfm_cache_evictions_total",
    "entries evicted (LRU) by the entry/byte bounds — includes entries "
    "stranded behind an old generation fence")
CACHE_BYTES_TOTAL = REGISTRY.counter(
    "mfm_cache_bytes_total",
    "cumulative response-body bytes inserted into the cache")
CACHE_ENTRIES = REGISTRY.gauge(
    "mfm_cache_entries", "resident response-cache entries")
CACHE_RESIDENT_BYTES = REGISTRY.gauge(
    "mfm_cache_resident_bytes", "resident response-cache body bytes")
CACHE_HIT_LATENCY_SECONDS = REGISTRY.histogram(
    "mfm_cache_hit_latency_seconds",
    "lookup-to-restamped-response wall time on a cache hit",
    buckets=(0.000005, 0.00001, 0.000025, 0.00005, 0.0001, 0.00025,
             0.0005, 0.001, 0.0025, 0.01))
RESPONSES_DELIVERED_TOTAL = REGISTRY.counter(
    "mfm_responses_delivered_total",
    "responses delivered through the caching layer (hits + computed); "
    "doctor --serve audits delivered == computed + hits")
CONSTRUCT_WARM_STARTS_TOTAL = REGISTRY.counter(
    "mfm_construct_warm_starts_total",
    "construction solves seeded from a near-miss cached solution")
CONSTRUCT_WARM_STEPS_SAVED_TOTAL = REGISTRY.counter(
    "mfm_construct_warm_steps_saved_total",
    "solver iterations saved by warm-started construction solves")

# -- scenario engine (scenario/engine.py batched stress tests) ----------------

SCENARIOS_RUN_TOTAL = REGISTRY.counter(
    "mfm_scenarios_run_total", "scenarios answered by admission outcome",
    labelnames=("status",))   # ok | rejected
SCENARIO_BATCH_SECONDS = REGISTRY.histogram(
    "mfm_scenario_batch_seconds",
    "device wall time per batched scenario run (all S lanes, one jit)")
SCENARIO_BATCH_SIZE = REGISTRY.histogram(
    "mfm_scenario_batch_size", "true (unpadded) scenarios per batch",
    buckets=(1, 2, 8, 32, 128, 512, 2048, 8192, 32768))
SCENARIO_PSD_PROJECTIONS_TOTAL = REGISTRY.counter(
    "mfm_scenario_psd_projections_total",
    "lanes whose stressed covariance went indefinite and was projected "
    "back to PSD (corr stress past the feasible cone)")

# -- streaming sweeps (scenario/sweep.py) -------------------------------------

SWEEP_SCENARIOS_TOTAL = REGISTRY.counter(
    "mfm_sweep_scenarios_total",
    "sweep lanes streamed by admission outcome",
    labelnames=("status",))   # ok | rejected
SWEEP_CHUNKS_TOTAL = REGISTRY.counter(
    "mfm_sweep_chunks_total",
    "donated chunk-kernel calls dispatched by sweeps (hot path + "
    "offender flushes)")
SWEEP_SECONDS = REGISTRY.histogram(
    "mfm_sweep_seconds",
    "host wall time per full sweep (coarse + refinement, carry pull "
    "included)")
SWEEP_OFFENDER_LANES_TOTAL = REGISTRY.counter(
    "mfm_sweep_offender_lanes_total",
    "lanes the host inertia certificate could not vouch for, routed "
    "through the exact per-lane eigh path")
SWEEP_PSD_PROJECTIONS_TOTAL = REGISTRY.counter(
    "mfm_sweep_psd_projections_total",
    "offender lanes whose stressed covariance was projected back to PSD "
    "before merging")


# -- recording helpers --------------------------------------------------------

def record_guard_report(report) -> None:
    """Tally one guarded step's verdicts (host-side, report already
    materialized by the update call)."""
    from mfm_tpu.serve import guard

    q = np.asarray(report.quarantined).astype(bool)
    reasons = np.asarray(report.reasons)
    staleness = np.asarray(report.staleness)
    n_q = int(q.sum())
    if n_q:
        QUARANTINED_DATES_TOTAL.inc(n_q)
    SERVED_DATES_TOTAL.inc(int(q.shape[0]))
    QUARANTINE_COUNT.set_value(n_q)
    if staleness.size:
        SERVED_COV_STALENESS.set_value(int(staleness[-1]))
    for bit, name in guard._REASON_NAMES:
        n = int(((reasons & bit) != 0).sum())
        if n:
            GUARD_REASON_TOTAL.inc(n, reason=name)


def record_update_latency(seconds: float) -> None:
    UPDATE_LATENCY.observe(float(seconds))


def record_stage_seconds(stage: str, seconds: float) -> None:
    STAGE_SECONDS.set_value(float(seconds), stage=stage)


def record_compiled_memory(stage: str, mem: dict) -> None:
    """Export a ``utils.obs.compiled_memory`` analysis as labeled gauges."""
    for kind, v in mem.items():
        if isinstance(v, (int, float)):
            COMPILED_BYTES.set_value(float(v), stage=stage, kind=kind)


@contextlib.contextmanager
def time_stage(stage: str):
    """Span a host-side stage; sets ``mfm_stage_seconds{stage=...}``.

    The body must force its JAX work before exiting (mfmlint R5 already
    polices perf_counter spans in bench/tools); this span only *reads* the
    clock, it never forces device work itself.
    """
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_stage_seconds(stage, time.perf_counter() - t0)


_COMPILE_WATCHER = None


def watch_compiles() -> None:
    """Install a process-lifetime lowering listener feeding
    ``mfm_jit_compiles_total`` (idempotent)."""
    global _COMPILE_WATCHER
    if _COMPILE_WATCHER is not None:
        return
    from jax._src import monitoring

    from mfm_tpu.utils.contracts import _COMPILE_EVENT

    def _listener(event: str, duration: float, **kwargs) -> None:
        if event == _COMPILE_EVENT:
            JIT_COMPILES_TOTAL.inc()

    monitoring.register_event_duration_secs_listener(_listener)
    _COMPILE_WATCHER = _listener


def unwatch_compiles() -> None:
    """Remove the listener installed by :func:`watch_compiles` (tests)."""
    global _COMPILE_WATCHER
    if _COMPILE_WATCHER is None:
        return
    from jax._src import monitoring

    unregister = getattr(
        monitoring, "_unregister_event_duration_listener_by_callback", None)
    if unregister is not None:
        unregister(_COMPILE_WATCHER)
    _COMPILE_WATCHER = None


def record_foreign_spans(n: int, uncorrectable: int = 0) -> None:
    """Tally one fleet-wire span merge: spans ingested + how many were
    flagged with uncorrectable clock skew."""
    if n:
        TRACE_FOREIGN_SPANS_TOTAL.inc(int(n))
    if uncorrectable:
        TRACE_SKEW_UNCORRECTABLE_TOTAL.inc(int(uncorrectable))


def record_flightrec_event(n: int = 1, dropped: int = 0) -> None:
    FLIGHTREC_EVENTS_TOTAL.inc(int(n))
    if dropped:
        FLIGHTREC_DROPPED_TOTAL.inc(int(dropped))


def record_flightrec_dump(trigger: str) -> None:
    FLIGHTREC_DUMPS_TOTAL.inc(1, trigger=str(trigger))


def record_slo_state(slo: str, state: str, burn_fast: float,
                     burn_slow: float) -> None:
    """Mirror one SLO evaluation onto the gauges; a burning state also
    tallies ``mfm_slo_breaches_total``."""
    SLO_BURN_RATE.set_value(float(burn_fast), slo=slo, window="fast")
    SLO_BURN_RATE.set_value(float(burn_slow), slo=slo, window="slow")
    SLO_STATE.set_value(
        {"ok": 0, "slow_burn": 1, "fast_burn": 2}.get(state, 0), slo=slo)
    if state != "ok":
        SLO_BREACHES_TOTAL.inc(1, slo=slo, state=state)


def record_query_outcome(outcome: str, n: int = 1) -> None:
    QUERY_REQUESTS_TOTAL.inc(n, outcome=outcome)


def record_query_batch(n_true: int, seconds: float) -> None:
    """Tally one drained batch: true (unpadded) size + device wall."""
    QUERY_BATCH_SIZE.observe(int(n_true))
    QUERY_BATCH_SECONDS.observe(float(seconds))
    QUERY_PORTFOLIOS_TOTAL.inc(int(n_true))


def record_query_latency(seconds: float) -> None:
    QUERY_LATENCY_SECONDS.observe(float(seconds))


def record_queue_depth(depth: int) -> None:
    QUERY_QUEUE_DEPTH.set_value(int(depth))


def record_shed(n: int = 1) -> None:
    QUERY_SHED_TOTAL.inc(int(n))


def record_breaker_state(state: str) -> None:
    """Mirror a breaker transition onto the gauge; entering ``open`` also
    tallies ``mfm_breaker_open_total``."""
    BREAKER_STATE.set_value(_BREAKER_STATE_CODE[state])
    if state == "open":
        BREAKER_OPEN_TOTAL.inc()


def serve_summary_from_registry() -> dict:
    """The manifest's query-service block, off the live counters.

    This is what ``mfm-tpu doctor --serve`` audits: a breaker left in the
    open state at shutdown (``breaker_state`` = "open") is a failed serve
    run even if every individual request got a well-formed response.
    """
    outcomes = {k[0]: int(v) for k, v in QUERY_REQUESTS_TOTAL.series().items()}
    total = sum(outcomes.values())
    shed = int(QUERY_SHED_TOTAL.value())
    state_code = int(BREAKER_STATE.value())
    p50 = QUERY_LATENCY_SECONDS.quantile_est(0.5)
    p99 = QUERY_LATENCY_SECONDS.quantile_est(0.99)
    out = {
        "requests": outcomes,
        "requests_total": total,
        "portfolios_total": int(QUERY_PORTFOLIOS_TOTAL.value()),
        "shed_total": shed,
        "shed_rate": (round(shed / total, 6) if total else 0.0),
        "breaker_open_total": int(BREAKER_OPEN_TOTAL.value()),
        "breaker_state": _BREAKER_CODE_STATE.get(state_code, "closed"),
        "query_p50_latency_s": (None if p50 != p50 else round(p50, 6)),
        "query_p99_latency_s": (None if p99 != p99 else round(p99, 6)),
        "cache": cache_summary_from_registry(),
    }
    # the SLO block rides along whenever an engine is installed (the
    # serve CLI installs one): /healthz, the serve/fleet manifests and
    # doctor --serve all read the same evaluation.  Deferred import —
    # obs/slo.py reads THIS module's catalog.
    from mfm_tpu.obs import slo as _slo
    slo_block = _slo.installed_summary()
    if slo_block is not None:
        out["slo"] = slo_block
    return out


def record_coalesce_flush(n_true: int, capacity: int, trigger: str,
                          lingered_s: float) -> None:
    """Tally one coalesced flush: fill fraction vs the geometric bucket
    the batch padded to, what triggered it, and how long the oldest
    queued request lingered."""
    COALESCE_FLUSHES_TOTAL.inc(1, trigger=trigger)
    if capacity > 0:
        COALESCE_BATCH_FILL.observe(min(1.0, n_true / capacity))
    COALESCE_LINGER_SECONDS.observe(max(0.0, float(lingered_s)))


def record_frontend_connection(n: int = 1) -> None:
    FRONTEND_CONNECTIONS_TOTAL.inc(int(n))


def record_cache_hit(latency_s: float) -> None:
    CACHE_HITS_TOTAL.inc()
    CACHE_HIT_LATENCY_SECONDS.observe(max(0.0, float(latency_s)))


def record_cache_miss() -> None:
    CACHE_MISSES_TOTAL.inc()


def record_cache_store(size_bytes: int, evicted: int,
                       entries_now: int, resident_now: int) -> None:
    """Tally one cache insertion: bytes added, entries it displaced, and
    the resulting occupancy gauges."""
    CACHE_BYTES_TOTAL.inc(int(size_bytes))
    if evicted:
        CACHE_EVICTIONS_TOTAL.inc(int(evicted))
    CACHE_ENTRIES.set_value(int(entries_now))
    CACHE_RESIDENT_BYTES.set_value(int(resident_now))


def record_responses_delivered(n: int = 1) -> None:
    RESPONSES_DELIVERED_TOTAL.inc(int(n))


def record_warm_start(steps_saved: int) -> None:
    CONSTRUCT_WARM_STARTS_TOTAL.inc()
    CONSTRUCT_WARM_STEPS_SAVED_TOTAL.inc(int(steps_saved))


def cache_summary_from_registry() -> dict:
    """The manifest's response-cache block, off the live counters.

    ``delivered_total`` counts every response that left through the
    caching layer; when a cache was active, ``mfm-tpu doctor --serve``
    checks ``delivered_total == requests_total + hits_total`` (every
    delivered response is exactly one of: computed with a recorded
    outcome, or served from cache)."""
    hits = int(CACHE_HITS_TOTAL.value())
    misses = int(CACHE_MISSES_TOTAL.value())
    looked = hits + misses
    p99 = CACHE_HIT_LATENCY_SECONDS.quantile_est(0.99)
    return {
        "hits_total": hits,
        "misses_total": misses,
        "hit_rate": (round(hits / looked, 6) if looked else 0.0),
        "evictions_total": int(CACHE_EVICTIONS_TOTAL.value()),
        "entries": int(CACHE_ENTRIES.value()),
        "resident_bytes": int(CACHE_RESIDENT_BYTES.value()),
        "inserted_bytes_total": int(CACHE_BYTES_TOTAL.value()),
        "delivered_total": int(RESPONSES_DELIVERED_TOTAL.value()),
        "hit_p99_latency_s": (None if p99 != p99 else round(p99, 9)),
        "warm_starts_total": int(CONSTRUCT_WARM_STARTS_TOTAL.value()),
        "warm_steps_saved_total": int(
            CONSTRUCT_WARM_STEPS_SAVED_TOTAL.value()),
    }


def record_fleet_dispatch(replica: int, n: int = 1) -> None:
    FLEET_DISPATCH_TOTAL.inc(int(n), replica=str(replica))


def record_replica_death(n: int = 1) -> None:
    FLEET_REPLICA_DEATHS_TOTAL.inc(int(n))


def record_replica_quarantine(n: int = 1) -> None:
    FLEET_REPLICA_QUARANTINED_TOTAL.inc(int(n))


def record_fleet_redispatch(n: int = 1) -> None:
    FLEET_REDISPATCH_TOTAL.inc(int(n))


def record_transport_reconnects(replica: int, n: int) -> None:
    if n:
        FLEET_TRANSPORT_RECONNECTS_TOTAL.inc(int(n), replica=str(replica))


def record_heartbeat_miss(replica: int, n: int = 1) -> None:
    FLEET_TRANSPORT_HEARTBEAT_MISSES_TOTAL.inc(int(n),
                                               replica=str(replica))


def record_transport_timeout(replica: int, phase: str,
                             n: int = 1) -> None:
    FLEET_TRANSPORT_IO_TIMEOUTS_TOTAL.inc(int(n), replica=str(replica),
                                          phase=str(phase))


def record_rollout_step(n: int = 1) -> None:
    FLEET_ROLLOUT_STEPS_TOTAL.inc(int(n))


def fleet_summary_from_registry() -> dict:
    """The fleet manifest's front-end block, off the live counters.

    Extends :func:`serve_summary_from_registry` with the coalescer and
    replica-dispatch counters; ``mfm-tpu doctor --serve`` audits the
    per-replica outcome counts in the merged manifest against this
    block's dispatch totals."""
    out = serve_summary_from_registry()
    flushes = {k[0]: int(v)
               for k, v in COALESCE_FLUSHES_TOTAL.series().items()}
    dispatch = {k[0]: int(v)
                for k, v in FLEET_DISPATCH_TOTAL.series().items()}
    fill_series = COALESCE_BATCH_FILL.series()
    fill_mean = None
    if fill_series:
        st = next(iter(fill_series.values()))
        if st.count:
            fill_mean = round(st.total / st.count, 6)
    linger_p99 = COALESCE_LINGER_SECONDS.quantile_est(0.99)
    out.update({
        "coalesce_flushes": flushes,
        "coalesce_flushes_total": sum(flushes.values()),
        "coalesce_batch_fill_frac": fill_mean,
        "coalesce_linger_p99_s": (None if linger_p99 != linger_p99
                                  else round(linger_p99, 6)),
        "connections_total": int(FRONTEND_CONNECTIONS_TOTAL.value()),
        "dispatch_by_replica": dispatch,
        "dispatch_total": sum(dispatch.values()),
        "replica_deaths_total": int(FLEET_REPLICA_DEATHS_TOTAL.value()),
        "replica_quarantined_total": int(
            FLEET_REPLICA_QUARANTINED_TOTAL.value()),
        "redispatch_total": int(FLEET_REDISPATCH_TOTAL.value()),
        "transport": {
            "reconnects_total": int(sum(
                FLEET_TRANSPORT_RECONNECTS_TOTAL.series().values())),
            "heartbeat_misses_total": int(sum(
                FLEET_TRANSPORT_HEARTBEAT_MISSES_TOTAL.series()
                .values())),
            "io_timeouts_total": int(sum(
                FLEET_TRANSPORT_IO_TIMEOUTS_TOTAL.series().values())),
        },
        "rollout_steps_total": int(FLEET_ROLLOUT_STEPS_TOTAL.value()),
    })
    return out


def record_scenario_batch(n_true: int, seconds: float) -> None:
    """Tally one batched scenario run: true (unpadded) S + device wall."""
    SCENARIO_BATCH_SIZE.observe(int(n_true))
    SCENARIO_BATCH_SECONDS.observe(float(seconds))


def record_scenario_outcome(status: str, n: int = 1) -> None:
    SCENARIOS_RUN_TOTAL.inc(int(n), status=status)


def record_psd_projections(n: int = 1) -> None:
    SCENARIO_PSD_PROJECTIONS_TOTAL.inc(int(n))


def record_sweep(n_ok: int, n_rejected: int, n_chunks: int,
                 seconds: float) -> None:
    """Tally one full sweep: admitted/rejected lanes, chunk-kernel calls
    and host wall."""
    if n_ok:
        SWEEP_SCENARIOS_TOTAL.inc(int(n_ok), status="ok")
    if n_rejected:
        SWEEP_SCENARIOS_TOTAL.inc(int(n_rejected), status="rejected")
    SWEEP_CHUNKS_TOTAL.inc(int(n_chunks))
    SWEEP_SECONDS.observe(float(seconds))


def record_sweep_offenders(n: int = 1) -> None:
    SWEEP_OFFENDER_LANES_TOTAL.inc(int(n))


def record_sweep_projections(n: int = 1) -> None:
    SWEEP_PSD_PROJECTIONS_TOTAL.inc(int(n))


def sweep_summary_from_registry() -> dict:
    """The sweep manifest's ``summary`` block, off the live counters (the
    one VOLATILE manifest field — wall quantiles don't replay)."""
    statuses = {k[0]: int(v) for k, v in SWEEP_SCENARIOS_TOTAL.series().items()}
    p50 = SWEEP_SECONDS.quantile_est(0.5)
    return {
        "sweep_lanes": statuses,
        "sweep_lanes_total": sum(statuses.values()),
        "chunks_total": int(SWEEP_CHUNKS_TOTAL.value()),
        "offender_lanes_total": int(SWEEP_OFFENDER_LANES_TOTAL.value()),
        "psd_projections_total": int(SWEEP_PSD_PROJECTIONS_TOTAL.value()),
        "sweep_p50_wall_s": (None if p50 != p50 else round(p50, 6)),
    }


def scenario_summary_from_registry() -> dict:
    """The scenario manifest's ``summary`` block, off the live counters
    (the one VOLATILE manifest field — latency quantiles don't replay)."""
    statuses = {k[0]: int(v) for k, v in SCENARIOS_RUN_TOTAL.series().items()}
    p50 = SCENARIO_BATCH_SECONDS.quantile_est(0.5)
    p99 = SCENARIO_BATCH_SECONDS.quantile_est(0.99)
    return {
        "scenarios": statuses,
        "scenarios_total": sum(statuses.values()),
        "psd_projections_total": int(
            SCENARIO_PSD_PROJECTIONS_TOTAL.value()),
        "batch_p50_latency_s": (None if p50 != p50 else round(p50, 6)),
        "batch_p99_latency_s": (None if p99 != p99 else round(p99, 6)),
    }


def guard_summary_from_registry() -> dict:
    """The manifest's guard verdict summary, off the live counters."""
    served = SERVED_DATES_TOTAL.value()
    quarantined = QUARANTINED_DATES_TOTAL.value()
    reasons = {}
    for key, n in GUARD_REASON_TOTAL.series().items():
        reasons[key[0]] = int(n)
    return {
        "served_dates": int(served),
        "quarantined_dates": int(quarantined),
        "quarantine_rate": (round(quarantined / served, 6) if served else 0.0),
        "reasons": reasons,
        "last_staleness": int(SERVED_COV_STALENESS.value()),
    }
