"""Instrumentation: the metric catalog + host-side recording helpers.

This module is the single place where the serving stack's metric names are
declared (``docs/OBSERVABILITY.md`` mirrors this catalog).  Everything
records CONCRETE host values — numpy scalars off already-materialized jit
outputs, wall-clock spans around jit calls, filesystem events — never
tracers; recording around the jit boundary is what keeps the fused update
at ≤1 compile with telemetry on (and mfmlint R7 makes reaching these from
traced code a lint error).

Compile visibility reuses the :class:`~mfm_tpu.utils.contracts.CompileCounter`
lowering hook: :func:`watch_compiles` registers a process-lifetime listener
that tallies ``mfm_jit_compiles_total``, so a steady-state recompile shows
up on a dashboard instead of only in a test assertion.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

from mfm_tpu.obs.metrics import REGISTRY

# -- catalog ------------------------------------------------------------------

GUARD_REASON_TOTAL = REGISTRY.counter(
    "mfm_guard_reason_total",
    "guard trips by reason bit (one date may tally several reasons)",
    labelnames=("reason",))
QUARANTINED_DATES_TOTAL = REGISTRY.counter(
    "mfm_quarantined_dates_total", "dates excised by the quarantine policy")
SERVED_DATES_TOTAL = REGISTRY.counter(
    "mfm_served_dates_total", "dates served (healthy + degraded-mode)")
SERVED_COV_STALENESS = REGISTRY.gauge(
    "mfm_served_cov_staleness",
    "dates since the most recently served covariance was fit (0 = fresh)")
QUARANTINE_COUNT = REGISTRY.gauge(
    "mfm_quarantine_count", "quarantined dates in the last guarded step")
UPDATE_LATENCY = REGISTRY.histogram(
    "mfm_update_latency_seconds", "guarded/unguarded update step wall time")

STAGE_SECONDS = REGISTRY.gauge(
    "mfm_stage_seconds", "last wall time of a pipeline/risk stage",
    labelnames=("stage",))
COMPILED_BYTES = REGISTRY.gauge(
    "mfm_compiled_bytes",
    "compiled-program memory analysis (utils.obs.compiled_memory)",
    labelnames=("stage", "kind"))

CHECKPOINT_SAVES_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_saves_total", "fenced artifact saves")
CHECKPOINT_LOADS_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_loads_total", "fenced artifact loads")
CHECKPOINT_CORRUPT_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_corrupt_total",
    "checksum/fence verification failures on load")
CHECKPOINT_STALE_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_stale_total", "generation-fence rejections on load")
CHECKPOINT_HEAL_FORWARD_TOTAL = REGISTRY.counter(
    "mfm_checkpoint_heal_forward_total",
    "pointer heal-forwards after a crash between rename and pointer swap")
CHECKPOINT_GENERATION = REGISTRY.gauge(
    "mfm_checkpoint_generation", "generation fence of the last save/load")
CHECKPOINT_SAVE_SECONDS = REGISTRY.histogram(
    "mfm_checkpoint_save_seconds", "artifact save wall time")
CHECKPOINT_LOAD_SECONDS = REGISTRY.histogram(
    "mfm_checkpoint_load_seconds", "artifact load wall time")

RETRY_ATTEMPTS_TOTAL = REGISTRY.counter(
    "mfm_retry_attempts_total", "with_retry attempts by outcome",
    labelnames=("outcome",))   # outcome: ok | retried | exhausted
RETRY_BACKOFF_SECONDS = REGISTRY.histogram(
    "mfm_retry_backoff_seconds", "with_retry sleep durations",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0))

JIT_COMPILES_TOTAL = REGISTRY.counter(
    "mfm_jit_compiles_total",
    "jit lowerings observed since watch_compiles() (steady state: flat)")


# -- recording helpers --------------------------------------------------------

def record_guard_report(report) -> None:
    """Tally one guarded step's verdicts (host-side, report already
    materialized by the update call)."""
    from mfm_tpu.serve import guard

    q = np.asarray(report.quarantined).astype(bool)
    reasons = np.asarray(report.reasons)
    staleness = np.asarray(report.staleness)
    n_q = int(q.sum())
    if n_q:
        QUARANTINED_DATES_TOTAL.inc(n_q)
    SERVED_DATES_TOTAL.inc(int(q.shape[0]))
    QUARANTINE_COUNT.set_value(n_q)
    if staleness.size:
        SERVED_COV_STALENESS.set_value(int(staleness[-1]))
    for bit, name in guard._REASON_NAMES:
        n = int(((reasons & bit) != 0).sum())
        if n:
            GUARD_REASON_TOTAL.inc(n, reason=name)


def record_update_latency(seconds: float) -> None:
    UPDATE_LATENCY.observe(float(seconds))


def record_stage_seconds(stage: str, seconds: float) -> None:
    STAGE_SECONDS.set_value(float(seconds), stage=stage)


def record_compiled_memory(stage: str, mem: dict) -> None:
    """Export a ``utils.obs.compiled_memory`` analysis as labeled gauges."""
    for kind, v in mem.items():
        if isinstance(v, (int, float)):
            COMPILED_BYTES.set_value(float(v), stage=stage, kind=kind)


@contextlib.contextmanager
def time_stage(stage: str):
    """Span a host-side stage; sets ``mfm_stage_seconds{stage=...}``.

    The body must force its JAX work before exiting (mfmlint R5 already
    polices perf_counter spans in bench/tools); this span only *reads* the
    clock, it never forces device work itself.
    """
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_stage_seconds(stage, time.perf_counter() - t0)


_COMPILE_WATCHER = None


def watch_compiles() -> None:
    """Install a process-lifetime lowering listener feeding
    ``mfm_jit_compiles_total`` (idempotent)."""
    global _COMPILE_WATCHER
    if _COMPILE_WATCHER is not None:
        return
    from jax._src import monitoring

    from mfm_tpu.utils.contracts import _COMPILE_EVENT

    def _listener(event: str, duration: float, **kwargs) -> None:
        if event == _COMPILE_EVENT:
            JIT_COMPILES_TOTAL.inc()

    monitoring.register_event_duration_secs_listener(_listener)
    _COMPILE_WATCHER = _listener


def unwatch_compiles() -> None:
    """Remove the listener installed by :func:`watch_compiles` (tests)."""
    global _COMPILE_WATCHER
    if _COMPILE_WATCHER is None:
        return
    from jax._src import monitoring

    unregister = getattr(
        monitoring, "_unregister_event_duration_listener_by_callback", None)
    if unregister is not None:
        unregister(_COMPILE_WATCHER)
    _COMPILE_WATCHER = None


def guard_summary_from_registry() -> dict:
    """The manifest's guard verdict summary, off the live counters."""
    served = SERVED_DATES_TOTAL.value()
    quarantined = QUARANTINED_DATES_TOTAL.value()
    reasons = {}
    for key, n in GUARD_REASON_TOTAL.series().items():
        reasons[key[0]] = int(n)
    return {
        "served_dates": int(served),
        "quarantined_dates": int(quarantined),
        "quarantine_rate": (round(quarantined / served, 6) if served else 0.0),
        "reasons": reasons,
        "last_staleness": int(SERVED_COV_STALENESS.value()),
    }
