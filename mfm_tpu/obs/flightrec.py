"""Flight recorder: a bounded postmortem ring + atomic crash dumps.

When a breaker opens or a worker wedges at 2 a.m., the counters say THAT
something went wrong; this module preserves WHAT was in flight.  A
bounded, thread-safe ring (the trace ring's drop-oldest discipline —
overflow evicts the oldest event and counts the loss) collects recent
request/batch/transport/fence events as the serving stack runs, and on a
triggering condition — breaker-open, wedge quarantine, failed fence
audit, SIGTERM — the whole ring is dumped atomically to
``flightrec.json`` (tmp -> fsync -> chaos point ``flightrec.after_tmp``
-> rename -> dir fsync, the manifest discipline) bundling:

- the event ring (newest last), each event stamped with its wall clock
  and, when known, the trace id of the request that produced it;
- the span ring snapshot (wire form — the same dicts the fleet ships),
  so the dump joins to the Chrome trace;
- a full metrics snapshot (``obs/metrics.py`` registry);
- caller-supplied state (breaker state/reason, rollout generation,
  replica ledgers).

The recorder is ARMED with a dump path by the serve CLI; unarmed,
:func:`trigger_dump` is a no-op, so unit-level servers never write
files.  Repeated triggers overwrite the same path (the newest postmortem
wins — each dump already contains the history that led to it).

Host-only module (mfmlint R7): stdlib + the obs registry, nothing here
may be reached from traced code.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from mfm_tpu.obs import instrument as _obs
from mfm_tpu.utils.chaos import chaos_point

#: default event-ring capacity — events are small dicts; 512 of them is
#: minutes of fleet context at steady load, one flush storm at peak
DEFAULT_RING_CAPACITY = 512

#: the dump's on-disk name beside the checkpoint/manifests
FLIGHTREC_NAME = "flightrec.json"

FLIGHTREC_SCHEMA_VERSION = 1

_lock = threading.Lock()
_ring: collections.deque = collections.deque()
_capacity = DEFAULT_RING_CAPACITY
_dump_path: str | None = None


def set_capacity(n: int) -> None:
    """Resize the event ring (overflow drops oldest, counted)."""
    global _capacity
    if int(n) < 1:
        raise ValueError(f"flightrec capacity must be >= 1, got {n}")
    with _lock:
        _capacity = int(n)
        _evict_locked()


def _evict_locked() -> int:
    dropped = 0
    while len(_ring) > _capacity:
        _ring.popleft()
        dropped += 1
    return dropped


def record_event(kind: str, *, trace_id: str | None = None,
                 **fields) -> dict:
    """Append one event to the ring.  ``kind`` is the event vocabulary
    ("batch", "batch_error", "dispatch", "transport_fail",
    "breaker_open", "fence_audit", "wedge_quarantine", "rollout", ...);
    ``trace_id`` joins it to the request timeline when one is in scope;
    ``fields`` are small JSON-safe details."""
    ev = {"kind": str(kind), "wall_ts": round(time.time(), 3)}
    if trace_id is not None:
        ev["trace_id"] = str(trace_id)
    if fields:
        ev.update(fields)
    with _lock:
        _ring.append(ev)
        dropped = _evict_locked()
    _obs.record_flightrec_event(1, dropped)
    return ev


def events() -> list:
    """Snapshot of the ring, oldest first."""
    with _lock:
        return [dict(ev) for ev in _ring]


def last_trace_id() -> str | None:
    """The most recent event's trace id, if any event carried one — the
    default "triggering request" stamp for dumps whose trigger site
    (e.g. the breaker's failure counter) does not know the request."""
    with _lock:
        for ev in reversed(_ring):
            tid = ev.get("trace_id")
            if tid is not None:
                return tid
    return None


def reset_flightrec() -> None:
    """Drop every event and disarm the recorder (tests)."""
    global _capacity, _dump_path
    with _lock:
        _ring.clear()
        _capacity = DEFAULT_RING_CAPACITY
        _dump_path = None


# -- arming + triggered dumps -------------------------------------------------

def arm(path: str | None) -> None:
    """Point triggered dumps at ``path`` (None disarms).  The serve CLI
    arms the recorder beside the checkpoint's manifests."""
    global _dump_path
    with _lock:
        _dump_path = path


def armed_path() -> str | None:
    with _lock:
        return _dump_path


def trigger_dump(trigger: str, *, trace_id: str | None = None,
                 state: dict | None = None) -> str | None:
    """Dump the recorder to the armed path (no-op when unarmed).  Never
    raises — a postmortem writer that can take down the serving loop
    would be worse than no postmortem; failures surface on stderr and in
    the returned None."""
    path = armed_path()
    if path is None:
        return None
    try:
        return dump_flightrec(path, trigger=trigger, trace_id=trace_id,
                              state=state)
    except OSError as e:  # pragma: no cover - disk-full/readonly paths
        import sys
        print(f"flightrec: dump failed ({e})", file=sys.stderr)
        return None


def dump_flightrec(path: str, *, trigger: str,
                   trace_id: str | None = None,
                   state: dict | None = None) -> str:
    """Atomically write the postmortem bundle to ``path``.

    ``trace_id`` defaults to the newest event's (the triggering
    request); ``state`` is the caller's live context (breaker, rollout,
    replica ledgers).  The write is tmp -> fsync -> chaos point ->
    rename -> dir fsync, so a SIGKILL mid-dump leaves either the prior
    dump or none — never a torn file.  Returns the final path."""
    from mfm_tpu.obs import trace as _trace
    from mfm_tpu.obs.metrics import REGISTRY

    bundle = {
        "schema": FLIGHTREC_SCHEMA_VERSION,
        "trigger": str(trigger),
        "trace_id": trace_id if trace_id is not None else last_trace_id(),
        "taken_at_unix": round(time.time(), 3),
        "events": events(),
        "spans": [_trace.wire_span(s) for s in _trace.spans()],
        "metrics": REGISTRY.snapshot(),
        "state": dict(state or {}),
    }
    text = json.dumps(bundle, sort_keys=True, default=str)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    chaos_point("flightrec.after_tmp", path)
    os.replace(tmp, path)
    try:
        fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - exotic filesystems
        pass
    _obs.record_flightrec_dump(trigger)
    return path


def read_flightrec(path: str) -> dict:
    """Load + schema-check a dump; raises ValueError on anything a
    postmortem reader would choke on (the torn-file check the chaos plan
    drives)."""
    with open(path, "r", encoding="utf-8") as fh:
        try:
            obj = json.load(fh)
        except ValueError as e:
            raise ValueError(
                f"not valid JSON ({e}) — torn flightrec dump?") from e
    if not isinstance(obj, dict):
        raise ValueError("flightrec dump must be a JSON object")
    if obj.get("schema") != FLIGHTREC_SCHEMA_VERSION:
        raise ValueError(f"unsupported flightrec schema "
                         f"{obj.get('schema')!r}")
    for key in ("trigger", "events", "spans", "metrics", "state"):
        if key not in obj:
            raise ValueError(f"flightrec dump missing {key!r}")
    if not isinstance(obj["events"], list) or \
            not isinstance(obj["spans"], list):
        raise ValueError("flightrec events/spans must be lists")
    return obj
