"""``mfm_tpu.obs`` — the telemetry subsystem (host-side ONLY; mfmlint R7).

- :mod:`~mfm_tpu.obs.metrics` — counters/gauges/histograms + REGISTRY
- :mod:`~mfm_tpu.obs.exporters` — JSONL events, Prometheus textfile
- :mod:`~mfm_tpu.obs.instrument` — metric catalog + recording helpers
- :mod:`~mfm_tpu.obs.manifest` — atomic per-run manifest beside checkpoints
- :mod:`~mfm_tpu.obs.health` — USE4 bias / R² drift / outlier monitors
- :mod:`~mfm_tpu.obs.trace` — request-scoped spans + Chrome-trace export
- :mod:`~mfm_tpu.obs.profile` — cost_analysis / memory / compile-wall probes
  (imports jax; import the module explicitly, it is not re-exported here)

Catalog + schemas: ``docs/OBSERVABILITY.md``.
"""

from mfm_tpu.obs.exporters import (EventLog, emit_event, parse_prometheus,
                                   render_prometheus, route_events_to,
                                   write_prometheus_textfile)
from mfm_tpu.obs.manifest import (MANIFEST_SCHEMA_VERSION, ManifestError,
                                  build_run_manifest, manifest_path_for,
                                  read_run_manifest, write_run_manifest)
from mfm_tpu.obs.health import HealthThresholds, evaluate_health
from mfm_tpu.obs.metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                                 REGISTRY, is_enabled, set_enabled,
                                 snapshot_json)
from mfm_tpu.obs.trace import (Span, current_trace_id, end_span, new_trace_id,
                               parse_chrome_trace, render_chrome_trace,
                               reset_tracing, set_tracing, span, spans,
                               start_span, tracing_enabled, write_chrome_trace)

__all__ = [
    "Counter", "EventLog", "Gauge", "HealthThresholds", "Histogram",
    "MANIFEST_SCHEMA_VERSION", "ManifestError", "MetricsRegistry", "REGISTRY",
    "Span", "build_run_manifest", "current_trace_id", "emit_event",
    "end_span", "evaluate_health", "is_enabled", "manifest_path_for",
    "new_trace_id", "parse_chrome_trace", "parse_prometheus",
    "read_run_manifest", "render_chrome_trace", "render_prometheus",
    "reset_tracing", "route_events_to", "set_enabled", "set_tracing",
    "snapshot_json", "span", "spans", "start_span", "tracing_enabled",
    "write_chrome_trace", "write_prometheus_textfile", "write_run_manifest",
]
