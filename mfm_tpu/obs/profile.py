"""Device profiling: measured cost/memory/compile-wall for compiled executables.

bench.py's roofline was hand-modeled (`_riskmodel_stage_models` counts the
flops we *think* each stage does); this module asks XLA what the compiled
program *actually* does.  Three probes, all HOST-SIDE and execution-free:

- :func:`compiled_cost` — ``compiled.cost_analysis()`` flops / bytes
  accessed, normalized across the dict / list-of-dict / None shapes JAX
  returns per backend.
- :func:`compiled_memory_of` — buffer-assignment byte totals, same fields
  as :func:`mfm_tpu.utils.obs.compiled_memory` but off an already-compiled
  executable (one compile serves both probes).
- :func:`capture_compile_walls` — a scoped listener on the same lowering
  event ``watch_compiles`` hooks, collecting per-executable compile wall
  into ``mfm_jit_compile_seconds``.  A warm persistent compile cache can
  legitimately yield ZERO events — callers must treat an empty capture as
  "cached", not "free".

:func:`executable_profile` bundles the three and tags ``source`` so the
roofline records whether its gflop/gbyte figures are measured
("cost_analysis") or fell back to the static model ("static_model") —
the acceptance bar for trusting a BENCH trajectory across JAX versions.
"""

from __future__ import annotations

import contextlib
from typing import Callable

import jax

from mfm_tpu.obs.instrument import JIT_COMPILE_SECONDS


def _normalize_cost(raw) -> dict | None:
    """``cost_analysis()`` returns a dict on new JAX, a list-of-dict on
    older releases, and None on backends without HLO cost modeling; fold
    them all into ``{"flops": float, "bytes_accessed": float}`` or None."""
    if raw is None:
        return None
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
        if raw is None:
            return None
    if not isinstance(raw, dict):
        return None
    flops = raw.get("flops")
    nbytes = raw.get("bytes accessed", raw.get("bytes_accessed"))
    out = {}
    if isinstance(flops, (int, float)) and flops == flops and flops >= 0:
        out["flops"] = float(flops)
    if isinstance(nbytes, (int, float)) and nbytes == nbytes and nbytes >= 0:
        out["bytes_accessed"] = float(nbytes)
    return out or None


def compile_fn(fn: Callable, *args, static_argnames=()):
    """``jax.jit(fn).lower(*args).compile()`` — one compile (or a
    persistent-cache hit) feeding every probe below."""
    return jax.jit(fn, static_argnames=static_argnames).lower(*args).compile()


def compiled_cost(fn: Callable, *args, static_argnames=()) -> dict | None:
    """Measured flops / bytes-accessed of the compiled program, or None
    when the backend's cost analysis is unavailable."""
    compiled = compile_fn(fn, *args, static_argnames=static_argnames)
    return cost_of(compiled)


def cost_of(compiled) -> dict | None:
    """:func:`compiled_cost` off an already-compiled executable."""
    try:
        return _normalize_cost(compiled.cost_analysis())
    except Exception:  # cost modeling is advisory; never fail the caller
        return None


def compiled_memory_of(compiled) -> dict:
    """Buffer-assignment byte totals off an already-compiled executable
    (field-compatible with ``utils.obs.compiled_memory``)."""
    try:
        m = compiled.memory_analysis()
    except Exception:
        return {}
    if m is None:
        return {}
    temp = int(m.temp_size_in_bytes)
    arg = int(m.argument_size_in_bytes)
    out = int(m.output_size_in_bytes)
    alias = int(m.alias_size_in_bytes)
    return {
        "temp_bytes": temp,
        "argument_bytes": arg,
        "output_bytes": out,
        "alias_bytes": alias,
        "generated_code_bytes": int(m.generated_code_size_in_bytes),
        # aliased bytes live in the argument total; don't double-count them
        "peak_bytes": temp + arg + out - alias,
    }


@contextlib.contextmanager
def capture_compile_walls():
    """Scoped compile-wall capture: registers a listener on the same
    lowering event ``watch_compiles`` uses, yields a list that accumulates
    each compile's wall seconds (also observed into
    ``mfm_jit_compile_seconds``), and unregisters on exit.

    An empty list after the block means every executable came from the
    persistent compile cache — record ``compile_wall_s: None``, not 0.
    """
    from jax._src import monitoring

    from mfm_tpu.utils.contracts import _COMPILE_EVENT

    walls: list[float] = []

    def _listener(event: str, duration: float, **kwargs) -> None:
        if event == _COMPILE_EVENT:
            walls.append(float(duration))
            JIT_COMPILE_SECONDS.observe(float(duration))

    monitoring.register_event_duration_secs_listener(_listener)
    try:
        yield walls
    finally:
        unregister = getattr(
            monitoring, "_unregister_event_duration_listener_by_callback",
            None)
        if unregister is not None:
            unregister(_listener)


def executable_profile(fn: Callable, *args, static_argnames=()) -> dict:
    """One compile, every probe: measured cost + memory + compile wall,
    with ``source`` tagging whether the cost figures are measured.

    ``compile_wall_s`` is the summed lowering wall for this call; None
    when the persistent cache served the executable without compiling.
    """
    with capture_compile_walls() as walls:
        compiled = compile_fn(fn, *args, static_argnames=static_argnames)
    cost = cost_of(compiled)
    return {
        "cost": cost,
        "memory": compiled_memory_of(compiled),
        "compile_wall_s": (round(sum(walls), 4) if walls else None),
        "source": ("cost_analysis" if cost else "static_model"),
    }
