"""Per-run manifest: what produced this checkpoint, and was it healthy.

One JSON file (``run_manifest.json``) written atomically NEXT TO the
checkpoint artifact it describes, carrying the run's identity (config
stamp, git describe, backend, argv), a metrics snapshot, the guard verdict
summary and the model-health verdict.  ``mfm-tpu doctor`` audits it against
the checkpoint it sits beside: a manifest whose stamp does not match the
checkpoint's identity means the directory holds artifacts from two
different runs — exactly the mix-up the stamp exists to catch.

The write mirrors ``data/artifacts.py``'s discipline (tmp -> fsync ->
rename -> dir fsync) with its own chaos point
(``run_manifest.after_tmp``), so the fault-injection harness can prove a
SIGKILL mid-manifest-write never leaves a torn manifest or touches the
checkpoint.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from mfm_tpu.utils.chaos import chaos_point

MANIFEST_SCHEMA_VERSION = 1
MANIFEST_NAME = "run_manifest.json"


class ManifestError(RuntimeError):
    """A run manifest exists but is unreadable, schema-incompatible, or
    inconsistent with the checkpoint it sits beside."""


def git_describe(cwd: str | None = None) -> str | None:
    """``git describe --always --dirty`` of the source tree (None outside a
    repo / without git) — the manifest's code-identity field."""
    try:
        out = subprocess.run(
            ["git", "describe", "--always", "--dirty"],
            cwd=cwd or os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.SubprocessError):
        return None
    return out.stdout.strip() or None if out.returncode == 0 else None


def manifest_path_for(checkpoint_path: str) -> str:
    """The manifest slot next to a checkpoint artifact (same directory)."""
    return os.path.join(os.path.dirname(checkpoint_path) or ".",
                        MANIFEST_NAME)


def build_run_manifest(*, stamp_json=None, checkpoint: str | None = None,
                       backend: str | None = None,
                       metrics_snapshot: dict | None = None,
                       guard_summary: dict | None = None,
                       health: dict | None = None,
                       extra: dict | None = None) -> dict:
    """Assemble the manifest dict (pure; :func:`write_run_manifest` persists).

    ``stamp_json`` is the checkpoint identity in its JSON-encoded form (the
    ``{"__tuple__": [...]}`` shape ``data/artifacts.py`` stores), so doctor
    can compare manifest and checkpoint stamps by JSON equality without
    rehydrating tuples.
    """
    return {
        "schema_version": MANIFEST_SCHEMA_VERSION,
        "written_at_unix": round(time.time(), 3),
        "argv": list(sys.argv),
        "git": git_describe(),
        "backend": backend,
        "checkpoint": (os.path.basename(checkpoint) if checkpoint else None),
        "config_stamp": stamp_json,
        "guard": guard_summary or {},
        "health": health or {"status": "unknown", "checks": {}},
        "metrics": metrics_snapshot or {},
        **(extra or {}),
    }


def write_run_manifest(path: str, manifest: dict) -> str:
    """Atomic manifest write (tmp -> fsync -> chaos point -> rename -> dir
    fsync).  ``path`` may be a directory (the checkpoint dir) or a file.
    Returns the final path."""
    if os.path.isdir(path):
        path = os.path.join(path, MANIFEST_NAME)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=1, sort_keys=True, default=str)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    chaos_point("run_manifest.after_tmp", path)
    os.replace(tmp, path)
    try:
        fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - exotic filesystems
        pass
    return path


def read_run_manifest(path: str) -> dict:
    """Load + schema-check a manifest (``path`` may be its directory).

    Raises :class:`ManifestError` on unreadable JSON, a missing/unsupported
    ``schema_version``, or a missing ``health`` field — the three ways a
    manifest stops being auditable.
    """
    if os.path.isdir(path):
        path = os.path.join(path, MANIFEST_NAME)
    try:
        with open(path, encoding="utf-8") as fh:
            m = json.load(fh)
    except OSError as e:
        raise ManifestError(f"{path}: unreadable run manifest ({e})") from e
    except ValueError as e:
        raise ManifestError(f"{path}: run manifest is not valid JSON ({e}) "
                            "— torn write?") from e
    if not isinstance(m, dict):
        raise ManifestError(f"{path}: run manifest is not a JSON object")
    ver = m.get("schema_version")
    if ver != MANIFEST_SCHEMA_VERSION:
        raise ManifestError(
            f"{path}: manifest schema_version {ver!r} unsupported "
            f"(expected {MANIFEST_SCHEMA_VERSION})")
    health = m.get("health")
    if not isinstance(health, dict) or "status" not in health:
        raise ManifestError(f"{path}: manifest has no health verdict")
    return m
