"""The dense masked Panel — the core data abstraction of the framework.

The reference keeps everything in long-form DataFrames (rows = stock-date,
e.g. the master panel built at ``Barra_factor_cal/load_data.py:329-378``) and
loops over ``groupby`` groups.  That shape cannot feed XLA.  Here a panel is a
dict of dense ``(T, N)`` arrays (dates x stocks) where ``NaN`` marks a missing
observation — ragged per-date universes (stocks entering/leaving, cf.
``mfm/MFM.py:65-66``) become masking, never dynamic shapes.

Host-side metadata (date ints, stock ids) stays in NumPy; field arrays are
whatever array type the caller put in (NumPy on host, jax.Array on device).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Mapping

import numpy as np

try:  # pandas is host-side optional sugar; the core never needs it
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None


@dataclasses.dataclass
class Panel:
    """A dense (T, N) panel of named fields with NaN-as-missing semantics.

    Attributes:
      dates:  (T,) np.ndarray of np.datetime64[D] (or int-like), ascending.
      stocks: (N,) np.ndarray of stock identifiers (strings), sorted.
      fields: name -> (T, N) float array; NaN = missing.
      static: name -> (N,) array of per-stock static data (e.g. industry code).
    """

    dates: np.ndarray
    stocks: np.ndarray
    fields: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    static: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)

    @property
    def T(self) -> int:
        return len(self.dates)

    @property
    def N(self) -> int:
        return len(self.stocks)

    def __getitem__(self, name: str) -> np.ndarray:
        return self.fields[name]

    def __setitem__(self, name: str, value) -> None:
        value = np.asarray(value) if not hasattr(value, "shape") else value
        if value.shape != (self.T, self.N):
            raise ValueError(
                f"field {name!r} has shape {value.shape}, want {(self.T, self.N)}"
            )
        self.fields[name] = value

    def __contains__(self, name: str) -> bool:
        return name in self.fields

    def mask(self, *names: str) -> np.ndarray:
        """Joint validity mask across the given fields (all finite)."""
        if not names:
            names = tuple(self.fields)
        m = np.ones((self.T, self.N), dtype=bool)
        for n in names:
            m &= np.isfinite(np.asarray(self.fields[n], dtype=np.float64))
        return m

    # ------------------------------------------------------------------
    # long <-> dense conversion
    # ------------------------------------------------------------------

    @classmethod
    def from_long(
        cls,
        df,
        *,
        date_col: str = "trade_date",
        stock_col: str = "ts_code",
        value_cols: Iterable[str] | None = None,
        dtype=np.float64,
    ) -> "Panel":
        """Pivot a long (stock-date rows) DataFrame into a dense Panel.

        Duplicated (date, stock) pairs keep the last occurrence, matching the
        reference's dedup-keep-latest convention (``load_data.py:269-296``).
        """
        if pd is None:  # pragma: no cover
            raise ImportError("pandas required for from_long")
        dates = np.sort(df[date_col].unique())
        stocks = np.sort(df[stock_col].unique())
        t_idx = {d: i for i, d in enumerate(dates)}
        s_idx = {s: j for j, s in enumerate(stocks)}
        ti = df[date_col].map(t_idx).to_numpy()
        si = df[stock_col].map(s_idx).to_numpy()
        if value_cols is None:
            value_cols = [c for c in df.columns if c not in (date_col, stock_col)]
        fields: Dict[str, np.ndarray] = {}
        for c in value_cols:
            arr = np.full((len(dates), len(stocks)), np.nan, dtype=dtype)
            vals = pd.to_numeric(df[c], errors="coerce").to_numpy(dtype=dtype)
            arr[ti, si] = vals  # later rows overwrite earlier ones
            fields[c] = arr
        return cls(dates=np.asarray(dates), stocks=np.asarray(stocks), fields=fields)

    def to_long(self, *names: str, dropna: bool = True):
        """Flatten back to a long DataFrame with one row per valid stock-date."""
        if pd is None:  # pragma: no cover
            raise ImportError("pandas required for to_long")
        names = names or tuple(self.fields)
        T, N = self.T, self.N
        out = {
            "trade_date": np.repeat(self.dates, N),
            "ts_code": np.tile(self.stocks, T),
        }
        for n in names:
            out[n] = np.asarray(self.fields[n]).reshape(-1)
        df = pd.DataFrame(out)
        if dropna:
            df = df.dropna(how="all", subset=list(names)).reset_index(drop=True)
        return df

    def select(self, names: Iterable[str]) -> "Panel":
        return Panel(
            dates=self.dates,
            stocks=self.stocks,
            fields={n: self.fields[n] for n in names},
            static=dict(self.static),
        )


def pct_change(close: np.ndarray) -> np.ndarray:
    """Per-stock simple returns along the date axis of a (T, N) close panel.

    Matches ``groupby('ts_code')['close'].pct_change()``
    (``factor_calculator.py:50``): NaN closes propagate — pandas pct_change
    computes close[t]/close[t-1] - 1 against the *previous row* (not the
    previous valid observation) with default fill_method=None semantics of
    recent pandas.
    """
    close = np.asarray(close, dtype=np.float64)
    out = np.full_like(close, np.nan)
    out[1:] = close[1:] / close[:-1] - 1.0
    return out


def log_return(close: np.ndarray) -> np.ndarray:
    """log(close_t) - log(close_{t-1}) per stock (``factor_calculator.py:51``)."""
    close = np.asarray(close, dtype=np.float64)
    out = np.full_like(close, np.nan)
    with np.errstate(divide="ignore", invalid="ignore"):
        lc = np.log(close)
    out[1:] = lc[1:] - lc[:-1]
    return out
