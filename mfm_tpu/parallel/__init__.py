"""Device-mesh construction and sharding specs for the pipeline.

The reference has no parallelism at all (SURVEY.md §2.4) — its serial axes
(dates for cross-sections/eigen-MC, stocks for rolling windows) are exactly
the axes this package shards over the TPU mesh.
"""

from mfm_tpu.parallel.mesh import (
    make_mesh,
    panel_sharding,
    replicated,
    shard_panel,
    PIPELINE_SPECS,
)

__all__ = [
    "make_mesh",
    "panel_sharding",
    "replicated",
    "shard_panel",
    "PIPELINE_SPECS",
]
