"""Mesh + sharding layout for the risk pipeline.

Layout doctrine (SURVEY.md §2.4 / §7):

- mesh axes ``('date', 'stock')``;
- (T, N) panels shard as ``P('date', 'stock')``;
- the cross-sectional regression vmaps over dates (embarrassingly parallel
  along 'date') while its stock-axis reductions (normal equations
  ``X' W X``, per-industry cap sums, masked means/stds) contract the 'stock'
  axis — XLA inserts psums over ICI automatically;
- factor-return series and KxK covariances are tiny: replicated;
- rolling kernels are parallel along 'stock' and windowed along time, so
  their natural layout is ``P(None, ('date', 'stock'))`` — the whole mesh
  shards the stock axis and the time axis stays local (windows never cross
  devices).  ``shard_panel(..., rolling=True)`` gives that layout.

Everything here is classic auto-sharding (jit + NamedSharding constraints);
no manual collectives are needed anywhere in the pipeline.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def use_mesh(mesh: Mesh):
    """Ambient-mesh context across JAX versions.

    ``jax.set_mesh`` (new API) when available, ``jax.sharding.use_mesh``
    (transitional) otherwise, falling back to entering the ``Mesh`` itself —
    the legacy context manager that sets the same ambient mesh for
    ``NamedSharding``/shard_map resolution.
    """
    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    um = getattr(jax.sharding, "use_mesh", None)
    if um is not None:
        return um(mesh)
    return mesh


def _ambient_mesh() -> Mesh | None:
    """The mesh set by ``use_mesh`` (or a legacy ``with mesh:``), if any."""
    try:
        from jax._src import mesh as _mesh_lib

        m = _mesh_lib.thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:
        return None


def replicate_under_mesh(x):
    """Constrain a pytree of small arrays to the ambient mesh's replicated
    layout; no-op when no mesh is active.

    The expanding scans (Newey-West, vol-regime) stack tiny (K,)/(K, K)
    per-date outputs; letting GSPMD shard that stacking axis buys nothing —
    the layout doctrine replicates tiny per-date series — and trips an XLA
    partitioner bug under x64 (the scan counter lowers as s64 while the
    shard-offset math in the rewritten dynamic_update_slice stays s32, which
    the HLO verifier rejects after spmd-partitioning).
    """
    m = _ambient_mesh()
    if m is None:
        return x
    s = NamedSharding(m, P())
    return jax.tree_util.tree_map(
        lambda a: jax.lax.with_sharding_constraint(a, s), x)


def constrain_cross_section(*panels):
    """Pin (T, N, ...) panels to the date-parallel, stock-LOCAL layout
    ``P('date', None, ...)``; no-op when no mesh is ambient.

    This is the bitwise doctrine: a reduction whose axis is sharded becomes
    partial-sums + a psum, which reorders the floating-point accumulation
    (~1e-7 drift on the WLS normal equations, measured).  Gathering the
    stock axis once at stage entry keeps every cross-sectional reduction
    (``X' W X``, per-industry cap sums, masked means/stds, guard coverage
    counts) device-local and in the unsharded summation order — sharded
    runs then match single-device runs bit for bit, while the date axis
    still spreads the embarrassingly-parallel per-date work over the mesh.
    The stock axis remains a *storage/ingest* axis (shard-local panel
    construction); XLA inserts the one all-gather per panel.
    """
    m = _ambient_mesh()
    if m is None or "date" not in m.axis_names:
        return panels
    out = []
    for x in panels:
        if x is None:
            out.append(None)
            continue
        spec = P("date", *([None] * (x.ndim - 1)))
        out.append(jax.lax.with_sharding_constraint(x, NamedSharding(m, spec)))
    return tuple(out)


def make_mesh(
    n_date: int | None = None,
    n_stock: int = 1,
    devices: Sequence | None = None,
) -> Mesh:
    """Build a ('date', 'stock') mesh over the available devices.

    Default: all devices on the 'date' axis (the cross-sectional stage is the
    dominant cost and is embarrassingly parallel over dates).
    """
    devs = np.array(devices if devices is not None else jax.devices())
    if n_date is None:
        n_date = devs.size // n_stock
    return Mesh(devs.reshape(n_date, n_stock), ("date", "stock"))


def panel_sharding(mesh: Mesh, *, rolling: bool = False) -> NamedSharding:
    """Sharding for a (T, N, ...) panel.

    cross-sectional layout: date axis over 'date', stock axis over 'stock'.
    rolling layout: time axis local, stock axis over the *whole* mesh.
    """
    if rolling:
        return NamedSharding(mesh, P(None, ("date", "stock")))
    return NamedSharding(mesh, P("date", "stock"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_mesh(x, mesh: Mesh, *, fill=0, rolling: bool = False):
    """Pad a (T, N, ...) array's sharded axes up to mesh-divisible sizes.

    ``jax.device_put`` requires each sharded dimension's global size to be
    divisible by its mesh axis; real panels rarely oblige (CSI300's
    T=1,390 divides neither 4 nor 8).  The framework's masked design makes
    padding inert: pad ``valid``/observed masks with False and data with
    ``fill`` — 0 for risk-stage arrays (their reductions multiply by the
    mask) or NaN for FactorEngine fields (NaN already means missing/never
    listed).  Time padding appends AFTER the last date, so every causal
    stage (expanding/trailing windows, the NW and vol-regime scans) leaves
    real-date outputs unchanged; crop outputs back with ``[:T]`` /
    ``[:, :N]``.  Bool arrays always pad False regardless of ``fill``.
    """
    n_date, n_stock = mesh.shape["date"], mesh.shape["stock"]
    if rolling:
        pads = {1: n_date * n_stock} if x.ndim > 1 else {}
    else:
        pads = {0: n_date}
        if x.ndim > 1:
            pads[1] = n_stock
    widths = [(0, 0)] * x.ndim
    for ax, div in pads.items():
        widths[ax] = (0, (-x.shape[ax]) % div)
    if not any(w[1] for w in widths):
        return x
    import jax.numpy as jnp

    v = False if x.dtype == bool else fill
    return jnp.pad(x, widths, constant_values=v)


def shard_panel(x, mesh: Mesh, *, rolling: bool = False):
    """device_put a (T, N, ...) array (or pytree of them) onto the mesh."""
    s = panel_sharding(mesh, rolling=rolling)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, s), x)


# canonical in_shardings for the risk-model stages, keyed by argument name
PIPELINE_SPECS = {
    "ret": P("date", "stock"),
    "cap": P("date", "stock"),
    "styles": P("date", "stock", None),
    "industry": P("date", "stock"),
    "valid": P("date", "stock"),
    "factor_ret": P("date", None),
    "covs": P("date", None, None),
    "sim_covs": P(),
}
