"""Multi-host (multi-slice) execution helpers.

The reference has no distributed backend at all (SURVEY.md §2.4) — its
"communication" is MongoDB reads/writes.  Here the communication backend is
XLA collectives: within a slice they ride ICI; across slices (DCN) only the
date axis should be partitioned, because every cross-date dependency in the
pipeline is either embarrassingly parallel (regression, eigen adjustment) or
a tiny KxK scan (Newey-West, vol regime) that runs replicated.

Topology doctrine for an (n_hosts x chips) fleet:

  mesh axes     ('date', 'stock')
  date axis     outer, spans hosts (DCN-friendly: no collectives cross it in
                the regression/eigen stages; only the final gather of KxK
                covariances does)
  stock axis    inner, within a slice (the normal-equation psums and
                cross-sectional reductions stay on ICI)

Usage on each host of a jax.distributed job:

    from mfm_tpu.parallel.distributed import initialize, make_global_mesh
    initialize()                       # reads env (coordinator, process id)
    mesh = make_global_mesh(n_stock=4)  # global devices, date x stock
"""

from __future__ import annotations

import os

import numpy as np

import jax
from jax.sharding import Mesh


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """Initialize jax.distributed from args or environment; True if multi-host.

    No-ops (returns False) when running single-process with no coordinator
    configured, so code paths can be shared between laptop and fleet.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "MFM_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None and num_processes is None:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def make_global_mesh(n_stock: int = 1) -> Mesh:
    """('date', 'stock') mesh over ALL global devices.

    The stock axis is kept within a host's devices (ICI) by construction:
    global device order enumerates each process's local devices contiguously,
    and n_stock must divide the local device count.
    """
    devs = np.array(jax.devices())
    if devs.size % n_stock:
        raise ValueError(f"{n_stock=} must divide device count {devs.size}")
    local = jax.local_device_count()
    if n_stock > local:
        raise ValueError(
            f"stock axis ({n_stock}) must fit within one host's {local} "
            "devices so its collectives stay on ICI"
        )
    return Mesh(devs.reshape(devs.size // n_stock, n_stock), ("date", "stock"))


def process_date_slice(T: int) -> slice:
    """The date range this host should load (data parallel ingestion):
    contiguous block partition of [0, T) over processes."""
    p = jax.process_index()
    n = jax.process_count()
    chunk = -(-T // n)
    return slice(p * chunk, min(T, (p + 1) * chunk))
