"""Benchmark: the north-star workload (BASELINE.json config 1) — full Barra
risk-model pipeline (per-date constrained WLS + Newey-West + eigenfactor
adjustment + vol-regime adjustment) on a CSI300-shaped panel
(T=1390 dates x N=300 stocks, K = 1 + 31 + 10 factors).

Prints ONE JSON line:
  {"metric": ..., "value": <TPU end-to-end seconds>, "unit": "s",
   "vs_baseline": <CPU-reference-time / TPU-time>}

The reference publishes no numbers (BASELINE.md), so the baseline is measured
here: the golden NumPy implementation of the identical math (same serial
per-date loops the reference runs, minus statsmodels overhead — a *favorable*
proxy for the reference) timed on subsamples of each stage and extrapolated
linearly in T.  vs_baseline > 1 means the TPU pipeline is faster end-to-end.
"""

import json
import time

import numpy as np


def _tpu_time():
    import jax
    import jax.numpy as jnp

    from mfm_tpu.config import RiskModelConfig
    from mfm_tpu.models.eigen import simulated_eigen_covs
    from mfm_tpu.models.risk_model import RiskModel
    from __graft_entry__ import _synthetic_risk_inputs

    T, N, P, Q = 1390, 300, 31, 10
    K = 1 + P + Q
    M = 100
    args = _synthetic_risk_inputs(T, N, P, Q, dtype=jnp.float32, seed=0)
    cfg = RiskModelConfig(eigen_n_sims=M, eigen_sim_length=T)
    sim_covs = simulated_eigen_covs(jax.random.key(0), K, T, M, jnp.float32)

    @jax.jit
    def step(ret, cap, styles, industry, valid, sim_covs):
        rm = RiskModel(ret, cap, styles, industry, valid,
                       n_industries=P, config=cfg)
        out = rm.run(sim_covs=sim_covs)
        # reduce outputs to one scalar: on this TPU tunnel block_until_ready
        # does not actually block, so timing must force a (tiny) host
        # transfer without paying multi-MB transfer costs
        checksum = (
            jnp.sum(out.factor_ret)
            + jnp.sum(out.r2)
            + jnp.sum(jnp.where(jnp.isfinite(out.vr_cov), out.vr_cov, 0.0))
            + jnp.sum(out.lamb)
        )
        return checksum

    float(np.asarray(step(*args, sim_covs)))  # compile + warmup
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(np.asarray(step(*args, sim_covs)))
        times.append(time.perf_counter() - t0)
    return min(times), (T, N, P, Q, K, M), args


def _cpu_baseline(shape, args):
    """Golden NumPy serial loops (the reference's structure) on subsamples,
    extrapolated to full T."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))
    from golden import golden_cross_section, golden_newey_west, golden_eigen_adj

    T, N, P, Q, K, M = shape
    ret, cap, styles, industry, valid = (np.asarray(a, np.float64) for a in args)
    industry = industry.astype(int)

    # stage 1: per-date WLS — time n1 dates, scale by T
    n1 = 40
    t0 = time.perf_counter()
    for t in range(n1):
        v = valid[t].astype(bool)
        ind_oh = np.eye(P)[industry[t][v]]
        golden_cross_section(ret[t][v], cap[t][v], styles[t][v], ind_oh)
    reg_s = (time.perf_counter() - t0) / n1 * T

    f = 0.01 * np.random.default_rng(0).standard_normal((T, K))
    # stage 2: expanding NW — time windows at stride, integrate over T
    sample_ts = list(range(K + 2, T, 100))
    t0 = time.perf_counter()
    for t in sample_ts:
        golden_newey_west(f[:t], 2, 252.0)
    per_window = (time.perf_counter() - t0) / len(sample_ts)  # at avg t ~ T/2
    nw_s = per_window * T

    # stage 3: eigen MC — time n3 dates with the full M sims, scale by T
    cov = golden_newey_west(f, 2, 252.0)
    draws = np.random.default_rng(1).standard_normal((M, K, T))
    n3 = 3
    t0 = time.perf_counter()
    for _ in range(n3):
        golden_eigen_adj(cov, draws, 1.4)
    eig_s = (time.perf_counter() - t0) / n3 * T

    # stage 4 (vol regime) is negligible next to 1-3; ignore (favors baseline)
    return reg_s + nw_s + eig_s


def main():
    tpu_s, shape, args = _tpu_time()
    T, N, P, Q, K, M = shape
    cpu_s = _cpu_baseline((T, N, P, Q, K, M), args)
    print(json.dumps({
        "metric": "csi300_riskmodel_e2e_wall",
        "value": round(tpu_s, 4),
        "unit": "s",
        "vs_baseline": round(cpu_s / tpu_s, 2),
    }))


if __name__ == "__main__":
    main()
