"""Benchmarks for the BASELINE.json configs.  Prints ONE JSON line.

Default (what the driver records): config 1, the north-star workload — full
Barra risk-model pipeline (per-date constrained WLS + Newey-West +
eigenfactor adjustment + vol-regime adjustment) on a CSI300-shaped panel
(T=1390 dates x N=300 stocks, K = 1 + 31 + 10 factors, M=100 sims).

  python bench.py                 # config 1 (the recorded metric)
  python bench.py --config beta   # config 2: rolling 252d BETA+HSIGMA, CSI300
  python bench.py --config factors# config 3: full style-factor calc + post
  python bench.py --config alla   # config 4: all-A full pipeline + risk stack
  python bench.py --config alpha  # config 5: 1000 alpha expressions, CSI300 panel
  python bench.py --config query  # config 6: batched portfolio-query service
  python bench.py --config fleet  # config 9: coalescing front end vs 1-at-a-time
  python bench.py --config fleet_mh # config 12: 2-host TCP fleet + kill drill

The reference publishes no numbers (BASELINE.md), so the config-1 baseline is
measured here: the golden NumPy implementation of the identical math (same
serial per-date loops the reference runs, minus statsmodels overhead — a
*favorable* proxy for the reference) timed on subsamples of each stage and
extrapolated linearly in T.  vs_baseline > 1 means the TPU run is faster.

NOTE: on this TPU tunnel ``block_until_ready`` does not actually block, so
every timing forces a scalar host transfer of a checksum.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))


def _force(x):
    return float(np.asarray(x))


def _time3(fn, *args):
    _force(fn(*args))  # compile + warmup
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        _force(fn(*args))
        times.append(time.perf_counter() - t0)
    return min(times)


#: public per-chip peaks: (MXU bf16 TFLOP/s, HBM GB/s).  The VPU peak is
#: not published per chip; the scaling-book estimate is ~1/25 of the MXU
#: bf16 number (8x128 lanes x 4 ALUs x FMA at ~0.94 GHz ~ 7.9 TFLOP/s on
#: v5e), which is what the VPU-bound stages are held to below.
_CHIP_PEAKS = {
    "TPU v4": (275.0, 1228.0),
    "TPU v5e": (197.0, 819.0),
    "TPU v5 lite": (197.0, 819.0),
    "TPU v5p": (459.0, 2765.0),
    "TPU v6e": (918.0, 1640.0),
    "TPU v6 lite": (918.0, 1640.0),
}


def _riskmodel_stage_models(T, N, P, Q, K, M, sweeps):
    """Analytic FLOP + HBM-byte model per risk stage at f32 (the roofline
    denominator: what the math REQUIRES, not what XLA emits).

    regression — per date: masked normal equations X'WX / X'Wy (2NK^2 MXU
    FLOPs), one K x K eigh-based pinv (~10K^3), constraint matmuls.
    newey_west — EWMA scan: (2q+1) rank-1 K x K updates + normalization.
    eigen — the dominant stage: T*M Jacobi eighs of K x K (weighted kernel:
    ~5K^3 per sweep covering A-rotations + the fused weighted-V reduction)
    plus the F0 decomposition and bias pairing (~2K^3 per date).  All
    rotations are vector ops — VPU, not MXU.
    vol_regime — elementwise (T, K, K) scaling: pure bandwidth.
    """
    f32 = 4
    return {
        "regression": {
            "gflop": T * (2 * N * K * K + 2 * N * K + 10 * K**3) / 1e9,
            "gbyte": (T * N * (Q + 4 + K) + T * (K * K + K)) * f32 / 1e9,
            "bound": "mxu",
        },
        "newey_west": {
            "gflop": T * (2 * 2 + 1 + 4) * 2 * K * K / 1e9,
            "gbyte": T * K * K * 2 * f32 / 1e9,
            "bound": "serial-scan (latency, not throughput)",
        },
        "eigen": {
            "gflop": (T * M * sweeps * 5 * K**3 + T * 2 * K**3) / 1e9,
            "gbyte": T * M * K * K * 2 * f32 / 1e9,
            "bound": "vpu",
        },
        "vol_regime": {
            "gflop": T * 6 * K * K / 1e9,
            "gbyte": T * K * K * 3 * f32 / 1e9,
            "bound": "hbm",
        },
    }


def _roofline(stage_seconds, models, measured=None):
    """Achieved GFLOP/s / GB/s per stage + fraction of the detected chip's
    peak for the stage's binding resource.  CPU or unknown chips report the
    achieved numbers with null fractions (no published peak to hold to).

    ``measured`` maps stage -> ``obs.profile.compiled_cost`` output; when a
    stage has measured flops/bytes those drive the achieved numbers
    (``source: cost_analysis``) and the hand model is kept alongside as
    ``static_*`` for drift inspection; otherwise the stage falls back to
    the analytic model (``source: static_model``)."""
    import jax

    d = jax.devices()[0]
    kind = getattr(d, "device_kind", d.platform)
    mxu_tflops, hbm_gbps = _CHIP_PEAKS.get(kind, (None, None))
    vpu_tflops = mxu_tflops / 25.0 if mxu_tflops else None
    out = {"device_kind": kind,
           "peaks": {"mxu_bf16_tflops": mxu_tflops,
                     "vpu_f32_tflops_est": vpu_tflops,
                     "hbm_gbps": hbm_gbps}}
    for name, s in stage_seconds.items():
        m = models[name]
        cost = (measured or {}).get(name) or {}
        if "flops" in cost and "bytes_accessed" in cost:
            gflop = cost["flops"] / 1e9
            gbyte = cost["bytes_accessed"] / 1e9
            source = "cost_analysis"
        else:
            gflop, gbyte, source = m["gflop"], m["gbyte"], "static_model"
        gflops = gflop / s
        gbps = gbyte / s
        rec = {"model_gflop": round(gflop, 2),
               "model_gbyte": round(gbyte, 3),
               "source": source,
               "static_gflop": round(m["gflop"], 2),
               "static_gbyte": round(m["gbyte"], 3),
               "achieved_gflops": round(gflops, 1),
               "achieved_gbps": round(gbps, 2),
               "bound": m["bound"], "frac_of_peak": None,
               "frac_of_hbm": None}
        if hbm_gbps:
            rec["frac_of_hbm"] = round(gbps / hbm_gbps, 4)
            peak = {"mxu": mxu_tflops, "vpu": vpu_tflops}.get(
                m["bound"], None)
            if peak:
                rec["frac_of_peak"] = round(gflops / (peak * 1e3), 4)
            elif m["bound"] == "hbm":
                rec["frac_of_peak"] = rec["frac_of_hbm"]
        out[name] = rec
    return out


def _smoke_t():
    """Optional history-length bound for --universe smoke runs.  The full
    alla history (T=2500) at N=5000 is a multi-minute single-core run; CI
    smokes set BENCH_SMOKE_T to bound it.  The override is baked into the
    universe NAME (resolve_universe), so a bounded record can never
    masquerade as the full-length workload."""
    raw = os.environ.get("BENCH_SMOKE_T", "")
    try:
        return max(8, int(raw))
    except ValueError:
        return None


def bench_riskmodel(universe="csi300", devices=None):
    import jax
    import jax.numpy as jnp
    from mfm_tpu.config import RiskModelConfig
    from mfm_tpu.data.synthetic import resolve_universe
    from mfm_tpu.models.eigen import simulated_eigen_covs
    from mfm_tpu.models.risk_model import RiskModel
    from __graft_entry__ import _synthetic_risk_inputs

    u = resolve_universe(universe, T=_smoke_t())
    if u.name != "csi300" or (devices or 1) > 1:
        # any non-flagship shape (or a mesh) takes the scaling path: fused
        # e2e + eigen stage under the ('date','stock') mesh, no full
        # observability battery — the record feeds the N x devices curve
        return _bench_riskmodel_universe(u, devices or 1)
    T, N, P, Q = u.T, u.N, u.P, u.Q
    K = 1 + P + Q
    M = 100
    args = _synthetic_risk_inputs(T, N, P, Q, dtype=jnp.float32, seed=0)
    cfg = RiskModelConfig(eigen_n_sims=M, eigen_sim_length=T)
    sim_covs = simulated_eigen_covs(jax.random.key(0), K, T, M, jnp.float32)

    def _checksum(out):
        return (jnp.sum(out.factor_ret) + jnp.sum(out.r2)
                + jnp.sum(jnp.where(jnp.isfinite(out.vr_cov), out.vr_cov, 0.0))
                + jnp.sum(out.lamb))

    def fused_step():
        # the production e2e path: all four stages as ONE jitted program
        # with donated panel inputs (RiskModel.run_fused).  Fresh device
        # copies per call — donation invalidates the operand buffers on
        # donation-capable backends, and the copies are timed because a
        # real caller pays them too (~25 MB, microseconds next to the run).
        # sim_length declares the draw count behind sim_covs, engaging the
        # PRODUCTION eigen path (auto sweep cap — the path tools/
        # tpu_parity.py gates); omitting it silently benchmarks the
        # conservative full-sweep fallback instead
        fresh = [jnp.array(a, copy=True) for a in args]
        rm = RiskModel(*fresh, n_industries=P, config=cfg)
        return _checksum(rm.run_fused(sim_covs=sim_covs, sim_length=T))

    tpu_s = _time3(fused_step)

    # the daily-serving path: resumable state over the first T-1 dates, then
    # ONE donated update step appending the last date — what a production
    # deployment pays per new date instead of the full-rebuild e2e above.
    # Each timed call copies the state + slab first (update donates both;
    # a real serving loop donates the old state and keeps the returned one,
    # so the copies are overhead the metric charges itself, not the user).
    def _prefix(a):
        return jnp.array(a[:-1], copy=True)

    rm_hist = RiskModel(*[_prefix(a) for a in args], n_industries=P,
                        config=cfg)
    _, state0 = rm_hist.init_state(sim_covs=jnp.array(sim_covs, copy=True),
                                   sim_length=T)

    def update_step():
        st = jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True),
                                    state0)
        fresh = [jnp.array(a[-1:], copy=True) for a in args]
        m = RiskModel(*fresh, n_industries=P, config=cfg)
        out, _ = m.update(st)
        return _checksum(out)

    upd_s = _time3(update_step)

    # the incremental-eigen serving path (config.eigen_incremental=True):
    # the same single-date append at FULL eigen fidelity — the appended
    # date's Monte-Carlo bias is computed from the frozen draw stream and
    # the carried prefix moments instead of freezing sim covariances, so
    # the eigen work per served date is O(M) eighs, not O(T*M)
    import dataclasses as _dci
    icfg = _dci.replace(cfg, eigen_sim_length=None, eigen_incremental=True)
    rm_inc = RiskModel(*[_prefix(a) for a in args], n_industries=P,
                       config=icfg)
    _, istate0 = rm_inc.init_state()

    def eigen_update_step():
        st = jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True),
                                    istate0)
        fresh = [jnp.array(a[-1:], copy=True) for a in args]
        m = RiskModel(*fresh, n_industries=P, config=icfg)
        out, _ = m.update(st)
        return _checksum(out)

    eig_upd_s = _time3(eigen_update_step)

    # the PRODUCTION serving path is guarded (input guards + degraded-mode
    # quarantine, serve/guard.py): same single-date append through
    # update_guarded, so the overhead of health-checking every slab is a
    # recorded number, not an assumption.  The synthetic panel is clean, so
    # the observed quarantine_rate doubles as the guards-are-free evidence.
    import dataclasses as _dcg
    from mfm_tpu.config import QuarantinePolicy
    from mfm_tpu.obs import instrument as _telemetry
    from mfm_tpu.obs.metrics import REGISTRY
    gcfg = _dcg.replace(cfg, quarantine=QuarantinePolicy(enabled=True))
    rm_gh = RiskModel(*[_prefix(a) for a in args], n_industries=P, config=gcfg)
    _, gstate0 = rm_gh.init_state(sim_covs=jnp.array(sim_covs, copy=True),
                                  sim_length=T)

    last_report = []

    def guarded_update_step():
        st = jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True),
                                    gstate0)
        fresh = [jnp.array(a[-1:], copy=True) for a in args]
        m = RiskModel(*fresh, n_industries=P, config=gcfg)
        out, rep, _ = m.update_guarded(st)
        # exactly what the production loop records per served date
        _telemetry.record_guard_report(rep)
        last_report[:] = [rep]
        return _checksum(out) + jnp.sum(rep.staleness)

    # production latency WITH telemetry (the serving loop's configuration)
    gupd_s = _time3(guarded_update_step)
    _telemetry.record_update_latency(gupd_s)
    # the telemetry overhead claim (docs/OBSERVABILITY.md: <= 1% of the
    # guarded update) is measured, not asserted — and measured DIRECTLY:
    # the per-date recording (guard-report tallies + latency observe) timed
    # alone on the already-materialized report, as a fraction of the step.
    # Differencing two ~ms jit walls would bury a ~30 us cost in scheduler
    # noise; timing the host-only recording isolates it exactly.
    rep0 = last_report[0]
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        _telemetry.record_guard_report(rep0)
        _telemetry.record_update_latency(gupd_s)
    telemetry_s = (time.perf_counter() - t0) / reps
    telemetry_overhead = telemetry_s / gupd_s
    # the tracing overhead claim (docs/OBSERVABILITY.md: <= 1%) gets the
    # same treatment: one request-span open/close per served date — exactly
    # what the serving loop adds per request (obs/trace.py) — timed alone
    from mfm_tpu.obs import trace as _trace
    _trace.reset_tracing()
    t0 = time.perf_counter()
    for i in range(reps):
        with _trace.span("bench.request", batch=i):
            pass
    tracing_s = (time.perf_counter() - t0) / reps
    tracing_overhead = tracing_s / gupd_s
    _trace.reset_tracing()
    gsum = _telemetry.guard_summary_from_registry()
    quarantine_rate = (gsum["quarantine_rate"] if gsum["served_dates"]
                       else None)

    # per-stage split (VERDICT r3 weak #4): each stage jitted alone with its
    # real inputs passed as jit ARGUMENTS (closed-over arrays would embed as
    # constants and invite compile-time folding), so drift in any one stage
    # is attributable
    def _sum_finite(*xs):
        return sum(jnp.sum(jnp.where(jnp.isfinite(x), x, 0.0)) for x in xs)

    def mk(stage):
        @jax.jit
        def f(ret, cap, styles, industry, valid, *extra):
            rm = RiskModel(ret, cap, styles, industry, valid,
                           n_industries=P, config=cfg)
            return _sum_finite(*stage(rm, *extra))
        return f

    rm = RiskModel(*args, n_industries=P, config=cfg)  # eager intermediates
    factor_ret = rm.reg_by_time()[0]
    nw_cov, nw_valid = rm.newey_west_by_time(factor_ret)
    eigen_cov, eigen_valid = rm.eigen_risk_adj_by_time(
        nw_cov, nw_valid, sim_covs=sim_covs, sim_length=T)

    reg_f = mk(lambda m: m.reg_by_time()[:2])
    nw_f = mk(lambda m, f: m.newey_west_by_time(f))
    eig_f = mk(lambda m, c, v, s: m.eigen_risk_adj_by_time(
        c, v, sim_covs=s, sim_length=T))
    vr_f = mk(lambda m, f, c, v: m.vol_regime_adj_by_time(f, c, v))
    reg_s = _time3(reg_f, *args)
    nw_s = _time3(nw_f, *args, factor_ret)
    eig_s = _time3(eig_f, *args, nw_cov, nw_valid, sim_covs)
    vr_s = _time3(vr_f, *args, factor_ret, eigen_cov, eigen_valid)

    # peak-memory observability (utils/obs.py::compiled_memory): XLA's
    # buffer-assignment totals per stage.  ``temp_bytes`` is the transient
    # high-water mark the chunked eigen stream exists to bound — the
    # unchunked eigen stage is re-lowered with eigen_chunk=None purely to
    # measure what the stream saves (the config default is "auto").
    import dataclasses as _dc

    from mfm_tpu.models.eigen import auto_eigen_chunk
    from mfm_tpu.utils.obs import compiled_memory

    def eigen_fn(chunk):
        cfgc = _dc.replace(cfg, eigen_chunk=chunk)

        def f(ret, cap, styles, industry, valid, c, v, s):
            rm = RiskModel(ret, cap, styles, industry, valid,
                           n_industries=P, config=cfgc)
            return _sum_finite(*rm.eigen_risk_adj_by_time(
                c, v, sim_covs=s, sim_length=T))
        return f

    stage_mem = {
        "regression": compiled_memory(reg_f, *args),
        "newey_west": compiled_memory(nw_f, *args, factor_ret),
        "eigen": compiled_memory(eig_f, *args, nw_cov, nw_valid, sim_covs),
        "vol_regime": compiled_memory(
            vr_f, *args, factor_ret, eigen_cov, eigen_valid),
        "eigen_unchunked": compiled_memory(
            eigen_fn(None), *args, nw_cov, nw_valid, sim_covs),
    }
    # device-memory watermarks flow through the registry
    # (mfm_compiled_bytes{stage,kind}) and the JSON record below reads the
    # gauges back — one source of truth for bench output and a scrape
    for k, v in stage_mem.items():
        _telemetry.record_compiled_memory(k, v)
    scal = REGISTRY.scalar_values()

    def _mem_bytes(stage, kind):
        v = scal.get(f"mfm_compiled_bytes{{stage={stage},kind={kind}}}")
        return None if v is None else int(v)

    auto_chunk = auto_eigen_chunk(T, M, K, itemsize=4)
    stages4 = ("regression", "newey_west", "eigen", "vol_regime")
    mem_rec = {
        "stages_temp_bytes": {k: _mem_bytes(k, "temp_bytes")
                              for k in stages4},
        "stages_peak_bytes": {k: _mem_bytes(k, "peak_bytes")
                              for k in stages4},
        "eigen_auto_chunk": auto_chunk,
        "eigen_unchunked_temp_bytes": _mem_bytes("eigen_unchunked",
                                                 "temp_bytes"),
        "eigen_auto_temp_bytes": _mem_bytes("eigen", "temp_bytes"),
    }
    if mem_rec["eigen_unchunked_temp_bytes"] and \
            mem_rec["eigen_auto_temp_bytes"]:
        mem_rec["eigen_temp_reduction"] = round(
            mem_rec["eigen_unchunked_temp_bytes"]
            / mem_rec["eigen_auto_temp_bytes"], 1)

    prof_dir = os.environ.get("BENCH_PROFILE_DIR")
    if prof_dir:
        # one traced execution of the already-compiled e2e step: the
        # committed profiler artifact for roofline inspection (xprof /
        # tensorboard reads the dir)
        with jax.profiler.trace(prof_dir):
            _force(fused_step())

    from mfm_tpu.models.eigen import sim_sweeps_for
    # every wall number lands in the registry first and the JSON record is
    # assembled from the registry's flat view — bench output and a metrics
    # scrape can never disagree
    for name, s in (("fused_e2e", tpu_s), ("daily_update", upd_s),
                    ("eigen_update", eig_upd_s),
                    ("guarded_update", gupd_s), ("regression", reg_s),
                    ("newey_west", nw_s), ("eigen", eig_s),
                    ("vol_regime", vr_s)):
        _telemetry.record_stage_seconds(name, s)
    scal = REGISTRY.scalar_values()

    def _stage_s(name):
        return scal[f"mfm_stage_seconds{{stage={name}}}"]

    stage_s = {k: _stage_s(k) for k in stages4}
    models = _riskmodel_stage_models(
        T, N, P, Q, K, M, sweeps=sim_sweeps_for(K, jnp.float32, T))

    # measured roofline numerators (obs/profile.py): what XLA says each
    # compiled stage actually does, replacing the hand-counted model where
    # the backend exposes cost analysis (per-stage static fallback otherwise)
    from mfm_tpu.obs.profile import compiled_cost
    measured_cost = {
        "regression": compiled_cost(reg_f, *args),
        "newey_west": compiled_cost(nw_f, *args, factor_ret),
        "eigen": compiled_cost(eig_f, *args, nw_cov, nw_valid, sim_covs),
        "vol_regime": compiled_cost(
            vr_f, *args, factor_ret, eigen_cov, eigen_valid),
    }

    cpu_s = _cpu_baseline_riskmodel((T, N, P, Q, K, M), args)
    return {"metric": "csi300_riskmodel_e2e_wall",
            "value": round(_stage_s("fused_e2e"), 4),
            "unit": "s", "vs_baseline": round(cpu_s / tpu_s, 2),
            # the universe axis (PR 11): every riskmodel record names its
            # (N, T) workload so tools/perfgate.py can key baselines by
            # (backend, universe_n) and an N=5000 wall never false-
            # regresses against N=300 history
            "universe": u.name, "universe_n": N, "universe_t": T,
            "devices": 1,
            "e2e_wall_s": round(_stage_s("fused_e2e"), 4),
            "stocks_per_sec": round(N * T / tpu_s),
            # the denominator is the golden-NumPy serial proxy timed on
            # subsamples and extrapolated (statsmodels absent) — a LOWER
            # BOUND on the reference's own time, so the ratio is a bound,
            # not a point estimate (BASELINE.md "Measured" preamble)
            "vs_baseline_note": "lower-bound ratio vs extrapolated NumPy "
                                "proxy of the reference's serial loops",
            # BASELINE.json names "cross-sectional WLS dates/sec" as the
            # metric — report it directly (T dates / regression-stage wall)
            "xreg_dates_per_sec": round(T / reg_s),
            "e2e_dates_per_sec": round(T / tpu_s),
            # the incremental serving metrics: latency of appending ONE date
            # to a (T-1)-date resumable state (RiskModel.update) vs
            # rebuilding the whole history (the e2e number above)
            "daily_update_latency_s": round(_stage_s("daily_update"), 4),
            "update_dates_per_sec": round(1.0 / upd_s),
            "update_speedup_vs_e2e": round(tpu_s / upd_s, 1),
            # the eigen stage alone (unfused wall) and the incremental-eigen
            # single-date append (full-fidelity MC bias per served date,
            # config.eigen_incremental=True) — the two walls the eigen
            # optimisation work is gated on (tools/perfgate.py)
            "eigen_stage_wall_s": round(_stage_s("eigen"), 4),
            "eigen_update_latency_s": round(_stage_s("eigen_update"), 4),
            # which Monte-Carlo dtype produced these numbers (the bf16 path
            # is a different draw realization — records are only comparable
            # within a dtype)
            "eigen_mc_dtype": cfg.eigen_mc_dtype or "float32",
            # the guarded (production) serving path: input guards +
            # degraded-mode quarantine run inside the same fused step,
            # WITH per-date telemetry recording (the production loop's
            # configuration); the frac below is its measured cost
            "guarded_update_latency_s": round(_stage_s("guarded_update"), 4),
            "guard_overhead_frac": round(gupd_s / upd_s - 1.0, 4),
            "telemetry_overhead_frac": round(telemetry_overhead, 4),
            "tracing_overhead_frac": round(tracing_overhead, 4),
            # fraction of served dates quarantined during the timed runs —
            # 0.0 on the clean synthetic panel (guards must cost nothing
            # and flag nothing when nothing is wrong)
            "quarantine_rate": quarantine_rate,
            # each stage timed as its OWN jitted program (intermediates
            # materialized at stage boundaries), so the sum exceeds the
            # fused e2e wall above — the gap IS the fusion win, not noise
            "stages_unfused": {k: round(v, 4) for k, v in stage_s.items()},
            "stages_note": "independently jitted per-stage walls; their sum "
                           "> e2e wall because the fused path elides the "
                           "stage-boundary materialization",
            "memory": mem_rec,
            "roofline": _roofline(stage_s, models, measured_cost)}


def _bench_riskmodel_universe(u, devices):
    """The --universe scaling path of config 1: fused risk-stack e2e and
    the eigen stage under a ``('date','stock')`` mesh of ``devices``
    devices (all on the embarrassingly-parallel 'date' axis).

    Deliberately lighter than the flagship csi300 record — no per-stage
    memory/roofline battery — because its job is the scaling curve: walls,
    stocks/sec and eigen GFLOP/s at each (N, devices) cell
    (MULTICHIP_r06.json).  Panels are ``pad_to_mesh``-padded (inert by the
    masked design: valid pads False, data pads 0) and sharded with the
    canonical cross-section layout; the math inside then follows the mesh
    doctrine (stock axis gathered once per stage), so these walls time the
    SAME program the bitwise parity tests in tests/test_sharding.py pin
    against the single-device run."""
    import jax
    import jax.numpy as jnp
    from mfm_tpu.config import RiskModelConfig
    from mfm_tpu.models.eigen import sim_sweeps_for, simulated_eigen_covs
    from mfm_tpu.models.risk_model import RiskModel
    from mfm_tpu.parallel.mesh import (
        make_mesh, pad_to_mesh, shard_panel, use_mesh)
    from __graft_entry__ import _synthetic_risk_inputs

    T, N, P, Q = u.T, u.N, u.P, u.Q
    K = 1 + P + Q
    M = 100
    n_dev = max(1, int(devices))
    avail = jax.device_count()
    if n_dev > avail:
        raise SystemExit(
            f"--devices {n_dev} but only {avail} JAX devices are up; run "
            "through bench.py --devices N (it sets XLA_FLAGS="
            "--xla_force_host_platform_device_count before importing jax)")
    args = _synthetic_risk_inputs(T, N, P, Q, dtype=jnp.float32, seed=0)
    cfg = RiskModelConfig(eigen_n_sims=M, eigen_sim_length=T)
    sim_covs = simulated_eigen_covs(jax.random.key(0), K, T, M, jnp.float32)

    mesh = make_mesh(devices=jax.devices()[:n_dev])
    padded = [pad_to_mesh(a, mesh) for a in args]

    def _sum_finite(*xs):
        return sum(jnp.sum(jnp.where(jnp.isfinite(x), x, 0.0)) for x in xs)

    with use_mesh(mesh):
        def fused_step():
            # fresh sharded copies per call: run_fused donates its panels
            # (jnp.array, not asarray — asarray aliases the committed
            # buffer on a 1-device mesh and the donation deletes it)
            fresh = shard_panel([jnp.array(a) for a in padded], mesh)
            rm = RiskModel(*fresh, n_industries=P, config=cfg)
            out = rm.run_fused(sim_covs=sim_covs, sim_length=T)
            return _sum_finite(out.factor_ret, out.vr_cov) + jnp.sum(out.lamb)

        e2e_s = _time3(fused_step)

        # the eigen stage alone (the 18 s serial-LAPACK floor this mesh
        # attacks): jitted with its real inputs as arguments, like the
        # csi300 per-stage split
        @jax.jit
        def eig_f(ret, cap, styles, industry, valid, c, v, s):
            m = RiskModel(ret, cap, styles, industry, valid,
                          n_industries=P, config=cfg)
            return _sum_finite(*m.eigen_risk_adj_by_time(
                c, v, sim_covs=s, sim_length=T))

        sharded = shard_panel([jnp.array(a) for a in padded], mesh)
        rm0 = RiskModel(*sharded, n_industries=P, config=cfg)
        factor_ret = rm0.reg_by_time()[0]
        nw_cov, nw_valid = rm0.newey_west_by_time(factor_ret)
        eig_s = _time3(eig_f, *sharded, nw_cov, nw_valid, sim_covs)

    models = _riskmodel_stage_models(
        T, N, P, Q, K, M, sweeps=sim_sweeps_for(K, jnp.float32, T))
    return {"metric": "riskmodel_e2e_wall",
            "value": round(e2e_s, 4), "unit": "s", "vs_baseline": None,
            "universe": u.name, "universe_n": N, "universe_t": T,
            "devices": n_dev,
            "mesh": {"date": int(mesh.shape["date"]),
                     "stock": int(mesh.shape["stock"])},
            "padded_t": int(padded[0].shape[0]),
            "e2e_wall_s": round(e2e_s, 4),
            "stocks_per_sec": round(N * T / e2e_s),
            "e2e_dates_per_sec": round(T / e2e_s),
            "eigen_stage_wall_s": round(eig_s, 4),
            "eigen_stage_gflops": round(models["eigen"]["gflop"] / eig_s, 1),
            # virtual host devices share physical cores — wall-clock
            # speedup is bounded by this, record it next to every cell
            "host_cpu_count": os.cpu_count()}


def bench_chunk_sweep():
    """Eigen-stage chunk sweep at CSI300 scale: wall clock + transient
    memory per ``eigen_chunk`` setting, the sizing evidence behind the
    "auto" policy (models/eigen.py::auto_eigen_chunk).  Chunked and
    unchunked results are identical, so this trades nothing but the
    numbers reported here."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    from mfm_tpu.config import RiskModelConfig
    from mfm_tpu.models.eigen import auto_eigen_chunk, simulated_eigen_covs
    from mfm_tpu.models.risk_model import RiskModel
    from mfm_tpu.utils.obs import compiled_memory
    from __graft_entry__ import _synthetic_risk_inputs

    T, N, P, Q = 1390, 300, 31, 10
    K = 1 + P + Q
    M = 100
    args = _synthetic_risk_inputs(T, N, P, Q, dtype=jnp.float32, seed=0)
    cfg = RiskModelConfig(eigen_n_sims=M, eigen_sim_length=T)
    sim_covs = simulated_eigen_covs(jax.random.key(0), K, T, M, jnp.float32)

    rm = RiskModel(*args, n_industries=P, config=cfg)
    factor_ret = rm.reg_by_time()[0]
    nw_cov, nw_valid = rm.newey_west_by_time(factor_ret)

    def eigen_fn(chunk):
        cfgc = _dc.replace(cfg, eigen_chunk=chunk)

        @jax.jit
        def f(ret, cap, styles, industry, valid, c, v, s):
            m = RiskModel(ret, cap, styles, industry, valid,
                          n_industries=P, config=cfgc)
            cov, ok = m.eigen_risk_adj_by_time(c, v, sim_covs=s, sim_length=T)
            return jnp.sum(jnp.where(jnp.isfinite(cov), cov, 0.0))
        return f

    auto_chunk = auto_eigen_chunk(T, M, K, itemsize=4)
    rows = []
    for chunk in (None, "auto", 32, 64, 128, 256, 512):
        f = eigen_fn(chunk)
        wall = _time3(f, *args, nw_cov, nw_valid, sim_covs)
        mem = compiled_memory(f, *args, nw_cov, nw_valid, sim_covs)
        rows.append({"chunk": chunk,
                     "resolved": auto_chunk if chunk == "auto" else chunk,
                     "wall_s": round(wall, 4),
                     "temp_bytes": mem.get("temp_bytes"),
                     "peak_bytes": mem.get("peak_bytes")})
    auto_row = next(r for r in rows if r["chunk"] == "auto")
    return {"metric": "csi300_eigen_chunk_sweep", "unit": "s",
            "value": auto_row["wall_s"], "vs_baseline": None,
            "auto_chunk": auto_chunk, "sweep": rows}


def _cpu_baseline_riskmodel(shape, args):
    """Golden NumPy serial loops (the reference's structure) on subsamples,
    extrapolated to full T."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))
    from golden import golden_cross_section, golden_newey_west, golden_eigen_adj

    T, N, P, Q, K, M = shape
    ret, cap, styles, industry, valid = (np.asarray(a, np.float64) for a in args)
    industry = industry.astype(int)

    n1 = 40
    t0 = time.perf_counter()
    for t in range(n1):
        v = valid[t].astype(bool)
        ind_oh = np.eye(P)[industry[t][v]]
        golden_cross_section(ret[t][v], cap[t][v], styles[t][v], ind_oh)
    reg_s = (time.perf_counter() - t0) / n1 * T

    f = 0.01 * np.random.default_rng(0).standard_normal((T, K))
    sample_ts = list(range(K + 2, T, 100))
    t0 = time.perf_counter()
    for t in sample_ts:
        golden_newey_west(f[:t], 2, 252.0)
    nw_s = (time.perf_counter() - t0) / len(sample_ts) * T

    cov = golden_newey_west(f, 2, 252.0)
    draws = np.random.default_rng(1).standard_normal((M, K, T))
    n3 = 3
    t0 = time.perf_counter()
    for _ in range(n3):
        golden_eigen_adj(cov, draws, 1.4)
    eig_s = (time.perf_counter() - t0) / n3 * T
    # vol-regime stage is negligible next to these; omitting favors the baseline
    return reg_s + nw_s + eig_s


def bench_beta(T=1390, N=300, label="csi300_beta_hsigma_wall"):
    import jax
    import jax.numpy as jnp
    from mfm_tpu.ops.rolling import rolling_beta_hsigma

    rng = np.random.default_rng(0)
    ret = (0.01 * rng.standard_normal((T, N))).astype(np.float32)
    ret[rng.random((T, N)) < 0.05] = np.nan
    mkt = (0.008 * rng.standard_normal(T)).astype(np.float32)

    f = jax.jit(lambda r, m: sum(
        jnp.sum(jnp.where(jnp.isfinite(x), x, 0.0))
        for x in rolling_beta_hsigma(r, m, window=252, half_life=63,
                                     min_periods=42, block=32)))
    tpu_s = _time3(f, jnp.asarray(ret), jnp.asarray(mkt))
    # CPU proxy: per-window closed-form WLS in NumPy (far cheaper than the
    # reference's statsmodels fit per window) on a subsample of stocks
    import pandas as pd
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))
    from golden import golden_beta_hsigma
    ns = 3
    t0 = time.perf_counter()
    for n in range(ns):
        golden_beta_hsigma(pd.Series(ret[:, n].astype(np.float64)),
                           pd.Series(mkt.astype(np.float64)))
    cpu_s = (time.perf_counter() - t0) / ns * N
    return {"metric": label, "value": round(tpu_s, 4), "unit": "s",
            "vs_baseline": round(cpu_s / tpu_s, 2),
            "vs_baseline_note": "lower-bound ratio vs per-window NumPy WLS "
                                "proxy (reference uses statsmodels fits)"}


def bench_factors():
    import jax.numpy as jnp
    from mfm_tpu.config import FactorConfig
    from mfm_tpu.data.synthetic import (
        panel_to_engine_fields, synthetic_market_panel,
    )
    from mfm_tpu.factors.engine import FactorEngine

    data = synthetic_market_panel(T=1390, N=300, n_industries=31, seed=0)
    fields = panel_to_engine_fields(data, jnp.float32)
    eng = FactorEngine(fields, jnp.asarray(data["index_close"], jnp.float32),
                       config=FactorConfig(), block=32)

    def run():
        out = eng.run()
        import jax.numpy as jnp2
        return sum(jnp2.sum(jnp2.where(jnp2.isfinite(v), v, 0.0))
                   for v in out.values())

    tpu_s = _time3(run)
    return {"metric": "csi300_factor_pipeline_wall", "value": round(tpu_s, 4),
            "unit": "s", "vs_baseline": None}


def bench_alla(universe="alla"):
    """Config 4, the REAL workload (VERDICT r3 weak #5): full 16-factor
    pipeline + post-processing + cross-sectional regression + covariance
    stack at all-A scale (5,000 stocks x 2,500 dates).

    Memory accounting for the 504-wide rolling windows (ops/rolling.py:52-90):
    each rolling kernel materializes block*window*N floats per input; at
    N=5000, window=504, f32 that is block*10.1 MB — block=16 keeps the
    largest live window buffer at ~161 MB/input (BETA has 2 inputs), well
    inside a single v5e chip's HBM next to the ~50 MB/field panel.
    """
    import jax
    import jax.numpy as jnp
    from mfm_tpu.config import FactorConfig, RiskModelConfig
    from mfm_tpu.data.synthetic import (
        panel_to_engine_fields, synthetic_market_panel,
    )
    from mfm_tpu.factors.engine import (
        FactorEngine, rowspace_index, gather_rows, scatter_rows)
    from mfm_tpu.models.eigen import simulated_eigen_covs
    from mfm_tpu.models.risk_model import RiskModel
    from mfm_tpu.pipeline import BARRA_OUTPUT_STYLES
    from mfm_tpu.data.synthetic import resolve_universe

    u = resolve_universe(universe, T=_smoke_t())
    T, N, P, Q, M = u.T, u.N, u.P, u.Q, 100
    K = 1 + P + Q
    data = synthetic_market_panel(T=T, N=N, n_industries=P, seed=1)
    fields = panel_to_engine_fields(data, jnp.float32)
    index_close = jnp.asarray(data["index_close"], jnp.float32)
    industry = jnp.broadcast_to(
        jnp.asarray(data["industry"], jnp.int32)[None, :], (T, N))

    eng = FactorEngine(fields, index_close, config=FactorConfig(), block=16)

    def factors_fn():
        out = eng.run()
        return sum(jnp.sum(jnp.where(jnp.isfinite(v), v, 0.0))
                   for v in out.values())

    fac_s = _time3(factors_fn)
    factors = eng.run()  # executable + outputs now cached

    cfg = RiskModelConfig(eigen_n_sims=M, eigen_sim_length=T)
    sim_covs = simulated_eigen_covs(jax.random.key(1), K, T, M, jnp.float32)

    @jax.jit
    def risk_fn(factors, cap, industry, sim_covs):
        styles = jnp.stack(
            [factors[src] for src, _ in BARRA_OUTPUT_STYLES], axis=-1)
        # t+1 return label in row space (main.py:99 groupby shift(-1))
        observed = jnp.isfinite(factors["ret"]) | jnp.isfinite(cap)
        idx = rowspace_index(observed)
        rs = gather_rows(factors["ret"], idx)
        nxt = scatter_rows(jnp.concatenate(
            [rs[1:], jnp.full((1, N), jnp.nan, rs.dtype)], axis=0), idx)
        valid = (jnp.isfinite(styles).all(axis=-1) & jnp.isfinite(nxt)
                 & jnp.isfinite(cap) & (cap > 0))
        rm = RiskModel(jnp.where(valid, nxt, jnp.nan), cap, styles, industry,
                       valid, n_industries=P, config=cfg)
        out = rm.run(sim_covs=sim_covs, sim_length=T)  # production eigen path
        return (jnp.sum(jnp.where(jnp.isfinite(out.factor_ret),
                                  out.factor_ret, 0.0))
                + jnp.sum(jnp.where(jnp.isfinite(out.vr_cov), out.vr_cov, 0.0))
                + jnp.sum(out.lamb))

    risk_s = _time3(risk_fn, factors, fields["circ_mv"], industry, sim_covs)
    e2e = fac_s + risk_s
    return {"metric": "alla_full_pipeline_wall",
            "value": round(e2e, 4), "unit": "s",
            "vs_baseline": None,
            "universe": u.name, "universe_n": N, "universe_t": T,
            "devices": 1,
            "e2e_wall_s": round(e2e, 4),
            "stocks_per_sec": round(N * T / e2e),
            "e2e_dates_per_sec": round(T / e2e),
            "stages": {"factors_post": round(fac_s, 4),
                       "risk_stack": round(risk_s, 4)}}


def _alpha_workload(T, N, n_exprs=1000):
    """The config-5 synthetic workload: price/volume/ret panel + templated
    expression batch + forward returns (shared by the CSI300 and all-A
    alpha benches so the two never drift apart)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    close = np.exp(np.cumsum(0.02 * rng.standard_normal((T, N)), axis=0))
    panel = {
        "close": jnp.asarray(close, jnp.float32),
        "volume": jnp.asarray(np.exp(rng.normal(10, 1, (T, N))), jnp.float32),
        "ret": jnp.asarray(np.vstack([np.full((1, N), np.nan),
                                      close[1:] / close[:-1] - 1]), jnp.float32),
    }
    templates = [
        "cs_rank(delta(close, {d}))",
        "-ts_corr(close, volume, {w})",
        "cs_zscore(ts_std(ret, {w}))",
        "decay_linear(cs_demean(ret), {w}) * {c}",
        "where(ret > 0, cs_rank(volume), -cs_rank(ts_mean(volume, {d})))",
        "ts_rank(close, {w}) - cs_rank(delta(volume, {d}))",
    ]
    exprs = [templates[i % len(templates)].format(
        d=2 + i % 9, w=5 + i % 20, c=round(0.5 + (i % 10) / 10, 2))
        for i in range(n_exprs)]
    fwd = jnp.concatenate([panel["ret"][1:],
                           jnp.full((1, N), jnp.nan, jnp.float32)], axis=0)
    return panel, exprs, fwd


def bench_alpha(T=1390, N=300, label="alpha_1000_exprs_csi300_wall"):
    import jax
    import jax.numpy as jnp
    from mfm_tpu.alpha.dsl import compile_alpha_batch
    from mfm_tpu.alpha.metrics import alpha_summary

    panel, exprs, fwd = _alpha_workload(T, N)
    batch = compile_alpha_batch(exprs)  # one jit at E=1000; chunks above
    summ = jax.jit(lambda out, fwd: jnp.sum(jnp.where(
        jnp.isfinite(alpha_summary(out, fwd)["mean_ic"]),
        alpha_summary(out, fwd)["mean_ic"], 0.0)))

    # no outer jit around `batch` — tracing would inline every chunk back
    # into the one unbounded program the chunking exists to avoid
    def run(p, fwd):
        return summ(batch(p), fwd)

    t0 = time.perf_counter()
    _force(run(dict(panel), fwd))
    compile_s = time.perf_counter() - t0
    tpu_s = _time3(run, dict(panel), fwd)
    return {"metric": label, "value": round(tpu_s, 4),
            "unit": "s", "vs_baseline": None,
            "compile_s": round(compile_s, 2)}


def bench_alpha_alla():
    """Config 5 at all-A scale (2500 x 5000): the (E, T, N) tensor would be
    50 GB, so this path uses the fused evaluate+score chunks
    (alpha/dsl.py::compile_alpha_scores — live HBM = chunk x 50 MB panels
    + one (T, W, N) window transient; chunk=50 -> ~2.5 GB)."""
    import jax
    import jax.numpy as jnp
    from mfm_tpu.alpha.dsl import compile_alpha_scores

    panel, exprs, fwd = _alpha_workload(T=2500, N=5000)
    score = compile_alpha_scores(exprs, chunk=50)

    def run(p, fwd):
        s = score(p, fwd)
        return sum(jnp.sum(jnp.where(jnp.isfinite(v), v, 0.0))
                   for v in s.values())

    t0 = time.perf_counter()
    _force(run(dict(panel), fwd))
    compile_s = time.perf_counter() - t0
    tpu_s = _time3(run, dict(panel), fwd)
    return {"metric": "alpha_1000_exprs_alla_wall", "value": round(tpu_s, 4),
            "unit": "s", "vs_baseline": None,
            "compile_s": round(compile_s, 2)}


def bench_query():
    """Config 6: the batched portfolio-query service (serve/query.py).

    Two numbers: raw engine throughput — the ONE vmapped, donated jit —
    at request-storm scales B = 1e3 / 1e5 / 1e6 over a CSI300-shaped
    factor space, each bucket holding the <=1-compile steady-state
    contract; and the serving loop's operational summary (latency
    percentiles, shed rate, breaker counters) from a real
    :class:`QueryServer` overload storm with telemetry recording on."""
    import io

    import jax.numpy as jnp
    from mfm_tpu.serve import QueryEngine, QueryServer, ServePolicy, \
        bucket_for
    from mfm_tpu.utils.contracts import assert_max_compiles

    K = 1 + 31 + 10          # country + industries + styles (config-1 shape)
    rng = np.random.default_rng(0)
    A = (rng.standard_normal((K, K)) / np.sqrt(K)).astype(np.float32)
    cov = (A @ A.T + 1e-3 * np.eye(K, dtype=np.float32)) * 1e-4
    engine = QueryEngine(
        cov, benchmarks={"idx": 0.1 * rng.standard_normal(K)})

    throughput = {}
    for b in (1_000, 100_000, 1_000_000):
        W = (0.2 * rng.standard_normal((b, K))).astype(np.float32)
        bucket = bucket_for(b)

        def step(W=W, bucket=bucket):
            res = engine.query(W, bucket=bucket, trim=False)
            return jnp.sum(res.total_vol)

        _force(step())  # compile + warmup: the bucket's one allowed compile
        times = []
        with assert_max_compiles(1, f"steady-state query bucket {bucket}"):
            for _ in range(3):
                t0 = time.perf_counter()
                _force(step())
                times.append(time.perf_counter() - t0)
        wall = min(times)
        throughput[str(b)] = {"bucket": bucket, "wall_s": round(wall, 4),
                              "portfolios_per_sec": round(b / wall)}

    # the serving loop under a deterministic overload storm (gulp mode):
    # 2048 requests against a 512-deep queue -> shed_rate 0.75 by
    # construction, latency percentiles from the registry histograms
    policy = ServePolicy(queue_max=512, batch_max=256,
                         default_deadline_s=30.0)
    server = QueryServer(engine, policy, health="ok")
    lines = (json.dumps({"id": f"q{i}",
                         "weights": np.round(0.2 * rng.standard_normal(K),
                                             6).tolist()})
             for i in range(2048))
    summary = server.run(lines, io.StringIO(), gulp=True)
    return {"metric": "portfolio_query_throughput",
            "value": throughput["1000000"]["portfolios_per_sec"],
            "unit": "portfolios/s", "vs_baseline": None,
            "k_factors": K,
            "throughput": throughput,
            "serving": summary}


def bench_scenario():
    """Config 7: the batched scenario engine (scenario/engine.py).

    scenarios_per_sec at S = 16 / 256 / 4096 over a CSI300-shaped factor
    space — a representative mix of vol shocks, regime multipliers and
    correlation stress, each S padded to its geometric bucket and holding
    the <=1-compile steady-state contract — plus the obs registry's batch
    latency percentiles (telemetry recording on, like production)."""
    from mfm_tpu.obs.instrument import scenario_summary_from_registry
    from mfm_tpu.scenario import ScenarioBuilder, ScenarioEngine
    from mfm_tpu.serve import bucket_for
    from mfm_tpu.utils.contracts import assert_max_compiles

    K = 1 + 31 + 10          # country + industries + styles (config-1 shape)
    rng = np.random.default_rng(0)
    A = (rng.standard_normal((K, K)) / np.sqrt(K)).astype(np.float32)
    cov = (A @ A.T + 1e-3 * np.eye(K, dtype=np.float32)) * 1e-4
    names = [f"f{i}" for i in range(K)]
    engine = ScenarioEngine(cov, factor_names=names)

    def specs_for(S):
        out = []
        for i in range(S):
            b = ScenarioBuilder(f"s{i}")
            b.shock(names[i % K], add=1e-4 * (1 + i % 7))
            b.vol_regime(1.0 + 0.1 * (i % 5))
            if i % 3 == 0:
                b.correlation(0.2 + 0.1 * (i % 4))
            out.append(b.build())
        return out

    throughput = {}
    for S in (16, 256, 4096):
        specs = specs_for(S)
        bucket = bucket_for(S)
        engine.run(specs)  # compile + warmup: the bucket's one allowed compile
        times, res = [], None
        with assert_max_compiles(1, f"steady-state scenario bucket {bucket}"):
            for _ in range(3):
                t0 = time.perf_counter()
                res = engine.run(specs)
                # engine.run already materializes every lane to numpy;
                # forcing the last cov keeps the span visibly synchronous
                _force(res[-1].cov[0, 0])
                times.append(time.perf_counter() - t0)
        bad = [r.spec.name for r in res if not r.ok]
        if bad:
            raise AssertionError(f"bench scenarios rejected: {bad[:5]}")
        wall = min(times)
        throughput[str(S)] = {"bucket": bucket, "wall_s": round(wall, 4),
                              "scenarios_per_sec": round(S / wall)}

    return {"metric": "scenario_throughput",
            "value": throughput["4096"]["scenarios_per_sec"],
            "unit": "scenarios/s", "vs_baseline": None,
            "k_factors": K,
            "throughput": throughput,
            "summary": scenario_summary_from_registry()}


def bench_sweep():
    """Config sweep: the streaming scenario sweep (scenario/sweep.py).

    Three legs over a K=42 factor space whose correlation is built to
    sit INSIDE the certificate cone — off-diagonals bounded so
    ``clip((1+cb) corr)`` never saturates within the sampler's ball,
    lambda_min(corr) clearing ``cb_hi/(1+cb_hi)`` with margin — so the
    hot (no-eigh) path carries ~every lane and the offender fraction
    stays a rounding error:

    - **streaming rate**: >= 10^6 scenarios through the donated-carry
      chunk kernel at the cache-resident chunk, zero compiles allowed
      after the one-chunk warmup sweep.
    - **materializing arm**: the SAME thetas as dense specs through
      ``ScenarioEngine.run`` at equal shapes (one chunk bucket); the
      streaming rate must be >= 50x this — the whole point of never
      materializing (S, K, K).
    - **refinement**: a coarse sweep + reverse-stress ascent + local
      re-sweep (refine ball = the full preset-covering ShockBall); the
      refined worst case must improve on the coarse top-1 for every
      book, round-trip to an admissible replayable spec, and dominate
      every preset drill.
    """
    import jax
    import jax.numpy as jnp

    from mfm_tpu.grad.engine import ShockBall
    from mfm_tpu.obs.instrument import sweep_summary_from_registry
    from mfm_tpu.scenario import (
        ScenarioSpec, SweepEngine, UniformSampler, theta_to_spec,
    )
    from mfm_tpu.scenario.engine import ScenarioEngine
    from mfm_tpu.scenario.kernel import book_vols
    from mfm_tpu.utils.contracts import assert_max_compiles

    K = 42
    rng = np.random.default_rng(0)
    # factor-structure correlation with SMALL loadings: max |corr_ij|
    # ~0.45 << 1/(1+cb_hi) and lambda_min ~0.36 >> cb_hi/(1+cb_hi)
    F = (rng.standard_normal((K, 6)) * 0.3)
    corr_raw = F @ F.T + np.diag(rng.uniform(0.5, 1.5, K))
    d = np.sqrt(np.diagonal(corr_raw))
    corr = corr_raw / np.outer(d, d)
    sig = rng.uniform(0.01, 0.03, K)
    cov = (corr * np.outer(sig, sig)).astype(np.float32)
    names = [f"f{i}" for i in range(K)]
    xs = (rng.standard_normal((2, K)) / np.sqrt(K)).astype(np.float32)
    # the coarse box: shifts/scales small next to the sigma floor so the
    # SWEEP_EIGH_GUARD conditioning margin holds lane-wise
    ball = ShockBall(shift_max=0.001, scale_range=0.3, vol_mult_lo=1.0,
                     vol_mult_hi=3.5, corr_beta_lo=0.0, corr_beta_hi=0.45)
    engine = SweepEngine(cov, factor_names=names)
    chunk = 8192
    S = 123 * chunk                      # 1,007,616 >= 10^6, whole chunks

    def sampler(seed, n=S):
        return UniformSampler(ball, K, n, seed=seed)

    engine.sweep(xs, sampler(1, chunk), chunk=chunk)   # compile + warmup
    with assert_max_compiles(0, "steady-state sweep chunk"):
        res = engine.sweep(xs, sampler(2), chunk=chunk)
    if res.counts["n_ok"] != S or res.counts["n_rejected"]:
        raise AssertionError(f"sweep admission drift: {res.counts}")
    rate = round(S / res.seconds)

    # materializing arm, equal shapes: the first chunk's exact thetas as
    # dense specs through the (freshly satellite-optimized) engine.run
    scen = ScenarioEngine(cov, factor_names=names)
    th0 = next(iter(sampler(2, chunk).blocks(chunk)))[0]
    specs = [theta_to_spec(t, names, f"m{i}") for i, t in enumerate(th0)]
    scen.run(specs)                      # compile + warmup
    times = []
    with assert_max_compiles(1, "steady-state materializing arm"):
        for _ in range(3):
            t0 = time.perf_counter()
            out = scen.run(specs)
            _force(out[-1].cov[0, 0])
            times.append(time.perf_counter() - t0)
    mat_rate = round(chunk / min(times))
    speedup = rate / max(mat_rate, 1)
    if speedup < 50.0:
        raise AssertionError(
            f"streaming sweep only {speedup:.1f}x the materializing "
            f"engine ({rate} vs {mat_rate} scen/s) — target is 50x")

    # refinement leg: coarse sweep at 50 chunks, ascent + local re-sweep
    # inside the FULL preset-covering ball
    S_r = 50 * chunk
    res_r = engine.sweep(xs, sampler(3, S_r), chunk=chunk,
                         refine={"ball": ShockBall(), "seed": 4})
    for b, blk in enumerate(res_r.refined):
        if not blk["improved"]:
            raise AssertionError(
                f"book{b}: refinement did not improve on the coarse "
                f"top-1 ({blk['vol_final_top1']} < "
                f"{blk['vol_coarse_top1']})")
        if not blk["admissible"]:
            raise AssertionError(f"book{b}: refined worst case left the "
                                 "admissible set")
    dominance = engine.preset_dominance(res_r, xs)
    losses = [row["label"] for row in dominance
              if not row["dominates_all"]]
    if losses:
        raise AssertionError(f"sweep worst case loses to preset drills "
                             f"for {losses}")
    # the recorded worst must round-trip: embedded spec -> engine.run ->
    # the SAME vol, bitwise (both sides are the exact serving path)
    bv = jax.jit(book_vols)
    for b, book in enumerate(res_r.books):
        top = book["top"][0]
        rerun = scen.run([ScenarioSpec.from_dict(top["spec"])])[0]
        if not rerun.ok:
            raise AssertionError(f"book{b}: top-1 spec does not replay "
                                 f"({rerun.problems})")
        v = float(np.asarray(bv(jnp.asarray(np.asarray(rerun.cov)[None]),
                                jnp.asarray(xs)))[b, 0])
        if v != top["vol"]:
            raise AssertionError(f"book{b}: top-1 vol {top['vol']} does "
                                 f"not round-trip ({v})")

    return {"metric": "sweep_throughput",
            "value": rate,
            "unit": "scenarios/s", "vs_baseline": None,
            "k_factors": K, "s_total": S,
            "chunk": chunk, "chunk_bucket": res.chunk_bucket,
            "speedup_x": round(speedup, 1),
            "materializing_scenarios_per_sec": mat_rate,
            "counts": res.counts,
            "offender_frac": round(res.counts["n_offenders"] / S, 6),
            "refine": {"s_total": S_r,
                       "blocks": res_r.refined,
                       "counts": res_r.counts,
                       "dominates_all_presets": not losses},
            "summary": sweep_summary_from_registry()}


def bench_grad():
    """Config 8: the differentiable-risk subsystem (mfm_tpu/grad/).

    Three numbers over a CSI300-shaped factor space, each inside the
    <=1-compile steady-state contract of its donated jit: min-vol
    construction throughput at B = 1e2 / 1e4 portfolios (with the KKT
    stationarity residual as the convergence diagnostic), and
    reverse-stress throughput (projected gradient ascent over the shock
    ball, differentiating through the gated PSD projection).  The
    reverse-stress answer is also checked against the preset drill
    catalog: the worst admissible shock must be admissible AND report at
    least every preset's vol for the same portfolio — a worst case that
    loses to a drill the desk already runs is a solver bug, not a
    benchmark."""
    import jax.numpy as jnp

    from mfm_tpu.grad.construct import minvol_batch
    from mfm_tpu.grad.engine import (
        GradEngine, MINVOL_ETA, MINVOL_STEPS, REVERSE_STEPS, ShockBall,
    )
    from mfm_tpu.models.risk_model import portfolio_vol
    from mfm_tpu.scenario import PRESETS
    from mfm_tpu.scenario.engine import ScenarioEngine
    from mfm_tpu.serve import bucket_for
    from mfm_tpu.utils.contracts import assert_max_compiles

    K = 1 + 31 + 10          # country + industries + styles (config-1 shape)
    rng = np.random.default_rng(0)
    A = (rng.standard_normal((K, K)) / np.sqrt(K)).astype(np.float32)
    cov = (A @ A.T + 1e-3 * np.eye(K, dtype=np.float32)) * 1e-4
    names = [f"f{i}" for i in range(K)]
    cov_j = jnp.array(cov)
    lo = jnp.zeros(K, jnp.float32)
    hi = jnp.ones(K, jnp.float32)
    eta = jnp.asarray(MINVOL_ETA, jnp.float32)
    steps = jnp.int32(MINVOL_STEPS)

    minvol = {}
    kkt_worst = 0.0
    for b in (100, 10_000):
        bucket = bucket_for(b)
        xs0_np = np.full((bucket, K), 1.0 / K, np.float32)

        def step(xs0_np=xs0_np):
            # xs0 is donated — a fresh device buffer per call, like the
            # engine path; the max KKT residual over the bucket forces
            # the whole solve
            x, vol, kkt = minvol_batch(jnp.array(xs0_np), cov_j, lo, hi,
                                       eta, steps)
            return jnp.max(kkt)

        kkt = _force(step())  # compile + warmup: the one allowed compile
        times = []
        with assert_max_compiles(1, f"steady-state min-vol bucket {bucket}"):
            for _ in range(3):
                t0 = time.perf_counter()
                kkt = _force(step())
                times.append(time.perf_counter() - t0)
        wall = min(times)
        kkt_worst = max(kkt_worst, float(kkt))
        minvol[str(b)] = {"bucket": bucket, "wall_s": round(wall, 4),
                          "portfolios_per_sec": round(b / wall)}

    # reverse stress: P books through the ascent (each step is a vjp
    # through stress + gated PSD projection), then the catalog check
    engine = GradEngine(cov, factor_names=names)
    P = 64
    rng2 = np.random.default_rng(1)
    W = (0.2 * rng2.standard_normal((P, K))).astype(np.float32)
    ball = ShockBall()
    engine.reverse_stress(W, ball=ball)    # compile + warmup
    times, entries = [], None
    bucket = bucket_for(P)
    with assert_max_compiles(1, f"steady-state reverse bucket {bucket}"):
        for _ in range(3):
            t0 = time.perf_counter()
            entries = engine.reverse_stress(W, ball=ball)
            times.append(time.perf_counter() - t0)
    wall = min(times)
    inadmissible = [e["label"] for e in entries if not e["admissible"]]
    if inadmissible:
        raise AssertionError("reverse-stress answers left the admissible "
                             f"set: {inadmissible[:5]}")
    # the worst case must dominate every preset drill for the same book
    scen = ScenarioEngine(cov, factor_names=names)
    drills = {r.spec.name: np.asarray(r.cov, np.float64)
              for r in scen.run([PRESETS[n] for n in sorted(PRESETS)])}
    x0 = np.asarray(W[0], np.float64)
    losses = []
    for name, dcov in drills.items():
        drill_vol = float(portfolio_vol(jnp.array(dcov), jnp.array(x0)))
        if entries[0]["vol_worst"] < drill_vol * (1 - 1e-5):
            losses.append((name, drill_vol))
    if losses:
        raise AssertionError("reverse-stress worst case loses to preset "
                             f"drills: {losses}")

    reverse = {"P": P, "bucket": bucket, "steps": REVERSE_STEPS,
               "wall_s": round(wall, 4),
               "scenarios_per_sec": round(P / wall, 1),
               "vol_worst_vs_presets": "dominates"}
    return {"metric": "grad_throughput",
            "value": minvol["10000"]["portfolios_per_sec"],
            "unit": "portfolios/s", "vs_baseline": None,
            "k_factors": K,
            "minvol_portfolios_per_sec_b100":
                minvol["100"]["portfolios_per_sec"],
            "minvol_portfolios_per_sec_b10000":
                minvol["10000"]["portfolios_per_sec"],
            "reverse_scenarios_per_sec": reverse["scenarios_per_sec"],
            "minvol_convergence_iters": MINVOL_STEPS,
            "minvol_kkt_residual": round(kkt_worst, 8),
            "minvol": minvol,
            "reverse": reverse}


def bench_fleet():
    """Config 9 (fleet): the coalescing front end vs the one-line-at-a-time
    baseline under seeded mixed small-request (B=1) traffic.

    Three measurements (tools/trafficgen.py drives all of them):

    - **baseline_qps**: submit + drain per line — one jit dispatch per
      request, the pre-fleet arrival-time behaviour of the stdin loop.
    - **fleet_qps / latency**: the same request shapes through a
      :class:`Coalescer` at a >= 2k req/s seeded OPEN-LOOP schedule;
      sustained QPS is completions over the span from first arrival to
      last completion, latency is per-request (scheduled arrival ->
      delivery).  The p99 must sit inside the configured linger plus one
      batch wall (the coalescer's latency contract), and every response
      must be BITWISE the sequential single-threaded loop's for the same
      request id (the bucket-ladder invariant).
    - **closed_loop_qps**: 32 virtual clients, one request in flight
      each — the self-throttled ceiling for comparison.
    """
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import io
    import threading

    import trafficgen
    from mfm_tpu.obs.instrument import fleet_summary_from_registry
    from mfm_tpu.serve import (
        Coalescer, QueryEngine, QueryServer, ServePolicy,
    )

    K = 1 + 31 + 10          # country + industries + styles (config-1 shape)
    rng = np.random.default_rng(0)
    A = (rng.standard_normal((K, K)) / np.sqrt(K)).astype(np.float32)
    cov = (A @ A.T + 1e-3 * np.eye(K, dtype=np.float32)) * 1e-4
    bench_map = {"idx": 0.1 * rng.standard_normal(K)}
    stressed = (cov * 1.21).astype(np.float32)   # a 10% vol-regime shock

    # batch_max 256 keeps each flush's construct sub-groups (~10% share
    # each) inside a FULL bucket-32 kernel — at 512 they straddle into a
    # half-empty bucket-128 and the padding halves sustained QPS
    def mk_server(batch_max=256):
        eng = QueryEngine(cov, benchmarks=bench_map)
        scen = {"stress": QueryEngine(stressed, benchmarks=bench_map)}
        return QueryServer(eng, ServePolicy(batch_max=batch_max,
                                            queue_max=65536,
                                            default_deadline_s=600.0),
                           health="ok", scenarios=scen)

    # construct solves are the expensive tail (a min_vol solve is ~30x a
    # risk query) — they are where batching amortizes hardest, so the mix
    # weights them at 20% (10% min_vol, 10% risk_parity by alternation).
    # zero sweep share: a sweep is a whole streaming batch job and would
    # need its own per-bucket warmup inside the bitwise timed window —
    # --config sweep owns that measurement
    mix = (0.45, 0.20, 0.15, 0.20, 0.0)
    n, rate, linger = 10000, 2400.0, 0.1
    lines = trafficgen.gen_requests(7, n, K, scenario="stress", mix=mix)

    wrng = np.random.default_rng(99)

    def _wline(kind, i):
        req = {"id": f"w{kind}{i}",
               "weights": np.round(
                   0.2 * wrng.standard_normal(K), 6).tolist(),
               "deadline_s": 600.0}
        if kind == "s":
            req["scenario"] = "stress"
        elif kind == "mv":
            req["construct"] = {"solver": "min_vol"}
        elif kind == "rp":
            req["construct"] = {"solver": "risk_parity"}
        return json.dumps(req, sort_keys=True)

    def warm(server, buckets):
        """Compile every (scenario, kernel-group, bucket) shape the run can
        hit, so no XLA compile lands inside a timed window."""
        for kind in ("q", "s", "mv", "rp"):
            for b in buckets:
                for i in range(b):
                    server.submit_line_routed(_wline(kind, b * 1000 + i),
                                              origin=None)
                while server._queue:
                    server.drain_routed()

    # -- baseline: one-line-at-a-time (dispatch latency per request) ---------
    base_lines = lines[:400]
    bserver = mk_server(batch_max=1)
    sink = io.StringIO()
    warm(bserver, (1,))
    t0 = time.perf_counter()
    for ln in base_lines:
        for r in bserver.submit_line(ln):
            sink.write(json.dumps(r, sort_keys=True))
        for r in bserver.drain():
            # drain() hands back host dicts, but force a scalar anyway so
            # the span is visibly synchronous (mfmlint R5)
            _force(r.get("total_vol") or 0.0)
            sink.write(json.dumps(r, sort_keys=True))
    base_wall = time.perf_counter() - t0
    baseline_qps = len(base_lines) / base_wall

    # -- sequential reference for the bitwise check --------------------------
    ref_buf = io.StringIO()
    mk_server().run(list(lines), ref_buf, gulp=True)
    ref = {}
    for ln in ref_buf.getvalue().splitlines():
        ref[json.loads(ln)["id"]] = ln

    # -- coalesced open loop -------------------------------------------------
    server = mk_server()
    warm(server, (8, 32, 128, 512))
    batch_walls = []
    orig_drain = server.drain_routed

    def timed_drain():
        t = time.perf_counter()
        out = orig_drain()
        batch_walls.append(time.perf_counter() - t)
        return out
    server.drain_routed = timed_drain

    completions, delivered = {}, {}
    done = threading.Event()

    def deliver(pairs):
        now = time.monotonic()
        for origin, resp in pairs:
            completions[origin] = now
            delivered[origin] = resp
        if len(delivered) >= n:
            done.set()

    co = Coalescer(server, linger_s=linger, deliver=deliver)
    co.start()
    sched = trafficgen.open_loop(
        lambda line, i: co.submit(line, origin=i), lines, rate)
    done.wait(timeout=120.0)
    co.stop()
    if completions:
        t_last = max(completions.values())
        fleet_wall = max(t_last - sched["t0"], 1e-9)
        fleet_qps = len(delivered) / fleet_wall
    else:
        # nothing completed inside the wait: report it (unanswered == n
        # via latency_stats) instead of crashing on max() of nothing
        fleet_qps = 0.0
    lat = trafficgen.latency_stats(sched["arrivals"], completions)
    max_batch_wall = max(batch_walls) if batch_walls else 0.0

    mismatched = [i for i, resp in delivered.items()
                  if json.dumps(resp, sort_keys=True)
                  != ref.get(resp.get("id"))]
    summary = fleet_summary_from_registry()

    # -- closed loop ---------------------------------------------------------
    cserver = mk_server()
    warm(cserver, (8, 32))
    events, cresp = {}, {}

    def cdeliver(pairs):
        for origin, resp in pairs:
            cresp[origin] = resp
            ev = events.get(origin)
            if ev is not None:
                ev.set()

    cco = Coalescer(cserver, linger_s=0.002, deliver=cdeliver)
    cco.start()

    def submit_and_wait(line, i):
        events[i] = threading.Event()
        cco.submit(line, origin=i)
        events[i].wait(timeout=60.0)
    closed = trafficgen.closed_loop(submit_and_wait, lines[:2000], 32)
    cco.stop()

    return {"metric": "fleet_serving_throughput",
            "value": round(fleet_qps),
            "unit": "requests/s", "vs_baseline": None,
            "k_factors": K, "n_requests": n,
            "offered_rate_rps": rate,
            "linger_s": linger,
            "fleet_qps": round(fleet_qps, 1),
            "baseline_qps": round(baseline_qps, 1),
            "speedup_vs_baseline": round(fleet_qps / baseline_qps, 2),
            "fleet_p50_latency_s": lat.get("p50_s"),
            "fleet_p99_latency_s": lat.get("p99_s"),
            "fleet_max_latency_s": lat.get("max_s"),
            "max_batch_wall_s": round(max_batch_wall, 6),
            "p99_within_linger_plus_batch": bool(
                lat.get("p99_s", float("inf"))
                <= linger + max_batch_wall),
            "coalesce_batch_fill_frac":
                summary["coalesce_batch_fill_frac"],
            "coalesce_flushes": summary["coalesce_flushes"],
            "bitwise_identical": not mismatched,
            "bitwise_mismatches": len(mismatched),
            "unanswered": lat.get("unanswered"),
            "closed_loop_qps": round(closed["qps"], 1),
            "closed_loop_concurrency": 32}


def bench_cache():
    """Config 10 (cache): the content-addressed response cache under
    repeat-heavy Zipf traffic, plus the construct warm-start tier.

    Measurements (tools/trafficgen.py --zipf drives the stream):

    - **cached_qps / cache_hit_rate**: Zipf(1.0) over a 150-body pool at
      a seeded open-loop schedule through a cache-fronted
      :class:`Coalescer`.  ~99% of arrivals are repeats; hits answer
      from the cache without touching admission, misses ride the normal
      coalesced path and populate on delivery.  The timed window runs
      under ``assert_max_compiles(0)`` — the cache is host-side dict
      work, and every kernel shape was compiled in warmup.
    - **bitwise proof**: every delivered response, stripped of the two
      per-caller identity keys (id, trace_id), must be BYTE-identical to
      a cache-off server's answer for the same request body — reuse is
      exact, not approximate.
    - **delivery audit**: delivered == computed (misses) + hits.
    - **warm_start_solver_iters_saved**: near-miss construct books seed
      the solver's warm-start blend at ``steps/4`` budget; parity deltas
      (|dvol|, max |dw|) vs full-budget cold solves of the SAME books
      are recorded — the documented "seeded, not bitwise" contract.
    """
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import io
    import threading

    import trafficgen
    from mfm_tpu.obs.instrument import cache_summary_from_registry
    from mfm_tpu.serve import (
        Coalescer, QueryEngine, QueryServer, ResponseCache, ServePolicy,
        WarmStartIndex,
    )
    from mfm_tpu.utils.contracts import assert_max_compiles

    K = 1 + 31 + 10          # country + industries + styles (config-1 shape)
    rng = np.random.default_rng(0)
    A = (rng.standard_normal((K, K)) / np.sqrt(K)).astype(np.float32)
    cov = (A @ A.T + 1e-3 * np.eye(K, dtype=np.float32)) * 1e-4
    bench_map = {"idx": 0.1 * rng.standard_normal(K)}
    stressed = (cov * 1.21).astype(np.float32)

    def mk_server(batch_max=256, warm_index=None):
        eng = QueryEngine(cov, benchmarks=bench_map)
        scen = {"stress": QueryEngine(stressed, benchmarks=bench_map)}
        return QueryServer(eng, ServePolicy(batch_max=batch_max,
                                            queue_max=65536,
                                            default_deadline_s=600.0),
                           health="ok", scenarios=scen,
                           warm_index=warm_index)

    wrng = np.random.default_rng(99)

    def _wline(kind, i):
        req = {"id": f"w{kind}{i}",
               "weights": np.round(
                   0.2 * wrng.standard_normal(K), 6).tolist(),
               "deadline_s": 600.0}
        if kind == "s":
            req["scenario"] = "stress"
        elif kind == "b":
            req["benchmark"] = "idx"
        elif kind == "mv":
            req["construct"] = {"solver": "min_vol"}
        elif kind == "rp":
            req["construct"] = {"solver": "risk_parity"}
        return json.dumps(req, sort_keys=True)

    def warm(server, buckets):
        for kind in ("q", "b", "s", "mv", "rp"):
            for b in buckets:
                for i in range(b):
                    server.submit_line_routed(_wline(kind, b * 1000 + i),
                                              origin=None)
                while server._queue:
                    server.drain_routed()

    # -- Zipf(1.0) repeat-heavy stream ---------------------------------------
    # zero sweep share: sweeps are cache-exempt and would recompute inside
    # the zero-compile steady-state window — --config sweep owns them
    mix = (0.45, 0.20, 0.15, 0.20, 0.0)
    n, distinct, alpha = 40000, 150, 1.0
    rate, linger = 14000.0, 0.05
    lines = trafficgen.gen_zipf_requests(7, n, K, alpha=alpha,
                                         distinct=distinct,
                                         scenario="stress", mix=mix)

    def _body_key(line):
        o = json.loads(line)
        o.pop("id", None)
        o.pop("trace_id", None)
        return json.dumps(o, sort_keys=True)

    body_keys = [_body_key(ln) for ln in lines]

    # -- cache-off reference: each unique BODY computed once ----------------
    first = {}
    for ln, bk in zip(lines, body_keys):
        first.setdefault(bk, ln)
    ref_buf = io.StringIO()
    mk_server().run(list(first.values()), ref_buf, gulp=True)
    id2key = {json.loads(ln)["id"]: bk for bk, ln in first.items()}
    ref_body = {}
    for ln in ref_buf.getvalue().splitlines():
        o = json.loads(ln)
        bk = id2key[o["id"]]
        for ik in ("id", "trace_id"):
            o.pop(ik, None)
        ref_body[bk] = json.dumps(o, sort_keys=True)

    # -- cached open loop ----------------------------------------------------
    server = mk_server()
    warm(server, (8, 32, 128, 512))
    cache = ResponseCache(8192, 64 << 20)
    completions, delivered = {}, {}
    done = threading.Event()

    def deliver(pairs):
        now = time.monotonic()
        for origin, resp in pairs:
            completions[origin] = now
            delivered[origin] = resp
        if len(delivered) >= n:
            done.set()

    co = Coalescer(server, linger_s=linger, deliver=deliver, cache=cache)
    co.start()
    with assert_max_compiles(0, "cache steady state (post-warmup)"):
        sched = trafficgen.open_loop(
            lambda line, i: co.submit(line, origin=i), lines, rate)
        done.wait(timeout=180.0)
        co.stop()
    if completions:
        t_last = max(completions.values())
        cached_qps = len(delivered) / max(t_last - sched["t0"], 1e-9)
    else:
        cached_qps = 0.0
    lat = trafficgen.latency_stats(sched["arrivals"], completions)
    cstats = cache.stats()
    hit_rate = (cstats["hits"] / max(cstats["hits"] + cstats["misses"], 1))

    mismatched = [i for i, resp in delivered.items()
                  if json.dumps({k: v for k, v in resp.items()
                                 if k not in ("id", "trace_id")},
                                sort_keys=True) != ref_body[body_keys[i]]]

    # -- construct warm-start tier -------------------------------------------
    wi = WarmStartIndex(tol=0.05)
    wserver = mk_server(batch_max=64, warm_index=wi)
    cserver = mk_server(batch_max=64)          # cold parity reference
    warm(wserver, (8,))
    warm(cserver, (8,))
    prng = np.random.default_rng(4242)
    base = np.round(0.2 * prng.standard_normal(K), 6)
    parity_dvol, parity_dw = 0.0, 0.0
    for solver in ("min_vol", "risk_parity"):
        seed_line = json.dumps(
            {"id": f"seed-{solver}", "weights": base.tolist(),
             "deadline_s": 600.0, "construct": {"solver": solver}},
            sort_keys=True)
        wserver.submit_line_routed(seed_line, origin=None)
        wserver.drain_routed()                 # cold solve feeds the index
        for t in range(4):
            book = np.round(base + 0.002 * prng.standard_normal(K), 6)
            wline = json.dumps(
                {"id": f"wm-{solver}-{t}", "weights": book.tolist(),
                 "deadline_s": 600.0, "construct": {"solver": solver}},
                sort_keys=True)
            wserver.submit_line_routed(wline, origin=None)
            (_, wresp), = wserver.drain_routed()
            assert wresp.get("warm_start", {}).get("used"), \
                f"warm start did not fire for {solver} book {t}"
            cserver.submit_line_routed(wline, origin=None)
            (_, cresp), = cserver.drain_routed()
            parity_dvol = max(parity_dvol,
                              abs(wresp["total_vol"] - cresp["total_vol"]))
            parity_dw = max(parity_dw, float(np.max(np.abs(
                np.asarray(wresp["weights"])
                - np.asarray(cresp["weights"])))))
    wstats = wi.stats()

    obs_cache = cache_summary_from_registry()
    try:
        with open(os.path.join(REPO, "BENCH_r07.json"),
                  encoding="utf-8") as fh:
            r07_qps = json.load(fh)["parsed"]["fleet_qps"]
    except (OSError, ValueError, KeyError, TypeError):
        r07_qps = None

    return {"metric": "cache_serving_throughput",
            "value": round(cached_qps),
            "unit": "requests/s",
            "vs_baseline": (round(cached_qps / r07_qps, 2)
                            if r07_qps else None),
            "k_factors": K, "n_requests": n,
            "zipf_alpha": alpha, "distinct_bodies": distinct,
            "offered_rate_rps": rate, "linger_s": linger,
            "cached_qps": round(cached_qps, 1),
            "cache_hit_rate": round(hit_rate, 4),
            "cache_hits": cstats["hits"],
            "cache_misses": cstats["misses"],
            "cache_entries": cstats["entries"],
            "cache_resident_bytes": cstats["resident_bytes"],
            "baseline_fleet_qps_r07": r07_qps,
            "cache_p50_latency_s": lat.get("p50_s"),
            "cache_p99_latency_s": lat.get("p99_s"),
            "cache_max_latency_s": lat.get("max_s"),
            "hit_p99_latency_s": obs_cache.get("hit_p99_latency_s"),
            "bitwise_identical_modulo_identity": not mismatched,
            "bitwise_mismatches": len(mismatched),
            "unanswered": lat.get("unanswered"),
            "delivery_audit_ok": (len(delivered)
                                  == cstats["hits"] + cstats["misses"]),
            "warm_start_uses": wstats["uses"],
            "warm_start_solver_iters_saved": wstats["steps_saved"],
            "warm_start_parity_max_dvol": round(parity_dvol, 9),
            "warm_start_parity_max_dw": round(parity_dw, 9)}


def bench_fleet_mh():
    """Config 12 (fleet_mh): the multi-host fleet over the TCP worker
    transport — 2 simulated hosts x 2 worker subprocesses each behind one
    in-process dispatcher (`Replica.connect`, docs/SERVING.md §10).

    Two phases, both seeded via tools/trafficgen.py:

    - **fleet_mh_qps / per-host latency**: a 2-client-host striped
      open-loop stream (the ``--hosts`` partition) through the healthy
      2x2 fleet; sustained QPS is completions over first-arrival ->
      last-completion, latency percentiles come back per client host,
      and every response must be BITWISE the single-process ``--gulp``
      replay's for the same request id.
    - **kill drill**: a second stream with one simulated host (both its
      workers) SIGKILLed mid-run.  The survivors must answer EVERY
      request, still bitwise, and the merged fleet manifest must count
      the loss and the redispatches with a consistent delivery audit —
      the standing ``>=2-host kill drill survivable`` gate on this cell.
    """
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import shutil
    import signal
    import tempfile
    import threading

    import trafficgen
    from mfm_tpu.config import (
        PipelineConfig, QuarantinePolicy, RiskModelConfig,
    )
    from mfm_tpu.data.artifacts import load_risk_state
    from mfm_tpu.data.synthetic import synthetic_barra_table
    from mfm_tpu.pipeline import run_risk_pipeline, save_pipeline_state
    from mfm_tpu.obs import trace as _trace
    from mfm_tpu.serve import QueryEngine, QueryServer, ServePolicy
    from mfm_tpu.serve.replica import (
        FleetServer, Replica, build_fleet_manifest, worker_cmd,
    )

    hosts, wph = 2, 2                 # 2 simulated hosts x 2 workers
    batch_max, linger = 32, 0.02
    # distributed tracing stays ON (the default) for the whole cell: the
    # bitwise checks below double as the proof that the trace prologue +
    # span piggyback never touch response bytes.  A big ring keeps every
    # merged span for the coverage audit.
    _trace.reset_tracing()
    _trace.set_ring_capacity(65536)
    tmp = tempfile.mkdtemp(prefix="bench_fleet_mh_")
    # workers/reference run with cwd=tmp, so the repo import path (and the
    # platform pin) must ride the environment
    env = {**os.environ, "PYTHONPATH": REPO,
           "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")}
    procs, replicas = [], []
    try:
        # -- a small guarded checkpoint for the workers to serve ------------
        cfg = PipelineConfig(
            risk=RiskModelConfig(eigen_n_sims=64, eigen_sim_length=40,
                                 quarantine=QuarantinePolicy(enabled=True)),
            dtype="float32")
        df, _ = synthetic_barra_table(T=40, N=48, P=4, Q=2, seed=7)
        res = run_risk_pipeline(barra_df=df, config=cfg, with_state=True)
        state_path = os.path.join(tmp, "state.npz")
        save_pipeline_state(state_path, res)
        state, meta = load_risk_state(state_path)
        # one shared benchmark vector so the mix's benchmark slice rides
        # the wire instead of bouncing off admission ("unknown benchmark")
        K = int(QueryEngine.from_risk_state(state, meta).K)
        bvec = np.round(
            0.1 * np.random.default_rng(3).standard_normal(K), 6).tolist()
        bpath = os.path.join(tmp, "benchmarks.json")
        with open(bpath, "w", encoding="utf-8") as fh:
            json.dump({"idx": bvec}, fh)
        eng = QueryEngine.from_risk_state(state, meta,
                                          benchmarks={"idx": bvec})

        # -- 4 TCP workers, grouped into simulated hosts --------------------
        def _boot(j):
            errp = os.path.join(tmp, f"worker{j}.err")
            cmd = worker_cmd(state_path, worker_id=j,
                             policy_args=["--batch-max", str(batch_max),
                                          "--deadline-s", "600",
                                          "--benchmarks", bpath,
                                          "--listen", "127.0.0.1:0"])
            proc = subprocess.Popen(cmd, stdin=subprocess.DEVNULL,
                                    stdout=subprocess.DEVNULL,
                                    stderr=open(errp, "w"), cwd=tmp,
                                    env=env)
            return proc, errp

        boots = [_boot(j) for j in range(hosts * wph)]
        for j, (proc, errp) in enumerate(boots):
            procs.append(proc)
            addr = None
            deadline = time.monotonic() + 180.0
            while time.monotonic() < deadline:
                try:
                    with open(errp, encoding="utf-8") as fh:
                        for ln in fh:
                            if '"worker_listening"' in ln:
                                addr = json.loads(ln)["worker_listening"]
                                break
                except OSError:
                    pass
                if addr is not None or proc.poll() is not None:
                    break
                time.sleep(0.25)
            if addr is None:
                raise AssertionError(
                    f"fleet_mh: worker {j} never announced its port "
                    f"(rc={proc.poll()})")
            whost, _, wport = addr.rpartition(":")
            rep = Replica.connect(j, (whost, int(wport)), io_timeout_s=60.0)
            rep.host = f"host{j // wph}"   # simulated-host grouping
            replicas.append(rep)

        # the admission server must stamp the SAME health the cli workers
        # and the reference replay derive from the manifest beside the
        # checkpoint — responses it answers locally (rejects) carry it
        from mfm_tpu.obs.manifest import (
            ManifestError, manifest_path_for, read_run_manifest,
        )
        try:
            health = read_run_manifest(
                manifest_path_for(state_path))["health"].get(
                    "status", "unknown")
        except (ManifestError, OSError, KeyError):
            health = "unknown"
        server = QueryServer(
            eng, ServePolicy(batch_max=batch_max, queue_max=65536,
                             default_deadline_s=600.0), health=health)

        comps = {"w": {}, "a": {}, "b": {}}
        resps = {"w": {}, "a": {}, "b": {}}
        done = threading.Event()
        target = {"phase": "w", "n": 0}

        def deliver(pairs):
            now = time.monotonic()
            for origin, resp in pairs:
                tag, i = origin
                comps[tag][i] = now
                resps[tag][i] = resp
            if len(resps[target["phase"]]) >= target["n"]:
                done.set()

        # heartbeat off: dead workers are found at dispatch (EOF), and an
        # idle probe inside the timed window would perturb the QPS number;
        # the heartbeat path is the chaos drills' evidence, not this cell's
        fleet = FleetServer(server, replicas, linger_s=linger,
                            deliver=deliver, heartbeat_s=0.0)
        fleet.start()

        mix = (0.55, 0.25, 0.0, 0.20, 0.0)

        def _run_phase(tag, lines, rate):
            target["phase"], target["n"] = tag, len(lines)
            done.clear()
            if len(resps[tag]) >= len(lines):   # pragma: no cover
                done.set()
            sched = trafficgen.open_loop(
                lambda line, i: fleet.submit(line, origin=(tag, i)),
                lines, rate)
            done.wait(timeout=300.0)
            return sched

        def _ref(tag, lines):
            req = os.path.join(tmp, f"req_{tag}.jsonl")
            out = os.path.join(tmp, f"ref_{tag}.jsonl")
            with open(req, "w", encoding="utf-8") as fh:
                fh.write("\n".join(lines) + "\n")
            proc = subprocess.run(
                [sys.executable, "-m", "mfm_tpu.cli", "serve", state_path,
                 "--input", req, "--output", out, "--gulp",
                 "--batch-max", str(batch_max), "--deadline-s", "600",
                 "--benchmarks", bpath],
                capture_output=True, text=True, timeout=600, cwd=tmp,
                env=env)
            if proc.returncode != 0:
                raise AssertionError(f"fleet_mh: reference replay failed "
                                     f"rc={proc.returncode}\n"
                                     f"{proc.stderr[-2000:]}")
            with open(out, encoding="utf-8") as fh:
                return {json.loads(ln)["id"]: ln
                        for ln in fh.read().splitlines() if ln}

        def _mismatches(tag):
            ref = _ref(tag, lines_a if tag == "a" else lines_b)
            return [i for i, resp in resps[tag].items()
                    if json.dumps(resp, sort_keys=True)
                    != ref.get(resp.get("id"))]

        # warm every (kernel-group, bucket) on every worker: fresh-first
        # routing hands the first rounds to each replica in turn
        warm_lines = trafficgen.gen_requests(1, 8 * batch_max, K, mix=mix)
        _run_phase("w", warm_lines, 4000.0)

        # -- phase A: healthy 2x2 fleet, striped across 2 client hosts ------
        n_a, rate_a = 800, 300.0
        lines_a = trafficgen.gen_requests(7, n_a, K, mix=mix)
        sched_a = _run_phase("a", lines_a, rate_a)
        if comps["a"]:
            wall = max(max(comps["a"].values()) - sched_a["t0"], 1e-9)
            mh_qps = len(resps["a"]) / wall
        else:
            mh_qps = 0.0
        lat = trafficgen.latency_stats(sched_a["arrivals"], comps["a"])
        by_host = trafficgen.per_host_latency(sched_a["arrivals"],
                                              comps["a"], hosts)
        mism_a = _mismatches("a")

        # -- phase B: SIGKILL one whole simulated host mid-stream -----------
        n_b, rate_b = 320, 200.0
        lines_b = trafficgen.gen_requests(8, n_b, K, mix=mix)
        victims = [j for j in range(hosts * wph) if j // wph == 1]

        def _kill_host1():
            for j in victims:
                if procs[j].poll() is None:
                    procs[j].send_signal(signal.SIGKILL)

        killer = threading.Timer(0.5 * n_b / rate_b, _kill_host1)
        killer.start()
        _run_phase("b", lines_b, rate_b)
        killer.cancel()
        _kill_host1()                  # fire even if the stream outran it
        mism_b = _mismatches("b")

        fleet.stop()
        fm = build_fleet_manifest({"bench": "fleet_mh",
                                   "n": n_a + n_b + len(warm_lines)},
                                  fleet, tmp)
        fleet.close_replicas()
        survived = (not mism_b and len(resps["b"]) == n_b
                    and fm["audit"]["consistent"])

        # -- distributed-trace audit: ONE corrected timeline per request ----
        # Every healthy-phase request id must appear in the merged ring
        # with BOTH a frontend-local span and a worker child span shipped
        # over the wire (stamped with its clock correction) — the >=95%
        # coverage gate on this cell.  The merged ring must also render a
        # Perfetto-loadable Chrome trace via the atomic writer.
        front_tids, worker_tids, n_skew = set(), set(), 0
        merged = _trace.spans()
        for sp in merged:
            if sp.trace_id is None:
                continue
            if "worker" in sp.attrs:          # ingested over the wire
                worker_tids.add(sp.trace_id)
                if sp.attrs.get("clock_skew") == "uncorrectable":
                    n_skew += 1
            else:
                front_tids.add(sp.trace_id)
        a_tids = [resp.get("trace_id") for resp in resps["a"].values()]
        a_tids = [t for t in a_tids if t]
        covered = sum(1 for t in a_tids
                      if t in front_tids and t in worker_tids)
        coverage = covered / max(1, len(a_tids))
        trace_path = _trace.write_chrome_trace(
            os.path.join(tmp, "fleet_trace.json"))
        with open(trace_path, encoding="utf-8") as fh:
            trace_events = _trace.parse_chrome_trace(fh.read())
        return {"metric": "fleet_mh_serving_throughput",
                "value": round(mh_qps),
                "unit": "requests/s", "vs_baseline": None,
                "k_factors": K, "hosts": hosts, "workers_per_host": wph,
                "n_requests": n_a, "offered_rate_rps": rate_a,
                "linger_s": linger, "batch_max": batch_max,
                "fleet_mh_qps": round(mh_qps, 1),
                "fleet_mh_p50_latency_s": lat.get("p50_s"),
                "fleet_mh_p99_latency_s": lat.get("p99_s"),
                "per_host_latency": by_host,
                "bitwise_identical": not mism_a,
                "bitwise_mismatches": len(mism_a),
                "unanswered": lat.get("unanswered"),
                "kill_drill": {
                    "n_requests": n_b,
                    "killed_host": "host1",
                    "killed_workers": victims,
                    "answered": len(resps["b"]),
                    "bitwise_identical": not mism_b,
                    "redispatches": fm["transport"]["redispatches"],
                    "lost_replicas": [r["replica"] for r in fm["replicas"]
                                      if r["lost"]],
                    "audit_consistent": fm["audit"]["consistent"],
                    "survived": survived,
                },
                "trace": {
                    "request_coverage_frac": round(coverage, 4),
                    "coverage_ok": coverage >= 0.95,
                    "requests_with_trace_id": len(a_tids),
                    "merged_spans": len(merged),
                    "chrome_events": len(trace_events),
                    "uncorrectable_skew_spans": n_skew,
                },
                "transport": fm["transport"]}
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        _trace.reset_tracing()
        shutil.rmtree(tmp, ignore_errors=True)


CONFIGS = {
    "riskmodel": bench_riskmodel,
    "chunk_sweep": bench_chunk_sweep,
    "beta": bench_beta,
    "factors": bench_factors,
    "alla": bench_alla,
    "alpha": bench_alpha,
    "alpha_alla": bench_alpha_alla,
    "query": bench_query,
    "scenario": bench_scenario,
    "sweep": bench_sweep,
    "grad": bench_grad,
    "fleet": bench_fleet,
    "cache": bench_cache,
    "fleet_mh": bench_fleet_mh,
}


def _probe_backend(attempts=None, timeout=None, extra_env=None):
    """Ask (in a subprocess, so a hung TPU plugin can't wedge this process)
    which backend JAX actually brings up.  Round 1 died here: the axon TPU
    client constructor blocks forever when the tunnel is down, and the first
    `device_put` raised with no JSON emitted (VERDICT.md weak #2).  Returns
    (platform|None, error|None).

    Growing backoff (10 x 90 s probes + 225 s of sleeps = ~19 min worst
    case at the default 10 attempts, overridable via BENCH_PROBE_ATTEMPTS)
    rides out a *flapping* tunnel — observed twice mid-round-4, dropping
    and recovering on a minutes-to-tens-of-minutes scale.  The stakes are
    asymmetric: a CPU number recorded under the TPU metric misstates the
    framework for a whole round, while waiting costs only driver minutes —
    though a genuinely dead tunnel still ends in the CPU-fallback record
    (with a structured ``probe`` field) rather than a hang.

    The per-probe timeout defaults to 90 s, overridable via
    ``MFM_PROBE_TIMEOUT_S`` (same tolerant parse as the attempts knob)."""
    if attempts is None:
        raw = os.environ.get("BENCH_PROBE_ATTEMPTS", "")
        try:
            attempts = max(1, int(raw))
        except ValueError:
            # a typo'd override must not crash before the JSON record, and
            # 0/negative must not silently skip the probe
            attempts = 10
    if timeout is None:
        raw = os.environ.get("MFM_PROBE_TIMEOUT_S", "")
        try:
            timeout = max(1.0, float(raw))
        except ValueError:
            timeout = 90.0
    # extra_env overlays os.environ in the child (e.g. mirroring an
    # in-process JAX_PLATFORMS config pin for __graft_entry__'s gate probe)
    env = {**os.environ, **extra_env} if extra_env else None
    err = None
    for i in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print('PLATFORM=' + jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=timeout, cwd=REPO,
                env=env,
            )
            for line in proc.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    return line.split("=", 1)[1], None
            tail = (proc.stderr or "").strip().splitlines()
            err = tail[-1] if tail else f"probe rc={proc.returncode}"
        except subprocess.TimeoutExpired:
            err = f"backend probe timed out after {timeout}s"
        if i + 1 < attempts:
            time.sleep(5 * (i + 1))
    return None, err


def _run_inner(config, platform, timeout, universe=None, devices=None):
    """Run one bench config in a subprocess; return (record|None, error|None).
    The subprocess prints the JSON record as its last stdout line."""
    cmd = [sys.executable, os.path.abspath(__file__), "--config", config,
           "--inner"]
    if platform:
        cmd += ["--platform", platform]
    if universe is not None:
        cmd += ["--universe", str(universe)]
    if devices is not None:
        cmd += ["--devices", str(devices)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, cwd=REPO)
    except subprocess.TimeoutExpired:
        return None, f"bench subprocess timed out after {timeout}s"
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
            if isinstance(rec, dict) and "metric" in rec:
                return rec, None
        except json.JSONDecodeError:
            continue
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return None, (tail[-1] if tail else f"bench rc={proc.returncode}")


def _inner_main(args):
    if args.devices and args.devices > 1:
        # must land before the FIRST jax import in this process — the
        # virtual host-device count is read once at backend bring-up.
        # An explicit count already in the env wins (conftest/CI pins).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.devices}").strip()
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        import jax
        # the config API wins over the axon site hook's env pin
        jax.config.update("jax_platforms", args.platform)
    from mfm_tpu.utils.cache import enable_persistent_compilation_cache

    # cross-process XLA cache: a rerun's "compile_s" measures the cache-hit
    # path (deserialize instead of compile) — the per-machine number
    # BASELINE.md documents next to the cold compile
    cache_dir = enable_persistent_compilation_cache()
    import inspect
    fn = CONFIGS[args.config]
    params = inspect.signature(fn).parameters
    kw = {}
    if args.universe is not None:
        if "universe" not in params:
            raise SystemExit(
                f"config {args.config!r} has no --universe axis")
        kw["universe"] = args.universe
    if args.devices is not None:
        if "devices" not in params:
            raise SystemExit(
                f"config {args.config!r} has no --devices axis")
        kw["devices"] = args.devices
    rec = fn(**kw)
    if "compile_s" in rec:
        rec["compilation_cache"] = cache_dir
    import jax
    rec["backend"] = jax.devices()[0].platform
    print(json.dumps(rec))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="riskmodel", choices=sorted(CONFIGS))
    ap.add_argument("--inner", action="store_true",
                    help="run the bench in-process (no probe/retry harness)")
    ap.add_argument("--platform", default=None,
                    help="pin a JAX platform (e.g. cpu) before running")
    ap.add_argument("--universe", default=None, metavar="U",
                    help="workload universe for configs with a universe "
                         "axis (riskmodel/alla): csi300, alla, or a stock "
                         "count N (data/synthetic.py::resolve_universe)")
    ap.add_argument("--devices", type=int, default=None, metavar="D",
                    help="run the config on a D-device ('date','stock') "
                         "mesh; on CPU hosts this sets XLA_FLAGS="
                         "--xla_force_host_platform_device_count=D in the "
                         "inner process (same pjit code path as TPU)")
    ap.add_argument("--timeout", type=float, default=2400.0,
                    help="per-attempt subprocess timeout, seconds")
    ap.add_argument("--profile-dir", default=None, metavar="DIR",
                    help="config-1 only: capture one jax.profiler trace of "
                         "the compiled e2e step into DIR (the roofline "
                         "evidence artifact; view with xprof/tensorboard)")
    ap.add_argument("--jax-profile", default=None, metavar="DIR",
                    help="synonym of --profile-dir (the device-profiling "
                         "flag name shared with the risk/pipeline CLIs)")
    ap.add_argument("--compare", action="store_true",
                    help="after the run, gate the record against the "
                         "BENCH_r*.json trajectory (tools/perfgate.py) and "
                         "exit non-zero on a perf regression")
    args = ap.parse_args()
    prof_dir = args.profile_dir or args.jax_profile
    if prof_dir:
        # inherited by the inner bench subprocess
        os.environ["BENCH_PROFILE_DIR"] = os.path.abspath(prof_dir)

    if args.inner:
        _inner_main(args)
        return

    errors = []
    if args.platform:
        # an explicit pin is an explicit pin: no silent fallback — a failed
        # TPU run must not emit a CPU timing under the same metric name
        probe_err = None
        attempts = [args.platform]
    else:
        platform, probe_err = _probe_backend()
        # probe OK -> run on the default backend (don't re-pin: the plugin
        # name, e.g. 'axon', need not match device.platform, e.g. 'tpu');
        # probe dead -> go straight to the CPU fallback.  Unpinned runs
        # always end with a CPU attempt so the driver records something.
        attempts = ([None, "cpu"] if platform else ["cpu"])
    rec = None
    for plat in attempts:
        rec, err = _run_inner(args.config, plat, args.timeout,
                              universe=args.universe, devices=args.devices)
        if rec is not None:
            break
        errors.append(f"{plat or 'default'}: {err}")
    if rec is None:
        # nothing ran to completion — still emit one parseable JSON line
        rec = {"metric": f"{args.config}_wall", "value": None, "unit": "s",
               "vs_baseline": None, "backend": None}
    if probe_err:
        # structured, not an ``errors`` entry: a probe timeout is an
        # environment statement (the tunnel never answered), not a bench
        # failure — downstream tooling keys off rec["probe"] == "timeout"
        rec["probe"] = ("timeout" if "timed out" in probe_err
                        else probe_err)
    if errors:
        rec["errors"] = errors
    print(json.dumps(rec))
    if args.compare:
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import perfgate
        verdict = perfgate.gate_record(rec, perfgate.load_trajectory(REPO))
        print(perfgate.format_report(verdict), file=sys.stderr)
        if verdict["regressions"]:
            sys.exit(1)


if __name__ == "__main__":
    main()
