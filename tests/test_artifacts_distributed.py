"""Stage artifacts and single-process distributed helpers."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mfm_tpu.data.artifacts import load_artifact, save_artifact, save_risk_outputs
from mfm_tpu.parallel.distributed import (
    initialize,
    make_global_mesh,
    process_date_slice,
)


def test_artifact_roundtrip(tmp_path):
    p = str(tmp_path / "stage.npz")
    arrays = {"a": np.arange(6.0).reshape(2, 3), "b": jnp.ones((4,))}
    save_artifact(p, arrays, meta={"stage": "nw_cov", "T": 2})
    out, meta = load_artifact(p)
    np.testing.assert_array_equal(out["a"], np.arange(6.0).reshape(2, 3))
    np.testing.assert_array_equal(out["b"], np.ones((4,)))
    assert meta["stage"] == "nw_cov" and meta["format"] == 1


def test_save_artifact_failure_leaves_no_tmp_file(tmp_path, monkeypatch):
    """A write that dies mid-savez must clean up its .tmp.npz and re-raise —
    stray temp files confuse globbing consumers and retention scripts."""
    import mfm_tpu.data.artifacts as art

    def exploding_savez(tmp, **payload):
        open(tmp, "wb").write(b"partial")  # half-written temp, then failure
        raise OSError("disk full")

    monkeypatch.setattr(art.np, "savez_compressed", exploding_savez)
    p = str(tmp_path / "stage.npz")
    with pytest.raises(OSError, match="disk full"):
        save_artifact(p, {"a": np.ones(3)})
    assert list(tmp_path.iterdir()) == []  # no stage.npz, no stage.npz.tmp.npz


def test_risk_outputs_roundtrip(tmp_path):
    from mfm_tpu.config import RiskModelConfig
    from mfm_tpu.models.risk_model import RiskModel
    from __graft_entry__ import _synthetic_risk_inputs

    args = _synthetic_risk_inputs(20, 12, 3, 2, dtype=jnp.float64, seed=0)
    rm = RiskModel(*args, n_industries=3,
                   config=RiskModelConfig(eigen_n_sims=4, eigen_sim_length=40))
    out = rm.run()
    p = str(tmp_path / "risk.npz")
    save_risk_outputs(p, out, meta={"universe": "test"})
    arrays, meta = load_artifact(p)
    np.testing.assert_allclose(arrays["factor_ret"], np.asarray(out.factor_ret))
    np.testing.assert_allclose(arrays["lamb"], np.asarray(out.lamb))
    assert meta["universe"] == "test"


def test_initialize_noop_single_process():
    assert initialize() is False  # no coordinator configured -> single process


def test_make_global_mesh_shapes():
    mesh = make_global_mesh(n_stock=2)
    assert mesh.axis_names == ("date", "stock")
    assert mesh.devices.shape == (4, 2)
    with pytest.raises(ValueError):
        make_global_mesh(n_stock=3)


def test_process_date_slice_covers_range():
    s = process_date_slice(100)
    assert s == slice(0, 100)  # single process owns everything
