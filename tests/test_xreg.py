"""Parity: batched constrained WLS vs the serial golden cross-section."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mfm_tpu.data.barra import barra_frame_to_arrays
from mfm_tpu.data.synthetic import synthetic_barra_table
from mfm_tpu.ops.xreg import cross_section_regress, regress_panel

import golden


@pytest.fixture(scope="module")
def barra():
    df, style_names = synthetic_barra_table(T=40, N=60, P=6, Q=4, seed=1, missing=0.05)
    arrays = barra_frame_to_arrays(df, style_names=style_names)
    gold = golden.golden_reg_by_time(df, style_names, arrays.industry_codes)
    return df, arrays, gold


def test_factor_returns_match_golden(barra):
    _, a, gold = barra
    res = regress_panel(
        jnp.asarray(a.ret), jnp.asarray(a.cap), jnp.asarray(a.styles),
        jnp.asarray(a.industry), jnp.asarray(a.valid),
        n_industries=a.n_industries,
    )
    for t, date in enumerate(a.dates):
        np.testing.assert_allclose(
            np.asarray(res.factor_ret[t]), gold[date]["f"], rtol=1e-8, atol=1e-12
        )
        np.testing.assert_allclose(
            float(res.r2[t]), gold[date]["r2"], rtol=1e-8
        )


def test_specific_returns_match_golden(barra):
    _, a, gold = barra
    res = regress_panel(
        jnp.asarray(a.ret), jnp.asarray(a.cap), jnp.asarray(a.styles),
        jnp.asarray(a.industry), jnp.asarray(a.valid),
        n_industries=a.n_industries,
    )
    spec = np.asarray(res.specific_ret)
    for t, date in enumerate(a.dates):
        g = gold[date]
        # golden rows are sorted by stockname; so are our columns
        cols = np.searchsorted(a.stocks, g["stocks"])
        np.testing.assert_allclose(spec[t, cols], g["spec"], rtol=1e-7, atol=1e-12)
        # everything outside the date's universe is NaN
        outside = np.setdiff1d(np.arange(a.stocks.size), cols)
        assert np.all(np.isnan(spec[t, outside]))


def test_pure_factor_exposure_identity(barra):
    """Pure-factor portfolios must have unit exposure to their own factor in
    the constrained subspace (CrossSection.py:104): Omega @ X @ R == R."""
    _, a, gold = barra
    t = 7
    res = cross_section_regress(
        jnp.asarray(a.ret[t]), jnp.asarray(a.cap[t]), jnp.asarray(a.styles[t]),
        jnp.asarray(a.industry[t]), jnp.asarray(a.valid[t]),
        n_industries=a.n_industries, return_exposure=True,
    )
    expo = np.asarray(res.exposure)
    # country exposure of country portfolio is 1; style block is identity
    assert abs(expo[0, 0] - 1.0) < 1e-8
    Q = a.styles.shape[-1]
    np.testing.assert_allclose(expo[-Q:, -Q:], np.eye(Q), atol=1e-8)


def test_no_industry_branch(barra):
    """P=0 runs the unconstrained branch (CrossSection.py:95-98)."""
    _, a, _ = barra
    t = 3
    v = a.valid[t]
    res = cross_section_regress(
        jnp.asarray(a.ret[t]), jnp.asarray(a.cap[t]), jnp.asarray(a.styles[t]),
        jnp.asarray(a.industry[t]), jnp.asarray(v),
        n_industries=0,
    )
    ret, cap, sty = a.ret[t][v], a.cap[t][v], a.styles[t][v]
    f, spec, r2 = golden.golden_cross_section(ret, cap, sty, np.zeros((v.sum(), 0)))
    np.testing.assert_allclose(np.asarray(res.factor_ret), f, rtol=1e-8, atol=1e-12)
    np.testing.assert_allclose(float(res.r2), r2, rtol=1e-8)


def test_jit_and_vmap_compose(barra):
    _, a, _ = barra
    fn = jax.jit(
        lambda r, c, s, i, v: regress_panel(
            r, c, s, i, v, n_industries=a.n_industries
        ).factor_ret
    )
    out = fn(
        jnp.asarray(a.ret), jnp.asarray(a.cap), jnp.asarray(a.styles),
        jnp.asarray(a.industry), jnp.asarray(a.valid),
    )
    assert out.shape == (a.ret.shape[0], 1 + a.n_industries + a.styles.shape[-1])
    assert np.all(np.isfinite(np.asarray(out)))
