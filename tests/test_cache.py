"""Persistent XLA compilation cache (utils/cache.py): entries must land in
the configured directory so a second PROCESS deserializes instead of
re-compiling (the config-5 32.5 s compile, round-4 VERDICT weak #6)."""

import os


def test_cache_dir_populated_and_off_switch(tmp_path, monkeypatch):
    import jax
    import jax.numpy as jnp

    from mfm_tpu.utils.cache import enable_persistent_compilation_cache

    d = str(tmp_path / "xla")
    try:
        got = enable_persistent_compilation_cache(d, min_compile_secs=0.0)
        assert got == d and os.path.isdir(d)

        f = jax.jit(lambda x: jnp.tanh(x) @ x.T)
        f(jnp.ones((32, 16))).block_until_ready()
        assert os.listdir(d), "no cache entries written"

        monkeypatch.setenv("MFM_COMPILATION_CACHE", "off")
        assert enable_persistent_compilation_cache() is None
    finally:
        # tmp_path is deleted after the test — the global config must not
        # keep pointing the rest of the suite's compiles at it, and the
        # initialized cache OBJECT must be dropped too (it holds the dir)
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        from jax.experimental.compilation_cache import compilation_cache
        compilation_cache.reset_cache()
