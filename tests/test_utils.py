"""Observability utilities."""

import numpy as np
import jax
import jax.numpy as jnp

from mfm_tpu.utils.obs import StageTimer, determinism_check, force


def test_force_returns_finite_checksum():
    x = {"a": jnp.ones((4, 4)), "b": jnp.asarray([jnp.nan, 1.0])}
    assert force(x) == 17.0


def test_stage_timer_accumulates():
    t = StageTimer("test")
    with t.stage("s1"):
        pass
    with t.stage("s1"):
        pass
    s = t.summary()
    assert "s1" in s and s["total_s"] >= 0


def test_determinism_check_keyed_random():
    def fn():
        k = jax.random.key(42)
        return jax.random.normal(k, (8, 8)) @ jax.random.normal(k, (8, 8))

    assert determinism_check(fn)


def test_determinism_check_catches_divergence():
    state = {"n": 0}

    def fn():
        state["n"] += 1
        return np.array([state["n"]], float)

    assert not determinism_check(fn)


def test_riskmodel_pipeline_is_deterministic():
    from mfm_tpu.config import RiskModelConfig
    from mfm_tpu.models.risk_model import RiskModel
    from __graft_entry__ import _synthetic_risk_inputs

    args = _synthetic_risk_inputs(24, 16, 3, 2, dtype=jnp.float64, seed=5)
    cfg = RiskModelConfig(eigen_n_sims=4, eigen_sim_length=50)

    def run():
        rm = RiskModel(*args, n_industries=3, config=cfg)
        out = rm.run()
        return out.factor_ret, out.vr_cov, out.lamb

    assert determinism_check(run)
