"""Serving fleet (mfm_tpu/serve/{coalesce,frontend,replica,transport}.py):
coalesced mixed-type batches bitwise-equal to the single-threaded loop, the
linger/full/eof flush triggers, the <=1-compile steady state with the
coalescer on, the worker wire protocol (pipe AND TCP parity), death
re-dispatch + fence-audit quarantine + the merged-manifest delivery audit,
heartbeat-miss wedge detection before dispatch, rolling zero-downtime
rollouts (no dropped requests, no generation-straddling batch, failed
fence audits quarantined), live /metrics//healthz worker-shard merging,
the thread-safety hammer for the breaker and the metrics registry,
fsync-on-emit, and the socket front end under concurrent clients."""

import io
import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from mfm_tpu.obs import instrument as _obs
from mfm_tpu.serve import (
    CircuitBreaker,
    Coalescer,
    FleetServer,
    QueryEngine,
    QueryServer,
    ReplicaDeadError,
    ReplicaWedgedError,
    ServePolicy,
    SocketFrontend,
)
from mfm_tpu.serve.replica import (
    CONTROL_KEY,
    Replica,
    build_fleet_manifest,
    replica_env,
    run_worker,
)
from mfm_tpu.serve.transport import serve_worker_socket

K = 4


def _engine(scale=1.0):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((K, K)) / 2
    cov = (a @ a.T + 1e-3 * np.eye(K)) * 1e-4 * scale
    return QueryEngine(cov, factor_names=["country", "ind0", "size", "mom"],
                       benchmarks={"idx": rng.standard_normal(K)})


def _server(batch_max=64, **kw):
    policy = ServePolicy(batch_max=batch_max, queue_max=4096,
                         default_deadline_s=600.0)
    return QueryServer(_engine(), policy, health="ok",
                       scenarios={"stress": _engine(scale=1.44)}, **kw)


def _mixed_lines(n, seed=3):
    """Seeded mixed request stream: plain, benchmark, scenario-tagged and
    both construct solvers, ids m0..m{n-1}."""
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        req = {"id": f"m{i}",
               "weights": np.round(0.2 * rng.standard_normal(K), 6).tolist(),
               "deadline_s": 600.0}
        kind = i % 5
        if kind == 1:
            req["benchmark"] = "idx"
        elif kind == 2:
            req["scenario"] = "stress"
        elif kind == 3:
            req["construct"] = {"solver": "min_vol"}
        elif kind == 4:
            req["construct"] = {"solver": "risk_parity"}
        lines.append(json.dumps(req, sort_keys=True))
    return lines


def _sequential_by_id(lines, batch_max=64):
    out = io.StringIO()
    _server(batch_max=batch_max).run(list(lines), out, gulp=True)
    return {json.loads(ln)["id"]: ln for ln in out.getvalue().splitlines()}


class Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


# -- coalescer: bitwise equality + triggers ----------------------------------

@pytest.mark.parametrize("batch_max", [4, 64])
def test_coalescer_mixed_bitwise_vs_sequential(batch_max):
    # batch_max=4 exercises repeated full-trigger flushes (several
    # bucket-8 rounds); 64 exercises one eof flush spanning buckets
    lines = _mixed_lines(22)
    ref = _sequential_by_id(lines, batch_max=batch_max)
    co = Coalescer(_server(batch_max=batch_max), linger_s=10.0)
    got = {}
    for i, ln in enumerate(lines):
        for origin, resp in co.submit(ln, origin=i):
            got[origin] = resp
    for origin, resp in co.stop():
        got[origin] = resp
    assert len(got) == len(lines)
    for i, ln in enumerate(lines):
        rid = json.loads(ln)["id"]
        assert json.dumps(got[i], sort_keys=True) == ref[rid], \
            f"coalesced response for {rid} diverges from sequential loop"


def test_coalescer_full_linger_eof_triggers():
    clk = Clock()
    co = Coalescer(_server(batch_max=4), linger_s=0.5, clock=clk)
    t0 = _obs.fleet_summary_from_registry()["coalesce_flushes"]
    # full: the 4th admitted request flushes immediately
    pairs = []
    for i, ln in enumerate(_mixed_lines(4, seed=5)):
        pairs += co.submit(ln, origin=i)
    assert len(pairs) == 4 and co.queued() == 0
    # linger: one queued request, poll is a no-op until the budget expires
    co.submit(_mixed_lines(1, seed=6)[0], origin=99)
    assert co.poll() == [] and co.queued() == 1
    assert co.next_deadline() == pytest.approx(clk.t + 0.5)
    clk.t += 0.6
    lingered = co.poll()
    assert [o for o, _ in lingered] == [99]
    # eof: stop drains the tail
    co.submit(_mixed_lines(1, seed=7)[0], origin=7)
    assert [o for o, _ in co.stop()] == [7]
    t1 = _obs.fleet_summary_from_registry()["coalesce_flushes"]

    def delta(trig):
        return t1.get(trig, 0) - t0.get(trig, 0)
    assert delta("full") == 1 and delta("linger") == 1 and delta("eof") == 1


def test_coalescer_steady_state_single_compile():
    """S4: with the coalescer on, a warmed (type, bucket) shape never
    recompiles — repeated same-shape flushes run with zero new jit
    compiles."""
    from mfm_tpu.utils.contracts import assert_max_compiles

    co = Coalescer(_server(batch_max=64), linger_s=10.0)
    lines = _mixed_lines(20, seed=11)   # all five kinds, buckets warmed
    for i, ln in enumerate(lines):
        co.submit(ln, origin=i)
    co.flush()
    with assert_max_compiles(0, "coalesced steady state"):
        for round_ in range(3):
            for i, ln in enumerate(_mixed_lines(20, seed=20 + round_)):
                co.submit(ln, origin=i)
            co.flush()
    co.stop()


# -- S1: thread-safety hammers ------------------------------------------------

def test_breaker_thread_hammer():
    """8 threads x 300 failures each: no lost increment — the breaker must
    be OPEN long before the end, and the final failure count is exact when
    kept below the threshold."""
    br = CircuitBreaker(failures=8 * 300, cooldown_s=1e9)
    n_threads, per = 8, 300

    def hammer():
        for _ in range(per - 1):
            br.record_failure()

    ts = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # (per - 1) * n failures < threshold: every increment must have landed
    # and none may have tripped it early
    assert br.state == "closed"
    assert br._consecutive == n_threads * (per - 1)
    br.record_failure()
    for _ in range(n_threads - 1):
        br.record_failure()
    assert br.state == "open" and br.open_reason == "failures"


def test_metrics_registry_thread_hammer():
    """Concurrent counter bumps and histogram observes tally exactly."""
    before = _obs.fleet_summary_from_registry()
    n_threads, per = 8, 250

    def hammer(idx):
        for i in range(per):
            _obs.record_fleet_dispatch(idx % 2, 1)
            _obs.record_coalesce_flush(4, 8, "full", 0.001)

    ts = [threading.Thread(target=hammer, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    after = _obs.fleet_summary_from_registry()
    assert after["dispatch_total"] - before["dispatch_total"] \
        == n_threads * per
    assert (after["coalesce_flushes_total"]
            - before["coalesce_flushes_total"]) == n_threads * per


# -- S2: fsync on emit --------------------------------------------------------

def test_fsync_emits_policy(tmp_path, monkeypatch):
    calls = []
    real_fsync = os.fsync

    def counting_fsync(fd):
        calls.append(fd)
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", counting_fsync)
    lines = _mixed_lines(4, seed=9)
    out_path = tmp_path / "resp.jsonl"
    policy = ServePolicy(batch_max=4, queue_max=64,
                         default_deadline_s=600.0, fsync_emits=True)
    server = QueryServer(_engine(), policy, health="ok")
    with open(out_path, "w") as fh:
        server.run(list(lines), fh, gulp=True)
    assert calls, "fsync_emits=True must fsync the response stream"
    assert len(out_path.read_text().splitlines()) == 4
    # a non-file sink (StringIO raises UnsupportedOperation) is tolerated
    server2 = QueryServer(_engine(), policy, health="ok")
    buf = io.StringIO()
    server2.run(list(_mixed_lines(2, seed=10)), buf, gulp=True)
    assert len(buf.getvalue().splitlines()) == 2


# -- worker wire protocol -----------------------------------------------------

def test_run_worker_wire_protocol():
    """Envelopes carry per-batch ordinals, every flush ends with the
    sentinel, seq resets between batches, and an EOF without a final flush
    still answers the tail."""
    lines = _mixed_lines(7, seed=13)
    flush = json.dumps({CONTROL_KEY: "flush"})
    in_text = "\n".join(lines[:3] + [flush] + lines[3:5] + [flush]
                        + lines[5:]) + "\n"   # tail: EOF, no flush
    out = io.StringIO()
    summary = run_worker(_server(batch_max=8), io.StringIO(in_text), out)
    assert isinstance(summary, dict) and "requests_total" in summary
    envs = [json.loads(ln) for ln in out.getvalue().splitlines()]
    sentinels = [e for e in envs if e.get(CONTROL_KEY) == "flushed"]
    assert [s["n"] for s in sentinels] == [3, 2]
    resps = [e for e in envs if CONTROL_KEY not in e]
    assert [e["seq"] for e in resps] == [0, 1, 2, 0, 1, 0, 1]
    ref = _sequential_by_id(lines, batch_max=8)
    for env, ln in zip(resps, lines[:3] + lines[3:5] + lines[5:]):
        rid = json.loads(ln)["id"]
        assert json.dumps(env["resp"], sort_keys=True) == ref[rid]


def test_control_key_rejected_at_admission():
    """A request smuggling the reserved __fleet__ key dead-letters at
    admission (never forwarded to a worker), with the schema reason."""
    server = _server()
    spoof = json.dumps({"id": "evil", "weights": [0.1] * K,
                        "__fleet__": "flush"}, sort_keys=True)
    resps = server.submit_line(spoof)
    assert len(resps) == 1
    assert resps[0]["outcome"] == "dead_letter"
    assert resps[0]["id"] == "evil"
    assert "schema" in resps[0]["reasons"]
    assert not server._queue


def test_worker_control_frame_not_spoofable():
    """Only an object that is EXACTLY {__fleet__: ...} is a control frame:
    a request line carrying the key among other keys consumes its seq
    ordinal and answers dead_letter — no mid-batch flush, no ordinal
    shift, no cross-client response misrouting."""
    lines = _mixed_lines(2, seed=31)
    spoof = json.dumps({"__fleet__": "flush", "id": "evil",
                        "weights": [0.1] * K}, sort_keys=True)
    assert spoof.startswith('{"__fleet__"')   # worst case for the prefix scan
    flush = json.dumps({CONTROL_KEY: "flush"})
    in_text = "\n".join([lines[0], spoof, lines[1], flush]) + "\n"
    out = io.StringIO()
    run_worker(_server(batch_max=8), io.StringIO(in_text), out)
    envs = [json.loads(ln) for ln in out.getvalue().splitlines()]
    sentinels = [e for e in envs if e.get(CONTROL_KEY) == "flushed"]
    assert [s["n"] for s in sentinels] == [3]
    resps = {e["seq"]: e["resp"] for e in envs if CONTROL_KEY not in e}
    assert set(resps) == {0, 1, 2}
    assert resps[1]["outcome"] == "dead_letter"
    assert resps[0]["outcome"] == "ok" and resps[2]["outcome"] == "ok"
    ref = _sequential_by_id(lines, batch_max=8)
    for seq, ln in ((0, lines[0]), (2, lines[1])):
        rid = json.loads(ln)["id"]
        assert json.dumps(resps[seq], sort_keys=True) == ref[rid]


def test_hold_fence_worker_refences_only_on_reload_frame():
    """A --hold-fence worker (poll_on_flush=False) must not move its
    generation on flush, and MUST re-fence and report the NEW generation
    on the frontend's reload frame — if the reply carried the startup
    generation instead, _roll_fleet would treat it as disagreement and
    re-roll forever while the worker kept pricing the old engine."""
    pending = {"gen": None}
    polls = []

    def reload_fn():
        polls.append(1)
        if pending["gen"] is None:
            return None
        return {"generation": pending["gen"]}

    server = _server(batch_max=8, reload_fn=reload_fn)
    server.generation = 1
    flush = json.dumps({CONTROL_KEY: "flush"})
    reload_frame = json.dumps({CONTROL_KEY: "reload"})
    lines = _mixed_lines(2, seed=37)
    in_text = "\n".join([lines[0], flush, reload_frame,
                         lines[1], flush]) + "\n"
    pending["gen"] = 2
    out = io.StringIO()
    run_worker(server, io.StringIO(in_text), out, poll_on_flush=False)
    envs = [json.loads(ln) for ln in out.getvalue().splitlines()]
    reloaded = [e for e in envs if e.get(CONTROL_KEY) == "reloaded"]
    assert len(reloaded) == 1
    assert reloaded[0]["ok"] is True
    # the frame reply must carry the PENDING generation, not the startup
    # one: this is what the frontend's agreement check reads
    assert reloaded[0]["generation"] == 2
    assert server.generation == 2
    # flushes (two of them) and EOF never polled: ONLY the reload frame
    assert len(polls) == 1


# -- fleet dispatch: death, quarantine, outage, manifest ----------------------

class _StubProc:
    def __init__(self):
        self.rc = None

    def poll(self):
        return self.rc


class _StubReplica:
    """Duck-typed Replica: answers through a real in-process worker server
    (so responses stay bitwise-comparable), or fails on demand."""

    def __init__(self, idx, mode="ok"):
        self.idx = idx
        self.mode = mode
        self.quarantined = False
        self.delivered = {}
        self.proc = _StubProc()
        self._wserver = _server(batch_max=64)

    @property
    def alive(self):
        return not self.quarantined and self.proc.poll() is None

    def run_batch(self, lines):
        if self.mode == "dead":
            self.proc.rc = -9
            raise ReplicaDeadError(f"replica {self.idx}: EOF mid-batch")
        if self.mode == "fence":
            return {i: {"id": json.loads(ln)["id"], "ok": False,
                        "outcome": "rejected", "breaker": "fence_audit"}
                    for i, ln in enumerate(lines)}
        resps = {}
        for i, ln in enumerate(lines):
            for o, r in self._wserver.submit_line_routed(ln, origin=i):
                resps[o] = r
        while self._wserver._queue:
            for o, r in self._wserver.drain_routed():
                resps[o] = r
        return resps

    def close(self, timeout=None):
        if self.proc.rc is None:
            self.proc.rc = 0
        return self.proc.rc


def _fleet_run(replicas, n=8, batch_max=4):
    fleet = FleetServer(_server(batch_max=batch_max), replicas,
                        linger_s=10.0)
    lines = _mixed_lines(n, seed=17)
    got = {}
    for i, ln in enumerate(lines):
        for o, r in fleet.submit(ln, origin=i):
            got[o] = r
    for o, r in fleet.stop():
        got[o] = r
    return fleet, lines, got


def test_fleet_death_redispatch_bitwise(tmp_path):
    """A replica dying mid-batch loses nothing: its batch re-dispatches to
    a survivor and every response matches the single-process loop."""
    dead = _StubReplica(0, mode="dead")
    ok = _StubReplica(1)
    fleet, lines, got = _fleet_run([dead, ok])
    assert len(got) == len(lines)
    ref = _sequential_by_id(lines, batch_max=4)
    for i, ln in enumerate(lines):
        rid = json.loads(ln)["id"]
        assert json.dumps(got[i], sort_keys=True) == ref[rid]
    fleet.close_replicas()
    fm = build_fleet_manifest({}, fleet, str(tmp_path))
    assert fm["audit"]["consistent"]
    assert fm["audit"]["accepted_total"] == len(lines)
    by_idx = {r["replica"]: r for r in fm["replicas"]}
    assert by_idx[0]["lost"] and by_idx[0]["outcomes_total"] == 0
    assert by_idx[0]["manifest_shard"] is None
    assert by_idx[1]["outcomes_total"] == len(lines)


def test_fleet_quarantine_on_fence_audit(tmp_path):
    """An all-fence_audit batch quarantines the replica WITHOUT delivering
    the rejections; the batch re-dispatches to a healthy replica."""
    fenced = _StubReplica(0, mode="fence")
    ok = _StubReplica(1)
    fleet, lines, got = _fleet_run([fenced, ok])
    assert fenced.quarantined and not fenced.alive
    assert all(r.get("breaker") != "fence_audit" for r in got.values())
    assert len(got) == len(lines)
    fleet.close_replicas()
    fm = build_fleet_manifest({}, fleet, str(tmp_path))
    assert fm["audit"]["consistent"]
    by_idx = {r["replica"]: r for r in fm["replicas"]}
    assert by_idx[0]["quarantined"] and by_idx[0]["outcomes_total"] == 0


def test_fleet_no_healthy_replicas_local_error(tmp_path):
    dead = _StubReplica(0, mode="dead")
    fleet, lines, got = _fleet_run([dead], n=4)
    assert len(got) == 4
    for r in got.values():
        assert r["outcome"] == "error" and "no healthy replicas" in r["detail"]
    # locally-answered outage responses land in the frontend's own ledger,
    # so the delivery audit still balances (clients DID get responses)
    fleet.close_replicas()
    fm = build_fleet_manifest({}, fleet, str(tmp_path))
    assert fm["frontend_local"]["outcomes"] == {"error": 4}
    assert fm["audit"]["consistent"]
    assert fm["audit"]["frontend_local_total"] == 4
    assert fm["audit"]["delivered_total"] == 4


def test_fleet_frontend_enforces_deadline(tmp_path):
    """Time queued at the front end (linger + dispatch backlog) counts
    against deadline_s: a request whose budget expires before dispatch
    answers `deadline` locally — never shipped to a worker, which would
    re-stamp the deadline at its own enqueue time — and the audit
    balances across the replica + frontend-local ledgers."""
    clk = Clock()
    ok = _StubReplica(1)
    fleet = FleetServer(_server(clock=clk), [ok], linger_s=5.0, clock=clk)
    fleet.submit(json.dumps({"id": "d0", "weights": [0.1] * K,
                             "deadline_s": 1.0}), origin=0)
    fleet.submit(json.dumps({"id": "d1", "weights": [0.1] * K,
                             "deadline_s": 600.0}), origin=1)
    clk.t += 2.0   # linger past d0's budget, inside d1's
    got = {o: r for o, r in fleet.stop()}
    assert got[0]["outcome"] == "deadline"
    assert got[1]["outcome"] == "ok"
    assert fleet.local_delivered == {"deadline": 1}
    assert sum(ok.delivered.values()) == 1
    fleet.close_replicas()
    fm = build_fleet_manifest({}, fleet, str(tmp_path))
    assert fm["audit"]["consistent"]
    assert fm["audit"]["accepted_total"] == 2


def test_build_fleet_manifest_inconsistent_audit(tmp_path):
    """S5: a delivery shortfall (responses lost between dispatch and
    delivery) must break the audit invariant the doctor checks."""
    ok = _StubReplica(1)
    fleet, lines, got = _fleet_run([ok], n=6)
    fleet.close_replicas()
    fleet.accepted_total += 1   # simulate a dropped response
    fm = build_fleet_manifest({}, fleet, str(tmp_path))
    assert not fm["audit"]["consistent"]
    assert fm["audit"]["replica_outcomes_sum"] == 6
    assert fm["audit"]["accepted_total"] == 7


def test_replica_env_chaos_targeting():
    base = {"MFM_CHAOS_KILL": "serve.after_batch",
            "MFM_CHAOS_KILL_MATCH": "batch1",
            "MFM_CHAOS_KILL_REPLICA": "1", "KEEP": "x"}
    victim = replica_env(1, base)
    clean = replica_env(0, base)
    assert victim["MFM_CHAOS_KILL"] == "serve.after_batch"
    assert "MFM_CHAOS_KILL" not in clean
    assert "MFM_CHAOS_KILL_MATCH" not in clean
    # the targeting var itself never reaches any worker
    assert "MFM_CHAOS_KILL_REPLICA" not in victim
    assert clean["KEEP"] == "x"


# -- socket front end ---------------------------------------------------------

def _client_roundtrip(addr, lines):
    """One raw JSONL client: send all lines, half-close, read to EOF."""
    with socket.create_connection(addr, timeout=30) as s:
        s.sendall(("\n".join(lines) + "\n").encode())
        s.shutdown(socket.SHUT_WR)
        buf = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return [json.loads(ln) for ln in buf.decode().splitlines()]


def test_socket_frontend_concurrent_clients():
    """3 concurrent connections: each reads exactly its own responses,
    coalesced across connections but routed by origin."""
    fe = SocketFrontend("127.0.0.1", 0)
    backend = Coalescer(_server(batch_max=64), linger_s=0.02,
                        deliver=fe.deliver)
    fe.backend = backend
    addr = fe.listen()
    fe.start()
    try:
        all_lines = _mixed_lines(12, seed=23)
        per_client = [all_lines[i::3] for i in range(3)]
        results = [None] * 3

        def go(ci):
            results[ci] = _client_roundtrip(addr, per_client[ci])

        ts = [threading.Thread(target=go, args=(ci,)) for ci in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        ref = _sequential_by_id(all_lines, batch_max=64)
        for ci in range(3):
            want_ids = [json.loads(ln)["id"] for ln in per_client[ci]]
            got = results[ci]
            assert got is not None and len(got) == len(want_ids)
            assert sorted(r["id"] for r in got) == sorted(want_ids)
            for r in got:
                assert json.dumps(r, sort_keys=True) == ref[r["id"]]
    finally:
        fe.stop()


def test_conn_delivery_never_blocks_on_slow_client(monkeypatch):
    """Delivery runs under the coalescer lock, so it must never block on
    a client socket: sends go through the per-connection outbox, and a
    client that stops reading overflows its outbox and is dropped —
    without ever stalling the delivering thread."""
    from mfm_tpu.serve.frontend import _Conn

    monkeypatch.setattr(_Conn, "OUTBOX_MAX", 8)
    a, b = socket.socketpair()
    try:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        conn = _Conn(a, 0)
        payload = "x" * 65536
        t0 = time.monotonic()
        results = [conn.send_line(payload) for _ in range(64)]
        elapsed = time.monotonic() - t0
        # enqueues are put_nowait: even with the peer never reading and
        # the writer thread wedged in sendall, no call blocked
        assert elapsed < 5.0
        assert results[0] and not results[-1]
        assert conn.closed
    finally:
        b.close()


def test_http_frontend_post_and_healthz():
    fe = SocketFrontend("127.0.0.1", 0, http=True)
    backend = Coalescer(_server(batch_max=64), linger_s=0.02,
                        deliver=fe.deliver)
    fe.backend = backend
    addr = fe.listen()
    fe.start()
    try:
        lines = _mixed_lines(3, seed=29)
        body = ("\n".join(lines) + "\n").encode()
        with socket.create_connection(addr, timeout=30) as s:
            s.sendall(b"POST / HTTP/1.1\r\nHost: x\r\n"
                      + f"Content-Length: {len(body)}\r\n".encode()
                      + b"Connection: close\r\n\r\n" + body)
            raw = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                raw += chunk
        head, _, payload = raw.partition(b"\r\n\r\n")
        assert head.startswith(b"HTTP/1.1 200")
        resps = [json.loads(ln) for ln in payload.decode().splitlines()]
        assert [r["id"] for r in resps] \
            == [json.loads(ln)["id"] for ln in lines]
        with socket.create_connection(addr, timeout=30) as s:
            s.sendall(b"GET /healthz HTTP/1.1\r\nHost: x\r\n"
                      b"Connection: close\r\n\r\n")
            raw = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                raw += chunk
        head, _, payload = raw.partition(b"\r\n\r\n")
        assert head.startswith(b"HTTP/1.1 200")
        assert "requests_total" in json.loads(payload.decode())
    finally:
        fe.stop()


def test_doctor_serve_accepts_fleet_manifest(tmp_path, capsys):
    """S5: a fleet-only dir has no serve_manifest.json — the merged fleet
    manifest carries the front end's serve summary and doctor --serve must
    audit THAT instead of flagging the single-process file as missing."""
    from mfm_tpu import cli
    from mfm_tpu.data.artifacts import save_artifact
    from mfm_tpu.obs.manifest import build_run_manifest, write_run_manifest
    from mfm_tpu.serve.replica import FLEET_MANIFEST_NAME

    d = str(tmp_path)
    save_artifact(os.path.join(d, "x.npz"), {"a": np.zeros(2)})

    def rc(args):
        with pytest.raises(SystemExit) as exc:
            cli.main(["doctor", *args])
        return exc.value.code

    assert rc([d, "--serve"]) == 1        # nothing to audit at all
    ok = _StubReplica(0)
    fleet, lines, got = _fleet_run([ok], n=4)
    fleet.close_replicas()
    fm = build_fleet_manifest({}, fleet, d)
    serve_block = {"breaker_state": "closed", "breaker_open_total": 0,
                   "shed_total": 0, "shed_rate": 0.0,
                   "requests_total": fleet.accepted_total}
    write_run_manifest(
        os.path.join(d, FLEET_MANIFEST_NAME),
        build_run_manifest(backend="cpu",
                           health={"status": "ok", "checks": {}},
                           extra={"serve": serve_block, "fleet": fm,
                                  "trace_id": "a" * 32}))
    capsys.readouterr()
    assert rc([d, "--serve"]) == 0
    recs = {r["kind"]: r for r in
            json.loads(capsys.readouterr().out)["records"]}
    srec = recs["serve_manifest"]
    assert srec["status"] == "ok"
    assert srec["file"].endswith(FLEET_MANIFEST_NAME)
    assert srec["breaker_state"] == "closed"
    frec = recs["fleet_manifest"]
    assert frec["status"] == "ok" and frec["accepted_total"] == 4


# -- TCP transport: parity, heartbeat, rollout, live /metrics merge -----------

def test_tcp_replica_parity_with_pipe():
    """A worker reached over TCP is byte-for-byte the in-process loop:
    same wire protocol, same envelopes, and the live probes (ping,
    metrics scrape) answer between batches without disturbing parity."""
    addr_box, ready, summary_box = [], threading.Event(), []

    def announce(addr):
        addr_box.append(addr)
        ready.set()

    def worker():
        summary_box.append(
            serve_worker_socket(_server(batch_max=64), "127.0.0.1", 0,
                                announce=announce))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    assert ready.wait(timeout=30)
    rep = Replica.connect(0, addr_box[0], io_timeout_s=30.0)
    lines = _mixed_lines(10, seed=31)
    ref = _sequential_by_id(lines)
    got = dict(rep.run_batch(lines[:6]))
    rep.ping(10.0)
    shard = rep.scrape(10.0)
    assert "summary" in shard and "metrics" in shard
    for seq, resp in rep.run_batch(lines[6:]).items():
        got[6 + seq] = resp
    assert len(got) == len(lines)
    for i, ln in enumerate(lines):
        rid = json.loads(ln)["id"]
        assert json.dumps(got[i], sort_keys=True) == ref[rid], \
            f"TCP response for {rid} diverges from the in-process loop"
    assert rep.close() is None     # TCP: the process belongs to its host
    t.join(timeout=30)
    # the summary reads the process-global registry (the in-process
    # reference run above counts too) — assert shape, not an absolute
    assert summary_box and summary_box[0]["requests_total"] >= len(lines)
    assert "breaker_state" in summary_box[0]
    tc = rep.transport_counters()
    assert tc["connect_attempts"] >= 1 and tc["heartbeat_misses"] == 0


class _WedgedStub(_StubReplica):
    """SIGSTOP stand-in: alive by every process-level check, silent on
    the wire — only a heartbeat ping can expose it."""

    def __init__(self, idx):
        super().__init__(idx)
        self.wedged = False
        self.heartbeat_misses = 0
        self.last_io_t = time.monotonic() - 60.0   # long idle: ping is due

    @property
    def alive(self):
        return (not self.quarantined and not self.wedged
                and self.proc.poll() is None)

    def ping(self, timeout_s=None):
        self.heartbeat_misses += 1
        self.wedged = True
        raise ReplicaWedgedError(f"replica {self.idx}: heartbeat miss")

    def run_batch(self, lines):
        raise AssertionError("a wedged replica must never see a batch")

    def transport_counters(self):
        return {"reconnects": 0, "heartbeat_misses": self.heartbeat_misses,
                "redispatches": 0, "send_timeouts": 0, "recv_timeouts": 0,
                "failure_phases": {}}


def test_fleet_heartbeat_miss_quarantines_before_dispatch(tmp_path):
    """A long-idle replica is pinged before it is trusted with a batch:
    the miss quarantines it PRE-dispatch (no batch lost, no redispatch),
    every response still matches the single-process loop, and the miss
    is on the manifest's books."""
    wedged = _WedgedStub(0)
    ok = _StubReplica(1)
    fleet = FleetServer(_server(batch_max=4), [wedged, ok], linger_s=10.0,
                        heartbeat_s=0.5, heartbeat_timeout_s=1.0)
    lines = _mixed_lines(8, seed=17)
    got = {}
    for i, ln in enumerate(lines):
        for o, r in fleet.submit(ln, origin=i):
            got[o] = r
    for o, r in fleet.stop():
        got[o] = r
    ref = _sequential_by_id(lines, batch_max=4)
    assert len(got) == len(lines)
    for i, ln in enumerate(lines):
        assert json.dumps(got[i], sort_keys=True) == ref[json.loads(ln)["id"]]
    assert wedged.wedged and not wedged.alive
    assert wedged.heartbeat_misses == 1   # one ping, never re-picked
    assert wedged.delivered == {}
    fleet.close_replicas()
    fm = build_fleet_manifest({}, fleet, str(tmp_path))
    by_idx = {r["replica"]: r for r in fm["replicas"]}
    assert by_idx[0]["wedged"] and by_idx[0]["outcomes_total"] == 0
    assert fm["transport"]["heartbeat_misses"] == 1
    assert fm["transport"]["redispatches"] == 0
    assert fm["audit"]["consistent"]


class _RollStub(_StubReplica):
    """Worker that re-fences only when told (``--hold-fence`` semantics):
    ``reload_worker`` adopts the pointed-at generation; every batch logs
    (replica, generation) into a shared timeline."""

    def __init__(self, idx, pointer, timeline, ok=True):
        super().__init__(idx)
        self._pointer = pointer
        self._timeline = timeline
        self._ok = ok
        self.generation = pointer[0]
        self.reloads = 0

    def run_batch(self, lines):
        self._timeline.append((self.idx, self.generation))
        return super().run_batch(lines)

    def reload_worker(self, timeout_s=None):
        self.reloads += 1
        if not self._ok:
            return {"ok": False, "generation": None}
        self.generation = self._pointer[0]
        return {"ok": True, "generation": self.generation}


def test_rollout_rolls_workers_without_dropping_requests():
    """The pointer flips mid-stream: the fleet rolls every worker between
    batches, zero requests are dropped, every response stays bitwise, and
    once any batch runs on the new generation no later batch anywhere in
    the fleet runs on the old one (no mixed-generation batches)."""
    pointer, timeline = ["gen-a"], []
    reps = [_RollStub(0, pointer, timeline), _RollStub(1, pointer, timeline)]
    fleet = FleetServer(_server(batch_max=4), reps, linger_s=10.0,
                        rollout_check=lambda: pointer[0])
    assert fleet._fleet_generation == "gen-a"
    lines = _mixed_lines(16, seed=17)
    got = {}
    for i, ln in enumerate(lines):
        if i == 8:
            pointer[0] = "gen-b"
        for o, r in fleet.submit(ln, origin=i):
            got[o] = r
    for o, r in fleet.stop():
        got[o] = r
    ref = _sequential_by_id(lines, batch_max=4)
    assert len(got) == len(lines)          # zero dropped across the roll
    for i, ln in enumerate(lines):
        assert json.dumps(got[i], sort_keys=True) == ref[json.loads(ln)["id"]]
    assert [r.reloads for r in reps] == [1, 1]
    assert fleet._fleet_generation == "gen-b"
    gens = [g for _, g in timeline]
    assert "gen-a" in gens and "gen-b" in gens
    first_b = gens.index("gen-b")
    assert all(g == "gen-b" for g in gens[first_b:])


def test_rollout_failed_fence_audit_quarantines_worker():
    """A worker whose new generation fails its fence audit is drained
    out of the rotation; the survivor finishes the roll, the fence still
    moves, and every request is answered bitwise."""
    pointer, timeline = ["gen-a"], []
    bad = _RollStub(0, pointer, timeline, ok=False)
    good = _RollStub(1, pointer, timeline)
    fleet = FleetServer(_server(batch_max=4), [bad, good], linger_s=10.0,
                        rollout_check=lambda: pointer[0])
    lines = _mixed_lines(12, seed=17)
    got = {}
    for i, ln in enumerate(lines):
        if i == 4:
            pointer[0] = "gen-b"
        for o, r in fleet.submit(ln, origin=i):
            got[o] = r
    for o, r in fleet.stop():
        got[o] = r
    ref = _sequential_by_id(lines, batch_max=4)
    assert len(got) == len(lines)
    for i, ln in enumerate(lines):
        assert json.dumps(got[i], sort_keys=True) == ref[json.loads(ln)["id"]]
    assert bad.quarantined and not bad.alive
    assert good.reloads == 1 and good.generation == "gen-b"
    assert fleet._fleet_generation == "gen-b"
    # after the roll the quarantined worker never saw another batch
    assert all(g == "gen-a" for ix, g in timeline if ix == 0)


def test_http_metrics_merges_live_worker_shards():
    """GET /metrics and /healthz on a fleet frontend carry one live
    entry per worker (scraped mid-run over the transport), not just the
    frontend's own registry."""
    ok = _StubReplica(0)
    ok.scrape = lambda timeout_s=None: {"summary": {"requests_total": 3},
                                        "metrics": {"fleet_probe": 1.0}}
    ok.transport_counters = lambda: {"reconnects": 0, "heartbeat_misses": 0,
                                     "redispatches": 0, "send_timeouts": 0,
                                     "recv_timeouts": 0, "failure_phases": {}}
    fe = SocketFrontend("127.0.0.1", 0, http=True)
    backend = FleetServer(_server(batch_max=64), [ok], linger_s=0.02,
                          deliver=fe.deliver)
    fe.backend = backend
    addr = fe.listen()
    fe.start()
    try:
        def get(path):
            with socket.create_connection(addr, timeout=30) as s:
                s.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                          "Connection: close\r\n\r\n".encode())
                raw = b""
                while True:
                    chunk = s.recv(65536)
                    if not chunk:
                        break
                    raw += chunk
            head, _, payload = raw.partition(b"\r\n\r\n")
            assert head.startswith(b"HTTP/1.1 200")
            return json.loads(payload.decode())

        snap = get("/metrics")
        (w0,) = snap["workers"]
        assert w0["replica"] == 0 and w0["alive"]
        assert w0["metrics"] == {"fleet_probe": 1.0}
        assert w0["transport"]["heartbeat_misses"] == 0
        hz = get("/healthz")
        (h0,) = hz["workers"]
        assert h0["summary"] == {"requests_total": 3}
        assert not h0["wedged"]
    finally:
        fe.stop()


# -- distributed tracing over the fleet wire ----------------------------------

def test_worker_trace_prologue_opens_child_spans_and_piggybacks():
    """The structured batch prologue consumes no seq ordinal; the worker
    opens worker.recv spans parented to the shipped frontend spans and a
    worker.batch span under the dispatch span, then ships them all back
    on the flushed reply — leaving its own ring empty and the response
    bytes untouched."""
    from mfm_tpu.obs import trace as _trace
    from mfm_tpu.serve.server import _line_trace_id

    _trace.reset_tracing()
    try:
        lines = _mixed_lines(2, seed=41)
        parents = [["11" * 16, "22" * 8], ["33" * 16, "44" * 8]]
        prologue = json.dumps({CONTROL_KEY: {"op": "batch", "trace": {
            "dispatch": ["fd" * 16, "55" * 8], "parents": parents}}},
            sort_keys=True)
        flush = json.dumps({CONTROL_KEY: "flush"})
        in_text = "\n".join([prologue] + lines + [flush]) + "\n"
        out = io.StringIO()
        run_worker(_server(batch_max=8), io.StringIO(in_text), out)
        envs = [json.loads(ln) for ln in out.getvalue().splitlines()]
        (sent,) = [e for e in envs if e.get(CONTROL_KEY) == "flushed"]
        assert sent["n"] == 2              # the prologue took no ordinal
        assert isinstance(sent["clock_us"], float)
        shipped = sent["spans"]
        recvs = [s for s in shipped if s["name"] == "worker.recv"]
        assert [(s["trace_id"], s["parent_id"], s["attrs"]["seq"])
                for s in recvs] == [("11" * 16, "22" * 8, 0),
                                    ("33" * 16, "44" * 8, 1)]
        (batch,) = [s for s in shipped if s["name"] == "worker.batch"]
        assert batch["trace_id"] == "fd" * 16
        assert batch["parent_id"] == "55" * 8
        assert batch["attrs"]["n"] == 2
        # the worker's own admission spans derive the SAME sha trace ids
        # the frontend derives, so the processes join without a lookup
        reqs = {s["trace_id"] for s in shipped
                if s["name"] == "serve.request"}
        assert reqs == {_line_trace_id(ln) for ln in lines}
        # spans ship exactly once: the worker ring is drained
        assert _trace.spans() == []
        resps = {e["seq"]: e["resp"] for e in envs if CONTROL_KEY not in e}
        ref = _sequential_by_id(lines, batch_max=8)
        for i, ln in enumerate(lines):
            rid = json.loads(ln)["id"]
            assert json.dumps(resps[i], sort_keys=True) == ref[rid], \
                f"traced response for {rid} diverges from sequential loop"
    finally:
        _trace.reset_tracing()


def test_worker_ignores_unknown_structured_control_op():
    """Forward compatibility: a structured control frame with an op this
    worker does not know is skipped — no crash, no ordinal shift."""
    lines = _mixed_lines(2, seed=43)
    mystery = json.dumps({CONTROL_KEY: {"op": "hologram", "x": 1}},
                         sort_keys=True)
    flush = json.dumps({CONTROL_KEY: "flush"})
    in_text = "\n".join([mystery] + lines + [flush]) + "\n"
    out = io.StringIO()
    run_worker(_server(batch_max=8), io.StringIO(in_text), out)
    envs = [json.loads(ln) for ln in out.getvalue().splitlines()]
    (sent,) = [e for e in envs if e.get(CONTROL_KEY) == "flushed"]
    assert sent["n"] == 2
    resps = {e["seq"]: e["resp"] for e in envs if CONTROL_KEY not in e}
    assert set(resps) == {0, 1}
    ref = _sequential_by_id(lines, batch_max=8)
    for i, ln in enumerate(lines):
        rid = json.loads(ln)["id"]
        assert json.dumps(resps[i], sort_keys=True) == ref[rid]


def test_piggyback_omits_spans_when_tracing_disabled():
    from mfm_tpu.obs.trace import reset_tracing, set_tracing

    set_tracing(False)
    try:
        lines = _mixed_lines(2, seed=47)
        flush = json.dumps({CONTROL_KEY: "flush"})
        out = io.StringIO()
        run_worker(_server(batch_max=8),
                   io.StringIO("\n".join(lines + [flush]) + "\n"), out)
        envs = [json.loads(ln) for ln in out.getvalue().splitlines()]
        (sent,) = [e for e in envs if e.get(CONTROL_KEY) == "flushed"]
        assert "clock_us" in sent         # the clock probe always rides
        assert "spans" not in sent        # the span payload never does
    finally:
        reset_tracing()
        set_tracing(True)


def test_replica_clock_estimate_tightens_and_ingests_spans():
    """A loose batch-wall probe seeds the offset; a tight ping refines
    it; a later LOOSER probe must not clobber the tight estimate; spans
    shipped on a reply ingest shifted by the negated offset, stamped
    with the worker ordinal."""
    from mfm_tpu.obs import trace as _trace

    _trace.reset_tracing()
    try:
        rep = Replica.__new__(Replica)
        rep.idx = 5
        rep._init_ledger()
        rep._absorb_reply_telemetry({"clock_us": 2_000_000.0}, 1.0, 1.2)
        assert rep.clock_offset_us == pytest.approx(900_000.0)
        assert rep.clock_uncertainty_us == pytest.approx(100_000.0)
        rep._absorb_reply_telemetry({"clock_us": 1_951_000.0}, 1.0, 1.002)
        assert rep.clock_offset_us == pytest.approx(950_000.0)
        assert rep.clock_uncertainty_us == pytest.approx(1_000.0)
        rep._absorb_reply_telemetry({"clock_us": 3_000_000.0}, 1.0, 1.5)
        assert rep.clock_uncertainty_us == pytest.approx(1_000.0)
        rep._absorb_reply_telemetry(
            {"clock_us": 1_951_000.0, "spans": [
                {"name": "worker.batch", "trace_id": "ab" * 16,
                 "span_id": "cd" * 8, "parent_id": None,
                 "start_us": 1_951_000.0, "dur_us": 500.0,
                 "wall_ts": 1.0, "tid": 1, "attrs": {}}]},
            1.0, 1.002)
        (sp,) = [s for s in _trace.spans() if s.name == "worker.batch"]
        assert sp.start_us == pytest.approx(1_001_000.0)
        assert sp.attrs["worker"] == 5
        assert "clock_skew" not in sp.attrs
    finally:
        _trace.reset_tracing()


def test_fleet_dispatch_spans_and_stub_replicas_get_plain_lines():
    """The dispatcher opens a fleet.dispatch span per attempt, keyed by
    the batch head's sha-derived trace id — and a replica without the
    accepts_trace_frames capability (every duck-typed stub) receives the
    batch WITHOUT a prologue, so its responses stay bitwise."""
    from mfm_tpu.obs import trace as _trace
    from mfm_tpu.serve.server import _line_trace_id

    _trace.reset_tracing()
    try:
        ok = _StubReplica(0)
        fleet, lines, got = _fleet_run([ok], n=6)
        assert len(got) == len(lines)
        dsp = [s for s in _trace.spans() if s.name == "fleet.dispatch"]
        assert dsp, "dispatch opened no spans with tracing on"
        tids = {_line_trace_id(ln) for ln in lines}
        for s in dsp:
            assert s.attrs["outcome"] == "ok"
            assert s.attrs["replica"] == 0
            assert s.trace_id in tids
        ref = _sequential_by_id(lines, batch_max=4)
        for i, ln in enumerate(lines):
            rid = json.loads(ln)["id"]
            assert json.dumps(got[i], sort_keys=True) == ref[rid]
    finally:
        _trace.reset_tracing()


# -- flight recorder + SLO wiring through the fleet ---------------------------

class _WedgeOnceStub(_StubReplica):
    """Wedges (transport deadline) on its first batch, then is drained."""

    def __init__(self, idx):
        super().__init__(idx)
        self.wedged = False

    def run_batch(self, lines):
        if not self.wedged:
            self.wedged = True
            self.quarantined = True
            raise ReplicaWedgedError(f"replica {self.idx}: silent mid-batch")
        return super().run_batch(lines)


def test_wedge_quarantine_triggers_flightrec_dump(tmp_path):
    """An armed recorder dumps on wedge quarantine: the postmortem
    carries the triggering batch head's trace id, the dispatch history
    and the live replica ledgers — and the survivors still answer
    everything bitwise."""
    from mfm_tpu.obs import flightrec as frec
    from mfm_tpu.serve.server import _line_trace_id

    frec.reset_flightrec()
    path = str(tmp_path / "flightrec.json")
    frec.arm(path)
    try:
        wedgy = _WedgeOnceStub(0)
        ok = _StubReplica(1)
        fleet, lines, got = _fleet_run([wedgy, ok])
        assert len(got) == len(lines)
        rec = frec.read_flightrec(path)
        assert rec["trigger"] == "wedge_quarantine"
        assert rec["trace_id"] in {_line_trace_id(ln) for ln in lines}
        kinds = [e["kind"] for e in rec["events"]]
        assert "wedge_quarantine" in kinds and "dispatch" in kinds
        byidx = {r["replica"]: r for r in rec["state"]["replicas"]}
        assert byidx[0]["wedged"] or byidx[0]["quarantined"]
        ref = _sequential_by_id(lines, batch_max=4)
        for i, ln in enumerate(lines):
            rid = json.loads(ln)["id"]
            assert json.dumps(got[i], sort_keys=True) == ref[rid]
    finally:
        frec.reset_flightrec()


def test_fleet_manifest_carries_slo_and_flightrec_blocks(tmp_path):
    from mfm_tpu.obs import flightrec as frec
    from mfm_tpu.obs import slo as slo_mod

    frec.reset_flightrec()
    slo_mod.install(slo_mod.SloEngine())
    try:
        frec.arm(str(tmp_path / "flightrec.json"))
        frec.record_event("dispatch", replica=0)
        ok = _StubReplica(0)
        fleet, lines, got = _fleet_run([ok], n=4)
        fleet.close_replicas()
        fm = build_fleet_manifest(_obs.serve_summary_from_registry(),
                                  fleet, str(tmp_path))
        assert fm["flightrec"]["armed"] is True
        assert fm["flightrec"]["events"] >= 1
        assert fm["slo"] is not None and fm["slo"]["schema"] == 1
        assert fm["slo"]["worst_state"] in ("ok", "slow_burn", "fast_burn")
    finally:
        slo_mod.reset_slo()
        frec.reset_flightrec()


def test_doctor_serve_fails_on_fast_burning_slo(tmp_path, capsys):
    """A fast-burn state persisted in the shutdown manifest is a missed
    page: doctor --serve must FAIL, naming the burning objective."""
    from mfm_tpu import cli
    from mfm_tpu.data.artifacts import save_artifact
    from mfm_tpu.obs.manifest import build_run_manifest, write_run_manifest
    from mfm_tpu.serve.replica import FLEET_MANIFEST_NAME

    d = str(tmp_path)
    save_artifact(os.path.join(d, "x.npz"), {"a": np.zeros(2)})
    ok = _StubReplica(0)
    fleet, lines, got = _fleet_run([ok], n=4)
    fleet.close_replicas()
    fm = build_fleet_manifest({}, fleet, d)
    slo_block = {"schema": 1, "window_fast_s": 300.0,
                 "window_slow_s": 3600.0, "fast_burn_threshold": 14.4,
                 "slow_burn_threshold": 3.0, "worst_state": "fast_burn",
                 "slos": [{"name": "availability", "kind": "availability",
                           "objective": 0.99, "budget": 0.01,
                           "burn_fast": 50.0, "burn_slow": 5.0,
                           "state": "fast_burn"}]}
    serve_block = {"breaker_state": "closed", "breaker_open_total": 0,
                   "shed_total": 0, "shed_rate": 0.0,
                   "requests_total": fleet.accepted_total,
                   "slo": slo_block}
    write_run_manifest(
        os.path.join(d, FLEET_MANIFEST_NAME),
        build_run_manifest(backend="cpu",
                           health={"status": "ok", "checks": {}},
                           extra={"serve": serve_block, "fleet": fm,
                                  "trace_id": "a" * 32}))
    capsys.readouterr()
    with pytest.raises(SystemExit) as exc:
        cli.main(["doctor", d, "--serve"])
    assert exc.value.code == 1
    recs = {r["kind"]: r for r in
            json.loads(capsys.readouterr().out)["records"]}
    srec = recs["serve_manifest"]
    assert srec["status"] == "unhealthy"
    assert srec["slo_worst_state"] == "fast_burn"
    assert any("FAST-BURNING" in p for p in srec["problems"])


def test_doctor_surfaces_and_validates_flightrec_dumps(tmp_path, capsys):
    """A parseable dump beside the artifacts is a warning (the run hit a
    postmortem trigger); a torn one is a doctor FAILURE."""
    from mfm_tpu import cli
    from mfm_tpu.data.artifacts import save_artifact
    from mfm_tpu.obs import flightrec as frec
    from mfm_tpu.obs.manifest import build_run_manifest, write_run_manifest
    from mfm_tpu.serve.replica import FLEET_MANIFEST_NAME

    d = str(tmp_path)
    save_artifact(os.path.join(d, "x.npz"), {"a": np.zeros(2)})
    ok = _StubReplica(0)
    fleet, lines, got = _fleet_run([ok], n=4)
    fleet.close_replicas()
    fm = build_fleet_manifest({}, fleet, d)
    serve_block = {"breaker_state": "closed", "breaker_open_total": 0,
                   "shed_total": 0, "shed_rate": 0.0,
                   "requests_total": fleet.accepted_total}
    write_run_manifest(
        os.path.join(d, FLEET_MANIFEST_NAME),
        build_run_manifest(backend="cpu",
                           health={"status": "ok", "checks": {}},
                           extra={"serve": serve_block, "fleet": fm,
                                  "trace_id": "a" * 32}))
    frec.reset_flightrec()
    frec.record_event("breaker_open", trace_id="ab" * 16)
    fr_path = os.path.join(d, frec.FLIGHTREC_NAME)
    frec.dump_flightrec(fr_path, trigger="breaker_open")
    frec.reset_flightrec()

    def rc(args):
        with pytest.raises(SystemExit) as exc:
            cli.main(["doctor", *args])
        return exc.value.code

    capsys.readouterr()
    assert rc([d, "--serve"]) == 0        # a valid dump only warns
    recs = {r["kind"]: r for r in
            json.loads(capsys.readouterr().out)["records"]}
    frrec = recs["flightrec"]
    assert frrec["trigger"] == "breaker_open"
    assert frrec["trace_id"] == "ab" * 16
    assert any("postmortem trigger" in w for w in frrec["warnings"])
    with open(fr_path, encoding="utf-8") as fh:
        text = fh.read()
    with open(fr_path, "w", encoding="utf-8") as fh:
        fh.write(text[: len(text) // 2])  # tear it
    capsys.readouterr()
    assert rc([d, "--serve"]) == 1
    recs = {r["kind"]: r for r in
            json.loads(capsys.readouterr().out)["records"]}
    assert recs["flightrec"]["status"] == "corrupt"


def test_metrics_diff_accepts_fleet_manifests(tmp_path, capsys):
    """mfm-tpu metrics diff takes merged fleet manifests on either side
    and reports per-replica shard deltas, not just merged totals."""
    import copy

    from mfm_tpu import cli

    ok = _StubReplica(0)
    fleet, lines, got = _fleet_run([ok], n=4)
    fleet.close_replicas()
    fm_a = build_fleet_manifest({}, fleet, str(tmp_path))
    fm_b = copy.deepcopy(fm_a)
    fm_b["accepted_total"] += 2
    fm_b["replicas"][0]["outcomes"]["ok"] = \
        fm_b["replicas"][0]["outcomes"].get("ok", 0) + 2
    fm_b["replicas"][0]["outcomes_total"] += 2
    a_path, b_path = str(tmp_path / "fa.json"), str(tmp_path / "fb.json")
    for p, fmx in ((a_path, fm_a), (b_path, fm_b)):
        with open(p, "w", encoding="utf-8") as fh:
            json.dump(fmx, fh)
    capsys.readouterr()
    try:
        cli.main(["metrics", "diff", a_path, b_path])
    except SystemExit as exc:
        assert exc.code in (0, None)
    out = json.loads(capsys.readouterr().out)
    series = out["series"]
    assert series["fleet:accepted_total"]["delta"] == 2
    assert series["r0:outcomes:ok"]["delta"] == 2
    assert series["r0:outcomes_total"]["delta"] == 2
