"""Specific-risk stage (the USE4 stage behind the reference's never-called
``bayes_shrink``, ``utils.py:133-168``) + the portfolio-risk combination."""

import numpy as np
import jax.numpy as jnp
import pytest

from mfm_tpu.models.bias import bayes_shrink
from mfm_tpu.models.specific import ewma_specific_vol, specific_risk_by_time


def _loopy_ewma_vol(u, half_life, min_periods):
    T, N = u.shape
    lam = 0.5 ** (1.0 / half_life)
    out = np.full((T, N), np.nan)
    for n in range(N):
        num = den = cnt = 0.0
        for t in range(T):
            ok = np.isfinite(u[t, n])
            num = lam * num + (u[t, n] ** 2 if ok else 0.0)
            den = lam * den + (1.0 if ok else 0.0)
            cnt += ok
            if cnt >= min_periods and den > 0:
                out[t, n] = np.sqrt(num / den)
    return out


def test_ewma_specific_vol_matches_loopy():
    rng = np.random.default_rng(0)
    T, N = 120, 9
    u = 0.02 * rng.standard_normal((T, N))
    u[rng.random((T, N)) < 0.15] = np.nan
    u[:30, 0] = np.nan  # late listing
    got = np.asarray(ewma_specific_vol(jnp.asarray(u), 42.0, 10))
    exp = _loopy_ewma_vol(u, 42.0, 10)
    np.testing.assert_allclose(got, exp, rtol=1e-10, atol=1e-14,
                               equal_nan=True)


def test_bayes_shrink_mask_full_equals_unmasked():
    rng = np.random.default_rng(1)
    N = 200
    vol = np.abs(rng.normal(0.02, 0.01, N))
    cap = np.exp(rng.normal(11, 1, N))
    base = np.asarray(bayes_shrink(jnp.asarray(vol), jnp.asarray(cap)))
    masked = np.asarray(bayes_shrink(jnp.asarray(vol), jnp.asarray(cap),
                                     mask=jnp.ones(N, bool)))
    np.testing.assert_allclose(masked, base, rtol=1e-12)


def test_bayes_shrink_masked_equals_subset():
    """Shrinking with a mask must equal shrinking the valid subset alone:
    invalid stocks must not shift quantile edges, group means, or
    dispersions."""
    rng = np.random.default_rng(2)
    N = 150
    vol = np.abs(rng.normal(0.02, 0.01, N))
    cap = np.exp(rng.normal(11, 1, N))
    mask = rng.random(N) > 0.3
    # poison the masked-out entries — they must have zero influence
    vol_p, cap_p = vol.copy(), cap.copy()
    vol_p[~mask] = 99.0
    cap_p[~mask] = 1e12
    got = np.asarray(bayes_shrink(jnp.asarray(vol_p), jnp.asarray(cap_p),
                                  mask=jnp.asarray(mask)))
    sub = np.asarray(bayes_shrink(jnp.asarray(vol[mask]),
                                  jnp.asarray(cap[mask])))
    np.testing.assert_allclose(got[mask], sub, rtol=1e-10)
    assert np.isnan(got[~mask]).all()


def test_specific_risk_by_time_shapes_and_nan_discipline():
    rng = np.random.default_rng(3)
    T, N = 90, 40
    u = 0.02 * rng.standard_normal((T, N))
    u[rng.random((T, N)) < 0.1] = np.nan
    cap = np.exp(rng.normal(11, 1, (T, N)))
    raw, shrunk = specific_risk_by_time(jnp.asarray(u), jnp.asarray(cap),
                                        min_periods=10)
    raw, shrunk = np.asarray(raw), np.asarray(shrunk)
    assert raw.shape == shrunk.shape == (T, N)
    # NaN wherever raw is NaN; finite (and positive) where raw is finite
    np.testing.assert_array_equal(np.isnan(raw), np.isnan(shrunk))
    m = np.isfinite(raw)
    assert m[-1].all()  # everyone has >=10 obs by the end
    assert (shrunk[m] > 0).all()
    # shrinkage moves vol toward group means: dispersion must not increase
    assert shrunk[-1].std() <= raw[-1].std() * 1.001


def test_portfolio_risk_decomposition():
    from mfm_tpu.config import PipelineConfig, RiskModelConfig
    from mfm_tpu.data.synthetic import synthetic_barra_table
    from mfm_tpu.pipeline import run_risk_pipeline

    df, _ = synthetic_barra_table(T=100, N=40, P=5, Q=3, seed=4)
    res = run_risk_pipeline(
        barra_df=df,
        config=PipelineConfig(risk=RiskModelConfig(eigen_n_sims=8),
                              dtype="float64"))
    a = res.arrays
    valid = np.asarray(a.valid[-1])
    w = np.where(valid, 1.0, 0.0)
    w /= w.sum()
    rep = res.portfolio_risk(w)
    assert rep["total_vol"] > 0
    assert rep["factor_var"] >= 0 and rep["specific_var"] >= 0
    assert np.isclose(rep["total_vol"],
                      np.sqrt(rep["factor_var"] + rep["specific_var"]))
    # country exposure of a fully-invested portfolio is exactly 1
    np.testing.assert_allclose(rep["factor_exposures"]["country"], 1.0,
                               rtol=1e-9)
    # manual cross-check of the factor part
    x = rep["factor_exposures"].to_numpy()
    F = np.asarray(res.outputs.vr_cov[-1], np.float64)
    np.testing.assert_allclose(rep["factor_var"], x @ F @ x, rtol=1e-9)
    # Euler attribution: per-factor contributions sum exactly to factor_var
    contrib = rep["factor_risk_contribution"]
    assert list(contrib.index) == list(rep["factor_exposures"].index)
    np.testing.assert_allclose(contrib.to_numpy(), x * (F @ x), rtol=1e-12)
    np.testing.assert_allclose(contrib.sum(), rep["factor_var"],
                               rtol=1e-14)

    # nonzero weight outside the universe is an error, not silence
    bad = np.ones_like(w) / len(w)
    if (~valid).any():
        with pytest.raises(ValueError, match="universe"):
            res.portfolio_risk(bad)

    # specific_risk() DataFrames align with the panel
    raw, shrunk = res.specific_risk()
    assert raw.shape == (100, 40) and shrunk.shape == (100, 40)


def test_portfolio_risk_error_paths():
    from mfm_tpu.config import PipelineConfig, RiskModelConfig
    from mfm_tpu.data.synthetic import synthetic_barra_table
    from mfm_tpu.pipeline import run_risk_pipeline

    df, _ = synthetic_barra_table(T=100, N=40, P=5, Q=3, seed=4)
    res = run_risk_pipeline(
        barra_df=df,
        config=PipelineConfig(risk=RiskModelConfig(eigen_n_sims=8),
                              dtype="float64"))
    valid = np.asarray(res.arrays.valid[-1])
    w = np.where(valid, 1.0, 0.0)
    w /= w.sum()

    # NaN weights (a pandas reindex artifact) must raise, not propagate
    w_nan = w.copy()
    w_nan[~valid] = np.nan
    if (~valid).any():
        with pytest.raises(ValueError, match="finite"):
            res.portfolio_risk(w_nan)

    # a held stock with no specific-vol estimate must raise, not be
    # silently treated as zero idiosyncratic variance
    sv = np.full(len(w), np.nan)
    with pytest.raises(ValueError, match="no specific-vol estimate"):
        res.portfolio_risk(w, specific_vol=sv)

    # the cached panel honors non-default parameters (distinct cache keys)
    rep_a = res.portfolio_risk(w)
    rep_b = res.portfolio_risk(w, half_life=84.0, ngroup=5)
    assert rep_a["specific_var"] != rep_b["specific_var"]
    assert len(res._spec_cache) == 2


def test_bayes_shrink_mask_tolerates_nan_inputs():
    """NaN vol/cap on masked-out stocks — the natural input for the mask
    parameter — must not poison masked-in outputs (0 * NaN in the one-hot
    matmuls)."""
    rng = np.random.default_rng(5)
    N = 80
    vol = np.abs(rng.normal(0.02, 0.01, N))
    cap = np.exp(rng.normal(11, 1, N))
    mask = rng.random(N) > 0.25
    vol_nan, cap_nan = vol.copy(), cap.copy()
    vol_nan[~mask] = np.nan
    cap_nan[~mask] = np.nan
    got = np.asarray(bayes_shrink(jnp.asarray(vol_nan), jnp.asarray(cap_nan),
                                  mask=jnp.asarray(mask)))
    sub = np.asarray(bayes_shrink(jnp.asarray(vol[mask]),
                                  jnp.asarray(cap[mask])))
    np.testing.assert_allclose(got[mask], sub, rtol=1e-10)
    assert np.isnan(got[~mask]).all()


def test_portfolio_risk_rejects_out_of_range_date():
    from mfm_tpu.config import PipelineConfig, RiskModelConfig
    from mfm_tpu.data.synthetic import synthetic_barra_table
    from mfm_tpu.pipeline import run_risk_pipeline

    df, _ = synthetic_barra_table(T=60, N=30, P=4, Q=2, seed=6)
    res = run_risk_pipeline(
        barra_df=df,
        config=PipelineConfig(risk=RiskModelConfig(eigen_n_sims=8),
                              dtype="float64"))
    valid = np.asarray(res.arrays.valid[-1])
    w = np.where(valid, 1.0, 0.0)
    w /= w.sum()
    for bad_t in (60, 61, -61):  # len(dates) off-by-one and beyond
        with pytest.raises(IndexError, match="out of range"):
            res.portfolio_risk(w, t=bad_t)
