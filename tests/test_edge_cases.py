"""Degenerate cross-sections, dtype drift, and reference-quirk documentation
tests."""

import numpy as np
import jax.numpy as jnp

from mfm_tpu.config import RiskModelConfig
from mfm_tpu.models.risk_model import RiskModel
from mfm_tpu.ops.xreg import cross_section_regress
from __graft_entry__ import _synthetic_risk_inputs


def test_empty_universe_date_yields_nan_not_crash():
    N, P, Q = 12, 3, 2
    rng = np.random.default_rng(0)
    res = cross_section_regress(
        jnp.asarray(rng.standard_normal(N)),
        jnp.asarray(np.exp(rng.normal(0, 1, N))),
        jnp.asarray(rng.standard_normal((N, Q))),
        jnp.asarray(rng.integers(0, P, N)),
        jnp.zeros(N, bool),  # nobody valid
        n_industries=P,
    )
    assert np.all(np.isnan(np.asarray(res.specific_ret)))
    assert not np.isfinite(float(res.r2))


def test_single_stock_date():
    N, P, Q = 8, 2, 1
    rng = np.random.default_rng(1)
    valid = np.zeros(N, bool)
    valid[3] = True
    res = cross_section_regress(
        jnp.asarray(rng.standard_normal(N)),
        jnp.asarray(np.exp(rng.normal(0, 1, N))),
        jnp.asarray(rng.standard_normal((N, Q))),
        jnp.asarray(np.full(N, 1)),
        jnp.asarray(valid),
        n_industries=P,
    )
    f = np.asarray(res.factor_ret)
    assert f.shape == (1 + P + Q,)
    # with one stock the country factor absorbs its return exactly when the
    # design is consistent; at minimum nothing crashes and spec is tiny
    spec = np.asarray(res.specific_ret)
    assert np.isnan(spec[~valid]).all()


def test_missing_industry_reproduces_reference_behavior():
    """A date where the LAST industry has no members: the reference divides
    by its zero cap sum (CrossSection.py:70) producing non-finite outputs —
    we reproduce rather than silently diverge (documented in xreg docstring).
    Industries missing in the MIDDLE are handled by the pinv."""
    N, P, Q = 20, 4, 2
    rng = np.random.default_rng(2)
    industry = rng.integers(0, P - 1, N)  # last industry absent
    res = cross_section_regress(
        jnp.asarray(rng.standard_normal(N)),
        jnp.asarray(np.exp(rng.normal(0, 1, N))),
        jnp.asarray(rng.standard_normal((N, Q))),
        jnp.asarray(industry),
        jnp.ones(N, bool),
        n_industries=P,
    )
    assert not np.all(np.isfinite(np.asarray(res.factor_ret)))


def test_float32_drift_vs_float64_risk_pipeline():
    """The TPU fast path runs float32; quantify drift against the float64
    parity path on identical inputs.  Factor returns are the contract
    surface: drift must stay well under the factor-return scale."""
    T, N, P, Q = 60, 40, 5, 3
    a64 = _synthetic_risk_inputs(T, N, P, Q, dtype=jnp.float64, seed=3)
    cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=100)

    import jax
    sim64 = None
    rm64 = RiskModel(*a64, n_industries=P, config=cfg)
    key = jax.random.key(0)
    from mfm_tpu.models.eigen import simulated_eigen_covs
    sim64 = simulated_eigen_covs(key, rm64.K, 100, 8, jnp.float64)
    out64 = rm64.run(sim_covs=sim64)

    a32 = tuple(x.astype(jnp.float32) if x.dtype == jnp.float64 else x for x in a64)
    rm32 = RiskModel(*a32, n_industries=P, config=cfg)
    out32 = rm32.run(sim_covs=sim64.astype(jnp.float32))

    f64 = np.asarray(out64.factor_ret)
    f32 = np.asarray(out32.factor_ret, np.float64)
    scale = np.abs(f64).max()
    drift = np.abs(f64 - f32).max()
    assert drift < 5e-4 * max(scale, 1e-3), (drift, scale)

    l64 = np.asarray(out64.lamb)
    l32 = np.asarray(out32.lamb, np.float64)
    m = np.isfinite(l64)
    assert np.abs(l64[m] - l32[m]).max() < 1e-2
