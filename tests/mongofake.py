"""In-memory pymongo stand-in: exactly the surface MongoPanelStore touches.

The image has no pymongo and no server, so without this every line of
``mfm_tpu/data/mongo_store.py`` is dead in CI (round-4 VERDICT missing #2).
The fake reproduces the Mongo semantics the adapter RELIES on, so the
adapter's real logic executes hermetically:

- unique indexes treat a missing field as null (two docs both missing a
  unique column COLLIDE — Mongo's non-sparse unique index semantics);
- ``insert_many(ordered=False)`` continues past duplicate-key rows and
  raises ``BulkWriteError`` whose ``details`` carry per-row ``writeErrors``
  (code 11000) and ``nInserted``;
- ``create_index`` can be made to fail (``fail_create_index``) to drive the
  adapter's authorization-vs-transient fallback paths;
- ``find`` / ``find_one`` support the exact filters/projections/sorts the
  adapter issues: ``{}``, ``{col: {"$exists": True}}``,
  ``{"_id": {"$in": [...]}}``; inclusion/exclusion projections; a
  single-column descending sort.

It is NOT a general mongomock: anything the adapter does not use raises.
"""

from __future__ import annotations

import itertools

ASCENDING = 1
DESCENDING = -1


class OperationFailure(Exception):
    """pymongo.errors.OperationFailure (e.g. not authorized)."""


class _Errors:
    OperationFailure = OperationFailure


errors = _Errors()


class BulkWriteError(Exception):
    def __init__(self, details):
        super().__init__("batch op errors occurred")
        self.details = details


class InsertManyResult:
    def __init__(self, ids):
        self.inserted_ids = ids


class FakeCollection:
    def __init__(self):
        self.docs: dict = {}          # _id -> doc
        self.unique_indexes: list = []  # list of column tuples
        self.plain_indexes: list = []
        self._ids = itertools.count()
        #: set to an exception INSTANCE to make create_index raise it
        self.fail_create_index = None

    # -- indexes -----------------------------------------------------------
    def create_index(self, keys, unique: bool = False):
        if self.fail_create_index is not None:
            raise self.fail_create_index
        cols = tuple(k for k, _ in keys)
        if unique:
            if cols not in self.unique_indexes:
                self.unique_indexes.append(cols)
        elif cols not in self.plain_indexes:
            self.plain_indexes.append(cols)
        return "_".join(f"{k}_{d}" for k, d in keys)

    # -- writes ------------------------------------------------------------
    @staticmethod
    def _key(doc, cols):
        # missing field == null: two docs both lacking a unique column
        # collide, exactly like Mongo's non-sparse unique index
        return tuple(doc.get(c) for c in cols)

    def insert_many(self, records, ordered: bool = True):
        existing = {cols: {self._key(d, cols) for d in self.docs.values()}
                    for cols in self.unique_indexes}
        inserted, write_errors = [], []
        for i, rec in enumerate(records):
            dup = any(self._key(rec, cols) in existing[cols]
                      for cols in self.unique_indexes)
            if dup:
                write_errors.append(
                    {"index": i, "code": 11000,
                     "errmsg": "E11000 duplicate key error"})
                if ordered:
                    break
                continue
            doc = dict(rec)
            doc["_id"] = next(self._ids)
            self.docs[doc["_id"]] = doc
            inserted.append(doc["_id"])
            for cols in self.unique_indexes:
                existing[cols].add(self._key(doc, cols))
        if write_errors:
            raise BulkWriteError({"writeErrors": write_errors,
                                  "nInserted": len(inserted)})
        return InsertManyResult(inserted)

    def delete_many(self, flt):
        if flt == {}:
            n = len(self.docs)
            self.docs.clear()
            return n
        if set(flt) == {"_id"} and set(flt["_id"]) == {"$in"}:
            ids = set(flt["_id"]["$in"])
            n = 0
            for _id in list(self.docs):
                if _id in ids:
                    del self.docs[_id]
                    n += 1
            return n
        raise NotImplementedError(f"delete_many filter {flt!r}")

    # -- reads -------------------------------------------------------------
    @staticmethod
    def _match(doc, flt):
        for col, cond in (flt or {}).items():
            if isinstance(cond, dict):
                for op, val in cond.items():
                    if op == "$exists":
                        if (col in doc) != bool(val):
                            return False
                    elif op == "$in":
                        if doc.get(col) not in val:
                            return False
                    else:
                        raise NotImplementedError(f"operator {op!r}")
            elif doc.get(col) != cond:
                return False
        return True

    @staticmethod
    def _project(doc, proj):
        if proj is None:
            return dict(doc)
        inclusions = [k for k, v in proj.items() if v and k != "_id"]
        if inclusions:
            out = {k: doc[k] for k in inclusions if k in doc}
        else:
            excluded = {k for k, v in proj.items() if not v}
            out = {k: v for k, v in doc.items() if k not in excluded}
        if proj.get("_id", 1):
            out["_id"] = doc["_id"]
        else:
            out.pop("_id", None)
        return out

    def find(self, flt=None, projection=None):
        return [self._project(d, projection)
                for d in self.docs.values() if self._match(d, flt)]

    def find_one(self, flt=None, projection=None, sort=None):
        docs = [d for d in self.docs.values() if self._match(d, flt)]
        if sort:
            (col, direction), = sort
            docs = [d for d in docs if d.get(col) is not None]
            docs.sort(key=lambda d: d[col], reverse=direction == DESCENDING)
        if not docs:
            return None
        return self._project(docs[0], projection)

    def distinct(self, col):
        out = []
        for d in self.docs.values():
            if col in d and d[col] not in out:
                out.append(d[col])
        return out


class FakeDatabase:
    def __init__(self, name="fake"):
        self.name = name
        self._colls: dict = {}

    def __getitem__(self, name) -> FakeCollection:
        if name not in self._colls:
            self._colls[name] = FakeCollection()
        return self._colls[name]
