"""Parity: winsorize / composite / orthogonalize vs pandas goldens."""

import numpy as np
import pandas as pd
import jax.numpy as jnp

from mfm_tpu.factors.post import (
    composite_factor,
    orthogonalize,
    winsorize_panel,
)

import golden


def _panel_and_long(seed=0, T=25, N=40, cols=("A", "B", "C")):
    rng = np.random.default_rng(seed)
    panels = {}
    for c in cols:
        x = rng.standard_normal((T, N)) * (1 + rng.random())
        x[rng.random((T, N)) < 0.15] = np.nan
        panels[c] = x
    ti, si = np.meshgrid(np.arange(T), np.arange(N), indexing="ij")
    df = pd.DataFrame({"trade_date": ti.ravel()})
    for c in cols:
        df[c] = panels[c].ravel()
    return panels, df


def test_winsorize_matches_pandas():
    panels, df = _panel_and_long()
    got = np.asarray(winsorize_panel(jnp.asarray(panels["A"]), n_std=2.5))
    g = golden.golden_winsorize(df, ["A"], n_std=2.5)["A"].to_numpy().reshape(got.shape)
    np.testing.assert_allclose(got, g, rtol=1e-10, atol=1e-14, equal_nan=True)


def test_composite_matches_pandas():
    panels, df = _panel_and_long()
    weights = [0.7, 0.15, 0.15]
    got = np.asarray(
        composite_factor([jnp.asarray(panels[c]) for c in "ABC"], weights)
    )
    g = golden.golden_composite(df, ["A", "B", "C"], weights).reshape(got.shape)
    np.testing.assert_allclose(got, g, rtol=1e-10, atol=1e-14, equal_nan=True)


def test_composite_all_missing_is_nan():
    x = jnp.asarray(np.full((3, 4), np.nan))
    out = np.asarray(composite_factor([x, x], [0.5, 0.5]))
    assert np.all(np.isnan(out))


def test_orthogonalize_matches_pandas():
    panels, df = _panel_and_long(seed=5)
    got = np.asarray(
        orthogonalize(jnp.asarray(panels["A"]),
                      [jnp.asarray(panels["B"]), jnp.asarray(panels["C"])])
    )
    g = golden.golden_ortho(df, "A", ["B", "C"]).reshape(got.shape)
    np.testing.assert_allclose(got, g, rtol=1e-7, atol=1e-10, equal_nan=True)


def test_orthogonalize_too_few_valid_rows_all_nan():
    T, N = 4, 2  # 2 valid rows < n_regressors + 2 == 3
    y = jnp.asarray(np.random.default_rng(0).standard_normal((T, N)))
    x = jnp.asarray(np.random.default_rng(1).standard_normal((T, N)))
    out = np.asarray(orthogonalize(y, [x]))
    assert np.all(np.isnan(out))


def test_winsorize_single_survivor_section_passes_through():
    """A cross-section with exactly one finite value has NaN sample std;
    pandas clip ignores NaN thresholds so the value must survive UNCLIPPED
    (reference post_processing.py:12-15 — divergence found by the
    end-to-end crosscheck: the first date a factor's expanding window
    matures for exactly one stock)."""
    import jax.numpy as jnp
    import numpy as np

    from mfm_tpu.ops.masked import winsorize_cs

    x = np.full((3, 4), np.nan)
    x[0, 1] = 7.5            # single survivor
    x[1, :] = [1.0, 1.0, 1.0, 1.0]   # zero-variance section: clips to mean
    x[2, :] = [0.0, 1.0, 2.0, 50.0]  # normal section: outlier clips
    got = np.asarray(winsorize_cs(jnp.asarray(x), n_std=2.5))
    assert got[0, 1] == 7.5
    assert np.isnan(got[0, [0, 2, 3]]).all()
    np.testing.assert_allclose(got[1], 1.0)
    import pandas as pd
    s = pd.Series(x[2])
    expect = s.clip(lower=s.mean() - 2.5 * s.std(),
                    upper=s.mean() + 2.5 * s.std()).to_numpy()
    np.testing.assert_allclose(got[2], expect)
