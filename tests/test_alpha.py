"""Alpha DSL: parsing safety, op semantics vs pandas, batch eval, scoring."""

import numpy as np
import pandas as pd
import jax.numpy as jnp
import pytest

from mfm_tpu.alpha.dsl import compile_alpha, evaluate_alphas
from mfm_tpu.alpha.metrics import alpha_summary, information_coefficient


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(0)
    T, N = 60, 12
    close = np.exp(np.cumsum(0.02 * rng.standard_normal((T, N)), axis=0))
    volume = np.exp(rng.normal(10, 1, (T, N)))
    close[rng.random((T, N)) < 0.05] = np.nan
    ret = np.full_like(close, np.nan)
    ret[1:] = close[1:] / close[:-1] - 1
    return {
        "close": jnp.asarray(close),
        "volume": jnp.asarray(volume),
        "ret": jnp.asarray(ret),
    }


def test_rejects_unsafe_syntax():
    for bad in (
        "__import__('os')",
        "close.attr",
        "close[0]",
        "(lambda: 1)()",
        "[x for x in close]",
        "unknown_fn(close)",
    ):
        with pytest.raises(ValueError):
            compile_alpha(bad)


def test_field_collection():
    e = compile_alpha("cs_rank(delta(close, 5)) * volume")
    assert e.fields == ("close", "volume")


def test_ts_ops_match_pandas(panel):
    close = np.asarray(panel["close"])
    out = evaluate_alphas(
        ["ts_mean(close, 5)", "ts_std(close, 5)", "delay(close, 3)",
         "delta(close, 3)", "ts_sum(close, 5)", "ts_product(ret + 1.0, 5)"],
        panel, jit=False,
    )
    df = pd.DataFrame(close)
    np.testing.assert_allclose(np.asarray(out[0]),
                               df.rolling(5, min_periods=1).mean().to_numpy(),
                               rtol=1e-9, atol=1e-12, equal_nan=True)
    np.testing.assert_allclose(np.asarray(out[1]),
                               df.rolling(5, min_periods=2).std().to_numpy(),
                               rtol=1e-7, atol=1e-10, equal_nan=True)
    np.testing.assert_allclose(np.asarray(out[2]), df.shift(3).to_numpy(),
                               equal_nan=True)
    np.testing.assert_allclose(np.asarray(out[3]),
                               (df - df.shift(3)).to_numpy(), equal_nan=True)
    np.testing.assert_allclose(np.asarray(out[4]),
                               df.rolling(5, min_periods=1).sum().to_numpy(),
                               rtol=1e-9, atol=1e-12, equal_nan=True)
    grw = pd.DataFrame(np.asarray(panel["ret"]) + 1.0)
    np.testing.assert_allclose(
        np.asarray(out[5]),
        grw.rolling(5, min_periods=1).apply(np.nanprod, raw=True).to_numpy(),
        rtol=1e-9, atol=1e-12, equal_nan=True)


def test_cs_rank_matches_pandas(panel):
    close = np.asarray(panel["close"])
    out = np.asarray(evaluate_alphas(["cs_rank(close)"], panel, jit=False)[0])
    want = pd.DataFrame(close).rank(axis=1, pct=True, method="first").to_numpy()
    np.testing.assert_allclose(out, want, rtol=1e-9, equal_nan=True)


def test_ts_corr_matches_pandas(panel):
    out = np.asarray(
        evaluate_alphas(["ts_corr(close, volume, 10)"], panel, jit=False)[0]
    )
    c = pd.DataFrame(np.asarray(panel["close"]))
    v = pd.DataFrame(np.asarray(panel["volume"]))
    want = c.rolling(10, min_periods=2).corr(v).to_numpy()
    # pandas uses pairwise-complete obs like ours
    np.testing.assert_allclose(out, want, rtol=1e-6, atol=1e-9, equal_nan=True)


def test_batch_eval_and_summary(panel):
    exprs = [
        "-delta(close, 5)",
        "cs_rank(ts_std(ret, 10))",
        "ts_corr(close, volume, 10)",
        "cs_zscore(log(volume))",
        "where(ret > 0, cs_rank(volume), -cs_rank(volume))",
    ]
    out = evaluate_alphas(exprs, panel)
    assert out.shape == (5,) + panel["close"].shape
    fwd = jnp.concatenate(
        [panel["ret"][1:], jnp.full((1, panel["ret"].shape[1]), jnp.nan)], axis=0
    )
    s = alpha_summary(out, fwd)
    assert s["mean_ic"].shape == (5,)
    assert np.all(np.isfinite(np.asarray(s["coverage"])))


def test_ic_perfect_alpha(panel):
    fwd = jnp.concatenate(
        [panel["ret"][1:], jnp.full((1, panel["ret"].shape[1]), jnp.nan)], axis=0
    )
    ic = information_coefficient(fwd, fwd)  # alpha == target
    m = np.isfinite(np.asarray(ic))
    np.testing.assert_allclose(np.asarray(ic)[m], 1.0, rtol=1e-6)


def test_chunked_batch_matches_single_jit(panel):
    """Chunked compile (VERDICT r3 weak #6) is a pure execution-strategy
    change: results must equal the one-jit batch exactly."""
    from mfm_tpu.alpha.dsl import compile_alpha_batch

    exprs = [f"cs_rank(delta(close, {2 + i % 5}))" for i in range(11)]
    single = compile_alpha_batch(exprs, chunk=None)(dict(panel))
    chunked = compile_alpha_batch(exprs, chunk=4)(dict(panel))
    assert chunked.shape == (11,) + panel["close"].shape
    np.testing.assert_array_equal(np.asarray(single), np.asarray(chunked))


def test_batch_stays_chunked():
    """Guard the chunking itself: above one chunk the batch must be the
    plain-python concatenating wrapper, NOT a single jitted program (the
    unchunked 1,000-expression jit took ~40 s to compile on TPU,
    BASELINE.md; superlinear in program size)."""
    from mfm_tpu.alpha.dsl import compile_alpha_batch

    exprs = [f"cs_rank(delta(close, {2 + i % 5}))" for i in range(250)]
    batch = compile_alpha_batch(exprs, chunk=100)   # 3 sub-jits
    assert not hasattr(batch, "lower")              # jitted fns expose .lower
    single = compile_alpha_batch(exprs[:50], chunk=100)  # one chunk: the jit
    assert hasattr(single, "lower")


@pytest.mark.slow
def test_batch_compile_ceiling(panel):
    """1,000 template expressions must compile+run inside a bounded wall
    (VERDICT r3 weak #6).  The ceiling is generous to stay unflaky while
    still catching a compile-cost blowup at the BASELINE config-5 scale."""
    import time

    from mfm_tpu.alpha.dsl import compile_alpha_batch

    templates = [
        "cs_rank(delta(close, {d}))",
        "-ts_corr(close, volume, {w})",
        "cs_zscore(ts_std(ret, {w}))",
        "decay_linear(cs_demean(ret), {w}) * {c}",
        "where(ret > 0, cs_rank(volume), -cs_rank(ts_mean(volume, {d})))",
        "ts_rank(close, {w}) - cs_rank(delta(volume, {d}))",
    ]
    exprs = [templates[i % len(templates)].format(
        d=2 + i % 9, w=5 + i % 20, c=round(0.5 + (i % 10) / 10, 2))
        for i in range(1000)]
    t0 = time.perf_counter()
    out = compile_alpha_batch(exprs)(dict(panel))
    out.block_until_ready()
    wall = time.perf_counter() - t0
    assert out.shape == (1000,) + panel["close"].shape
    assert wall < 300.0, f"compile+exec took {wall:.1f}s"


def test_ts_cov_matches_pandas(panel):
    import pandas as pd

    from mfm_tpu.alpha.dsl import ts_cov

    x = np.asarray(panel["close"], np.float64)
    y = np.asarray(panel["volume"], np.float64)
    got = np.asarray(ts_cov(panel["close"], panel["volume"], 10))
    exp = np.stack([
        pd.Series(x[:, j]).rolling(10, min_periods=2).cov(pd.Series(y[:, j]))
        for j in range(x.shape[1])
    ], axis=1)
    # pandas pairwise-masks inside cov the same way; compare where both defined
    m = np.isfinite(got) & np.isfinite(exp)
    assert m.sum() > got.size * 0.5
    np.testing.assert_allclose(got[m], exp[m], rtol=1e-4, atol=1e-10)


def test_ts_argmax_argmin(panel):
    from mfm_tpu.alpha.dsl import ts_argmax, ts_argmin

    x = np.asarray(panel["close"], np.float64)
    got_mx = np.asarray(ts_argmax(panel["close"], 7))
    got_mn = np.asarray(ts_argmin(panel["close"], 7))
    T, N = x.shape
    for t in range(6, T, 11):
        for j in range(N):
            win = x[t - 6: t + 1, j]
            if not np.isfinite(win).any():
                assert np.isnan(got_mx[t, j])
                continue
            w = np.where(np.isfinite(win), win, -np.inf)[::-1]
            assert got_mx[t, j] == np.argmax(w)  # 0 = today, recent tie wins
            w2 = np.where(np.isfinite(win), win, np.inf)[::-1]
            assert got_mn[t, j] == np.argmin(w2)


def test_cs_winsorize_matches_pipeline_convention(panel):
    from mfm_tpu.alpha.dsl import cs_winsorize

    x = np.asarray(panel["close"], np.float64)
    got = np.asarray(cs_winsorize(panel["close"], 2.0))
    for t in (5, 30, 55):
        row = x[t]
        m = np.isfinite(row)
        mu, sd = row[m].mean(), row[m].std(ddof=1)
        exp = np.clip(row[m], mu - 2 * sd, mu + 2 * sd)
        np.testing.assert_allclose(got[t][m], exp, rtol=1e-6)
        assert np.isnan(got[t][~m]).all()


def test_cs_neutralize_group_demean(panel):
    from mfm_tpu.alpha.dsl import cs_neutralize

    T, N = np.asarray(panel["close"]).shape
    rng = np.random.default_rng(4)
    g = jnp.asarray(np.broadcast_to(rng.integers(0, 3, N), (T, N)).astype(float))
    out = np.asarray(cs_neutralize(panel["close"], g))
    x = np.asarray(panel["close"], np.float64)
    gi = np.asarray(g[0], int)
    for t in (10, 40):
        for grp in range(3):
            sel = (gi == grp) & np.isfinite(x[t])
            if sel.sum():
                np.testing.assert_allclose(out[t][sel].mean(), 0.0, atol=1e-5)
    # expression-level use parses and evaluates
    from mfm_tpu.alpha.dsl import evaluate_alphas
    p = dict(panel)
    p["industry"] = g
    r = evaluate_alphas(["cs_rank(cs_neutralize(ret, industry))"], p)
    assert r.shape == (1, T, N)


def test_signed_power_expression(panel):
    from mfm_tpu.alpha.dsl import evaluate_alphas

    out = np.asarray(evaluate_alphas(["signed_power(ret, 0.5)"], panel))[0]
    x = np.asarray(panel["ret"], np.float64)
    m = np.isfinite(x)
    np.testing.assert_allclose(out[m], np.sign(x[m]) * np.abs(x[m]) ** 0.5,
                               rtol=1e-5, atol=1e-8)


def test_rank_turnover_semantics(panel):
    from mfm_tpu.alpha.dsl import cs_rank
    from mfm_tpu.alpha.metrics import rank_turnover

    x = panel["close"]
    # a constant-through-time signal has zero turnover wherever defined on
    # consecutive days
    const = jnp.broadcast_to(x[0:1], x.shape)
    to = np.asarray(rank_turnover(const))
    defined = np.isfinite(to[1:])
    np.testing.assert_allclose(to[1:][defined], 0.0, atol=1e-7)
    # loopy check on the real signal
    got = np.asarray(rank_turnover(x))
    r = np.asarray(cs_rank(x))
    t = 30
    m = np.isfinite(r[t]) & np.isfinite(r[t - 1])
    exp = np.abs(r[t][m] - r[t - 1][m]).mean()
    np.testing.assert_allclose(got[t], exp, rtol=1e-6)


def test_quantile_spread_perfect_alpha(panel):
    from mfm_tpu.alpha.metrics import quantile_spread

    fwd = jnp.concatenate(
        [panel["ret"][1:], jnp.full((1, panel["ret"].shape[1]), jnp.nan)],
        axis=0)
    # alpha == forward return: the spread must be positive wherever defined
    sp = np.asarray(quantile_spread(fwd, fwd, q=0.25))
    d = sp[np.isfinite(sp)]
    assert d.size > 10
    assert (d > 0).all()
    # loopy check for one date
    t = 20
    f = np.asarray(fwd, np.float64)[t]
    m = np.isfinite(f)
    ranks = pd.Series(f[m]).rank(pct=True, method="first").to_numpy()
    exp = f[m][ranks > 0.75].mean() - f[m][ranks <= 0.25].mean()
    np.testing.assert_allclose(sp[t], exp, rtol=1e-5)


def test_alpha_summary_includes_new_metrics(panel):
    from mfm_tpu.alpha.dsl import cs_rank

    fwd = jnp.concatenate(
        [panel["ret"][1:], jnp.full((1, panel["ret"].shape[1]), jnp.nan)],
        axis=0)
    # alphas genuinely aligned with the target: the rank of fwd itself and
    # its negation (cs_rank(ret) would rank the SAME-day return — i.i.d. of
    # fwd, so its spread sign would be a coin flip)
    out = jnp.stack([cs_rank(fwd), -cs_rank(fwd)], axis=0)
    s = alpha_summary(out, fwd)
    for k in ("mean_turnover", "mean_spread"):
        assert s[k].shape == (2,)
        assert np.isfinite(np.asarray(s[k])).all()
    # perfectly aligned alpha is positively spread; its negation flips.
    # Exact antisymmetry does NOT hold: the top (r > 1-q) and bottom
    # (r <= q) buckets capture different counts for N not divisible by 1/q.
    sp = np.asarray(s["mean_spread"])
    assert sp[0] > 0 > sp[1]
    # negation approximately preserves turnover (not exactly: the reversal
    # offset (n+1)/n shifts with the per-date valid count)
    to = np.asarray(s["mean_turnover"])
    np.testing.assert_allclose(to[0], to[1], rtol=2e-2)


def test_compile_alpha_scores_matches_unfused_summary():
    """The fused evaluate+score path (the all-A memory plan: summaries
    reduce inside each chunk's jit, the (E, T, N) tensor never
    materializes) must equal scoring the materialized batch — including
    across chunk boundaries."""
    import numpy as np
    import jax.numpy as jnp

    from mfm_tpu.alpha.dsl import compile_alpha_scores, evaluate_alphas
    from mfm_tpu.alpha.metrics import alpha_summary

    rng = np.random.default_rng(3)
    T, N = 40, 12
    close = np.exp(np.cumsum(0.02 * rng.standard_normal((T, N)), axis=0))
    panel = {
        "close": jnp.asarray(close, jnp.float32),
        "ret": jnp.asarray(np.vstack([np.full((1, N), np.nan),
                                      close[1:] / close[:-1] - 1]),
                           jnp.float32),
    }
    fwd = jnp.concatenate([panel["ret"][1:],
                           jnp.full((1, N), jnp.nan, jnp.float32)], axis=0)
    exprs = ["cs_rank(delta(close, 2))", "-ts_corr(close, ret, 5)",
             "cs_zscore(ts_std(ret, 7))", "decay_linear(cs_demean(ret), 4)",
             "ts_rank(close, 6)"]

    base = alpha_summary(evaluate_alphas(exprs, panel), fwd)
    fused = compile_alpha_scores(exprs, chunk=2)(panel, fwd)

    assert set(fused) == set(base)
    for k in base:
        np.testing.assert_allclose(np.asarray(fused[k]), np.asarray(base[k]),
                                   rtol=1e-6, atol=1e-7, equal_nan=True,
                                   err_msg=k)
