"""Quarantine / degraded-mode serving (the guarded daily-update path).

The production contract (serve/guard.py, RiskModel.update_guarded): a date
that trips an input guard is QUARANTINED — it never enters the Newey-West /
vol-regime EWMA carries, so the carry after (good, BAD, good) equals the
carry after (good, good) BITWISE, and the serving layer hands out the last
healthy covariance with an explicit staleness counter.  A clean slab must
pass through the guards bitwise-untouched: guarded serving costs nothing
when nothing is wrong.

Everything here is assert_array_equal, not a tolerance — same discipline as
tests/test_risk_state.py, whose donation rules also apply (guarded updates
donate panels, carries AND guard leaves; copy states before reuse).
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mfm_tpu.config import QuarantinePolicy, RiskModelConfig
from mfm_tpu.data.artifacts import load_risk_state, save_risk_state
from mfm_tpu.models.risk_model import RiskModel
from mfm_tpu.serve.guard import (
    REASON_CAP_NONPOS,
    REASON_DATE_ORDER,
    REASON_NAN_DENSITY,
    REASON_RET_OUTLIER,
    REASON_UNIVERSE_COLLAPSE,
    host_date_reasons,
    reason_names,
)
from mfm_tpu.utils.contracts import assert_max_compiles

T, N, P, Q = 48, 24, 4, 3
K = 1 + P + Q
GCFG = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=48,
                       quarantine=QuarantinePolicy(enabled=True))
UCFG = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=48)


def _panels(seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.normal(0, 0.02, (T, N)),
        rng.lognormal(10, 1, (T, N)),
        rng.normal(size=(T, N, Q)),
        rng.integers(0, P, (T, N)),
        rng.random((T, N)) > 0.05,
    )


def _model(panels, sl=slice(None), cfg=GCFG):
    # fresh OWNED device arrays per call: the fused steps donate their
    # inputs, and jnp.asarray can zero-copy a same-dtype numpy view (the
    # bool valid panel) — donating that alias lets XLA scribble over the
    # fixture's memory.  jnp.array always copies.
    return RiskModel(*(jnp.array(np.asarray(p)[sl]) for p in panels),
                     n_industries=P, config=cfg)


def _copy(state):
    return jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), state)


def _carries(state):
    return jax.tree_util.tree_leaves(
        (state.nw_carry, state.vr_num, state.vr_den))


def _assert_outputs_equal(got, want, msg, rows=None):
    """Bitwise equality over output fields, optionally on a row subset."""
    for i, name in enumerate(want._fields):
        g, w = np.asarray(got[i]), np.asarray(want[i])
        if rows is not None:
            g, w = g[rows[0]], w[rows[1]]
        np.testing.assert_array_equal(g, w, err_msg=f"{msg}: {name}")


def _assert_carries_equal(a, b, msg):
    for i, (x, y) in enumerate(zip(_carries(a), _carries(b))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"{msg}: carry leaf {i}")


def _assert_guard_equal(a, b, msg):
    """Degraded-mode leaves, except quarantine_count (a run that excised a
    bad date has counted it; the run that never saw it has not)."""
    for f in ("last_good_cov", "staleness", "guard_ring", "guard_ring_pos"):
        np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                      np.asarray(getattr(b, f)),
                                      err_msg=f"{msg}: {f}")


def _poison_nan(panels, t, frac=0.6):
    """NaN-poison date ``t``: ``frac`` of the universe's returns go
    non-finite while valid stays True (a poisoned feed, not a thin one)."""
    ret = np.array(panels[0], copy=True)
    ret[t, : int(round(frac * N))] = np.nan
    return (ret,) + tuple(panels[1:])


@pytest.fixture(scope="module")
def panels():
    return _panels()


@pytest.fixture(scope="module")
def pref(panels):
    """Clean guarded prefix: outputs + checkpoint after the first 20 dates."""
    return _model(panels, slice(0, 20), cfg=GCFG).init_state()


def test_clean_guarded_run_is_bitwise_unguarded(panels):
    """Guards on a healthy feed are free: the guarded init and a guarded
    slab update produce outputs BITWISE equal to the unguarded path, nothing
    is quarantined, and served_cov is vr_cov untouched at eigen-valid
    dates."""
    T0 = 20
    out_u, st_u = _model(panels, cfg=UCFG).init_state()
    out_g, _ = _model(panels, cfg=GCFG).init_state()
    _assert_outputs_equal(out_g, out_u, "guarded init vs unguarded init")

    _, gst = _model(panels, slice(0, T0), cfg=GCFG).init_state()
    _, ust = _model(panels, slice(0, T0), cfg=UCFG).init_state()
    o_u, _ = _model(panels, slice(T0, T), cfg=UCFG).update(ust)
    o_g, rep, gst2 = _model(panels, slice(T0, T), cfg=GCFG).update_guarded(gst)
    _assert_outputs_equal(o_g, o_u, "guarded slab vs unguarded slab")
    assert not np.asarray(rep.quarantined).any()
    assert int(np.asarray(gst2.quarantine_count)) == 0

    ev = np.asarray(o_g.eigen_valid, bool)
    assert ev.any()
    np.testing.assert_array_equal(
        np.asarray(rep.served_cov)[ev], np.asarray(o_g.vr_cov)[ev],
        err_msg="served_cov must be vr_cov bitwise at eigen-valid dates")
    np.testing.assert_array_equal(np.asarray(rep.staleness)[ev], 0)


# a poisoned date at absolute index 1 sits inside the q=2 Newey-West lag
# warmup, at index 5 inside the t <= K (=8) invalid region, at 25 in plain
# mid-history — the excision must be bitwise at every boundary
@pytest.mark.parametrize("T0,off", [(1, 0), (2, 3), (20, 5), (40, 6)])
def test_quarantined_date_is_excised_bitwise(panels, T0, off):
    """The carry contract: a guarded run over (.., good, BAD, good, ..)
    lands on the SAME carries — bitwise — as a run whose feed never
    contained the bad date, and every healthy date's outputs match that
    never-saw-it run row for row."""
    t_bad = T0 + off
    bad = _poison_nan(panels, t_bad)

    _, st = _model(panels, slice(0, T0), cfg=GCFG).init_state()
    o_g, rep, st_g = _model(bad, slice(T0, T), cfg=GCFG).update_guarded(
        _copy(st))

    q = np.asarray(rep.quarantined)
    assert q[off] and q.sum() == 1, "exactly the poisoned date quarantines"
    assert int(np.asarray(rep.reasons)[off]) & REASON_NAN_DENSITY

    # reference: the same slab with the bad date cut out of the feed
    keep = np.r_[T0:t_bad, t_bad + 1:T]
    o_r, rep_r, st_r = _model(panels, keep, cfg=GCFG).update_guarded(
        _copy(st))
    assert not np.asarray(rep_r.quarantined).any()

    healthy = np.r_[0:off, off + 1:T - T0]
    _assert_outputs_equal(o_g, o_r, f"T0={T0} off={off} healthy rows",
                          rows=(healthy, slice(None)))
    _assert_carries_equal(st_g, st_r, f"T0={T0} off={off}")
    _assert_guard_equal(st_g, st_r, f"T0={T0} off={off}")
    assert int(np.asarray(st_g.quarantine_count)) == 1


def test_reason_bits_per_check(panels, pref):
    """Each guard trips its own bit, and only its own, on a single-date
    slab: NaN density, return outliers, universe collapse, non-positive
    caps, and the host-side date-order pre-check."""
    _, st = pref
    t = 20  # the first un-fitted date

    def verdict(mod_panels, pre=None):
        _, rep, _ = _model(mod_panels, slice(t, t + 1), cfg=GCFG).\
            update_guarded(_copy(st), pre_reasons=pre)
        return int(np.asarray(rep.reasons)[0])

    ret, cap, styles, ind, valid = (np.array(p, copy=True) for p in panels)

    nan = _poison_nan(panels, t)
    assert verdict(nan) == REASON_NAN_DENSITY
    assert reason_names(REASON_NAN_DENSITY) == ["nan_density"]

    out_ret = np.array(ret, copy=True)
    out_ret[t, : N // 4] += 50.0  # ~25% of cells at ~2500 MADs
    assert verdict((out_ret, cap, styles, ind, valid)) == REASON_RET_OUTLIER

    thin = np.array(valid, copy=True)
    thin[t] = False
    thin[t, :3] = True  # 3 of ~23 — far below half the trailing median
    assert verdict((ret, cap, styles, ind, thin)) == REASON_UNIVERSE_COLLAPSE

    bad_cap = np.array(cap, copy=True)
    bad_cap[t, 5] = -1.0
    assert verdict((ret, bad_cap, styles, ind, valid)) == REASON_CAP_NONPOS

    pre = host_date_reasons(["2020-01-02"], last_date="2020-01-02")
    assert verdict(panels, pre=pre) == REASON_DATE_ORDER


def test_staleness_counts_and_served_cov(panels, pref):
    """Across (good, BAD, BAD, good): staleness reads 0, 1, 2, 0; both bad
    dates serve the good date's covariance bitwise; the recovery date
    serves its own."""
    _, st = pref
    bad = _poison_nan(_poison_nan(panels, 21), 22)
    o, rep, _ = _model(bad, slice(20, 24), cfg=GCFG).update_guarded(_copy(st))

    np.testing.assert_array_equal(np.asarray(rep.quarantined),
                                  [False, True, True, False])
    np.testing.assert_array_equal(np.asarray(rep.staleness), [0, 1, 2, 0])
    vr = np.asarray(o.vr_cov)
    served = np.asarray(rep.served_cov)
    np.testing.assert_array_equal(served[1], vr[0])
    np.testing.assert_array_equal(served[2], vr[0])
    np.testing.assert_array_equal(served[3], vr[3])


def test_guard_leaves_survive_npz_roundtrip(panels, pref, tmp_path):
    """A guarded checkpoint written to disk resumes bitwise: guard leaves
    round-trip exactly and a guarded update from the loaded state matches
    the in-process continuation, verdicts included."""
    _, st = pref
    p = str(tmp_path / "state.npz")
    save_risk_state(p, _copy(st))
    loaded, meta = load_risk_state(p)
    assert meta["kind"] == "risk_state"
    assert loaded.guarded
    _assert_guard_equal(loaded, st, "roundtrip")
    np.testing.assert_array_equal(np.asarray(loaded.quarantine_count),
                                  np.asarray(st.quarantine_count))

    bad = _poison_nan(panels, 23)
    o_mem, rep_mem, st_mem = _model(bad, slice(20, 26), cfg=GCFG).\
        update_guarded(_copy(st))
    o_dsk, rep_dsk, st_dsk = _model(bad, slice(20, 26), cfg=GCFG).\
        update_guarded(loaded)
    _assert_outputs_equal(o_dsk, o_mem, "disk-vs-memory guarded update")
    np.testing.assert_array_equal(np.asarray(rep_dsk.quarantined),
                                  np.asarray(rep_mem.quarantined))
    np.testing.assert_array_equal(np.asarray(rep_dsk.served_cov),
                                  np.asarray(rep_mem.served_cov))
    _assert_carries_equal(st_dsk, st_mem, "disk-vs-memory carry")
    _assert_guard_equal(st_dsk, st_mem, "disk-vs-memory guard")


def test_changed_policy_rejects_checkpoint(panels, pref):
    """Quarantine thresholds are math identity (they decide which dates
    enter the EWMA sums): a checkpoint fitted under one policy must refuse
    to continue under another."""
    _, st = pref
    retuned = RiskModelConfig(
        eigen_n_sims=8, eigen_sim_length=48,
        quarantine=QuarantinePolicy(enabled=True, mad_k=5.0))
    with pytest.raises(ValueError, match="stamp"):
        _model(panels, slice(20, T), cfg=retuned).update_guarded(_copy(st))


def test_update_guarded_refusals(panels, pref):
    """update_guarded refuses a quarantine-disabled config outright, and a
    state lacking the degraded-mode leaves (initialized unguarded)."""
    _, st = pref
    with pytest.raises(ValueError, match="quarantine.enabled"):
        _model(panels, slice(20, T), cfg=UCFG).update_guarded(_copy(st))

    stripped = dataclasses.replace(
        _copy(st), last_good_cov=None, staleness=None, quarantine_count=None,
        guard_ring=None, guard_ring_pos=None)
    assert not stripped.guarded
    with pytest.raises(ValueError, match="degraded-mode leaves"):
        _model(panels, slice(20, T), cfg=GCFG).update_guarded(stripped)


def test_guarded_daily_loop_compiles_once(panels, pref):
    """The guarded serving loop keeps the compile-once contract — and a
    quarantine verdict mid-loop must NOT retrace (the verdict is data, not
    program structure)."""
    _, st = pref
    bad = _poison_nan(panels, 24)
    st_seq = _copy(st)
    # warm the single-date guarded signature
    _, _, st_seq = _model(bad, slice(20, 21), cfg=GCFG).update_guarded(st_seq)
    hits = 0
    with assert_max_compiles(1, what="guarded daily loop"):
        for t in range(21, 28):
            _, rep, st_seq = _model(bad, slice(t, t + 1), cfg=GCFG).\
                update_guarded(st_seq)
            hits += int(np.asarray(rep.quarantined)[0])
    assert hits == 1, "the poisoned date must quarantine inside the loop"
    assert int(np.asarray(st_seq.quarantine_count)) == 1


def test_host_date_reasons_flags_order_violations():
    """Non-monotone and duplicate dates get REASON_DATE_ORDER; the monotone
    subsequence survives (a flagged date does not become the new
    watermark)."""
    out = host_date_reasons(
        ["2020-01-02", "2020-01-02", "2020-01-03", "2020-01-01"],
        last_date="2020-01-01")
    np.testing.assert_array_equal(
        out, [0, REASON_DATE_ORDER, 0, REASON_DATE_ORDER])
    assert host_date_reasons(["2020-01-02"], last_date="2020-01-02")[0] \
        == REASON_DATE_ORDER
    assert not host_date_reasons(["2020-01-02", "2020-01-03"]).any()
