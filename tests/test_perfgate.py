"""The perf-regression sentinel (tools/perfgate.py): metric extraction,
same-backend baseline selection, tolerance bands, the overhead floor, and
the non-zero exit on an injected synthetic regression."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import perfgate  # noqa: E402


def _risk_rec(value, backend="cpu", **extra):
    rec = {"metric": "csi300_riskmodel_e2e_wall", "value": value,
           "backend": backend}
    rec.update(extra)
    return rec


def _write_traj(d, *recs):
    for i, rec in enumerate(recs, 1):
        with open(os.path.join(d, f"BENCH_r{i:02d}.json"), "w") as fh:
            json.dump({"n": i, "rc": 0, "parsed": rec}, fh)


def test_extract_metrics_per_config():
    m = perfgate.extract_metrics(_risk_rec(
        12.5, daily_update_latency_s=0.04, telemetry_overhead_frac=0.001,
        tracing_overhead_frac=0.0008))
    assert m == {"e2e_wall_s": 12.5, "daily_update_latency_s": 0.04,
                 "telemetry_overhead_frac": 0.001,
                 "tracing_overhead_frac": 0.0008}
    assert perfgate.extract_metrics(
        {"metric": "portfolio_query_throughput", "value": 9000}) == \
        {"portfolios_per_sec": 9000}
    assert perfgate.extract_metrics(
        {"metric": "scenario_throughput", "value": 400}) == \
        {"scenarios_per_sec": 400}
    # failed rounds (value null) and junk extract to nothing
    assert perfgate.extract_metrics(_risk_rec(None)) == {}
    assert perfgate.extract_metrics("nope") == {}


def test_gate_passes_within_band_and_fails_past_it(tmp_path):
    _write_traj(str(tmp_path), _risk_rec(10.0), _risk_rec(11.0))
    traj = perfgate.load_trajectory(str(tmp_path))
    assert [t["name"] for t in traj] == ["BENCH_r01.json", "BENCH_r02.json"]

    ok = perfgate.gate_record(_risk_rec(12.0), traj)   # 10.0 * 1.25 = 12.5
    assert ok["regressions"] == []
    (check,) = ok["checks"]
    assert check["baseline"] == 10.0 and check["baseline_run"] == \
        "BENCH_r01.json"

    bad = perfgate.gate_record(_risk_rec(13.0), traj)
    assert [c["metric"] for c in bad["regressions"]] == ["e2e_wall_s"]
    # a widened band clears it
    assert perfgate.gate_record(_risk_rec(13.0), traj,
                                tolerances={"e2e_wall_s": 0.5})[
        "regressions"] == []


def test_higher_is_better_direction(tmp_path):
    _write_traj(str(tmp_path),
                {"metric": "portfolio_query_throughput", "value": 10000,
                 "backend": "cpu"})
    traj = perfgate.load_trajectory(str(tmp_path))
    cur = {"metric": "portfolio_query_throughput", "value": 7000,
           "backend": "cpu"}                     # 10000 * 0.8 = 8000 floor
    assert perfgate.gate_record(cur, traj)["regressions"]
    cur["value"] = 8500
    assert perfgate.gate_record(cur, traj)["regressions"] == []


def test_cross_backend_records_never_compare(tmp_path):
    _write_traj(str(tmp_path), _risk_rec(1.0, backend="tpu"))
    verdict = perfgate.gate_record(_risk_rec(50.0, backend="cpu"),
                                   perfgate.load_trajectory(str(tmp_path)))
    assert verdict["checks"] == [] and verdict["regressions"] == []
    assert any("baseline" in s["reason"] for s in verdict["skipped"])


def test_overhead_floor_suppresses_sub_budget_jitter(tmp_path):
    _write_traj(str(tmp_path), _risk_rec(
        10.0, telemetry_overhead_frac=0.0002, tracing_overhead_frac=0.0002))
    traj = perfgate.load_trajectory(str(tmp_path))
    # 4x the baseline fraction but far under the 1% budget: not a regression
    ok = perfgate.gate_record(_risk_rec(
        10.0, telemetry_overhead_frac=0.0008, tracing_overhead_frac=0.0008),
        traj)
    assert ok["regressions"] == []
    # past the band AND past the budget: caught
    bad = perfgate.gate_record(_risk_rec(
        10.0, tracing_overhead_frac=0.02), traj)
    assert [c["metric"] for c in bad["regressions"]] == \
        ["tracing_overhead_frac"]


def test_unreadable_trajectory_files_are_skipped(tmp_path):
    _write_traj(str(tmp_path), _risk_rec(10.0))
    with open(os.path.join(str(tmp_path), "BENCH_r99.json"), "w") as fh:
        fh.write('{"torn')
    traj = perfgate.load_trajectory(str(tmp_path))
    assert [t["name"] for t in traj] == ["BENCH_r01.json"]


@pytest.mark.slow
def test_cli_exits_nonzero_on_injected_regression(tmp_path):
    """The acceptance drill: a synthetic slowdown against a synthetic
    trajectory makes ``perfgate`` (and therefore ``bench.py --compare`` and
    ``tools/bench_all.sh``) exit non-zero."""
    d = str(tmp_path)
    _write_traj(d, _risk_rec(10.0, daily_update_latency_s=0.05))
    cur = os.path.join(d, "current.json")

    def run(rec, *extra):
        with open(cur, "w") as fh:
            json.dump(rec, fh)
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "perfgate.py"),
             cur, "--root", d, *extra],
            capture_output=True, text=True, timeout=120)

    good = run(_risk_rec(10.4, daily_update_latency_s=0.051))
    assert good.returncode == 0, good.stdout + good.stderr
    assert "PASS" in good.stdout

    bad = run(_risk_rec(20.0, daily_update_latency_s=0.2))
    assert bad.returncode == 1
    assert "REGRESSED" in bad.stdout and "FAIL" in bad.stdout

    # per-metric overrides rescue a deliberate trade-off
    widened = run(_risk_rec(20.0, daily_update_latency_s=0.2),
                  "--tol", "e2e_wall_s=1.5", "--tol",
                  "daily_update_latency_s=4.0")
    assert widened.returncode == 0, widened.stdout

    # a non-record input is a usage error (rc 2), not a pass
    with open(cur, "w") as fh:
        json.dump({"hello": 1}, fh)
    assert subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perfgate.py"), cur,
         "--root", d], capture_output=True, text=True,
        timeout=120).returncode == 2


def test_universe_n_backfill_and_explicit():
    """(backend, universe_n) baseline keying (PR 11): explicit universe_n
    wins; pre-PR-11 records backfill from the metric family; non-universe
    records key to None and keep gating across universes."""
    assert perfgate.universe_n(_risk_rec(10.0)) == 300
    assert perfgate.universe_n(
        {"metric": "riskmodel_e2e_wall", "value": 5.0}) == 300
    assert perfgate.universe_n(
        {"metric": "alla_full_pipeline_wall", "value": 50.0}) == 5000
    assert perfgate.universe_n(
        {"metric": "riskmodel_e2e_wall", "value": 5.0,
         "universe_n": 5000}) == 5000
    assert perfgate.universe_n(
        {"metric": "portfolio_query_throughput", "value": 9000}) is None
    assert perfgate.universe_n("junk") is None


def test_gate_keys_baselines_by_universe(tmp_path):
    """An N=5000 wall must never be held to the N=300 trajectory: same
    backend, same metric namespace, different universe_n -> no baseline
    (skip), not a 10x 'regression'."""
    _write_traj(str(tmp_path), _risk_rec(10.0))  # csi300 -> universe_n 300
    traj = perfgate.load_trajectory(str(tmp_path))

    big = {"metric": "riskmodel_e2e_wall", "value": 100.0, "backend": "cpu",
           "universe_n": 5000, "e2e_wall_s": 100.0}
    verdict = perfgate.gate_record(big, traj)
    assert verdict["universe_n"] == 5000
    assert verdict["regressions"] == []
    assert all(not c["regressed"] for c in verdict["checks"])
    assert any("universe_n=5000" in s["reason"] for s in verdict["skipped"])

    # same universe still gates: a 300-keyed record past the band fails
    slow = _risk_rec(13.0, universe_n=300)
    verdict2 = perfgate.gate_record(slow, traj)
    assert [c["metric"] for c in verdict2["regressions"]] == ["e2e_wall_s"]
