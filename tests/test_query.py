"""Batched portfolio-query engine (mfm_tpu/serve/query.py): math vs
NumPy, the bitwise batch==singles contract (including ragged batches
padded across different buckets), padding/validation, guarded-checkpoint
refusal, and the <=1-compile-per-bucket steady state."""

import types

import numpy as np
import pytest

from mfm_tpu.serve import QueryEngine, bucket_for
from mfm_tpu.utils.contracts import assert_max_compiles

K = 5


def _cov(seed=0, k=K, dtype=np.float64):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((k, k)) / np.sqrt(k)
    return ((a @ a.T + 1e-3 * np.eye(k)) * 1e-4).astype(dtype)


@pytest.fixture
def factor_engine():
    rng = np.random.default_rng(1)
    return QueryEngine(_cov(), benchmarks={"idx": rng.standard_normal(K)})


@pytest.fixture
def stock_engine():
    rng = np.random.default_rng(2)
    n = 11
    X = rng.standard_normal((n, K))
    svar = (0.02 * rng.random(n)) ** 2
    bench = rng.dirichlet(np.ones(n))
    return QueryEngine(_cov(), exposures=X, specific_var=svar,
                       stocks=[f"s{i}" for i in range(n)],
                       benchmarks={"bmk": bench})


def test_bucket_ladder():
    assert [bucket_for(n) for n in (1, 8, 9, 32, 33, 1000)] == \
        [8, 8, 32, 32, 128, 2048]
    assert bucket_for(100_000) == 131072
    assert bucket_for(1_000_000) == 2097152
    with pytest.raises(ValueError):
        bucket_for(0)


def test_factor_math_vs_numpy(factor_engine):
    eng = factor_engine
    rng = np.random.default_rng(3)
    W = rng.standard_normal((7, K))
    res = eng.query(W, bench=["idx"] * 7)
    F = np.asarray(_cov())
    xb = np.asarray(eng._bx)  # benchmark table; row 1 is "idx"
    for i in range(7):
        x = W[i]
        Fx = F @ x
        fvar = x @ Fx
        np.testing.assert_allclose(res.factor_var[i], fvar, rtol=1e-12)
        np.testing.assert_allclose(res.total_vol[i], np.sqrt(fvar),
                                   rtol=1e-12)
        np.testing.assert_allclose(res.marginal[i], Fx, rtol=1e-12)
        np.testing.assert_allclose(res.contribution[i], x * Fx, rtol=1e-12)
        # Euler: contributions sum exactly to the factor variance
        np.testing.assert_allclose(res.contribution[i].sum(), fvar,
                                   rtol=1e-10)
        a = x - xb[1]
        np.testing.assert_allclose(res.active_risk[i],
                                   np.sqrt(a @ F @ a), rtol=1e-12)
        np.testing.assert_allclose(res.beta[i],
                                   (x @ F @ xb[1]) / (xb[1] @ F @ xb[1]),
                                   rtol=1e-12)
    assert float(res.specific_var[i]) == 0.0  # factor space: no idio term


def test_stock_math_vs_numpy(stock_engine):
    eng = stock_engine
    rng = np.random.default_rng(4)
    n = eng.N
    W = rng.dirichlet(np.ones(n), size=3)
    res = eng.query(W, bench=["bmk", None, "bmk"])
    F = _cov()
    X = np.asarray(eng._X)
    svar = np.asarray(eng._svar)
    wb = np.asarray(eng._bw)[1]
    for i in range(3):
        w = W[i]
        x = w @ X
        fvar = x @ F @ x
        sv = np.sum(w * w * svar)
        np.testing.assert_allclose(res.total_vol[i], np.sqrt(fvar + sv),
                                   rtol=1e-12)
        np.testing.assert_allclose(res.specific_var[i], sv, rtol=1e-12)
    # benchmark row: active risk includes the specific leg; beta via
    # cov(p,b)/var(b) with the idio cross term
    w, i = W[0], 0
    x, xbv = w @ X, wb @ X
    a = x - xbv
    avar = a @ F @ a + np.sum((w - wb) ** 2 * svar)
    var_b = xbv @ F @ xbv + np.sum(wb * wb * svar)
    cov_pb = x @ F @ xbv + np.sum(w * wb * svar)
    np.testing.assert_allclose(res.active_risk[i], np.sqrt(avar), rtol=1e-12)
    np.testing.assert_allclose(res.beta[i], cov_pb / var_b, rtol=1e-12)
    # no benchmark (row 1): beta vs the zero portfolio is NaN, never 0/0
    assert np.isnan(res.beta[1])


@pytest.mark.parametrize("space", ["factor", "stock"])
def test_batch_equals_singles_bitwise(space, factor_engine, stock_engine):
    """One vmapped batch of B portfolios == B single-portfolio queries,
    BITWISE — even though the ragged batch pads to a LARGER bucket than
    the singles do (row-local dataflow; the compile contract depends on
    cross-bucket determinism holding)."""
    eng = factor_engine if space == "factor" else stock_engine
    bname = "idx" if space == "factor" else "bmk"
    rng = np.random.default_rng(5)
    B = 13                      # bucket 32; singles pad to bucket 8
    W = rng.standard_normal((B, eng.N))
    bench = [bname if i % 3 == 0 else None for i in range(B)]
    batch = eng.query(W, bench=bench)
    for i in range(B):
        one = eng.query(W[i], bench=[bench[i]])
        for field in batch._fields:
            got = np.asarray(getattr(batch, field))[i]
            want = np.asarray(getattr(one, field))[0]
            assert np.array_equal(got, want, equal_nan=True), \
                f"{field} row {i}: batch != single (bitwise)"


def test_pad_batch_validation(factor_engine):
    eng = factor_engine
    with pytest.raises(ValueError, match="expects 5 values"):
        eng.pad_batch(np.zeros((2, 4)))
    with pytest.raises(ValueError, match="bucket 8 < batch"):
        eng.pad_batch(np.zeros((9, K)), bucket=8)
    with pytest.raises(ValueError, match="3 benchmark entries"):
        eng.pad_batch(np.zeros((2, K)), bench=["idx", None, "idx"])
    with pytest.raises(KeyError):
        eng.pad_batch(np.zeros((2, K)), bench=["nope", None])
    w, bidx, B, bucket = eng.pad_batch(np.zeros((3, K)), bench=["idx"] * 3)
    assert (B, bucket) == (3, 8)
    assert w.shape == (8, K) and bidx.shape == (8,)
    assert bidx.dtype == np.int32
    assert list(np.asarray(bidx)) == [1, 1, 1, 0, 0, 0, 0, 0]


def test_engine_input_validation():
    with pytest.raises(ValueError, match="must be \\(K, K\\)"):
        QueryEngine(np.zeros((2, 3)))
    with pytest.raises(ValueError, match="non-finite"):
        QueryEngine(np.full((2, 2), np.nan))
    with pytest.raises(ValueError, match="needs exposures"):
        QueryEngine(_cov(), specific_var=np.ones(K))
    with pytest.raises(ValueError, match="finite"):
        QueryEngine(_cov(), benchmarks={"b": [np.nan] * K})


def test_from_risk_state_requires_guarded():
    with pytest.raises(ValueError, match="quarantine"):
        QueryEngine.from_risk_state(types.SimpleNamespace(guarded=False))


def test_from_risk_state_names_and_staleness():
    state = types.SimpleNamespace(guarded=True, last_good_cov=_cov(k=4),
                                  staleness=np.int32(2))
    meta = {"style_names": ["size"], "industry_codes": [10, 20]}
    eng = QueryEngine.from_risk_state(state, meta)
    assert eng.factor_names == ["country", "10", "20", "size"]
    assert eng.staleness == 2 and eng.space == "factor"
    # meta from a foreign checkpoint (wrong K): fall back to f0..fK
    eng2 = QueryEngine.from_risk_state(state, {"style_names": ["a"],
                                               "industry_codes": [1]})
    assert eng2.factor_names == ["f0", "f1", "f2", "f3"]


def test_steady_state_compile_contract(factor_engine):
    """Same-bucket batches after warmup never recompile (the serving
    loop's <=1-compile-per-bucket contract, telemetry or not)."""
    eng = factor_engine
    rng = np.random.default_rng(6)
    eng.query(rng.standard_normal((6, K)))          # warmup bucket 8
    eng.query(rng.standard_normal((20, K)))         # warmup bucket 32
    with assert_max_compiles(1, "steady-state query buckets"):
        for b in (3, 8, 17, 32, 5, 30):
            res = eng.query(rng.standard_normal((b, K)))
            assert res.total_vol.shape == (b,)
