"""Incremental + mixed-precision eigen adjustment through the public
RiskModel API (``config.eigen_incremental`` / ``config.eigen_mc_dtype``).

Contracts pinned here:

- **Bitwise suffix**: under ``eigen_incremental=True`` the daily serving
  loop (init on a prefix + per-date / slab updates) reproduces the
  full-history run BITWISE — outputs and the ``(eig_R, eig_p, eig_n)``
  carry both, via ``assert_array_equal``, never a tolerance.  The contract
  holds for the jitted production entry points (the only paths a serving
  process runs); eager stage-by-stage replays may differ in fusion order
  and are out of scope.
- **Compile-once serving**: the steady-state one-date update reuses one
  compiled signature — ``sim_length`` is a host-side mirror (aux data),
  not a traced operand, so the growing history never retraces the step.
- **Quarantine excision**: a quarantined date consumes no draw column and
  leaves the eigen carry untouched, so (good, BAD, good) lands on the
  same carry and post-BAD outputs as (good, good).
- **Checkpoint round trip**: the eigen carry and the frozen draw tensor
  (including bf16 draws, which numpy's npz cannot represent natively)
  survive ``save_risk_state``/``load_risk_state`` bitwise.
- **bf16 statistical parity**: the bfloat16 Monte-Carlo path is a
  different random realization, so its gate is the USE4 eigenfactor bias
  stat staying within the frozen budget in tools/parity_budget.json
  (entry ``eigen_mc_bf16``), not bitwise equality.
"""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mfm_tpu.config import QuarantinePolicy, RiskModelConfig
from mfm_tpu.data.artifacts import load_risk_state, save_risk_state
from mfm_tpu.models.bias import eigenfactor_bias_stat
from mfm_tpu.models.eigen import draw_bucket, simulated_eigen_draws
from mfm_tpu.models.risk_model import RiskModel
from mfm_tpu.utils.contracts import assert_max_compiles

T, N, P, Q = 14, 24, 3, 2
K = 1 + P + Q
CFG = RiskModelConfig(eigen_n_sims=8, eigen_incremental=True)
GCFG = RiskModelConfig(eigen_n_sims=8, eigen_incremental=True,
                       quarantine=QuarantinePolicy(enabled=True))

_BUDGET_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools", "parity_budget.json")


def _panels(seed=0):
    rng = np.random.default_rng(seed)
    return (
        (rng.standard_normal((T, N)) * 0.02).astype(np.float32),
        rng.uniform(1.0, 5.0, (T, N)).astype(np.float32),
        rng.standard_normal((T, N, Q)).astype(np.float32),
        rng.integers(0, P, (T, N)).astype(np.int32),
        rng.random((T, N)) > 0.1,
    )


def _model(panels, sl=slice(None), cfg=CFG):
    # fresh owned arrays per call: the fused steps donate their inputs
    return RiskModel(*(jnp.array(np.asarray(p)[sl]) for p in panels),
                     n_industries=P, config=cfg)


def _copy(state):
    return jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), state)


def _eig_carries(state):
    return jax.tree_util.tree_leaves(
        (state.nw_carry, state.vr_num, state.vr_den,
         state.eig_R, state.eig_p, state.eig_n))


def _assert_outputs_equal(got, want, msg):
    for i, name in enumerate(want._fields):
        np.testing.assert_array_equal(np.asarray(got[i]),
                                      np.asarray(want[i]),
                                      err_msg=f"{msg}: {name}")


def _assert_carries_equal(a, b, msg):
    for x, y in zip(_eig_carries(a), _eig_carries(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=msg)


@pytest.fixture(scope="module")
def panels():
    return _panels()


@pytest.fixture(scope="module")
def full(panels):
    return _model(panels).init_state()


# T0 = 5 sits inside the t <= K invalid region (K = 6); 9 is a plain
# mid-history cut; 13 forces the one-date (duplicated-lane) update path
@pytest.mark.parametrize("T0", [5, 9, 13])
def test_incremental_update_is_bitwise_suffix_of_full_run(panels, full, T0):
    full_out, full_state = full
    assert full_state.sim_covs is None          # incremental carries moments,
    assert full_state.eig_R is not None         # not materialized sim covs
    out0, st = _model(panels, slice(0, T0)).init_state()
    _assert_outputs_equal(
        out0, jax.tree_util.tree_map(lambda x: x[:T0], full_out),
        f"T0={T0} prefix")

    st_seq = _copy(st)
    o, st_seq = _model(panels, slice(T0, T0 + 1)).update(st_seq)
    rows = [o]
    with assert_max_compiles(1, what="incremental daily update loop"):
        for t in range(T0 + 1, T):
            o, st_seq = _model(panels, slice(t, t + 1)).update(st_seq)
            rows.append(o)
    got = type(full_out)(*[
        np.concatenate([np.asarray(r[i]) for r in rows], axis=0)
        for i in range(len(full_out))])
    _assert_outputs_equal(
        got, jax.tree_util.tree_map(lambda x: x[T0:], full_out),
        f"T0={T0} sequential suffix")

    # the whole remainder as ONE slab
    o_slab, st_slab = _model(panels, slice(T0, T)).update(st)
    _assert_outputs_equal(
        o_slab, jax.tree_util.tree_map(lambda x: x[T0:], full_out),
        f"T0={T0} slab suffix")

    _assert_carries_equal(st_seq, st_slab, f"T0={T0} seq-vs-slab eig carry")
    _assert_carries_equal(st_slab, full_state,
                          f"T0={T0} slab-vs-full eig carry")
    # the host mirror tracks the consumed history length
    assert st_seq.sim_length == T == full_state.sim_length


def test_incremental_guarded_excision_is_bitwise(panels):
    """(good, BAD, good) == (good, good) on the eigen carry and every
    post-BAD output: the quarantined date consumes no draw column."""
    T0 = 8

    def gmodel(sl, override=None):
        ps = [np.asarray(p)[sl] for p in panels]
        if override is not None:
            ps[0] = override
        return _model(ps, cfg=GCFG)

    _, stA = gmodel(slice(0, T0)).init_state()
    stB = _copy(stA)
    oA1, _, stA = gmodel(slice(T0, T0 + 1)).update_guarded(stA)
    oB1, _, stB = gmodel(slice(T0, T0 + 1)).update_guarded(stB)

    # path B serves a poisoned date in between
    bad_ret = np.full((1, N), np.nan, np.float32)
    _, repB, stB = gmodel(slice(T0, T0 + 1), bad_ret).update_guarded(stB)
    assert bool(np.asarray(repB.quarantined)[0])

    oA2, _, stA = gmodel(slice(T0 + 1, T0 + 2)).update_guarded(stA)
    oB2, _, stB = gmodel(slice(T0 + 1, T0 + 2)).update_guarded(stB)

    for f in ("eig_R", "eig_p", "eig_n"):
        np.testing.assert_array_equal(np.asarray(getattr(stA, f)),
                                      np.asarray(getattr(stB, f)),
                                      err_msg=f"excision: {f}")
    _assert_outputs_equal(oB2, oA2, "post-quarantine output")
    # the mirror is an upper bound (counts the served, quarantined date)
    assert stB.sim_length == stA.sim_length + 1


@pytest.mark.parametrize("mc_dtype", [None, "bfloat16"])
def test_incremental_state_npz_roundtrip_is_bitwise(panels, tmp_path,
                                                    mc_dtype):
    """The eigen carry AND the frozen draw tensor survive the checkpoint
    bitwise — including bf16 draws, which npz stores as a uint16
    bit-pattern view plus the dtype name in the meta."""
    cfg = RiskModelConfig(eigen_n_sims=8, eigen_incremental=True,
                          eigen_mc_dtype=mc_dtype)
    T0 = 9
    _, st = _model(panels, slice(0, T0), cfg=cfg).init_state()
    if mc_dtype:
        assert st.eig_draws.dtype == jnp.dtype(mc_dtype)
    p = str(tmp_path / "state.npz")
    save_risk_state(p, _copy(st), meta={"note": "inc"})
    loaded, meta = load_risk_state(p)
    assert meta["kind"] == "risk_state"
    assert loaded.stamp == st.stamp
    assert loaded.sim_length == st.sim_length
    assert loaded.sim_covs is None and st.sim_covs is None
    assert loaded.eig_draws.dtype == st.eig_draws.dtype
    np.testing.assert_array_equal(np.asarray(loaded.eig_draws),
                                  np.asarray(st.eig_draws))
    _assert_carries_equal(loaded, st, "roundtrip eig carry")

    o_mem, st_mem = _model(panels, slice(T0, T), cfg=cfg).update(st)
    o_disk, st_disk = _model(panels, slice(T0, T), cfg=cfg).update(loaded)
    _assert_outputs_equal(o_disk, o_mem, "disk-vs-memory update")
    _assert_carries_equal(st_disk, st_mem, "disk-vs-memory eig carry")


def test_draw_bucket_prefix_stability():
    """A bucket rollover extends the draw tensor without rewriting the
    consumed prefix — the property the bitwise-suffix contract stands on."""
    assert draw_bucket(1) == 64 and draw_bucket(64) == 64
    assert draw_bucket(65) == 128 and draw_bucket(1390) == 2048
    key = jax.random.key(0)
    d64 = simulated_eigen_draws(key, K, 64, 8, dtype=jnp.float32)
    d128 = simulated_eigen_draws(key, K, 128, 8, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(d128[..., :64]),
                                  np.asarray(d64))
    # and the bf16 tensor holds the same property in its own realization
    b64 = simulated_eigen_draws(key, K, 64, 8, dtype=jnp.float32,
                                mc_dtype="bfloat16")
    b128 = simulated_eigen_draws(key, K, 128, 8, dtype=jnp.float32,
                                 mc_dtype="bfloat16")
    assert b64.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(b128[..., :64]),
                                  np.asarray(b64))


def test_bf16_parity_within_budget():
    """The bfloat16 Monte-Carlo path must keep the USE4 eigenfactor bias
    stat within the frozen budget (tools/parity_budget.json:
    ``eigen_mc_bf16``) at the budget's own documented shape and seed."""
    with open(_BUDGET_PATH) as fh:
        entry = json.load(fh)["eigen_mc_bf16"]
    shp = entry["shape"]
    Tb, Nb = shp["T"], shp["N"]
    Pb, Qb, Mb = shp["n_industries"], shp["n_styles"], shp["n_sims"]
    rng = np.random.default_rng(entry["seed"])
    panels = (
        (rng.standard_normal((Tb, Nb)) * 0.02).astype(np.float32),
        rng.uniform(1.0, 5.0, (Tb, Nb)).astype(np.float32),
        rng.standard_normal((Tb, Nb, Qb)).astype(np.float32),
        rng.integers(0, Pb, (Tb, Nb)).astype(np.int32),
        rng.uniform(size=(Tb, Nb)) > 0.05,
    )
    stats = {}
    for mc in (None, "bfloat16"):
        cfg = RiskModelConfig(eigen_n_sims=Mb, eigen_sim_length=Tb,
                              eigen_mc_dtype=mc)
        out = RiskModel(*(jnp.array(p) for p in panels),
                        n_industries=Pb, config=cfg).run()
        stats[mc] = np.asarray(eigenfactor_bias_stat(
            out.eigen_cov, out.eigen_valid, out.factor_ret))
    delta = np.max(np.abs(np.abs(stats["bfloat16"] - 1.0)
                          - np.abs(stats[None] - 1.0)))
    assert delta <= entry["bias_abs_delta"], (
        f"bf16 bias-stat delta {delta:.4f} exceeds the frozen budget "
        f"{entry['bias_abs_delta']} — the mixed-precision path regressed")


def test_incremental_config_and_injection_validation(panels):
    with pytest.raises(ValueError, match="bfloat16"):
        RiskModelConfig(eigen_mc_dtype="float16")
    # pinned sim_length contradicts the growing-panel semantics
    with pytest.raises(ValueError, match="eigen_incremental"):
        RiskModelConfig(eigen_incremental=True, eigen_sim_length=48)
    # injected randomness would break the bitwise-suffix contract
    with pytest.raises(ValueError, match="injected key/sim_covs"):
        _model(panels).init_state(key=jax.random.key(3))
    with pytest.raises(ValueError, match="injected key/sim_covs"):
        _model(panels).init_state(
            sim_covs=jnp.zeros((8, K, K), jnp.float32))
