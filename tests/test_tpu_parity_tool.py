"""tools/tpu_parity.py: the hardware-parity gate tool itself, run hermetically
on CPU at tiny shapes.  Same-backend captures must agree bitwise (the
determinism half of the gate); the verdict must still flag the identical
platforms so a mis-pinned run can never masquerade as hardware parity."""

import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture(scope="module")
def tool():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "tpu_parity.py")
    spec = importlib.util.spec_from_file_location("tpu_parity_tool", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("stage", ["risk", "factors"])
def test_same_backend_capture_is_deterministic(tool, stage, tmp_path, capsys):
    shape = ["--dates", "40", "--stocks", "12", "--industries", "3",
             "--styles", "2", "--sims", "4", "--stage", stage,
             "--platform", "cpu"]
    a, b = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    tool.main(["run", "--out", a, *shape])
    tool.main(["run", "--out", b, *shape])
    capsys.readouterr()

    with pytest.raises(SystemExit) as ei:
        tool.main(["compare", a, b, "--gate", "1e-5"])
    assert ei.value.code == 1  # identical platforms must fail the verdict
    lines = [json.loads(x) for x in capsys.readouterr().out.splitlines()]
    verdict = lines[-1]
    assert verdict["failed"] == ["platforms:identical"]
    assert verdict["platforms"] == ["cpu", "cpu"]
    per_stage = {r["stage"]: r for r in lines[:-1]}
    assert len(per_stage) >= 6  # both halves capture a real stage set
    for name, rec in per_stage.items():
        assert rec["max_rel"] == 0.0, (name, rec)  # bitwise same backend


def test_incomparable_captures_rejected(tool, tmp_path, capsys):
    shape = ["--dates", "30", "--stocks", "10", "--industries", "3",
             "--styles", "2", "--sims", "4", "--platform", "cpu"]
    a, b = str(tmp_path / "risk.npz"), str(tmp_path / "fac.npz")
    tool.main(["run", "--out", a, *shape, "--stage", "risk"])
    tool.main(["run", "--out", b, *shape, "--stage", "factors"])
    capsys.readouterr()
    with pytest.raises(SystemExit, match="incomparable"):
        tool.main(["compare", a, b])


def test_empty_stage_set_rejected(tool, tmp_path):
    import numpy as np

    a, b = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    np.savez(a, platform=np.array("tpu"))
    np.savez(b, platform=np.array("cpu"))
    with pytest.raises(SystemExit, match="nothing compared"):
        tool.main(["compare", a, b])
