"""tools/tpu_parity.py: the hardware-parity gate tool itself, run hermetically
on CPU at tiny shapes.  Same-backend captures must agree bitwise (the
determinism half of the gate); the verdict must still flag the identical
platforms so a mis-pinned run can never masquerade as hardware parity."""

import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture(scope="module")
def tool():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "tpu_parity.py")
    spec = importlib.util.spec_from_file_location("tpu_parity_tool", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("stage", ["risk", "factors"])
def test_same_backend_capture_is_deterministic(tool, stage, tmp_path, capsys):
    shape = ["--dates", "40", "--stocks", "12", "--industries", "3",
             "--styles", "2", "--sims", "4", "--stage", stage,
             "--platform", "cpu"]
    a, b = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    tool.main(["run", "--out", a, *shape])
    tool.main(["run", "--out", b, *shape])
    capsys.readouterr()

    with pytest.raises(SystemExit) as ei:
        tool.main(["compare", a, b, "--gate", "1e-5"])
    assert ei.value.code == 1  # identical platforms must fail the verdict
    lines = [json.loads(x) for x in capsys.readouterr().out.splitlines()]
    verdict = lines[-1]
    assert verdict["failed"] == ["platforms:identical"]
    assert verdict["platforms"] == ["cpu", "cpu"]
    per_stage = {r["stage"]: r for r in lines[:-1]}
    assert len(per_stage) >= 6  # both halves capture a real stage set
    for name, rec in per_stage.items():
        assert rec["max_rel"] == 0.0, (name, rec)  # bitwise same backend


def test_incomparable_captures_rejected(tool, tmp_path, capsys):
    shape = ["--dates", "30", "--stocks", "10", "--industries", "3",
             "--styles", "2", "--sims", "4", "--platform", "cpu"]
    a, b = str(tmp_path / "risk.npz"), str(tmp_path / "fac.npz")
    tool.main(["run", "--out", a, *shape, "--stage", "risk"])
    tool.main(["run", "--out", b, *shape, "--stage", "factors"])
    capsys.readouterr()
    with pytest.raises(SystemExit, match="incomparable"):
        tool.main(["compare", a, b])


def test_truncated_capture_rejected(tool, tmp_path):
    """A capture missing required stages must fail loudly — two truncated
    files agreeing with each other is not parity."""
    import numpy as np

    a, b = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    # stage-less legacy files are risk captures by construction
    np.savez(a, platform=np.array("tpu"))
    np.savez(b, platform=np.array("cpu"))
    with pytest.raises(SystemExit, match="missing stage"):
        tool.main(["compare", a, b])
    # a subset capture (only factor_ret) must also fail, not gate 1 stage
    np.savez(a, platform=np.array("tpu"), stage=np.array("risk"),
             factor_ret=np.zeros((4, 3)))
    np.savez(b, platform=np.array("cpu"), stage=np.array("risk"),
             factor_ret=np.zeros((4, 3)))
    with pytest.raises(SystemExit, match="missing stage"):
        tool.main(["compare", a, b])


BUDGET = os.path.join(os.path.dirname(__file__), "..", "tools",
                      "parity_budget.json")


def _forge_platform(src, dst, platform):
    """Clone a capture under a different platform marker so the budget
    logic is testable hermetically (the identical-platform tripwire would
    otherwise dominate every verdict on a CPU-only image)."""
    import numpy as np

    with np.load(src) as f:
        data = {k: f[k] for k in f.files if k != "platform"}
    np.savez(dst, platform=np.array(platform), **data)


@pytest.fixture(scope="module")
def risk_pair(tool, tmp_path_factory):
    """One tiny CPU risk capture + a platform-forged twin ('tpu')."""
    d = tmp_path_factory.mktemp("budget")
    a = str(d / "cpu.npz")
    tool.main(["run", "--out", a, "--dates", "40", "--stocks", "12",
               "--industries", "3", "--styles", "2", "--sims", "4",
               "--platform", "cpu"])
    b = str(d / "tpu.npz")
    _forge_platform(a, b, "tpu")
    return a, b


def test_budget_passes_on_agreeing_captures(tool, risk_pair, capsys):
    a, b = risk_pair
    with pytest.raises(SystemExit) as ei:
        tool.main(["compare", a, b, "--budget", BUDGET])
    lines = [json.loads(x) for x in capsys.readouterr().out.splitlines()]
    verdict = lines[-1]
    assert ei.value.code == 0
    assert verdict["parity"] is True and verdict["budget"] == BUDGET
    # every stage record carries its resolved budget ceiling
    assert all("budget" in r for r in lines[:-1])


def test_budget_fails_on_regressed_tail_and_median(tool, risk_pair, tmp_path,
                                                   capsys):
    """A drift regression in ONE stage must name that stage: a tail bump
    beyond its max_rel ceiling, and separately a broad offset that moves
    the median while staying under the tail ceiling."""
    import numpy as np

    a, b = risk_pair
    with np.load(b) as f:
        data = {k: f[k] for k in f.files}
    scale = float(np.nanmax(np.abs(data["eigen_cov"])))
    # tail regression: one element off by 100x the 5e-4 eigen budget — the
    # LAST date's cell (early expanding-window dates are NaN and masked)
    tail = dict(data)
    tail["eigen_cov"] = data["eigen_cov"].copy()
    tail["eigen_cov"][-1, 0, 0] += 5e-2 * scale
    bad = str(tmp_path / "tail.npz")
    np.savez(bad, **tail)
    with pytest.raises(SystemExit) as ei:
        tool.main(["compare", a, bad, "--budget", BUDGET])
    verdict = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert ei.value.code == 1
    assert verdict["failed"] == ["eigen_cov:max_rel"]

    # median regression: every element off by 1e-4 of scale — under the
    # 5e-4 tail ceiling, far over the 5e-6 median ceiling
    med = dict(data)
    med["eigen_cov"] = data["eigen_cov"] + 1e-4 * scale
    bad2 = str(tmp_path / "med.npz")
    np.savez(bad2, **med)
    with pytest.raises(SystemExit) as ei:
        tool.main(["compare", a, bad2, "--budget", BUDGET])
    verdict = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert verdict["failed"] == ["eigen_cov:median_rel"]


def test_low_sweep_count_fails_budget(tool, risk_pair, tmp_path, capsys):
    """The scenario the budget exists for: a deliberately under-converged
    Jacobi sweep count (1 sweep vs the solver default) produces eigen-stage
    drift that MUST trip the eigen_cov budget — run through the real
    compare path with the low-sweep covariances injected into a capture."""
    import numpy as np
    import jax.numpy as jnp
    from mfm_tpu.ops.eigh_pallas import jacobi_eigh_weighted_diag_tpu

    rng = np.random.default_rng(7)
    n, M = 8, 3
    X = rng.standard_normal((M, n, 64)).astype(np.float32)
    C = np.einsum("mkt,mlt->mkl", X, X) / 64
    d0 = np.abs(rng.normal(1.0, 0.3, (M, n))).astype(np.float32)
    full = jacobi_eigh_weighted_diag_tpu(jnp.asarray(C), jnp.asarray(d0),
                                         interpret=True)
    low = jacobi_eigh_weighted_diag_tpu(jnp.asarray(C), jnp.asarray(d0),
                                        sweeps=1, interpret=True)

    def cov_like(w_h):
        w = np.asarray(w_h[0], np.float64)
        return np.einsum("mi,mj->mij", w, w)  # any smooth function of w

    a, b = risk_pair
    with np.load(a) as f:
        base = {k: f[k] for k in f.files}
    ca, cb = dict(base), dict(base)
    ca["eigen_cov"] = cov_like(full)
    cb["eigen_cov"] = cov_like(low)
    cb["platform"] = np.array("tpu")
    fa, fb = str(tmp_path / "full.npz"), str(tmp_path / "low.npz")
    np.savez(fa, **ca)
    np.savez(fb, **cb)
    with pytest.raises(SystemExit) as ei:
        tool.main(["compare", fa, fb, "--budget", BUDGET])
    verdict = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert ei.value.code == 1
    assert any(f.startswith("eigen_cov:") for f in verdict["failed"])


def test_budget_file_must_cover_the_kind(tool, risk_pair, tmp_path):
    import json as _json

    a, b = risk_pair
    empty = str(tmp_path / "empty_budget.json")
    with open(empty, "w") as fh:
        _json.dump({"factors": {"default": {"max_rel": 1e-3}}}, fh)
    with pytest.raises(SystemExit, match="no 'risk' section"):
        tool.main(["compare", a, b, "--budget", empty])


def test_legacy_capture_compares_against_fresh_one(tool, tmp_path, capsys):
    """A pre-marker (legacy) risk capture stays comparable with a fresh one
    that carries the stage key; only genuinely different stages or data
    sets are incomparable."""
    import numpy as np

    shape = ["--dates", "30", "--stocks", "10", "--industries", "3",
             "--styles", "2", "--sims", "4", "--platform", "cpu"]
    fresh, legacy = str(tmp_path / "fresh.npz"), str(tmp_path / "legacy.npz")
    tool.main(["run", "--out", fresh, *shape])
    with np.load(fresh) as f:
        legacy_data = {k: f[k] for k in f.files if k != "stage"}
    np.savez(legacy, **legacy_data)
    capsys.readouterr()
    with pytest.raises(SystemExit) as ei:
        tool.main(["compare", fresh, legacy, "--gate", "1e-5"])
    out = capsys.readouterr().out
    import json
    verdict = json.loads(out.splitlines()[-1])
    # all stages compared (bitwise-equal data); only the same-platform
    # tripwire fails — NOT an "incomparable captures" rejection
    assert verdict["failed"] == ["platforms:identical"]
