"""tools/tpu_parity.py: the hardware-parity gate tool itself, run hermetically
on CPU at tiny shapes.  Same-backend captures must agree bitwise (the
determinism half of the gate); the verdict must still flag the identical
platforms so a mis-pinned run can never masquerade as hardware parity."""

import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture(scope="module")
def tool():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "tpu_parity.py")
    spec = importlib.util.spec_from_file_location("tpu_parity_tool", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("stage", ["risk", "factors"])
def test_same_backend_capture_is_deterministic(tool, stage, tmp_path, capsys):
    shape = ["--dates", "40", "--stocks", "12", "--industries", "3",
             "--styles", "2", "--sims", "4", "--stage", stage,
             "--platform", "cpu"]
    a, b = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    tool.main(["run", "--out", a, *shape])
    tool.main(["run", "--out", b, *shape])
    capsys.readouterr()

    with pytest.raises(SystemExit) as ei:
        tool.main(["compare", a, b, "--gate", "1e-5"])
    assert ei.value.code == 1  # identical platforms must fail the verdict
    lines = [json.loads(x) for x in capsys.readouterr().out.splitlines()]
    verdict = lines[-1]
    assert verdict["failed"] == ["platforms:identical"]
    assert verdict["platforms"] == ["cpu", "cpu"]
    per_stage = {r["stage"]: r for r in lines[:-1]}
    assert len(per_stage) >= 6  # both halves capture a real stage set
    for name, rec in per_stage.items():
        assert rec["max_rel"] == 0.0, (name, rec)  # bitwise same backend


def test_incomparable_captures_rejected(tool, tmp_path, capsys):
    shape = ["--dates", "30", "--stocks", "10", "--industries", "3",
             "--styles", "2", "--sims", "4", "--platform", "cpu"]
    a, b = str(tmp_path / "risk.npz"), str(tmp_path / "fac.npz")
    tool.main(["run", "--out", a, *shape, "--stage", "risk"])
    tool.main(["run", "--out", b, *shape, "--stage", "factors"])
    capsys.readouterr()
    with pytest.raises(SystemExit, match="incomparable"):
        tool.main(["compare", a, b])


def test_truncated_capture_rejected(tool, tmp_path):
    """A capture missing required stages must fail loudly — two truncated
    files agreeing with each other is not parity."""
    import numpy as np

    a, b = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    # stage-less legacy files are risk captures by construction
    np.savez(a, platform=np.array("tpu"))
    np.savez(b, platform=np.array("cpu"))
    with pytest.raises(SystemExit, match="missing stage"):
        tool.main(["compare", a, b])
    # a subset capture (only factor_ret) must also fail, not gate 1 stage
    np.savez(a, platform=np.array("tpu"), stage=np.array("risk"),
             factor_ret=np.zeros((4, 3)))
    np.savez(b, platform=np.array("cpu"), stage=np.array("risk"),
             factor_ret=np.zeros((4, 3)))
    with pytest.raises(SystemExit, match="missing stage"):
        tool.main(["compare", a, b])


def test_legacy_capture_compares_against_fresh_one(tool, tmp_path, capsys):
    """A pre-marker (legacy) risk capture stays comparable with a fresh one
    that carries the stage key; only genuinely different stages or data
    sets are incomparable."""
    import numpy as np

    shape = ["--dates", "30", "--stocks", "10", "--industries", "3",
             "--styles", "2", "--sims", "4", "--platform", "cpu"]
    fresh, legacy = str(tmp_path / "fresh.npz"), str(tmp_path / "legacy.npz")
    tool.main(["run", "--out", fresh, *shape])
    with np.load(fresh) as f:
        legacy_data = {k: f[k] for k in f.files if k != "stage"}
    np.savez(legacy, **legacy_data)
    capsys.readouterr()
    with pytest.raises(SystemExit) as ei:
        tool.main(["compare", fresh, legacy, "--gate", "1e-5"])
    out = capsys.readouterr().out
    import json
    verdict = json.loads(out.splitlines()[-1])
    # all stages compared (bitwise-equal data); only the same-platform
    # tripwire fails — NOT an "incomparable captures" rejection
    assert verdict["failed"] == ["platforms:identical"]
