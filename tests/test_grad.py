"""Differentiable-risk subsystem (mfm_tpu/grad): analytic sensitivities
vs central differences at f64, bitwise batch-of-B == B-singles across a
bucket boundary for every grad kernel, closed-form solver anchors
(2-asset min-vol KKT, 1/sigma risk parity), forward parity of the
grad-safe PSD gate against the serving kernel's inline gate, reverse-
stress admissibility + preset dominance, and the serve-side construct
request surface (guards, dead-lettering, <= 1 compile per bucket)."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from mfm_tpu.grad.construct import hedge_batch, minvol_batch, riskparity_batch
from mfm_tpu.grad.engine import (
    HEDGE_ETA,
    HEDGE_STEPS,
    MINVOL_ETA,
    MINVOL_STEPS,
    RISKPARITY_ETA,
    RISKPARITY_STEPS,
    GradEngine,
    ShockBall,
)
from mfm_tpu.grad.reverse import reverse_stress_batch
from mfm_tpu.grad.sensitivity import sensitivity_batch
from mfm_tpu.models.risk_model import portfolio_vol
from mfm_tpu.scenario.kernel import _one_scenario, psd_project, stress_cov
from mfm_tpu.scenario.spec import PRESETS, ScenarioSpec
from mfm_tpu.utils.contracts import assert_max_compiles

K = 6


def _cov(K=K, seed=0):
    """The bench/test covariance recipe: well-conditioned, vol ~1e-2."""
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((K, K)) / np.sqrt(K)
    return (a @ a.T + 1e-3 * np.eye(K)) * 1e-4


def _pad(rows, B, K=K):
    out = np.zeros((B, K))
    out[:len(rows)] = rows
    return out


# -- PSD-gate forward parity --------------------------------------------------
# psd_project is the grad-safe twin of the single-eigh gate inlined in
# _one_scenario (kernel.py's docstring points here).  The two must stay
# value-identical on BOTH gate branches, or a sensitivity would describe
# a different matrix than the one serving answers from.

@pytest.mark.parametrize("corr_beta,expect_fired", [
    (0.0, False),    # untouched world: gate closed, output IS the input
    (0.9, True),     # corr melt-up clips off-diagonals -> indefinite
])
def test_psd_gate_forward_parity(corr_beta, expect_fired):
    cov = jnp.array(_cov())
    shift = jnp.zeros(K)
    scale = jnp.ones(K)
    vm = jnp.asarray(1.3)
    cb = jnp.asarray(corr_beta)

    cov_s = stress_cov(cov, shift, scale, vm, cb)
    grad_cov, grad_needs, grad_min = psd_project(cov_s)
    serve_cov, serve_needs, serve_min = _one_scenario(
        cov, shift, scale, vm, cb, jnp.asarray(False))

    assert bool(grad_needs) == bool(serve_needs) == expect_fired
    assert np.array_equal(np.asarray(grad_cov), np.asarray(serve_cov))
    assert float(grad_min) == float(serve_min)
    if not expect_fired:
        # gate closed: the output is the stressed matrix itself, bitwise
        assert np.array_equal(np.asarray(grad_cov), np.asarray(cov_s))
    else:
        lam = np.linalg.eigvalsh(np.asarray(grad_cov, np.float64))
        assert float(serve_min) < 0       # the gate had a reason to fire
        assert lam[0] >= -K * np.finfo(np.float64).eps * lam[-1]


# -- analytic sensitivities vs central differences ----------------------------

def test_sensitivity_rows_match_central_differences():
    """Every Jacobian block of one vjp pull-back — ∂vol/∂shift, ∂scale,
    ∂vol_mult, ∂corr_beta, ∂exposure — against central differences of the
    same forward composition at f64 (conftest enables x64).  The chosen
    point FIRES the projection gate, so this also proves the grad-safe
    gate differentiates the projected branch correctly."""
    K4 = 4
    cov = _cov(K4, seed=0)
    shift = np.array([0.002, -0.001, 0.0005, 0.00025])
    scale = np.array([1.1, 0.9, 1.05, 1.0])
    vm, cb = 1.5, 0.3
    x = np.array([0.3, -0.2, 0.5, 0.1])

    def vol_of(sh, sc, m, b, xx):
        cov_s = stress_cov(jnp.array(cov), jnp.array(sh), jnp.array(sc),
                           jnp.asarray(m), jnp.asarray(b))
        cov_p, _, _ = psd_project(cov_s)
        return float(portfolio_vol(cov_p, jnp.array(xx)))

    vol, d_shift, d_scale, d_vm, d_cb, d_x = [
        np.asarray(o) for o in sensitivity_batch(
            jnp.array(cov)[None], jnp.array(shift)[None],
            jnp.array(scale)[None], jnp.asarray([vm]), jnp.asarray([cb]),
            jnp.array(x))]
    assert vol[0] == pytest.approx(vol_of(shift, scale, vm, cb, x))

    h = 1e-6
    for j in range(K4):
        e = np.zeros(K4)
        e[j] = h
        fd = (vol_of(shift + e, scale, vm, cb, x)
              - vol_of(shift - e, scale, vm, cb, x)) / (2 * h)
        assert d_shift[0, j] == pytest.approx(fd, rel=1e-6, abs=1e-9)
        fd = (vol_of(shift, scale + e, vm, cb, x)
              - vol_of(shift, scale - e, vm, cb, x)) / (2 * h)
        assert d_scale[0, j] == pytest.approx(fd, rel=1e-6, abs=1e-9)
        fd = (vol_of(shift, scale, vm, cb, x + e)
              - vol_of(shift, scale, vm, cb, x - e)) / (2 * h)
        assert d_x[0, j] == pytest.approx(fd, rel=1e-6, abs=1e-9)
    fd = (vol_of(shift, scale, vm + h, cb, x)
          - vol_of(shift, scale, vm - h, cb, x)) / (2 * h)
    assert d_vm[0] == pytest.approx(fd, rel=1e-6, abs=1e-9)
    fd = (vol_of(shift, scale, vm, cb + h, x)
          - vol_of(shift, scale, vm, cb - h, x)) / (2 * h)
    assert d_cb[0] == pytest.approx(fd, rel=1e-6, abs=1e-9)


def test_engine_sensitivity_entries():
    """Host-layer contract: ok lanes carry name-keyed Jacobian rows,
    rejected specs carry problems and NO rows, identity lanes report the
    local gradient at the unshocked world."""
    names = [f"f{i}" for i in range(K)]
    eng = GradEngine(_cov(), factor_names=names)
    x = np.linspace(0.1, 0.6, K)
    specs = [ScenarioSpec.identity(),
             PRESETS["crash-2015-analog"],
             ScenarioSpec(name="bogus", shift=(("nope", 0.01),))]
    ident, crash, bogus = eng.sensitivities(specs, x)

    assert ident["status"] == "ok" and not ident["problems"]
    assert set(ident["d_shift"]) == set(names)
    assert ident["vol"] == pytest.approx(
        float(portfolio_vol(jnp.array(eng.cov), jnp.array(x))))
    # at the identity point ∂vol/∂vol_mult is the vol itself
    # (vol scales linearly in vol_mult: d(vm * vol)/d vm at vm=1)
    assert ident["d_vol_mult"] == pytest.approx(ident["vol"], rel=1e-6)

    assert crash["status"] == "ok"
    assert crash["vol"] > ident["vol"]     # the drill is a stress

    assert bogus["status"] == "rejected" and bogus["problems"]
    assert "d_shift" not in bogus


# -- reverse stress testing ---------------------------------------------------

def test_reverse_batch_equals_singles_across_bucket_boundary():
    """Batch-of-9 at bucket 32 == 9 singles at bucket 8, bitwise — the
    scenario kernel's lane-isolation anchor re-proven for the ascent
    (nothing contracts across the batch axis; pad lanes are frozen by the
    isfinite guard)."""
    eng = GradEngine(_cov(), factor_names=[f"f{i}" for i in range(K)])
    rng = np.random.default_rng(1)
    W = rng.standard_normal((9, K)) * 0.4
    labels = [f"x{i}" for i in range(9)]

    batch = eng.reverse_stress(W, bucket=32, steps=60, labels=labels)
    for i in range(9):
        single, = eng.reverse_stress(W[i:i + 1], bucket=8, steps=60,
                                     labels=[labels[i]])
        assert single == batch[i], f"lane {i} diverged from its solo run"


def test_reverse_worst_case_admissible_and_dominates_presets():
    """The worst shock the ascent returns must (a) sit inside the ball,
    round-trip to a valid ScenarioSpec and keep the stressed matrix PSD
    (the ``admissible`` flag), and (b) report at least as much vol as
    every preset drill — the ball CONTAINS the whole preset catalog, so a
    weaker answer would mean the search missed an admissible point the
    desk already knows about."""
    names = [f"f{i}" for i in range(K)]
    cov = _cov()
    eng = GradEngine(cov, factor_names=names)
    rng = np.random.default_rng(2)
    x = rng.standard_normal(K) * 0.4

    entry, = eng.reverse_stress(x[None])   # default ball, default steps
    assert entry["admissible"]
    assert entry["vol_worst"] >= entry["vol_base"]
    assert entry["vol_delta"] == pytest.approx(
        entry["vol_worst"] - entry["vol_base"])

    # the answer is REPLAYABLE: the spec round-trips through the forward
    # scenario path to the same worst-case vol
    from mfm_tpu.scenario.engine import ScenarioEngine
    scen = ScenarioEngine(cov, factor_names=names)
    results = scen.run([ScenarioSpec.from_dict(entry["spec"])]
                       + [PRESETS[n] for n in sorted(PRESETS)])
    replay, presets = results[0], results[1:]
    assert replay.status == "ok"
    assert float(portfolio_vol(jnp.array(replay.cov), jnp.array(x))) == \
        pytest.approx(entry["vol_worst"], rel=1e-6)
    for r in presets:
        preset_vol = float(portfolio_vol(jnp.array(r.cov), jnp.array(x)))
        assert entry["vol_worst"] >= preset_vol * (1 - 1e-9), r.spec.name


def test_reverse_respects_a_tighter_ball():
    """Shrinking the ball shrinks the answer: the box is a real
    constraint, not a suggestion."""
    eng = GradEngine(_cov(), factor_names=[f"f{i}" for i in range(K)])
    x = np.linspace(-0.3, 0.5, K)
    tight = ShockBall(shift_max=0.001, scale_range=0.1,
                      vol_mult_hi=1.5, corr_beta_hi=0.2)
    wide, = eng.reverse_stress(x[None], steps=60)
    small, = eng.reverse_stress(x[None], ball=tight, steps=60)
    assert small["admissible"]
    assert tight.contains(
        np.concatenate([
            [dict(small["spec"]["shift"]).get(f, 0.0)
             for f in eng.factor_names],
            [dict(small["spec"]["scale"]).get(f, 1.0)
             for f in eng.factor_names],
            [small["spec"]["vol_mult"], small["spec"]["corr_beta"]]]), K)
    assert small["vol_worst"] < wide["vol_worst"]


# -- portfolio construction ---------------------------------------------------

def test_minvol_matches_closed_form_two_asset():
    """With two assets and no binding box, the min-vol weight has the
    closed form x1* = (F22 - F12) / (F11 + F22 - 2 F12); the KKT
    stationarity residual at the solution must be ~0."""
    F = np.array([[4.0, 0.5], [0.5, 1.0]]) * 1e-4
    star = (F[1, 1] - F[0, 1]) / (F[0, 0] + F[1, 1] - 2 * F[0, 1])
    x, vol, kkt = minvol_batch(
        jnp.array(np.full((1, 2), 0.5)), jnp.array(F),
        jnp.zeros(2), jnp.ones(2),
        jnp.asarray(MINVOL_ETA), jnp.int32(MINVOL_STEPS))
    x = np.asarray(x)[0]
    assert x[0] == pytest.approx(star, abs=1e-6)
    assert x[1] == pytest.approx(1 - star, abs=1e-6)
    assert float(kkt[0]) < 1e-6
    assert float(vol[0]) == pytest.approx(
        float(np.sqrt(x @ F @ x)), rel=1e-12)


def test_minvol_kkt_residual_small_at_k6():
    eng = GradEngine(_cov(), factor_names=[f"f{i}" for i in range(K)])
    res = eng.construct_solve("min_vol", np.full((3, K), 1.0 / K))
    assert res["weights"].shape == (3, K)
    np.testing.assert_allclose(res["weights"].sum(axis=1), 1.0, rtol=1e-9)
    assert np.all(res["weights"] >= 0)
    assert np.all(res["diag"] < 1e-3)      # ISSUE acceptance: KKT at tol


def test_riskparity_equalizes_contributions():
    # diagonal 2-asset: exact closed form x ∝ 1/σ
    D = np.diag([4e-4, 1e-4])
    x, _, spread = riskparity_batch(
        jnp.array(np.full((1, 2), 0.5)), jnp.array(D),
        jnp.asarray(RISKPARITY_ETA), jnp.int32(RISKPARITY_STEPS))
    np.testing.assert_allclose(np.asarray(x)[0], [1 / 3, 2 / 3], atol=1e-9)
    assert float(spread[0]) < 1e-9
    # dense K=6: every risk contribution equal to machine-ish tolerance
    cov = _cov()
    x, _, spread = riskparity_batch(
        jnp.array(np.full((1, K), 1.0 / K)), jnp.array(cov),
        jnp.asarray(RISKPARITY_ETA), jnp.int32(RISKPARITY_STEPS))
    x = np.asarray(x)[0]
    rc = x * (cov @ x)
    assert rc.max() - rc.min() < 1e-8 * rc.mean()
    assert float(spread[0]) < 1e-6


def _minvol_reference(cov):
    """Exact min-vol on the simplex (no binding upper box) by active-set
    elimination: solve the equality-constrained QP on the support, drop
    the most negative weight, repeat until feasible."""
    n = cov.shape[0]
    act = np.ones(n, bool)
    for _ in range(n):
        kc = int(act.sum())
        A = np.zeros((kc + 1, kc + 1))
        A[:kc, :kc] = 2.0 * cov[np.ix_(act, act)]
        A[:kc, kc] = 1.0
        A[kc, :kc] = 1.0
        b = np.zeros(kc + 1)
        b[kc] = 1.0
        xs = np.linalg.solve(A, b)[:kc]
        if (xs >= -1e-12).all():
            x = np.zeros(n)
            x[act] = np.clip(xs, 0.0, None)
            return x
        act[np.where(act)[0][int(xs.argmin())]] = False
    raise AssertionError("active-set elimination did not terminate")


def test_minvol_converges_on_negative_correlation_cov():
    """Regression for the constant-step limit cycle.  On a covariance
    with strongly negative correlations the marginals (F x)_i change
    sign across coordinates, the max-normalized gradient never vanishes,
    and a constant EG step orbits the optimum in a period-2 cycle
    instead of converging (observed on a real fitted checkpoint: 44%
    excess vol, KKT diag ~9).  The annealed schedule must land on the
    active-set optimum."""
    corr = np.array([[1.0, -0.9, -0.2, 0.3],
                     [-0.9, 1.0, 0.1, -0.4],
                     [-0.2, 0.1, 1.0, -0.6],
                     [0.3, -0.4, -0.6, 1.0]])
    sig = np.array([0.02, 0.025, 0.015, 0.03])
    cov = corr * np.outer(sig, sig)
    assert (cov @ np.full(4, 0.25) < 0).any()   # the regime under test
    ref = _minvol_reference(cov)

    x, vol, kkt = minvol_batch(
        jnp.array(np.full((1, 4), 0.25)), jnp.array(cov),
        jnp.zeros(4), jnp.ones(4),
        jnp.asarray(MINVOL_ETA), jnp.int32(MINVOL_STEPS))
    x = np.asarray(x)[0]
    np.testing.assert_allclose(x, ref, atol=1e-8)
    assert float(vol[0]) == pytest.approx(
        float(np.sqrt(ref @ cov @ ref)), rel=1e-10)
    assert float(kkt[0]) < 1e-8


def test_hedge_reduces_vol_and_respects_mask_and_box():
    cov = _cov()
    rng = np.random.default_rng(3)
    x0 = rng.standard_normal(K) * 0.3
    mask = np.array([1.0, 1.0, 0.0, 0.0, 1.0, 0.0])
    hmax = 0.25
    xt, h, vol = hedge_batch(
        jnp.array(_pad(x0[None], 8)), jnp.array(np.zeros((8, K))),
        jnp.array(cov), jnp.array(_pad(mask[None], 8)),
        jnp.asarray(hmax), jnp.asarray(HEDGE_ETA), jnp.int32(HEDGE_STEPS))
    xt = np.asarray(xt)[0]
    h = np.asarray(h)[0]
    base_vol = float(portfolio_vol(jnp.array(cov), jnp.array(x0)))
    assert float(vol[0]) < base_vol        # the overlay is a hedge
    assert np.all(h[mask == 0] == 0)       # unhedgeable factors untouched
    assert np.all(np.abs(h) <= hmax + 1e-12)
    np.testing.assert_array_equal(xt[mask == 0], x0[mask == 0])


@pytest.mark.parametrize("solver", ["min_vol", "risk_parity", "hedge"])
def test_construct_batch_equals_singles_bitwise(solver):
    """Batch-of-9 at bucket 32 == 9 singles at bucket 8 for every solver
    kernel, and all-zero pad lanes stay EXACTLY zero (construct.py's
    pad-lane isolation contract)."""
    cov = jnp.array(_cov())
    rng = np.random.default_rng(4)
    W = np.abs(rng.standard_normal((9, K)))
    W = W / W.sum(axis=1, keepdims=True)
    steps = jnp.int32(60)

    def solve(rows, B):
        xs0 = jnp.array(_pad(rows, B))
        if solver == "min_vol":
            return minvol_batch(xs0, cov, jnp.zeros(K), jnp.ones(K),
                                jnp.asarray(MINVOL_ETA), steps)
        if solver == "risk_parity":
            return riskparity_batch(xs0, cov,
                                    jnp.asarray(RISKPARITY_ETA), steps)
        return hedge_batch(xs0, jnp.array(np.zeros((B, K))), cov,
                           jnp.array(_pad(np.ones_like(rows), B)),
                           jnp.asarray(0.5), jnp.asarray(HEDGE_ETA), steps)

    batch = [np.asarray(o) for o in solve(W, 32)]
    for i in range(9):
        single = [np.asarray(o) for o in solve(W[i:i + 1], 8)]
        for b, s in zip(batch, single):
            assert np.array_equal(b[i], s[0]), f"lane {i} diverged"
    assert np.all(batch[0][9:] == 0)       # pad weights frozen at zero


# -- serve-side construction --------------------------------------------------

K4 = 4


def _serve_engine():
    from mfm_tpu.serve import QueryEngine
    rng = np.random.default_rng(0)
    a = rng.standard_normal((K4, K4)) / 2
    cov = (a @ a.T + 1e-3 * np.eye(K4)) * 1e-4
    return QueryEngine(cov, factor_names=["country", "ind0", "size", "mom"],
                       benchmarks={"idx": rng.standard_normal(K4)})


def _req(rid, w=None, **kw):
    return json.dumps({"id": rid,
                       "weights": [0.1] * K4 if w is None else w, **kw})


def test_serve_construct_end_to_end():
    """Construction requests ride the query loop: same admission, same
    stamps, answers from the grad solvers against the SERVED covariance
    — and a mixed drain answers risk queries on the exact pre-construct
    path."""
    from mfm_tpu.serve import QueryServer, ServePolicy
    eng = _serve_engine()
    server = QueryServer(eng, ServePolicy(default_deadline_s=60.0),
                         health="ok")
    server.submit_line(_req("q1"))                       # plain risk query
    server.submit_line(_req("c1", construct="min_vol"))
    server.submit_line(_req("c2", construct={"solver": "risk_parity"}))
    server.submit_line(_req("c3", construct={
        "solver": "hedge", "hedge_factors": ["size", "mom"], "hmax": 0.5}))
    out = {r["id"]: r for r in server.drain()}
    assert len(out) == 4 and all(r["ok"] for r in out.values())

    assert "kind" not in out["q1"]         # risk answers are unchanged
    for rid, solver in (("c1", "min_vol"), ("c2", "risk_parity"),
                        ("c3", "hedge")):
        r = out[rid]
        assert r["kind"] == "construct" and r["solver"] == solver
        assert len(r["weights"]) == K4 and r["total_vol"] > 0
        assert r["health"] == "ok" and r["scenario_id"] is None
    # simplex solvers return simplex weights
    assert sum(out["c1"]["weights"]) == pytest.approx(1.0, rel=1e-9)
    assert min(out["c2"]["weights"]) > 0
    # the hedge held the unhedgeable factors at the request book
    assert out["c3"]["weights"][:2] == [0.1, 0.1]

    # the served answer IS the GradEngine answer over the served matrix
    ge = GradEngine(np.asarray(eng._cov), factor_names=eng.factor_names)
    ref = ge.construct_solve("min_vol", np.full((1, K4), 0.1))
    assert out["c1"]["total_vol"] == float(ref["vols"][0])


def test_serve_construct_bad_solver_dead_letters(tmp_path):
    from mfm_tpu.serve import QueryServer, ServePolicy
    from mfm_tpu.serve.server import REQ_REASON_BAD_CONSTRUCT
    dl = str(tmp_path / "dead.jsonl")
    server = QueryServer(_serve_engine(), ServePolicy(), health="ok",
                         dead_letter_path=dl)
    bad, = server.submit_line(_req("b1", construct="sharpe_max"))
    assert bad["outcome"] == "dead_letter"
    assert bad["reasons"] == ["bad_construct"]
    # hedge over factors the engine does not serve is inadmissible too
    bad2, = server.submit_line(_req("b2", construct={
        "solver": "hedge", "hedge_factors": ["bogus"]}))
    assert bad2["reasons"] == ["bad_construct"]
    server.close()
    recs = [json.loads(ln) for ln in open(dl)]
    assert [r["id"] for r in recs] == ["b1", "b2"]
    assert all(r["mask"] == REQ_REASON_BAD_CONSTRUCT for r in recs)


def test_serve_construct_scenario_tagged_solves_stressed_world():
    """A scenario-tagged construct request solves against the STRESSED
    covariance: under a pure vol-regime doubling the min-vol weights are
    unchanged (argmin is scale-free) but the reported vol doubles."""
    from mfm_tpu.scenario import ScenarioBuilder, ScenarioEngine
    from mfm_tpu.serve import QueryServer, ServePolicy
    eng = _serve_engine()
    sc = ScenarioEngine(np.asarray(eng._cov), factor_names=eng.factor_names)
    results = sc.run([ScenarioBuilder("hot").vol_regime(2.0).build()])
    server = QueryServer(eng, ServePolicy(default_deadline_s=60.0),
                         health="ok",
                         scenarios=sc.query_engines(results, eng))
    server.submit_line(_req("plain", construct="min_vol"))
    server.submit_line(_req("hot", construct="min_vol", scenario="hot"))
    out = {r["id"]: r for r in server.drain()}
    assert out["hot"]["scenario_id"] == "hot"
    np.testing.assert_allclose(out["hot"]["weights"], out["plain"]["weights"],
                               atol=1e-9)
    assert out["hot"]["total_vol"] == pytest.approx(
        2.0 * out["plain"]["total_vol"], rel=1e-9)


def test_serve_construct_steady_state_compiles():
    """<= 1 compile per (solver, bucket): after a warm drain, further
    construct traffic at the same bucket must not recompile."""
    from mfm_tpu.serve import QueryServer, ServePolicy
    server = QueryServer(_serve_engine(),
                         ServePolicy(default_deadline_s=60.0), health="ok")
    for i in range(2):                     # warm the (min_vol, 8) bucket
        server.submit_line(_req(f"w{i}", construct="min_vol"))
    assert all(r["ok"] for r in server.drain())
    with assert_max_compiles(1, "steady-state construct bucket 8"):
        for i in range(5):
            server.submit_line(_req(f"s{i}", construct="min_vol"))
        out = server.drain()
    assert len(out) == 5 and all(r["ok"] for r in out)
