"""mfmsync (lock-discipline static analysis) + the deterministic
scheduler, gated into tier-1.

Mirrors test_mfmlint.py's three layers:
 1. the real tree analyzes clean against the committed baseline (at most
    5 entries, every one carrying a written justification) — the strict
    gate bench_all.sh runs before collecting any fleet numbers;
 2. per-rule fixture snippets (positive + negative) pin S1/S2/S3
    semantics: guarded-field inference, the ``_locked`` naming
    convention, the private-method entry-held fixpoint, Condition
    aliasing, lock-order cycles, non-reentrant re-acquire, and the
    blocking-under-lock catalog (sleep/subprocess/socket/join/get/
    foreign-wait/jit-dispatch);
 3. injection drills on scratch copies of the real package: an
    unguarded write to a Coalescer guarded field and a cache->coalescer
    lock inversion must each flip the CLI to exit 1 while the pristine
    copy exits 0.

Plus the runtime half: DetScheduler determinism (same seed -> same
interleaving), schedule exploration across seeds, and the instrumented
primitives' semantics (mutual exclusion, condition wake rules, queue
blocking, deadlock detection).

No jax import here: the analyzer is pure-AST and the scheduler is
stdlib-only, so these tests stay cheap.
"""

import json
import shutil
import textwrap
from pathlib import Path

import pytest

from mfm_tpu.analysis.sync import (
    DEFAULT_BASELINE,
    REPO_ROOT,
    load_baseline,
    main,
    run_sync,
)
from mfm_tpu.utils.sched import (
    DeadlockError,
    DetCondition,
    DetLock,
    DetQueue,
    DetRLock,
    DetScheduler,
    SchedulerError,
)

REPO = Path(REPO_ROOT)


def _sync(tmp_path, files, baseline=None):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_sync([str(tmp_path)], baseline=baseline, root=str(tmp_path))


def _found(res):
    return sorted((v.rule, v.qualname) for v in res.new)


# -- layer 1: the real tree ---------------------------------------------------

def test_repo_syncs_clean_with_committed_baseline():
    baseline = load_baseline(str(REPO / DEFAULT_BASELINE))
    # the acceptance budget: at most 5 justified exceptions, and every
    # one must say WHY it is the design rather than a race
    assert 0 < len(baseline) <= 5, "baseline creep: fix, don't excuse"
    for b in baseline:
        assert b.get("justification"), f"unjustified baseline entry: {b}"
    res = run_sync(baseline=baseline)
    assert not res.new, "\n".join(v.render() for v in res.new)
    assert not res.stale, f"stale baseline entries: {res.stale}"
    assert res.baselined, "baseline matches nothing — prune it"


# -- layer 2: per-rule fixtures ----------------------------------------------

def test_s1_guarded_field_inference(tmp_path):
    res = _sync(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self.tag = "x"

            def bump(self):
                with self._lock:
                    self._n += 1      # the guarding write

            def reset(self):
                self._n = 0           # S1: unguarded write

            def peek(self):
                return self._n        # S1: unguarded read

            def label(self):
                self.tag = "y"        # clean: tag is never lock-guarded
    """})
    assert _found(res) == [("S1", "Box.peek"), ("S1", "Box.reset")]


def test_s1_locked_suffix_and_private_fixpoint(tmp_path):
    res = _sync(tmp_path, {"mod.py": """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add_item(self, x):
                with self._lock:
                    self._items.append(x)

            def _drain_locked(self):
                # the repo convention: *_locked is entered lock-held
                return list(self._items)

            def _size(self):
                # private: entry-held inferred from its call sites
                return len(self._items)

            def snapshot(self):
                with self._lock:
                    return self._size()

            def racy(self):
                return len(self._items)     # S1: public, lock-free
    """})
    assert _found(res) == [("S1", "Pool.racy")]


def test_condition_alias_and_held_wait_allowed(tmp_path):
    res = _sync(tmp_path, {"mod.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._wake = threading.Condition(self._lock)
                self._evt = threading.Event()
                self._q = []

            def put_item(self, x):
                with self._wake:          # aliases to _lock
                    self._q.append(x)
                    self._wake.notify()

            def take(self):
                with self._wake:
                    while not self._q:
                        self._wake.wait()  # wait on the HELD cond: legal
                    return self._q.pop(0)

            def bad_wait(self):
                with self._lock:
                    self._evt.wait()       # S3: foreign wait under lock
    """})
    assert _found(res) == [("S3", "W.bad_wait")]


def test_s2_lock_order_cycle(tmp_path):
    res = _sync(tmp_path, {"mod.py": """
        import threading

        L1 = threading.Lock()
        L2 = threading.Lock()

        def fwd():
            with L1:
                with L2:
                    pass

        def rev():
            with L2:
                with L1:
                    pass
    """})
    assert [r for r, _q in _found(res)] == ["S2"]
    res_ok = _sync(tmp_path / "ok", {"mod.py": """
        import threading

        L1 = threading.Lock()
        L2 = threading.Lock()

        def fwd():
            with L1:
                with L2:
                    pass

        def also_fwd():
            with L1:
                with L2:
                    pass
    """})
    assert not res_ok.new


def test_s2_nonreentrant_reacquire(tmp_path):
    res = _sync(tmp_path, {"mod.py": """
        import threading

        class Plain:
            def __init__(self):
                self._lock = threading.Lock()

            def oops(self):
                with self._lock:
                    with self._lock:     # S2: plain Lock self-deadlock
                        pass

        class Reentrant:
            def __init__(self):
                self._lock = threading.RLock()

            def fine(self):
                with self._lock:
                    with self._lock:     # RLock: legal
                        pass
    """})
    assert _found(res) == [("S2", "Plain.oops")]


def test_s3_blocking_catalog(tmp_path):
    res = _sync(tmp_path, {"mod.py": """
        import subprocess
        import threading
        import time

        LOCK = threading.Lock()

        def bad_sleep():
            with LOCK:
                time.sleep(0.1)

        def ok_sleep():
            time.sleep(0.1)

        def bad_spawn():
            with LOCK:
                subprocess.run(["true"])

        def bad_join(t):
            with LOCK:
                t.join()                  # zero-arg join: blocking

        def ok_strjoin(xs):
            with LOCK:
                return ", ".join(xs)      # has an argument: str.join

        def bad_get(q):
            with LOCK:
                return q.get()            # zero-arg get: queue.get

        def ok_dictget(d):
            with LOCK:
                return d.get("k")
    """})
    assert _found(res) == [("S3", "bad_get"), ("S3", "bad_join"),
                           ("S3", "bad_sleep"), ("S3", "bad_spawn")]


def test_s3_jit_dispatch_under_lock(tmp_path):
    res = _sync(tmp_path, {"mod.py": """
        import threading

        import jax
        import jax.numpy as jnp

        LOCK = threading.Lock()

        @jax.jit
        def kernel(x):
            return jnp.sum(x)

        def bad_dispatch(x):
            with LOCK:
                return kernel(x)          # S3: jit dispatch under lock

        def ok_dispatch(x):
            return kernel(x)

        def bad_direct(x):
            with LOCK:
                return jnp.dot(x, x)      # S3: direct jax call
    """})
    # bad_dispatch is flagged twice (jit-dispatch rule + transitive
    # blocking through kernel's own jax call) — set semantics here
    assert set(_found(res)) == {("S3", "bad_direct"), ("S3", "bad_dispatch")}


def test_s3_os_handle_receiver_is_not_a_jax_edge(tmp_path):
    """``self.proc.poll()`` on a subprocess.Popen field must not resolve
    through the bare-name index onto some unrelated class's ``poll`` that
    happens to dispatch jax — that alias would drag every transport
    method into the jax_touch closure and flag locked callers as S3."""
    res = _sync(tmp_path, {"mod.py": """
        import subprocess
        import threading

        import jax.numpy as jnp

        LOCK = threading.Lock()

        class Engine:
            def poll(self):
                return jnp.zeros(3)       # genuine jax toucher named poll

        class Worker:
            def __init__(self):
                self.proc = subprocess.Popen(["true"])

            def alive(self):
                return self.proc.poll() is None   # OS handle, not Engine.poll

        def route(w):
            with LOCK:
                return w.alive()          # must NOT be S3: no jax reachable
    """})
    assert _found(res) == []


def test_s3_local_handle_and_container_receivers_are_not_jax_edges(tmp_path):
    """The LOCAL form of the typed-receiver barrier: ``fh.flush()`` on a
    ``with open(...) as fh`` handle and ``ev.update(...)`` on a dict
    literal must not alias package methods named flush/update that
    genuinely dispatch jax (the atomic-writer and event-record idioms
    would otherwise drag every locked caller into S3)."""
    res = _sync(tmp_path, {"mod.py": """
        import threading

        import jax.numpy as jnp

        LOCK = threading.Lock()

        class Engine:
            def update(self, x):
                return jnp.sum(x)         # genuine jax toucher named update

            def flush(self):
                return jnp.zeros(2)       # ... and one named flush

            def refit(self, x):
                return jnp.dot(x, x)      # distinctive name (no generic-
                                          # attr suppression in the way)

        def record(**fields):
            ev = {"kind": "x"}
            ev.update(fields)             # dict literal, not Engine.update
            return ev

        def dump(path, text):
            with open(path, "w") as fh:
                fh.write(text)
                fh.flush()                # OS handle, not Engine.flush
            rows = list(text)
            rows.append("eof")            # list(), not some package append

        def locked_writer(path):
            with LOCK:
                record(a=1)               # must NOT be S3
                dump(path, "x")           # must NOT be S3

        def rebound(x):
            ev = {}
            ev = Engine()                 # rebind untracks the name
            with LOCK:
                return ev.refit(x)        # IS S3: a real Engine.refit
    """})
    assert set(_found(res)) == {("S3", "rebound")}


def test_baseline_and_strict_stale(tmp_path):
    files = {"mod.py": """
        import threading
        import time

        LOCK = threading.Lock()

        def bad_sleep():
            with LOCK:
                time.sleep(0.1)
    """}
    res = _sync(tmp_path, files)
    assert len(res.new) == 1
    bl = [{"file": "mod.py", "rule": "S3", "qualname": "bad_sleep",
           "justification": "fixture"}]
    res2 = run_sync([str(tmp_path)], baseline=bl, root=str(tmp_path))
    assert not res2.new and len(res2.baselined) == 1 and not res2.stale
    # stale entry: warning by default, failure under --strict
    blp = tmp_path / "bl.json"
    blp.write_text(json.dumps(bl + [{"file": "mod.py", "rule": "S2",
                                     "qualname": "ghost"}]))
    args = [str(tmp_path), "--baseline", str(blp), "--root", str(tmp_path)]
    assert main(args) == 0
    assert main(args + ["--strict"]) == 1


# -- layer 3: injection drills against the real package -----------------------

def _scratch_package(tmp_path):
    shutil.copytree(REPO / "mfm_tpu", tmp_path / "mfm_tpu",
                    ignore=shutil.ignore_patterns("__pycache__"))
    return [str(tmp_path / "mfm_tpu"),
            "--baseline", str(REPO / DEFAULT_BASELINE),
            "--root", str(tmp_path)]


def test_injected_unguarded_write_fails_cli(tmp_path):
    """An unguarded write to a Coalescer guarded field on a scratch copy
    of the package must flip the CLI from exit 0 to exit 1 — the drill
    that proves the gate would catch a PR 18-class regression."""
    args = _scratch_package(tmp_path)
    assert main(args) == 0, "pristine scratch package should be clean"
    mod = tmp_path / "mfm_tpu" / "serve" / "coalesce.py"
    mod.write_text(mod.read_text() + textwrap.dedent("""

        class _DrillPoker(Coalescer):
            def poke(self):
                self._oldest_t = None
    """))
    assert main(args) == 1
    res = run_sync([str(tmp_path / "mfm_tpu")], root=str(tmp_path))
    assert any(v.rule == "S1" and v.qualname == "_DrillPoker.poke"
               for v in res.new)


def test_injected_lock_inversion_fails_cli(tmp_path):
    """Taking the coalescer's lock while holding the cache's reverses a
    real edge (Coalescer._emit -> ResponseCache.absorb), closing a
    cycle the CLI must refuse."""
    args = _scratch_package(tmp_path)
    assert main(args) == 0, "pristine scratch package should be clean"
    mod = tmp_path / "mfm_tpu" / "serve" / "cache.py"
    mod.write_text(mod.read_text() + textwrap.dedent("""

        class _DrillInverse(ResponseCache):
            def poke(self, co):
                with self._lock:
                    co.flush()
    """))
    assert main(args) == 1
    res = run_sync([str(tmp_path / "mfm_tpu")], root=str(tmp_path))
    assert any(v.rule == "S2" and "cycle" in v.message for v in res.new)


# -- the deterministic scheduler ----------------------------------------------

def _contended_run(seed, threads=3, rounds=3):
    s = DetScheduler(seed)
    lk = DetLock(s, "L")
    order = []
    for i in range(threads):
        def worker(i=i):
            for _ in range(rounds):
                with lk:
                    order.append(i)
        s.spawn(worker, name=f"w{i}")
    trace = s.run()
    return trace, order


def test_same_seed_same_interleaving():
    assert _contended_run(42) == _contended_run(42)
    assert _contended_run(7) == _contended_run(7)


def test_seeds_explore_different_interleavings():
    orders = {tuple(_contended_run(seed)[1]) for seed in range(10)}
    assert len(orders) > 1, "seed sweep never changed the schedule"


def test_detlock_mutual_exclusion_and_reacquire():
    s = DetScheduler(3)
    lk = DetLock(s, "L")
    depth = {"now": 0, "max": 0}

    def worker():
        for _ in range(5):
            with lk:
                depth["now"] += 1
                depth["max"] = max(depth["max"], depth["now"])
                s.yield_point("critical")      # invite a context switch
                depth["now"] -= 1
    for i in range(3):
        s.spawn(worker, name=f"w{i}")
    s.run()
    assert depth["max"] == 1, "two workers inside one DetLock"

    s2 = DetScheduler(0)
    lk2 = DetLock(s2, "L2")

    def reacquirer():
        with lk2:
            with lk2:
                pass
    s2.spawn(reacquirer, name="re")
    with pytest.raises(SchedulerError, match="re-acquire"):
        s2.run()


def test_detrlock_is_reentrant():
    s = DetScheduler(1)
    lk = DetRLock(s, "R")
    hits = []

    def worker():
        with lk:
            with lk:
                hits.append("ok")
    s.spawn(worker, name="w")
    s.run()
    assert hits == ["ok"]


def test_detcondition_untimed_wait_needs_notify():
    s = DetScheduler(11)
    lk = DetRLock(s, "L")
    cv = DetCondition(s, lk)
    log = []

    def consumer():
        with lk:
            while not log:
                cv.wait()
            log.append("consumed")

    def producer():
        with lk:
            log.append("item")
            cv.notify_all()
    s.spawn(consumer, name="c")
    s.spawn(producer, name="p")
    s.run()
    assert log == ["item", "consumed"]


def test_detcondition_timed_wait_is_spurious():
    s = DetScheduler(5)
    lk = DetRLock(s, "L")
    cv = DetCondition(s, lk)
    woke = []

    def waiter():
        with lk:
            woke.append(cv.wait(timeout=0.5))
    s.spawn(waiter, name="w")
    s.run()     # nobody notifies: the timeout path must still wake
    assert woke == [False]


def test_detqueue_blocking_handoff():
    s = DetScheduler(9)
    q = DetQueue(s, maxsize=2, name="q")
    got = []

    def producer():
        for i in range(5):
            q.put(i)

    def consumer():
        for _ in range(5):
            got.append(q.get())
    s.spawn(producer, name="p")
    s.spawn(consumer, name="c")
    s.run()
    assert got == [0, 1, 2, 3, 4]


def test_deadlock_detection_finds_ab_ba():
    found = None
    for seed in range(40):
        s = DetScheduler(seed)
        a, b = DetLock(s, "A"), DetLock(s, "B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass
        s.spawn(ab, name="ab")
        s.spawn(ba, name="ba")
        try:
            s.run()
        except DeadlockError as e:
            found = (seed, str(e))
            break
    assert found is not None, "seed sweep never hit the AB-BA deadlock"
    assert "no runnable thread" in found[1]
    # replay: the SAME seed deadlocks again (determinism of the failure)
    s = DetScheduler(found[0])
    a, b = DetLock(s, "A"), DetLock(s, "B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass
    s.spawn(ab, name="ab")
    s.spawn(ba, name="ba")
    with pytest.raises(DeadlockError):
        s.run()


def test_worker_exception_propagates():
    s = DetScheduler(2)

    def boomer():
        raise ValueError("boom")
    s.spawn(boomer, name="boomer")
    with pytest.raises(ValueError, match="boomer.*boom"):
        s.run()
