"""Hermetic ETL tests: watermark resume, rate limiting, retry, dedup inserts,
delete-then-insert refresh, repair tooling — all against fakes."""

import os

import numpy as np
import pandas as pd
import pytest

from mfm_tpu.data.etl import (
    IncrementalUpdater,
    PanelStore,
    RateLimiter,
    find_missing_stocks,
    verify_store,
    with_retry,
)


class FakeSource:
    def __init__(self):
        self.calls = []
        self.fail_next = 0

    def fetch_daily_prices(self, trade_date):
        self.calls.append(("daily", trade_date))
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError("transient")
        return pd.DataFrame({
            "ts_code": ["A.SH", "B.SH"],
            "trade_date": [trade_date, trade_date],
            "close": [1.0, 2.0],
        })

    def fetch_cashflow_by_stock(self, ts_code, start_date=None, end_date=None):
        self.calls.append(("cashflow", ts_code))
        return pd.DataFrame({
            "ts_code": [ts_code], "f_ann_date": ["20240430"],
            "end_date": ["20240331"], "n_cashflow_act": [1.5],
        })

    def fetch_index_components(self, index_code, trade_date):
        self.calls.append(("components", index_code, trade_date))
        return pd.DataFrame({
            "index_code": [index_code] * 2, "trade_date": [trade_date] * 2,
            "con_code": ["A.SH", "B.SH"], "weight": [60.0, 40.0],
        })


def test_watermark_resume(tmp_path):
    store = PanelStore(str(tmp_path))
    src = FakeSource()
    up = IncrementalUpdater(store, src, sleep=lambda s: None)
    cal = ["20240101", "20240102", "20240103"]
    up.update_daily_prices(cal)
    assert store.last_date("daily_prices") == "20240103"
    n_calls = len(src.calls)
    # second run: nothing after the watermark -> no fetches
    up.update_daily_prices(cal)
    assert len(src.calls) == n_calls
    # extending the calendar fetches only the new day
    up.update_daily_prices(cal + ["20240104"])
    assert src.calls[-1] == ("daily", "20240104")
    assert store.distinct_count("daily_prices", "trade_date") == 4


def test_insert_is_idempotent(tmp_path):
    store = PanelStore(str(tmp_path))
    df = pd.DataFrame({"ts_code": ["A", "B"], "trade_date": ["d1", "d1"],
                       "close": [1.0, 2.0]})
    assert store.insert("x", df, unique=("ts_code", "trade_date")) == 2
    assert store.insert("x", df, unique=("ts_code", "trade_date")) == 0
    assert len(store.read("x")) == 2


def test_retry_recovers_from_transient_failures(tmp_path):
    store = PanelStore(str(tmp_path))
    src = FakeSource()
    src.fail_next = 2  # two failures, third attempt succeeds
    sleeps = []
    up = IncrementalUpdater(store, src, backoff_s=5.0,
                            sleep=lambda s: sleeps.append(s))
    up.update_daily_prices(["20240101"])
    assert len(store.read("daily_prices")) == 2
    assert sleeps == [5.0, 5.0]


def test_retry_exhausts_and_raises():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("permanent")

    with pytest.raises(ValueError):
        with_retry(boom, attempts=3, backoff_s=0, sleep=lambda s: None)
    assert len(calls) == 3


def test_rate_limiter_sliding_window():
    now = [0.0]
    sleeps = []
    rl = RateLimiter(3, clock=lambda: now[0], sleep=lambda s: sleeps.append(s))
    for _ in range(3):
        rl.wait()
        now[0] += 1.0
    rl.wait()  # 4th call within 60s -> must sleep until first stamp expires
    assert sleeps and abs(sleeps[0] - 57.0) < 1e-9


def test_statements_and_components(tmp_path):
    store = PanelStore(str(tmp_path))
    src = FakeSource()
    up = IncrementalUpdater(store, src, sleep=lambda s: None)
    up.update_statements(["A.SH", "B.SH"], "cashflow")
    assert store.distinct_count("cashflow", "ts_code") == 2
    up.update_statements(["A.SH"], "cashflow")  # idempotent
    assert len(store.read("cashflow")) == 2

    up.update_index_components(["000300.SH"], "20240101")
    assert len(store.read("index_components")) == 2
    # refresh replaces, not duplicates
    up.update_index_components(["000300.SH"], "20240101")
    assert len(store.read("index_components")) == 2


def _day_frame(d, n=40):
    return pd.DataFrame({
        "ts_code": [f"{600000 + i}.SH" for i in range(n)],
        "trade_date": [f"2024{d // 31 + 1:02d}{d % 31 + 1:02d}"] * n,
        "close": np.linspace(1, 2, n) + d,
    })


def test_insert_appends_without_rescanning(tmp_path, monkeypatch):
    """The round-1 O(total^2) finding: an insert must not re-read the whole
    collection.  After the one-time key scan, N inserts perform zero reads."""
    store = PanelStore(str(tmp_path))
    reads = []
    orig = PanelStore.read

    def counting_read(self, name, columns=None):
        reads.append(name)
        return orig(self, name, columns)

    monkeypatch.setattr(PanelStore, "read", counting_read)
    for d in range(60):
        store.insert("daily_prices", _day_frame(d),
                     unique=("ts_code", "trade_date"))
    assert reads.count("daily_prices") <= 1
    monkeypatch.setattr(PanelStore, "read", orig)
    assert len(store.read("daily_prices")) == 60 * 40

    # a fresh instance (cold key cache) still dedups against what's on disk
    s2 = PanelStore(str(tmp_path))
    assert s2.insert("daily_prices", _day_frame(0),
                     unique=("ts_code", "trade_date")) == 0
    assert s2.last_date("daily_prices") == _day_frame(59)["trade_date"][0]


@pytest.mark.slow
def test_insert_wall_clock_grows_linearly(tmp_path):
    import time as _time

    store = PanelStore(str(tmp_path))

    def batch(lo, hi):
        t0 = _time.perf_counter()
        for d in range(lo, hi):
            store.insert("daily_prices", _day_frame(d),
                         unique=("ts_code", "trade_date"))
        return _time.perf_counter() - t0

    first = batch(0, 100)
    # warm steady state: per-insert cost must not scale with store size (the
    # old full-rewrite design was >5x slower by the second batch); generous
    # margin because this is a wall-clock assertion on shared hardware
    second = batch(100, 200)
    assert second < 5.0 * max(first, 0.05), (first, second)


def test_legacy_single_file_store_reads_and_dedups(tmp_path):
    legacy = pd.DataFrame({"ts_code": ["A", "B"], "trade_date": ["d1", "d1"],
                           "close": [1.0, 2.0]})
    legacy.to_parquet(str(tmp_path / "x.parquet"), index=False)
    store = PanelStore(str(tmp_path))
    assert len(store.read("x")) == 2
    # inserts dedup against the legacy file and append as parts
    added = store.insert("x", pd.DataFrame({
        "ts_code": ["A", "C"], "trade_date": ["d1", "d1"],
        "close": [9.0, 3.0]}), unique=("ts_code", "trade_date"))
    assert added == 1
    got = store.read("x").sort_values("ts_code")
    assert list(got["ts_code"]) == ["A", "B", "C"]
    assert got[got.ts_code == "A"]["close"].item() == 1.0  # first wins


def test_compact_preserves_contents(tmp_path):
    store = PanelStore(str(tmp_path))
    for d in range(5):
        store.insert("y", _day_frame(d, n=3), unique=("ts_code", "trade_date"))
    before = store.read("y").sort_values(["trade_date", "ts_code"])
    assert len(store._parts("y")) == 5
    store.compact("y")
    assert len(store._parts("y")) == 1
    after = store.read("y").sort_values(["trade_date", "ts_code"])
    pd.testing.assert_frame_equal(before.reset_index(drop=True),
                                  after.reset_index(drop=True))
    # key cache was reset; dedup still correct post-compaction
    assert store.insert("y", _day_frame(0, n=3),
                        unique=("ts_code", "trade_date")) == 0


def test_repeated_rewrites_do_not_clobber(tmp_path):
    """Part names must come from max-index+1, not the file count: two
    consecutive replace_where calls previously wiped the collection."""
    store = PanelStore(str(tmp_path))
    store.insert("c", pd.DataFrame({"index_code": ["i"], "trade_date": ["d1"],
                                    "con_code": ["A"]}))
    for day in ("d2", "d3"):
        store.replace_where(
            "c", lambda cur, day=day: cur["trade_date"] == day,
            pd.DataFrame({"index_code": ["i"], "trade_date": [day],
                          "con_code": ["A"]}))
    got = store.read("c")
    assert sorted(got["trade_date"]) == ["d1", "d2", "d3"]

    # compact followed by inserts must also not collide/lose parts
    store2 = PanelStore(str(tmp_path / "s2"))
    for d in range(3):
        store2.insert("y", _day_frame(d, n=2), unique=("ts_code", "trade_date"))
    store2.compact("y")
    for d in range(3, 7):
        store2.insert("y", _day_frame(d, n=2), unique=("ts_code", "trade_date"))
    assert len(store2.read("y")) == 7 * 2


def test_nan_unique_keys_dedup_like_drop_duplicates(tmp_path):
    """Null key values (real in tushare announcement dates) must dedup:
    NaN != NaN under tuple equality previously re-admitted them forever."""
    store = PanelStore(str(tmp_path))
    df = pd.DataFrame({
        "ts_code": ["A", "A"], "end_date": ["20240331", "20240630"],
        "f_ann_date": [None, np.nan],
        "n_cashflow_act": [1.0, 2.0],
    })
    u = ("ts_code", "end_date", "f_ann_date")
    assert store.insert("cashflow", df, unique=u) == 2
    assert store.insert("cashflow", df, unique=u) == 0
    # and across a fresh instance (keys reloaded from parquet)
    assert PanelStore(str(tmp_path)).insert("cashflow", df, unique=u) == 0
    assert len(store.read("cashflow")) == 2


def test_cross_instance_deletion_invalidates_cache(tmp_path):
    """replace_where by ANOTHER instance must not leave this instance's key
    cache claiming the deleted keys still exist (silent row loss)."""
    a = PanelStore(str(tmp_path))
    b = PanelStore(str(tmp_path))
    u = ("index_code", "trade_date", "con_code")
    row = pd.DataFrame({"index_code": ["i"], "trade_date": ["d1"],
                        "con_code": ["A"]})
    assert a.insert("c", row, unique=u) == 1
    b.replace_where("c", lambda cur: cur["trade_date"] == "d1",
                    pd.DataFrame({"index_code": ["i"], "trade_date": ["d2"],
                                  "con_code": ["A"]}))
    # the d1 row is gone on disk; A must accept its corrected re-insert
    assert a.insert("c", row, unique=u) == 1
    got = a.read("c")
    assert sorted(got["trade_date"]) == ["d1", "d2"]


def test_interrupted_rewrite_heals_without_duplicates(tmp_path):
    store = PanelStore(str(tmp_path))
    u = ("ts_code", "trade_date")
    for d in range(3):
        store.insert("y", _day_frame(d, n=2), unique=u)
    before = store.read("y").sort_values(["trade_date", "ts_code"])

    # simulate a crash mid-_rewrite: merged part + marker written, old parts
    # NOT yet deleted (the double-count window)
    old = store._parts("y")
    d = store._dir("y")
    final = f"part-{store._next_part_index(d):06d}-999.parquet"
    before.reset_index(drop=True).to_parquet(
        os.path.join(d, final), index=False)
    import json as _json
    with open(store._marker_path("y"), "w") as f:
        _json.dump({"pending": final + ".pending", "final": final,
                    "obsolete": [os.path.relpath(p, store.root) for p in old]},
                   f)

    fresh = PanelStore(str(tmp_path))
    after = fresh.read("y").sort_values(["trade_date", "ts_code"])
    assert len(after) == len(before)  # healed: no doubled rows
    pd.testing.assert_frame_equal(before.reset_index(drop=True),
                                  after.reset_index(drop=True))
    assert not os.path.exists(store._marker_path("y"))
    assert fresh.insert("y", _day_frame(0, n=2), unique=u) == 0


def test_second_instance_inserts_are_seen(tmp_path):
    """A stale per-instance key cache must not re-admit keys another store
    instance wrote to the same root."""
    a = PanelStore(str(tmp_path))
    b = PanelStore(str(tmp_path))
    u = ("ts_code", "trade_date")
    assert a.insert("d", _day_frame(0, n=2), unique=u) == 2
    assert b.insert("d", _day_frame(1, n=2), unique=u) == 2
    assert a.insert("d", _day_frame(1, n=2), unique=u) == 0  # stale cache
    assert len(a.read("d")) == 4


def test_corrupt_part_does_not_reset_watermark(tmp_path):
    store = PanelStore(str(tmp_path))
    store.insert("daily_prices", _day_frame(0), unique=("ts_code", "trade_date"))
    part = store._parts("daily_prices")[0]
    with open(part, "wb") as f:
        f.write(b"not parquet")
    with pytest.raises(Exception):
        store.last_date("daily_prices")  # surfaced, not None
    # a missing date column, by contrast, is a clean None
    s2 = PanelStore(str(tmp_path / "s2"))
    s2.insert("z", pd.DataFrame({"a": [1]}))
    assert s2.last_date("z") is None


def test_repair_and_verify(tmp_path):
    store = PanelStore(str(tmp_path))
    store.insert("stock_info", pd.DataFrame({"ts_code": ["A", "B", "C"]}))
    store.insert("daily_prices", pd.DataFrame({
        "ts_code": ["A", "B"], "trade_date": ["d1", "d1"]}))
    assert find_missing_stocks(store) == ["C"]
    v = verify_store(store)
    assert v["stocks"] == 2 and v["rows"] == 2
