"""Hermetic ETL tests: watermark resume, rate limiting, retry, dedup inserts,
delete-then-insert refresh, repair tooling — all against fakes."""

import numpy as np
import pandas as pd
import pytest

from mfm_tpu.data.etl import (
    IncrementalUpdater,
    PanelStore,
    RateLimiter,
    find_missing_stocks,
    verify_store,
    with_retry,
)


class FakeSource:
    def __init__(self):
        self.calls = []
        self.fail_next = 0

    def fetch_daily_prices(self, trade_date):
        self.calls.append(("daily", trade_date))
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError("transient")
        return pd.DataFrame({
            "ts_code": ["A.SH", "B.SH"],
            "trade_date": [trade_date, trade_date],
            "close": [1.0, 2.0],
        })

    def fetch_cashflow_by_stock(self, ts_code, start_date=None, end_date=None):
        self.calls.append(("cashflow", ts_code))
        return pd.DataFrame({
            "ts_code": [ts_code], "f_ann_date": ["20240430"],
            "end_date": ["20240331"], "n_cashflow_act": [1.5],
        })

    def fetch_index_components(self, index_code, trade_date):
        self.calls.append(("components", index_code, trade_date))
        return pd.DataFrame({
            "index_code": [index_code] * 2, "trade_date": [trade_date] * 2,
            "con_code": ["A.SH", "B.SH"], "weight": [60.0, 40.0],
        })


def test_watermark_resume(tmp_path):
    store = PanelStore(str(tmp_path))
    src = FakeSource()
    up = IncrementalUpdater(store, src, sleep=lambda s: None)
    cal = ["20240101", "20240102", "20240103"]
    up.update_daily_prices(cal)
    assert store.last_date("daily_prices") == "20240103"
    n_calls = len(src.calls)
    # second run: nothing after the watermark -> no fetches
    up.update_daily_prices(cal)
    assert len(src.calls) == n_calls
    # extending the calendar fetches only the new day
    up.update_daily_prices(cal + ["20240104"])
    assert src.calls[-1] == ("daily", "20240104")
    assert store.distinct_count("daily_prices", "trade_date") == 4


def test_insert_is_idempotent(tmp_path):
    store = PanelStore(str(tmp_path))
    df = pd.DataFrame({"ts_code": ["A", "B"], "trade_date": ["d1", "d1"],
                       "close": [1.0, 2.0]})
    assert store.insert("x", df, unique=("ts_code", "trade_date")) == 2
    assert store.insert("x", df, unique=("ts_code", "trade_date")) == 0
    assert len(store.read("x")) == 2


def test_retry_recovers_from_transient_failures(tmp_path):
    store = PanelStore(str(tmp_path))
    src = FakeSource()
    src.fail_next = 2  # two failures, third attempt succeeds
    sleeps = []
    up = IncrementalUpdater(store, src, backoff_s=5.0,
                            sleep=lambda s: sleeps.append(s))
    up.update_daily_prices(["20240101"])
    assert len(store.read("daily_prices")) == 2
    assert sleeps == [5.0, 5.0]


def test_retry_exhausts_and_raises():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("permanent")

    with pytest.raises(ValueError):
        with_retry(boom, attempts=3, backoff_s=0, sleep=lambda s: None)
    assert len(calls) == 3


def test_rate_limiter_sliding_window():
    now = [0.0]
    sleeps = []
    rl = RateLimiter(3, clock=lambda: now[0], sleep=lambda s: sleeps.append(s))
    for _ in range(3):
        rl.wait()
        now[0] += 1.0
    rl.wait()  # 4th call within 60s -> must sleep until first stamp expires
    assert sleeps and abs(sleeps[0] - 57.0) < 1e-9


def test_statements_and_components(tmp_path):
    store = PanelStore(str(tmp_path))
    src = FakeSource()
    up = IncrementalUpdater(store, src, sleep=lambda s: None)
    up.update_statements(["A.SH", "B.SH"], "cashflow")
    assert store.distinct_count("cashflow", "ts_code") == 2
    up.update_statements(["A.SH"], "cashflow")  # idempotent
    assert len(store.read("cashflow")) == 2

    up.update_index_components(["000300.SH"], "20240101")
    assert len(store.read("index_components")) == 2
    # refresh replaces, not duplicates
    up.update_index_components(["000300.SH"], "20240101")
    assert len(store.read("index_components")) == 2


def test_repair_and_verify(tmp_path):
    store = PanelStore(str(tmp_path))
    store.insert("stock_info", pd.DataFrame({"ts_code": ["A", "B", "C"]}))
    store.insert("daily_prices", pd.DataFrame({
        "ts_code": ["A", "B"], "trade_date": ["d1", "d1"]}))
    assert find_missing_stocks(store) == ["C"]
    v = verify_store(store)
    assert v["stocks"] == 2 and v["rows"] == 2
