"""Alpha -> risk-model integration (alpha/integrate.py): selected alpha
expressions become extra style columns of the barra table, priced by the
constrained regression like any classic style."""

import numpy as np
import jax.numpy as jnp
import pytest

from mfm_tpu.alpha.integrate import alpha_style_columns


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(0)
    T, N = 80, 30
    close = np.cumprod(1 + 0.02 * rng.standard_normal((T, N)), axis=0) * 20
    vol = np.exp(rng.normal(12, 1, (T, N)))
    close[rng.random((T, N)) < 0.05] = np.nan
    fields = {"close": jnp.asarray(close, jnp.float32),
              "volume": jnp.asarray(vol, jnp.float32)}
    fwd = np.vstack([close[1:] / close[:-1] - 1.0,
                     np.full((1, N), np.nan)]).astype(np.float32)
    return fields, jnp.asarray(fwd)


def test_alpha_style_columns_shapes_and_report(panel):
    fields, fwd = panel
    srcs = ["-delta(close, 5)",               # reversal: real signal vs fwd
            "cs_rank(ts_mean(volume, 10))",   # volume level
            "-delta(close, 5) * 1.0001"]      # near-duplicate of #1
    names, expo, report = alpha_style_columns(srcs, fields, fwd, k=2,
                                              max_corr=0.9)
    T, N = fields["close"].shape
    assert expo.shape == (T, N, len(names)) and len(names) <= 2
    # z-scored with NaN->0: every date's cross-section is finite
    assert np.isfinite(expo).all()
    # per-date mean ~ 0 on dates with valid data (z-score + zero fill)
    assert np.abs(expo.mean(axis=1)).max() < 0.5
    # the near-duplicate must not be selected alongside its twin
    picked = {report[n]["expression"] for n in names}
    assert not {"-delta(close, 5)", "-delta(close, 5) * 1.0001"} <= picked
    for n in names:
        assert np.isfinite(report[n]["mean_ic"])
        assert np.isfinite(report[n]["score"])


def test_alpha_style_columns_validates(panel):
    fields, fwd = panel
    with pytest.raises(ValueError, match="unknown panel field"):
        alpha_style_columns(["delta(nope, 3)"], fields, fwd, k=1)
    with pytest.raises(ValueError, match="no alpha expressions"):
        alpha_style_columns([], fields, fwd, k=1)
