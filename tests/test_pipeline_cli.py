"""End-to-end CLI smoke tests: raw synthetic collections -> prepare ->
factors -> risk, and the one-command ``pipeline`` path (VERDICT round-1
missing #2).  Asserts all five demo.py result tables exist and are sane."""

import json
import os

import numpy as np
import pandas as pd
import pytest

from mfm_tpu.cli import main as cli_main
from mfm_tpu.data.etl import PanelStore
from mfm_tpu.data.synthetic import synthetic_collections


@pytest.fixture(scope="module")
def store_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("store")
    synthetic_collections(PanelStore(str(d)), T=100, N=16, n_industries=4,
                          seed=7)
    return str(d)


RESULT_TABLES = ("factor_returns.csv", "r_squared.csv",
                 "specific_returns.csv", "final_covariance.csv", "lambda.csv")


def test_pipeline_one_command(store_dir, tmp_path, capsys):
    out = str(tmp_path / "results")
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              "--eigen-sims", "8", "--start", "20200101"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["stocks"] == 16
    assert rec["rows"] > 0

    # stage artifacts
    assert os.path.exists(os.path.join(out, "barra_data.csv"))
    assert os.path.exists(os.path.join(out, "industry_info.csv"))
    assert os.path.exists(os.path.join(out, "risk_outputs.npz"))
    for name in RESULT_TABLES:  # the five demo.py:60-94 tables
        assert os.path.exists(os.path.join(out, name)), name

    fr = pd.read_csv(os.path.join(out, "factor_returns.csv"), index_col=0)
    info = pd.read_csv(os.path.join(out, "industry_info.csv"))
    # country + industries + 10 styles
    assert fr.shape[1] == 1 + len(info) + 10
    assert np.isfinite(fr.to_numpy()).any()
    r2 = pd.read_csv(os.path.join(out, "r_squared.csv"), index_col=0)
    assert np.nanmean(r2.to_numpy()) > 0.0

    cov = pd.read_csv(os.path.join(out, "final_covariance.csv"), index_col=0)
    assert cov.shape[0] == cov.shape[1] == fr.shape[1]
    c = cov.to_numpy()
    assert np.allclose(c, c.T, atol=1e-8)

    # resume path: reuses the stage artifact without touching the store
    cli_main(["pipeline", "--store", str(tmp_path / "nonexistent"),
              "--out", out, "--resume", "--eigen-sims", "8"])
    rec2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec2["rows"] == rec["rows"]


def test_prepare_then_factors_chain(store_dir, tmp_path, capsys):
    prep_out = str(tmp_path / "prepared")
    cli_main(["prepare", "--store", store_dir, "--out", prep_out,
              "--start", "20200101"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["stocks"] == 16
    for k in ("panel", "index", "industry"):
        assert os.path.exists(rec[k])

    fact_out = str(tmp_path / "factors")
    cli_main(["factors", "--panel", rec["panel"], "--index", rec["index"],
              "--industry", rec["industry"], "--out", fact_out])
    rec2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    barra = pd.read_csv(rec2["out"])
    for col in ("date", "stocknames", "capital", "ret", "industry", "size",
                "beta", "momentum", "residual_volatility", "liquidity"):
        assert col in barra.columns, col
    assert barra["stocknames"].nunique() == 16

    # --prepared DIR is the same run in one flag
    cli_main(["factors", "--prepared", prep_out,
              "--out", str(tmp_path / "factors2")])
    rec3 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    barra2 = pd.read_csv(rec3["out"])
    pd.testing.assert_frame_equal(barra2, barra)

    # conflicting / missing sources are rejected up front
    with pytest.raises(SystemExit, match="--prepared already provides"):
        cli_main(["factors", "--prepared", prep_out, "--panel", rec["panel"],
                  "--out", fact_out])
    with pytest.raises(SystemExit, match="pass either"):
        cli_main(["factors", "--panel", rec["panel"], "--out", fact_out])


def test_pipeline_to_store_risk_from_store_roundtrip(store_dir, tmp_path,
                                                     capsys):
    """pipeline --to-store persists barra_factors +
    sw_industry_info_for_factors (main.py:144-155's Mongo save against the
    PanelStore); risk --barra-store reproduces the CSV path's outputs from
    those collections (demo.ipynb's Mongo-sourced variant)."""
    out1 = str(tmp_path / "res_csv")
    fstore = str(tmp_path / "factor_store")
    cli_main(["pipeline", "--store", store_dir, "--out", out1,
              "--eigen-sims", "8", "--start", "20200101",
              "--to-store", fstore])
    capsys.readouterr()

    st = PanelStore(fstore)
    barra = st.read("barra_factors")
    info = st.read("sw_industry_info_for_factors")
    assert len(barra) and len(info)
    assert set(pd.read_csv(os.path.join(out1, "industry_info.csv"))["code"]) \
        == set(info["code"])

    out2 = str(tmp_path / "res_store")
    cli_main(["risk", "--barra-store", fstore, "--out", out2,
              "--eigen-sims", "8"])
    capsys.readouterr()
    for name in ("factor_returns.csv", "r_squared.csv", "lambda.csv"):
        a = pd.read_csv(os.path.join(out1, name), index_col=0)
        b = pd.read_csv(os.path.join(out2, name), index_col=0)
        np.testing.assert_allclose(b.to_numpy(), a.to_numpy(),
                                   rtol=2e-5, atol=1e-7, equal_nan=True)

    # a second --to-store run is a full refresh, not an append
    cli_main(["pipeline", "--store", store_dir, "--out", out1,
              "--eigen-sims", "8", "--start", "20200101",
              "--to-store", fstore, "--resume"])
    capsys.readouterr()
    assert len(st.read("barra_factors")) == len(barra)


def test_risk_from_empty_store_errors(tmp_path, capsys):
    with pytest.raises(SystemExit, match="barra_factors"):
        cli_main(["risk", "--barra-store", str(tmp_path / "nothing"),
                  "--out", str(tmp_path / "o")])
    capsys.readouterr()


def test_demo_check_determinism_cli(tmp_path, capsys):
    cli_main(["demo", "--dates", "30", "--stocks", "12", "--industries", "3",
              "--styles", "2", "--eigen-sims", "4",
              "--out", str(tmp_path / "o"), "--check-determinism"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["deterministic"] is True


def test_risk_profile_writes_trace(tmp_path, capsys):
    from mfm_tpu.data.synthetic import synthetic_barra_table

    df, _ = synthetic_barra_table(T=30, N=12, P=3, Q=2, seed=3)
    barra = str(tmp_path / "b.csv")
    df.to_csv(barra, index=False)
    prof = str(tmp_path / "trace")
    cli_main(["risk", "--barra", barra, "--out", str(tmp_path / "o"),
              "--eigen-sims", "4", "--profile", prof])
    json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # jax.profiler.trace writes plugins/profile/<ts>/*.xplane.pb
    hits = [f for _, _, fs in os.walk(prof) for f in fs]
    assert any(f.endswith(".xplane.pb") for f in hits), hits


def test_pipeline_profile_writes_trace(store_dir, tmp_path, capsys):
    prof = str(tmp_path / "trace")
    cli_main(["pipeline", "--store", store_dir, "--out", str(tmp_path / "o"),
              "--eigen-sims", "4", "--start", "20200101", "--profile", prof])
    json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    hits = [f for _, _, fs in os.walk(prof) for f in fs]
    assert any(f.endswith(".xplane.pb") for f in hits), hits


def test_pipeline_portfolio_bias_flag(store_dir, tmp_path, capsys):
    out = str(tmp_path / "o")
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              "--eigen-sims", "4", "--start", "20200101",
              "--portfolio-bias", "5"])
    json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    rec = json.load(open(os.path.join(out, "portfolio_bias.json")))
    assert rec["n_portfolios"] == 5
    assert len(rec["all_valid_dates"]["bias"]) == 5


def test_factors_prepared_missing_artifacts(tmp_path):
    with pytest.raises(SystemExit, match="missing artifact"):
        cli_main(["factors", "--prepared", str(tmp_path / "typo_dir"),
                  "--out", str(tmp_path / "o")])


def test_load_risk_pipeline_result_roundtrip(store_dir, tmp_path, capsys):
    """A finished pipeline out dir rehydrates into a working result: same
    tables, and the post-hoc acceptance tests run without the model."""
    from mfm_tpu.pipeline import load_risk_pipeline_result

    out = str(tmp_path / "res")
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              "--eigen-sims", "8", "--start", "20200101"])
    capsys.readouterr()

    res = load_risk_pipeline_result(out)
    assert res.model is None
    fr_live = pd.read_csv(os.path.join(out, "factor_returns.csv"),
                          index_col=0)
    np.testing.assert_allclose(res.factor_returns().to_numpy(),
                               fr_live.to_numpy(), rtol=2e-5, atol=1e-7,
                               equal_nan=True)
    # post-hoc analytics off the artifact alone
    rep = res.portfolio_bias(n_portfolios=4, burn_in=20, min_periods=5)
    assert len(rep["all_valid_dates"]["bias"]) == 4
    raw, shrunk = res.specific_risk(min_periods=5)
    assert shrunk.shape == res.specific_returns().shape


def test_load_risk_pipeline_result_rejects_mismatched_dir(store_dir,
                                                          tmp_path, capsys):
    from mfm_tpu.pipeline import load_risk_pipeline_result

    out = str(tmp_path / "res")
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              "--eigen-sims", "4", "--start", "20200101"])
    capsys.readouterr()
    # swap in a barra table with a different universe
    df = pd.read_csv(os.path.join(out, "barra_data.csv"))
    df[df["stocknames"] != df["stocknames"].iloc[0]].to_csv(
        os.path.join(out, "barra_data.csv"), index=False)
    with pytest.raises(ValueError, match="does not match"):
        load_risk_pipeline_result(out)


def test_risk_save_outputs_flag(tmp_path, capsys):
    from mfm_tpu.data.artifacts import load_risk_outputs
    from mfm_tpu.data.synthetic import synthetic_barra_table

    df, _ = synthetic_barra_table(T=40, N=16, P=3, Q=2, seed=5)
    barra = str(tmp_path / "b.csv")
    df.to_csv(barra, index=False)
    out = str(tmp_path / "res")
    cli_main(["risk", "--barra", barra, "--out", out, "--eigen-sims", "4",
          "--save-outputs"])
    capsys.readouterr()
    outputs, meta = load_risk_outputs(os.path.join(out, "risk_outputs.npz"))
    assert outputs.vr_cov.shape[0] == 40  # FULL covariance series
    assert meta["source"] == barra
    assert len(meta["dates"]) == 2 and meta["n_stocks"] == 16


def test_pipeline_portfolio_risk_flag(store_dir, tmp_path, capsys):
    import numpy as np
    import pandas as pd

    out = str(tmp_path / "o")
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              "--eigen-sims", "4", "--start", "20200101"])
    json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # equal-weight the last date's universe from the produced barra table
    df = pd.read_csv(os.path.join(out, "barra_data.csv"))
    # the final date's t+1 return is NaN (main.py:99 shift), so the last
    # date with a full universe is the second-to-last — exercise
    # --portfolio-date while at it
    dates = sorted(df.date.unique())
    last = df[df.date == dates[-2]].dropna()
    assert len(last) > 0
    pf = str(tmp_path / "pf.csv")
    pd.DataFrame({"ts_code": last.stocknames,
                  "weight": 1.0 / len(last)}).to_csv(pf, index=False)
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              "--eigen-sims", "4", "--start", "20200101",
              "--resume", "--portfolio", pf, "--portfolio-date", "-2"])
    capsys.readouterr()
    rec = json.load(open(os.path.join(out, "portfolio_risk.json")))
    assert rec["total_vol"] > 0
    contrib = rec["factor_risk_contribution"]
    assert np.isclose(sum(contrib.values()), rec["factor_var"], rtol=1e-6)
    assert np.isclose(rec["factor_exposures"]["country"], 1.0, atol=1e-6)

    # unknown ts_codes must be an error, not a silent drop
    bad = str(tmp_path / "bad.csv")
    pd.DataFrame({"ts_code": ["NOPE.SZ"], "weight": [1.0]}).to_csv(
        bad, index=False)
    with pytest.raises(SystemExit, match="outside the panel"):
        cli_main(["pipeline", "--store", store_dir, "--out", out,
                  "--eigen-sims", "4", "--start", "20200101",
                  "--resume", "--portfolio", bad])

    # duplicate rows must be an error, not last-wins
    code = last.stocknames.iloc[0]
    dup = str(tmp_path / "dup.csv")
    pd.DataFrame({"ts_code": [code, code], "weight": [0.5, 0.5]}).to_csv(
        dup, index=False)
    with pytest.raises(SystemExit, match="more than once"):
        cli_main(["pipeline", "--store", store_dir, "--out", out,
                  "--eigen-sims", "4", "--start", "20200101",
                  "--resume", "--portfolio", dup])


def test_pipeline_alpha_styles_flag(store_dir, tmp_path, capsys):
    """The title's loop end-to-end: --alphas expressions become priced style
    factors — factor_returns.csv grows alpha_* columns and the report maps
    them to expressions."""
    exprs = str(tmp_path / "alphas.txt")
    with open(exprs, "w") as fh:
        fh.write("# candidates\n"
                 "-delta(close, 5)\n"
                 "cs_rank(ts_mean(turnover_rate, 10))\n"
                 "-delta(close, 5) * 1.0001\n")
    out = str(tmp_path / "o")
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              "--eigen-sims", "4", "--start", "20200101",
              "--alphas", exprs, "--alpha-top", "2"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["alpha_styles"] >= 1
    rep = json.load(open(os.path.join(out, "alpha_styles.json")))
    assert set(rep) == {f"alpha_{i+1:02d}" for i in range(rec["alpha_styles"])}
    fr = pd.read_csv(os.path.join(out, "factor_returns.csv"), index_col=0)
    for name in rep:
        assert name in fr.columns
        assert np.isfinite(fr[name].to_numpy(float)).all()
    # the near-duplicate pair must not BOTH survive selection
    picked = {v["expression"] for v in rep.values()}
    assert not {"-delta(close, 5)", "-delta(close, 5) * 1.0001"} <= picked
    # the stage artifact stays the classic table (no alpha columns persisted)
    barra = pd.read_csv(os.path.join(out, "barra_data.csv"), nrows=1)
    assert not any(c.startswith("alpha_") for c in barra.columns)

    # --resume re-prepares the raw panel for the alpha stage and reproduces
    # the same selection
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              "--eigen-sims", "4", "--start", "20200101",
              "--resume", "--alphas", exprs, "--alpha-top", "2"])
    capsys.readouterr()
    rep2 = json.load(open(os.path.join(out, "alpha_styles.json")))
    assert rep2 == rep

    # bad expression or missing file fails fast with file:line, before the
    # factor stage runs
    bad = str(tmp_path / "bad_alphas.txt")
    with open(bad, "w") as fh:
        fh.write("delta(close, 5\n")  # unclosed paren -> SyntaxError
    with pytest.raises(SystemExit, match="bad_alphas.txt:1"):
        cli_main(["pipeline", "--store", store_dir, "--out", out,
                  "--eigen-sims", "4", "--start", "20200101",
                  "--resume", "--alphas", bad])
    with pytest.raises(SystemExit, match="--alphas"):
        cli_main(["pipeline", "--store", store_dir, "--out", out,
                  "--eigen-sims", "4", "--start", "20200101",
                  "--resume", "--alphas", str(tmp_path / "nope.txt")])


def test_pipeline_append_subprocess_matches_from_scratch(store_dir, tmp_path,
                                                         capsys):
    """The acceptance round trip: init a pipeline up to a cut date, append
    the remaining store dates from a SEPARATE process (state rehydrated from
    risk_state.npz only), and land bitwise on the from-scratch full run —
    all five result tables, risk_outputs.npz, and the advanced checkpoint.
    --eigen-sim-length is pinned so runs of different history lengths draw
    the same Monte-Carlo sims (the default draw length is T)."""
    import subprocess
    import sys

    import mfm_tpu
    from mfm_tpu.data.artifacts import load_artifact

    prices = PanelStore(store_dir).read("daily_prices")
    counts = prices.groupby("trade_date")["ts_code"].nunique()
    dates = sorted(counts.index)
    # a revision-free cut: every stock trades on it, so no t+1 return label
    # straddles the boundary (see _check_append_prefix_unrevised)
    full_days = [d for d in dates[-12:-4]
                 if counts[d] == prices["ts_code"].nunique()]
    assert full_days, "store has no full-universe date near the end"
    cut = pd.Timestamp(full_days[-1]).strftime("%Y%m%d")
    common = ["--eigen-sims", "8", "--eigen-sim-length", "50",
              "--start", "20200101"]

    out = str(tmp_path / "out")
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              *common, "--end", cut])
    capsys.readouterr()
    assert os.path.exists(os.path.join(out, "risk_state.npz"))

    # conftest's XLA_FLAGS (8 virtual devices) rides along via os.environ;
    # x64 it sets through jax.config, so mirror it explicitly
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(
        mfm_tpu.__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root, "JAX_PLATFORMS": "cpu",
           "JAX_ENABLE_X64": "1"}
    proc = subprocess.run(
        [sys.executable, "-m", "mfm_tpu.cli", "pipeline", "--store",
         store_dir, "--out", out, *common, "--append"],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert len(rec["appended_dates"]) >= 4
    assert rec["update_wall_s"] > 0

    ref = str(tmp_path / "ref")
    cli_main(["pipeline", "--store", store_dir, "--out", ref, *common])
    capsys.readouterr()

    for name in RESULT_TABLES:
        a = pd.read_csv(os.path.join(ref, name), index_col=0)
        b = pd.read_csv(os.path.join(out, name), index_col=0)
        pd.testing.assert_frame_equal(a, b, check_exact=True, obj=name)
    xa, _ = load_artifact(os.path.join(ref, "risk_outputs.npz"))
    xb, _ = load_artifact(os.path.join(out, "risk_outputs.npz"))
    for k in xa:
        np.testing.assert_array_equal(xa[k], xb[k], err_msg=k)
    sa, ma = load_artifact(os.path.join(ref, "risk_state.npz"))
    sb, mb = load_artifact(os.path.join(out, "risk_state.npz"))
    for k in sa:
        np.testing.assert_array_equal(sa[k], sb[k], err_msg=k)
    assert ma["last_date"] == mb["last_date"]

    # the checkpoint advanced past every store date, so appending again has
    # nothing to do — that is an error, not a silent no-op
    with pytest.raises(SystemExit, match="already covers every date"):
        cli_main(["pipeline", "--store", store_dir, "--out", out,
                  *common, "--append"])
    capsys.readouterr()


def test_pipeline_append_refuses_revised_history(store_dir, tmp_path,
                                                 capsys):
    """Cut at a date where some stock is suspended: the from-scratch rerun
    fills that stock's t+1 return label in across the gap (next-traded-day
    semantics), revising a prefix row the checkpoint already served.  The
    append path must detect that and refuse, not silently diverge from a
    full-history run."""
    prices = PanelStore(store_dir).read("daily_prices")
    counts = prices.groupby("trade_date")["ts_code"].nunique()
    dates = sorted(counts.index)
    gap_days = [d for d in dates[-8:-3]
                if counts[d] < prices["ts_code"].nunique()]
    assert gap_days, "store has no suspension near the end"
    cut = pd.Timestamp(gap_days[-1]).strftime("%Y%m%d")
    common = ["--eigen-sims", "8", "--eigen-sim-length", "50",
              "--start", "20200101"]

    out = str(tmp_path / "out")
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              *common, "--end", cut])
    capsys.readouterr()
    with pytest.raises(SystemExit, match="revised history"):
        cli_main(["pipeline", "--store", store_dir, "--out", out,
                  *common, "--append"])
    capsys.readouterr()
