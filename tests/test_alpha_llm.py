"""Tolerant extraction of DSL expressions from raw LLM output
(mfm_tpu/alpha/llm.py) and its --llm CLI surface."""

import json

import pytest

from mfm_tpu.alpha.llm import extract_expressions
from mfm_tpu.cli import main as cli_main

from test_alpha_cli import panel_csv  # noqa: F401  (fixture reuse)


CHAT = """\
Here are some alpha factor ideas for your panel:

1. `cs_rank(delta(close, 3))`
2. **Mean reversion**: -ts_corr(close, volume, 10)
3. alpha_momentum = cs_zscore(ts_mean(ret, 5))

```python
signed_power(cs_winsorize(ret, 2.5), 0.5)
cs_rank(delta(close, 3))
```

Note that factor 1 captures short-term momentum, while factor 2
is a classic price-volume divergence signal.

- volume

Hope these help! Let me know if you want variations.
"""


def test_extracts_valid_dedups_and_reports():
    exprs, rep = extract_expressions(
        CHAT, known_fields={"close", "ret", "volume"})
    # four unique expressions; the fenced repeat of #1 dedups away
    assert exprs == [
        "cs_rank(delta(close, 3))",
        "-ts_corr(close, volume, 10)",
        "cs_zscore(ts_mean(ret, 5))",
        "signed_power(cs_winsorize(ret, 2.5), 0.5)",
    ]
    assert rep["n_extracted"] == 4
    assert rep["n_duplicates"] == 1
    # prose lines land in the rejection report, not in the result
    assert rep["rejected"]
    assert all(r not in exprs for _, r, _ in rep["rejected"])


def test_bare_name_needs_code_markup():
    # "- volume" is a valid DSL expression but indistinguishable from prose;
    # only code markup (backticks / fences) vouches for it
    exprs, rep = extract_expressions("- volume\n")
    assert exprs == []
    assert rep["rejected"][0][2].startswith("trivial")
    exprs, _ = extract_expressions("`volume`\n")
    assert exprs == ["volume"]


def test_every_backtick_span_is_a_candidate():
    # "or"-style lines offer alternatives; none may vanish silently
    exprs, rep = extract_expressions(
        "Try `cs_rank(delta(close, 3))` or `cs_rank(volume)` here\n")
    assert exprs == ["cs_rank(delta(close, 3))", "cs_rank(volume)"]
    assert rep["n_candidates"] == 2


def test_unknown_fields_are_rejected_not_fatal():
    exprs, rep = extract_expressions(
        "cs_rank(close)\ncs_rank(unknown_thing)\n", known_fields={"close"})
    assert exprs == ["cs_rank(close)"]
    assert any("unknown-field" in r for _, _, r in rep["rejected"])


def test_label_stripping_keeps_comparisons():
    # `x = expr` labels strip; comparison operators inside expressions don't
    exprs, _ = extract_expressions(
        "a1 = cs_rank(close) * (close >= delay(close, 5))\n")
    assert exprs == ["cs_rank(close) * (close >= delay(close, 5))"]


def test_alpha_cli_llm_mode(panel_csv, tmp_path, capsys):  # noqa: F811
    chat = tmp_path / "chat.md"
    chat.write_text(CHAT)
    out = str(tmp_path / "scores.csv")
    cli_main(["alpha", "--llm", "--exprs", str(chat), "--panel", panel_csv,
              "--out", out])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["n_exprs"] == 4
    assert rec["llm_extraction"]["n_extracted"] == 4
    assert rec["llm_extraction"]["n_duplicates"] == 1


def test_alpha_cli_llm_mode_all_prose_fails(panel_csv, tmp_path):  # noqa: F811
    chat = tmp_path / "chat.md"
    chat.write_text("I could not think of any factors today, sorry.\n")
    with pytest.raises(SystemExit, match="no expressions"):
        cli_main(["alpha", "--llm", "--exprs", str(chat), "--panel",
                  panel_csv])


def test_constant_candidates_rejected_everywhere():
    """'IC: -0.03' chrome and code-marked '5' are field-free constants —
    rejected, never handed to the batch evaluator to crash on."""
    exprs, rep = extract_expressions("IC: -0.03\nwhere `5` is the lookback\n")
    assert exprs == []
    assert all("no panel fields" in r for _, _, r in rep["rejected"])


def test_single_line_triple_fence_is_inline_code():
    exprs, rep = extract_expressions(
        "```cs_rank(close)```\nsome prose follows\nvolume\n",
        known_fields={"close", "volume"})
    # the expression is kept AND the fence state does not invert: the
    # following bare prose words stay unmarked and are rejected
    assert exprs == ["cs_rank(close)"]
    assert all("trivial" in r or "not DSL" in r
               for _, _, r in rep["rejected"])


def test_alias_and_canonical_spellings_dedup():
    exprs, rep = extract_expressions("`rank(close)`\n`cs_rank(close)`\n")
    assert exprs == ["rank(close)"]
    assert rep["n_duplicates"] == 1


def test_op_names_are_reserved_words():
    """A backticked bare op name (LLM prose: 'where `rank` is ...') must be
    rejected at compile, not crash evaluation with a panel KeyError."""
    import pytest as _pytest

    from mfm_tpu.alpha.dsl import compile_alpha

    for bad in ("rank", "sum + 1", "delta(rank, 5)"):
        with _pytest.raises(ValueError, match="reserved"):
            compile_alpha(bad)
    exprs, rep = extract_expressions("where `rank` is the rank op\n")
    assert exprs == []
    assert "reserved" in rep["rejected"][0][2]


def test_arity_checked_at_compile():
    import pytest as _pytest

    from mfm_tpu.alpha.dsl import compile_alpha

    for bad in ("scale(cs_rank(close), 2)", "sum(close)",
                "ts_corr(close, 5)"):
        with _pytest.raises(ValueError, match="argument"):
            compile_alpha(bad)
    compile_alpha("cs_winsorize(close)")      # optional k still optional
    compile_alpha("cs_winsorize(close, 3.0)")
    # ops whose raw jnp signatures under-constrain sig.bind: jnp.where
    # defaults x/y (1- and 2-arg calls bound, then crashed inside the jit
    # batch), the minimum/maximum ufunc wrappers report zero required args
    for bad in ("where(close > 0)", "where(close > 0, close)",
                "where(close > 0, close, 0.0, 1.0)", "min(close)", "max()",
                "power(close)", "power(close, 2.0, 3.0)"):
        with _pytest.raises(ValueError, match="argument"):
            compile_alpha(bad)
    compile_alpha("where(close > 0, close, -close)")  # the 3-arg contract
    compile_alpha("power(close, 2.0)")                # the 2-arg contract


def test_window_args_must_be_positive_int_constants():
    """Window/lag/group-count args parameterize static shapes: a float
    window silently truncates (arange(5.5) -> 6), zero/negative windows and
    panel-valued lags crash the shared jit batch at trace time — all must
    be rejected per line at compile instead."""
    import pytest as _pytest

    from mfm_tpu.alpha.dsl import compile_alpha

    for bad in ("ts_mean(close, 5.5)", "ts_mean(close, 0)",
                "delta(close, -2)", "delay(close, volume)",
                "cs_neutralize(close, ind, 32.5)",
                "cs_neutralize(close, ind, 1000000000)",  # (T, G) table OOM
                "ts_rank(close, 50000)",       # (T, w, N) window OOM
                "ts_corr(close, volume, 10.0)",
                "stddev(close, 2.5)",          # alias resolves to ts_std
                "ts_rank(close, True)"):
        with _pytest.raises(ValueError, match="integer constant"):
            compile_alpha(bad)
    # valid forms unaffected, including the optional num_groups, the
    # delay/delta zero-lag identity, and genuinely-float parameters
    # (winsorize k, exponents)
    compile_alpha("ts_mean(close, 5)")
    compile_alpha("delay(close, 0)")
    compile_alpha("delta(close, 0)")
    compile_alpha("cs_neutralize(close, ind)")
    compile_alpha("cs_neutralize(close, ind, 32)")
    compile_alpha("cs_winsorize(close, 2.5)")
    compile_alpha("signed_power(close, 0.5)")
    # rejection lands in the tolerant-mode report, not a batch crash
    exprs, rep = extract_expressions("`ts_mean(close, 5.5)`\n")
    assert exprs == []
    assert "integer constant" in rep["rejected"][0][2]


def test_compile_alpha_fuzz_raises_only_value_or_syntax_errors():
    """Tolerant-mode contract: ANY junk line fed to compile_alpha either
    compiles or raises ValueError/SyntaxError — never a third exception
    type, which would escape the per-line handler and abort the whole
    ingestion run.  Seeded fragment-soup fuzz (a 20k-sample run of the same
    generator found zero violations)."""
    import random
    import warnings

    from mfm_tpu.alpha.dsl import compile_alpha

    rng = random.Random(0)
    frags = ["cs_rank", "ts_mean", "close", "volume", "(", ")", ",", "5",
             "5.5", "+", "-", "*", "/", "**", ">", "where", "min", "lambda",
             "[", "]", ".", "sum", "delay", "'x'", "__import__", "None",
             "True", "1e300", "0", "-3", "close.T", "{", "}", ":", "x", " ",
             "ind"]
    n_ok = 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", SyntaxWarning)
        for _ in range(2000):
            s = "".join(rng.choice(frags)
                        for _ in range(rng.randint(1, 12)))
            try:
                compile_alpha(s)
                n_ok += 1
            except (ValueError, SyntaxError):
                pass
    assert n_ok > 50  # the generator does produce valid DSL too


def test_degenerate_sampling_loop_lines_rejected_per_line():
    """Repeated-token LLM sampling loops produce single pathological lines
    ('-'*20000 + 'close', 'close' '+close'*10000, deep paren nests) that
    blow up the CPython parser (RecursionError/MemoryError) or would
    overflow _eval_node's recursion mid-batch.  Ingestion must land every
    one of them in the per-line rejection report and keep going."""
    import pytest as _pytest

    from mfm_tpu.alpha.dsl import compile_alpha

    # under the length cap but over the AST depth cap -> compile-time
    # rejection.  (Parens are not AST nodes: deep paren nests either
    # collapse to depth ~3 or hit CPython's own ~200-paren SyntaxError,
    # both already safe.)
    with _pytest.raises(ValueError, match="levels deep"):
        compile_alpha("-" * 500 + "close")
    with _pytest.raises(ValueError, match="levels deep"):
        compile_alpha("close" + "+close" * 150)
    compile_alpha("((((close))))")            # sane nesting unaffected
    compile_alpha("close" + "+close" * 50)    # long-but-sane sums too

    dump = "\n".join([
        "`cs_rank(delta(close, 3))`",
        "`" + "-" * 20000 + "close`",          # parser MemoryError class
        "`close" + "+close" * 10000 + "`",     # parser RecursionError class
        "`" + "-" * 500 + "close`",            # depth cap
        "`" + "(" * 500 + "close" + ")" * 500 + "`",  # parser paren limit
    ])
    exprs, rep = extract_expressions(dump, known_fields={"close"})
    assert exprs == ["cs_rank(delta(close, 3))"]
    reasons = [r for _, _, r in rep["rejected"]]
    assert len(reasons) == 4
    assert sum("too long" in r for r in reasons) == 2
    assert sum("levels deep" in r for r in reasons) == 1
    # monster candidates are truncated in the report, not echoed whole
    assert all(len(c) <= 203 for _, c, _ in rep["rejected"])
    # the same degenerate lines must stay per-line failures for the STRICT
    # readers too (cli --exprs): compile_alpha itself raises ValueError,
    # never RecursionError/MemoryError out of the parser
    for line in ("-" * 20000 + "close", "close" + "+close" * 10000):
        with _pytest.raises(ValueError, match="too long"):
            compile_alpha(line)


def test_compile_rejects_everything_eval_cannot_run():
    """The validator is a whitelist of exactly _eval_node's capabilities:
    anything it lets through must evaluate.  These all previously COMPILED
    and then died mid-batch inside the shared jit trace (unsupported-node
    ValueError or a _BINOPS KeyError)."""
    import pytest as _pytest

    from mfm_tpu.alpha.dsl import compile_alpha

    for bad in ("[close]", "(close, volume)", "{1: close}",
                "close if volume else ret", "close and volume",
                "not close", "close // volume", "close ^ volume",
                "close << 2", "f'{close}'", "close + 'x'",
                "ts_mean(close, 3) < ret < close"):
        with _pytest.raises((ValueError, SyntaxError)):
            compile_alpha(bad)
    # the full legitimate surface still compiles
    for good in ("cs_rank(close) > 0.5", "-close % 2 + +volume",
                 "where(close > 0, close ** 2, 0.0) / ts_mean(close, 5)"):
        compile_alpha(good)


def test_delay_past_series_start_keeps_panel_shape():
    """delay(x, d >= T) is all pre-history: it must return an all-NaN
    (T, N) panel, not the (d, N) shape the pad+concat form would emit."""
    import jax.numpy as jnp
    import numpy as np

    from mfm_tpu.alpha.dsl import delay

    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(4, 3))
    for d in (4, 7):
        out = np.asarray(delay(x, d))
        assert out.shape == (4, 3)
        assert np.isnan(out).all()
    np.testing.assert_array_equal(np.asarray(delay(x, 0)), np.asarray(x))


def test_ambiguous_windowed_min_max_rejected():
    import pytest as _pytest

    from mfm_tpu.alpha.dsl import compile_alpha

    with _pytest.raises(ValueError, match="ambiguous"):
        compile_alpha("max(close, 5)")   # 101 paper means ts_max
    compile_alpha("ts_max(close, 5)")    # the window form
    compile_alpha("max(close, 5.0)")     # elementwise clamp, explicit
    compile_alpha("max(close, open)")    # two-panel elementwise


def test_dash_bullet_convention_is_counted():
    exprs, rep = extract_expressions("- cs_rank(delta(close, 3))\n")
    assert exprs == ["cs_rank(delta(close, 3))"]
    assert rep["n_dash_bullets_stripped"] == 1
    # no-space negation is NOT a bullet
    exprs, rep = extract_expressions("-ts_corr(close, volume, 10)\n")
    assert exprs == ["-ts_corr(close, volume, 10)"]
    assert rep["n_dash_bullets_stripped"] == 0


def test_worldquant_alias_vocabulary():
    """A genuine 101-Alphas-style expression parses and the aliases compute
    exactly what their canonical ops compute."""
    import numpy as np

    from mfm_tpu.alpha.dsl import compile_alpha

    src = ("-1 * correlation(rank(delta(log(volume), 1)), "
           "rank((close - open) / open), 6)")
    canon = ("-1 * ts_corr(cs_rank(delta(log(volume), 1)), "
             "cs_rank((close - open) / open), 6)")
    rng = np.random.default_rng(3)
    T, N = 30, 8
    close = np.exp(rng.normal(1, 0.1, (T, N))).astype(np.float32)
    panel = {"close": close,
             "open": (close * np.exp(rng.normal(0, 0.01, (T, N)))
                      ).astype(np.float32),
             "volume": np.exp(rng.normal(10, 1, (T, N))).astype(np.float32)}
    a = np.asarray(compile_alpha(src)(panel))
    b = np.asarray(compile_alpha(canon)(panel))
    np.testing.assert_array_equal(a, b)
    # extraction accepts the alias vocabulary too
    exprs, _ = extract_expressions(
        f"`{src}`\n", known_fields={"close", "open", "volume"})
    assert exprs == [src]


def test_pipeline_alphas_llm_tolerates_hallucinated_fields(tmp_path, capsys):
    """pipeline --alphas-llm: a chat dump with one hallucinated field name
    must not abort the run — the bad expression drops with a stderr report,
    the good ones get priced, and stdout stays one clean JSON line."""
    import json
    import os

    from mfm_tpu.data.etl import PanelStore
    from mfm_tpu.data.synthetic import synthetic_collections

    store = tmp_path / "store"
    synthetic_collections(PanelStore(str(store)), T=100, N=16,
                          n_industries=4, seed=7)
    chat = tmp_path / "chat.md"
    chat.write_text(
        "Two ideas:\n"
        "1. `-delta(close, 5)`\n"
        "2. `cs_rank(market_cap_weighted_sentiment)`\n"  # hallucinated field
    )
    out = str(tmp_path / "o")
    cli_main(["pipeline", "--store", str(store), "--out", out,
              "--eigen-sims", "4", "--start", "20200101",
              "--alphas", str(chat), "--alphas-llm", "--alpha-top", "2"])
    cap = capsys.readouterr()
    rec = json.loads(cap.out.strip().splitlines()[-1])
    assert rec["alpha_styles"] == 1
    assert "market_cap_weighted_sentiment" in cap.err
    rep = json.load(open(os.path.join(out, "alpha_styles.json")))
    assert [v["expression"] for v in rep.values()] == ["-delta(close, 5)"]
